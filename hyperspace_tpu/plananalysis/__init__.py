from .buffer_stream import BufferStream  # noqa: F401
from .display_mode import ConsoleMode, DisplayMode, HTMLMode, PlainTextMode, create_display_mode  # noqa: F401
from .op_analyzer import PhysicalOperatorComparison, compare_operators, count_operators  # noqa: F401
from .analyze import explain_analyze_string  # noqa: F401
from .fingerprint import plan_fingerprint  # noqa: F401
from .plan_analyzer import explain_string  # noqa: F401
