"""Signature providers: fingerprint a query plan to decide index applicability.

Parity: reference `index/LogicalPlanSignatureProvider.scala` (trait + reflective
factory), `FileBasedSignatureProvider.scala:39-79` (md5 fold over every source file's
(length, modTime, path)), `PlanSignatureProvider.scala:36-43` (fold over operator
names), `IndexSignatureProvider.scala:33-49` (combined = default). An index created
against a plan is applicable to a query iff the recorded provider recomputes the same
signature on the query's plan — this is what makes the rewrite rules safe against
changed source data.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..engine.logical import LogicalPlan, ScanNode
from ..exceptions import HyperspaceException
from ..util.hashing_utils import md5_hex


class LogicalPlanSignatureProvider:
    """Contract: signature(plan) -> hex digest or None if the plan is unsupported."""

    @property
    def name(self) -> str:
        return type(self).__name__

    def signature(self, plan: LogicalPlan) -> Optional[str]:
        raise NotImplementedError


class FileBasedSignatureProvider(LogicalPlanSignatureProvider):
    """Fingerprint of all source data files reachable from the plan's relations
    (reference `FileBasedSignatureProvider.scala:48-66`)."""

    def signature(self, plan: LogicalPlan) -> Optional[str]:
        acc = ""
        found = False
        for node in plan.collect_nodes():
            if isinstance(node, ScanNode):
                found = True
                for f in node.relation.files:
                    acc = md5_hex(acc + f"{f.size}{f.modified_time}{f.path}")
        return acc if found else None


class PlanSignatureProvider(LogicalPlanSignatureProvider):
    """Fingerprint of the plan shape: fold over operator names
    (reference `PlanSignatureProvider.scala:36-43`)."""

    def signature(self, plan: LogicalPlan) -> Optional[str]:
        acc = ""
        for node in plan.collect_nodes():
            acc = md5_hex(acc + type(node).__name__)
        return acc


class IndexSignatureProvider(LogicalPlanSignatureProvider):
    """Combined file+plan fingerprint — the default recorded by index creation
    (reference `IndexSignatureProvider.scala:33-49`)."""

    def signature(self, plan: LogicalPlan) -> Optional[str]:
        f = FileBasedSignatureProvider().signature(plan)
        if f is None:
            return None
        p = PlanSignatureProvider().signature(plan)
        return md5_hex(f + p)


_BUILTIN = {
    "IndexSignatureProvider": IndexSignatureProvider,
    "FileBasedSignatureProvider": FileBasedSignatureProvider,
    "PlanSignatureProvider": PlanSignatureProvider,
}


def create_provider(name: Optional[str] = None) -> LogicalPlanSignatureProvider:
    """Factory; default = IndexSignatureProvider; dotted paths load reflectively
    (reference `LogicalPlanSignatureProvider.scala:28-62`)."""
    if name is None:
        return IndexSignatureProvider()
    if name in _BUILTIN:
        return _BUILTIN[name]()
    import importlib

    module_name, _, attr = name.rpartition(".")
    if not module_name:
        raise HyperspaceException(f"Unknown signature provider: {name}")
    mod = importlib.import_module(module_name)
    return getattr(mod, attr)()
