"""Index management layer: CRUD orchestration + metadata cache.

Parity: reference `index/IndexManager.scala:24-90` (contract),
`index/IndexCollectionManager.scala:26-191` (wires actions to per-index log/data
managers via factories; `indexes` summary excludes DOESNOTEXIST),
`index/CachingIndexCollectionManager.scala:37-168` + `index/Cache.scala` (TTL read
cache cleared by every mutation).
"""

from __future__ import annotations

import os
import time
from typing import List, Optional, Sequence

from ..actions import states
from ..actions.create import CreateAction
from ..actions.lifecycle import CancelAction, DeleteAction, RestoreAction, VacuumAction
from ..actions.refresh import RefreshAction
from ..engine.session import DataFrame, HyperspaceSession
from ..engine.table import Table
from ..exceptions import HyperspaceException
from ..telemetry.event_logging import EventLoggerFactory
from .builder import CoveringIndexBuilder
from .data_manager import IndexDataManagerImpl
from .factories import FileSystemFactory, IndexDataManagerFactory, IndexLogManagerFactory
from .index_config import IndexConfig
from .log_entry import IndexLogEntry
from .path_resolver import PathResolver


class IndexManager:
    """CRUD + listing contract (reference `IndexManager.scala:24-90`)."""

    def create(self, df: DataFrame, index_config: IndexConfig) -> None:
        raise NotImplementedError

    def delete(self, index_name: str) -> None:
        raise NotImplementedError

    def restore(self, index_name: str) -> None:
        raise NotImplementedError

    def vacuum(self, index_name: str) -> None:
        raise NotImplementedError

    def refresh(self, index_name: str, mode: str = "full") -> None:
        raise NotImplementedError

    def optimize(self, index_name: str, mode: str = "quick") -> None:
        raise NotImplementedError

    def cancel(self, index_name: str) -> None:
        raise NotImplementedError

    def indexes(self) -> Table:
        raise NotImplementedError

    def get_indexes(self, states_filter: Optional[Sequence[str]] = None) -> List[IndexLogEntry]:
        raise NotImplementedError


class IndexCollectionManager(IndexManager):
    def __init__(
        self,
        session: HyperspaceSession,
        log_manager_factory: Optional[IndexLogManagerFactory] = None,
        data_manager_factory: Optional[IndexDataManagerFactory] = None,
        fs_factory: Optional[FileSystemFactory] = None,
    ):
        self._session = session
        self._log_factory = log_manager_factory or IndexLogManagerFactory()
        self._data_factory = data_manager_factory or IndexDataManagerFactory()
        self._fs_factory = fs_factory or FileSystemFactory()
        self._resolver = PathResolver(session.conf, session.fs, warehouse=session.warehouse)

    def _event_logger(self):
        return EventLoggerFactory.get_logger(self._session.hs_conf.event_logger_class)

    def _managers_for(self, name: str):
        index_path = self._resolver.get_index_path(name)
        fs = self._fs_factory.create(index_path)
        # Startup/steady-state reclamation: every action resolving this index
        # sweeps staging dirs whose writer died (SIGKILLed builds). Live
        # writers are pid-checked and never touched.
        from .staging import reclaim_orphans

        reclaim_orphans(index_path)
        return (
            self._log_factory.create(index_path, fs),
            self._data_factory.create(index_path, fs),
            index_path,
        )

    def _existing_log_manager(self, name: str):
        """Resolve an EXISTING index by name (reference `withLogManager`,
        `IndexCollectionManager.scala:107-118`)."""
        log_mgr, data_mgr, index_path = self._managers_for(name)
        if log_mgr.get_latest_id() is None:
            raise HyperspaceException(f"Index with name {name} could not be found.")
        return log_mgr, data_mgr, index_path

    # -- CRUD ---------------------------------------------------------------

    def _builder_for_config(self, index_config):
        from .dataskipping import DataSkippingIndexBuilder, DataSkippingIndexConfig

        if isinstance(index_config, DataSkippingIndexConfig):
            return DataSkippingIndexBuilder(self._session)
        return CoveringIndexBuilder(self._session)

    def _builder_for_entry(self, entry):
        from .dataskipping import DATA_SKIPPING_KIND, DataSkippingIndexBuilder

        if entry is not None and entry.kind == DATA_SKIPPING_KIND:
            return DataSkippingIndexBuilder(self._session)
        return CoveringIndexBuilder(self._session)

    def create(self, df: DataFrame, index_config: IndexConfig) -> None:
        log_mgr, data_mgr, index_path = self._managers_for(index_config.index_name)
        latest = data_mgr.get_latest_version_id()
        next_version = 0 if latest is None else latest + 1
        builder = self._builder_for_config(index_config)
        CreateAction(
            df,
            index_config,
            builder,
            log_mgr,
            index_path,
            data_mgr.get_path(next_version),
            self._event_logger(),
        ).run()
        # Fresh data supersedes any quarantined corrupt files (`index/quarantine`).
        from . import quarantine

        quarantine.clear(index_config.index_name)

    def refresh(self, index_name: str, mode: str = "full") -> None:
        import time as _time

        from ..actions.refresh import RefreshIncrementalAction
        from ..telemetry import metrics as _metrics

        log_mgr, data_mgr, index_path = self._existing_log_manager(index_name)
        latest = data_mgr.get_latest_version_id()
        next_version = 0 if latest is None else latest + 1
        builder = self._builder_for_entry(log_mgr.get_latest_log())

        def make_action(cls):
            return cls(
                builder,
                log_mgr,
                index_path,
                data_mgr.get_path(next_version),
                self._event_logger(),
            )

        if mode == "incremental":
            action = make_action(RefreshIncrementalAction)
        elif mode == "full":
            action = make_action(RefreshAction)
        elif mode == "auto":
            # Serving-loop mode (docs/reliability.md "Live tables"): take the
            # cheap incremental path whenever its preconditions hold, fall
            # back to a full rebuild when they don't (modified-in-place files,
            # deletes without lineage, no per-file signatures), and NO-OP when
            # the index already covers the current source. The fallback is
            # decided by validate() alone — a failure past begin() propagates,
            # never silently re-runs as full.
            from ..actions.refresh import NothingToRefreshError
            from . import quarantine as _quarantine

            action = make_action(RefreshIncrementalAction)
            try:
                action.validate()
            except NothingToRefreshError:
                if not _quarantine.is_quarantined(index_name):
                    return  # already fresh: refresh is a no-op
                # Fresh but QUARANTINED (corrupt data file): the serving
                # loop's auto refresh is the documented remediation path, so
                # rebuild full instead of no-opping forever.
                action = make_action(RefreshAction)
            except HyperspaceException:
                # Not incrementally refreshable (modified-in-place, deletes
                # without lineage, missing per-file inventory): full rebuild.
                action = make_action(RefreshAction)
        else:
            raise HyperspaceException(
                f"Unsupported refresh mode '{mode}'; supported: full, "
                "incremental, auto."
            )
        t0 = _time.monotonic()
        action.run()
        dt = _time.monotonic() - t0
        resolved = (
            "incremental" if isinstance(action, RefreshIncrementalAction) else "full"
        )
        _metrics.histogram("refresh.latency").observe(dt)
        _metrics.histogram(f"refresh.latency.{resolved}").observe(dt)
        # The refresh covered the current source state by construction.
        _metrics.gauge(f"index.staleness_s.{index_name}").set(0.0)
        from . import quarantine

        quarantine.clear(index_name)

    def optimize(self, index_name: str, mode: str = "quick") -> None:
        import time as _time

        from ..actions.optimize import OptimizeAction
        from ..telemetry import metrics as _metrics

        log_mgr, data_mgr, index_path = self._existing_log_manager(index_name)
        latest = data_mgr.get_latest_version_id()
        next_version = 0 if latest is None else latest + 1
        builder = CoveringIndexBuilder(self._session)
        t0 = _time.monotonic()
        OptimizeAction(
            builder,
            self._session,
            log_mgr,
            index_path,
            data_mgr.get_path(next_version),
            mode,
            self._event_logger(),
        ).run()
        _metrics.histogram("compact.latency").observe(_time.monotonic() - t0)
        from . import quarantine

        quarantine.clear(index_name)

    def delete(self, index_name: str) -> None:
        log_mgr, _, _ = self._existing_log_manager(index_name)
        DeleteAction(log_mgr, self._event_logger()).run()

    def restore(self, index_name: str) -> None:
        log_mgr, _, _ = self._existing_log_manager(index_name)
        RestoreAction(log_mgr, self._event_logger()).run()

    def vacuum(self, index_name: str) -> None:
        log_mgr, data_mgr, index_path = self._existing_log_manager(index_name)
        VacuumAction(data_mgr, log_mgr, self._event_logger()).run()
        # Vacuum also sweeps any dead-writer staging dirs (hard-delete pass).
        from . import quarantine
        from .staging import reclaim_orphans

        reclaim_orphans(index_path)
        quarantine.clear(index_name)

    def cancel(self, index_name: str) -> None:
        log_mgr, _, _ = self._existing_log_manager(index_name)
        CancelAction(log_mgr, self._event_logger()).run()

    # -- listing (reference IndexCollectionManager.scala:79-105) ------------

    def get_indexes(self, states_filter: Optional[Sequence[str]] = None) -> List[IndexLogEntry]:
        system = self._resolver.system_path()
        fs = self._session.fs
        out: List[IndexLogEntry] = []
        if not fs.exists(system):
            return out
        for st in fs.list_status(system):
            if not st.is_dir:
                continue
            log_mgr = self._log_factory.create(st.path, self._fs_factory.create(st.path))
            entry = log_mgr.get_latest_log()
            if entry is None:
                continue
            if entry.state in states.TRANSIENT_STATES:
                # A writer's in-flight (or died-in-flight) window: readers
                # ride the last COMMITTED generation instead of losing the
                # index for the duration of every refresh/compaction — the
                # live-table contract (docs/reliability.md "Live tables").
                # The stable entry's content refers only to committed data
                # dirs, so this can never see torn files; if no stable entry
                # exists (a first create in flight), the index sits out
                # exactly as before.
                stable = log_mgr.get_latest_stable_log()
                if stable is None:
                    continue
                entry = stable
            if states_filter is None or entry.state in states_filter:
                out.append(entry)
        return out

    def indexes(self) -> Table:
        """Summary table (reference `IndexSummary`, :151-191), excluding DOESNOTEXIST."""
        rows = {
            "name": [],
            "indexedColumns": [],
            "includedColumns": [],
            "numBuckets": [],
            "schema": [],
            "indexLocation": [],
            "state": [],
        }
        for e in self.get_indexes():
            if e.state == states.DOESNOTEXIST:
                continue
            rows["name"].append(e.name)
            rows["indexedColumns"].append(",".join(e.indexed_columns))
            rows["includedColumns"].append(",".join(e.included_columns))
            rows["numBuckets"].append(e.num_buckets)
            rows["schema"].append(e.schema_json)
            rows["indexLocation"].append(e.index_location())
            rows["state"].append(e.state)
        return Table.from_pydict(rows)


# ---------------------------------------------------------------------------
# Caching wrapper (reference CachingIndexCollectionManager.scala + Cache.scala)
# ---------------------------------------------------------------------------


class IndexCache:
    """Cache trait (reference `index/Cache.scala:23-41`): get/set/clear of the
    full entry list."""

    def get(self) -> Optional[List[IndexLogEntry]]:
        raise NotImplementedError

    def set(self, entries: List[IndexLogEntry]) -> None:
        raise NotImplementedError

    def clear(self) -> None:
        raise NotImplementedError


class CreationTimeBasedIndexCache(IndexCache):
    """TTL cache of the full entry list (reference `CreationTimeBasedIndexCache`,
    :117-168)."""

    def __init__(self, expiry_seconds_fn):
        self._expiry_fn = expiry_seconds_fn
        self._entries: Optional[List[IndexLogEntry]] = None
        self._set_time: float = 0.0

    def get(self) -> Optional[List[IndexLogEntry]]:
        if self._entries is None:
            return None
        if time.time() - self._set_time > self._expiry_fn():
            self.clear()
            return None
        return self._entries

    def set(self, entries: List[IndexLogEntry]) -> None:
        self._entries = list(entries)
        self._set_time = time.time()

    def clear(self) -> None:
        self._entries = None
        self._set_time = 0.0


class IndexCacheFactory:
    """Cache impl keyed by policy name (reference `IndexCacheFactory.scala:23-38`);
    `register` is the pluggability seam tests/extensions inject through."""

    CREATION_TIME_BASED = "CREATION_TIME_BASED"
    _registry = {}

    @classmethod
    def register(cls, cache_type: str, ctor) -> None:
        """ctor: (session) -> IndexCache"""
        cls._registry[cache_type.upper()] = ctor

    @classmethod
    def create(cls, cache_type: str, session: HyperspaceSession) -> IndexCache:
        ctor = cls._registry.get(cache_type.upper())
        if ctor is None:
            raise HyperspaceException(f"Unknown index cache type: {cache_type}")
        return ctor(session)


IndexCacheFactory.register(
    IndexCacheFactory.CREATION_TIME_BASED,
    lambda session: CreationTimeBasedIndexCache(
        lambda: session.hs_conf.cache_expiry_seconds
    ),
)


class CachingIndexCollectionManager(IndexCollectionManager):
    """Read-path cache; every mutating API clears it (reference :77-100). The
    cache policy comes from `hyperspace.index.cache.type` via the factory.

    Mutations clear the cache BEFORE and AFTER the action: an action takes
    seconds, and a concurrent reader (the live-table serving mix) repopulates
    the cache with the pre-commit generation DURING that window — with only
    the pre-clear, the committed entry stayed invisible for up to the cache
    TTL after the action returned. The after-clear runs in a `finally` so a
    failed action's transient orphan is also re-read, not trusted from cache.

    Under a replica fleet (``HYPERSPACE_REPLICAS=1``, `serve.replicas`) the
    clear crosses processes: a committed mutation additionally PUBLISHES the
    index's new latest ``log_entry_id`` to the fleet's epoch file, and every
    replica's `get_indexes` polls the epoch signature (one rate-limited
    `os.stat`) before trusting its TTL cache — a refresh/compaction landed by
    ANY replica flips every replica's readers to the new stable generation
    without waiting out the TTL. Fleet off = one env read, byte-identical
    single-process behavior."""

    def __init__(self, session: HyperspaceSession, **kwargs):
        super().__init__(session, **kwargs)
        self._cache = IndexCacheFactory.create(session.hs_conf.cache_type, session)
        # This manager's PRIVATE invalidation cursor (serve.replicas): a
        # shared cursor would let one manager consume the epoch signal and
        # starve every other manager of its cache clear.
        self._epoch_state: dict = {}

    def _fleet_registry_dir(self) -> str:
        from ..serve import replicas as _replicas

        return _replicas.registry_dir(self._session.warehouse)

    def get_indexes(self, states_filter: Optional[Sequence[str]] = None) -> List[IndexLogEntry]:
        from ..serve import replicas as _replicas

        if _replicas.fleet_enabled() and _replicas.check_invalidation(
            self._epoch_state, self._fleet_registry_dir()
        ):
            self._cache.clear()
        cached = self._cache.get()
        if cached is None:
            cached = super().get_indexes(None)
            self._cache.set(cached)
        if states_filter is None:
            return list(cached)
        return [e for e in cached if e.state in states_filter]

    def clear_cache(self) -> None:
        self._cache.clear()

    def _publish_fleet_invalidation(self, index_name: Optional[str]) -> None:
        """Announce a committed mutation's latest log id to the fleet
        (no-op at one env read without a fleet; never fails the action)."""
        from ..serve import replicas as _replicas

        if index_name is None or not _replicas.fleet_enabled():
            return
        try:
            log_mgr, _, _ = self._managers_for(index_name)
            _replicas.publish_invalidation(
                index_name, log_mgr.get_latest_id(), self._fleet_registry_dir()
            )
        except Exception:
            pass

    def _mutate(self, fn, index_name: Optional[str] = None) -> None:
        self.clear_cache()
        try:
            fn()
            self._publish_fleet_invalidation(index_name)
        finally:
            self.clear_cache()

    def create(self, df, index_config) -> None:
        self._mutate(
            lambda: super(CachingIndexCollectionManager, self).create(df, index_config),
            index_config.index_name,
        )

    def delete(self, index_name: str) -> None:
        self._mutate(
            lambda: super(CachingIndexCollectionManager, self).delete(index_name),
            index_name,
        )

    def restore(self, index_name: str) -> None:
        self._mutate(
            lambda: super(CachingIndexCollectionManager, self).restore(index_name),
            index_name,
        )

    def vacuum(self, index_name: str) -> None:
        self._mutate(
            lambda: super(CachingIndexCollectionManager, self).vacuum(index_name),
            index_name,
        )

    def refresh(self, index_name: str, mode: str = "full") -> None:
        self._mutate(
            lambda: super(CachingIndexCollectionManager, self).refresh(index_name, mode),
            index_name,
        )

    def optimize(self, index_name: str, mode: str = "quick") -> None:
        self._mutate(
            lambda: super(CachingIndexCollectionManager, self).optimize(index_name, mode),
            index_name,
        )

    def cancel(self, index_name: str) -> None:
        self._mutate(
            lambda: super(CachingIndexCollectionManager, self).cancel(index_name),
            index_name,
        )
