"""Crash-safe index-data commits: stage → single rename → operation-log CAS.

Builders previously wrote index files DIRECTLY into the final version
directory (`v__=N`); a process killed mid-build left a partial directory that
the next build's `Content.from_directory` inventory could pick up, and that
nothing ever reclaimed. This module makes the data commit atomic:

1. `stage_commit(final_path)` yields a STAGING directory (dot-prefixed, so the
   data-path filter, the version-id scan, and `Content.from_directory` all
   ignore it by construction) that the build writes into;
2. on success the staging dir is renamed to `final_path` in ONE `os.rename` —
   a SIGKILL before the rename leaves only an invisible staging dir, a SIGKILL
   after leaves a complete version dir that only becomes VISIBLE when the
   action's `end()` commits the log entry via the operation-log CAS;
3. a concurrent writer that already renamed `final_path` into place wins — the
   loser raises `ConcurrentWriteError` and deletes its staging dir (clean
   abort);
4. `reclaim_orphans(index_path)` deletes staging dirs whose creating process
   is dead (the pid rides the directory name), and runs at every action's
   manager resolution plus vacuum — killed builds are reclaimed by the next
   action on the index, exactly the "startup/vacuum reclaims" contract.

The staging dir lives in the same parent as `final_path` (same filesystem →
the rename is atomic on POSIX).
"""

from __future__ import annotations

import contextlib
import os
import shutil
import socket
import time
import uuid
from typing import Iterator, List, Optional, Tuple

from ..exceptions import ConcurrentWriteError
from ..telemetry import metrics as _metrics

#: Dot prefix: `util.path_utils.is_data_path` treats '.'-prefixed names as
#: metadata UNCONDITIONALLY (unlike '_'-prefixed, where '=' re-admits hive
#: partition dirs — and version dirs are named `v__=N`).
STAGING_PREFIX = ".staging-"

#: Reclamation age threshold for staging dirs from OTHER hosts (seconds):
#: pid liveness is only knowable for writers on THIS host, so a foreign
#: host's staging dir is reclaimed only once it has sat untouched this long —
#: a live cross-host build must never have its in-progress data deleted out
#: from under it (which would silently commit an index missing buckets).
ENV_STAGING_TTL_S = "HYPERSPACE_STAGING_TTL_S"
_DEFAULT_STAGING_TTL_S = 24 * 3600.0

_RECLAIMED = _metrics.counter("index.staging.reclaimed")
_COMMITS = _metrics.counter("index.staging.commits")
_ABORTS = _metrics.counter("index.staging.aborts")


def _staging_ttl_s() -> float:
    try:
        return max(
            0.0,
            float(os.environ.get(ENV_STAGING_TTL_S, "") or _DEFAULT_STAGING_TTL_S),
        )
    except ValueError:
        return _DEFAULT_STAGING_TTL_S


def _staging_name(final_name: str) -> str:
    # '~'-separated tail: hostnames may contain '-' and '.', so the owner
    # coordinates need a separator that cannot appear in them (or in the
    # `v__=N` final name).
    return (
        f"{STAGING_PREFIX}{final_name}"
        f"~{socket.gethostname()}~{os.getpid()}~{uuid.uuid4().hex[:8]}"
    )


def _owner_of(name: str) -> Tuple[Optional[str], int]:
    """(hostname, pid) encoded in a staging dir name; (None, -1) when
    unparseable (e.g. a dir from an older layout)."""
    parts = name.split("~")
    try:
        return parts[-3], int(parts[-2])
    except (IndexError, ValueError):
        return None, -1


def _pid_alive(pid: int) -> bool:
    from ..util.procs import pid_alive

    return pid_alive(pid)


@contextlib.contextmanager
def stage_commit(final_path: str) -> Iterator[str]:
    """Yield a staging directory for the build of `final_path`; commit it by
    rename on clean exit, delete it on failure. Raises `ConcurrentWriteError`
    (after cleaning up) when `final_path` appeared concurrently."""
    final_path = final_path.rstrip(os.sep)
    parent = os.path.dirname(final_path) or "."
    os.makedirs(parent, exist_ok=True)
    stage = os.path.join(parent, _staging_name(os.path.basename(final_path)))
    try:
        yield stage
    except BaseException:
        _ABORTS.inc()
        shutil.rmtree(stage, ignore_errors=True)
        raise
    if not os.path.isdir(stage):
        # The build wrote nothing (e.g. a fake builder in FSM tests): nothing
        # to commit, and `Content.from_directory` of a missing final dir is
        # already the empty inventory.
        return
    try:
        os.rename(stage, final_path)
    except OSError as e:
        _ABORTS.inc()
        shutil.rmtree(stage, ignore_errors=True)
        if os.path.exists(final_path):
            raise ConcurrentWriteError(
                f"Another writer committed {final_path} first; this build was "
                "aborted cleanly. Please retry."
            ) from e
        raise
    _COMMITS.inc()


def _is_orphan(path: str, name: str) -> bool:
    host, pid = _owner_of(name)
    if host == socket.gethostname() and pid > 0:
        # Our host: pid liveness is authoritative.
        return not _pid_alive(pid)
    # Another host (or an unparseable name): liveness is unknowable locally —
    # reclaim only once the dir has aged past the TTL, so a live cross-host
    # build keeps its in-progress staging area.
    try:
        age = time.time() - os.stat(path).st_mtime
    except OSError:
        return False  # vanished concurrently: someone else reclaimed it
    return age > _staging_ttl_s()


def list_orphans(index_path: str) -> List[str]:
    """Staging dirs under `index_path` whose writer is provably dead (same
    host, dead pid) or stale past `HYPERSPACE_STAGING_TTL_S` (foreign host)."""
    if not os.path.isdir(index_path):
        return []
    out = []
    for name in os.listdir(index_path):
        if not name.startswith(STAGING_PREFIX):
            continue
        if _is_orphan(os.path.join(index_path, name), name):
            out.append(os.path.join(index_path, name))
    return out


def reclaim_orphans(index_path: str) -> int:
    """Delete orphaned staging dirs under `index_path`; returns the count.
    Live writers are never touched (pid liveness on this host, TTL age for
    other hosts), so a concurrent build's staging area survives other
    actions racing on the same index."""
    n = 0
    for p in list_orphans(index_path):
        shutil.rmtree(p, ignore_errors=True)
        n += 1
    if n:
        _RECLAIMED.inc(n)
    return n
