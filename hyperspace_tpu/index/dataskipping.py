"""DataSkippingIndex: per-source-file sketches that prune files from scans.

North-star extension (BASELINE.md config 4) — absent from the v0 reference snapshot.
Two sketch types:

- MinMaxSketch(col): per-file min/max, prunes range/equality filters.
- BloomFilterSketch(col, num_bits, num_hashes): per-file bloom filter over the
  column's values, prunes equality/IN filters.

TPU-first: the per-file scan that feeds each sketch runs on device — min/max are
jnp reductions; the bloom filter is built by hashing the whole column with the same
murmur lanes the join path uses and scattering bits in one vectorized `.at[].max`.
Sketch data persists as one parquet file per index version (bloom bitsets hex-encoded),
and the metadata record reuses the covering-index log machinery with
kind="DataSkippingIndex".
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..actions.create import IndexerBuilder
from ..engine import io as engine_io
from ..engine.logical import ScanNode
from ..engine.schema import Schema
from ..engine.table import Column, Table
from ..config import IndexConstants
from ..exceptions import HyperspaceException
from ..ops.hashing import _SEED1, _SEED2, column_hash_u32
from ..util.resolver_utils import resolve_all
from .index_config import IndexConfig
from .log_entry import (
    Content,
    CoveringIndexProperties,
    Directory,
    IndexLogEntry,
    LogicalPlanFingerprint,
    Relation,
    Signature,
    Source,
    SourcePlanProperties,
    register_entry_kind,
)
from .signatures import create_provider

DATA_SKIPPING_KIND = "DataSkippingIndex"
_FILE_COL = "_file"


class Sketch:
    kind = "Sketch"

    def __init__(self, column: str):
        self.column = column

    def to_json(self) -> dict:
        return {"kind": self.kind, "column": self.column}

    @staticmethod
    def from_json(d: dict) -> "Sketch":
        if d["kind"] == "MinMaxSketch":
            return MinMaxSketch(d["column"], d.get("granularity", "file"))
        if d["kind"] == "BloomFilterSketch":
            return BloomFilterSketch(d["column"], d.get("numBits", 1024), d.get("numHashes", 5))
        raise HyperspaceException(f"Unknown sketch kind: {d['kind']}")


class MinMaxSketch(Sketch):
    """Per-file min/max zone. `granularity="rowgroup"` additionally records
    the PER-ROW-GROUP [min, max] zones of each parquet source file (read from
    the footers at build time — no extra decode): a file whose overall range
    straddles a literal still prunes when no individual row group can contain
    it (clustered data), through the same zone-map evaluator the scan
    pushdown uses (`engine.pushdown.minmax_keeps`)."""

    kind = "MinMaxSketch"

    def __init__(self, column: str, granularity: str = "file"):
        super().__init__(column)
        if granularity not in ("file", "rowgroup"):
            raise HyperspaceException(
                f"MinMaxSketch granularity must be 'file' or 'rowgroup': {granularity}"
            )
        self.granularity = granularity

    def to_json(self) -> dict:
        d = super().to_json()
        if self.granularity != "file":
            d["granularity"] = self.granularity
        return d


class BloomFilterSketch(Sketch):
    kind = "BloomFilterSketch"

    def __init__(self, column: str, num_bits: int = 1024, num_hashes: int = 5):
        super().__init__(column)
        self.num_bits = num_bits
        self.num_hashes = num_hashes

    def to_json(self) -> dict:
        d = super().to_json()
        d.update({"numBits": self.num_bits, "numHashes": self.num_hashes})
        return d


class DataSkippingIndexConfig:
    """Spec: name + sketches (the DataSkippingIndexConfig analogue)."""

    def __init__(self, index_name: str, sketches: Sequence[Sketch]):
        if not index_name or not index_name.strip():
            raise HyperspaceException("Index name cannot be empty.")
        if not sketches:
            raise HyperspaceException("At least one sketch is required.")
        self.index_name = index_name
        self.sketches = list(sketches)

    # IndexConfig-compatible surface for the action/manager machinery:
    @property
    def indexed_columns(self) -> List[str]:
        return list(dict.fromkeys(s.column for s in self.sketches))

    @property
    def included_columns(self) -> List[str]:
        return []


# ---------------------------------------------------------------------------
# Sketch computation (device side)
# ---------------------------------------------------------------------------


def _bloom_bits(col: Column, num_bits: int, num_hashes: int) -> np.ndarray:
    """Bloom bitset of a column's values: double hashing h1 + i*h2, one vectorized
    scatter for all rows × hash lanes."""
    arr = jnp.asarray(col.data)
    h1 = column_hash_u32(col, arr, _SEED1).astype(jnp.uint64)
    h2 = column_hash_u32(col, arr, _SEED2).astype(jnp.uint64)
    i = jnp.arange(num_hashes, dtype=jnp.uint64)[:, None]
    idx = ((h1[None, :] + i * h2[None, :]) % jnp.uint64(num_bits)).astype(jnp.int32)
    bits = jnp.zeros((num_bits,), dtype=jnp.uint8).at[idx.reshape(-1)].max(1)
    return np.asarray(bits)


def bloom_probe(bits: np.ndarray, value, column_dtype: str, num_hashes: int) -> bool:
    """Membership probe for one literal (host side; bits already tiny).

    The probe must hash the literal the way the COLUMN's values were hashed: numeric
    literals are cast to the column's dtype first (int 5 vs float 5.0 canonicalize
    differently), and any cast that changes the value or fails means the column can
    never equal the literal exactly as hashed — we conservatively keep the file."""
    expect_string = column_dtype == "string"
    if expect_string:
        probe_col = Column.from_values(np.asarray([value]))
        if not probe_col.is_string:
            return True  # type mismatch: cannot prune safely
    else:
        try:
            cast = np.asarray([value], dtype=np.dtype(column_dtype))
            if cast[0] != value:
                return True  # value not representable in the column dtype
        except (ValueError, OverflowError, TypeError):
            return True
        probe_col = Column.from_values(cast)
    arr = jnp.asarray(probe_col.data)
    h1 = int(np.asarray(column_hash_u32(probe_col, arr, _SEED1))[0])
    h2 = int(np.asarray(column_hash_u32(probe_col, arr, _SEED2))[0])
    num_bits = len(bits)
    for i in range(num_hashes):
        if not bits[(h1 + i * h2) % num_bits]:
            return False
    return True


def _row_group_zones(path: str, file_format: str, column: str) -> list:
    """Per-row-group [min, max] zones of one source file's column from its
    parquet footer (no decode): a list of 2-lists, with None for a zone whose
    statistics are absent (that zone always keeps). [] when the file carries
    no usable footer (non-parquet or unreadable) — the sketch then degrades
    to its file-level min/max."""
    meta = engine_io.footer_metadata(path, file_format)
    if meta is None:
        return []
    ci = [n for n in meta.names if n.lower() == column.lower()]
    name = column if column in meta.names else (ci[0] if len(ci) == 1 else None)
    if name is None:
        return []
    zones = []
    for rg in meta.row_groups:
        st = rg.stats.get(name)
        if st is None or not st.has_minmax:
            zones.append(None)
        else:
            mn = st.mn.item() if hasattr(st.mn, "item") else st.mn
            mx = st.mx.item() if hasattr(st.mx, "item") else st.mx
            zones.append([mn, mx])
    return zones


def _bits_to_hex(bits: np.ndarray) -> str:
    return np.packbits(bits.astype(np.uint8)).tobytes().hex()


def hex_to_bits(s: str, num_bits: int) -> np.ndarray:
    return np.unpackbits(np.frombuffer(bytes.fromhex(s), dtype=np.uint8))[:num_bits]


# ---------------------------------------------------------------------------
# Builder (plugs into the same CreateAction FSM as covering indexes)
# ---------------------------------------------------------------------------


class DataSkippingIndexBuilder(IndexerBuilder):
    def __init__(self, session):
        self._session = session

    def validate_source(self, df, index_config: DataSkippingIndexConfig) -> None:
        if not isinstance(df.plan, ScanNode):
            raise HyperspaceException(
                "Only creating index over a plain relation scan is supported."
            )
        names = df.plan.output_schema.names
        if resolve_all(
            index_config.indexed_columns, names, self._session.hs_conf.case_sensitive
        ) is None:
            raise HyperspaceException(
                f"Sketch columns {index_config.indexed_columns} could not be resolved "
                f"against dataframe columns {names}."
            )

    def write(self, df, index_config: DataSkippingIndexConfig, index_data_path: str) -> None:
        # Same crash-safe staged commit as the covering build: the sketch file
        # lands via one atomic rename, never as a partial visible write.
        from .staging import stage_commit

        with stage_commit(index_data_path) as stage:
            self._write_sketches(df, index_config, stage)

    def _write_sketches(
        self, df, index_config: DataSkippingIndexConfig, index_data_path: str
    ) -> None:
        rel = df.plan.relation
        cols = list(dict.fromkeys(s.column for s in index_config.sketches))
        partitions = (
            None
            if rel.partition_spec is None
            else (rel.partition_spec, rel.root_paths)
        )
        rows: Dict[str, list] = {_FILE_COL: []}
        for f in rel.files:
            t = engine_io.read_files(
                [f.path], rel.file_format, cols, partitions=partitions
            )
            rows[_FILE_COL].append(f.path)
            for s in index_config.sketches:
                c = t.column(s.column)
                if isinstance(s, MinMaxSketch):
                    if c.is_string:
                        decoded = c.dictionary
                        mn, mx = str(decoded.min()), str(decoded.max())
                    else:
                        arr = jnp.asarray(c.data)
                        mn = np.asarray(jnp.min(arr)).item()
                        mx = np.asarray(jnp.max(arr)).item()
                    rows.setdefault(f"min_{s.column}", []).append(mn)
                    rows.setdefault(f"max_{s.column}", []).append(mx)
                    if s.granularity == "rowgroup":
                        rows.setdefault(f"rgzm_{s.column}", []).append(
                            json.dumps(_row_group_zones(f.path, rel.file_format, s.column))
                        )
                elif isinstance(s, BloomFilterSketch):
                    bits = _bloom_bits(c, s.num_bits, s.num_hashes)
                    rows.setdefault(f"bloom_{s.column}", []).append(_bits_to_hex(bits))
        engine_io.write_parquet(
            Table.from_pydict(rows), os.path.join(index_data_path, "part-00000.parquet")
        )

    def derive_log_entry(
        self, df, index_config: DataSkippingIndexConfig, index_path: str, index_data_path: str
    ) -> IndexLogEntry:
        rel = df.plan.relation
        provider = create_provider()
        sig = provider.signature(df.plan)
        if sig is None:
            raise HyperspaceException("Signature provider does not support this plan.")
        relation = Relation(
            root_paths=list(rel.root_paths),
            data=Content(Directory.from_leaf_files("/", rel.files)),
            data_schema_json=rel.schema.to_json_string(),
            file_format=rel.file_format,
            options=dict(rel.options),
        )
        entry = IndexLogEntry(
            name=index_config.index_name,
            derived_dataset=CoveringIndexProperties(
                indexed_columns=index_config.indexed_columns,
                included_columns=[],
                schema_json=Schema([]).to_json_string(),
                num_buckets=1,
                properties={
                    "sketches": json.dumps([s.to_json() for s in index_config.sketches]),
                    IndexConstants.HASH_SCHEME_KEY: IndexConstants.HASH_SCHEME_VERSION,
                },
            ),
            content=Content.from_directory(index_data_path, self._session.fs),
            source=Source(
                SourcePlanProperties(
                    relations=[relation],
                    fingerprint=LogicalPlanFingerprint(
                        signatures=[Signature(provider.name, sig)]
                    ),
                )
            ),
            kind=DATA_SKIPPING_KIND,
        )
        return entry

    def reconstruct_df(self, relation: Relation):
        from .builder import CoveringIndexBuilder

        return CoveringIndexBuilder(self._session).reconstruct_df(relation)

    def restrict_df_to_files(self, df, file_paths):
        from .builder import CoveringIndexBuilder

        return CoveringIndexBuilder(self._session).restrict_df_to_files(df, file_paths)

    def config_from_entry(self, entry: IndexLogEntry) -> DataSkippingIndexConfig:
        return DataSkippingIndexConfig(entry.name, sketches_of(entry))


def sketches_of(entry: IndexLogEntry) -> List[Sketch]:
    raw = entry.derived_dataset.properties.get("sketches", "[]")
    return [Sketch.from_json(d) for d in json.loads(raw)]


register_entry_kind(DATA_SKIPPING_KIND, IndexLogEntry.from_json)
