from .index_config import IndexConfig  # noqa: F401
from .log_entry import (  # noqa: F401
    Content,
    CoveringIndexProperties,
    Directory,
    FileInfo,
    IndexLogEntry,
    LogEntry,
    LogicalPlanFingerprint,
    Relation,
    Signature,
    Source,
    SourcePlanProperties,
)
from .log_manager import IndexLogManager, IndexLogManagerImpl  # noqa: F401
from .data_manager import IndexDataManager, IndexDataManagerImpl  # noqa: F401
from .path_resolver import PathResolver  # noqa: F401
