"""Index specification.

Parity: reference `index/IndexConfig.scala:28-175` — name + indexedColumns +
includedColumns; validates non-empty and no case-insensitive duplicates; case-insensitive
equality; fluent builder (`indexBy/include/create`).
"""

from __future__ import annotations

from typing import List, Sequence

from ..exceptions import HyperspaceException


class IndexConfig:
    def __init__(
        self,
        index_name: str,
        indexed_columns: Sequence[str],
        included_columns: Sequence[str] = (),
    ):
        if not index_name or not index_name.strip():
            raise HyperspaceException("Index name cannot be empty.")
        if not indexed_columns:
            raise HyperspaceException("Indexed columns cannot be empty.")
        lower_indexed = [c.lower() for c in indexed_columns]
        lower_included = [c.lower() for c in included_columns]
        if len(set(lower_indexed)) != len(lower_indexed) or len(set(lower_included)) != len(
            lower_included
        ):
            raise HyperspaceException("Duplicate column names are not allowed.")
        if set(lower_indexed) & set(lower_included):
            raise HyperspaceException(
                "Duplicate column names in indexed/included columns are not allowed."
            )
        self.index_name = index_name
        self.indexed_columns: List[str] = list(indexed_columns)
        self.included_columns: List[str] = list(included_columns)

    def __eq__(self, other):
        if not isinstance(other, IndexConfig):
            return False
        return (
            self.index_name.lower() == other.index_name.lower()
            and [c.lower() for c in self.indexed_columns]
            == [c.lower() for c in other.indexed_columns]
            and sorted(c.lower() for c in self.included_columns)
            == sorted(c.lower() for c in other.included_columns)
        )

    def __hash__(self):
        return hash(
            (
                self.index_name.lower(),
                tuple(c.lower() for c in self.indexed_columns),
                tuple(sorted(c.lower() for c in self.included_columns)),
            )
        )

    def __repr__(self):
        return (
            f"IndexConfig({self.index_name!r}, indexed={self.indexed_columns}, "
            f"included={self.included_columns})"
        )

    class Builder:
        def __init__(self):
            self._name = ""
            self._indexed: List[str] = []
            self._included: List[str] = []

        def index_name(self, name: str) -> "IndexConfig.Builder":
            if not name or not name.strip():
                raise HyperspaceException("Index name cannot be empty.")
            if self._name:
                raise HyperspaceException("Index name is already set.")
            self._name = name
            return self

        def index_by(self, *columns: str) -> "IndexConfig.Builder":
            if self._indexed:
                raise HyperspaceException("Indexed columns are already set.")
            if not columns:
                raise HyperspaceException("Indexed columns cannot be empty.")
            self._indexed = list(columns)
            return self

        def include(self, *columns: str) -> "IndexConfig.Builder":
            if self._included:
                raise HyperspaceException("Included columns are already set.")
            if not columns:
                raise HyperspaceException("Included columns cannot be empty.")
            self._included = list(columns)
            return self

        def create(self) -> "IndexConfig":
            return IndexConfig(self._name, self._indexed, self._included)

    @staticmethod
    def builder() -> "IndexConfig.Builder":
        return IndexConfig.Builder()
