"""Index metadata model: the operation-log record and the on-lake file inventory.

Parity: reference `index/LogEntry.scala:22-47` (abstract versioned record) and
`index/IndexLogEntry.scala` (the full metadata record: CoveringIndex properties, Content
file tree, Source relations with plan fingerprint). The JSON layout mirrors the
reference's spec example (`IndexLogEntryTest.scala:69`) in spirit: polymorphic decode on a
version field, nested `content`/`source` trees, value-equality on
config+signature+content+source+state.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..storage.filesystem import FileStatus, FileSystem
from ..util.path_utils import is_data_path


# ---------------------------------------------------------------------------
# Content: directory tree of index data files (reference IndexLogEntry.scala:39-228)
# ---------------------------------------------------------------------------


@dataclass
class FileInfo:
    """One leaf file: name, size, modification time (reference `FileInfo`, :221-228)."""

    name: str
    size: int
    modified_time: int

    def to_json(self) -> dict:
        return {"name": self.name, "size": self.size, "modifiedTime": self.modified_time}

    @staticmethod
    def from_json(d: dict) -> "FileInfo":
        return FileInfo(d["name"], d["size"], d["modifiedTime"])


@dataclass
class Directory:
    """A directory node: name, files, subDirs (reference `Directory`)."""

    name: str
    files: List[FileInfo] = field(default_factory=list)
    subdirs: List["Directory"] = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "files": [f.to_json() for f in self.files],
            "subDirs": [d.to_json() for d in self.subdirs],
        }

    @staticmethod
    def from_json(d: dict) -> "Directory":
        return Directory(
            d["name"],
            [FileInfo.from_json(f) for f in d.get("files", [])],
            [Directory.from_json(s) for s in d.get("subDirs", [])],
        )

    @staticmethod
    def from_directory(path: str, fs: FileSystem) -> "Directory":
        """Build a tree by recursively listing leaf files under ``path``
        (reference `Directory.fromDirectory`, :106-121). Metadata files and
        directories (`_*`, `.*`) are filtered out via the data-path filter applied to
        every path component below the root — so e.g. `_hyperspace_log/0` is never
        inventoried as index data."""
        rootnorm = os.path.normpath(path)

        def is_data_leaf(st) -> bool:
            rel = os.path.relpath(os.path.normpath(st.path), rootnorm)
            return all(is_data_path(part) for part in rel.split(os.sep))

        leaves = [f for f in fs.list_leaf_files(path) if is_data_leaf(f)]
        return Directory.from_leaf_files(path, leaves)

    @staticmethod
    def from_leaf_files(root: str, leaves: List[FileStatus]) -> "Directory":
        """Reconstruct the tree from a flat FileStatus list
        (reference `Directory.fromLeafFiles`, :141-193)."""
        rootnorm = os.path.normpath(root)
        tree = Directory(name=rootnorm)
        for st in leaves:
            rel = os.path.relpath(os.path.normpath(st.path), rootnorm)
            parts = [p for p in rel.split(os.sep) if p and p != "."]
            node = tree
            for part in parts[:-1]:
                child = next((d for d in node.subdirs if d.name == part), None)
                if child is None:
                    child = Directory(name=part)
                    node.subdirs.append(child)
                node = child
            node.files.append(FileInfo(parts[-1], st.size, st.modified_time))
        return tree


@dataclass
class Content:
    """Root of the file inventory; `files` flattens to full paths
    (reference `Content.files`, :42-52)."""

    root: Directory

    def files(self) -> List[str]:
        return [f.name for f in self.file_infos()]

    def file_infos(self) -> List[FileInfo]:
        out: List[FileInfo] = []

        def walk(node: Directory, prefix: str):
            base = node.name if not prefix else os.path.join(prefix, node.name)
            for f in node.files:
                out.append(FileInfo(os.path.join(base, f.name), f.size, f.modified_time))
            for d in node.subdirs:
                walk(d, base)

        walk(self.root, "")
        return sorted(out, key=lambda f: f.name)

    def to_json(self) -> dict:
        return {"root": self.root.to_json()}

    @staticmethod
    def from_json(d: dict) -> "Content":
        return Content(Directory.from_json(d["root"]))

    @staticmethod
    def from_directory(path: str, fs: FileSystem) -> "Content":
        return Content(Directory.from_directory(path, fs))

    @staticmethod
    def from_file_infos(infos: List["FileInfo"]) -> "Content":
        """Build from absolute-path FileInfos (used to merge multi-version index data
        after incremental refresh / optimize)."""
        leaves = [FileStatus(f.name, f.size, f.modified_time, False) for f in infos]
        return Content(Directory.from_leaf_files("/", leaves))

    @staticmethod
    def merge(contents: List["Content"]) -> "Content":
        all_infos: List[FileInfo] = []
        for c in contents:
            all_infos.extend(c.file_infos())
        return Content.from_file_infos(all_infos)


# ---------------------------------------------------------------------------
# Source lineage: relations + plan fingerprint (reference IndexLogEntry.scala:242-282)
# ---------------------------------------------------------------------------


@dataclass
class Signature:
    provider: str
    value: str

    def to_json(self) -> dict:
        return {"provider": self.provider, "value": self.value}

    @staticmethod
    def from_json(d: dict) -> "Signature":
        return Signature(d["provider"], d["value"])


@dataclass
class LogicalPlanFingerprint:
    """Fingerprint of the source logical plan (reference `LogicalPlanFingerprint`, :245-250)."""

    kind: str = "LogicalPlan"
    signatures: List[Signature] = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "kind": self.kind,
            "properties": {"signatures": [s.to_json() for s in self.signatures]},
        }

    @staticmethod
    def from_json(d: dict) -> "LogicalPlanFingerprint":
        return LogicalPlanFingerprint(
            d.get("kind", "LogicalPlan"),
            [Signature.from_json(s) for s in d.get("properties", {}).get("signatures", [])],
        )


@dataclass
class Relation:
    """One source relation: root paths, data file inventory, schema, format, options
    (reference `Relation`, :261-266)."""

    root_paths: List[str]
    data: Content
    data_schema_json: str
    file_format: str
    options: Dict[str, str] = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "rootPaths": self.root_paths,
            "data": {"properties": {"content": self.data.to_json()}},
            "dataSchemaJson": self.data_schema_json,
            "fileFormat": self.file_format,
            "options": self.options,
        }

    @staticmethod
    def from_json(d: dict) -> "Relation":
        return Relation(
            d["rootPaths"],
            Content.from_json(d["data"]["properties"]["content"]),
            d["dataSchemaJson"],
            d["fileFormat"],
            d.get("options", {}),
        )


@dataclass
class SourcePlanProperties:
    """Plan properties: relations + raw plan + fingerprint (reference `SparkPlan`, :269-279).

    `raw_plan` carries the serialized logical plan when plan persistence is on (the
    reference designed-for-but-dormant serde path, `CreateActionBase.scala:65-70`)."""

    relations: List[Relation]
    raw_plan: Optional[str] = None
    sql: Optional[str] = None
    fingerprint: LogicalPlanFingerprint = field(default_factory=LogicalPlanFingerprint)

    def to_json(self) -> dict:
        return {
            "properties": {
                "relations": [r.to_json() for r in self.relations],
                "rawPlan": self.raw_plan,
                "sql": self.sql,
                "fingerprint": self.fingerprint.to_json(),
            },
            "kind": "QueryPlan",
        }

    @staticmethod
    def from_json(d: dict) -> "SourcePlanProperties":
        p = d["properties"]
        return SourcePlanProperties(
            [Relation.from_json(r) for r in p.get("relations", [])],
            p.get("rawPlan"),
            p.get("sql"),
            LogicalPlanFingerprint.from_json(p["fingerprint"]),
        )


@dataclass
class Source:
    plan: SourcePlanProperties

    def to_json(self) -> dict:
        return {"plan": self.plan.to_json()}

    @staticmethod
    def from_json(d: dict) -> "Source":
        return Source(SourcePlanProperties.from_json(d["plan"]))


# ---------------------------------------------------------------------------
# Derived-dataset (index) properties
# ---------------------------------------------------------------------------


@dataclass
class CoveringIndexProperties:
    """indexed/included columns + schema + bucketing (reference `CoveringIndex`, :231-239)."""

    indexed_columns: List[str]
    included_columns: List[str]
    schema_json: str
    num_buckets: int
    properties: Dict[str, str] = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "kind": "CoveringIndex",
            "properties": {
                "columns": {
                    "indexed": self.indexed_columns,
                    "included": self.included_columns,
                },
                "schemaJson": self.schema_json,
                "numBuckets": self.num_buckets,
                "properties": self.properties,
            },
        }

    @staticmethod
    def from_json(d: dict) -> "CoveringIndexProperties":
        p = d["properties"]
        return CoveringIndexProperties(
            p["columns"]["indexed"],
            p["columns"]["included"],
            p["schemaJson"],
            p["numBuckets"],
            p.get("properties", {}),
        )


# ---------------------------------------------------------------------------
# LogEntry base + IndexLogEntry (reference LogEntry.scala, IndexLogEntry.scala:285-334)
# ---------------------------------------------------------------------------


class LogEntry:
    """Abstract versioned log record with mutable id/state/timestamp/enabled
    (reference `LogEntry.scala:22-47`)."""

    VERSION = "0.1"

    def __init__(self):
        self.id: int = 0
        self.state: str = ""
        self.timestamp: int = 0
        self.enabled: bool = True

    def base_json(self) -> dict:
        return {
            "version": self.VERSION,
            "id": self.id,
            "state": self.state,
            "timestamp": self.timestamp,
            "enabled": self.enabled,
        }

    @staticmethod
    def from_json(text_or_dict) -> "LogEntry":
        """Polymorphic decode keyed on the version/kind fields
        (reference `LogEntry.fromJson`)."""
        from ..util import json_utils

        d = text_or_dict if isinstance(text_or_dict, dict) else json_utils.from_json(text_or_dict)
        version = d.get("version")
        if version != LogEntry.VERSION:
            raise ValueError(f"Unsupported log entry version: {version!r}")
        kind = d.get("kind", "CoveringIndex")
        decoder = _ENTRY_DECODERS.get(kind)
        if decoder is None:
            raise ValueError(f"Unsupported log entry kind: {kind!r}")
        return decoder(d)


#: `extra` key carrying source-file paths whose rows are still PRESENT in the
#: index data but logically deleted — folded in by an incremental refresh that
#: observed the files vanish (`actions/refresh.RefreshIncrementalAction`).
#: Readers prune these rows at scan time via the lineage column
#: (`rules.rule_utils.lineage_prune_condition`); the set is physically
#: compacted away (and this key cleared) by the next optimize or full rewrite.
DELETED_SOURCE_FILES_KEY = "deletedSourceFiles"


class IndexLogEntry(LogEntry):
    """The full index metadata record (reference `IndexLogEntry.scala:285-334`)."""

    def __init__(
        self,
        name: str,
        derived_dataset: CoveringIndexProperties,
        content: Content,
        source: Source,
        extra: Optional[Dict[str, Any]] = None,
        kind: str = "CoveringIndex",
    ):
        super().__init__()
        self.name = name
        self.derived_dataset = derived_dataset
        self.content = content
        self.source = source
        self.extra = dict(extra or {})
        self.kind = kind

    # -- helpers mirroring the reference's accessors ------------------------

    @property
    def schema_json(self) -> str:
        return self.derived_dataset.schema_json

    @property
    def indexed_columns(self) -> List[str]:
        return self.derived_dataset.indexed_columns

    @property
    def included_columns(self) -> List[str]:
        return self.derived_dataset.included_columns

    @property
    def num_buckets(self) -> int:
        return self.derived_dataset.num_buckets

    @property
    def created(self) -> bool:
        return self.state == "ACTIVE"

    @property
    def relations(self) -> List[Relation]:
        return self.source.plan.relations

    def signature(self) -> Signature:
        sigs = self.source.plan.fingerprint.signatures
        if len(sigs) != 1:
            raise ValueError(f"expected exactly one signature, got {len(sigs)}")
        return sigs[0]

    def has_lineage(self) -> bool:
        """Whether the index data carries the per-row source-file lineage
        column (`_data_file_name`) — the precondition for delete folding."""
        from ..config import IndexConstants
        from ..engine.schema import Schema

        target = IndexConstants.DATA_FILE_NAME_COLUMN.lower()
        return any(
            n.lower() == target
            for n in Schema.from_json_string(self.schema_json).names
        )

    def deleted_source_files(self) -> List[str]:
        """Source-file paths whose rows remain in the index data but were
        folded as deleted by an incremental refresh (pruned at scan time via
        lineage; cleared by compaction / full rewrite)."""
        v = self.extra.get(DELETED_SOURCE_FILES_KEY)
        return list(v) if v else []

    def index_location(self) -> str:
        """Root directory of the index data (common prefix of content files — may
        span multiple version dirs after incremental refresh)."""
        files = self.content.files()
        if not files:
            return self.content.root.name
        if len(files) == 1:
            return os.path.dirname(files[0])
        return os.path.commonpath(files)

    # -- serde --------------------------------------------------------------

    def to_json(self) -> dict:
        d = self.base_json()
        d.update(
            {
                "name": self.name,
                "derivedDataset": self.derived_dataset.to_json(),
                "content": self.content.to_json(),
                "source": self.source.to_json(),
                "extra": self.extra,
                "kind": self.kind,
            }
        )
        return d

    @staticmethod
    def from_json(d: dict) -> "IndexLogEntry":
        e = IndexLogEntry(
            d["name"],
            CoveringIndexProperties.from_json(d["derivedDataset"]),
            Content.from_json(d["content"]),
            Source.from_json(d["source"]),
            d.get("extra", {}),
            d.get("kind", "CoveringIndex"),
        )
        e.id = d.get("id", 0)
        e.state = d.get("state", "")
        e.timestamp = d.get("timestamp", 0)
        e.enabled = d.get("enabled", True)
        return e

    # -- value equality on config+signature+content+source+state
    #    (reference IndexLogEntry equality) --------------------------------

    def _eq_key(self):
        return (
            self.name.lower(),
            tuple(c.lower() for c in self.indexed_columns),
            tuple(c.lower() for c in self.included_columns),
            self.num_buckets,
            tuple(s.value for s in self.source.plan.fingerprint.signatures),
            tuple(self.content.files()),
            self.state,
        )

    def __eq__(self, other):
        return isinstance(other, IndexLogEntry) and self._eq_key() == other._eq_key()

    def __hash__(self):
        return hash(self._eq_key())


# Registry for polymorphic LogEntry decode; extension index kinds (e.g. DataSkipping)
# register themselves here.
_ENTRY_DECODERS = {
    "CoveringIndex": IndexLogEntry.from_json,
}


def register_entry_kind(kind: str, decoder) -> None:
    _ENTRY_DECODERS[kind] = decoder
