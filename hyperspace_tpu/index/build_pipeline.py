"""Pipelined covering-index build: overlap decode, transfer, sort, and writes.

The serial build (`CoveringIndexBuilder.write` before this module) is a chain:
decode ALL parquet files → concat on host → one bucketize+sort → per-bucket
writes. At the 8M-row bench scale the device spends ~0.2 s sorting inside a
~5 s build — everything else is host work the device waits on. The reference
design hid exactly this behind Spark's pipelined shuffle executors
(PAPER.md §0); this module is the TPU-native equivalent, shaped like a
training input pipeline:

1. **Decode pool** (``HYPERSPACE_BUILD_DECODE_THREADS``): source files decode
   concurrently (pyarrow C++ releases the GIL) through the per-file scan
   cache, each decoded file split into row chunks of at most
   ``HYPERSPACE_BUILD_CHUNK_ROWS``.
2. **Hash / transfer stage**: as each chunk lands, its bucket ids are computed
   (CPU backend) or its key columns are padded to pow2 rows and
   ``jax.device_put`` onto the device (device backend) — staging overlaps the
   remaining decodes instead of serializing after them. Pow2 quantization
   bounds the set of transfer/compile shapes; the staged buffers are donated
   to the sort program, so XLA reuses their memory.
3. **Fused bucketize+sort**: on the device path the bucket hash, chunk
   concatenation, and the stable variadic sort run as ONE jitted program
   (`ops.partition.fused_bucketize_sort_perm`), or the Pallas in-VMEM bitonic
   composite sort for small builds (`pallas_composite_build_sort`). On the
   CPU backend the permutation comes from the exact same
   `ops.partition.host_sort_perm` the serial path uses.
4. **Writer pool** (``HYPERSPACE_BUILD_WRITERS``): per-bucket files gather
   their rows straight from the decoded chunks via ``perm[lo:hi]`` (no
   materialized full-table copy) and encode in parallel, overlapped with each
   other's gathers.

**Determinism contract**: the pipelined build produces BYTE-IDENTICAL index
files to the serial path, for any thread counts. The global row order is
fixed by the same (file order, chunk concat order) the serial concat uses;
bucket hashing is elementwise; the sort permutation comes from the identical
sort implementation over identical arrays; and bucket rows gathered through
``perm[lo:hi]`` equal ``sorted_table[lo:hi]`` by construction.
``HYPERSPACE_BUILD_DECODE_THREADS=1`` bypasses this module entirely and runs
the pre-pipeline serial code path (`tests/test_build_pipeline.py` pins the
two to each other).

The ordering this contract fixes is the engine's ONE canonical build order —
stable (bucket, keys...) with ties broken by original row id — which the
MESH build (`parallel/table_ops.distributed_bucketize_table`, taken instead
of this pipeline when a multi-device mesh claims the source) also produces:
all three build strategies (serial, pipelined, mesh) emit byte-identical
index files, pinned by `tests/test_build_pipeline.py` and
`tests/test_mesh_compile.py` respectively. Any change to the sort tie order
here breaks BOTH contracts at once.

Stage timings (decode/hash/h2d/sort/write, wall, overlap ratio) are recorded
via `telemetry.profiling.record_build_stages` and surfaced in `bench.py`'s
``bench_detail``.
"""

from __future__ import annotations

import os
import queue
import threading
from concurrent.futures import FIRST_EXCEPTION, ThreadPoolExecutor, wait
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..config import IndexConstants
from ..engine import io as engine_io
from ..engine.schema import STRING
from ..engine.table import Column, Table
from ..exceptions import HyperspaceException
from ..telemetry.profiling import StageTimings, record_build_stages

# The decode-pool knob is defined in engine.io (`decode_pool_size`) — ONE
# threading contract shared by the build pipeline, `read_files`, and the
# streaming query executor; re-exported here for existing importers.
from ..engine.io import ENV_DECODE_THREADS

ENV_WRITERS = "HYPERSPACE_BUILD_WRITERS"
ENV_CHUNK_ROWS = "HYPERSPACE_BUILD_CHUNK_ROWS"

_DEFAULT_WRITERS = 8
_DEFAULT_CHUNK_ROWS = 4_000_000


@dataclass(frozen=True)
class PipelineConfig:
    """Env-tunable pipeline knobs. ``decode_threads == 1`` means "serial
    fallback": the caller runs the pre-pipeline code path unchanged."""

    decode_threads: int
    writers: int
    chunk_rows: int

    @staticmethod
    def from_env(n_files: int) -> "PipelineConfig":
        # Shared parse (`engine.io.decode_pool_size`): `1` = serial fallback,
        # explicit values cap at the file count. The build floors n_files at 2
        # so the default still pipelines single-file sources (the
        # decode-threads value doubles as the pipelined-vs-serial flag here).
        decode = engine_io.decode_pool_size(max(2, n_files))
        writers = max(1, int(os.environ.get(ENV_WRITERS, _DEFAULT_WRITERS) or _DEFAULT_WRITERS))
        chunk_rows = max(
            1, int(os.environ.get(ENV_CHUNK_ROWS, _DEFAULT_CHUNK_ROWS) or _DEFAULT_CHUNK_ROWS)
        )
        return PipelineConfig(decode_threads=decode, writers=writers, chunk_rows=chunk_rows)

    @property
    def pipelined(self) -> bool:
        return self.decode_threads != 1


def _pow2_ceil(n: int) -> int:
    return 1 << max(n - 1, 1).bit_length()


def _lineage_column(path: str, n: int) -> Column:
    """The per-file `_data_file_name` column: value-identical to the serial
    path's `Table.from_pydict({...: [path] * n})` (dictionary [path], codes 0)."""
    return Column(STRING, np.zeros(n, dtype=np.int32), np.asarray([path]))


def _decode_file(
    path: str,
    file_format: str,
    wanted: Optional[List[str]],
    partitions,
    lineage: bool,
) -> Table:
    """One file's decoded, decorated table — the per-file unit of the serial
    path (`read_files` semantics incl. partition columns + scan cache), plus
    the lineage column when enabled."""
    file_cols = engine_io.file_columns_for(wanted, partitions)
    t = engine_io.file_table(path, file_format, file_cols)
    t = engine_io.decorate_file_table(t, path, partitions, wanted)
    if lineage:
        cols = dict(t.columns)
        cols[IndexConstants.DATA_FILE_NAME_COLUMN] = _lineage_column(path, t.num_rows)
        t = Table(cols)
    return t


def _effective_chunk_rows(cfg: PipelineConfig) -> int:
    """Sub-file chunking exists to QUANTIZE DEVICE TRANSFERS (bound staging
    buffer sizes); on the CPU path it would only force re-concatenation
    copies, so the chunk is the whole file/table there."""
    from ..ops.backend import use_device_path

    return cfg.chunk_rows if use_device_path() else (1 << 62)


def _split_chunks(t: Table, chunk_rows: int) -> List[Table]:
    """Row-slice a decoded file table into pipeline chunks (numpy views — the
    chunk boundaries have no effect on output order or values)."""
    if t.num_rows <= chunk_rows:
        return [t]
    out = []
    for lo in range(0, t.num_rows, chunk_rows):
        hi = min(lo + chunk_rows, t.num_rows)
        out.append(
            Table(
                {
                    n: Column(
                        c.dtype,
                        c.data[lo:hi],
                        c.dictionary,
                        None if c.validity is None else c.validity[lo:hi],
                    )
                    for n, c in t.columns.items()
                }
            )
        )
    return out


def _concat_key_columns(chunks: List[Table], key_names: List[str]) -> List[Column]:
    """Global key columns in concat order. Deliberately THE `Table.concat`
    implementation (the serial path's concat), restricted to the key columns —
    the bit-for-bit contract depends on identical union-dictionary/promotion/
    validity behavior, so there must be exactly one copy of that logic.
    `Table.concat` returns the single table unchanged (no copies) for the
    warm one-chunk case."""
    merged = Table.concat([t.select(key_names) for t in chunks])
    return [merged.column(n) for n in key_names]


def _sort_pipeline(
    chunks: List[Table],
    chunk_bucket_ids: List[Optional[np.ndarray]],
    staged_device: Optional[List[List["object"]]],
    key_names: List[str],
    num_buckets: int,
    stages: StageTimings,
) -> Tuple[np.ndarray, np.ndarray]:
    """The sort stage: global (perm, starts) over the chunk concat order."""
    from ..ops.backend import use_device_path
    from ..ops.partition import (
        _sort_perm,
        _sortable,
        bucket_starts,
        fused_bucketize_sort_perm,
        host_sort_perm,
        pallas_composite_build_sort,
    )

    n = sum(t.num_rows for t in chunks)
    if not use_device_path():
        with stages.timed("sort"):
            b_host = (
                np.concatenate(chunk_bucket_ids)
                if chunk_bucket_ids
                else np.empty(0, np.int32)
            )
            key_cols = _concat_key_columns(chunks, key_names)
            perm = host_sort_perm(b_host, key_cols, num_buckets)
            sorted_b = b_host[perm]
    elif staged_device is not None:
        # Numeric keys, staged while decoding: hash+concat+sort in ONE
        # donated-buffer program (or the Pallas composite sort when the whole
        # build fits VMEM).
        with stages.timed("sort"):
            valid_lens = [t.num_rows for t in chunks]
            perm = sorted_b = None
            if len(key_names) == 1 and len(staged_device[0]) >= 1:
                import jax.numpy as jnp

                from ..ops.hashing import bucket_id

                if _pow2_ceil(max(n, 1)) <= 32768:
                    key_dev = jnp.concatenate(
                        [c[:v] for c, v in zip(staged_device[0], valid_lens)]
                    )
                    key_cols = _concat_key_columns(chunks, key_names)
                    b_dev = bucket_id(key_cols, [key_dev], num_buckets)
                    res = pallas_composite_build_sort(b_dev, key_dev, n, num_buckets)
                    if res is not None:
                        perm, sorted_b = res
            if perm is None:
                perm, sorted_b = fused_bucketize_sort_perm(
                    staged_device, valid_lens, num_buckets
                )
    else:
        # Device path, but the keys need host-side union-dictionary encoding
        # (strings) — replicate the serial device program over the global
        # key columns.
        import jax.numpy as jnp

        from ..ops.hashing import bucket_id

        with stages.timed("concat"):
            key_cols = _concat_key_columns(chunks, key_names)
        with stages.timed("h2d"):
            arrs = [jnp.asarray(c.data) for c in key_cols]
        with stages.timed("sort"):
            b = bucket_id(key_cols, arrs, num_buckets)
            perm_d, sorted_b_d = _sort_perm(
                b, tuple(_sortable(a) for a in arrs), n
            )
            perm = np.asarray(perm_d)
            sorted_b = np.asarray(sorted_b_d)
    return perm, bucket_starts(sorted_b, num_buckets)


class _BucketWriter:
    """Writer-pool stage: per-bucket gather + parquet encode, GIL-free.

    `prepare()` assembles ONE arrow array per output column over the chunk
    concatenation — decoded values + null mask, exactly what the serial path's
    `table_to_arrow` feeds the writer. Under encoded execution
    (``HYPERSPACE_ENCODED_EXEC``), string columns stay CODES end to end
    instead: the chunk columns re-encode over their union dictionary (the
    exact `Table.concat` implementation the serial path runs), the gather
    moves int32 codes, and `write_bucket` emits a compacted
    `pa.DictionaryArray` through the SAME `encoding.dictionary_arrow_array`
    helper the serial `table_to_arrow` uses — so serial == pipelined stays
    byte-identical in both flag states, and the N decoded strings never
    materialize. `write_bucket` gathers `perm[lo:hi]` with
    `pyarrow.compute.take` and encodes — both C++ paths that release the GIL,
    so the writer pool runs bucket gathers and encodes truly in parallel
    (the earlier numpy per-bucket gather serialized the pool on the GIL).

    `prepare()` is designed to run on its own thread OVERLAPPED with the sort
    stage: the sort only touches the key columns, the writers need them all."""

    def __init__(self, chunks: List[Table], index_data_path: str, stages: StageTimings):
        self.chunks = chunks
        self.names = chunks[0].column_names
        self.index_data_path = index_data_path
        self.stages = stages
        self.arrays: Dict[str, "object"] = {}
        self.dicts: Dict[str, np.ndarray] = {}  # union dict of encoded string cols

    def prepare(self) -> None:
        import pyarrow as pa

        from ..engine import encoding as _encoding

        encode = _encoding.encoded_exec_enabled()
        with self.stages.timed("concat"):
            for name in self.names:
                cols = [t.column(name) for t in self.chunks]
                if any(c.validity is not None for c in cols):
                    if len(cols) == 1:
                        validity = cols[0].validity
                    else:
                        validity = np.concatenate(
                            [
                                c.validity
                                if c.validity is not None
                                else np.ones(len(c), dtype=bool)
                                for c in cols
                            ]
                        )
                    mask = ~validity
                else:
                    mask = None
                if cols[0].is_string and encode:
                    # Encoded path: ONE union re-encode over the chunk
                    # dictionaries (`Table.concat` — the serial concat's own
                    # implementation, so codes and dictionary are bit-equal
                    # to the serial path's) — the gather below then moves
                    # int32 codes, never decoded strings.
                    merged = Table.concat([Table({name: c}) for c in cols])
                    mc = merged.column(name)
                    self.dicts[name] = mc.dictionary
                    self.arrays[name] = pa.array(mc.data, mask=mask)
                elif cols[0].is_string:
                    # Decoded fallback: decode per chunk through its own
                    # dictionary — value-identical to the serial union-
                    # dictionary decode.
                    values = np.concatenate([c.dictionary[c.data] for c in cols])
                    self.arrays[name] = pa.array(values, mask=mask)
                elif len(cols) == 1:
                    self.arrays[name] = pa.array(cols[0].data, mask=mask)
                else:
                    self.arrays[name] = pa.array(
                        np.concatenate([c.data for c in cols]), mask=mask
                    )

    def _bucket_array(self, n: str, lo: int, hi: int):
        """One column's arrow array for rows [lo, hi): a zero-copy slice, or
        — for encoded string columns — the compacted dictionary array built
        from the sliced codes (the shared write-side primitive)."""
        from ..engine import encoding as _encoding

        sl = self.gathered[n].slice(lo, hi - lo)
        if n not in self.dicts:
            return sl
        if sl.null_count:
            mask = np.asarray(sl.is_null())
            codes = np.asarray(sl.fill_null(0))
        else:
            mask = None
            codes = np.asarray(sl)
        return _encoding.dictionary_arrow_array(codes, self.dicts[n], mask)

    def write_bucket(self, b: int, lo: int, hi: int) -> None:
        if hi <= lo:
            return  # empty bucket: no file (same contract as the serial path)
        import pyarrow as pa

        out = pa.table({n: self._bucket_array(n, lo, hi) for n in self.names})
        # Bounded row groups over the key-sorted bucket rows: the footer zone
        # maps then resolve point/range filters INSIDE the bucket file (scan
        # pushdown). Same bound as the serial writer — the byte-identity
        # contract between the two paths includes the row-group layout, and
        # both paths now write through ONE `storage.write` fault/retry site.
        engine_io.checked_write_table(
            out,
            os.path.join(self.index_data_path, f"part-{b:05d}.parquet"),
            row_group_rows=engine_io.index_row_group_rows(),
        )

    def run(self, perm: np.ndarray, starts: np.ndarray, pool_size: int) -> None:
        import pyarrow as pa
        import pyarrow.compute as pc

        num_buckets = len(starts) - 1
        with ThreadPoolExecutor(max_workers=pool_size) as pool:
            # One full gather per column (column-parallel, C++ GIL-free),
            # then per-bucket ZERO-COPY slices feed the encoders — strictly
            # less gather work than per-bucket takes (no re-walk of the
            # bucket index lists) and both stages spread over the pool.
            idx = pa.array(perm)
            futs = {
                n: pool.submit(self._timed_take, pc, self.arrays[n], idx)
                for n in self.names
            }
            self.gathered = {}
            for n, f in futs.items():
                self.gathered[n] = f.result()
                # Release the pre-gather copy as soon as its take resolves:
                # keeps peak memory at ~one extra full-table copy, like the
                # serial path's sorted_table.
                self.arrays.pop(n, None)
            bfuts = [
                pool.submit(self._timed_bucket, b, int(starts[b]), int(starts[b + 1]))
                for b in range(num_buckets)
            ]
            done, _ = wait(bfuts, return_when=FIRST_EXCEPTION)
            for f in done:
                f.result()  # re-raise the first worker failure

    def _timed_take(self, pc, arr, idx):
        with self.stages.timed("take"):
            return pc.take(arr, idx)

    def _timed_bucket(self, b: int, lo: int, hi: int) -> None:
        with self.stages.timed("write"):
            self.write_bucket(b, lo, hi)


def pipelined_write(
    files_in_order: List[str],
    file_format: str,
    wanted: Optional[List[str]],
    partitions,
    lineage: bool,
    key_names: List[str],
    num_buckets: int,
    index_data_path: str,
    cfg: PipelineConfig,
) -> dict:
    """Run the staged build: decode → hash/stage → fused sort → bucket writes.
    Returns the stage-timing summary (also recorded in telemetry)."""
    if not files_in_order:
        raise HyperspaceException("No data files to read.")
    from ..ops.backend import use_device_path

    stages = StageTimings(mode="pipelined-device" if use_device_path() else "pipelined-cpu")
    n_files = len(files_in_order)

    # Warm-source shortcut: when the exact concat this build would assemble is
    # already cached (a prior query or build read the same files + columns),
    # the whole decode stage collapses to reusing it — the "reuse scan_cache
    # entries when warm" contract, one level up.
    if not lineage:
        _, cached_concat = engine_io.concat_cache_probe(
            files_in_order, file_format, wanted, partitions
        )
        if cached_concat is not None:
            stages.add("decode", 0.0)
            return _finish_from_chunks(
                _split_chunks(cached_concat, _effective_chunk_rows(cfg)),
                key_names,
                num_buckets,
                index_data_path,
                cfg,
                stages,
                n_files,
            )

    return _decode_and_finish(
        files_in_order,
        file_format,
        wanted,
        partitions,
        lineage,
        key_names,
        num_buckets,
        index_data_path,
        cfg,
        stages,
    )


def _stage_chunk_device(key_cols: List[Column], stages: StageTimings) -> List["object"]:
    """Pad a chunk's key arrays to pow2 rows and transfer (device path).
    Pow2-quantized staging bounds the set of buffer shapes the fused sort
    program compiles against (and that the compile cache must hold) to log2
    variety; the buffers are later DONATED to the sort program."""
    import jax

    with stages.timed("h2d"):
        bufs = []
        for c in key_cols:
            pad_n = _pow2_ceil(len(c.data))
            host = c.data
            if pad_n != len(host):
                host = np.concatenate([host, np.zeros(pad_n - len(host), host.dtype)])
            bufs.append(jax.device_put(host))
        return bufs


def _hash_chunk(key_cols: List[Column], num_buckets: int, stages: StageTimings) -> np.ndarray:
    """One chunk's bucket ids (CPU path) — elementwise, so the per-chunk
    concat equals the serial whole-table hash."""
    import jax.numpy as jnp

    from ..ops.hashing import bucket_id

    with stages.timed("hash"):
        arrs = [jnp.asarray(c.data) for c in key_cols]
        return np.asarray(bucket_id(key_cols, arrs, num_buckets))


def _stage_or_hash_chunk(
    ch: Table,
    key_names: List[str],
    num_buckets: int,
    device: bool,
    stages: StageTimings,
):
    """(staged device buffers | None, bucket ids | None) for one chunk — THE
    staging decision, shared by the streaming and warm-concat paths so they
    can never diverge (string keys need host union-dictionary encoding and
    disqualify the fused device staging)."""
    key_cols = [ch.column(k) for k in key_names]
    if device:
        if any(c.is_string for c in key_cols):
            return None, None
        return _stage_chunk_device(key_cols, stages), None
    return None, _hash_chunk(key_cols, num_buckets, stages)


def _finish_from_chunks(
    chunks: List[Table],
    key_names: List[str],
    num_buckets: int,
    index_data_path: str,
    cfg: PipelineConfig,
    stages: StageTimings,
    n_files: int,
) -> dict:
    """Hash/stage the given chunks inline (no decode stage to overlap with),
    then run the shared sort + write tail."""
    from ..ops.backend import use_device_path

    device = use_device_path()
    bucket_ids: List[Optional[np.ndarray]] = []
    staged: List[Optional[List["object"]]] = []
    for ch in chunks:
        bufs, b = _stage_or_hash_chunk(ch, key_names, num_buckets, device, stages)
        staged.append(bufs)
        bucket_ids.append(b)
    staged_device = None
    if device and chunks and all(b is not None for b in staged):
        staged_device = [[bufs[k] for bufs in staged] for k in range(len(key_names))]
    return _sort_write_summarize(
        chunks,
        bucket_ids,
        staged_device,
        key_names,
        num_buckets,
        index_data_path,
        cfg,
        stages,
        n_files,
    )


def _decode_and_finish(
    files_in_order: List[str],
    file_format: str,
    wanted: Optional[List[str]],
    partitions,
    lineage: bool,
    key_names: List[str],
    num_buckets: int,
    index_data_path: str,
    cfg: PipelineConfig,
    stages: StageTimings,
) -> dict:
    n_files = len(files_in_order)
    from ..ops.backend import use_device_path

    # Per-file decoded tables land at their file's slot so the chunk order is
    # deterministic regardless of decode completion order.
    file_tables: List[Optional[Table]] = [None] * n_files
    hash_q: "queue.Queue[int | None]" = queue.Queue()

    from .. import resilience as _resilience
    from ..telemetry import accounting as _accounting
    from ..telemetry import faults as _faults

    led = _accounting.current_ledger()  # pool decodes charge the build's ledger
    sc = _resilience.current_scope()  # workers honor the build's deadline

    def decode_one(i: int) -> None:
        with _accounting.use_ledger(led), _resilience.use_scope(sc):
            _faults.check("pool.worker")
            with stages.timed("decode"):
                file_tables[i] = _decode_file(
                    files_in_order[i], file_format, wanted, partitions, lineage
                )
        hash_q.put(i)

    device = use_device_path()
    # Chunk state, filled by the hash/stage worker in completion order (the
    # values are per-chunk and order-independent; chunk identity is the slot).
    chunk_lists: List[Optional[List[Table]]] = [None] * n_files
    chunk_buckets: Dict[Tuple[int, int], np.ndarray] = {}
    staged: Dict[Tuple[int, int], List["object"]] = {}

    hash_err: List[BaseException] = []

    def hash_worker() -> None:
        """Single consumer overlapping per-chunk hash/transfer with the
        remaining decodes; jax dispatch stays single-threaded."""
        done = 0
        while done < n_files:
            i = hash_q.get()
            if i is None:
                return  # abort: a decode worker failed
            t = file_tables[i]
            chunks = _split_chunks(t, _effective_chunk_rows(cfg))
            chunk_lists[i] = chunks
            for j, ch in enumerate(chunks):
                bufs, b = _stage_or_hash_chunk(
                    ch, key_names, num_buckets, device, stages
                )
                if bufs is not None:
                    staged[(i, j)] = bufs
                if b is not None:
                    chunk_buckets[(i, j)] = b
            done += 1

    def hash_worker_guarded() -> None:
        try:
            hash_worker()
        except BaseException as e:  # surfaced after join — never swallowed
            hash_err.append(e)

    hasher = threading.Thread(target=hash_worker_guarded, daemon=True)
    hasher.start()
    try:
        with ThreadPoolExecutor(max_workers=min(cfg.decode_threads, n_files)) as pool:
            futs = [pool.submit(decode_one, i) for i in range(n_files)]
            done, _ = wait(futs, return_when=FIRST_EXCEPTION)
            for f in done:
                f.result()  # re-raise the first decode failure
    except BaseException:
        hash_q.put(None)  # unblock the hash worker before propagating
        raise
    hasher.join()
    if hash_err:
        raise hash_err[0]

    chunks: List[Table] = [c for cl in chunk_lists for c in (cl or [])]
    bucket_ids: List[Optional[np.ndarray]] = [
        chunk_buckets.get((i, j))
        for i, cl in enumerate(chunk_lists)
        for j in range(len(cl or []))
    ]
    staged_device = None
    if device:
        ordered = [
            staged.get((i, j))
            for i, cl in enumerate(chunk_lists)
            for j in range(len(cl or []))
        ]
        if all(bufs is not None for bufs in ordered) and ordered:
            # [key column][chunk] layout for the fused program.
            staged_device = [
                [bufs[k] for bufs in ordered] for k in range(len(key_names))
            ]
    return _sort_write_summarize(
        chunks,
        bucket_ids,
        staged_device,
        key_names,
        num_buckets,
        index_data_path,
        cfg,
        stages,
        n_files,
    )


def _sort_write_summarize(
    chunks: List[Table],
    bucket_ids: List[Optional[np.ndarray]],
    staged_device,
    key_names: List[str],
    num_buckets: int,
    index_data_path: str,
    cfg: PipelineConfig,
    stages: StageTimings,
    n_files: int,
) -> dict:
    os.makedirs(index_data_path, exist_ok=True)
    writer = _BucketWriter(chunks, index_data_path, stages)
    prep_err: List[BaseException] = []

    def prep_guarded() -> None:
        try:
            writer.prepare()
        except BaseException as e:
            prep_err.append(e)

    # Arrow-array assembly (all columns) overlaps the sort (key columns only).
    prep = threading.Thread(target=prep_guarded, daemon=True)
    prep.start()
    perm, starts = _sort_pipeline(
        chunks, bucket_ids, staged_device, key_names, num_buckets, stages
    )
    prep.join()
    if prep_err:
        raise prep_err[0]

    writer.run(perm, starts, cfg.writers)

    summary = stages.summary()
    summary.update(
        {
            "rows": int(perm.shape[0]),
            "files": n_files,
            "chunks": len(chunks),
            "decode_threads": cfg.decode_threads,
            "writers": cfg.writers,
        }
    )
    record_build_stages(summary)
    return summary
