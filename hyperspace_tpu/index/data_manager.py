"""Versioned index-data directories.

Parity: reference `index/IndexDataManager.scala:38-73` — data lives under
`<indexRoot>/v__=<n>/` (hive-partition-style naming); `get_latest_version_id` scans
directory names; `delete` removes one version dir.
"""

from __future__ import annotations

import os
from typing import List, Optional

from ..config import IndexConstants
from ..storage.filesystem import FileSystem, LocalFileSystem


class IndexDataManager:
    def get_latest_version_id(self) -> Optional[int]:
        raise NotImplementedError

    def get_path(self, version_id: int) -> str:
        raise NotImplementedError

    def delete(self, version_id: int) -> None:
        raise NotImplementedError


class IndexDataManagerImpl(IndexDataManager):
    def __init__(self, index_path: str, fs: Optional[FileSystem] = None):
        self._index_path = index_path
        self._fs = fs or LocalFileSystem()

    def _version_ids(self) -> List[int]:
        if not self._fs.exists(self._index_path):
            return []
        prefix = IndexConstants.INDEX_VERSION_DIR_PREFIX + "="
        out = []
        for st in self._fs.list_status(self._index_path):
            if st.is_dir and st.name.startswith(prefix):
                suffix = st.name[len(prefix):]
                if suffix.isdigit():
                    out.append(int(suffix))
        return out

    def get_latest_version_id(self) -> Optional[int]:
        ids = self._version_ids()
        return max(ids) if ids else None

    def get_path(self, version_id: int) -> str:
        return os.path.join(
            self._index_path, f"{IndexConstants.INDEX_VERSION_DIR_PREFIX}={version_id}"
        )

    def delete(self, version_id: int) -> None:
        path = self.get_path(version_id)
        if self._fs.exists(path):
            self._fs.delete(path, recursive=True)
