"""Corruption quarantine: a broken index sits out instead of failing queries.

A truncated or corrupt index bucket file used to kill every query whose plan
the rules had rewritten onto that index. Now the decode failure surfaces as a
`CorruptIndexError` carrying the index name (`engine.physical`), the query
layer marks the index here and RE-PLANS (`DataFrame.collect/count`), and the
rules skip quarantined indexes at candidate selection
(`rules.rule_utils.get_candidate_indexes`, ticking
``rule.<Name>.quarantined``) — the query falls back to the source scan with a
warning and stays correct.

Quarantine is process-local, advisory state (the lake's log is not touched):
any mutation of the index (create/refresh/optimize/vacuum/delete) clears its
entry, since new data supersedes the corrupt files.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..telemetry import metrics as _metrics

_EVENTS = _metrics.counter("index.quarantine.events")
_ACTIVE = _metrics.gauge("index.quarantine.active")

_lock = threading.Lock()
_entries: Dict[str, dict] = {}


def mark(index_name: str, reason: str, path: Optional[str] = None) -> bool:
    """Quarantine `index_name`; False if it already was (the caller then knows
    re-planning cannot help and should propagate the failure)."""
    with _lock:
        if index_name in _entries:
            return False
        _entries[index_name] = {
            "reason": reason,
            "path": path,
            "ts": time.time(),
        }
        _ACTIVE.set(len(_entries))
    _EVENTS.inc()
    return True


def is_quarantined(index_name: str) -> bool:
    with _lock:
        return index_name in _entries


def clear(index_name: Optional[str] = None) -> None:
    """Lift the quarantine of one index (rebuilt/refreshed data supersedes the
    corrupt files) or of all (None)."""
    with _lock:
        if index_name is None:
            _entries.clear()
        else:
            _entries.pop(index_name, None)
        _ACTIVE.set(len(_entries))


def snapshot() -> Dict[str, dict]:
    with _lock:
        return {k: dict(v) for k, v in _entries.items()}
