"""DI seams for log/data managers and filesystems (reference `index/factories.scala:23-50`).

Tests inject fakes here exactly like the reference's mocked factories
(`IndexCollectionManagerTest.scala:29-91`).
"""

from __future__ import annotations

from typing import Optional

from ..storage.filesystem import FileSystem, LocalFileSystem
from .data_manager import IndexDataManager, IndexDataManagerImpl
from .log_manager import IndexLogManager, IndexLogManagerImpl


class FileSystemFactory:
    def create(self, path: str) -> FileSystem:
        """Backend by path scheme (reference `FileSystemFactory.create(path)`,
        `factories.scala:43-50`): remote protocols (memory://, s3://, ...) get the
        fsspec adapter; everything else the local disk."""
        from ..storage.remote import filesystem_for_path

        remote = filesystem_for_path(path)
        return remote if remote is not None else LocalFileSystem()


class IndexLogManagerFactory:
    def create(self, index_path: str, fs: Optional[FileSystem] = None) -> IndexLogManager:
        return IndexLogManagerImpl(index_path, fs)


class IndexDataManagerFactory:
    def create(self, index_path: str, fs: Optional[FileSystem] = None) -> IndexDataManager:
        return IndexDataManagerImpl(index_path, fs)
