"""Index path resolution.

Parity: reference `index/PathResolver.scala:30-106` — resolves the system root
(`spark.hyperspace.system.path`, default `<warehouse>/indexes`) and the per-index path
with a case-insensitive name match against existing directories, so `createIndex("MyIdx")`
followed by `deleteIndex("myidx")` hits the same directory.
"""

from __future__ import annotations

import os
from typing import Optional

from ..config import IndexConstants, SessionConf
from ..storage.filesystem import FileSystem, LocalFileSystem

DEFAULT_INDEX_SYSTEM_DIR = "indexes"


class PathResolver:
    def __init__(self, conf: SessionConf, fs: Optional[FileSystem] = None, warehouse: str = "."):
        self._conf = conf
        self._fs = fs or LocalFileSystem()
        self._warehouse = warehouse

    def system_path(self) -> str:
        p = self._conf.get(IndexConstants.INDEX_SYSTEM_PATH)
        if p:
            return p
        return os.path.join(self._warehouse, DEFAULT_INDEX_SYSTEM_DIR)

    def get_index_path(self, name: str) -> str:
        """Per-index root; reuses an existing dir whose name matches case-insensitively
        (reference :39-58)."""
        root = self.system_path()
        if self._fs.exists(root):
            for st in self._fs.list_status(root):
                if st.is_dir and st.name.lower() == name.lower():
                    return st.path
        return os.path.join(root, name)
