"""Operation log with optimistic concurrency.

Parity: reference `index/IndexLogManager.scala` — numbered JSON entries under
`<indexRoot>/_hyperspace_log/<id>`, `writeLog` refuses existing ids and commits via
temp-file + atomic rename (`:146-162`); `latestStable` pointer copy (`:113-130`);
`getLatestStableLog` falls back to scanning ids descending for a stable state (`:92-111`).
"""

from __future__ import annotations

import os
from typing import List, Optional

from .. import resilience as _resilience
from ..actions.states import STABLE_STATES
from ..config import IndexConstants
from ..exceptions import is_transient
from ..storage.filesystem import FileSystem, LocalFileSystem
from ..telemetry import faults as _faults
from ..util import json_utils
from .log_entry import IndexLogEntry, LogEntry


LATEST_STABLE = "latestStable"


class IndexLogManager:
    """Contract (reference `IndexLogManager.scala:33-55`)."""

    def get_log(self, log_id: int) -> Optional[LogEntry]:
        raise NotImplementedError

    def get_latest_id(self) -> Optional[int]:
        raise NotImplementedError

    def get_latest_log(self) -> Optional[LogEntry]:
        latest = self.get_latest_id()
        return self.get_log(latest) if latest is not None else None

    def get_latest_stable_log(self) -> Optional[LogEntry]:
        raise NotImplementedError

    def create_latest_stable_log(self, log_id: int) -> bool:
        raise NotImplementedError

    def delete_latest_stable_log(self) -> bool:
        raise NotImplementedError

    def write_log(self, log_id: int, entry: LogEntry) -> bool:
        raise NotImplementedError


class IndexLogManagerImpl(IndexLogManager):
    """Filesystem-backed implementation (reference `IndexLogManagerImpl`, :57-163)."""

    def __init__(self, index_path: str, fs: Optional[FileSystem] = None):
        self._index_path = index_path
        self._fs = fs or LocalFileSystem()

    @property
    def _log_dir(self) -> str:
        return os.path.join(self._index_path, IndexConstants.HYPERSPACE_LOG)

    def _path_for(self, log_id) -> str:
        return os.path.join(self._log_dir, str(log_id))

    def _read(self, path: str) -> Optional[LogEntry]:
        if not self._fs.exists(path):
            return None
        return LogEntry.from_json(self._fs.read_text(path))

    def get_log(self, log_id: int) -> Optional[LogEntry]:
        return self._read(self._path_for(log_id))

    def get_latest_id(self) -> Optional[int]:
        if not self._fs.exists(self._log_dir):
            return None
        ids = [
            int(st.name)
            for st in self._fs.list_status(self._log_dir)
            if st.name.isdigit()
        ]
        return max(ids) if ids else None

    def get_latest_stable_log(self) -> Optional[LogEntry]:
        stable = self._read(self._path_for(LATEST_STABLE))
        if stable is not None:
            return stable
        # Fallback: scan ids descending for a stable state (reference :92-111).
        latest = self.get_latest_id()
        if latest is None:
            return None
        for i in range(latest, -1, -1):
            entry = self.get_log(i)
            if entry is not None and entry.state in STABLE_STATES:
                return entry
        return None

    def create_latest_stable_log(self, log_id: int) -> bool:
        entry = self.get_log(log_id)
        if entry is None or entry.state not in STABLE_STATES:
            return False
        # The pointer is an overwritable copy (reference uses FileUtil.copy with
        # overwrite, IndexLogManager.scala:113-130) — unlike numbered entries it is
        # NOT an OCC participant, so replace any existing pointer.
        path = self._path_for(LATEST_STABLE)
        if self._fs.exists(path):
            self._fs.delete(path)
        text = json_utils.to_json(entry.to_json())
        return self._atomic_write_with_retry(path, text)

    def _atomic_write_with_retry(self, path: str, text: str) -> bool:
        """Retry-safe atomic commit: transient faults retry with backoff, and
        a fault raised AFTER our own rename landed (e.g. the temp-file cleanup
        delete failing on a flaky fs) is recognized by re-reading the target —
        the retry must NOT see our own committed write as a lost OCC race
        (which would abort the action over its own success). A `False` return
        is a real OCC loss: a decided outcome, never retried."""

        def _attempt() -> bool:
            _faults.check("log.write")
            try:
                return self._fs.atomic_write_text(path, text)
            except BaseException as e:
                if is_transient(e) and self._content_is(path, text):
                    return True  # our write committed before the fault
                raise

        return _resilience.retry_io("log.write", _attempt)

    def _content_is(self, path: str, text: str) -> bool:
        try:
            return self._fs.exists(path) and self._fs.read_text(path) == text
        except Exception:
            return False

    def delete_latest_stable_log(self) -> bool:
        path = self._path_for(LATEST_STABLE)
        if not self._fs.exists(path):
            return True
        # The real failure mode here is an fs EXCEPTION, not a False return:
        # transient ones retry; a persistent one propagates for the caller
        # (`Action.end`) to classify as LogCommitError.
        _resilience.retry_io("log.write", lambda: self._fs.delete(path))
        return True

    def write_log(self, log_id: int, entry: LogEntry) -> bool:
        """OCC point: fails if ``log_id`` already exists (reference :146-162).

        The caller's entry is not mutated on a lost race: the id is stamped onto the
        serialized record, and written back to the entry only after the commit wins."""
        d = entry.to_json()
        d["id"] = log_id
        text = json_utils.to_json(d)
        ok = self._atomic_write_with_retry(self._path_for(log_id), text)
        if ok:
            entry.id = log_id
        return ok
