"""CoveringIndexBuilder: the engine-side implementation of index creation/refresh.

Parity: reference `actions/CreateActionBase.scala` — validates the source plan, builds
the IndexLogEntry (signature over source files, relation inventory, numBuckets from
conf), and writes the index data. The write path is TPU-native: one `lax.sort` over
(bucket_id, indexed columns) replaces Spark's repartition+shuffle+per-bucket-sort
(see `ops/partition.py`), then per-bucket parquet files are written under the
`part-<bucket>` naming contract the bucketed join scan relies on.
"""

from __future__ import annotations

import os
from typing import List, Optional

from ..actions.create import IndexerBuilder
from ..config import IndexConstants
from ..engine import io as engine_io
from ..engine.logical import ScanNode, SourceRelation
from ..engine.schema import STRING, Field, Schema
from ..engine.session import DataFrame, HyperspaceSession
from ..engine.table import Column, Table
from ..exceptions import HyperspaceException
from ..ops.partition import bucketize_table
from ..util.resolver_utils import resolve_all
from .index_config import IndexConfig
from .log_entry import (
    Content,
    CoveringIndexProperties,
    Directory,
    IndexLogEntry,
    LogicalPlanFingerprint,
    Relation,
    Signature,
    Source,
    SourcePlanProperties,
)
from .signatures import create_provider


class CoveringIndexBuilder(IndexerBuilder):
    def __init__(self, session: HyperspaceSession):
        self._session = session

    # -- validation (reference CreateAction.scala:44-64) --------------------

    def validate_source(self, df: DataFrame, index_config: IndexConfig) -> None:
        if not isinstance(df.plan, ScanNode):
            raise HyperspaceException(
                "Only creating index over a plain relation scan is supported."
            )
        schema_names = df.plan.output_schema.names
        cs = self._session.hs_conf.case_sensitive
        for group in (index_config.indexed_columns, index_config.included_columns):
            if resolve_all(group, schema_names, cs) is None:
                raise HyperspaceException(
                    f"Index config columns {group} could not be resolved against "
                    f"dataframe columns {schema_names}."
                )

    def _resolved_columns(self, df: DataFrame, index_config: IndexConfig):
        names = df.plan.output_schema.names
        cs = self._session.hs_conf.case_sensitive
        indexed = resolve_all(index_config.indexed_columns, names, cs)
        included = resolve_all(index_config.included_columns, names, cs)
        return indexed, included

    # -- the build (reference CreateActionBase.scala:119-191) ---------------

    def _missing_partition_columns(self, rel: SourceRelation, wanted: List[str]) -> List[str]:
        """Partition columns not already selected — lineage mode pulls them into the
        index so a lineage row can be mapped back to its source partition
        (reference `CreateActionBase.scala:176-188`)."""
        if rel.partition_spec is None:
            return []
        have = {w.lower() for w in wanted}
        return [c for c in rel.partition_spec.columns if c.lower() not in have]

    def _prepare_index_table(self, df: DataFrame, index_config: IndexConfig) -> Table:
        """Select indexed+included columns (+ lineage `_data_file_name` and missing
        partition columns when lineage is enabled)."""
        indexed, included = self._resolved_columns(df, index_config)
        rel = df.plan.relation
        wanted = indexed + included
        partitions = (
            None
            if rel.partition_spec is None
            else (rel.partition_spec, rel.root_paths)
        )
        if self._session.hs_conf.lineage_enabled:
            wanted = wanted + self._missing_partition_columns(rel, wanted)
            parts = []
            for f in rel.files:
                t = engine_io.read_files(
                    [f.path], rel.file_format, wanted, partitions=partitions
                )
                lineage = Table.from_pydict(
                    {IndexConstants.DATA_FILE_NAME_COLUMN: [f.path] * t.num_rows}
                )
                cols = dict(t.columns)
                cols[IndexConstants.DATA_FILE_NAME_COLUMN] = lineage.column(
                    IndexConstants.DATA_FILE_NAME_COLUMN
                )
                parts.append(Table(cols))
            return Table.concat(parts)
        files = [f.path for f in rel.files]
        return engine_io.read_files(files, rel.file_format, wanted, partitions=partitions)

    def write(self, df: DataFrame, index_config: IndexConfig, index_data_path: str) -> None:
        """The bucketed build. Routed three ways:

        - mesh build (distributed all_to_all) when a device mesh applies;
        - the staged PIPELINE (`index/build_pipeline.py`) by default — decode,
          transfer, fused bucketize+sort and bucket writes overlap;
        - the pre-pipeline SERIAL chain under
          ``HYPERSPACE_BUILD_DECODE_THREADS=1`` (the bit-for-bit reference
          the pipeline is pinned to by `tests/test_build_pipeline.py`).

        Crash-safe commit: the build writes into a dot-prefixed STAGING
        directory that every inventory/scan path ignores, committed to
        `index_data_path` by ONE atomic rename (`index/staging.py`). A failure
        deletes the staging dir; a SIGKILL at any point leaves either an
        invisible staging dir (reclaimed by the next action on the index) or
        the complete committed dir — never partial visible files for a later
        `Content.from_directory` inventory to pick up (the log entry stays
        uncommitted either way)."""
        from .staging import stage_commit

        with stage_commit(index_data_path) as stage:
            self._write_routed(df, index_config, stage)

    def _write_routed(
        self, df: DataFrame, index_config: IndexConfig, index_data_path: str
    ) -> None:
        from .build_pipeline import PipelineConfig, pipelined_write

        indexed, included = self._resolved_columns(df, index_config)
        num_buckets = self._session.hs_conf.num_buckets
        rel = df.plan.relation
        cfg = PipelineConfig.from_env(len(rel.files))
        if cfg.pipelined and not self._mesh_may_apply(rel):
            lineage = self._session.hs_conf.lineage_enabled
            wanted = indexed + included
            if lineage:
                wanted = wanted + self._missing_partition_columns(rel, wanted)
            partitions = (
                None
                if rel.partition_spec is None
                else (rel.partition_spec, rel.root_paths)
            )
            files_in_order = (
                # Lineage reads per file in inventory order; the plain path
                # rides `read_files`, which sorts — the pipeline's chunk
                # order must match the serial concat order exactly.
                [f.path for f in rel.files]
                if lineage
                else sorted(f.path for f in rel.files)
            )
            pipelined_write(
                files_in_order,
                rel.file_format,
                wanted,
                partitions,
                lineage,
                indexed,
                num_buckets,
                index_data_path,
                cfg,
            )
            return

        from ..telemetry.profiling import StageTimings, record_build_stages

        stages = StageTimings(mode="serial")
        with stages.timed("decode"):
            table = self._prepare_index_table(df, index_config)
        mesh = self._session.mesh_for(table.num_rows)
        if mesh is not None:
            stages.mode = "mesh"
        with stages.timed("sort"):
            if mesh is not None:
                # Cluster-wide build (the reference's repartition+bucketed-write
                # runs on the whole Spark cluster, `CreateActionBase.scala:119-140`):
                # rows ride an all_to_all over the mesh; identical hash →
                # identical index files.
                from ..parallel.table_ops import distributed_bucketize_table

                sorted_table, starts = distributed_bucketize_table(
                    mesh, table, indexed, num_buckets
                )
            else:
                sorted_table, starts = bucketize_table(table, indexed, num_buckets)
        os.makedirs(index_data_path, exist_ok=True)
        import numpy as np
        from concurrent.futures import ThreadPoolExecutor

        def write_bucket(b: int) -> None:
            lo, hi = int(starts[b]), int(starts[b + 1])
            if hi <= lo:
                return  # empty bucket: no file
            bucket_table = sorted_table.take(np.arange(lo, hi))
            # Bounded, key-sorted row groups (same bound as the pipelined
            # writer — the byte-identity contract includes the layout): scan
            # pushdown prunes inside bucket files through the footer stats.
            engine_io.write_parquet(
                bucket_table,
                os.path.join(index_data_path, f"part-{b:05d}.parquet"),
                row_group_rows=engine_io.index_row_group_rows(),
            )

        # Parquet encode is pyarrow C++ work that releases the GIL: writing the
        # bucket files concurrently keeps the build from serializing on host I/O
        # (SURVEY §7 — the executors of the reference's bucketed write ran
        # cluster-wide for the same reason).
        # Per-bucket tasks for BOTH paths: the pool load-balances small
        # parquet encodes regardless of which device owned a bucket. The mesh
        # layout's per-shard file ownership (device d's exchange block IS its
        # contiguous bucket range [d·B/n, (d+1)·B/n)) matters on a MULTI-HOST
        # mesh, where each host would map only its own devices' bucket range
        # here — on one host, coarser shard-sized tasks would only serialize
        # a skewed shard's writes behind one worker. File names and bytes are
        # identical across the mesh and single-device paths either way.
        with stages.timed("write"):
            with ThreadPoolExecutor(max_workers=cfg.writers) as pool:
                list(pool.map(write_bucket, range(num_buckets)))
        summary = stages.summary()
        summary["rows"] = table.num_rows
        record_build_stages(summary)

    def _mesh_may_apply(self, rel: SourceRelation) -> bool:
        """Whether the distributed mesh build could claim this source — decided
        BEFORE decoding (the pipeline wants to stream chunks, the mesh build
        wants the whole table). Parquet row counts come from the footers; for
        formats without cheap counts the answer is conservatively True, which
        routes to the legacy path where `mesh_for(table.num_rows)` decides
        exactly as before."""
        if not self._session.hs_conf.distributed_enabled:
            return False
        import jax

        if len(jax.devices()) < 2:
            return False
        if rel.file_format not in ("parquet", "delta"):
            return True
        try:
            import pyarrow.parquet as pq

            est = sum(pq.ParquetFile(f.path).metadata.num_rows for f in rel.files)
        except Exception:
            return True
        return self._session.mesh_for(est) is not None

    # -- metadata derivation (reference CreateActionBase.scala:41-117) ------

    def _index_schema(self, df: DataFrame, index_config: IndexConfig) -> Schema:
        indexed, included = self._resolved_columns(df, index_config)
        src = df.plan.output_schema
        fields: List[Field] = [src.field(n) for n in indexed + included]
        if self._session.hs_conf.lineage_enabled:
            for p in self._missing_partition_columns(df.plan.relation, indexed + included):
                fields.append(src.field(p))
            fields.append(Field(IndexConstants.DATA_FILE_NAME_COLUMN, STRING))
        return Schema(fields)

    def derive_log_entry(
        self, df: DataFrame, index_config: IndexConfig, index_path: str, index_data_path: str
    ) -> IndexLogEntry:
        rel = df.plan.relation
        provider = create_provider()
        sig = provider.signature(df.plan)
        if sig is None:
            raise HyperspaceException("Signature provider does not support this plan.")
        indexed, included = self._resolved_columns(df, index_config)

        relation = Relation(
            root_paths=list(rel.root_paths),
            data=Content(Directory.from_leaf_files("/", rel.files)),
            data_schema_json=rel.schema.to_json_string(),
            file_format=rel.file_format,
            options=dict(rel.options),
        )
        entry = IndexLogEntry(
            name=index_config.index_name,
            derived_dataset=CoveringIndexProperties(
                indexed_columns=indexed,
                included_columns=included,
                schema_json=self._index_schema(df, index_config).to_json_string(),
                num_buckets=self._session.hs_conf.num_buckets,
                properties={
                    IndexConstants.HASH_SCHEME_KEY: IndexConstants.HASH_SCHEME_VERSION
                },
            ),
            content=Content.from_directory(index_data_path, self._session.fs),
            source=Source(
                SourcePlanProperties(
                    relations=[relation],
                    fingerprint=LogicalPlanFingerprint(
                        signatures=[Signature(provider.name, sig)]
                    ),
                )
            ),
        )
        return entry

    # -- refresh support (reference RefreshAction.scala:44-56) --------------

    def reconstruct_df(self, relation: Relation) -> DataFrame:
        reader = self._session.read
        fmt = relation.file_format
        if fmt == "parquet":
            return reader.parquet(*relation.root_paths)
        if fmt == "csv":
            return reader.csv(*relation.root_paths)
        if fmt == "json":
            return reader.json(*relation.root_paths)
        if fmt == "orc":
            return reader.orc(*relation.root_paths)
        if fmt == "delta":
            return reader.delta(*relation.root_paths)
        raise HyperspaceException(f"Unsupported file format: {fmt}")

    def restrict_df_to_files(self, df: DataFrame, file_paths) -> DataFrame:
        """A view of the same relation limited to a subset of its files (used by
        incremental refresh to index only appended data)."""
        from ..engine.logical import ScanNode, SourceRelation
        from ..engine.session import DataFrame as DF

        rel = df.plan.relation
        wanted = set(file_paths)
        sub = SourceRelation(
            root_paths=list(rel.root_paths),
            file_format="parquet" if rel.file_format == "delta" else rel.file_format,
            schema=rel.schema,
            files=[f for f in rel.files if f.path in wanted],
            options=dict(rel.options),
            partition_spec=rel.partition_spec,
        )
        return DF(self._session, ScanNode(sub))
