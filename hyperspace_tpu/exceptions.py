"""Framework exception taxonomy.

Parity: reference `HyperspaceException.scala:19` — one exception class carrying a
message, raised for all user-facing error conditions. The reproduction extends the
single class into a transient/permanent taxonomy (absent from the v0 reference,
which delegated fault handling to Spark's task retry machinery): the resilience
layer (`hyperspace_tpu.resilience`) retries `TransientError`s with bounded
exponential backoff, while `PermanentError`s fail fast — and index-data
corruption (`CorruptIndexError`) routes to quarantine + source-scan fallback
instead of failing the query at all.
"""

from __future__ import annotations

from typing import Optional


class HyperspaceException(Exception):
    """Raised for all Hyperspace-TPU error conditions (validation, concurrency, state)."""

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class TransientError(HyperspaceException):
    """A fault that a bounded retry may clear (flaky IO, injected transient
    faults). The ONLY HyperspaceException subclass `resilience.retry_io`
    retries."""


class PermanentError(HyperspaceException):
    """A fault retrying cannot clear (corrupt data, missing files, contract
    violations). Never retried."""


class CorruptIndexError(PermanentError):
    """An index data file failed to parse (truncated/corrupt bucket file).

    Carries the index name so the query layer can QUARANTINE the index and
    re-plan against the source data — the query stays correct, the index sits
    out until rebuilt (`index/quarantine.py`)."""

    def __init__(self, message: str, index_name: str, path: Optional[str] = None):
        super().__init__(message)
        self.index_name = index_name
        self.path = path


class ConcurrentWriteError(HyperspaceException):
    """Lost the operation-log optimistic-concurrency race: another writer
    committed the contested log id first. The loser aborts cleanly (its staged
    data is discarded) and may retry from scratch."""


class LogCommitError(HyperspaceException):
    """A metadata-log write that MUST succeed failed for a non-OCC reason
    (e.g. the latestStable pointer write) — the classified replacement for the
    silently-ignored `bool` returns the log manager used to hand back."""


class QueryTimeoutError(HyperspaceException):
    """The query exceeded ``HYPERSPACE_QUERY_TIMEOUT_S``. Raised at a chunk or
    pool boundary (cooperative cancellation) — workers drain and no partial
    cache/memo entry is left behind (the standing only-cache-on-success
    contract)."""

    def __init__(self, message: str, elapsed_s: float = 0.0, timeout_s: float = 0.0):
        super().__init__(message)
        self.elapsed_s = elapsed_s
        self.timeout_s = timeout_s


class CompileTimeoutError(QueryTimeoutError):
    """An XLA compile (an `observed_jit` program) exceeded
    ``HYPERSPACE_COMPILE_TIMEOUT_S`` — the classified, program-attributed
    replacement for the r05 silent 2400 s compile hang."""


class RetryBudgetExceededError(PermanentError):
    """One query burned through its per-query retry budget
    (``HYPERSPACE_QUERY_RETRY_BUDGET``) — the fault is transient per site but
    systemic per query, so failing is better than retrying forever."""


class AdmissionRejectedError(PermanentError):
    """The serving layer REFUSED to run the query (`serve.scheduler`):
    the submission queue is past ``HYPERSPACE_SERVE_QUEUE_DEPTH``, or the
    tenant is past its ``HYPERSPACE_SERVE_TENANT_BUDGET`` of in-flight
    queries. Classified as permanent so `resilience.retry_io` never spins on
    an overloaded server — load shedding is the CALLER's backpressure signal
    (retry later, with backoff of its own choosing). Carries the machine-
    readable `reason` (``queue_depth`` / ``tenant_budget``) and `tenant`."""

    def __init__(self, message: str, reason: str = "", tenant: str = ""):
        super().__init__(message)
        self.reason = reason
        self.tenant = tenant


def is_transient(exc: BaseException) -> bool:
    """Whether `exc` is retry-eligible. Hyperspace's own taxonomy decides for
    framework errors; for foreign exceptions, connection-ish/OS-level IO
    errors are transient (flaky network filesystems) while parse errors,
    missing files, and everything else are not."""
    if isinstance(exc, TransientError):
        return True
    if isinstance(exc, HyperspaceException):
        return False
    if isinstance(exc, (FileNotFoundError, IsADirectoryError, PermissionError)):
        return False
    if isinstance(exc, (ConnectionError, TimeoutError, InterruptedError, OSError)):
        # Note: pyarrow's ArrowInvalid (corrupt parquet) subclasses ValueError,
        # not OSError — parse failures are correctly permanent here.
        return True
    return False
