"""The single framework exception type.

Parity: reference `HyperspaceException.scala:19` — one exception class carrying a
message, raised for all user-facing error conditions.
"""


class HyperspaceException(Exception):
    """Raised for all Hyperspace-TPU error conditions (validation, concurrency, state)."""

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message
