"""Configuration system.

Parity: the reference piggybacks on Spark SQLConf with the `spark.hyperspace.*` namespace;
all keys + defaults are centralized in `index/IndexConstants.scala:21-57` with the typed
accessor `util/HyperspaceConf.scala`. Here the session carries a flat string-keyed conf
(`SessionConf`) with the same knob set, plus typed accessors (`HyperspaceConf`).
"""

from __future__ import annotations

from typing import Dict, List, Optional


class IndexConstants:
    """All config keys and defaults (reference `index/IndexConstants.scala:21-57`)."""

    INDEX_SYSTEM_PATH = "hyperspace.system.path"
    INDEX_CREATION_PATH = "hyperspace.index.creation.path"
    INDEX_SEARCH_PATHS = "hyperspace.index.search.paths"

    INDEX_NUM_BUCKETS = "hyperspace.index.num.buckets"
    INDEX_NUM_BUCKETS_DEFAULT = 200  # reference default = spark.sql.shuffle.partitions

    INDEX_CACHE_EXPIRY_DURATION_SECONDS = "hyperspace.index.cache.expiryDurationInSeconds"
    INDEX_CACHE_EXPIRY_DURATION_SECONDS_DEFAULT = 300

    # Metadata-cache policy (reference `IndexCacheFactory.scala:23-38` keys the
    # cache impl by type name; CREATION_TIME_BASED is the only built-in).
    INDEX_CACHE_TYPE = "hyperspace.index.cache.type"
    INDEX_CACHE_TYPE_DEFAULT = "CREATION_TIME_BASED"

    INDEX_HYBRID_SCAN_ENABLED = "hyperspace.index.hybridscan.enabled"
    INDEX_HYBRID_SCAN_ENABLED_DEFAULT = False

    INDEX_LINEAGE_ENABLED = "hyperspace.index.lineage.enabled"
    INDEX_LINEAGE_ENABLED_DEFAULT = False
    DATA_FILE_NAME_COLUMN = "_data_file_name"

    # Bucket/sketch hash-scheme version recorded at build time. Bucket
    # co-location across independently built indexes (and bloom-sketch
    # probing) requires BUILD and QUERY to hash identically, so a future
    # hash-function change must bump this — candidates built under another
    # scheme are then skipped instead of silently mis-joined. "1" = the
    # kind-split exact scheme (ints as int64 bits, floats as float64 bits);
    # entries with no recorded version predate the field and used scheme 1.
    HASH_SCHEME_KEY = "hashSchemeVersion"
    HASH_SCHEME_VERSION = "1"

    # On-lake layout names (reference `IndexConstants.scala:41-42`).
    HYPERSPACE_LOG = "_hyperspace_log"
    INDEX_VERSION_DIR_PREFIX = "v__"

    # Explain display modes (reference `IndexConstants.scala:45-52`).
    DISPLAY_MODE = "hyperspace.explain.displayMode"
    HIGHLIGHT_BEGIN_TAG = "hyperspace.explain.displayMode.highlight.beginTag"
    HIGHLIGHT_END_TAG = "hyperspace.explain.displayMode.highlight.endTag"

    EVENT_LOGGER_CLASS = "hyperspace.eventLoggerClass"

    # Column-name resolution (the spark.sql.caseSensitive analogue; reference
    # `util/ResolverUtils.scala:26-74` reads the session resolver). Consumed by
    # index creation, both covering rules, data skipping, and planner pruning.
    RESOLUTION_CASE_SENSITIVE = "hyperspace.resolution.caseSensitive"
    RESOLUTION_CASE_SENSITIVE_DEFAULT = False

    # Point-lookup bucket pruning for the filter-index rewrite (north-star
    # extension; the reference always scanned every index file,
    # `FilterIndexRule.scala:100-132`). An equality/IN filter on the head
    # indexed column can only match rows in the literals' hash buckets, so the
    # substituted scan reads just those `part-<bucket>` files.
    INDEX_FILTER_BUCKET_PRUNING = "hyperspace.index.filter.bucketPruning"
    INDEX_FILTER_BUCKET_PRUNING_DEFAULT = True

    # Data-skipping extension (north-star; absent from the v0 reference snapshot).
    DATASKIPPING_TARGET_INDEX_DATA_FILE_SIZE = "hyperspace.index.dataskipping.targetIndexDataFileSize"

    # Number of mesh devices the build path shards over (TPU-native knob; no
    # reference analogue — Spark's parallelism came from its cluster manager).
    BUILD_MESH_DEVICES = "hyperspace.build.mesh.devices"

    # Distributed execution over the ambient device mesh (TPU-native knobs; the
    # reference's analogue is Spark's cluster, which is ambient the same way).
    # When enabled and >1 jax device is visible, index builds exchange rows over
    # the mesh (all_to_all) and joins execute as sharded per-bucket kernels.
    DISTRIBUTED_ENABLED = "hyperspace.distributed.enabled"
    DISTRIBUTED_ENABLED_DEFAULT = True
    # Below this row count single-device execution wins (exchange + shard_map
    # compile overhead dwarfs the work); tests set 0 to force the mesh path.
    DISTRIBUTED_MIN_ROWS = "hyperspace.distributed.minRows"
    DISTRIBUTED_MIN_ROWS_DEFAULT = 65536
    # Hash partitions per device for the general-join exchange (more partitions =
    # finer probe granularity per device, more padding overhead).
    DISTRIBUTED_PARTITIONS_PER_DEVICE = "hyperspace.distributed.partitionsPerDevice"
    DISTRIBUTED_PARTITIONS_PER_DEVICE_DEFAULT = 8


class SessionConf:
    """Flat string-keyed conf map with defaults (the SQLConf analogue)."""

    def __init__(self, initial: Optional[Dict[str, str]] = None):
        self._conf: Dict[str, str] = dict(initial or {})

    def set(self, key: str, value) -> None:
        self._conf[key] = str(value)

    def unset(self, key: str) -> None:
        self._conf.pop(key, None)

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._conf.get(key, default)

    def get_int(self, key: str, default: int) -> int:
        v = self._conf.get(key)
        return int(v) if v is not None else default

    def get_bool(self, key: str, default: bool) -> bool:
        v = self._conf.get(key)
        if v is None:
            return default
        return v.strip().lower() in ("1", "true", "yes", "on")

    def copy(self) -> "SessionConf":
        return SessionConf(dict(self._conf))


class HyperspaceConf:
    """Typed accessors over a SessionConf (reference `util/HyperspaceConf.scala`)."""

    def __init__(self, conf: SessionConf):
        self._c = conf

    @property
    def num_buckets(self) -> int:
        return self._c.get_int(
            IndexConstants.INDEX_NUM_BUCKETS, IndexConstants.INDEX_NUM_BUCKETS_DEFAULT
        )

    @property
    def hybrid_scan_enabled(self) -> bool:
        return self._c.get_bool(
            IndexConstants.INDEX_HYBRID_SCAN_ENABLED,
            IndexConstants.INDEX_HYBRID_SCAN_ENABLED_DEFAULT,
        )

    @property
    def lineage_enabled(self) -> bool:
        return self._c.get_bool(
            IndexConstants.INDEX_LINEAGE_ENABLED, IndexConstants.INDEX_LINEAGE_ENABLED_DEFAULT
        )

    @property
    def cache_expiry_seconds(self) -> int:
        return self._c.get_int(
            IndexConstants.INDEX_CACHE_EXPIRY_DURATION_SECONDS,
            IndexConstants.INDEX_CACHE_EXPIRY_DURATION_SECONDS_DEFAULT,
        )

    @property
    def cache_type(self) -> str:
        return self._c.get(
            IndexConstants.INDEX_CACHE_TYPE, IndexConstants.INDEX_CACHE_TYPE_DEFAULT
        )

    @property
    def system_path(self) -> Optional[str]:
        return self._c.get(IndexConstants.INDEX_SYSTEM_PATH)

    @property
    def search_paths(self) -> Optional[List[str]]:
        v = self._c.get(IndexConstants.INDEX_SEARCH_PATHS)
        return v.split(",") if v else None

    @property
    def event_logger_class(self) -> Optional[str]:
        return self._c.get(IndexConstants.EVENT_LOGGER_CLASS)

    @property
    def case_sensitive(self) -> bool:
        return self._c.get_bool(
            IndexConstants.RESOLUTION_CASE_SENSITIVE,
            IndexConstants.RESOLUTION_CASE_SENSITIVE_DEFAULT,
        )

    @property
    def filter_bucket_pruning(self) -> bool:
        return self._c.get_bool(
            IndexConstants.INDEX_FILTER_BUCKET_PRUNING,
            IndexConstants.INDEX_FILTER_BUCKET_PRUNING_DEFAULT,
        )

    @property
    def build_mesh_devices(self) -> int:
        return self._c.get_int(IndexConstants.BUILD_MESH_DEVICES, 1)

    @property
    def distributed_enabled(self) -> bool:
        # HYPERSPACE_DISTRIBUTED is the process-level master switch, in the
        # standing env-flag fallback-contract style (BUILD_DECODE_THREADS,
        # QUERY_STREAMING, ...): "0" pins the exact single-device path
        # byte-for-byte, "1" (or any other non-empty value) enables the mesh
        # path, unset defers to the session conf. Read per call so tests can
        # flip it without touching session state.
        import os

        env = os.environ.get("HYPERSPACE_DISTRIBUTED")
        if env is not None and env != "":
            return env != "0"
        return self._c.get_bool(
            IndexConstants.DISTRIBUTED_ENABLED, IndexConstants.DISTRIBUTED_ENABLED_DEFAULT
        )

    @property
    def distributed_min_rows(self) -> int:
        return self._c.get_int(
            IndexConstants.DISTRIBUTED_MIN_ROWS, IndexConstants.DISTRIBUTED_MIN_ROWS_DEFAULT
        )

    @property
    def partitions_per_device(self) -> int:
        # Clamped: 0/negative would reach the exchange as a zero modulus and
        # fail far from the misconfigured key.
        return max(
            1,
            self._c.get_int(
                IndexConstants.DISTRIBUTED_PARTITIONS_PER_DEVICE,
                IndexConstants.DISTRIBUTED_PARTITIONS_PER_DEVICE_DEFAULT,
            ),
        )
