"""Process-liveness probe shared by every dead-writer reclaim path.

`index/staging.py` (staging-dir orphans) and `telemetry/history.py`
(history-segment compaction) both key same-host reclamation on "is the
writer's pid alive" — one implementation, so a future refinement (EPERM
classification on hardened kernels, pid-reuse guards) cannot diverge
between the two. Lives in `util/` because both layers may import it
(`index` already imports `telemetry`; the reverse edge must not exist).
"""

from __future__ import annotations

import os


def pid_alive(pid: int) -> bool:
    """Whether `pid` refers to a live process ON THIS HOST. Errs on the
    side of "alive": anything other than a definitive ProcessLookupError
    means the owner might still be running, and a reclaim path must never
    delete what might be live."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    except OSError:
        return True  # unknown: never reclaim what might be live
