from . import hashing_utils, json_utils, path_utils, resolver_utils  # noqa: F401
