"""Path helpers.

Parity: reference `util/PathUtils.scala:21-38` — absolute-path normalization and the
data-path filter that hides `_*`/`.*` metadata files (except hive-style partition dirs,
which contain `=`).
"""

from __future__ import annotations

import os


def make_absolute(path: str) -> str:
    return os.path.abspath(path)


def is_data_path(name: str) -> bool:
    """True if a file/dir name is user data (not `_`/`.`-prefixed metadata).

    Hive-style partition directory names like ``v__=12`` or ``date=2020-01-01`` are
    data paths even when they begin with ``_`` (reference `PathUtils.DataPathFilter`).
    """
    base = os.path.basename(name.rstrip("/"))
    # The '=' exception applies only to '_'-prefixed names; '.'-prefixed is always
    # metadata (reference PathUtils.scala:33-38).
    return not ((base.startswith("_") and "=" not in base) or base.startswith("."))
