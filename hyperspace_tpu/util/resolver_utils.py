"""Column-name resolution honoring case sensitivity.

Parity: reference `util/ResolverUtils.scala:26-74` — resolves requested column names
against available ones using the session resolver (case-insensitive by default,
controlled by conf `caseSensitive`). Returns the *available* spelling on match, so
downstream code uses the canonical column name.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence


def resolve(requested: str, available: Iterable[str], case_sensitive: bool = False) -> Optional[str]:
    """Resolve one requested column name; returns canonical (available) spelling or None."""
    for a in available:
        if requested == a if case_sensitive else requested.lower() == a.lower():
            return a
    return None


def resolve_all(
    requested: Sequence[str], available: Iterable[str], case_sensitive: bool = False
) -> Optional[List[str]]:
    """Resolve all requested names; None if any fails to resolve."""
    avail = list(available)
    out: List[str] = []
    for r in requested:
        m = resolve(r, avail, case_sensitive)
        if m is None:
            return None
        out.append(m)
    return out


def resolution_key(name: str, case_sensitive: bool = False):
    """The canonical comparison key for one column name under the session's
    case-sensitivity conf — the ONE home of the `name if cs else name.lower()`
    rule, shared by the rewrite rules and planner pruning."""
    return name if case_sensitive else name.lower()
