"""JSON (de)serialization helpers.

Parity: reference `util/JsonUtils.scala:28-45` (Jackson pretty printer, Include.ALWAYS).
Here: stdlib json with stable key order off (insertion order preserved), pretty output,
and dataclass-aware encoding handled by the caller via `to_json_dict` protocols.
"""

from __future__ import annotations

import json
from typing import Any


def to_json(obj: Any) -> str:
    """Serialize a JSON-compatible object tree to a pretty-printed string."""
    return json.dumps(obj, indent=2, ensure_ascii=False)


def from_json(text: str) -> Any:
    """Parse a JSON string into Python objects."""
    return json.loads(text)


def json_to_map(text: str) -> dict:
    obj = from_json(text)
    if not isinstance(obj, dict):
        raise ValueError(f"expected JSON object, got {type(obj)}")
    return obj
