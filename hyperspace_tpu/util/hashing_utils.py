"""Stable hashing helpers used by signature providers.

Parity: reference `util/HashingUtils.scala:24-34` (`md5Hex(any)`).
"""

from __future__ import annotations

import hashlib
from typing import Any


def md5_hex(obj: Any) -> str:
    """md5 hex digest of the string rendering of ``obj`` (stable across processes)."""
    return hashlib.md5(str(obj).encode("utf-8")).hexdigest()
