"""hyperspace_tpu: a TPU-native lakehouse indexing framework.

A brand-new JAX/XLA/Pallas implementation of the capabilities of Microsoft Hyperspace
(the reference at /root/reference): covering-index CRUD over an on-lake operation log
with optimistic concurrency, transparent query rewrite rules (filter + join), and a
TPU-first execution path — index builds as on-device hash-partition + sort with
all-to-all over the device mesh, index scans and co-bucketed shuffle-free sort-merge
joins as XLA/Pallas programs.
"""

from .config import HyperspaceConf, IndexConstants, SessionConf  # noqa: F401
from .exceptions import (  # noqa: F401
    AdmissionRejectedError,
    CompileTimeoutError,
    ConcurrentWriteError,
    CorruptIndexError,
    HyperspaceException,
    LogCommitError,
    PermanentError,
    QueryTimeoutError,
    RetryBudgetExceededError,
    TransientError,
)
from .index.index_config import IndexConfig  # noqa: F401


def __getattr__(name):
    # Facade exports are lazy: the engine stack (jax import, x64 config) only loads
    # when actually used, keeping `import hyperspace_tpu` light for metadata-only use.
    if name in ("Hyperspace", "enable_hyperspace", "disable_hyperspace", "is_hyperspace_enabled"):
        from . import hyperspace as _h

        return getattr(_h, name)
    if name == "HyperspaceSession":
        from .engine.session import HyperspaceSession

        return HyperspaceSession
    if name == "QueryServer":
        from .serve import QueryServer

        return QueryServer
    raise AttributeError(name)


__version__ = "0.4.0"  # keep in sync with pyproject.toml
