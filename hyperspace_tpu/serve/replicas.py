"""Scale-out replica fleet: K server processes over ONE shared lake.

PR 9 scaled one process to many tenants; PR 10 scaled one process to many
devices. This module scales to many PROCESSES — the product the north star
needs — without adding any external service: the fleet coordinates the same
way everything else in this engine does, through files on the lake (the
reference's no-external-catalog operation-log design, the PR 11 history
store's multi-process OCC idioms). Four pieces:

- **Registry** (``<warehouse>/.hyperspace_replicas``): one heartbeat file
  per replica — ``replica-<host>-<pid>-<uuid8>.json``, rewritten tmp +
  `os.replace` every ``HYPERSPACE_REPLICA_HEARTBEAT_S`` by a daemon thread.
  Liveness is the history store's exact two-rule scheme: same-host entries
  are pid-checked (`util.procs.pid_alive`), foreign-host entries age out
  past ``HYPERSPACE_REPLICA_TTL_S``. Dead entries are reclaimed by
  CLAIM-BY-RENAME (``.claimed-<host>~<pid>~<orig>`` — losers of the race
  skip, exactly the `telemetry/history.py` arbitration), so K replicas
  racing a SIGKILLed peer's entry delete it once.
- **Invalidation** (``epoch.json``): a refresh/compaction committed by ANY
  replica publishes ``{"epoch": N, "entries": {index: log_entry_id}}``
  (tmp + `os.replace`); every replica's `CachingIndexCollectionManager`
  polls the file signature (rate-limited to one `os.stat` per
  ``HYPERSPACE_REPLICA_EPOCH_CHECK_S``) and drops its TTL entry cache the
  instant the epoch moved — readers flip to the new stable generation
  without waiting out the TTL. Keying on the committed ``log_entry_id``
  (not wall time) makes the signal exact: an epoch moves only when a log
  commit moved it.
- **Cold-file routing + lease**: every lake file has ONE owner replica
  under rendezvous (highest-random-weight) hashing of the live-member
  view — stable, balanced, and minimally disturbed by membership change.
  A replica decoding a file it owns proceeds directly (the fast path: the
  bench's point-lookup mix routes by bucket-file ownership, so K replicas
  decode each cold file once fleet-wide). A replica decoding a FOREIGN
  cold file takes the on-lake single-flight lease for that file first —
  concurrent cross-replica decodes of one cold file serialize, so the
  herd's redundant lake reads collapse onto the OS page cache the first
  decode warmed (cost = bytes moved off the lake; the waiters' decodes
  move ~none). Results are byte-identical either way: every replica still
  decodes the same committed immutable file into its own cache.
- **Fleet admission**: a tenant's in-flight budget is a FLEET budget —
  each replica enforces ``ceil(budget / live_replicas)``, recomputed from
  the live view, so membership changes (join, SIGKILL) rebalance shares
  automatically within one view-refresh period.

``HYPERSPACE_REPLICAS`` unset/``0`` is the standing flag contract's exact
fallback: `fleet_enabled()` is one env read, every hook below it is a
no-op, and a single process behaves byte-identically to the pre-fleet
engine (no registry dir, no stat polling, no lease files).

Metrics: ``replicas.live`` gauge, ``replicas.reclaimed``,
``replicas.route.owned`` / ``replicas.route.foreign``,
``replicas.lease.acquired`` / ``replicas.lease.waited`` /
``replicas.lease.broken``, ``replicas.invalidations.published`` /
``replicas.invalidations.observed``.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import socket
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple, TypeVar

from .. import resilience as _resilience
from ..telemetry import metrics as _metrics
from ..util.procs import pid_alive

ENV_REPLICAS = "HYPERSPACE_REPLICAS"
ENV_REPLICA_DIR = "HYPERSPACE_REPLICA_DIR"
ENV_HEARTBEAT_S = "HYPERSPACE_REPLICA_HEARTBEAT_S"
ENV_TTL_S = "HYPERSPACE_REPLICA_TTL_S"
ENV_VIEW_S = "HYPERSPACE_REPLICA_VIEW_S"
ENV_EPOCH_CHECK_S = "HYPERSPACE_REPLICA_EPOCH_CHECK_S"
ENV_LEASE_TTL_S = "HYPERSPACE_REPLICA_LEASE_TTL_S"

_DEFAULT_HEARTBEAT_S = 1.0
#: Foreign-host liveness horizon (same-host entries are pid-checked and
#: never wait this out). Generous vs the heartbeat so one slow NFS write
#: cannot evict a live peer.
_DEFAULT_TTL_S = 15.0
#: Live-member view refresh period: membership changes (and the budget
#: shares / routing ring derived from them) are visible within this.
_DEFAULT_VIEW_S = 0.25
#: Invalidation poll rate limit: one os.stat per this interval bounds the
#: read-path cost of cross-replica cache coherence.
_DEFAULT_EPOCH_CHECK_S = 0.05
#: A lease whose holder stopped heartbeating its mtime for this long is
#: breakable even cross-host (same-host holders are pid-checked).
_DEFAULT_LEASE_TTL_S = 30.0

REPLICA_PREFIX = "replica-"
CLAIMED_PREFIX = ".claimed-"
LEASE_PREFIX = "lease-"
EPOCH_FILE = "epoch.json"
_TMP_PREFIX = ".tmp-"

#: Follower wake-up slice while waiting on a foreign decode lease (the
#: singleflight module's cadence: long enough to cost nothing, short
#: enough that a query deadline is honored promptly).
_LEASE_WAIT_SLICE_S = 0.05

_LIVE = _metrics.gauge("replicas.live")
_RECLAIMED = _metrics.counter("replicas.reclaimed")
_ROUTE_OWNED = _metrics.counter("replicas.route.owned")
_ROUTE_FOREIGN = _metrics.counter("replicas.route.foreign")
_LEASE_ACQUIRED = _metrics.counter("replicas.lease.acquired")
_LEASE_WAITED = _metrics.counter("replicas.lease.waited")
_LEASE_BROKEN = _metrics.counter("replicas.lease.broken")
_INVAL_PUBLISHED = _metrics.counter("replicas.invalidations.published")
_INVAL_OBSERVED = _metrics.counter("replicas.invalidations.observed")


def fleet_enabled() -> bool:
    """One env read: the fleet hot-path gate. Unset/``0`` = exact
    single-process fallback (the standing flag contract)."""
    return os.environ.get(ENV_REPLICAS, "0") not in ("", "0")


def _env_float(name: str, default: float, lo: float = 0.0) -> float:
    try:
        return max(lo, float(os.environ.get(name, "") or default))
    except ValueError:
        return default


def heartbeat_s() -> float:
    return _env_float(ENV_HEARTBEAT_S, _DEFAULT_HEARTBEAT_S, 0.05)


def ttl_s() -> float:
    return _env_float(ENV_TTL_S, _DEFAULT_TTL_S, 0.0)


def view_s() -> float:
    return _env_float(ENV_VIEW_S, _DEFAULT_VIEW_S, 0.0)


def epoch_check_s() -> float:
    return _env_float(ENV_EPOCH_CHECK_S, _DEFAULT_EPOCH_CHECK_S, 0.0)


def lease_ttl_s() -> float:
    return _env_float(ENV_LEASE_TTL_S, _DEFAULT_LEASE_TTL_S, 0.0)


def registry_dir(warehouse: Optional[str] = None) -> str:
    """The on-lake registry location: ``HYPERSPACE_REPLICA_DIR`` when set,
    else ``<warehouse>/.hyperspace_replicas`` (next to the index logs and
    the history store — all metadata lives ON THE LAKE), else the active
    session's warehouse, else the cwd."""
    env = os.environ.get(ENV_REPLICA_DIR)
    if env:
        return env
    if warehouse is None:
        try:
            from ..engine.session import HyperspaceSession

            sess = HyperspaceSession._active
            if sess is not None:
                warehouse = sess.warehouse
        except Exception:
            pass
    return os.path.join(warehouse or ".", ".hyperspace_replicas")


# ---------------------------------------------------------------------------
# Identity
# ---------------------------------------------------------------------------

_id_lock = threading.Lock()
_replica_id: Optional[str] = None


def replica_id() -> str:
    """This process's stable fleet identity: ``<host>-<pid>-<uuid8>``, minted
    once per process. Available fleet-on or -off — exporter frames, closed
    ledgers, and Prometheus info series stamp it unconditionally so fleet
    dashboards can attribute a segment even before (or without) a join."""
    global _replica_id
    if _replica_id is None:
        with _id_lock:
            if _replica_id is None:
                _replica_id = (
                    f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:8]}"
                )
    return _replica_id


def _owner_of(name: str) -> Tuple[Optional[str], int]:
    """(host, pid) from a ``replica-<host>-<pid>-<uuid8>.json`` name — hosts
    may contain '-', so parse from the RIGHT (the history-segment rule)."""
    stem = name[: -len(".json")] if name.endswith(".json") else name
    parts = stem.split("-")
    try:
        return "-".join(parts[1:-2]) or None, int(parts[-2])
    except (IndexError, ValueError):
        return None, -1


def _claim_parts(name: str) -> Tuple[Optional[str], int, Optional[str]]:
    rest = name[len(CLAIMED_PREFIX):]
    parts = rest.split("~", 2)
    if len(parts) != 3:
        return None, -1, None
    try:
        return parts[0], int(parts[1]), parts[2]
    except ValueError:
        return None, -1, None


def _entry_alive(name: str, path: str) -> bool:
    """The two-rule liveness scheme shared with history segments: same-host
    entries are pid-checked; foreign/unparseable entries live until their
    heartbeat mtime ages past the TTL (0 disables foreign reclaim)."""
    host, pid = _owner_of(name)
    if host == socket.gethostname():
        return pid_alive(pid)
    try:
        ttl = ttl_s()
        return ttl <= 0 or time.time() - os.stat(path).st_mtime <= ttl
    except OSError:
        return False  # vanished: a racing reclaim won


def _reclaim_entry(dir_path: str, name: str) -> bool:
    """Claim-by-rename one dead entry: atomic rename arbitrates racing
    reclaimers (losers get FileNotFoundError and skip), the winner unlinks.
    Returns True when THIS process won the claim."""
    claim = os.path.join(
        dir_path,
        f"{CLAIMED_PREFIX}{socket.gethostname()}~{os.getpid()}~{name}",
    )
    try:
        os.rename(os.path.join(dir_path, name), claim)
    except OSError:
        return False  # lost the race (or already gone)
    try:
        os.unlink(claim)
    except OSError:
        pass  # the orphaned-claim sweep below gets it
    _RECLAIMED.inc()
    return True


def _sweep_orphaned_claims(dir_path: str, names: List[str]) -> None:
    """Unlink claims whose claimant died between rename and unlink (same
    rules as the entries themselves: same-host pid, foreign TTL age)."""
    for n in names:
        if not n.startswith(CLAIMED_PREFIX):
            continue
        host, pid, _orig = _claim_parts(n)
        path = os.path.join(dir_path, n)
        dead = False
        if host == socket.gethostname():
            dead = not pid_alive(pid)
        else:
            try:
                ttl = ttl_s()
                dead = ttl > 0 and time.time() - os.stat(path).st_mtime > ttl
            except OSError:
                continue
        if dead:
            try:
                os.unlink(path)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Registry: join / heartbeat / live view
# ---------------------------------------------------------------------------


class _Membership:
    """This process's join state + the rate-limited live-member view."""

    def __init__(self):
        self.lock = threading.Lock()
        self.dir: Optional[str] = None
        self.file: Optional[str] = None
        self.stop: Optional[threading.Event] = None
        self.thread: Optional[threading.Thread] = None
        self.view: List[str] = []
        self.view_t: float = 0.0


_m = _Membership()


def _entry_payload() -> dict:
    return {
        "replica_id": replica_id(),
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "ts": round(time.time(), 3),
        "heartbeat_s": heartbeat_s(),
    }


def _write_entry(dir_path: str, name: str) -> None:
    """tmp + `os.replace`: the heartbeat is atomic (a reader never sees a
    torn entry) and bumps mtime (the foreign-host liveness signal)."""
    tmp = os.path.join(dir_path, f"{_TMP_PREFIX}{name}.{uuid.uuid4().hex[:6]}")
    with open(tmp, "w") as f:
        json.dump(_entry_payload(), f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dir_path, name))


def _heartbeat_loop(dir_path: str, name: str, stop: threading.Event) -> None:
    while not stop.wait(heartbeat_s()):
        try:
            _write_entry(dir_path, name)
        except OSError:
            pass  # transient lake hiccup: the next beat retries


def join_fleet(dir_path: Optional[str] = None) -> str:
    """Register this process in the on-lake fleet and start its heartbeat;
    idempotent (re-joins the same dir are no-ops). Returns `replica_id()`.
    Called by `QueryServer` construction when the fleet flag is on; safe to
    call directly (bench children, tests)."""
    rid = replica_id()
    d = dir_path or registry_dir()
    with _m.lock:
        if _m.thread is not None and _m.dir == d:
            return rid
        _leave_locked()
        os.makedirs(d, exist_ok=True)
        name = f"{REPLICA_PREFIX}{rid}.json"
        _write_entry(d, name)
        _m.dir, _m.file = d, name
        _m.view, _m.view_t = [], 0.0
        _m.stop = threading.Event()
        _m.thread = threading.Thread(
            target=_heartbeat_loop,
            args=(d, name, _m.stop),
            name="hyperspace-replica-heartbeat",
            daemon=True,
        )
        _m.thread.start()
    # Prime the invalidation cursor: epochs published BEFORE this replica
    # built any cache are already visible to its first cold read.
    _epoch_prime(d)
    return rid


def _leave_locked() -> None:
    if _m.stop is not None:
        _m.stop.set()
    if _m.thread is not None:
        _m.thread.join(timeout=2.0)
    if _m.dir and _m.file:
        try:
            os.unlink(os.path.join(_m.dir, _m.file))
        except OSError:
            pass
    _m.dir = _m.file = _m.stop = _m.thread = None
    _m.view, _m.view_t = [], 0.0


def leave_fleet() -> None:
    """Deregister (clean shutdown). A SIGKILLed replica never runs this —
    that is what the claim-by-rename reclaim is for."""
    with _m.lock:
        _leave_locked()


def joined() -> bool:
    return _m.thread is not None


def _scan_live(dir_path: str) -> List[str]:
    """One registry pass: reclaim dead entries, sweep orphaned claims,
    return the sorted live replica ids."""
    try:
        names = sorted(os.listdir(dir_path))
    except OSError:
        return []
    live: List[str] = []
    for n in names:
        if not (n.startswith(REPLICA_PREFIX) and n.endswith(".json")):
            continue
        path = os.path.join(dir_path, n)
        if _entry_alive(n, path):
            live.append(n[len(REPLICA_PREFIX): -len(".json")])
        else:
            _reclaim_entry(dir_path, n)
    _sweep_orphaned_claims(dir_path, names)
    _LIVE.set(len(live))
    return live


def live_replicas(dir_path: Optional[str] = None, refresh: bool = False) -> List[str]:
    """The live-member view, cached for ``HYPERSPACE_REPLICA_VIEW_S`` (every
    admit/route consults this; one listdir per refresh period fleet-wide,
    never per query). `refresh=True` forces a rescan (tests, rebalance
    probes)."""
    d = dir_path or _m.dir or registry_dir()
    now = time.monotonic()
    with _m.lock:
        if (
            not refresh
            and d == _m.dir
            and _m.view
            and now - _m.view_t < view_s()
        ):
            return list(_m.view)
    view = _scan_live(d)
    with _m.lock:
        if d == _m.dir:
            _m.view, _m.view_t = view, now
    return view


def live_count(dir_path: Optional[str] = None) -> int:
    return max(1, len(live_replicas(dir_path)))


# ---------------------------------------------------------------------------
# Rendezvous routing
# ---------------------------------------------------------------------------


def owner_of(key: str, members: Optional[List[str]] = None) -> Optional[str]:
    """The one member that owns `key` under rendezvous (highest-random-
    weight) hashing: every member scores ``sha256(member|key)`` and the
    highest score wins. Stable (same members + key → same owner), balanced
    (scores are uniform), and minimally disruptive: removing a member remaps
    ONLY the keys it owned — the property that keeps a SIGKILL from
    re-routing (and re-decoding) the whole lake."""
    if members is None:
        members = live_replicas()
    best, best_score = None, b""
    for m in members:
        score = hashlib.sha256(f"{m}|{key}".encode()).digest()
        if best is None or score > best_score:
            best, best_score = m, score
    return best


def owns(key: str, members: Optional[List[str]] = None) -> bool:
    """Whether THIS replica owns `key`. Fleet off, not joined, or an
    unreadable registry all answer True — routing degrades to every replica
    owning everything (correct, just not deduplicated), never to a key
    nobody serves."""
    if not fleet_enabled() or not joined():
        return True
    owner = owner_of(key, members)
    return owner is None or owner == replica_id()


# ---------------------------------------------------------------------------
# Cross-replica invalidation (epoch.json)
# ---------------------------------------------------------------------------

_epoch_lock = threading.Lock()
#: Last-seen epoch-file signature per registry dir — the JOIN-time cursor.
#: Per-consumer cursors (each caching manager) live in the `state` dicts
#: passed to `check_invalidation`; this one only primes new consumers.
_epoch_seen: Dict[str, tuple] = {}

_SIG_MISSING = ("missing",)


def _epoch_path(dir_path: str) -> str:
    return os.path.join(dir_path, EPOCH_FILE)


def _epoch_signature(dir_path: str):
    """Cheap change detector: (mtime_ns, size, ino) of epoch.json — one
    `os.stat`, no JSON parse on the read path. `os.replace` always moves the
    inode, so every publish changes the signature even within one mtime
    granule."""
    try:
        st = os.stat(_epoch_path(dir_path))
        return (st.st_mtime_ns, st.st_size, st.st_ino)
    except OSError:
        return _SIG_MISSING


def _epoch_prime(dir_path: str) -> None:
    with _epoch_lock:
        _epoch_seen[dir_path] = _epoch_signature(dir_path)


def read_epoch(dir_path: Optional[str] = None) -> dict:
    """The parsed epoch document: ``{"epoch": N, "entries": {index:
    log_entry_id}, "publisher": replica_id}``; empty-start when missing or
    torn (a torn read means a publish is mid-replace — the next poll sees
    the committed document)."""
    d = dir_path or _m.dir or registry_dir()
    try:
        with open(_epoch_path(d)) as f:
            doc = json.load(f)
        if isinstance(doc, dict):
            return doc
    except (OSError, ValueError):
        pass
    return {"epoch": 0, "entries": {}}


def publish_invalidation(
    index_name: str,
    log_entry_id,
    dir_path: Optional[str] = None,
) -> None:
    """Announce one committed mutation to the fleet: merge ``{index_name:
    log_entry_id}`` into the epoch document, bump the epoch, commit tmp +
    `os.replace`. Racing publishers last-write-win the MERGE — harmless,
    because readers key on the signature moving at all, and both commits
    move it (each racer's reader re-reads the log on its next probe
    anyway). Called by `CachingIndexCollectionManager._mutate` after the
    action commits; no-op when the fleet is off."""
    if not fleet_enabled():
        return
    d = dir_path or _m.dir or registry_dir()
    try:
        os.makedirs(d, exist_ok=True)
        doc = read_epoch(d)
        entries = doc.get("entries") or {}
        entries[str(index_name)] = log_entry_id
        out = {
            "epoch": int(doc.get("epoch") or 0) + 1,
            "entries": entries,
            "publisher": replica_id(),
            "ts": round(time.time(), 3),
        }
        tmp = os.path.join(d, f"{_TMP_PREFIX}epoch.{uuid.uuid4().hex[:6]}")
        with open(tmp, "w") as f:
            json.dump(out, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, _epoch_path(d))
        _INVAL_PUBLISHED.inc()
    except OSError:
        pass  # the lake hiccuped: readers still converge via their TTL


def check_invalidation(state: dict, dir_path: Optional[str] = None) -> bool:
    """Whether the fleet epoch moved since this CONSUMER last looked.
    `state` is the consumer's private cursor dict (each caching manager
    owns one — a shared cursor would let one manager consume the signal
    and starve the rest). Rate-limited to one `os.stat` per
    ``HYPERSPACE_REPLICA_EPOCH_CHECK_S``; fleet off = False at one env
    read."""
    if not fleet_enabled():
        return False
    now = time.monotonic()
    if now - state.get("t", -math.inf) < epoch_check_s():
        return False
    state["t"] = now
    d = dir_path or _m.dir or registry_dir()
    sig = _epoch_signature(d)
    prev = state.get("sig")
    if prev is None:
        # First look: inherit the join-time cursor so an epoch published
        # before this consumer existed doesn't fire a spurious clear, but
        # one published since the join does.
        with _epoch_lock:
            prev = _epoch_seen.get(d, sig)
    state["sig"] = sig
    if sig != prev:
        _INVAL_OBSERVED.inc()
        return True
    return False


# ---------------------------------------------------------------------------
# Cold-file decode coordination (routing fast path + on-lake lease)
# ---------------------------------------------------------------------------

T = TypeVar("T")


def _lease_path(dir_path: str, key: str) -> str:
    return os.path.join(
        dir_path, f"{LEASE_PREFIX}{hashlib.sha256(key.encode()).hexdigest()[:16]}.json"
    )


def _lease_holder_dead(path: str) -> bool:
    """Same two-rule scheme: a same-host holder is pid-checked; a foreign or
    unreadable holder is dead once the lease file ages past the lease TTL."""
    try:
        with open(path) as f:
            doc = json.load(f)
        if doc.get("host") == socket.gethostname():
            return not pid_alive(int(doc.get("pid") or -1))
    except (OSError, ValueError):
        pass  # racing unlink, or torn write: fall through to age
    try:
        ttl = lease_ttl_s()
        return ttl > 0 and time.time() - os.stat(path).st_mtime > ttl
    except OSError:
        return False  # vanished: the holder finished


def _break_lease(dir_path: str, path: str) -> None:
    """Atomic-rename arbitration (losers get OSError and just re-poll), then
    unlink — the claim-by-rename idiom applied to a dead holder's lease."""
    tomb = os.path.join(
        dir_path, f"{_TMP_PREFIX}broken.{os.getpid()}.{uuid.uuid4().hex[:6]}"
    )
    try:
        os.rename(path, tomb)
    except OSError:
        return
    try:
        os.unlink(tomb)
    except OSError:
        pass
    _LEASE_BROKEN.inc()


def coordinate_decode(key: str, attempt: Callable[[], T]) -> T:
    """Run one cold-decode `attempt` under the fleet's cross-replica
    single-flight discipline. Fleet off / not joined / <2 live members /
    THIS replica owns `key`: `attempt()` verbatim (the owned fast path —
    byte- and accounting-identical to the single-process engine). A FOREIGN
    cold decode first takes the on-lake lease for `key`: concurrent
    cross-replica decodes of one cold file serialize, each waiter honoring
    its own query deadline (`resilience.check_deadline`) at every slice,
    and a lease whose holder died (SIGKILL mid-decode) is broken by the
    same liveness rules the registry uses. The waiter still runs its own
    `attempt` after acquiring — per-process caches mean the bytes must
    land in THIS process — but it reads what the leader's decode left in
    the OS page cache instead of re-pulling the lake."""
    if not fleet_enabled() or not joined():
        return attempt()
    members = live_replicas()
    if len(members) < 2 or owns(key, members):
        _ROUTE_OWNED.inc()
        return attempt()
    _ROUTE_FOREIGN.inc()
    d = _m.dir or registry_dir()
    path = _lease_path(d, key)
    waited = False
    while True:
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            if not waited:
                waited = True
                _LEASE_WAITED.inc()
            if _lease_holder_dead(path):
                _break_lease(d, path)
                continue
            _resilience.check_deadline("serve.replica_lease")
            time.sleep(_LEASE_WAIT_SLICE_S)
            continue
        except OSError:
            # Registry dir unreachable: degrade to an uncoordinated decode
            # (correct, just not deduplicated) rather than failing the query.
            return attempt()
        try:
            os.write(fd, json.dumps(_entry_payload()).encode())
        finally:
            os.close(fd)
        _LEASE_ACQUIRED.inc()
        try:
            return attempt()
        finally:
            try:
                os.unlink(path)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Fleet admission
# ---------------------------------------------------------------------------


def apportioned_budget(total: int, dir_path: Optional[str] = None) -> int:
    """One replica's share of a fleet-wide tenant budget:
    ``ceil(total / live_replicas)``, floor 1 (a positive fleet budget must
    never round a replica to zero capacity). Fleet off = `total` verbatim;
    membership changes rebalance within one view-refresh period because
    the live count is re-read per admit."""
    if total <= 0 or not fleet_enabled() or not joined():
        return total
    return max(1, math.ceil(total / live_count(dir_path)))


# ---------------------------------------------------------------------------
# Introspection
# ---------------------------------------------------------------------------


def fleet_stats() -> dict:
    """One snapshot for `QueryServer.stats()` / bench artifacts."""
    out = {
        "enabled": fleet_enabled(),
        "replica_id": replica_id(),
        "joined": joined(),
    }
    if joined():
        members = live_replicas()
        out.update(
            {
                "registry_dir": _m.dir,
                "live": len(members),
                "members": members,
                "epoch": read_epoch().get("epoch", 0),
            }
        )
    return out


def _reset_for_tests() -> None:
    """Tear down join state + cursors (test isolation only)."""
    global _replica_id
    leave_fleet()
    with _epoch_lock:
        _epoch_seen.clear()
    _replica_id = None
