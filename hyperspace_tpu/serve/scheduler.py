"""The query scheduler: bounded workers, priority lanes, tenant labels.

`QueryServer` is the multi-tenant front door to one engine process. Callers
submit thunks (typically ``lambda: df.collect()``); the server admission-
checks them (`serve.admission`), queues them into a PRIORITY LANE, and runs
them on a bounded worker pool. Everything below the thunk is the unmodified
engine — scheduling composes with (never reaches into) the per-query
machinery: each executed query opens its own `resilience.query_scope`
(deadline + retry budget), its own root span/ledger (labeled with the
submitting tenant via `accounting.tenant_scope`), and shares the process
caches under single-flight deduplication (`serve.singleflight`).

Design points:

- **Bounded concurrency** (``HYPERSPACE_SERVE_MAX_CONCURRENT``, default 4):
  worker THREADS, because the engine's heavy work releases the GIL (pyarrow
  decode, XLA compile/execute) — io-bound decode-pool work and device-bound
  XLA work from different queries genuinely interleave, while Python-level
  bookkeeping serializes harmlessly. More workers than cores is fine for an
  io-heavy mix; the decode pool underneath stays bounded by its own contract
  (`engine.io.decode_pool_size`).
- **Priority lanes**: ``interactive`` (point lookups, metadata probes) is
  always popped before ``batch`` (cold scans, big aggregates), and with ≥2
  workers ONE worker is RESERVED for the interactive lane — so even at full
  batch saturation an interactive query starts immediately instead of
  waiting out the shortest in-flight cold scan. Starvation the other way is
  impossible because the remaining workers still pop interactive first and
  interactive queries finish fast by definition of being routed there.
- **Exact fallback**: ``HYPERSPACE_SERVING=0`` executes every submission
  INLINE on the submitting thread under one server-wide lock — one query at
  a time, in arrival order, no admission control, no flights: byte-identical
  single-caller behavior (the same flag contract as
  ``HYPERSPACE_QUERY_STREAMING=0``). Futures resolve before `submit`
  returns.

Metrics: ``serve.queue.depth`` / ``serve.active`` gauges,
``serve.queue.wait_s`` histogram (admission → execution-start),
``serve.latency.interactive|batch`` histograms (admission → completion),
``serve.completed`` / ``serve.failed`` counters — on top of the admission
and single-flight counters of the sibling modules.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Optional, TypeVar

from .. import resilience as _resilience
from ..exceptions import HyperspaceException
from ..telemetry import accounting as _accounting
from ..telemetry import metrics as _metrics
from ..telemetry import slo as _slo
from .admission import AdmissionController
from .singleflight import serving_enabled

ENV_MAX_CONCURRENT = "HYPERSPACE_SERVE_MAX_CONCURRENT"
ENV_BATCH_NICE = "HYPERSPACE_SERVE_BATCH_NICE"
ENV_GIL_SWITCH_S = "HYPERSPACE_SERVE_GIL_SWITCH_S"
ENV_GC_TUNE = "HYPERSPACE_SERVE_GC_TUNE"
_DEFAULT_MAX_CONCURRENT = 4
#: `sys.setswitchinterval` applied once when the first worker spawns (0
#: disables): a batch thread holding the GIL in Python code then offers it
#: every millisecond instead of every five — the OS wakes the higher-
#: priority interactive worker at each offer. Process-global by nature; a
#: serving process stays a serving process.
_DEFAULT_GIL_SWITCH_S = 0.001
#: Niceness the non-reserved (batch-eligible) workers give THEMSELVES at
#: spawn: on a saturated core the OS then schedules the reserved interactive
#: worker (nice 0) ahead of batch whenever both are runnable. Lowering one's
#: own priority needs no privileges; 0 disables.
_DEFAULT_BATCH_NICE = 10
#: Cooperative yield: how long one batch-lane boundary pause may last, in
#: slices — bounded so interactive pressure NUDGES batch, never starves it.
_YIELD_SLICE_S = 0.002
_YIELD_MAX_S = 0.05

#: Lane pop order IS the priority order.
LANES = ("interactive", "batch")

_QUEUE_DEPTH = _metrics.gauge("serve.queue.depth")
_ACTIVE = _metrics.gauge("serve.active")
_QUEUE_WAIT_S = _metrics.histogram("serve.queue.wait_s")
_COMPLETED = _metrics.counter("serve.completed")
_FAILED = _metrics.counter("serve.failed")
_LANE_LATENCY = {lane: _metrics.histogram(f"serve.latency.{lane}") for lane in LANES}
# Lane visibility (Prometheus output previously only distinguished TENANTS):
# per-lane queue depth and in-flight gauges, plus lane histograms in the
# shared `latency.*` family the ledger's `latency.<root>` series live in —
# one scrape now separates the interactive tail from the batch tail.
_LANE_QUEUE_DEPTH = {
    lane: _metrics.gauge(f"serve.queue.depth.{lane}") for lane in LANES
}
_LANE_INFLIGHT = {lane: _metrics.gauge(f"serve.inflight.{lane}") for lane in LANES}
_LANE_SERVE_LATENCY = {
    lane: _metrics.histogram(f"latency.serve.{lane}") for lane in LANES
}


def default_max_concurrent() -> int:
    try:
        return max(
            1, int(os.environ.get(ENV_MAX_CONCURRENT, "") or _DEFAULT_MAX_CONCURRENT)
        )
    except ValueError:
        return _DEFAULT_MAX_CONCURRENT


def _batch_nice() -> int:
    try:
        return max(
            0, int(os.environ.get(ENV_BATCH_NICE, "") or _DEFAULT_BATCH_NICE)
        )
    except ValueError:
        return _DEFAULT_BATCH_NICE


# -- interactive pressure (the cooperative yield gate's state) --------------
# Queued-or-running interactive queries, process-wide (all servers share the
# engine's caches and the one CPU budget, so the gate is global too).
_pressure_lock = threading.Lock()
_interactive_pending = 0


def _interactive_begin() -> None:
    global _interactive_pending
    with _pressure_lock:
        _interactive_pending += 1


def _interactive_end() -> None:
    global _interactive_pending
    with _pressure_lock:
        _interactive_pending = max(0, _interactive_pending - 1)


def interactive_pending() -> bool:
    return _interactive_pending > 0


def _yield_to_interactive() -> None:
    """Batch-lane boundary pause (registered into `resilience.check_deadline`
    when the first worker spawns): while interactive work is queued or
    running, batch threads sleep in small slices — on a saturated core this
    hands a point lookup the CPU mid-scan, something thread priority alone
    cannot do against GIL-holding stretches. Bounded at `_YIELD_MAX_S` per
    boundary so heavy interactive traffic slows batch, never stops it.
    A batch thread LEADING a single-flight someone waits on never pauses —
    the waiter may BE the interactive query (priority inversion otherwise)."""
    from .singleflight import leading_with_followers

    waited = 0.0
    while _interactive_pending > 0 and waited < _YIELD_MAX_S:
        if leading_with_followers():
            return
        time.sleep(_YIELD_SLICE_S)
        waited += _YIELD_SLICE_S


T = TypeVar("T")


class _Item:
    __slots__ = ("future", "fn", "tenant", "lane", "t_admitted")

    def __init__(self, future, fn, tenant, lane):
        self.future = future
        self.fn = fn
        self.tenant = tenant
        self.lane = lane
        self.t_admitted = time.monotonic()


class QueryServer:
    """One serving front door over the ambient engine process.

    >>> with QueryServer() as srv:
    ...     fut = srv.submit(lambda: df.collect(), tenant="alice",
    ...                      lane="interactive")
    ...     table = fut.result()

    Constructor args override the env knobs (None = env/default). The server
    is reusable across queries and tenants; `close()` (or the context exit)
    drains queued work and joins the workers."""

    def __init__(
        self,
        max_concurrent: Optional[int] = None,
        queue_depth: Optional[int] = None,
        tenant_budget: Optional[int] = None,
    ):
        self.max_concurrent = (
            default_max_concurrent()
            if max_concurrent is None
            else max(1, int(max_concurrent))
        )
        self.admission = AdmissionController(queue_depth, tenant_budget)
        # Fleet membership (HYPERSPACE_REPLICAS=1, serve.replicas): the
        # serving front door IS the replica — constructing one registers
        # this process in the on-lake registry and starts its heartbeat.
        # Idempotent across servers in one process; one env read when off.
        from . import replicas as _replicas

        if _replicas.fleet_enabled():
            _replicas.join_fleet()
        self._cv = threading.Condition()
        self._lanes = {lane: deque() for lane in LANES}
        self._workers: list = []
        self._closed = False
        # The HYPERSPACE_SERVING=0 fallback: one query at a time, inline.
        self._serial_lock = threading.Lock()

    # -- lifecycle ----------------------------------------------------------

    def __enter__(self) -> "QueryServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _ensure_workers_locked(self) -> None:
        """Spawn workers lazily up to the bound (holding `_cv`): a server
        that only ever ran the serial fallback never owns a thread. Worker 0
        is the RESERVED interactive worker whenever there are ≥2 workers
        (with exactly one, it must serve both lanes or batch would starve)."""
        if not self._workers:
            _resilience.register_yield_hook(_yield_to_interactive)
            try:
                switch = float(
                    os.environ.get(ENV_GIL_SWITCH_S, "") or _DEFAULT_GIL_SWITCH_S
                )
            except ValueError:
                switch = _DEFAULT_GIL_SWITCH_S
            if switch > 0:
                import sys

                sys.setswitchinterval(min(sys.getswitchinterval(), switch))
            if os.environ.get(ENV_GC_TUNE, "") != "0":
                # Measured on this engine: CPython gen-2 collections pause
                # EVERY thread 20-40 ms — the single biggest point-lookup
                # tail event once scheduling is fixed. Freeze the warm
                # startup set out of the scan and make full collections 10x
                # rarer (gen-0/1 cadence unchanged, so short-lived query
                # garbage still collects promptly). `=0` opts out.
                import gc

                gc.freeze()
                t0, t1, _t2 = gc.get_threshold()
                gc.set_threshold(t0, t1, 100)
        while len(self._workers) < self.max_concurrent:
            idx = len(self._workers)
            reserved = idx == 0 and self.max_concurrent >= 2
            t = threading.Thread(
                target=self._worker_loop,
                args=(reserved,),
                name=f"hyperspace-serve-{idx}{'-interactive' if reserved else ''}",
                daemon=True,
            )
            # Start BEFORE registering: a failed start (thread limit) must
            # not leave an unstarted Thread in _workers for close() to
            # crash joining.
            t.start()
            self._workers.append(t)

    def close(self, wait: bool = True) -> None:
        """Stop accepting work; queued work still runs (futures resolve)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
            workers = list(self._workers)
        if wait:
            for t in workers:
                t.join()

    # -- submission ---------------------------------------------------------

    def submit(
        self,
        fn: Callable[[], T],
        *,
        tenant: str = "default",
        lane: str = "batch",
    ) -> "Future[T]":
        """Admission-check and enqueue one query thunk; returns its future.

        Raises `AdmissionRejectedError` (queue depth / tenant budget) at the
        door — a rejected query holds no slot and owns no future. `lane` is
        ``interactive`` (priority: point lookups and other sub-second work)
        or ``batch`` (default)."""
        if lane not in LANES:
            raise HyperspaceException(
                f"Unknown serve lane '{lane}'; expected one of {LANES}"
            )
        with self._cv:
            if self._closed:
                raise HyperspaceException("QueryServer is closed")
        if not serving_enabled():
            return self._run_serial(fn, tenant, lane)
        self.admission.admit(tenant)
        fut: "Future[T]" = Future()
        item = _Item(fut, fn, tenant, lane)
        try:
            with self._cv:
                if self._closed:
                    raise HyperspaceException("QueryServer is closed")
                self._ensure_workers_locked()
                if lane == "interactive":
                    _interactive_begin()  # ended in _execute's finally
                self._lanes[lane].append(item)
                _QUEUE_DEPTH.set(sum(len(q) for q in self._lanes.values()))
                _LANE_QUEUE_DEPTH[lane].set(len(self._lanes[lane]))
                # notify_all, not notify: a single wake could land on the
                # reserved interactive worker for a batch item, which would
                # ignore it and leave the item queued with everyone else
                # asleep.
                self._cv.notify_all()
        except BaseException:
            # Enqueue failed (closed race, worker spawn at the thread
            # limit): the admission token must not leak — a leaked token
            # would ratchet _in_flight until the server rejects everything.
            self.admission.release(tenant)
            raise
        return fut

    def run(self, fn: Callable[[], T], *, tenant: str = "default", lane: str = "batch") -> T:
        """`submit` + wait: the blocking convenience for scripted callers."""
        return self.submit(fn, tenant=tenant, lane=lane).result()

    def _run_serial(self, fn, tenant: str, lane: str = "batch") -> Future:
        """The ``HYPERSPACE_SERVING=0`` path: execute inline on the calling
        thread, one submission at a time — indistinguishable from a single
        caller invoking the engine directly (no admission, no priority, no
        flights; the tenant and lane labels still ride for telemetry/SLO
        parity — an operator flipping the flag must not lose SLO history)."""
        fut: Future = Future()
        fut.set_running_or_notify_cancel()
        t0 = time.monotonic()
        failed = False
        try:
            with self._serial_lock, _accounting.tenant_scope(
                tenant
            ), _resilience.lane_scope(lane):
                out = fn()
        except BaseException as e:
            failed = True
            _FAILED.inc()
            fut.set_exception(e)
            return fut
        finally:
            wall = time.monotonic() - t0
            _LANE_SERVE_LATENCY[lane].observe(wall)
            _slo.observe(lane, wall, tenant=tenant, failed=failed)
        _COMPLETED.inc()
        fut.set_result(out)
        return fut

    # -- execution ----------------------------------------------------------

    def _pop_locked(self, reserved: bool = False) -> Optional[_Item]:
        lanes = ("interactive",) if reserved else LANES
        for lane in lanes:  # priority = declaration order
            if self._lanes[lane]:
                item = self._lanes[lane].popleft()
                _QUEUE_DEPTH.set(sum(len(q) for q in self._lanes.values()))
                _LANE_QUEUE_DEPTH[lane].set(len(self._lanes[lane]))
                return item
        return None

    def _worker_loop(self, reserved: bool = False) -> None:
        if not reserved and self.max_concurrent >= 2:
            # Batch-eligible workers deprioritize THEMSELVES (allowed without
            # privileges): on a saturated core the OS then runs the reserved
            # interactive worker first whenever both are runnable. The numpy/
            # eval work of a batch query runs on this thread, so the niceness
            # covers exactly the contention that matters.
            nice = _batch_nice()
            if nice:
                try:
                    os.setpriority(os.PRIO_PROCESS, threading.get_native_id(), nice)
                except (OSError, AttributeError):
                    pass  # unsupported platform/container: priority is a nudge
        while True:
            with self._cv:
                item = self._pop_locked(reserved)
                while item is None and not self._closed:
                    self._cv.wait()
                    item = self._pop_locked(reserved)
            if item is None:
                return  # closed and drained
            self._execute(item)

    def _execute(self, item: _Item) -> None:
        t_start = time.monotonic()
        if not item.future.set_running_or_notify_cancel():
            self.admission.release(item.tenant)
            if item.lane == "interactive":
                _interactive_end()
            return  # caller cancelled while queued
        _QUEUE_WAIT_S.observe(t_start - item.t_admitted)
        _ACTIVE.inc()
        _LANE_INFLIGHT[item.lane].inc()
        failed = False
        try:
            # The tenant label wraps the WHOLE query: the root span/ledger
            # the thunk opens (collect/count/build) inherits it, and every
            # pool worker below inherits it through the ledger. The lane
            # label rides the query scope the same way — batch-lane threads
            # then pause at chunk boundaries while interactive work is
            # pending (`_yield_to_interactive`).
            with _accounting.tenant_scope(item.tenant), _resilience.lane_scope(
                item.lane
            ):
                out = item.fn()
        except BaseException as e:
            failed = True
            _FAILED.inc()
            item.future.set_exception(e)
        else:
            _COMPLETED.inc()
            item.future.set_result(out)
        finally:
            _ACTIVE.dec()
            _LANE_INFLIGHT[item.lane].dec()
            if item.lane == "interactive":
                _interactive_end()
            self.admission.release(item.tenant)
            wall = time.monotonic() - item.t_admitted
            _LANE_LATENCY[item.lane].observe(wall)
            _LANE_SERVE_LATENCY[item.lane].observe(wall)
            # SLO accounting on the client-experienced latency (admission →
            # completion, queue wait included — the only honest SLI). A
            # failed query is a violation however fast it errored.
            _slo.observe(item.lane, wall, tenant=item.tenant, failed=failed)

    # -- introspection ------------------------------------------------------

    def stats(self) -> dict:
        with self._cv:
            queued = {lane: len(q) for lane, q in self._lanes.items()}
            workers = len(self._workers)
        out = self.admission.stats()
        out.update(
            {
                "queued": queued,
                "workers": workers,
                "max_concurrent": self.max_concurrent,
                "serving_enabled": serving_enabled(),
            }
        )
        from . import replicas as _replicas

        if _replicas.fleet_enabled():
            out["replicas"] = _replicas.fleet_stats()
        return out
