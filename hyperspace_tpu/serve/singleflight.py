"""Single-flight deduplication over the engine's shared caches.

Under concurrent traffic the dominant redundant cost is identical cold work:
two tenants issuing the same cold scan in the same instant each miss the
cache and each decode the lake — the caches only help AFTER someone finishes
(the external-memory cost model of "Evaluating Learned Indexes for
External-Memory Joins": cost = bytes moved, and here the bytes move twice).
Single-flight collapses that: the FIRST requester of a cold cache entry
becomes the LEADER and computes it; every concurrent requester of the same
key becomes a FOLLOWER that blocks until the leader finishes, then re-probes
the cache the leader populated. N identical concurrent cold requests decode
once; N−1 are served for a wait.

Keying: flights are keyed by the SAME keys the underlying caches use —
per-file scan-cache entries (projection + row-group selection: a pruned
decode's flight can never alias the whole-file flight, exactly like the
cache keys it guards), footer-metadata entries, multi-file concat keys, and
bucketed/filtered concat keys. One process-wide flight table covers them all
(keys are namespaced tuples).

Failure propagation — the poisoned-entry rules:

- A leader FAILURE never poisons followers: the flight is cleared in a
  ``finally`` and marked not-ok, the leader's exception propagates to the
  leader's caller only, and each follower INDEPENDENTLY retries (becoming
  the next leader) — composing with the PR-7 retry/quarantine contracts,
  which the leader's own attempt already rode. Nothing about a failure is
  cached (the standing only-cache-on-success contract), so a follower's
  retry starts clean.
- A follower's WAIT is bounded by its own query deadline
  (`resilience.check_deadline`): a leader that hangs past the follower's
  ``HYPERSPACE_QUERY_TIMEOUT_S`` costs the follower a classified
  `QueryTimeoutError`, never an unbounded block. A leader that itself times
  out clears the flight on the way out, unblocking followers immediately.
- A successful leader whose entry was EVICTED before the follower re-probed
  (pathologically small budget) degrades to the follower leading its own
  flight — correct, just not deduplicated.

``HYPERSPACE_SERVING=0`` disables every flight: `shared` runs the attempt
inline, byte-and-accounting-identical to the single-caller engine (the same
flag-contract style as STREAMING/PUSHDOWN/ENCODED_EXEC).

Metrics: ``serve.singleflight.leaders`` (flights led),
``serve.singleflight.dedup_hits`` (followers served by a leader's work —
each one is a whole cold decode NOT paid), ``serve.singleflight.
follower_retries`` (followers that retried after a leader failure/eviction),
``serve.singleflight.wait_s`` histogram (follower block time).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional, TypeVar

from .. import resilience as _resilience
from ..telemetry import metrics as _metrics

ENV_SERVING = "HYPERSPACE_SERVING"

_LEADERS = _metrics.counter("serve.singleflight.leaders")
_DEDUP_HITS = _metrics.counter("serve.singleflight.dedup_hits")
_FOLLOWER_RETRIES = _metrics.counter("serve.singleflight.follower_retries")
_WAIT_S = _metrics.histogram("serve.singleflight.wait_s")

#: Follower wake-up slice while waiting on a leader: long enough to cost
#: nothing, short enough that a query deadline is honored promptly.
_WAIT_SLICE_S = 0.05


def serving_enabled() -> bool:
    """Default ON; ``HYPERSPACE_SERVING=0`` is the exact single-caller
    fallback — no flights, no scheduler concurrency, every code path
    byte-identical to the pre-serving engine."""
    return os.environ.get(ENV_SERVING, "") != "0"


class _Flight:
    """One in-progress computation other requesters can wait on."""

    __slots__ = ("done", "ok", "waiters")

    def __init__(self):
        self.done = threading.Event()
        self.ok = False  # set True by a leader that completed normally
        self.waiters = 0  # followers currently blocked on this flight


_lock = threading.Lock()
_flights: Dict[tuple, _Flight] = {}

# Flights THIS thread currently leads (leaders can nest: a scan-flight
# leader leads file flights inside it). Read by `leading_with_followers` —
# the anti-priority-inversion predicate of the scheduler's yield gate.
_local = threading.local()


def leading_with_followers() -> bool:
    """True when this thread leads a flight someone is blocked on — its work
    is on another query's critical path, so the cooperative yield gate must
    NOT pause it (a batch leader sleeping while an interactive follower
    waits on its flight would be priority inversion, not protection)."""
    flights = getattr(_local, "leading", None)
    return bool(flights) and any(fl.waiters > 0 for fl in flights)


def in_flight_count() -> int:
    """Live flight count (tests / stats)."""
    with _lock:
        return len(_flights)


T = TypeVar("T")


def _wait(fl: _Flight) -> None:
    """Block until the flight completes, honoring the ambient query deadline
    at every wake-up slice — a hung leader costs a follower its classified
    `QueryTimeoutError`, never an unbounded wait."""
    t0 = time.monotonic()
    while not fl.done.wait(_WAIT_SLICE_S):
        _resilience.check_deadline("serve.singleflight")
    _WAIT_S.observe(time.monotonic() - t0)


def shared(
    key: tuple,
    attempt: Callable[[], T],
    reprobe: Optional[Callable[[], Optional[T]]] = None,
) -> T:
    """Run `attempt` with at most ONE concurrent execution per `key`.

    The first caller (leader) runs `attempt` — which is expected to populate
    the underlying cache on success. Concurrent callers (followers) wait;
    when the leader succeeded they return `reprobe()` (the accounting-true
    cache re-probe — a non-None value ticks ``dedup_hits``). A follower whose
    leader failed, or whose re-probe found the entry already evicted, loops
    and leads its own flight (independent retry, no poisoned entry).

    With no `reprobe` (pure compute, nothing cached) a follower always
    retries — dedup then only bounds concurrency, not total work; every
    engine integration passes one. Serving disabled = `attempt()` verbatim.
    """
    if not serving_enabled():
        return attempt()
    while True:
        with _lock:
            fl = _flights.get(key)
            if fl is None:
                fl = _Flight()
                _flights[key] = fl
                leader = True
            else:
                leader = False
        if leader:
            _LEADERS.inc()
            leading = getattr(_local, "leading", None)
            if leading is None:
                leading = _local.leading = []
            leading.append(fl)
            try:
                out = attempt()
                fl.ok = True
                return out
            finally:
                leading.pop()
                # Clear BEFORE waking: a woken follower that retries must
                # find the slot free (or taken by another follower), never
                # this completed flight.
                with _lock:
                    _flights.pop(key, None)
                fl.done.set()
        with _lock:
            fl.waiters += 1
        try:
            _wait(fl)
        finally:
            with _lock:
                fl.waiters -= 1
        if fl.ok and reprobe is not None:
            hit = reprobe()
            if hit is not None:
                _DEDUP_HITS.inc()
                return hit
        # Leader failed (its exception is its caller's; ours starts clean) or
        # the entry was already evicted: retry independently.
        _FOLLOWER_RETRIES.inc()
