"""hyperspace_tpu.serve: the multi-tenant serving layer (ROADMAP item 2).

One engine process, heavy parallel traffic. Three pieces compose:

- `scheduler.QueryServer` — bounded worker pool, priority lanes
  (``interactive`` before ``batch``), per-tenant admission control and
  token budgets, classified `AdmissionRejectedError` load shedding.
- `singleflight` — cross-query deduplication over the engine's shared
  caches: N identical concurrent cold requests decode the lake once.
- `replicas` — the scale-out half (``HYPERSPACE_REPLICAS=1``): an on-lake
  replica registry with heartbeat liveness and claim-by-rename reclaim,
  rendezvous-hash file routing + an on-lake decode lease (K processes
  decode each cold file once fleet-wide), epoch-file cache invalidation
  keyed on committed log entry ids, and fleet-apportioned tenant budgets.
- tenant labels end to end — every served query's root span, ledger,
  exporter frame, and Prometheus series carries its tenant
  (`telemetry.accounting.tenant_scope`).

``HYPERSPACE_SERVING=0`` disables all of it: submissions execute inline,
serially, byte-identical to a single caller (docs/serving.md).
"""

from .admission import (  # noqa: F401
    ENV_QUEUE_DEPTH,
    ENV_TENANT_BUDGET,
    AdmissionController,
    default_queue_depth,
    default_tenant_budget,
)
from .replicas import (  # noqa: F401
    ENV_REPLICA_DIR,
    ENV_REPLICAS,
    fleet_enabled,
    fleet_stats,
    join_fleet,
    leave_fleet,
    live_replicas,
    owner_of,
    replica_id,
)
from .scheduler import (  # noqa: F401
    ENV_MAX_CONCURRENT,
    LANES,
    QueryServer,
    default_max_concurrent,
)
from .singleflight import ENV_SERVING, serving_enabled, shared  # noqa: F401
