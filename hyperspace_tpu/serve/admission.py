"""Admission control: queue-depth rejection + per-tenant in-flight budgets.

Uncoordinated peak demand is how one hot tenant starves the rest (the
contention JSPIM addresses at the operator level, moved up to the query
level): without admission control a burst just queues, every queued query
holds its submitter's latency budget hostage, and the tail explodes. The
controller enforces two cheap invariants at SUBMIT time, before any work is
queued:

- **Queue depth** (``HYPERSPACE_SERVE_QUEUE_DEPTH``, default 256): the total
  number of admitted-but-unfinished queries the server will hold. Past it,
  submissions fail fast with a classified `AdmissionRejectedError`
  (``reason="queue_depth"``) — load shedding at the door beats timing out
  inside.
- **Tenant budget** (``HYPERSPACE_SERVE_TENANT_BUDGET``, default 0 =
  unlimited): the in-flight (queued + running) query TOKENS one tenant may
  hold. Each admitted query holds one token until it finishes; a tenant past
  its budget gets `AdmissionRejectedError` (``reason="tenant_budget"``)
  while everyone else keeps flowing — per-tenant isolation without weighing
  queries against each other.

Under a replica fleet (``HYPERSPACE_REPLICAS=1``, `serve.replicas`) the
tenant budget is a FLEET budget: each replica enforces its apportioned
share ``ceil(budget / live_replicas)`` (floor 1), re-read from the live
membership view at every admit — a joining replica shrinks everyone's
share, a SIGKILLed one returns its share to the survivors, both within one
view-refresh period and with no coordination beyond the on-lake registry.
Fleet off = the configured budget verbatim (one env read).

``serve.admit`` is a named fault point (`telemetry.faults`): the chaos
harness can make admission itself flaky, and the mixed-workload chaos leg
asserts results stay byte-identical to serial execution anyway.

Metrics: ``serve.admitted``, ``serve.rejected.queue_depth``,
``serve.rejected.tenant_budget``, ``serve.tenants.active`` gauge.
"""

from __future__ import annotations

import os
import threading
from typing import Dict

from ..exceptions import AdmissionRejectedError
from ..telemetry import faults as _faults
from ..telemetry import metrics as _metrics

ENV_QUEUE_DEPTH = "HYPERSPACE_SERVE_QUEUE_DEPTH"
ENV_TENANT_BUDGET = "HYPERSPACE_SERVE_TENANT_BUDGET"

_DEFAULT_QUEUE_DEPTH = 256
_DEFAULT_TENANT_BUDGET = 0  # unlimited

_ADMITTED = _metrics.counter("serve.admitted")
_REJECTED_DEPTH = _metrics.counter("serve.rejected.queue_depth")
_REJECTED_TENANT = _metrics.counter("serve.rejected.tenant_budget")
_TENANTS_ACTIVE = _metrics.gauge("serve.tenants.active")


def default_queue_depth() -> int:
    try:
        return max(
            1, int(os.environ.get(ENV_QUEUE_DEPTH, "") or _DEFAULT_QUEUE_DEPTH)
        )
    except ValueError:
        return _DEFAULT_QUEUE_DEPTH


def default_tenant_budget() -> int:
    """0 = unlimited (the knob must be opted into — a default cap would make
    the serving layer reject traffic the single-caller engine accepts)."""
    try:
        return max(
            0, int(os.environ.get(ENV_TENANT_BUDGET, "") or _DEFAULT_TENANT_BUDGET)
        )
    except ValueError:
        return _DEFAULT_TENANT_BUDGET


class AdmissionController:
    """In-flight token accounting for one `QueryServer`. `admit` either
    grants a token (release it in a finally) or raises the classified
    rejection — it never blocks: backpressure is the caller's policy."""

    def __init__(self, queue_depth=None, tenant_budget=None):
        self.queue_depth = (
            default_queue_depth() if queue_depth is None else max(1, int(queue_depth))
        )
        self.tenant_budget = (
            default_tenant_budget()
            if tenant_budget is None
            else max(0, int(tenant_budget))
        )
        self._lock = threading.Lock()
        self._in_flight = 0
        self._per_tenant: Dict[str, int] = {}

    def effective_tenant_budget(self) -> int:
        """The budget this replica enforces RIGHT NOW: the configured value
        apportioned across live fleet members (`serve.replicas`), or
        verbatim outside a fleet. Recomputed per admit so membership
        changes rebalance without any explicit signal."""
        if not self.tenant_budget:
            return self.tenant_budget
        from . import replicas as _replicas

        return _replicas.apportioned_budget(self.tenant_budget)

    def admit(self, tenant: str) -> None:
        """Grant one in-flight token to `tenant` or raise
        `AdmissionRejectedError`. The ``serve.admit`` fault point fires first
        (an injected fault is an admission-path failure, not a rejection)."""
        _faults.check("serve.admit")
        budget = self.effective_tenant_budget()
        with self._lock:
            if self._in_flight >= self.queue_depth:
                _REJECTED_DEPTH.inc()
                raise AdmissionRejectedError(
                    f"server at HYPERSPACE_SERVE_QUEUE_DEPTH={self.queue_depth} "
                    f"in-flight queries; rejecting tenant '{tenant}' (retry "
                    "with backoff)",
                    reason="queue_depth",
                    tenant=tenant,
                )
            held = self._per_tenant.get(tenant, 0)
            if budget and held >= budget:
                _REJECTED_TENANT.inc()
                raise AdmissionRejectedError(
                    f"tenant '{tenant}' at HYPERSPACE_SERVE_TENANT_BUDGET="
                    f"{self.tenant_budget} (this replica's fleet share: "
                    f"{budget}) in-flight queries; rejecting (other "
                    "tenants are unaffected)",
                    reason="tenant_budget",
                    tenant=tenant,
                )
            self._in_flight += 1
            self._per_tenant[tenant] = held + 1
            _TENANTS_ACTIVE.set(len(self._per_tenant))
        _ADMITTED.inc()

    def release(self, tenant: str) -> None:
        with self._lock:
            self._in_flight = max(0, self._in_flight - 1)
            held = self._per_tenant.get(tenant, 0) - 1
            if held <= 0:
                self._per_tenant.pop(tenant, None)
            else:
                self._per_tenant[tenant] = held
            _TENANTS_ACTIVE.set(len(self._per_tenant))

    def stats(self) -> dict:
        effective = self.effective_tenant_budget()
        with self._lock:
            out = {
                "in_flight": self._in_flight,
                "queue_depth": self.queue_depth,
                "tenant_budget": self.tenant_budget,
                "per_tenant": dict(self._per_tenant),
            }
        if effective != self.tenant_budget:
            out["tenant_budget_fleet_share"] = effective
        return out
