"""Batched co-bucketed merge join: ALL bucket pairs joined in one device program.

The co-bucketed sort-merge join (reference `JoinIndexRule.scala:137-162`: equal keys
are co-located in equal-numbered buckets, so no shuffle is needed) must not be executed
as a Python loop over buckets — B small per-bucket dispatches with distinct shapes
defeat XLA. Instead the bucket axis becomes a *batch dimension*:

1. Scatter each side's per-row key64 into a padded [B, cap] matrix (pad = i64 max).
2. One batched sort along the row axis (pads sort to the end).
3. One batched searchsorted probe (vmap), ranges clamped to each bucket's valid length.
4. Two-pass expansion (count → scalar sync → scatter) exactly like the global join.

Static shapes throughout; the bucket axis is also the natural shard axis on a device
mesh (each device owns a contiguous bucket range and never communicates).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

_PAD = jnp.iinfo(jnp.int64).max


@partial(jax.jit, static_argnums=(2, 3))
def _pad_and_sort(keys, starts, num_buckets: int, cap: int):
    """Scatter per-row keys (concatenated in bucket order) into a sorted [B, cap]
    matrix. Returns (sorted_keys [B,cap], order [B,cap] slot→original-slot, lengths)."""
    n = keys.shape[0]
    pos = jnp.arange(n)
    b_of_row = jnp.searchsorted(starts, pos, side="right") - 1
    slot = pos - starts[b_of_row]
    padded = jnp.full((num_buckets, cap), _PAD, dtype=jnp.int64)
    padded = padded.at[b_of_row, slot].set(keys)
    order = jnp.argsort(padded, axis=1)
    sorted_keys = jnp.take_along_axis(padded, order, axis=1)
    lengths = starts[1:] - starts[:-1]
    return sorted_keys, order, lengths


@jax.jit
def _probe(ls, rs, l_len, r_len):
    """Batched range probe: for each left slot, the [lo, hi) match range in the
    right bucket, clamped to valid rows; counts zeroed for left pad slots."""
    lo = jax.vmap(lambda r, l: jnp.searchsorted(r, l, side="left"))(rs, ls)
    hi = jax.vmap(lambda r, l: jnp.searchsorted(r, l, side="right"))(rs, ls)
    r_len_b = r_len[:, None]
    lo = jnp.minimum(lo, r_len_b)
    hi = jnp.minimum(hi, r_len_b)
    valid_left = jnp.arange(ls.shape[1])[None, :] < l_len[:, None]
    counts = jnp.where(valid_left, hi - lo, 0)
    return lo, counts


def _expand(lo, counts, l_order, r_order, l_starts, r_starts, total: int):
    """Expand count ranges into global (left_row, right_row) index pairs.

    Deliberately NOT jitted: `total` is data-dependent, so a jit keyed on it would
    recompile for every distinct join result size (same reasoning as
    `ops.join.merge_join_pairs`)."""
    B, cap = counts.shape
    counts_flat = counts.reshape(-1)
    lo_flat = lo.reshape(-1)
    starts_flat = jnp.cumsum(counts_flat) - counts_flat
    l_flat = jnp.repeat(jnp.arange(B * cap), counts_flat, total_repeat_length=total)
    offset = jnp.arange(total) - starts_flat[l_flat]
    b = l_flat // cap
    l_slot_sorted = l_flat % cap
    r_slot_sorted = lo_flat[l_flat] + offset
    l_global = l_starts[b] + l_order[b, l_slot_sorted]
    r_global = r_starts[b] + r_order[b, r_slot_sorted]
    return l_global, r_global


@partial(jax.jit, static_argnums=(2, 3))
def _pad_only(vals, starts, num_buckets: int, cap: int, pad_value):
    """Scatter per-row values (concatenated in bucket order) into a padded [B, cap]
    matrix WITHOUT sorting, plus a per-bucket sortedness check."""
    n = vals.shape[0]
    pos = jnp.arange(n)
    b_of_row = jnp.searchsorted(starts, pos, side="right") - 1
    slot = pos - starts[b_of_row]
    padded = jnp.full((num_buckets, cap), pad_value, dtype=vals.dtype)
    padded = padded.at[b_of_row, slot].set(vals)
    lengths = starts[1:] - starts[:-1]
    valid = jnp.arange(cap)[None, :] < (lengths - 1)[:, None]
    non_decreasing = jnp.where(valid, padded[:, 1:] >= padded[:, :-1], True).all()
    return padded, lengths, non_decreasing


def bucketed_sorted_value_join_pairs(
    l_vals, l_starts_np: np.ndarray, r_vals, r_starts_np: np.ndarray
):
    """Value-direct co-bucketed join for a single numeric key when both sides'
    buckets are ALREADY sorted by the key — the covering-index fast path: the sort
    happened once at build time (`ops.partition.bucketize_table` orders each bucket
    by the indexed columns), so the query needs no hashing, no argsort, and no
    collision verification. Returns None if either side's buckets turn out unsorted
    (multi-file buckets from incremental refresh); caller falls back to the hash path.
    """
    B = len(l_starts_np) - 1
    l_lens = np.diff(l_starts_np)
    r_lens = np.diff(r_starts_np)
    cap_l = int(l_lens.max()) if B else 0
    cap_r = int(r_lens.max()) if B else 0
    if cap_l == 0 or cap_r == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)

    l_vals = jnp.asarray(l_vals)
    r_vals = jnp.asarray(r_vals)
    if l_vals.dtype != r_vals.dtype:
        common = jnp.promote_types(l_vals.dtype, r_vals.dtype)
        l_vals = l_vals.astype(common)
        r_vals = r_vals.astype(common)
    if jnp.issubdtype(l_vals.dtype, jnp.floating):
        pad = jnp.asarray(jnp.finfo(l_vals.dtype).max, dtype=l_vals.dtype)
    else:
        pad = jnp.asarray(jnp.iinfo(l_vals.dtype).max, dtype=l_vals.dtype)

    l_starts = jnp.asarray(l_starts_np)
    r_starts = jnp.asarray(r_starts_np)
    ls, l_len, l_sorted = _pad_only(l_vals, l_starts, B, cap_l, pad)
    rs, r_len, r_sorted = _pad_only(r_vals, r_starts, B, cap_r, pad)
    if not (bool(l_sorted) and bool(r_sorted)):
        return None  # fall back to the hash path
    lo, counts = _probe(ls, rs, l_len, r_len)
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    iota_l = jnp.broadcast_to(jnp.arange(cap_l)[None, :], (B, cap_l))
    iota_r = jnp.broadcast_to(jnp.arange(cap_r)[None, :], (B, cap_r))
    l_global, r_global = _expand(lo, counts, iota_l, iota_r, l_starts, r_starts, total)
    return np.asarray(l_global), np.asarray(r_global)


def bucketed_merge_join_pairs(
    l_keys, l_starts_np: np.ndarray, r_keys, r_starts_np: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """All (left_row, right_row) pairs with equal key64 across co-located buckets.

    `l_keys`/`r_keys`: per-row key64 of each side, rows ordered bucket-by-bucket.
    `*_starts_np`: bucket start offsets (length B+1, from the bucketed scan)."""
    B = len(l_starts_np) - 1
    assert len(r_starts_np) - 1 == B
    l_lens = np.diff(l_starts_np)
    r_lens = np.diff(r_starts_np)
    cap_l = int(l_lens.max()) if B else 0
    cap_r = int(r_lens.max()) if B else 0
    if cap_l == 0 or cap_r == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)

    l_starts = jnp.asarray(l_starts_np)
    r_starts = jnp.asarray(r_starts_np)
    # Reserve the pad value: a real key equal to _PAD (p≈2^-63) is nudged down one;
    # the resulting potential false match is removed by the caller's verification.
    l_keys = jnp.minimum(jnp.asarray(l_keys), _PAD - 1)
    r_keys = jnp.minimum(jnp.asarray(r_keys), _PAD - 1)
    ls, l_order, l_len = _pad_and_sort(l_keys, l_starts, B, cap_l)
    rs, r_order, r_len = _pad_and_sort(r_keys, r_starts, B, cap_r)
    lo, counts = _probe(ls, rs, l_len, r_len)
    total = int(counts.sum())  # the one scalar sync
    if total == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    l_global, r_global = _expand(lo, counts, l_order, r_order, l_starts, r_starts, total)
    return np.asarray(l_global), np.asarray(r_global)
