"""Batched co-bucketed merge join: ALL bucket pairs joined in one device program.

The co-bucketed sort-merge join (reference `JoinIndexRule.scala:137-162`: equal keys
are co-located in equal-numbered buckets, so no shuffle is needed) must not be executed
as a Python loop over buckets — B small per-bucket dispatches with distinct shapes
defeat XLA. Instead the bucket axis becomes a *batch dimension*:

1. Scatter each side's per-row key64 into a padded [B, cap] matrix (pad = i64 max).
2. One batched sort along the row axis (pads sort to the end).
3. One batched searchsorted probe (vmap), ranges clamped to each bucket's valid length.
4. Two-pass expansion (count → scalar sync → scatter) exactly like the global join.

Static shapes throughout; the bucket axis is also the natural shard axis on a device
mesh (each device owns a contiguous bucket range and never communicates).
"""

from __future__ import annotations

import os
import time

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import device_observatory as _devobs
from ..telemetry.compile_log import observed_jit as _observed_jit

_PAD = jnp.iinfo(jnp.int64).max

#: Size-classed padding (the skew-aware layout) is the default; ``=0`` restores
#: the single global-cap dense layout exactly as it was.
ENV_SIZE_CLASSES = "HYPERSPACE_JOIN_SIZE_CLASSES"
#: A bucket whose larger side exceeds ``factor × median`` of the active
#: buckets' larger sides leaves the padded layout entirely and merges on host
#: (per bucket). ``<=0`` disables the outlier path.
ENV_OUTLIER_FACTOR = "HYPERSPACE_JOIN_OUTLIER_FACTOR"
_DEFAULT_OUTLIER_FACTOR = 8.0
# Cap on the number of capacity classes: beyond this the per-class dispatch
# (and, on the device path, per-shape compiles) start eating the padding win.
_MAX_CLASSES = 8


def size_classes_enabled() -> bool:
    """Default ON; unset defers to the adaptive planner's per-query decision
    when one is ambient — explicit flags always win (`docs/planner.md`)."""
    raw = os.environ.get(ENV_SIZE_CLASSES, "")
    if raw != "":
        return raw != "0"
    from ..plananalysis.planner import decided_value

    decided = decided_value("join_size_classes")
    return True if decided is None else bool(decided)


def _outlier_factor() -> float:
    raw = os.environ.get(ENV_OUTLIER_FACTOR, "")
    try:
        return float(raw) if raw else _DEFAULT_OUTLIER_FACTOR
    except ValueError:
        return _DEFAULT_OUTLIER_FACTOR


def _cap_pow2(n: int) -> int:
    """Quantize a padded-bucket capacity to the next power of two: growing data
    reuses the compiled kernels instead of recompiling per exact max bucket size."""
    return 1 << (max(1, n) - 1).bit_length()


def mesh_probe_skew_safe(l_starts, r_starts) -> bool:
    """Whether the MESH-sharded co-bucketed probe should claim this bucket
    layout. The sharded probe pads every bucket to the GLOBAL max bucket
    length (one [B_local, cap] matrix per device) — a single outlier bucket
    multiplies every device's probe area, exactly the skew blowup JSPIM
    measures and the PR-3 size-classed executor exists to avoid. Reuses the
    classed executor's own outlier criterion (larger side > factor × median
    of active larger sides): skewed layouts stay on the size-classed
    single-device path; balanced layouts take the mesh. Disabled size
    classes (=0) always answer True — with the skew machinery off there is
    no better fallback to protect."""
    if not size_classes_enabled():
        return True
    l_lens = np.diff(np.asarray(l_starts, np.int64))
    r_lens = np.diff(np.asarray(r_starts, np.int64))
    n = min(len(l_lens), len(r_lens))
    l_lens, r_lens = l_lens[:n], r_lens[:n]
    active = np.nonzero((l_lens > 0) & (r_lens > 0))[0]
    if len(active) == 0:
        return True
    factor = _outlier_factor()
    if factor <= 0:
        return True
    mx = np.maximum(l_lens, r_lens)[active]
    return bool(mx.max(initial=0) <= factor * max(float(np.median(mx)), 1.0))


@_observed_jit(label="bucket_join.pad_scatter", static_argnums=(2, 3))
def _pad_scatter(keys, starts, num_buckets: int, cap: int):
    """Scatter per-row keys (concatenated in bucket order) into an UNSORTED
    padded [B, cap] matrix (pad = i64 max) + per-bucket lengths — the input
    shape the Pallas in-VMEM sort consumes."""
    n = keys.shape[0]
    pos = jnp.arange(n)
    b_of_row = jnp.searchsorted(starts, pos, side="right") - 1
    slot = pos - starts[b_of_row]
    padded = jnp.full((num_buckets, cap), _PAD, dtype=jnp.int64)
    padded = padded.at[b_of_row, slot].set(keys)
    lengths = starts[1:] - starts[:-1]
    return padded, lengths


@_observed_jit(label="bucket_join.pad_and_sort", static_argnums=(2, 3))
def _pad_and_sort(keys, starts, num_buckets: int, cap: int):
    """Scatter per-row keys (concatenated in bucket order) into a sorted [B, cap]
    matrix. Returns (sorted_keys [B,cap], order [B,cap] slot→original-slot, lengths).
    ONE scatter implementation: composes `_pad_scatter` (jit nests fine), so the
    Pallas and XLA paths can never diverge on the bucket-mapping semantics."""
    padded, lengths = _pad_scatter(keys, starts, num_buckets, cap)
    order = jnp.argsort(padded, axis=1)
    sorted_keys = jnp.take_along_axis(padded, order, axis=1)
    return sorted_keys, order, lengths


@_observed_jit(label="bucket_join.probe")
def _probe(ls, rs, l_len, r_len):
    """Batched range probe: for each left slot, the [lo, hi) match range in the
    right bucket, clamped to valid rows; counts zeroed for left pad slots.
    int32 outputs (slots/counts are bounded by cap): halves the device→host
    transfer the expansion consumes."""
    lo = jax.vmap(lambda r, l: jnp.searchsorted(r, l, side="left"))(rs, ls)
    hi = jax.vmap(lambda r, l: jnp.searchsorted(r, l, side="right"))(rs, ls)
    r_len_b = r_len[:, None]
    lo = jnp.minimum(lo, r_len_b)
    hi = jnp.minimum(hi, r_len_b)
    valid_left = jnp.arange(ls.shape[1])[None, :] < l_len[:, None]
    counts = jnp.where(valid_left, hi - lo, 0)
    return lo.astype(jnp.int32), counts.astype(jnp.int32)


def _probe_host(L, R, l_len, r_len):
    """Host twin of `_probe` for the CPU backend: per-bucket `np.searchsorted`
    over the valid regions (XLA-CPU's vmap'd searchsorted measured ~4x slower
    at bench shapes — 1.19 s vs 0.31 s at 64x131072 probing 64x16384). Same
    contract: int32 (lo, counts), counts zeroed on left pad slots, ranges
    clamped to the right side's valid length by construction (the build slice
    stops at r_len)."""
    lo = np.zeros(L.shape, np.int32)
    cnt = np.zeros(L.shape, np.int32)
    for b in range(L.shape[0]):
        n, m = int(l_len[b]), int(r_len[b])
        if n == 0 or m == 0:
            continue
        probe, build = L[b, :n], R[b, :m]
        left = np.searchsorted(build, probe, "left")
        lo[b, :n] = left
        cnt[b, :n] = (np.searchsorted(build, probe, "right") - left).astype(
            np.int32
        )
    return lo, cnt


def _expand_np(
    lo: np.ndarray,
    counts: np.ndarray,
    l_starts: np.ndarray,
    r_starts: np.ndarray,
    l_order: np.ndarray = None,
    r_order: np.ndarray = None,
):
    """Expand count ranges into global (left_row, right_row) index pairs.

    Host-side numpy: the expansion is data-dependent-size gather/repeat work that
    the final host gather consumes anyway — running it eagerly op-by-op on device
    costs more in dispatch than the arithmetic (measured 0.5s → ~30ms at 2M rows).
    `l_order`/`r_order` map within-bucket sorted slots back to storage slots; None
    means the matrices were built value-direct (slot == storage position)."""
    B, cap_l = counts.shape
    counts_flat = counts.reshape(-1)
    lo_flat = lo.reshape(-1).astype(np.int64)
    starts_flat = np.cumsum(counts_flat, dtype=np.int64) - counts_flat
    l_flat = np.repeat(np.arange(B * cap_l), counts_flat)
    offset = np.arange(l_flat.shape[0]) - starts_flat[l_flat]
    b = l_flat // cap_l
    l_slot = l_flat % cap_l
    r_slot = lo_flat[l_flat] + offset
    if l_order is not None:
        l_slot = l_order[b, l_slot]
    if r_order is not None:
        r_slot = r_order[b, r_slot]
    return l_starts[b] + l_slot, r_starts[b] + r_slot


@_observed_jit(label="bucket_join.expand_pairs", static_argnums=(0, 1))
def _expand_pairs_dev(
    out_cap: int,
    has_order: bool,
    lo,
    counts,
    a_starts,
    b_starts,
    a_order,
    b_order,
):
    """ON-DEVICE expansion of probe count ranges into global (a_row, b_row)
    index pairs, padded to a static `out_cap` (pow2-quantized so repeat queries
    reuse the compiled program). The host variant (`_expand_np`) materializes
    the ranges with numpy; on a TPU the gathered pairs feed DEVICE consumers
    (count, fused join+aggregate), so expanding on device avoids the
    device->host->device round trip of the probe matrices entirely.

    Standard searchsorted expansion: output position j belongs to the flat left
    slot whose inclusive count prefix first exceeds j. Slots past `total` carry
    garbage and are masked by the returned validity lane (gathers clamp)."""
    cap_l = counts.shape[1]
    counts_flat = counts.reshape(-1).astype(jnp.int64)
    e = jnp.cumsum(counts_flat)  # inclusive prefix
    total = e[-1]
    j = jnp.arange(out_cap, dtype=jnp.int64)
    src = jnp.searchsorted(e, j, side="right")
    src = jnp.minimum(src, counts_flat.shape[0] - 1)
    offset = j - (e[src] - counts_flat[src])
    bkt = src // cap_l
    a_slot = src % cap_l
    b_slot = lo.reshape(-1).astype(jnp.int64)[src] + offset
    if has_order:
        a_slot = a_order[bkt, a_slot]
        b_slot = b_order[bkt, jnp.clip(b_slot, 0, b_order.shape[1] - 1)]
    ai = a_starts[bkt] + a_slot
    bi = b_starts[bkt] + b_slot
    return ai, bi, j < total


@_observed_jit(label="bucket_join.compact_pairs", static_argnums=(0,))
def _compact_pairs_dev(out_cap2: int, ai, bi, keep):
    """Stream-compact verified pairs to a static pow2 size. Pad slots repeat
    the FIRST kept pair (a real, verified pair), so downstream group detection
    over gathered values cannot invent spurious groups — pad contributions are
    masked out of every reduction by the `j < n_keep` lane the caller builds."""
    pos = jnp.cumsum(keep.astype(jnp.int64)) - 1
    idx = jnp.where(keep, pos, out_cap2)  # dropped -> out-of-bounds
    a2 = jnp.zeros(out_cap2, ai.dtype).at[idx].set(ai, mode="drop")
    b2 = jnp.zeros(out_cap2, bi.dtype).at[idx].set(bi, mode="drop")
    a2 = jnp.where(jnp.arange(out_cap2) < pos[-1] + 1, a2, a2[0])
    b2 = jnp.where(jnp.arange(out_cap2) < pos[-1] + 1, b2, b2[0])
    return a2, b2


def _counts_total(counts):
    if isinstance(counts, np.ndarray):  # host probe output: no device hop
        return counts.sum(dtype=np.int64)
    return _counts_total_jit(counts)


@_observed_jit(label="bucket_join.counts_total")
def _counts_total_jit(counts):
    return counts.sum(dtype=jnp.int64)


@_observed_jit(label="bucket_join.pad_only", static_argnums=(2, 3))
def _pad_only(vals, starts, num_buckets: int, cap: int, pad_value):
    """Scatter per-row values (concatenated in bucket order) into a padded [B, cap]
    matrix WITHOUT sorting, plus a per-bucket sortedness check."""
    n = vals.shape[0]
    pos = jnp.arange(n)
    b_of_row = jnp.searchsorted(starts, pos, side="right") - 1
    slot = pos - starts[b_of_row]
    padded = jnp.full((num_buckets, cap), pad_value, dtype=vals.dtype)
    padded = padded.at[b_of_row, slot].set(vals)
    lengths = starts[1:] - starts[:-1]
    valid = jnp.arange(cap - 1)[None, :] < (lengths - 1)[:, None]
    non_decreasing = jnp.where(valid, padded[:, 1:] >= padded[:, :-1], True).all()
    return padded, lengths, non_decreasing


class PaddedBuckets:
    """Device-resident padded representation of one side of a co-bucketed join:
    `keys` [B, cap] sorted within each row (pad = dtype max), `lengths` [B] valid
    counts, `order` [B, cap] host map sorted-slot → storage-slot (None when the
    matrix was built value-direct, i.e. storage order IS sorted order), `starts`
    host bucket offsets. Cacheable across queries — the whole point: a steady-state
    indexed join starts at the probe."""

    __slots__ = ("keys", "lengths", "order", "starts", "mode")

    def __init__(self, keys, lengths, order, starts, mode: str):
        self.keys = keys
        self.lengths = lengths
        self.order = order
        self.starts = starts
        self.mode = mode  # "value" | "hash"

    @property
    def nbytes(self) -> int:
        """Bytes pinned by this rep (device matrices + host maps) — what the
        engine's device-cache byte budget accounts."""
        total = 0
        for a in (self.keys, self.lengths, self.order, self.starts):
            total += int(getattr(a, "nbytes", 0) or 0)
        return total


def pad_buckets_by_value(vals, starts_np: np.ndarray) -> Optional[PaddedBuckets]:
    """Value-direct padded matrices for a side whose buckets are ALREADY sorted by
    the (single, numeric, null-free) key — the covering-index fast path: the sort
    happened once at build time (`ops.partition.bucketize_table` orders each bucket
    by the indexed columns), so queries need no hashing and no argsort. Returns
    None if the buckets turn out unsorted (e.g. multi-file buckets after
    incremental refresh); caller falls back to the hash path."""
    B = len(starts_np) - 1
    lens = np.diff(starts_np)
    if B == 0 or lens.max(initial=0) == 0:
        return None
    cap = _cap_pow2(int(lens.max()))
    vals = jnp.asarray(vals)
    if jnp.issubdtype(vals.dtype, jnp.floating):
        # NaN keys disqualify value mode EXPLICITLY: value-mode probe counts
        # are trusted without verification, but every probe implementation
        # counts NaN as matching NaN while the engine's equality says NaN
        # never equals anything. (Multi-row NaN buckets already fail the
        # non-decreasing check below — NaN >= x is false — but a SINGLETON
        # NaN bucket has zero comparisons and would slip through.) The hash
        # rep canonicalizes NaN and verifies exactly.
        if bool(jnp.isnan(vals).any()):
            return None
        # Canonicalize -0.0 -> +0.0: probe implementations disagree on signed
        # zeros (numpy searchsorted compares IEEE-equal; lax.sort's total
        # order puts -0.0 < +0.0 on some backends), and the engine's equality
        # treats them equal — canonical keys make every probe agree.
        vals = jnp.where(vals == 0, jnp.zeros((), vals.dtype), vals)
        pad = jnp.asarray(jnp.finfo(vals.dtype).max, dtype=vals.dtype)
    else:
        pad = jnp.asarray(jnp.iinfo(vals.dtype).max, dtype=vals.dtype)
    keys, lengths, ok = _pad_only(vals, jnp.asarray(starts_np), B, cap, pad)
    if not bool(ok):
        return None
    _record_bucket_pad(int(starts_np[-1]), B, cap, int(vals.dtype.itemsize))
    return PaddedBuckets(keys, lengths, None, starts_np, "value")


def _record_bucket_pad(rows: int, B: int, cap: int, itemsize: int) -> None:
    """Padding-tax ledger for one padded [B, cap] key matrix: `rows` real
    keys staged inside B×cap slots (the cost the size-classed layout exists
    to shrink — now measured per query, not modeled)."""
    _devobs.record_pad(
        "join_buckets", rows * itemsize, (B * cap - rows) * itemsize
    )


def pad_buckets_by_hash(key64_arr, starts_np: np.ndarray) -> PaddedBuckets:
    """Hash-key padded matrices (argsort within bucket) for the general case:
    multi-column or string keys, nullable keys, or unsorted buckets. Within
    its VMEM shape budget the in-bucket sort dispatches to the Pallas
    single-pass bitonic kernel (`ops.pallas_sort`), guarded like the probe —
    any lowering failure falls back to the XLA argsort permanently."""
    from .backend import pallas_maybe_wanted

    B = len(starts_np) - 1
    lens = np.diff(starts_np)
    cap = _cap_pow2(int(lens.max())) if B else 1
    keys_nudged = jnp.minimum(jnp.asarray(key64_arr), _PAD - 1)
    if pallas_maybe_wanted("HYPERSPACE_PALLAS_SORT"):
        from .pallas_sort import (
            pallas_sort_wanted,
            record_sort_failure,
            sort_padded_with_order,
        )

        if pallas_sort_wanted(B, cap):
            try:
                padded, lengths = _pad_scatter(
                    keys_nudged, jnp.asarray(starts_np), B, cap
                )
                keys, order = sort_padded_with_order(padded)
                _record_bucket_pad(int(starts_np[-1]), B, cap, 8)
                return PaddedBuckets(
                    keys, lengths, np.asarray(order), starts_np, "hash"
                )
            except Exception as e:  # Mosaic lowering/runtime problems
                record_sort_failure(e)
    keys, order, lengths = _pad_and_sort(keys_nudged, jnp.asarray(starts_np), B, cap)
    _record_bucket_pad(int(starts_np[-1]), B, cap, 8)
    return PaddedBuckets(keys, lengths, np.asarray(order), starts_np, "hash")


def probe_orientation(left, right):
    """Canonical probe orientation — the SMALLER-capacity side probes into the
    larger (search count scales linearly with the probing side's capacity, only
    logarithmically with the other's). Single source of the heuristic, shared by
    `probe_padded`, the sharded `probe_dist_blocks`, and the bench's kernel
    isolation. Returns (probe_side, build_side, swapped)."""
    if left.keys.shape[1] > right.keys.shape[1]:
        return right, left, True
    return left, right, False


def probe_keys_promoted(a_keys, b_keys):
    """Key matrices promoted to a common dtype (value-direct sides may be int32/
    float while hash sides are int64). NUMPY's promotion lattice, matching the
    exact-verification pass (int64 x float32 -> float64 there; JAX's lattice
    would give float32 and a 2^24-magnitude int could falsely probe-match)."""
    if a_keys.dtype != b_keys.dtype:
        common = np.promote_types(np.dtype(a_keys.dtype), np.dtype(b_keys.dtype))
        return a_keys.astype(common), b_keys.astype(common)
    return a_keys, b_keys


def probe_ranges(ls, rs, l_len, r_len):
    """Probe dispatcher: the Pallas tiled-compare kernel when wanted (on-TPU
    within its capacity budget, or HYPERSPACE_PALLAS_PROBE=1), else the XLA
    vmap'd-searchsorted probe; the CPU backend probes on host (numpy
    searchsorted, ~4x the XLA-CPU probe). Any Pallas failure is recorded once
    and falls back permanently — an index problem must never break a query."""
    from .backend import pallas_maybe_wanted, use_device_path

    # Cheap pre-gate before touching pallas at all: importing
    # jax.experimental.pallas costs ~1 s on first use, and on the plain CPU
    # backend the kernel is never wanted — the import would be pure cold-path
    # tax (measured as the dominant cost of the first 8M indexed count).
    if pallas_maybe_wanted("HYPERSPACE_PALLAS_PROBE"):
        from .pallas_probe import (
            pallas_probe_wanted,
            probe_pallas,
            record_pallas_failure,
        )

        if pallas_probe_wanted(
            int(ls.shape[1]), int(rs.shape[1]), int(ls.shape[0]), ls.dtype
        ):
            # Checked FIRST: HYPERSPACE_PALLAS_PROBE=1 forces the kernel even
            # on the CPU backend (interpret-mode validation rides this).
            try:
                return probe_pallas(ls, rs, l_len, r_len)
            except Exception as e:  # Mosaic lowering/runtime problems
                record_pallas_failure(e, ls.dtype)
    if not use_device_path():
        return _probe_host(
            np.asarray(ls), np.asarray(rs), np.asarray(l_len), np.asarray(r_len)
        )
    return _probe(ls, rs, l_len, r_len)


# ---------------------------------------------------------------------------
# Packed code-mode padded reps (sub-byte dictionary codes)
# ---------------------------------------------------------------------------
#
# A single low-cardinality STRING key doesn't need 64-bit hash keys at all:
# its dictionary codes order-embed the join equality (equal code <=> equal
# string within the shared dictionary), and below int8 they pack into uint32
# lane words (`engine/packed_codes.py`). These reps keep the device-resident
# padded matrices in PACKED form — 8-32x smaller HBM residency than the int64
# hash rep — and the probe computes on the words directly (Pallas packed
# kernel) or unpacks once and reuses the generic probe (the widen-then-probe
# fallback the bench compares against).


class PackedCodeBuckets:
    """Packed-word twin of `PaddedBuckets`: `words` [B, cap/lpw] uint32 rows
    of sorted BIASED codes (code + 1; pad slots hold the top lane value),
    `bits` the lane width, `lengths`/`order`/`starts` as in the hash rep."""

    __slots__ = ("words", "bits", "lengths", "order", "starts", "cap")

    def __init__(self, words, bits: int, lengths, order, starts, cap: int):
        self.words = words
        self.bits = bits
        self.lengths = lengths
        self.order = order
        self.starts = starts
        self.cap = cap

    @property
    def nbytes(self) -> int:
        total = 0
        for a in (self.words, self.lengths, self.order, self.starts):
            total += int(getattr(a, "nbytes", 0) or 0)
        return total


@_observed_jit(label="bucket_join.pad_scatter_codes", static_argnums=(2, 3, 4))
def _pad_scatter_codes(codes, starts, num_buckets: int, cap: int, bits: int):
    """`_pad_scatter` for code lanes: scatter raw codes (null = -1) into an
    UNSORTED padded [B, cap] int32 matrix of BIASED codes (code + 1), pad =
    2**bits - 1 — the top lane value `probe_bits_for_cardinality` reserves, so
    pads sort last exactly like the i64-max pad of the hash rep."""
    n = codes.shape[0]
    pos = jnp.arange(n)
    b_of_row = jnp.searchsorted(starts, pos, side="right") - 1
    slot = pos - starts[b_of_row]
    padded = jnp.full((num_buckets, cap), (1 << bits) - 1, dtype=jnp.int32)
    padded = padded.at[b_of_row, slot].set(codes.astype(jnp.int32) + 1)
    lengths = starts[1:] - starts[:-1]
    return padded, lengths


@_observed_jit(label="bucket_join.pad_and_sort_codes", static_argnums=(2, 3, 4))
def _pad_and_sort_codes(codes, starts, num_buckets: int, cap: int, bits: int):
    """XLA fallback twin of `pallas_sort.sort_codes_packed`: scatter + stable
    argsort on the flat biased matrix. Same (sorted, order, lengths) contract."""
    padded, lengths = _pad_scatter_codes(codes, starts, num_buckets, cap, bits)
    order = jnp.argsort(padded, axis=1)
    return jnp.take_along_axis(padded, order, axis=1), order, lengths


@_observed_jit(label="bucket_join.pack_code_rows", static_argnums=(1,))
def _pack_code_rows(mat, bits: int):
    from ..engine.packed_codes import pack_rows_traced

    return pack_rows_traced(mat, bits)


@_observed_jit(label="bucket_join.unpack_code_rows", static_argnums=(1,))
def _unpack_code_rows(words, bits: int):
    from ..engine.packed_codes import unpack_rows_traced

    return unpack_rows_traced(words, bits)


def pad_buckets_by_codes(
    codes, starts_np: np.ndarray, cardinality: int, has_nulls: bool = False
) -> Optional[PackedCodeBuckets]:
    """Packed code-mode rep for a single low-cardinality string key. Returns
    None when the key doesn't qualify (cardinality past the 4-bit compute
    bound, nulls present — like the value-direct rep, null semantics belong
    to the hash path — or degenerate bucket layouts). In-bucket sorting rides
    the Pallas packed-word sort when wanted, else the XLA argsort fallback;
    either way the RESIDENT matrix is packed words."""
    from ..engine.packed_codes import (
        lanes_per_word,
        probe_bits_for_cardinality,
    )
    from .backend import pallas_maybe_wanted

    if has_nulls:
        return None
    bits = probe_bits_for_cardinality(int(cardinality))
    if bits is None:
        return None
    B = len(starts_np) - 1
    lens = np.diff(starts_np)
    if B == 0 or lens.max(initial=0) == 0:
        return None
    cap = max(_cap_pow2(int(lens.max())), lanes_per_word(bits))
    codes = jnp.asarray(codes)
    starts = jnp.asarray(starts_np)
    sorted_codes = order = lengths = None
    if pallas_maybe_wanted("HYPERSPACE_PALLAS_SORT"):
        from .pallas_sort import (
            pallas_packed_sort_wanted,
            record_sort_failure,
            sort_codes_packed,
        )

        if pallas_packed_sort_wanted(B, cap, bits):
            try:
                padded, lengths = _pad_scatter_codes(codes, starts, B, cap, bits)
                sorted_codes, order = sort_codes_packed(
                    _pack_code_rows(padded, bits), bits
                )
            except Exception as e:  # Mosaic lowering/runtime problems
                record_sort_failure(e)
                sorted_codes = None
    if sorted_codes is None:
        sorted_codes, order, lengths = _pad_and_sort_codes(
            codes, starts, B, cap, bits
        )
    words = _pack_code_rows(sorted_codes, bits)
    rows = int(starts_np[-1])
    _devobs.record_pad(
        "join_buckets", -(-rows * bits // 8), -(-(B * cap - rows) * bits // 8)
    )
    return PackedCodeBuckets(
        words, bits, lengths, np.asarray(order), starts_np, cap
    )


def probe_code_ranges(l: PackedCodeBuckets, r: PackedCodeBuckets):
    """Probe dispatcher for packed code reps: the Pallas packed-word kernel
    when wanted (own "packed" latch), else widen-then-probe — one device
    unpack to flat int32 matrices feeding the generic probe (`_probe`, or the
    host searchsorted off the device path). Biased codes compare consistently
    on both sides, so ranges are identical across all three paths."""
    from .backend import pallas_maybe_wanted, use_device_path

    if l.bits != r.bits:
        raise ValueError(f"packed rep bits mismatch: {l.bits} != {r.bits}")
    B = l.words.shape[0]
    if pallas_maybe_wanted("HYPERSPACE_PALLAS_PROBE"):
        from .pallas_probe import (
            pallas_packed_probe_wanted,
            probe_packed_pallas,
            record_pallas_failure,
        )

        if pallas_packed_probe_wanted(l.cap, r.cap, B, l.bits):
            try:
                return probe_packed_pallas(
                    l.words, r.words, l.bits, l.lengths, r.lengths
                )
            except Exception as e:  # Mosaic lowering/runtime problems
                record_pallas_failure(e, kind="packed")
    ls = _unpack_code_rows(l.words, l.bits)
    rs = _unpack_code_rows(r.words, r.bits)
    if not use_device_path():
        return _probe_host(
            np.asarray(ls),
            np.asarray(rs),
            np.asarray(l.lengths),
            np.asarray(r.lengths),
        )
    return _probe(ls, rs, l.lengths, r.lengths)


# ---------------------------------------------------------------------------
# Size-classed (skew-aware) layout
# ---------------------------------------------------------------------------
#
# The dense layout above pads EVERY bucket to the global max bucket size, so a
# single hot key inflates `num_buckets × cap` — at the 8M CPU bench the padded
# sort alone (`pad_sort_p50`) was the slowest surviving kernel (2.44 s), and a
# skewed key distribution multiplies the padded area by the skew ratio. The
# classed layout (JSPIM-style, PAPERS.md) groups the ACTIVE buckets (non-empty
# on both sides) into a small set of pow2 capacity classes; each class gets its
# own padded matrices and its own probe program (the Pallas tiled-compare
# kernel dispatches per class on TPU, where the smaller per-class capacity
# products fall inside its quadratic-compare budget far more often than the
# global cap did). Oversized outlier buckets skip padding entirely and merge
# on host per bucket (`ops.join.host_merge_pairs`). On the CPU backend the
# class matrices are built with numpy (per-bucket stable argsort over the
# actual rows) — no XLA scatter/argsort over padded slots at all.


class _ClassSide:
    """One side of one capacity class: `keys` [B, cap] sorted within each row,
    `lengths` [B] valid counts, `order` [B, cap] sorted-slot → storage-slot
    (None in value mode), `starts` [B] GLOBAL row offsets of the class's
    buckets (indexable by the class-local bucket row)."""

    __slots__ = ("keys", "lengths", "order", "starts", "cap")

    def __init__(self, keys, lengths, order, starts, cap: int):
        self.keys = keys
        self.lengths = lengths
        self.order = order
        self.starts = starts
        self.cap = cap

    @property
    def nbytes(self) -> int:
        return sum(
            int(getattr(a, "nbytes", 0) or 0)
            for a in (self.keys, self.lengths, self.order, self.starts)
        )


class JoinSegment:
    """One capacity class of a classed join plan: the bucket ids it covers
    (ascending, SHARED by both sides — the partition is joint) and the two
    padded sides."""

    __slots__ = ("ids", "l", "r")

    def __init__(self, ids: np.ndarray, l: _ClassSide, r: _ClassSide):
        self.ids = ids
        self.l = l
        self.r = r


class ClassedJoinPlan:
    """Joint size-classed layout of one co-bucketed join pair. `l_vals`/
    `r_vals` are the HOST key arrays in the joint key space (key64 for hash
    mode, canonicalized actual values for value mode), concatenated in bucket
    order — the outlier merge and the host probe slice them directly.
    Cacheable per table pair (the classed analogue of `PaddedBuckets`)."""

    __slots__ = (
        "mode",
        "segments",
        "outlier_ids",
        "l_vals",
        "r_vals",
        "l_starts",
        "r_starts",
        "num_buckets",
    )

    def __init__(
        self, mode, segments, outlier_ids, l_vals, r_vals, l_starts, r_starts
    ):
        self.mode = mode  # "value" | "hash"
        self.segments = segments
        self.outlier_ids = outlier_ids
        self.l_vals = l_vals
        self.r_vals = r_vals
        self.l_starts = l_starts
        self.r_starts = r_starts
        self.num_buckets = len(l_starts) - 1

    @property
    def nbytes(self) -> int:
        total = int(self.l_vals.nbytes) + int(self.r_vals.nbytes)
        for seg in self.segments:
            total += seg.l.nbytes + seg.r.nbytes
        return total


class ClassedRanges:
    """Probe output of a classed plan: per segment (lo, counts, swapped,
    seg_total) in the segment's own probe orientation, plus the outlier
    buckets' already-expanded GLOBAL candidate pairs. `total` counts every
    candidate pair (exact matches in value mode)."""

    __slots__ = ("segments", "outliers", "total")

    def __init__(self, segments, outliers, total: int):
        self.segments = segments
        self.outliers = outliers
        self.total = total

    @property
    def nbytes(self) -> int:
        total = 0
        for lo, counts, _sw, _tot in self.segments:
            total += int(getattr(lo, "nbytes", 0)) + int(getattr(counts, "nbytes", 0))
        for _b, li, ri in self.outliers:
            total += int(li.nbytes) + int(ri.nbytes)
        return total


def joint_partition(
    l_starts: np.ndarray, r_starts: np.ndarray
) -> Tuple[List[np.ndarray], np.ndarray]:
    """Partition the ACTIVE buckets (rows on BOTH sides — a bucket empty on
    either side produces no pairs and is skipped entirely) into capacity
    classes by the pow2 caps of their two sides, with oversized outliers
    split off for the host merge path. Returns (class id-arrays ascending,
    outlier ids). The partition is a pure function of the two bucket-offset
    arrays, so both sides of a join always agree on it."""
    l_lens = np.diff(np.asarray(l_starts, np.int64))
    r_lens = np.diff(np.asarray(r_starts, np.int64))
    active = np.nonzero((l_lens > 0) & (r_lens > 0))[0]
    if len(active) == 0:
        return [], np.empty(0, np.int64)
    mx = np.maximum(l_lens, r_lens)[active]
    factor = _outlier_factor()
    if factor > 0:
        out_mask = mx > factor * max(float(np.median(mx)), 1.0)
    else:
        out_mask = np.zeros(len(active), bool)
    outliers = active[out_mask]
    rest = active[~out_mask]
    if len(rest) == 0:
        return [], outliers

    def group_by_caps(quantize) -> dict:
        classes: dict = {}
        for b in rest:
            key = (quantize(int(l_lens[b])), quantize(int(r_lens[b])))
            classes.setdefault(key, []).append(int(b))
        return classes

    classes = group_by_caps(_cap_pow2)
    if len(classes) > _MAX_CLASSES:
        # Coarsen to power-of-4 caps (halves the distinct-class count bound).
        def cap_pow4(n: int) -> int:
            bits = (max(1, n) - 1).bit_length()
            return 1 << (bits + (bits & 1))

        classes = group_by_caps(cap_pow4)
    if len(classes) > _MAX_CLASSES:
        classes = {("all", "all"): [int(b) for b in rest]}
    groups = [
        np.asarray(sorted(ids), np.int64)
        for _key, ids in sorted(
            classes.items(), key=lambda kv: (str(kv[0]), kv[1][0])
        )
    ]
    return groups, outliers


def value_mode_vals(data, starts) -> Optional[np.ndarray]:
    """Canonicalized HOST key values for value mode, or None when the column
    disqualifies: NaN keys (probe equality would disagree with SQL's
    NaN != NaN) or buckets not sorted by the key (e.g. multi-file buckets
    after incremental refresh). Same contract as `pad_buckets_by_value`,
    checked on host without building any padded matrix."""
    vals = np.asarray(data)
    if np.issubdtype(vals.dtype, np.floating):
        if bool(np.isnan(vals).any()):
            return None
        # -0.0 -> +0.0: probe implementations must agree on signed zeros.
        vals = np.where(vals == 0, np.zeros((), vals.dtype), vals)
    n = vals.shape[0]
    if n > 1:
        adj = vals[1:] >= vals[:-1]
        # Bucket boundaries are exempt from the non-decreasing check.
        bounds = np.asarray(starts, np.int64)[1:-1] - 1
        bounds = bounds[(bounds >= 0) & (bounds < n - 1)]
        adj[bounds] = True
        if not bool(adj.all()):
            return None
    return vals


def _host_pad_value(dtype) -> np.ndarray:
    if np.issubdtype(dtype, np.floating):
        return np.asarray(np.finfo(dtype).max, dtype=dtype)
    return np.asarray(np.iinfo(dtype).max, dtype=dtype)


def _build_side(
    vals: np.ndarray,
    starts: np.ndarray,
    ids: np.ndarray,
    mode: str,
    device: bool,
) -> Optional[_ClassSide]:
    """Padded matrices of one class of one side. Host build (CPU backend):
    numpy scatter + per-bucket stable argsort over the ACTUAL rows only —
    measured ~2x the XLA-CPU padded argsort at bench shapes, and pad slots are
    never sorted at all. Device build: the class rows re-concatenate and ride
    the existing jitted `_pad_and_sort`/`_pad_only` programs (Pallas sort
    included via `pad_buckets_by_hash`), with the bucket axis pow2-quantized
    by EMPTY virtual buckets so growing class populations reuse compiles."""
    lens = (starts[ids + 1] - starts[ids]).astype(np.int64)
    cap = _cap_pow2(int(lens.max()))
    gstarts = starts[ids].astype(np.int64)
    if device:
        concat = (
            np.concatenate([vals[starts[b] : starts[b + 1]] for b in ids])
            if len(ids)
            else vals[:0]
        )
        b_pad = _cap_pow2(len(ids))
        cstarts = np.zeros(b_pad + 1, np.int64)
        np.cumsum(lens, out=cstarts[1 : len(ids) + 1])
        cstarts[len(ids) + 1 :] = cstarts[len(ids)]
        if mode == "hash":
            rep = pad_buckets_by_hash(jnp.asarray(concat), cstarts)
        else:
            rep = pad_buckets_by_value(jnp.asarray(concat), cstarts)
            if rep is None:
                return None
        gstarts_pad = np.zeros(b_pad, np.int64)
        gstarts_pad[: len(ids)] = gstarts
        return _ClassSide(
            rep.keys, rep.lengths, rep.order, gstarts_pad, int(rep.keys.shape[1])
        )
    B = len(ids)
    # Classed host build stages its own [B, cap] matrix; the device branch's
    # tax is recorded inside `pad_buckets_by_*` (no double counting).
    _devobs.record_pad(
        "join_class",
        int(lens.sum()) * int(vals.dtype.itemsize),
        (B * cap - int(lens.sum())) * int(vals.dtype.itemsize),
    )
    keys = np.full((B, cap), _host_pad_value(vals.dtype), vals.dtype)
    order = np.zeros((B, cap), np.int64) if mode == "hash" else None
    for k, b in enumerate(ids):
        s, e = int(starts[b]), int(starts[b + 1])
        sl = vals[s:e]
        if mode == "hash":
            o = np.argsort(sl, kind="stable")
            keys[k, : e - s] = sl[o]
            order[k, : e - s] = o
        else:
            keys[k, : e - s] = sl
    return _ClassSide(keys, lens, order, gstarts, cap)


def fact_bucket_layout(
    bucket_ids: np.ndarray, num_buckets: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Bucket-order layout of a PROBE-side table for one star dimension:
    `perm` stably reorders rows into ascending-bucket order and `starts`
    (length num_buckets+1) delimits each bucket's slice — the same
    (bucket-ordered rows, starts) contract `build_classed_plan` expects of a
    bucketed index concat, computed on the fly for a fact table that was
    never bucket-partitioned on this dimension's keys. Stability keeps the
    within-bucket order deterministic (table order), so repeated probes and
    the pair memos agree."""
    bid = np.asarray(bucket_ids, np.int64)
    perm = np.argsort(bid, kind="stable")
    counts = np.bincount(bid, minlength=num_buckets)
    starts = np.zeros(num_buckets + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    return perm, starts


def build_classed_plan(
    l_vals: np.ndarray,
    r_vals: np.ndarray,
    l_starts: np.ndarray,
    r_starts: np.ndarray,
    mode: str,
    device: bool = False,
    timings: Optional[list] = None,
) -> Optional[ClassedJoinPlan]:
    """Build the joint size-classed layout for one co-bucketed join pair.
    `l_vals`/`r_vals` are HOST arrays in the joint key space (key64 hashes for
    ``mode="hash"``, `value_mode_vals`-canonicalized values for
    ``mode="value"``), concatenated in bucket order. Returns None when a
    value-mode segment fails the device-side sortedness check (caller retries
    in hash mode). `timings` (a list) receives per-class build records —
    the bench's `pad_sort_classes` breakdown."""
    l_starts = np.asarray(l_starts, np.int64)
    r_starts = np.asarray(r_starts, np.int64)
    if mode == "hash":
        l_vals = np.minimum(np.asarray(l_vals, np.int64), _PAD - 1)
        r_vals = np.minimum(np.asarray(r_vals, np.int64), _PAD - 1)
    else:
        l_vals = np.asarray(l_vals)
        r_vals = np.asarray(r_vals)
    groups, outlier_ids = joint_partition(l_starts, r_starts)
    segments = []
    for ids in groups:
        t0 = time.monotonic()
        l_side = _build_side(l_vals, l_starts, ids, mode, device)
        r_side = _build_side(r_vals, r_starts, ids, mode, device)
        if l_side is None or r_side is None:
            return None
        segments.append(JoinSegment(ids, l_side, r_side))
        if timings is not None:
            timings.append(
                {
                    "cap_l": l_side.cap,
                    "cap_r": r_side.cap,
                    "buckets": int(len(ids)),
                    "build_s": round(time.monotonic() - t0, 5),
                }
            )
    if timings is not None and len(outlier_ids):
        lens = np.maximum(
            np.diff(l_starts)[outlier_ids], np.diff(r_starts)[outlier_ids]
        )
        timings.append(
            {
                "outliers": int(len(outlier_ids)),
                "max_rows": int(lens.max()),
            }
        )
    return ClassedJoinPlan(
        mode, segments, outlier_ids, l_vals, r_vals, l_starts, r_starts
    )


def _outlier_bucket_pairs(plan: ClassedJoinPlan, b: int):
    """Host merge of ONE oversized bucket → GLOBAL candidate (li, ri) pairs
    (exact matches in value mode; hash candidates verified by the caller's
    exact-equality pass, same as every padded candidate)."""
    from .join import host_merge_pairs

    ls, le = int(plan.l_starts[b]), int(plan.l_starts[b + 1])
    rs, re = int(plan.r_starts[b]), int(plan.r_starts[b + 1])
    lv, rv = plan.l_vals[ls:le], plan.r_vals[rs:re]
    lv, rv = probe_keys_promoted(lv, rv)
    li, ri = host_merge_pairs(lv, rv)
    return li + ls, ri + rs


def probe_classed(plan: ClassedJoinPlan) -> ClassedRanges:
    """Range-probe every segment (each class runs its own probe program via
    `probe_ranges` — the Pallas tiled kernel where its per-class shape budget
    admits it, the XLA vmap'd searchsorted or host numpy probe elsewhere) and
    merge the outlier buckets on host."""
    segs = []
    total = 0
    for seg in plan.segments:
        if seg.l.cap > seg.r.cap:
            a, b, swapped = seg.r, seg.l, True
        else:
            a, b, swapped = seg.l, seg.r, False
        ak, bk = probe_keys_promoted(a.keys, b.keys)
        lo, counts = probe_ranges(ak, bk, a.lengths, b.lengths)
        seg_total = int(_counts_total(counts))
        total += seg_total
        segs.append((lo, counts, swapped, seg_total))
    outs = []
    for b in plan.outlier_ids:
        li, ri = _outlier_bucket_pairs(plan, int(b))
        total += len(li)
        outs.append((int(b), li, ri))
    return ClassedRanges(segs, outs, total)


def classed_pairs(
    plan: ClassedJoinPlan, ranges: ClassedRanges
) -> Tuple[np.ndarray, np.ndarray]:
    """Expand a classed probe into HOST candidate (li, ri) pairs in BUCKET-
    MAJOR order (ascending bucket id; within a bucket, probe-side sorted-slot
    order) — one deterministic order regardless of how buckets landed in
    classes, so repeated queries and the materialized fallback agree."""
    per_bucket = np.zeros(plan.num_buckets, np.int64)
    seg_out = []
    for seg, (lo, counts, swapped, seg_total) in zip(plan.segments, ranges.segments):
        counts_np = np.asarray(counts)
        if seg_total == 0:
            continue
        a, b = (seg.r, seg.l) if swapped else (seg.l, seg.r)
        ai, bi = _expand_np(
            np.asarray(lo), counts_np, a.starts, b.starts, a.order, b.order
        )
        li, ri = (bi, ai) if swapped else (ai, bi)
        tots = counts_np.sum(axis=1, dtype=np.int64)[: len(seg.ids)]
        per_bucket[seg.ids] = tots
        seg_out.append((seg.ids, li, ri, tots))
    for b, li_o, ri_o in ranges.outliers:
        per_bucket[b] = len(li_o)
    out_starts = np.zeros(plan.num_buckets + 1, np.int64)
    np.cumsum(per_bucket, out=out_starts[1:])
    total = int(out_starts[-1])
    li_all = np.empty(total, np.int64)
    ri_all = np.empty(total, np.int64)
    for ids, li, ri, tots in seg_out:
        cum = np.cumsum(tots) - tots
        pos = np.repeat(out_starts[ids] - cum, tots) + np.arange(li.shape[0])
        li_all[pos] = li
        ri_all[pos] = ri
    for b, li_o, ri_o in ranges.outliers:
        s = int(out_starts[b])
        li_all[s : s + len(li_o)] = li_o
        ri_all[s : s + len(ri_o)] = ri_o
    return li_all, ri_all


def classed_pairs_dev(plan: ClassedJoinPlan, ranges: ClassedRanges):
    """DEVICE expansion of a classed probe: per-segment `_expand_pairs_dev`
    programs (pow2 out-caps, so repeat shapes reuse compiles) concatenated
    with the host outlier pairs — (li, ri, valid) device lanes for the fused
    join→aggregate / on-device count paths. Pair order is NOT the host
    bucket-major order (device consumers are order-insensitive reductions)."""
    from ..engine.device_cache import device_array

    has_order = plan.mode == "hash"
    dummy = jnp.zeros((1, 1), dtype=jnp.int64)
    parts = []
    for seg, (lo, counts, swapped, seg_total) in zip(plan.segments, ranges.segments):
        if seg_total == 0:
            continue
        a, b = (seg.r, seg.l) if swapped else (seg.l, seg.r)
        ai, bi, valid = _expand_pairs_dev(
            _cap_pow2(seg_total),
            has_order,
            jnp.asarray(lo),
            jnp.asarray(counts),
            device_array(a.starts),
            device_array(b.starts),
            device_array(a.order) if has_order else dummy,
            device_array(b.order) if has_order else dummy,
        )
        li, ri = (bi, ai) if swapped else (ai, bi)
        parts.append((li, ri, valid))
    for _b, li_o, ri_o in ranges.outliers:
        if len(li_o) == 0:
            continue
        parts.append(
            (
                jnp.asarray(li_o),
                jnp.asarray(ri_o),
                jnp.ones(len(li_o), bool),
            )
        )
    if not parts:
        return None
    if len(parts) == 1:
        return parts[0]
    li = jnp.concatenate([p[0] for p in parts])
    ri = jnp.concatenate([p[1] for p in parts])
    valid = jnp.concatenate([p[2] for p in parts])
    return li, ri, valid


def probe_padded(left: PaddedBuckets, right: PaddedBuckets, ranges=None):
    """Batched range probe of two padded sides → host (left_row, right_row) pairs.

    Both sides must be in the SAME mode: value-direct keys and key64 hashes live in
    different spaces, so a mixed probe would silently find nothing. The caller makes
    the mode decision jointly (`_padded_rep` + the mode reconciliation in
    `SortMergeJoinExec._execute_bucketed`). `ranges` optionally supplies
    already-computed (lo, counts) in the canonical probe orientation (the
    engine's probe-range memo), skipping the probe entirely."""
    if left.mode != right.mode:
        raise ValueError(f"mixed padded modes: {left.mode} vs {right.mode}")
    a, b, swapped = probe_orientation(left, right)
    if ranges is not None:
        lo, counts = ranges
    else:
        ak, bk = probe_keys_promoted(a.keys, b.keys)
        lo, counts = probe_ranges(ak, bk, a.lengths, b.lengths)
    counts_np = np.asarray(counts)
    if counts_np.sum() == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    ai, bi = _expand_np(
        np.asarray(lo), counts_np, a.starts, b.starts, a.order, b.order
    )
    return (bi, ai) if swapped else (ai, bi)


