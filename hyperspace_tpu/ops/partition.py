"""On-device bucketize: hash-partition + in-bucket sort in ONE XLA sort.

This is the TPU replacement for the reference's index-build hot path —
`df.repartition(numBuckets, indexedCols)` (a full Spark shuffle) followed by
per-bucket sort in the bucketed writer (`CreateActionBase.scala:119-140`,
`DataFrameWriterExtensions.scala:49-81`). Here both steps collapse into a single
`lax.sort` over the composite key (bucket_id, indexed_cols...): after the sort, rows
are grouped by bucket AND sorted by the indexed columns within each bucket, so bucket
extraction is a contiguous slice. Static shapes throughout; one device sort is the
whole job.

Backend-adaptive: on the CPU backend the permutation comes from a host
`np.lexsort` instead (XLA's CPU variadic sort is single-threaded and ~3x slower
at build sizes); the device `lax.sort` path is the TPU design. Both produce the
identical (bucket, keys...) ordering contract —
`tests/test_engine.py::test_device_sort_perm_matches_lexsort` pins them to each
other.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.table import Column, Table
from .hashing import bucket_id


@partial(jax.jit, static_argnums=(2,))
def _sort_perm(bucket, keys: Tuple, n: int):
    """Permutation ordering rows by (bucket, key1, key2, ...)."""
    iota = jnp.arange(n, dtype=jnp.int32)
    operands = (bucket, *keys, iota)
    res = jax.lax.sort(operands, num_keys=1 + len(keys))
    return res[-1], res[0]  # (permutation, sorted bucket ids)


def _sortable(arr: jnp.ndarray) -> jnp.ndarray:
    if arr.dtype == jnp.bool_:
        return arr.astype(jnp.int32)
    return arr


def _composite_sort_host(
    b_host: np.ndarray, cols, num_buckets: int
) -> "np.ndarray | None":
    """Single-lane composite sort for the common single-key case: with one
    non-null integer-or-dictionary key of bounded range, `bucket * range +
    (key - min)` fits int64 and one unstable introsort orders by
    (bucket, key) — measured 0.84 s vs lexsort's 2.58 s at 8M. Instability
    within equal (bucket, key) is arbitrary-safe by the same argument as the
    Pallas bitonic sort (`ops/pallas_sort.py` docstring): joins emit whole
    equal-key ranges and verify actual values. Strings ride their sorted-
    dictionary codes (code order IS value order). None = use the lexsort."""
    if len(cols) != 1:
        return None
    c = cols[0]
    if getattr(c, "validity", None) is not None:
        return None
    data = c.data  # codes for strings
    if data.dtype == np.bool_:
        data = data.astype(np.int64)
    if not np.issubdtype(data.dtype, np.integer):
        return None
    if data.shape[0] == 0:
        return np.empty(0, np.int64)
    lo, hi = int(data.min()), int(data.max())
    span = hi - lo + 1
    if span > (1 << 62) // max(num_buckets, 1):
        return None
    comp = b_host.astype(np.int64) * span + (data.astype(np.int64) - lo)
    return np.argsort(comp)


def bucketize_table(
    table: Table, bucket_columns: Sequence[str], num_buckets: int
) -> Tuple[Table, np.ndarray]:
    """Hash-partition `table` into `num_buckets` by `bucket_columns`, sorted by those
    columns within each bucket. Returns (reordered table, bucket start offsets of
    length num_buckets+1): bucket b = rows[starts[b]:starts[b+1]]."""
    cols = [table.column(c) for c in bucket_columns]
    from ..engine.device_cache import device_array

    arrs = [device_array(c.data) for c in cols]
    b = bucket_id(cols, arrs, num_buckets)
    from .backend import use_device_path

    if not use_device_path():
        # Backend-adaptive: XLA's CPU variadic sort is single-threaded and ~3x
        # slower than numpy's lexsort at index-build sizes; the one-device-sort
        # design is for the TPU, where lax.sort is the right primitive. The
        # output contract (permutation by (bucket, keys...)) is identical.
        b_host = np.asarray(b)
        perm_host = _composite_sort_host(b_host, cols, num_buckets)
        if perm_host is None:
            lanes = tuple(
                c.data.astype(np.int32) if c.data.dtype == np.bool_ else c.data
                for c in reversed(cols)
            ) + (b_host,)
            perm_host = np.lexsort(lanes)
        sorted_b_host = b_host[perm_host]
    else:
        perm, sorted_b = _sort_perm(
            b, tuple(_sortable(a) for a in arrs), table.num_rows
        )
        perm_host = np.asarray(perm)
        sorted_b_host = np.asarray(sorted_b)
    starts = np.searchsorted(sorted_b_host, np.arange(num_buckets + 1))
    return table.take(perm_host), starts
