"""On-device bucketize: hash-partition + in-bucket sort in ONE XLA sort.

This is the TPU replacement for the reference's index-build hot path —
`df.repartition(numBuckets, indexedCols)` (a full Spark shuffle) followed by
per-bucket sort in the bucketed writer (`CreateActionBase.scala:119-140`,
`DataFrameWriterExtensions.scala:49-81`). Here both steps collapse into a single
`lax.sort` over the composite key (bucket_id, indexed_cols...): after the sort, rows
are grouped by bucket AND sorted by the indexed columns within each bucket, so bucket
extraction is a contiguous slice. Static shapes throughout; one device sort is the
whole job.

Backend-adaptive: on the CPU backend the permutation comes from a host
`np.lexsort` instead (XLA's CPU variadic sort is single-threaded and ~3x slower
at build sizes); the device `lax.sort` path is the TPU design. Both produce the
identical (bucket, keys...) ordering contract —
`tests/test_engine.py::test_device_sort_perm_matches_lexsort` pins them to each
other.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.table import Column, Table
from ..telemetry.compile_log import observed_jit as _observed_jit
from .hashing import bucket_id


@_observed_jit(label="partition.sort_perm", static_argnums=(2,))
def _sort_perm(bucket, keys: Tuple, n: int):
    """Permutation ordering rows by (bucket, key1, key2, ...)."""
    iota = jnp.arange(n, dtype=jnp.int32)
    operands = (bucket, *keys, iota)
    res = jax.lax.sort(operands, num_keys=1 + len(keys))
    return res[-1], res[0]  # (permutation, sorted bucket ids)


def _sortable(arr: jnp.ndarray) -> jnp.ndarray:
    if arr.dtype == jnp.bool_:
        return arr.astype(jnp.int32)
    return arr


def _composite_sort_host(
    b_host: np.ndarray, cols, num_buckets: int
) -> "np.ndarray | None":
    """Single-lane composite sort for the common single-key case: with one
    non-null integer-or-dictionary key of bounded range, `(bucket * range +
    (key - min)) * n + row` fits int64 and one unstable introsort orders by
    (bucket, key, original row) — measured 0.84 s vs lexsort's 2.58 s at 8M.
    The row-id low bits make every composite UNIQUE, so the unstable introsort
    reproduces the engine's CANONICAL build order — stable (bucket, key) with
    ties in original row order — exactly: the same order `np.lexsort`, the
    stable `lax.sort` device paths, the Pallas composite sort, and the mesh
    exchange's receive-side sort all produce. One canonical order is what
    makes the mesh build's index files byte-identical to single-device ones
    (`HYPERSPACE_DISTRIBUTED=0` oracle). Strings ride their sorted-dictionary
    codes (code order IS value order). None = use the lexsort."""
    if len(cols) != 1:
        return None
    c = cols[0]
    if getattr(c, "validity", None) is not None:
        return None
    data = c.data  # codes for strings
    if data.dtype == np.bool_:
        data = data.astype(np.int64)
    if not np.issubdtype(data.dtype, np.integer):
        return None
    n = data.shape[0]
    if n == 0:
        return np.empty(0, np.int64)
    lo, hi = int(data.min()), int(data.max())
    span = hi - lo + 1
    if span > (1 << 62) // max(num_buckets * n, 1):
        return None
    comp = (
        b_host.astype(np.int64) * span + (data.astype(np.int64) - lo)
    ) * np.int64(n) + np.arange(n, dtype=np.int64)
    return np.argsort(comp)


def host_sort_perm(b_host: np.ndarray, cols, num_buckets: int) -> np.ndarray:
    """The CPU-backend permutation by (bucket, keys...): single-lane composite
    sort when eligible, else lexsort. ONE implementation shared by the serial
    build path and the pipelined build's sort stage — the bit-for-bit
    reproducibility contract between them rides on this being the same code
    over the same arrays (np.argsort/np.lexsort are unstable, so even an
    equivalent reformulation could permute equal-key rows differently)."""
    perm_host = _composite_sort_host(b_host, cols, num_buckets)
    if perm_host is None:
        lanes = tuple(
            c.data.astype(np.int32) if c.data.dtype == np.bool_ else c.data
            for c in reversed(cols)
        ) + (b_host,)
        perm_host = np.lexsort(lanes)
    return perm_host


def bucket_starts(sorted_b_host: np.ndarray, num_buckets: int) -> np.ndarray:
    """Bucket start offsets (length num_buckets+1) of a bucket-sorted id array."""
    return np.searchsorted(sorted_b_host, np.arange(num_buckets + 1))


def bucketize_table(
    table: Table, bucket_columns: Sequence[str], num_buckets: int
) -> Tuple[Table, np.ndarray]:
    """Hash-partition `table` into `num_buckets` by `bucket_columns`, sorted by those
    columns within each bucket. Returns (reordered table, bucket start offsets of
    length num_buckets+1): bucket b = rows[starts[b]:starts[b+1]]."""
    cols = [table.column(c) for c in bucket_columns]
    from ..engine.encoded_device import stage_codes

    # String key lanes stage as NARROW dictionary codes when the cardinality
    # allows (engine/encoded_device.py): the hash gathers dh_table[codes] and
    # the sort compares code VALUES, so both are bit-identical from narrow
    # lanes — only the upload bytes shrink.
    arrs = [stage_codes(c, "partition_build") for c in cols]
    b = bucket_id(cols, arrs, num_buckets)
    from .backend import use_device_path

    if not use_device_path():
        # Backend-adaptive: XLA's CPU variadic sort is single-threaded and ~3x
        # slower than numpy's lexsort at index-build sizes; the one-device-sort
        # design is for the TPU, where lax.sort is the right primitive. The
        # output contract (permutation by (bucket, keys...)) is identical.
        b_host = np.asarray(b)
        perm_host = host_sort_perm(b_host, cols, num_buckets)
        sorted_b_host = b_host[perm_host]
    else:
        res = None
        if (
            len(cols) == 1
            and getattr(cols[0], "is_string", False)
            and cols[0].dictionary is not None
        ):
            # Sub-byte code build: (bucket | biased code | row) packs into ONE
            # int32 composite — same canonical order as the variadic sort,
            # a quarter of the sorted state. None when out of budget.
            res = pallas_packed_build_sort(
                b, arrs[0], len(cols[0].dictionary), table.num_rows, num_buckets
            )
        if res is not None:
            perm_host, sorted_b_host = res
        else:
            perm, sorted_b = _sort_perm(
                b, tuple(_sortable(a) for a in arrs), table.num_rows
            )
            perm_host = np.asarray(perm)
            sorted_b_host = np.asarray(sorted_b)
    starts = bucket_starts(sorted_b_host, num_buckets)
    return table.take(perm_host), starts


# -- fused bucketize+sort for the pipelined build (device path) --------------
#
# The serial device path runs TWO dispatches (bucket-id hash, then the
# variadic sort); on a relay-backed TPU each dispatch is a round-trip. The
# pipelined build stages pow2-padded chunk buffers onto the device as files
# decode, then runs hash + concat + sort as ONE jitted program over the whole
# chunk group, with every staging buffer donated (the build owns them; XLA
# reuses their HBM for the sort operands). Numeric keys only — string keys
# need the union-dictionary re-encoding that happens on host anyway.

from functools import lru_cache


@lru_cache(maxsize=64)
def _fused_sort_program(n_keys: int, n_chunks: int, num_buckets: int):
    from .hashing import _SEED1, _mix_combine, fmix32, hash_device_values

    def impl(valid_lens, *flat):
        # flat layout: key column 0's chunks, then column 1's chunks, ...
        # Pad rows ride INSIDE the sort with a sentinel bucket id (they sort
        # last; lax.sort is stable, so real rows keep their relative —
        # i.e. unpadded-concat — order), which keeps the program's compile
        # shapes a function of the pow2-quantized buffer shapes ONLY: the
        # actual row counts are traced operands, not static values.
        cols = []
        for k in range(n_keys):
            cols.append(jnp.concatenate(flat[k * n_chunks : (k + 1) * n_chunks]))
        starts = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(valid_lens).astype(jnp.int32)]
        )
        real_parts, gidx_parts = [], []
        for i in range(n_chunks):
            li = jnp.arange(int(flat[i].shape[0]), dtype=jnp.int32)
            real = li < valid_lens[i]
            real_parts.append(real)
            # Each real row carries its UNPADDED global index; pad rows get a
            # sentinel (they land past the first n outputs anyway).
            gidx_parts.append(jnp.where(real, starts[i] + li, jnp.int32(2**31 - 1)))
        real = jnp.concatenate(real_parts)
        gidx = jnp.concatenate(gidx_parts)
        h = None
        for arr in cols:
            hc = hash_device_values(arr, _SEED1)
            h = hc if h is None else fmix32(_mix_combine(h, hc))
        b = jnp.where(
            real, (h % jnp.uint32(num_buckets)).astype(jnp.int32), jnp.int32(num_buckets)
        )
        operands = (b, *(_sortable(a) for a in cols), gidx)
        res = jax.lax.sort(operands, num_keys=1 + n_keys)
        return res[-1], res[0]  # (permutation, sorted bucket ids) incl. pad tail

    return _observed_jit(
        impl,
        label="partition.fused_bucketize_sort",
        donate_argnums=tuple(range(1, 1 + n_keys * n_chunks)),
    )


def fused_bucketize_sort_perm(
    chunk_arrays: List[List[jnp.ndarray]], valid_lens: Sequence[int], num_buckets: int
) -> Tuple[np.ndarray, np.ndarray]:
    """One-dispatch bucketize+sort over staged device chunks.

    `chunk_arrays[k][i]` = key column k's chunk i (device array, possibly
    pow2-padded beyond `valid_lens[i]`). All chunk buffers are DONATED —
    callers must not reuse them (best-effort: XLA reuses their memory where
    aliasing allows). Returns host (perm, sorted_bucket_ids) of length
    sum(valid_lens); identical ordering to the serial device path (`lax.sort`
    is stable, and the hash math is the same ops `bucket_id` runs)."""
    n_keys = len(chunk_arrays)
    n_chunks = len(chunk_arrays[0])
    n = int(sum(int(v) for v in valid_lens))
    fn = _fused_sort_program(n_keys, n_chunks, int(num_buckets))
    flat = [c for col in chunk_arrays for c in col]
    perm, sorted_b = fn(jnp.asarray(list(valid_lens), dtype=jnp.int32), *flat)
    return np.asarray(perm)[:n], np.asarray(sorted_b)[:n]


@_observed_jit(
    label="partition.packed_build_comp", static_argnums=(2, 3, 4, 5)
)
def _packed_build_comp(b, codes, bits: int, log2np: int, n_pad: int, num_buckets: int):
    """(bucket | biased code | row) int32 composites, padded to [1, n_pad]
    with the supremum composite (bucket field = num_buckets exceeds every real
    bucket, so pads sort last regardless of the remaining bits)."""
    n = codes.shape[0]
    biased = codes.astype(jnp.int32) + 1  # null (-1) -> reserved lane 0
    comp = (
        ((b.astype(jnp.int32) << bits) | biased) << log2np
    ) | jnp.arange(n, dtype=jnp.int32)
    pad_val = jnp.int32((num_buckets << bits) << log2np)
    return jnp.full((1, n_pad), pad_val, dtype=jnp.int32).at[0, :n].set(comp)


def pallas_packed_build_sort(
    b_dev, codes_dev, cardinality: int, n: int, num_buckets: int
) -> "Tuple[np.ndarray, np.ndarray] | None":
    """Sub-byte-key build fast path: for a single dictionary-encoded key whose
    cardinality fits a packed lane class (`engine/packed_codes.py`), the
    (bucket, biased code, row) triple bit-packs into ONE int32 composite —
    sorted by the single-lane Pallas bitonic (`pallas_sort.sort_comp_padded`),
    a QUARTER of the in-VMEM state of the int64 composite path and 1/3 of the
    (hi, lo, idx) network's exchanges. Unique row bits => the unstable bitonic
    reproduces the engine's canonical stable (bucket, code) order exactly
    (same argument as `_composite_sort_host`), so index files stay
    byte-identical whichever sort ran. Biased codes (code + 1) keep the null
    lane (-1 -> 0) ordered first, matching the raw-code variadic sort.
    Returns None when out of budget (flag off, cardinality past the 4-bit
    class, int32 headroom, or sort-gate shapes)."""
    from ..engine.packed_codes import bits_for_cardinality, packed_codes_enabled
    from .pallas_sort import (
        pallas_sort_wanted,
        record_sort_failure,
        sort_comp_padded,
    )

    if not packed_codes_enabled():
        return None
    bits = bits_for_cardinality(int(cardinality))
    if bits is None:
        return None
    if n == 0:
        return np.empty(0, np.int32), np.empty(0, np.int32)
    n_pad = 1 << max(int(n) - 1, 1).bit_length()
    log2np = n_pad.bit_length() - 1
    # The pad composite is the largest value the encoding produces: it must
    # fit signed int32.
    if (num_buckets << bits) << log2np >= 1 << 31:
        return None
    if not pallas_sort_wanted(1, n_pad):
        return None
    try:
        comp = _packed_build_comp(
            b_dev, codes_dev, bits, log2np, n_pad, num_buckets
        )
        sorted_comp = sort_comp_padded(comp, jax.default_backend() != "tpu")
        head = sorted_comp[0, :n]
        perm = np.asarray(head & (n_pad - 1)).astype(np.int32)
        sorted_b = np.asarray(head >> (bits + log2np)).astype(np.int32)
        return perm, sorted_b
    except Exception as e:  # Mosaic lowering/runtime problems
        record_sort_failure(e)
        return None


def pallas_composite_build_sort(
    b_dev, key_dev, n: int, num_buckets: int
) -> "Tuple[np.ndarray, np.ndarray] | None":
    """Small-build fast path: pack (bucket, key, row) into ONE int64 composite
    and sort it with the Pallas in-VMEM bitonic kernel (`ops/pallas_sort`) —
    the whole O(log² n) network in a single HBM round-trip instead of the
    multi-stage XLA variadic sort. The row-index tiebreaker in the low bits
    makes the unstable bitonic network reproduce the STABLE (bucket, key)
    order exactly, so the output contract matches `_sort_perm` bit-for-bit.
    Returns None when out of budget (shape, dtype, or int64 headroom)."""
    from .pallas_sort import pallas_sort_wanted, record_sort_failure, sort_padded_with_order

    key_dev = jnp.asarray(key_dev)
    if not jnp.issubdtype(key_dev.dtype, jnp.integer):
        return None
    n_pad = 1 << max(int(n) - 1, 1).bit_length()
    if not pallas_sort_wanted(1, n_pad):
        return None
    if n == 0:
        return np.empty(0, np.int32), np.empty(0, np.int32)
    k64 = key_dev.astype(jnp.int64)
    lo = int(jax.device_get(k64.min()))
    hi = int(jax.device_get(k64.max()))
    span = hi - lo + 1
    # Composite headroom: (num_buckets+1) * span * n_pad must fit signed 64.
    if span > (1 << 62) // max((num_buckets + 1) * n_pad, 1):
        return None
    try:
        iota = jnp.arange(n, dtype=jnp.int64)
        comp = (
            b_dev.astype(jnp.int64) * jnp.int64(span) + (k64 - jnp.int64(lo))
        ) * jnp.int64(n_pad) + iota
        pad_val = jnp.int64(num_buckets) * jnp.int64(span) * jnp.int64(n_pad)
        padded = jnp.full((1, n_pad), pad_val, dtype=jnp.int64).at[0, :n].set(comp)
        sorted_keys, order = sort_padded_with_order(padded)
        perm = np.asarray(order[0, :n]).astype(np.int32)
        sorted_b = np.asarray(
            (sorted_keys[0, :n] // jnp.int64(n_pad)) // jnp.int64(span)
        ).astype(np.int32)
        return perm, sorted_b
    except Exception as e:  # Mosaic lowering/runtime problems
        record_sort_failure(e)
        return None
