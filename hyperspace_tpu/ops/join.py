"""Device-side sort-merge join primitive.

TPU-first: both sides' join keys are 64-bit value hashes (`ops.hashing.key64`), so the
merge works on a single comparable integer key regardless of column count or string
dictionaries. The pipeline is sort → searchsorted range probe → two-pass expansion
(count, then scatter), which keeps every step static-shaped for XLA except one scalar
sync for the output size — the classic way around ragged output shapes on TPU
(SURVEY §7 "hard parts": two-pass partitioning).

Equal key tuples always produce equal key64s; unequal tuples that collide (~2^-64) are
eliminated by the caller's exact-equality verification on the gathered rows, so results
are exact.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry.compile_log import observed_jit as _observed_jit


def stable_argsort_host(x) -> np.ndarray:
    """The host branch of the backend-adaptive sort trade, as a NUMPY
    permutation (callers that continue host-side skip the device round-trip)."""
    return np.argsort(np.asarray(x), kind="stable")


def _range_probe_body(l_key64, r_key64, l_order, r_order, xp=jnp):
    """Range probe of sorted views — the ONE home of the lo/hi/count
    semantics, used traced in the fused device program (xp=jnp) and on HOST
    arrays by the CPU branch of `merge_join_pairs` (xp=np; eager jnp ops
    there are per-operator XLA-CPU dispatches)."""
    ls = l_key64[l_order]
    rs = r_key64[r_order]
    lo = xp.searchsorted(rs, ls, side="left")
    hi = xp.searchsorted(rs, ls, side="right")
    return lo, hi - lo


@_observed_jit(label="join.sorted_ranges")
def _merge_phase_a(l_key64, r_key64):
    """Sort both sides + range-probe in ONE compiled program (each eager op is
    a dispatch, and on the axon relay every dispatch is a round-trip)."""
    l_order = jnp.argsort(l_key64)
    r_order = jnp.argsort(r_key64)
    lo, counts = _range_probe_body(l_key64, r_key64, l_order, r_order)
    return l_order, r_order, lo, counts, counts.sum()


def merge_join_pairs(l_key64, r_key64) -> Tuple[np.ndarray, np.ndarray]:
    """All (left_index, right_index) pairs with equal keys, as host numpy arrays.

    Works on unsorted inputs: sorts both sides internally and maps positions back to
    the original row order."""
    from .backend import use_device_path

    l_key64 = jnp.asarray(l_key64)
    r_key64 = jnp.asarray(r_key64)
    if l_key64.shape[0] == 0 or r_key64.shape[0] == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)

    if use_device_path():
        l_order, r_order, lo, counts, total_dev = _merge_phase_a(l_key64, r_key64)
        total = int(total_dev)  # the one scalar sync (dynamic output size)
        if total == 0:
            return np.empty(0, np.int64), np.empty(0, np.int64)
        starts = jnp.cumsum(counts) - counts  # exclusive prefix sum
        l_pos = jnp.repeat(
            jnp.arange(l_key64.shape[0]), counts, total_repeat_length=total
        )
        offset = jnp.arange(total) - starts[l_pos]
        r_pos = lo[l_pos] + offset
        return np.asarray(l_order[l_pos]), np.asarray(r_order[r_pos])
    # CPU backend: the WHOLE merge stays on host — eager jnp sorts/probes/
    # expansions here are per-op XLA-CPU dispatches (the sort ~3x slower than
    # numpy, the expansion a chain of eager gathers). Same probe body as the
    # device program (xp=np), same host sort as every other host path.
    return host_merge_pairs(np.asarray(l_key64), np.asarray(r_key64))


def host_merge_pairs(lk: np.ndarray, rk: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """All equal-key (left_index, right_index) pairs of two HOST arrays,
    always on numpy regardless of backend — the CPU branch of
    `merge_join_pairs`, and the per-bucket merge of the size-classed join's
    OUTLIER path (one oversized bucket must not drag the device-wide padded
    layout along, nor pay a per-bucket device dispatch). Pair order: left
    rows in sorted-key order, each with its matches in the right side's
    sorted order — the same within-bucket order the padded expansion emits."""
    lk, rk = np.asarray(lk), np.asarray(rk)
    if lk.shape[0] == 0 or rk.shape[0] == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    l_order = stable_argsort_host(lk)
    r_order = stable_argsort_host(rk)
    lo, counts = _range_probe_body(lk, rk, l_order, r_order, xp=np)
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    starts = np.cumsum(counts) - counts
    l_pos = np.repeat(np.arange(lk.shape[0]), counts)
    offset = np.arange(total) - starts[l_pos]
    r_pos = lo[l_pos] + offset
    return l_order[l_pos], r_order[r_pos]


def nonzero_indices(mask) -> np.ndarray:
    """Compact a device boolean mask into host row indices (one scalar sync).

    CPU backend: plain numpy. `jnp.nonzero(mask, size=n)` compiles per
    distinct (shape, n) — and n is the SURVIVOR COUNT, so every new filter
    literal (or index generation's new file shape) minted ~16 eager-op
    compiles ≈ 300 ms on the interactive point-lookup path (the PR-2
    varying-survivor-count lesson, applied to the general filter path)."""
    from .backend import use_device_path

    if not use_device_path():
        return np.nonzero(np.asarray(mask))[0].astype(np.int64, copy=False)
    mask = jnp.asarray(mask)
    n = int(mask.sum())
    if n == 0:
        return np.empty(0, np.int64)
    return np.asarray(jnp.nonzero(mask, size=n)[0])
