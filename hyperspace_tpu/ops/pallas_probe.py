"""Pallas TPU kernel for the co-bucketed range probe.

The XLA path (`bucket_join._probe`) vmaps `jnp.searchsorted` over the bucket
axis. This kernel recasts the probe as the VPU-friendly identity

    searchsorted(sorted_row, key, 'left')  == count(row <  key)
    searchsorted(sorted_row, key, 'right') == count(row <= key)

computed as tiled broadcast-compare + reductions: grid (bucket-group,
left-tile, right-tile), each step compares [TB, TL] left keys against [TB, TR]
right keys as a 3D broadcast and accumulates the two counts. No gathers, no
dynamic shapes — exactly the shape of work Mosaic schedules well; the block
shapes honor Mosaic's (x8, x128)-or-equal-to-dim tiling rule (validated on a
real TPU v5 lite this round — see TPU_EVIDENCE.md). The per-bucket merge this
implements is what the reference gets from SortMergeJoinExec over co-bucketed
index scans (`JoinIndexRule.scala:137-162`).

Key dtype: 64-bit keys (hash mode is int64; value mode is promoted) do not
exist on the TPU VPU, so keys are pre-split OUTSIDE the kernel into a
lexicographic (hi, lo) int32 pair whose signed compare reproduces the 64-bit
order (floats go through the standard order-preserving bit transform first,
with -0.0 canonicalized to +0.0 so searchsorted equality classes survive).

Cost note: the tiled compare is O(cap_l * cap_r) per bucket vs the XLA path's
O(cap_l * log cap_r); it wins on dispatch/fusion for small-to-medium buckets
and loses asymptotically on very large ones, so `probe_ranges` dispatches by
capacity product (override with HYPERSPACE_PALLAS_PROBE=1/0). Equivalence with
the XLA path is pinned by tests/test_pallas_probe.py (interpret mode off-TPU).
"""

from __future__ import annotations

import os
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_ENV_KEY = "HYPERSPACE_PALLAS_PROBE"
# Auto-dispatch budget on TOTAL compare ops (B * cap_l * cap_r): the tiled
# compare is quadratic per bucket, so it wins below this (measured on v5e:
# 64x4096x512 = 2^27 ops -> 91 ms Pallas vs 176 ms XLA probe) and loses to
# XLA's log-probe above it; 2^28 gives the measured win point 2x headroom
# without admitting shapes whose linear scaling clearly loses.
_AUTO_MAX_OPS = 1 << 28
# Failure latch, scoped PER KEY KIND ("int" | "float"): the int64 path is
# validated on real Mosaic (round 4, 1.9-2.3x over the XLA probe), while the
# float path's 32-bit split — designed around the terminal's rejection of
# `bitcast f64->s64` — has only interpret-mode validation so far. A float
# lowering failure must disable FLOAT dispatch only, never the proven int
# path (the round-4 guard existed precisely for this blast radius).
_pallas_broken: dict = {}  # kind -> first failure message; permanent fallback
_fallback_counts: dict = {}  # kind -> how many probes fell back to XLA/host

from ..telemetry import metrics as _metrics
from ..telemetry.compile_log import observed_jit as _observed_jit

# Bound once: after a latch, EVERY subsequent dispatch increments — no name
# formatting or registry lookup on that path (same convention as the engine's
# cache counters).
_FALLBACK_METRICS = {
    k: _metrics.counter(f"pallas.probe.{k}.fallbacks")
    for k in ("int", "float", "packed")
}


def pallas_fallback_stats() -> dict:
    """Session counters of probe-kernel fallbacks, per key kind: how many
    probes were diverted after a failure latched, and the first error. Empty
    when the kernel never failed — rides bench_detail.join_stages /
    bench_detail.pallas_fallbacks so silent host fallbacks are visible."""
    if not _pallas_broken and not _fallback_counts:
        return {}
    return {
        "failures": dict(_fallback_counts),
        "errors": dict(_pallas_broken),
    }


def _key_kind(dtype) -> str:
    return "float" if dtype is not None and jnp.issubdtype(dtype, jnp.floating) else "int"


def _pallas_mode() -> str:
    return os.environ.get(_ENV_KEY, "auto")


def _sortable_i64(x: jnp.ndarray) -> jnp.ndarray:
    """Order-preserving map of any 64-bit key space into signed int64."""
    if jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(jnp.float64) + 0.0  # canonicalize -0.0
        bits = jax.lax.bitcast_convert_type(x, jnp.int64)
        # Negative floats: flip magnitude bits (reverses their order, keeps
        # sign); positives: unchanged. Signed compare == float total order.
        return bits ^ ((bits >> 63) & jnp.int64(0x7FFFFFFFFFFFFFFF))
    return x.astype(jnp.int64)


def _split_hi_lo(k: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(hi, lo) int32 pair whose lexicographic signed compare == int64 compare."""
    hi = (k >> 32).astype(jnp.int32)
    lo = ((k & jnp.int64(0xFFFFFFFF)) - jnp.int64(0x80000000)).astype(jnp.int32)
    return hi, lo


def _split_hi_lo_float(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Float keys → the kernel's (hi, lo) int32 pair WITHOUT any 64-bit bitcast.

    The axon terminal's X64-elimination rewrite rejects `bitcast f64->s64`
    (round-4 HTTP-500, `TPU_EVIDENCE.md`), so the order-preserving transform
    `bits ^ ((bits >> 63) & 0x7FFF…)` is computed on the two 32-bit words of
    `bitcast f64->s32[..,2]` instead (word 0 = low bits, word 1 = high bits):
    the sign mask comes from the high word's arithmetic shift, the magnitude
    flip applies to the low word in full and to the high word below the sign
    bit, and the lo word gets the same signed-compare bias `_split_hi_lo`
    applies. Equivalence with the 64-bit transform is pinned by
    tests/test_pallas_probe.py."""
    x = x.astype(jnp.float64) + 0.0  # canonicalize -0.0
    words = jax.lax.bitcast_convert_type(x, jnp.int32)
    lo, hi = words[..., 0], words[..., 1]
    mask = hi >> 31  # all-ones for negative floats, zero otherwise
    hi = hi ^ (mask & jnp.int32(0x7FFFFFFF))
    lo = lo ^ mask
    lo = lo ^ jnp.int32(-0x80000000)  # unsigned->signed bias, as a flip
    return hi, lo


def _probe_kernel(lh_ref, ll_ref, rh_ref, rl_ref, lo_ref, hi_ref):
    """One (bucket-group, left-tile, right-tile) step: accumulate lt/le counts.

    Blocks carry TB buckets at once — Mosaic requires the last two block dims
    to be (x8, x128)-divisible or equal to the array dims, so per-bucket
    (1, TL) blocks are illegal; (TB, TL) blocks with the bucket axis widened
    to TB=8 (or the whole axis when B<8) satisfy it. The compare runs as a 3D
    broadcast [TB, TL, 1] x [TB, 1, TR] with a lane-axis reduction.
    VALIDATED ON REAL MOSAIC (TPU v5 lite, round 4): matches the XLA probe."""
    lhv = lh_ref[...][:, :, None]  # [TB, TL, 1]
    llv = ll_ref[...][:, :, None]
    rhv = rh_ref[...][:, None, :]  # [TB, 1, TR]
    rlv = rl_ref[...][:, None, :]
    # r < key  /  r <= key, 64-bit order via the (hi, lo) int32 pair.
    r_lt_k = (rhv < lhv) | ((rhv == lhv) & (rlv < llv))
    r_eq_k = (rhv == lhv) & (rlv == llv)
    lt_counts = jnp.sum(r_lt_k, axis=2, dtype=jnp.int32)  # [TB, TL]
    le_counts = lt_counts + jnp.sum(r_eq_k, axis=2, dtype=jnp.int32)

    @pl.when(pl.program_id(2) == 0)
    def _init():
        lo_ref[...] = jnp.zeros_like(lo_ref)
        hi_ref[...] = jnp.zeros_like(hi_ref)

    lo_ref[...] += lt_counts
    hi_ref[...] += le_counts


def _bucket_tile(B: int) -> int:
    """Bucket-axis block size: 8 when divisible (the min int32 sublane tile),
    else the whole axis (equal-to-dimension is the other legal shape)."""
    return 8 if B % 8 == 0 else B


def _tiles(cap_l: int, cap_r: int):
    """The (TL, TR) tile sizes — ONE home, shared by shape_supported and the
    pallas_call so they cannot drift."""
    return min(cap_l, 256), min(cap_r, 512)


def shape_supported(B: int, cap_l: int, cap_r: int) -> bool:
    """Shapes this kernel can lower: bucket axis tileable, caps tile-multiples
    (guaranteed for _cap_pow2 caps), and a bounded VMEM compare block."""
    if B <= 0:
        return False
    tb = _bucket_tile(B)
    if tb > 8 and B > 8:  # non-multiple-of-8 bucket count > 8: whole-axis
        # block would blow VMEM; let the XLA path take it.
        return False
    tl, tr = _tiles(cap_l, cap_r)
    return cap_l % tl == 0 and cap_r % tr == 0


@_observed_jit(label="pallas.probe", static_argnums=(4,))
def _probe_pallas_call(lh, ll, rh, rl, interpret: bool):
    B, cap_l = lh.shape
    cap_r = rh.shape[1]
    TB = _bucket_tile(B)
    TL, TR = _tiles(cap_l, cap_r)
    # Caps reaching this kernel are _cap_pow2-shaped; guard loudly so a future
    # non-multiple cap cannot silently skip tail tiles (unwritten output blocks).
    assert B % TB == 0 and cap_l % TL == 0 and cap_r % TR == 0, (B, cap_l, cap_r)
    grid = (B // TB, cap_l // TL, cap_r // TR)
    left_spec = pl.BlockSpec((TB, TL), lambda b, i, j: (b, i))
    right_spec = pl.BlockSpec((TB, TR), lambda b, i, j: (b, j))
    out_spec = pl.BlockSpec((TB, TL), lambda b, i, j: (b, i))
    lo, hi = pl.pallas_call(
        _probe_kernel,
        grid=grid,
        in_specs=[left_spec, left_spec, right_spec, right_spec],
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, cap_l), jnp.int32),
            jax.ShapeDtypeStruct((B, cap_l), jnp.int32),
        ],
        interpret=interpret,
    )(lh, ll, rh, rl)
    return lo, hi


def probe_pallas(ls, rs, l_len, r_len) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Drop-in replacement for `bucket_join._probe`: (lo, counts) int32, with
    ranges clamped to each right bucket's valid length and counts zeroed for
    left pad slots."""
    ls, rs = jnp.asarray(ls), jnp.asarray(rs)
    if jnp.issubdtype(ls.dtype, jnp.floating):
        # Pure 32-bit split: no 64-bit bitcast for the relay to reject.
        lh, ll = _split_hi_lo_float(ls)
        rh, rl = _split_hi_lo_float(rs)
    else:
        lh, ll = _split_hi_lo(_sortable_i64(ls))
        rh, rl = _split_hi_lo(_sortable_i64(rs))
    interpret = jax.default_backend() != "tpu"
    lo, hi = _probe_pallas_call(lh, ll, rh, rl, interpret)
    r_len_b = jnp.asarray(r_len)[:, None]
    lo = jnp.minimum(lo, r_len_b).astype(jnp.int32)
    hi = jnp.minimum(hi, r_len_b)
    valid_left = jnp.arange(ls.shape[1])[None, :] < jnp.asarray(l_len)[:, None]
    counts = jnp.where(valid_left, hi - lo, 0).astype(jnp.int32)
    return lo, counts


# --- probe on PACKED sub-byte code words -------------------------------------
#
# Dictionary codes below int8 ship and persist as big-endian uint32 lane words
# (`engine/packed_codes.py`): the big-endian layout makes unsigned word order
# equal lexicographic lane order, so a packed padded-bucket rep sorts/probes
# consistently without ever materializing a flat int matrix in HBM. This
# kernel reads the WORD matrices (bits-per-code HBM traffic, 8-32x less than
# the int32 flat probe), unpacks lanes in VMEM with shift/mask (VPU-cheap),
# and runs the same broadcast-compare reduction as `_probe_kernel` on
# single-lane int32 operands — no (hi, lo) split, codes are tiny.


def _unpack_words_block(w, bits: int):
    """In-kernel unpack: [TB, W] uint32 words -> [TB, W*lpw] int32 biased
    lanes (big-endian lane 0 in the TOP bits, matching pack_rows_traced)."""
    tb, nw = w.shape
    lpw = 32 // bits
    k = jax.lax.broadcasted_iota(jnp.uint32, (tb, nw, lpw), 2)
    shifts = jnp.uint32(32) - jnp.uint32(bits) * (k + jnp.uint32(1))
    lanes = (w[:, :, None] >> shifts) & jnp.uint32((1 << bits) - 1)
    return lanes.reshape(tb, nw * lpw).astype(jnp.int32)


def _probe_packed_kernel(lw_ref, rw_ref, lo_ref, hi_ref, *, bits, tl, tr):
    """Packed twin of `_probe_kernel`. Input blocks carry WHOLE word rows
    (the word axis is far too narrow for (x8, x128) sub-blocks — cap/lpw
    words; equal-to-dimension is the legal shape), and the per-step tile is
    carved INSIDE the kernel with a word-granular dynamic slice. The probe
    tiles are lpw-aligned by construction (`_tiles` sizes are multiples of
    every lanes-per-word), so the slice start always lands on a word."""
    lpw = 32 // bits
    i = pl.program_id(1)
    j = pl.program_id(2)
    lw = lw_ref[:, pl.dslice(i * (tl // lpw), tl // lpw)]
    rw = rw_ref[:, pl.dslice(j * (tr // lpw), tr // lpw)]
    l = _unpack_words_block(lw, bits)[:, :, None]  # [TB, TL, 1]
    r = _unpack_words_block(rw, bits)[:, None, :]  # [TB, 1, TR]
    lt_counts = jnp.sum(r < l, axis=2, dtype=jnp.int32)  # [TB, TL]
    le_counts = lt_counts + jnp.sum(r == l, axis=2, dtype=jnp.int32)

    @pl.when(pl.program_id(2) == 0)
    def _init():
        lo_ref[...] = jnp.zeros_like(lo_ref)
        hi_ref[...] = jnp.zeros_like(hi_ref)

    lo_ref[...] += lt_counts
    hi_ref[...] += le_counts


@_observed_jit(label="pallas.probe_packed", static_argnums=(2, 3))
def _probe_packed_call(lw, rw, bits: int, interpret: bool):
    import functools

    B, wl = lw.shape
    lpw = 32 // bits
    cap_l, cap_r = wl * lpw, rw.shape[1] * lpw
    TB = _bucket_tile(B)
    TL, TR = _tiles(cap_l, cap_r)
    assert B % TB == 0 and cap_l % TL == 0 and cap_r % TR == 0, (B, cap_l, cap_r)
    assert TL % lpw == 0 and TR % lpw == 0, (TL, TR, lpw)
    grid = (B // TB, cap_l // TL, cap_r // TR)
    word_l = pl.BlockSpec((TB, wl), lambda b, i, j: (b, 0))
    word_r = pl.BlockSpec((TB, rw.shape[1]), lambda b, i, j: (b, 0))
    out_spec = pl.BlockSpec((TB, TL), lambda b, i, j: (b, i))
    kern = functools.partial(_probe_packed_kernel, bits=bits, tl=TL, tr=TR)
    lo, hi = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[word_l, word_r],
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, cap_l), jnp.int32),
            jax.ShapeDtypeStruct((B, cap_l), jnp.int32),
        ],
        interpret=interpret,
    )(lw, rw)
    return lo, hi


def probe_packed_pallas(l_words, r_words, bits: int, l_len, r_len):
    """`probe_pallas` over packed BIASED-code word matrices: (lo, counts)
    int32. Both sides must share the same bits class and hold sorted biased
    codes with pad slots at the top lane value (2**bits - 1, which
    `probe_bits_for_cardinality` reserves above every real biased code, so
    pads sort last and the r_len clamp excises them)."""
    lo, hi = _probe_packed_call(
        jnp.asarray(l_words),
        jnp.asarray(r_words),
        bits,
        jax.default_backend() != "tpu",
    )
    r_len_b = jnp.asarray(r_len)[:, None]
    lo = jnp.minimum(lo, r_len_b).astype(jnp.int32)
    hi = jnp.minimum(hi, r_len_b)
    cap_l = lo.shape[1]
    valid_left = jnp.arange(cap_l)[None, :] < jnp.asarray(l_len)[:, None]
    counts = jnp.where(valid_left, hi - lo, 0).astype(jnp.int32)
    return lo, counts


def pallas_packed_probe_wanted(
    cap_l: int, cap_r: int, num_buckets: int, bits: int
) -> bool:
    """Dispatch decision for the packed probe: the ordinary probe gate plus
    whole-word caps. Failures latch under their own "packed" kind — a packed
    lowering failure can never disable the validated int/float kernels."""
    if "packed" in _pallas_broken:
        _fallback_counts["packed"] = _fallback_counts.get("packed", 0) + 1
        _FALLBACK_METRICS["packed"].inc()
        return False
    lpw = 32 // bits
    if cap_l % lpw or cap_r % lpw:
        return False
    mode = _pallas_mode()
    if mode == "0":
        return False
    if not shape_supported(num_buckets, cap_l, cap_r):
        return False
    if mode == "1":
        return True
    return (
        jax.default_backend() == "tpu"
        and num_buckets * cap_l * cap_r <= _AUTO_MAX_OPS
    )


def pallas_probe_wanted(
    cap_l: int, cap_r: int, num_buckets: int, dtype=None
) -> bool:
    """Dispatch decision for `probe_ranges`: forced on/off by env, else on-TPU
    with a capacity-product bound (the quadratic-compare budget). Shapes the
    kernel cannot lower (see `shape_supported`) always take the XLA path.
    Float value-mode keys ride the kernel via the pure-32-bit split
    (`_split_hi_lo_float`) — the round-4 exclusion existed only because the
    old transform's `bitcast f64->s64` was rejected by the terminal's
    X64-elimination rewrite. `dtype` scopes the failure latch: a float-path
    lowering failure can never disable the Mosaic-validated integer path."""
    kind = _key_kind(dtype)
    if kind in _pallas_broken:
        # Count every DIVERTED dispatch, not just the first failure: the
        # bench's fallback counter should reflect how much work actually ran
        # off-kernel in this session.
        _fallback_counts[kind] = _fallback_counts.get(kind, 0) + 1
        _FALLBACK_METRICS[kind].inc()
        return False
    mode = _pallas_mode()
    if mode == "0":
        return False
    if not shape_supported(num_buckets, cap_l, cap_r):
        return False
    if mode == "1":
        return True
    return (
        jax.default_backend() == "tpu"
        and num_buckets * cap_l * cap_r <= _AUTO_MAX_OPS
    )


def record_pallas_failure(exc: BaseException, dtype=None, kind=None) -> None:
    import logging

    kind = kind or _key_kind(dtype)
    _pallas_broken[kind] = f"{type(exc).__name__}: {exc}"
    _fallback_counts[kind] = _fallback_counts.get(kind, 0) + 1
    _FALLBACK_METRICS[kind].inc()
    logging.getLogger("hyperspace_tpu.ops").warning(
        "pallas probe failed for %s keys; falling back to the XLA probe "
        "permanently for that key kind: %s",
        kind,
        _pallas_broken[kind],
    )
