"""Pallas TPU kernel for the co-bucketed range probe.

The XLA path (`bucket_join._probe`) vmaps `jnp.searchsorted` over the bucket
axis. This kernel recasts the probe as the VPU-friendly identity

    searchsorted(sorted_row, key, 'left')  == count(row <  key)
    searchsorted(sorted_row, key, 'right') == count(row <= key)

computed as tiled broadcast-compare + reductions: grid (bucket, left-tile,
right-tile), each step compares a [TL] slice of left keys against a [TR] slice
of the right bucket and accumulates the two counts. No gathers, no dynamic
shapes — exactly the shape of work Mosaic schedules well. The per-bucket merge
this implements is what the reference gets from SortMergeJoinExec over
co-bucketed index scans (`JoinIndexRule.scala:137-162`).

Key dtype: 64-bit keys (hash mode is int64; value mode is promoted) do not
exist on the TPU VPU, so keys are pre-split OUTSIDE the kernel into a
lexicographic (hi, lo) int32 pair whose signed compare reproduces the 64-bit
order (floats go through the standard order-preserving bit transform first,
with -0.0 canonicalized to +0.0 so searchsorted equality classes survive).

Cost note: the tiled compare is O(cap_l * cap_r) per bucket vs the XLA path's
O(cap_l * log cap_r); it wins on dispatch/fusion for small-to-medium buckets
and loses asymptotically on very large ones, so `probe_ranges` dispatches by
capacity product (override with HYPERSPACE_PALLAS_PROBE=1/0). Equivalence with
the XLA path is pinned by tests/test_pallas_probe.py (interpret mode off-TPU).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_ENV_KEY = "HYPERSPACE_PALLAS_PROBE"
# Above this cap_l*cap_r the quadratic compare loses to XLA's log-probe.
_AUTO_MAX_PRODUCT = 1 << 22
_pallas_broken: list = []  # first failure recorded; falls back permanently


def _pallas_mode() -> str:
    return os.environ.get(_ENV_KEY, "auto")


def _sortable_i64(x: jnp.ndarray) -> jnp.ndarray:
    """Order-preserving map of any 64-bit key space into signed int64."""
    if jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(jnp.float64) + 0.0  # canonicalize -0.0
        bits = jax.lax.bitcast_convert_type(x, jnp.int64)
        # Negative floats: flip magnitude bits (reverses their order, keeps
        # sign); positives: unchanged. Signed compare == float total order.
        return bits ^ ((bits >> 63) & jnp.int64(0x7FFFFFFFFFFFFFFF))
    return x.astype(jnp.int64)


def _split_hi_lo(k: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(hi, lo) int32 pair whose lexicographic signed compare == int64 compare."""
    hi = (k >> 32).astype(jnp.int32)
    lo = ((k & jnp.int64(0xFFFFFFFF)) - jnp.int64(0x80000000)).astype(jnp.int32)
    return hi, lo


def _probe_kernel(lh_ref, ll_ref, rht_ref, rlt_ref, lo_ref, hi_ref):
    """One (bucket, left-tile, right-tile) step: accumulate lt/le counts.

    The right side arrives TRANSPOSED ([cap_r, B] arrays, (TR, 1) blocks) so
    the broadcast compare is [TR, 1] x [1, TL] -> [TR, TL] and the sublane
    reduction lands directly in the (1, TL) output block — no in-kernel
    reshapes/relayouts for Mosaic to choke on."""
    lh = lh_ref[...]  # [1, TL]
    ll = ll_ref[...]
    rh = rht_ref[...]  # [TR, 1]
    rl = rlt_ref[...]
    # r < key  /  r <= key, 64-bit order via the (hi, lo) int32 pair.
    r_lt_k = (rh < lh) | ((rh == lh) & (rl < ll))
    r_eq_k = (rh == lh) & (rl == ll)
    lt_counts = jnp.sum(r_lt_k, axis=0, keepdims=True, dtype=jnp.int32)
    le_counts = lt_counts + jnp.sum(r_eq_k, axis=0, keepdims=True, dtype=jnp.int32)

    @pl.when(pl.program_id(2) == 0)
    def _init():
        lo_ref[...] = jnp.zeros_like(lo_ref)
        hi_ref[...] = jnp.zeros_like(hi_ref)

    lo_ref[...] += lt_counts
    hi_ref[...] += le_counts


@partial(jax.jit, static_argnums=(4,))
def _probe_pallas_call(lh, ll, rh, rl, interpret: bool):
    B, cap_l = lh.shape
    cap_r = rh.shape[1]
    TL = min(cap_l, 256)
    TR = min(cap_r, 1024)
    # Caps reaching this kernel are _cap_pow2-shaped; guard loudly so a future
    # non-multiple cap cannot silently skip tail tiles (unwritten output blocks).
    assert cap_l % TL == 0 and cap_r % TR == 0, (cap_l, cap_r, TL, TR)
    grid = (B, cap_l // TL, cap_r // TR)
    rht = rh.T  # [cap_r, B]; one fused XLA transpose outside the kernel
    rlt = rl.T
    left_spec = pl.BlockSpec((1, TL), lambda b, i, j: (b, i))
    right_spec = pl.BlockSpec((TR, 1), lambda b, i, j: (j, b))
    out_spec = pl.BlockSpec((1, TL), lambda b, i, j: (b, i))
    lo, hi = pl.pallas_call(
        _probe_kernel,
        grid=grid,
        in_specs=[left_spec, left_spec, right_spec, right_spec],
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, cap_l), jnp.int32),
            jax.ShapeDtypeStruct((B, cap_l), jnp.int32),
        ],
        interpret=interpret,
    )(lh, ll, rht, rlt)
    return lo, hi


def probe_pallas(ls, rs, l_len, r_len) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Drop-in replacement for `bucket_join._probe`: (lo, counts) int32, with
    ranges clamped to each right bucket's valid length and counts zeroed for
    left pad slots."""
    lk = _sortable_i64(jnp.asarray(ls))
    rk = _sortable_i64(jnp.asarray(rs))
    lh, ll = _split_hi_lo(lk)
    rh, rl = _split_hi_lo(rk)
    interpret = jax.default_backend() != "tpu"
    lo, hi = _probe_pallas_call(lh, ll, rh, rl, interpret)
    r_len_b = jnp.asarray(r_len)[:, None]
    lo = jnp.minimum(lo, r_len_b).astype(jnp.int32)
    hi = jnp.minimum(hi, r_len_b)
    valid_left = jnp.arange(ls.shape[1])[None, :] < jnp.asarray(l_len)[:, None]
    counts = jnp.where(valid_left, hi - lo, 0).astype(jnp.int32)
    return lo, counts


def pallas_probe_wanted(cap_l: int, cap_r: int) -> bool:
    """Dispatch decision for `probe_ranges`: forced on/off by env, else on-TPU
    with a capacity-product bound (the quadratic-compare budget)."""
    if _pallas_broken:
        return False
    mode = _pallas_mode()
    if mode == "0":
        return False
    if mode == "1":
        return True
    return (
        jax.default_backend() == "tpu" and cap_l * cap_r <= _AUTO_MAX_PRODUCT
    )


def record_pallas_failure(exc: BaseException) -> None:
    import logging

    _pallas_broken.append(f"{type(exc).__name__}: {exc}")
    logging.getLogger("hyperspace_tpu.ops").warning(
        "pallas probe failed; falling back to the XLA probe permanently: %s",
        _pallas_broken[-1],
    )
