"""Stable on-device hashing for bucket assignment and join keys.

TPU-first design: bucket ids and join keys are computed on device with uint32 vector
ops (murmur3 finalizer mixing), so the index build's partitioning step
(the analogue of Spark's `repartition(numBuckets, indexedCols)` hash partitioning,
`CreateActionBase.scala:130-131`) runs on the VPU, not the host.

- Numeric columns hash on device from their bit patterns.
- String columns hash via their dictionary: one host-side blake2b per *unique* value,
  then a device gather through the codes — O(dict) host work, O(n) device work.
  This IS the encoded-execution hash path (docs/encoded-execution.md): keys
  arrive as dictionary codes from the reader and are never decoded to hash —
  the per-column dictionary-hash table is the only place the string bytes
  are ever touched, once per distinct value.
- Multi-column keys combine per-column hashes with a murmur-style mixer.
- Join keys are 64-bit (two independent 32-bit lanes packed), verified exactly at join
  time, so hash collisions can never produce wrong results.

Hash stability matters: the same value must hash identically in any table on any
backend (bucket co-location across independently-built indexes is what makes the
shuffle-free bucketed join sound — reference `JoinIndexRule.scala:144-156`).
"""

from __future__ import annotations

import hashlib
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import device_observatory as _devobs
from ..telemetry.compile_log import observed_jit as _observed_jit

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # annotation-only: a runtime import would cycle through
    # engine/__init__ -> session -> physical -> ops.hashing when `ops` is
    # imported before `engine`.
    from ..engine.table import Column

_SEED1 = np.uint32(0x9747B28C)
_SEED2 = np.uint32(0x85EBCA6B)

#: Pow2-quantize the ROW dimension of every fused hash program ("1" on, "0"
#: off, unset = auto: on exactly when the DEVICE kernel path is active). The
#: hash is elementwise, so padding the inputs to the next power of two and
#: slicing the output changes NOTHING for real rows — but it bounds the
#: number of distinct shapes each program ever traces to log2(max rows)
#: instead of one per exact table size. The r05 TPU bench died inside a
#: 2400 s compile of `hashing.bucket_id` fed a raw table-sized shape stream;
#: quantization at THIS boundary is the structural fix (every caller
#: inherits it), and it is what lets the persistent XLA compilation cache
#: stay small and hot across processes. The auto default is
#: backend-adaptive because the trade inverts: on a TPU (relay transports
#: included) one avoided compile pays for years of pad/slice copies, while
#: on the XLA-CPU backend compiles are ~0.2 s and the two O(n) copies showed
#: up as a measured 45% cold-join regression at 2M — so CPU runs exact
#: shapes unless explicitly opted in. (The MESH path is quantized either
#: way: `parallel/table_ops.py` pads rows onto the mesh grid before the hash
#: regardless of this knob.)
ENV_HASH_QUANTIZE = "HYPERSPACE_HASH_QUANTIZE"


def _hash_quantize_enabled() -> bool:
    env = os.environ.get(ENV_HASH_QUANTIZE)
    if env is not None and env != "":
        return env != "0"
    # Unset: the adaptive planner's calibrated decision replaces the raw
    # device-only heuristic (same prior, but both arms are priced and the
    # per-class outcome store can overturn a wrong guess — the measured 45%
    # CPU regression case lands on the span either way).
    from ..plananalysis.planner import decided_value

    decided = decided_value("hash_quantize")
    if decided is not None:
        return bool(decided)
    from .backend import use_device_path

    return use_device_path()


def _pow2_len(n: int) -> int:
    return 1 << (max(1, int(n)) - 1).bit_length()


def _pad_pow2(arr):
    """Pad a 1-D device array to the next pow2 length (zeros: a valid bit
    pattern for every kind — numeric words hash fine, string CODES index slot
    0 — and the caller slices the padded rows back off)."""
    a = jnp.asarray(arr)
    n = int(a.shape[0])
    n_pad = _pow2_len(n)
    if n_pad == n:
        return a
    return jnp.concatenate([a, jnp.zeros(n_pad - n, dtype=a.dtype)])


def fmix32(h):
    """murmur3 32-bit finalizer — a cheap, well-mixed bijection on uint32."""
    h = h.astype(jnp.uint32)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def _mix_combine(h, k):
    """Combine an accumulated hash with a new lane (murmur-style stream step)."""
    h = h ^ fmix32(k)
    h = (h * jnp.uint32(5)) + jnp.uint32(0xE6546B64)
    return h


def _words_u32(arr, force_float: bool = False):
    """Split an array into two uint32 word arrays from its canonical bit pattern.

    Values canonicalize WITHIN their kind (ints/bools → int64 bits, floats →
    float64 bits) so equal values hash equal regardless of storage width — an
    int32 id column must bucket/join against an int64 one. Integer hashing
    stays EXACT (float64 canonicalization would alias dense ids beyond 2^53 —
    snowflake ids, nanosecond timestamps — into systematic collision runs).

    `force_float` canonicalizes integers through float64 too: the CROSS-KIND
    join case (int key ⋈ float key), where equality is numpy-promoted float64
    equality (Spark casts both sides to double), so both sides must hash in
    that space. The JOIN decides this jointly per key pair; it never applies
    to single-table hashing (builds, group-bys)."""
    x = jnp.asarray(arr)
    if force_float or jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(jnp.float64)
        # Normalize -0.0 to +0.0 so equal floats hash equal.
        x = jnp.where(x == 0, jnp.zeros_like(x), x)
        bits = jax.lax.bitcast_convert_type(x, jnp.uint32)  # shape (..., 2)
        return [bits[..., 0], bits[..., 1]]
    x = x.astype(jnp.int64)
    lo = (x & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = ((x >> jnp.int64(32)) & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    return [lo, hi]


def hash_device_values(arr, seed: np.uint32, force_float: bool = False):
    """uint32 hash of a numeric device array's values."""
    words = _words_u32(arr, force_float)
    h = jnp.full(words[0].shape, jnp.uint32(seed))
    for w in words:
        h = _mix_combine(h, w)
    return fmix32(h)


# Dictionary-hash memo: one blake2b pass per (dictionary identity, seed), not
# per query — weakref-keyed so entries die with their dictionaries (the same
# id-reuse-safe pattern as the engine's device memo caches).
_dict_hash_cache: dict = {}
import threading as _threading

_dict_hash_lock = _threading.RLock()  # concurrent queries share the memo


def host_hash_dictionary(dictionary: np.ndarray, seed: int):
    """Stable uint32 hash per unique string, as a DEVICE array (host blake2b
    once per dictionary entry; hash + upload both memoized per dictionary
    object + seed, so repeat queries are transfer-free on the relay)."""
    import weakref

    key = (id(dictionary), int(seed))
    with _dict_hash_lock:
        ent = _dict_hash_cache.get(key)
        if ent is not None and ent[0]() is dictionary:
            return ent[1]
    out = np.empty(len(dictionary), dtype=np.uint32)
    seed_bytes = int(seed).to_bytes(4, "little")
    for i, s in enumerate(dictionary):
        d = hashlib.blake2b(str(s).encode("utf-8"), digest_size=4, salt=seed_bytes).digest()
        out[i] = np.frombuffer(d, dtype=np.uint32)[0]
    if _hash_quantize_enabled():
        # Pow2-pad the table: dictionary sizes are data-dependent, and the
        # table is an operand SHAPE of every fused string-hash program — an
        # unpadded table would re-trace those programs once per distinct
        # cardinality. Gathers only ever index real codes, so padding is
        # invisible to the hash values.
        n_pad = _pow2_len(len(out))
        if n_pad != len(out):
            _devobs.record_pad("hash_dict", len(out) * 4, (n_pad - len(out)) * 4)
            out = np.concatenate([out, np.zeros(n_pad - len(out), np.uint32)])
    dev = jnp.asarray(out)

    def _evict(wr, key=key):
        with _dict_hash_lock:
            ent_now = _dict_hash_cache.get(key)
            if ent_now is not None and ent_now[0] is wr:
                _dict_hash_cache.pop(key, None)

    try:
        with _dict_hash_lock:
            _dict_hash_cache[key] = (weakref.ref(dictionary, _evict), dev)
    except TypeError:
        pass  # non-weakref-able dictionary container: skip memoization
    return dev


def column_hash_u32(column: Column, device_data, seed: np.uint32):
    """uint32 hash of one column's values (device array in, device array out).

    ``device_data`` is the column's device representation (codes for strings)."""
    if column.is_string:
        from ..engine.encoded_device import widen_for_gather

        device_data = widen_for_gather(device_data)
        return host_hash_dictionary(column.dictionary, int(seed))[device_data]
    return hash_device_values(device_data, seed)


def _lane_trace(seed, dh_slot, cols):
    """Trace-time combine over prepared per-column inputs: `cols[i]` is
    ("num", arr) or ("str", codes, dh_table_per_seed...); `dh_slot` picks the
    dict-hash table matching `seed` for string columns (tables sit at
    c[2], c[3], ... in seed order)."""
    h = None
    for c in cols:
        if c[0] == "str":
            from ..engine.encoded_device import widen_for_gather

            codes = widen_for_gather(c[1])
            hc = c[2 + dh_slot][codes]
        else:
            hc = hash_device_values(c[1], seed, force_float=(c[0] == "numf"))
        h = hc if h is None else fmix32(_mix_combine(h, hc))
    return h


def _unflatten(kinds, flat, per_str: int):
    cols, i = [], 0
    for kind in kinds:
        if kind == "str":
            cols.append(("str", *flat[i : i + per_str]))
            i += per_str
        else:
            cols.append((kind, flat[i]))  # "num" | "numf" (forced-float canon)
            i += 1
    return cols


@_observed_jit(label="hashing.key64", static_argnums=(0,))
def _key64_fused(kinds, *flat):
    """Both hash lanes + the 64-bit pack in ONE compiled program. Each eager
    jnp op is a dispatch — ~40 per key64 — and on the axon relay every
    dispatch is a round-trip, so fusing is a direct wall-clock win on TPU
    (measured: the non-indexed scan join spends seconds in hash dispatches)."""
    cols = _unflatten(kinds, flat, 3)
    h1 = _lane_trace(_SEED1, 0, cols)
    h2 = _lane_trace(_SEED2, 1, cols)
    return (h1.astype(jnp.int64) << jnp.int64(32)) | h2.astype(jnp.int64)


@_observed_jit(label="hashing.combined_hash", static_argnums=(0, 1))
def _combined_fused(kinds, seed, *flat):
    cols = _unflatten(kinds, flat, 2)
    return _lane_trace(seed, 0, cols)


@_observed_jit(label="hashing.bucket_id", static_argnums=(0, 1))
def _bucket_id_fused(kinds, num_buckets, *flat):
    cols = _unflatten(kinds, flat, 2)
    h1 = _lane_trace(_SEED1, 0, cols)
    return (h1 % jnp.uint32(num_buckets)).astype(jnp.int32)


def _flat_inputs(columns, device_arrays, seeds, force_float=None):
    """(kinds, flat) for the fused kernels: string columns contribute their
    codes plus one host-hashed dictionary table per seed. `force_float[i]`
    canonicalizes numeric column i through float64 (the cross-kind join
    space — see `_words_u32`).

    Code arrays may arrive NARROW (int8/int16 — engine/encoded_device.py
    stages them that way when the dictionary fits): the string lane is a
    `dh_table[codes]` gather, so any integer code width produces identical
    hashes, and the width folds into the jit cache key as a bounded
    {int8, int16, int32} class set — never a per-cardinality shape."""
    kinds, flat = [], []
    for i, (col, arr) in enumerate(zip(columns, device_arrays)):
        if col.is_string:
            kinds.append("str")
            flat.append(arr)
            for s in seeds:
                flat.append(host_hash_dictionary(col.dictionary, int(s)))
        else:
            kinds.append("numf" if force_float is not None and force_float[i] else "num")
            flat.append(arr)
    return tuple(kinds), flat


def _quantized_row_inputs(device_arrays):
    """(device_arrays possibly pow2-padded, real row count or None). None =
    already on the grid / quantization off — call the fused program as-is."""
    if not _hash_quantize_enabled() or not device_arrays:
        return device_arrays, None
    n = int(jnp.asarray(device_arrays[0]).shape[0])
    if n == 0 or _pow2_len(n) == n:
        return device_arrays, None
    padded = [_pad_pow2(a) for a in device_arrays]
    # Padding-tax ledger: real rows vs the pow2 tail, summed over operands.
    itemsizes = [int(jnp.asarray(a).dtype.itemsize) for a in device_arrays]
    _devobs.record_pad(
        "hash_quantize",
        sum(n * sz for sz in itemsizes),
        sum((_pow2_len(n) - n) * sz for sz in itemsizes),
    )
    return padded, n


def combined_hash_u32(columns, device_arrays, seed: np.uint32):
    """Combine multiple key columns into one uint32 hash (one fused program,
    row dimension pow2-quantized — see `ENV_HASH_QUANTIZE`)."""
    device_arrays, n = _quantized_row_inputs(device_arrays)
    kinds, flat = _flat_inputs(columns, device_arrays, (seed,))
    out = _combined_fused(kinds, seed, *flat)
    return out if n is None else out[:n]


def key64(columns, device_arrays, force_float=None):
    """Signed 64-bit join/sort key from two independent 32-bit hash lanes.

    Equal key tuples always map to equal key64 (value-based hashing); unequal tuples
    collide with probability ~2^-64 and are removed by the join's exact-equality
    verification pass. `force_float[i]` hashes numeric column i in the
    cross-kind float64 space (joint decision of both join sides)."""
    device_arrays, n = _quantized_row_inputs(device_arrays)
    kinds, flat = _flat_inputs(columns, device_arrays, (_SEED1, _SEED2), force_float)
    out = _key64_fused(kinds, *flat)
    return out if n is None else out[:n]


def bucket_id(columns, device_arrays, num_buckets: int):
    """Bucket assignment: h1 % num_buckets (the repartition hash)."""
    device_arrays, n = _quantized_row_inputs(device_arrays)
    kinds, flat = _flat_inputs(columns, device_arrays, (_SEED1,))
    out = _bucket_id_fused(kinds, int(num_buckets), *flat)
    return out if n is None else out[:n]
