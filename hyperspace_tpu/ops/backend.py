"""Backend dispatch for the sort kernels.

Two implementations exist for the build/join sort primitives: the device path
(`lax.sort` / `jnp.argsort` — the TPU design) and a host-numpy fallback used on
the CPU backend, where XLA's single-threaded variadic sort is ~3x slower than
numpy at index-build sizes. Tests and CI run on XLA-CPU, so without an override
they would only ever certify the numpy branch; `HYPERSPACE_FORCE_DEVICE_OPS=1`
forces the device kernels on any backend so the suite exercises the exact
program a TPU runs (r3 verdict weak item 5). The CI matrix runs the full suite
once per mode.
"""

from __future__ import annotations

import os

import jax

_ENV_KEY = "HYPERSPACE_FORCE_DEVICE_OPS"


def device_ops_forced() -> bool:
    return os.environ.get(_ENV_KEY, "") not in ("", "0")


def use_device_path() -> bool:
    """True when the lax.sort/argsort device kernels should run: any non-CPU
    backend, or any backend under HYPERSPACE_FORCE_DEVICE_OPS=1."""
    return jax.default_backend() != "cpu" or device_ops_forced()


def pallas_maybe_wanted(env_key: str) -> bool:
    """Cheap pre-gate evaluated BEFORE importing any pallas module: importing
    `jax.experimental.pallas` costs ~1 s, and off-TPU a kernel can only be
    wanted under an explicit `<env_key>=1` force — exactly the dispatchers'
    own off-TPU condition, so the gate can never produce a false negative.
    `=0` (explicit disable) must NOT trigger the import."""
    return jax.default_backend() == "tpu" or os.environ.get(env_key) == "1"
