from . import hashing, join  # noqa: F401
