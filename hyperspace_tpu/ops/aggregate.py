"""Grouped aggregation on device: hash-sort + segment reductions.

The reference's real workloads are aggregation-bearing SQL (every BASELINE TPC-H/DS
config groups the indexed join's output; the reference gets GROUP BY for free from
Spark — `docs/_docs/13-toh-overview.md:33-36`). The engine analogue, TPU-first:

1. key64-hash the group columns on device (`ops.hashing`), argsort once — equal key
   tuples are guaranteed adjacent because equal tuples hash equal (null slots hold
   the canonical fill, so they hash equal too and form one cluster).
2. Detect group boundaries by comparing ADJACENT ACTUAL values (+ validity lanes),
   not hashes — so a 64-bit hash collision between different tuples can only SPLIT
   a group (the colliding tuples interleave within one sorted run), never merge two.
3. Segment-reduce every aggregate in one device pass (`jax.ops.segment_sum/min/max`
   with a static segment count → compiled once per shape class).
4. A host pass dedups representative key tuples; the astronomically-rare split from
   step 2 is repaired by recomputing on host — the exactness contract matches the
   join path's verify step (hash suggests, values decide).

SQL semantics: group-key nulls form one group (GROUP BY groups nulls); sum/min/max/
avg ignore null inputs and are NULL for all-null groups; count(col) counts non-null,
count(*) counts rows.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.schema import BOOL, FLOAT32, FLOAT64, INT32, INT64, STRING
from ..engine.table import Column, Table
from ..exceptions import HyperspaceException
from ..engine.device_cache import device_array
from ..telemetry import device_observatory as _devobs
from ..telemetry.compile_log import observed_jit as _observed_jit
from .hashing import key64

#: (out_name, fn, column|None) — column is None only for count(*).
AggTriple = Tuple[str, str, Optional[str]]


def _group_ids_body(has_valid: tuple, perm, flat, xp=jnp):
    """Boundary detection + group ids from a given sort permutation — the ONE
    home of the adjacent-value (+validity) semantics, used traced (fused
    device program, xp=jnp) and eagerly on HOST arrays (CPU path, xp=np:
    eager jnp ops here were measured at ~0.5 s of device round-trips per 8M
    aggregate on the CPU backend). `has_valid[i]` tells whether key column i
    contributes a validity lane in `flat`."""
    n = perm.shape[0]
    eq = xp.ones(max(n - 1, 0), bool)
    i = 0
    for hv in has_valid:
        a = flat[i]
        i += 1
        sa = a[perm]
        col_eq = sa[1:] == sa[:-1]
        if hv:
            sv = flat[i][perm]
            i += 1
            both_null = (~sv[1:]) & (~sv[:-1])
            col_eq = (col_eq & (sv[1:] == sv[:-1])) | both_null
        eq = eq & col_eq
    boundary = xp.concatenate([xp.ones(1, bool), ~eq])
    gid = xp.cumsum(boundary.astype(xp.int64)) - 1
    return boundary, gid


@_observed_jit(label="aggregate.group_ids", static_argnums=(0,))
def _group_ids_fused(has_valid: tuple, k64, *flat):
    """Device path of the group-id pipeline as ONE compiled program."""
    perm = jnp.argsort(k64)  # stable by default
    boundary, gid = _group_ids_body(has_valid, perm, flat)
    return perm, boundary, gid

_NUMERIC = (INT32, INT64, FLOAT32, FLOAT64, BOOL)


def _minmax_fill(dtype: np.dtype, fn: str):
    """Null-mask fill for min/max: the opposite extreme of the value domain,
    so masked slots never win the reduction. The ONE home of this rule for
    the device program (`_seg_reduce_body`), the CPU fast path
    (`_segment_reduce_host`), and the collision-repair oracle
    (`_host_aggregate`). Bool inputs are converted to int32 by callers first."""
    if np.issubdtype(dtype, np.floating):
        return np.asarray(np.inf if fn == "min" else -np.inf, dtype=dtype)
    info = np.iinfo(dtype)
    return np.asarray(info.max if fn == "min" else info.min, dtype=dtype)


def _acc_dtype(dtype):
    """sum/avg accumulator widening: floats → float64, ints/bools → int64
    (np scalar types are jnp-compatible, so both paths share this)."""
    return np.float64 if np.issubdtype(np.dtype(dtype), np.floating) else np.int64


def result_dtype(fn: str, in_dtype: Optional[str]) -> str:
    """Aggregate result type: count/count_distinct→int64; avg→float64; sum widens
    to int64/float64; min/max preserve the input type (strings included —
    dictionary order is value order because dictionaries are sorted)."""
    if fn == "count":
        return INT64
    if in_dtype is None:
        raise HyperspaceException(f"{fn}() requires a column")
    if fn == "count_distinct":
        return INT64
    if fn == "avg":
        if in_dtype not in _NUMERIC:
            raise HyperspaceException(f"avg() unsupported for {in_dtype}")
        return FLOAT64
    if fn == "sum":
        if in_dtype in (FLOAT32, FLOAT64):
            return FLOAT64
        if in_dtype in (INT32, INT64, BOOL):
            return INT64
        raise HyperspaceException(f"sum() unsupported for {in_dtype}")
    if fn in ("min", "max"):
        return in_dtype
    raise HyperspaceException(f"Unknown aggregate function: {fn}")


def _out_column(
    fn: str, col: Optional[Column], dtype: str, vals: np.ndarray, validity
) -> Column:
    """Package reduced values (+ all-null-group validity) as an output column."""
    v = None if validity is None or bool(np.all(validity)) else np.asarray(validity, bool)
    if dtype == STRING:
        d = col.dictionary if col is not None and len(col.dictionary) else np.array([""], "<U1")
        codes = np.asarray(vals, np.int64)
        if v is not None:
            # All-null groups hold the min/max fill sentinel — not a valid code.
            codes = np.where(v, codes, 0)
        return Column(STRING, codes.astype(np.int32), d, v)
    return Column(dtype, np.asarray(vals).astype(np.dtype(dtype)), None, v)


def _empty_result(table: Table, group_keys, aggs: Sequence[AggTriple]) -> Table:
    out = {}
    for k in group_keys:
        out[k] = table.column(k)
    for out_name, fn, col_name in aggs:
        col = table.column(col_name) if col_name is not None else None
        dtype = result_dtype(fn, None if col is None else col.dtype)
        out[out_name] = _out_column(fn, col, dtype, np.empty(0, np.int64), None)
    return Table(out)


def _distinct_values(data: np.ndarray) -> np.ndarray:
    """Value lane for distinct-dedup: floats canonicalized (all NaNs one value,
    -0.0 == +0.0) and viewed as bit patterns, because structured np.unique
    compares NaN != NaN and would count every NaN occurrence separately."""
    if np.issubdtype(data.dtype, np.floating):
        d = data.astype(np.float64)
        d = np.where(np.isnan(d), np.float64("nan"), d)
        d = np.where(d == 0.0, np.float64(0.0), d)
        return d.view(np.int64)
    return data


def _count_distinct_per_group(
    group_ids: np.ndarray, col: Column, valid: np.ndarray, n_groups: int
) -> np.ndarray:
    """Exact per-group distinct counts via (group, value) pair dedup — the ONE
    implementation behind the grouped device path, the host oracle, and
    (with a single group) the global path. Values are codes for strings."""
    pairs = np.rec.fromarrays(
        [group_ids[valid], _distinct_values(col.data)[valid]]
    )
    uniq_pairs = np.unique(pairs)
    vals = np.zeros(n_groups, np.int64)
    np.add.at(vals, uniq_pairs.f0, 1)
    return vals


def _canon_distinct_traced(x):
    """Traced twin of `_distinct_values`' canonicalization (all NaNs one value,
    -0.0 == +0.0, floats viewed as int64 bit patterns) so the device
    count-distinct compares the same value identities the host oracle does."""
    if jnp.issubdtype(x.dtype, jnp.floating):
        d = x.astype(jnp.float64)
        d = jnp.where(jnp.isnan(d), jnp.float64(np.nan), d)
        d = jnp.where(d == 0.0, jnp.float64(0.0), d)
        return jax.lax.bitcast_convert_type(d, jnp.int64)
    if x.dtype == jnp.bool_:
        return x.astype(jnp.int32)
    return x


@_observed_jit(label="aggregate.count_distinct", static_argnums=(0, 1))
def _count_distinct_dev_jit(n_groups: int, has_valid: bool, gid, perm, x, valid=None):
    """Per-group exact distinct counts ON DEVICE, for rows already run through
    the group-id program (`gid`/`perm` from `_group_ids_fused`): sort each
    group's values adjacent (invalid slots to the back of their group), count
    first-of-run valid slots per group. Exactness matches the host
    `_count_distinct_per_group` (actual canonicalized values, never hashes)."""
    xs = _canon_distinct_traced(x)[perm]
    v = valid[perm] if has_valid else jnp.ones(xs.shape[0], bool)
    # lexsort: LAST key is primary → (value, invalid-last, group).
    order = jnp.lexsort((xs, ~v, gid))
    sg = gid[order]
    sx = xs[order]
    sv = v[order]
    first = jnp.concatenate(
        [jnp.ones(1, bool), (sg[1:] != sg[:-1]) | (sx[1:] != sx[:-1])]
    )
    # Valids sort before invalids within a group, so a valid slot never follows
    # an invalid one of the same group: `first & valid` counts distinct valids.
    return jax.ops.segment_sum(
        (first & sv).astype(jnp.int64), sg, num_segments=n_groups
    )


def _global_aggregate(table: Table, aggs: Sequence[AggTriple]) -> Table:
    """No group keys: one output row (SQL global aggregate; empty input gives
    count=0 and NULL sum/min/max/avg)."""
    out = {}
    n = table.num_rows
    for out_name, fn, col_name in aggs:
        col = table.column(col_name) if col_name is not None else None
        dtype = result_dtype(fn, None if col is None else col.dtype)
        if fn == "count" and col is None:
            out[out_name] = _out_column(fn, col, dtype, np.array([n]), None)
            continue
        valid = col.validity if col.validity is not None else np.ones(n, bool)
        nv = int(valid.sum())
        if fn == "count":
            out[out_name] = _out_column(fn, col, dtype, np.array([nv]), None)
            continue
        if fn == "count_distinct":
            counts = _count_distinct_per_group(
                np.zeros(n, np.int64), col, np.asarray(valid, bool), 1
            )
            out[out_name] = _out_column(fn, col, dtype, counts, None)
            continue
        if nv == 0:
            out[out_name] = _out_column(
                fn, col, dtype, np.zeros(1, np.int64), np.zeros(1, bool)
            )
            continue
        data = col.data[valid]  # codes for strings: min code == min value
        if fn == "min":
            r = data.min()
        elif fn == "max":
            r = data.max()
        else:
            acc = data.astype(np.float64 if dtype == FLOAT64 else np.int64)
            r = acc.sum()
            if fn == "avg":
                r = float(r) / nv
        out[out_name] = _out_column(fn, col, dtype, np.array([r]), None)
    return Table(out)


def _seg_reduce_body(fn: str, n_groups: int, has_valid: bool, gid, perm, x, valid=None):
    """One aggregate's permute + mask + segment reduce — the traced body shared
    by the single-agg program and the all-aggs-fused program. Returns
    (values, n_valid)."""
    n = x.shape[0]
    v = valid[perm] if has_valid else jnp.ones(n, bool)
    n_valid = jax.ops.segment_sum(v.astype(jnp.int64), gid, num_segments=n_groups)
    if fn == "count":
        return n_valid, n_valid
    xs = x[perm]
    if fn in ("sum", "avg"):
        acc = xs.astype(_acc_dtype(xs.dtype))
        s = jax.ops.segment_sum(jnp.where(v, acc, 0), gid, num_segments=n_groups)
        if fn == "sum":
            return s, n_valid
        c = jnp.maximum(n_valid, 1)
        return s.astype(jnp.float64) / c.astype(jnp.float64), n_valid
    # min/max: mask nulls to the opposite extreme; all-null groups are invalid.
    if xs.dtype == jnp.bool_:
        xs = xs.astype(jnp.int32)  # segment_min/iinfo don't take bools
    masked = jnp.where(v, xs, _minmax_fill(np.dtype(xs.dtype), fn))
    reduce = jax.ops.segment_min if fn == "min" else jax.ops.segment_max
    return reduce(masked, gid, num_segments=n_groups), n_valid


@_observed_jit(label="aggregate.seg_reduce", static_argnums=(0, 1, 2))
def _seg_reduce_jit(fn: str, n_groups: int, has_valid: bool, gid, perm, x, valid=None):
    """One aggregate's whole device pipeline as a single compiled program,
    keyed on (fn, n_groups, validity presence, shapes/dtypes)."""
    return _seg_reduce_body(fn, n_groups, has_valid, gid, perm, x, valid)


@_observed_jit(label="aggregate.seg_reduce_multi", static_argnums=(0, 1))
def _seg_reduce_multi_jit(specs: tuple, n_groups: int, gid, perm, *flat):
    """EVERY aggregate's segment reduction in ONE compiled program — on a
    remote PJRT transport each dispatch is a round-trip, so a 4-aggregate
    query pays 1 RTT here instead of 4. `specs[i] = (fn, has_valid)`; `flat`
    carries x [+ valid] per aggregate in order. XLA CSEs the shared permute.
    Returns a flat tuple of (values, n_valid) pairs."""
    out = []
    i = 0
    for fn, has_valid in specs:
        x = flat[i]
        i += 1
        valid = None
        if has_valid:
            valid = flat[i]
            i += 1
        out.extend(_seg_reduce_body(fn, n_groups, has_valid, gid, perm, x, valid))
    return tuple(out)


def _segment_reduce(
    fn: str,
    col: Optional[Column],
    gid: jnp.ndarray,
    perm: jnp.ndarray,
    n_groups: int,
    seg_rows: jnp.ndarray,
):
    """One aggregate over the hash-sorted rows → (values[n_groups], validity|None)."""
    if fn == "count" and col is None:
        return np.asarray(seg_rows), None
    assert col is not None
    has_valid = col.validity is not None
    args = (device_array(col.data),)
    if has_valid:
        args = args + (device_array(col.validity),)
    vals, n_valid = _seg_reduce_jit(fn, int(n_groups), has_valid, gid, perm, *args)
    if fn == "count":
        return np.asarray(n_valid), None
    any_valid = np.asarray(n_valid) > 0
    return np.asarray(vals), any_valid


def _segment_reduce_host(
    fn: str,
    col: Optional[Column],
    perm: np.ndarray,
    starts: np.ndarray,
    seg_rows: np.ndarray,
):
    """Host twin of `_segment_reduce` for the CPU backend: `ufunc.reduceat`
    over the sorted rows at the group-start offsets. The device branch's
    `_seg_reduce_jit` on XLA-CPU pays an upload per 8M-row column plus a
    single-threaded segment scatter — measured ~0.65 s per aggregate at 8M,
    vs ~0.1 s for the gather+reduceat pair here. Returns (values, n_valid):
    the per-group non-null input counts, from which callers derive the
    all-null-group validity (and which the streaming carry accumulates)."""
    if fn == "count" and col is None:
        n_rows = seg_rows.astype(np.int64)
        return n_rows, n_rows
    assert col is not None
    has_valid = col.validity is not None
    sv = col.validity[perm] if has_valid else None
    n_valid = (
        np.add.reduceat(sv.astype(np.int64), starts)
        if has_valid
        else seg_rows.astype(np.int64)
    )
    if fn == "count":
        return n_valid, n_valid
    xs = col.data[perm]
    if fn in ("sum", "avg"):
        acc = xs.astype(_acc_dtype(xs.dtype))
        if has_valid:
            acc = np.where(sv, acc, 0)
        s = np.add.reduceat(acc, starts)
        if fn == "sum":
            return s, n_valid
        return s.astype(np.float64) / np.maximum(n_valid, 1), n_valid
    # min/max: mask nulls to the opposite extreme; all-null groups are invalid.
    if xs.dtype == np.bool_:
        xs = xs.astype(np.int32)
    if has_valid:
        xs = np.where(sv, xs, _minmax_fill(xs.dtype, fn))
    op = np.minimum if fn == "min" else np.maximum
    return op.reduceat(xs, starts), n_valid


def _key_records(table: Table, group_keys) -> np.ndarray:
    """Key tuples as one comparable structured array: per column (data, valid) with
    invalid slots' data masked to the canonical fill, so null == null and
    null != everything-else exactly."""
    fields = []
    for k in group_keys:
        c = table.column(k)
        valid = c.validity if c.validity is not None else np.ones(len(c.data), bool)
        data = np.where(valid, c.data, np.zeros((), dtype=c.data.dtype))
        fields.append(data)
        fields.append(valid)
    return np.rec.fromarrays(fields)


def _host_aggregate(table: Table, group_keys, aggs: Sequence[AggTriple]) -> Table:
    """Exact host groupby — the collision-repair path (and the oracle the tests
    compare the device path against). np.unique group ids + ufunc.at reductions."""
    recs = _key_records(table, group_keys)
    uniq, first_idx, inverse = np.unique(recs, return_index=True, return_inverse=True)
    n_groups = len(uniq)
    out = {}
    rep_rows = table.take(np.sort(first_idx))
    # np.unique sorts groups; keep FIRST-OCCURRENCE order stable instead so the
    # device and host paths are comparable after row sorting.
    order = np.argsort(first_idx, kind="stable")
    remap = np.empty(n_groups, np.int64)
    remap[order] = np.arange(n_groups)
    inverse = remap[inverse]
    for k in group_keys:
        out[k] = rep_rows.column(k)
    for out_name, fn, col_name in aggs:
        col = table.column(col_name) if col_name is not None else None
        dtype = result_dtype(fn, None if col is None else col.dtype)
        if fn == "count" and col is None:
            vals = np.zeros(n_groups, np.int64)
            np.add.at(vals, inverse, 1)
            out[out_name] = _out_column(fn, col, dtype, vals, None)
            continue
        valid = col.validity if col.validity is not None else np.ones(len(col.data), bool)
        nv = np.zeros(n_groups, np.int64)
        np.add.at(nv, inverse[valid], 1)
        if fn == "count":
            out[out_name] = _out_column(fn, col, dtype, nv, None)
            continue
        if fn == "count_distinct":
            vals = _count_distinct_per_group(inverse, col, valid, n_groups)
            out[out_name] = _out_column(fn, col, dtype, vals, None)
            continue
        any_valid = nv > 0
        data = col.data
        if fn in ("sum", "avg"):
            acc = data.astype(_acc_dtype(data.dtype))
            s = np.zeros(n_groups, acc.dtype)
            np.add.at(s, inverse[valid], acc[valid])
            vals = s if fn == "sum" else s.astype(np.float64) / np.maximum(nv, 1)
        else:
            if data.dtype == np.bool_:
                data = data.astype(np.int32)
            vals = np.full(n_groups, _minmax_fill(data.dtype, fn), data.dtype)
            op = np.minimum if fn == "min" else np.maximum
            op.at(vals, inverse[valid], data[valid])
        out[out_name] = _out_column(fn, col, dtype, vals, any_valid)
    return Table(out)


class DevCol:
    """A device-resident virtual column for the fused join→aggregate pipeline:
    jnp value array (codes for strings), host dictionary, optional jnp validity
    lane. Duck-types the attrs `key64`/`_out_column` read from `Column`."""

    __slots__ = ("dtype", "arr", "dictionary", "validity")

    def __init__(self, dtype: str, arr, dictionary=None, validity=None):
        self.dtype = dtype
        self.arr = arr
        self.dictionary = dictionary
        self.validity = validity

    @property
    def is_string(self) -> bool:
        return self.dtype == STRING


def hash_aggregate_device(
    cols: dict,
    row_valid,
    group_keys: Sequence[str],
    aggs: Sequence[AggTriple],
) -> Optional[Table]:
    """GROUP BY over DEVICE-resident virtual columns (`DevCol`) — the aggregate
    core of the fused bucketed-join→aggregate path. Same pipeline as
    `hash_aggregate` (hash-sort, adjacent-ACTUAL-value boundaries, segment
    reductions) but the input never materializes as a host table: only the
    per-group results and representative key rows are pulled (n_groups-sized).

    `row_valid` is an optional global validity lane: the fused join pads its
    compacted pair arrays by REPEATING a real pair to keep shapes static, and
    those pad slots must contribute to no aggregate (they can never form a
    spurious group — they duplicate real key values). Returns None on the
    astronomically-rare 64-bit hash collision split (caller recomputes exactly)."""
    group_keys = list(group_keys)
    key_cols = [cols[k] for k in group_keys]
    k64 = key64(key_cols, [c.arr for c in key_cols])

    flat = []
    has_valid = []
    for c in key_cols:
        flat.append(c.arr)
        has_valid.append(c.validity is not None)
        if c.validity is not None:
            flat.append(c.validity)
    perm, boundary, gid = _group_ids_fused(tuple(has_valid), k64, *flat)
    n_groups = int(gid[-1]) + 1

    # Representative rows: one device compaction + tiny gathers, pulled host-side
    # at n_groups size (never the full pair count).
    rep_rows = perm[jnp.nonzero(boundary, size=n_groups)[0]]
    rep_cols = {}
    for k, c in zip(group_keys, key_cols):
        data = np.asarray(c.arr[rep_rows])
        v = None if c.validity is None else np.asarray(c.validity[rep_rows], bool)
        if c.is_string:
            codes = data.astype(np.int32)
            if v is not None:
                codes = np.where(v, codes, 0).astype(np.int32)
            rep_cols[k] = Column(STRING, codes, c.dictionary, v)
        else:
            if v is not None:
                data = np.where(v, data, np.zeros((), dtype=data.dtype))
            rep_cols[k] = Column(c.dtype, data.astype(np.dtype(c.dtype)), None, v)
    rep_table = Table(rep_cols)
    if len(np.unique(_key_records(rep_table, group_keys))) != n_groups:
        return None  # collision split: caller takes the exact path

    out = dict(rep_cols)
    # ALL aggregates reduce in ONE compiled program (1 dispatch RTT), results
    # pulled host-side in ONE transfer.
    specs, flat, metas = [], [], []
    for out_name, fn, col_name in aggs:
        c = cols[col_name] if col_name is not None else None
        dtype = result_dtype(fn, None if c is None else c.dtype)
        if fn == "count" and c is None:
            # count(*) counts surviving rows: the row_valid lane IS the data.
            specs.append(("count", row_valid is not None))
            flat.append(row_valid if row_valid is not None else k64)
            if row_valid is not None:
                flat.append(row_valid)
            metas.append((out_name, fn, None, dtype))
            continue
        v = c.validity
        if row_valid is not None:
            v = row_valid if v is None else (v & row_valid)
        specs.append((fn, v is not None))
        flat.append(c.arr)
        if v is not None:
            flat.append(v)
        metas.append((out_name, fn, c, dtype))
    results = jax.device_get(
        _seg_reduce_multi_jit(tuple(specs), n_groups, gid, perm, *flat)
    )
    for i, (out_name, fn, c, dtype) in enumerate(metas):
        vals, n_valid = np.asarray(results[2 * i]), np.asarray(results[2 * i + 1])
        if fn == "count":
            out[out_name] = _out_column(fn, None, dtype, n_valid, None)
            continue
        out[out_name] = _out_column(fn, c, dtype, vals, n_valid > 0)
    return Table(out)


# Direct-address aggregation cell budget: 4M cells ≈ 32 MB per int64
# accumulator — bincount is O(n + R), so a bounded R keeps the pass linear.
_DIRECT_CELL_BUDGET = 1 << 22


def _direct_layout(key_cols, aggs: Sequence[AggTriple]):
    """Eligibility + cell layout of the direct-address host aggregation:
    (los, ranges, datas, strides, cells), or None when the shape doesn't
    apply (float or null-able keys, unbounded ranges, min/max aggregates).
    The ONE home of this decision: `_direct_host_aggregate` takes it over the
    full key columns, and the streaming finalizer re-derives it over the
    carried group keys (whose value ranges/dictionaries equal the full
    columns') to reproduce the same output order."""
    for _, fn, _ in aggs:
        if fn in ("min", "max"):
            return None
    los, ranges, datas = [], [], []
    for c in key_cols:
        if c.validity is not None:
            return None
        data = c.data
        if c.is_string:
            lo, hi = 0, max(len(c.dictionary) - 1, 0)
        elif data.dtype == np.bool_:
            data = data.astype(np.int64)
            lo, hi = 0, 1
        elif np.issubdtype(data.dtype, np.integer):
            if len(data) == 0:
                return None
            lo, hi = int(data.min()), int(data.max())
        else:
            return None
        los.append(lo)
        ranges.append(hi - lo + 1)
        datas.append(data)
    cells = 1
    for r in ranges:
        cells *= r
        if cells > _DIRECT_CELL_BUDGET:
            return None
    # Mixed-radix cell id strides: last key fastest (row-major).
    strides = [1] * len(ranges)
    for i in range(len(ranges) - 2, -1, -1):
        strides[i] = strides[i + 1] * ranges[i + 1]
    return los, ranges, datas, strides, cells


def _direct_host_aggregate(
    table: Table, group_keys, key_cols, aggs: Sequence[AggTriple]
) -> Optional[Table]:
    """Sort-free host aggregation for bounded-range integer/dictionary keys:
    each key tuple maps to a dense cell id (mixed-radix over per-key value
    ranges) and every aggregate is one `np.bincount` pass — no 8M-row argsort
    (measured 0.58 s of the 8M CPU Q3 aggregate) and no representative-row
    gather (key values are reconstructed from the cell id). Returns None
    whenever the shape doesn't apply (`_direct_layout`) — the sort path is
    always correct."""
    n = table.num_rows
    layout = _direct_layout(key_cols, aggs)
    if layout is None:
        return None
    los, ranges, datas, strides, cells = layout

    gid0 = np.zeros(n, np.int64)
    for data, lo, st in zip(datas, los, strides):
        gid0 += (data.astype(np.int64) - lo) * st

    counts = np.bincount(gid0, minlength=cells)
    present = np.nonzero(counts)[0]
    n_groups = len(present)
    counts_p = counts[present]
    remap = None  # dense per-row group ids, built only if an agg needs them

    out = {}
    for k, c, lo, rng, st in zip(group_keys, key_cols, los, ranges, strides):
        vals = lo + (present // st) % rng
        if c.is_string:
            out[k] = Column(STRING, vals.astype(np.int32), c.dictionary, None)
        else:
            out[k] = Column(c.dtype, vals.astype(c.data.dtype), None, None)

    # Per-column memo of (valid-cell ids, valid counts): count(v)+sum(v)+avg(v)
    # over one nullable column must not pay three O(n) mask gathers and
    # full-cells bincounts for the same answer.
    nv_cache: dict = {}

    def _valid_stats(col_name, valid):
        if valid is None:
            return gid0, counts_p
        if col_name not in nv_cache:
            g = gid0[valid]
            nv_cache[col_name] = (g, np.bincount(g, minlength=cells)[present])
        return nv_cache[col_name]

    for out_name, fn, col_name in aggs:
        col = table.column(col_name) if col_name is not None else None
        dtype = result_dtype(fn, None if col is None else col.dtype)
        if fn == "count" and col is None:
            out[out_name] = _out_column(fn, col, dtype, counts_p, None)
            continue
        valid = col.validity
        if fn == "count":
            _, nv = _valid_stats(col_name, valid)
            out[out_name] = _out_column(fn, col, dtype, nv, None)
            continue
        if fn == "count_distinct":
            if remap is None:
                remap = np.full(cells, -1, np.int64)
                remap[present] = np.arange(n_groups)
            v = valid if valid is not None else np.ones(n, bool)
            vals = _count_distinct_per_group(remap[gid0], col, v, n_groups)
            out[out_name] = _out_column(fn, col, dtype, vals, None)
            continue
        # sum / avg
        g, nv = _valid_stats(col_name, valid)
        any_valid = nv > 0
        data = col.data
        if np.issubdtype(data.dtype, np.floating):
            w = data.astype(np.float64)
            if valid is not None:
                w = w[valid]
            s = np.bincount(g, weights=w, minlength=cells)[present]
        else:
            # Exact int64 accumulation (bincount weights are float64 and
            # would round sums past 2^53).
            acc = data.astype(np.int64)
            if valid is not None:
                acc = acc[valid]
            s = np.zeros(cells, np.int64)
            np.add.at(s, g, acc)
            s = s[present]
        if fn == "avg":
            s = s.astype(np.float64) / np.maximum(nv, 1)
        out[out_name] = _out_column(fn, col, dtype, s, any_valid)
    return Table(out)


def hash_aggregate(table: Table, group_keys, aggs: Sequence[AggTriple]) -> Table:
    """GROUP BY `group_keys` computing `aggs` = [(out_name, fn, column|None)]."""
    group_keys = list(group_keys)
    if not group_keys:
        return _global_aggregate(table, aggs)
    key_cols = [table.column(k) for k in group_keys]
    if table.num_rows == 0:
        return _empty_result(table, group_keys, aggs)

    n = table.num_rows
    from .backend import use_device_path

    device = use_device_path()
    if not device:
        direct = _direct_host_aggregate(table, group_keys, key_cols, aggs)
        if direct is not None:
            return direct
    from ..engine.encoded_device import stage_codes

    arrs = [stage_codes(c, "agg_keys") for c in key_cols]
    k64 = key64(key_cols, arrs)

    # Group boundaries from ADJACENT ACTUAL VALUES (+ validity), never the
    # hash. ONE host-side lane list (data [+ validity] per key column); the
    # device branch stages each lane through the memoized upload cache
    # (string keys as narrow codes — adjacent equality is value-preserving
    # under narrowing), the host branch consumes the flat lanes directly.
    flat_host = []
    has_valid = []
    flat_dev = [] if device else None
    for c in key_cols:
        flat_host.append(c.data)
        if device:
            flat_dev.append(stage_codes(c, "agg_keys"))
        has_valid.append(c.validity is not None)
        if c.validity is not None:
            flat_host.append(c.validity)
            if device:
                flat_dev.append(device_array(c.validity))
    if device:
        # One fused program for sort + boundary detection + group ids: each
        # eager op is a dispatch, and on the axon relay a round-trip.
        perm, boundary, gid = _group_ids_fused(
            tuple(has_valid), k64, *flat_dev
        )
        n_groups = int(gid[-1]) + 1
        seg_rows = jax.ops.segment_sum(
            jnp.ones(n, jnp.int64), gid, num_segments=n_groups
        )
        perm_np = starts_np = seg_rows_np = None
    else:
        # Host argsort beats XLA-CPU's sort, and the boundary pipeline runs on
        # the HOST key arrays directly (same body, xp=np) — eager jnp ops here
        # are CPU device round-trips per operator. The reductions stay on host
        # too (`_segment_reduce_host`): round-tripping the payload columns
        # through XLA-CPU's segment ops cost ~1.9 s of the 8M aggregate.
        from .join import stable_argsort_host

        perm_np = stable_argsort_host(k64)
        boundary, gid = _group_ids_body(tuple(has_valid), perm_np, flat_host, xp=np)
        perm = perm_np
        starts_np = np.nonzero(boundary)[0]
        n_groups = len(starts_np)
        seg_rows_np = np.diff(np.append(starts_np, n))
    gid_of_row = None
    reduced = []
    for out_name, fn, col_name in aggs:
        col = table.column(col_name) if col_name is not None else None
        dtype = result_dtype(fn, None if col is None else col.dtype)
        if fn == "count_distinct":
            if device:
                # The group-id program already ran on device: keep the distinct
                # dedup there too (sort-adjacent + first-of-run counting on
                # actual values) instead of pulling gid/perm and the column to
                # the host. The host path below stays the pinned oracle.
                has_v = col.validity is not None
                if getattr(col, "is_string", False):
                    from ..engine.encoded_device import widen_for_gather

                    # Narrow/packed staging is distinctness-preserving; widen
                    # back so the jitted program keeps ONE int32 compile class.
                    args = (widen_for_gather(stage_codes(col, "agg_distinct")),)
                else:
                    args = (device_array(col.data),)
                if has_v:
                    args = args + (device_array(col.validity),)
                vals = np.asarray(
                    _count_distinct_dev_jit(int(n_groups), has_v, gid, perm, *args)
                )
                reduced.append((out_name, fn, col, dtype, vals, None))
                continue
            # Exact distinct: dedupe (group, value) pairs on host (same exactness
            # contract as the collision-repair path).
            if gid_of_row is None:
                gid_of_row = np.empty(n, np.int64)
                gid_of_row[np.asarray(perm)] = np.asarray(gid)
            valid = (
                col.validity if col.validity is not None else np.ones(n, bool)
            )
            vals = _count_distinct_per_group(gid_of_row, col, valid, n_groups)
            reduced.append((out_name, fn, col, dtype, vals, None))
            continue
        if device:
            vals, validity = _segment_reduce(fn, col, gid, perm, n_groups, seg_rows)
        else:
            vals, n_valid = _segment_reduce_host(
                fn, col, perm_np, starts_np, seg_rows_np
            )
            validity = None if fn == "count" else n_valid > 0
        reduced.append((out_name, fn, col, dtype, vals, validity))

    # Representative row of each group → materialize the key columns on host.
    reps = (
        perm_np[starts_np]
        if not device
        else np.asarray(perm)[np.nonzero(np.asarray(boundary))[0]]
    )
    rep_rows = table.take(reps).select(group_keys)
    if len(np.unique(_key_records(rep_rows, group_keys))) != n_groups:
        # 64-bit collision interleaved two tuples in one sorted run: recompute
        # exactly on host (rarity ~2^-64; correctness over speed).
        return _host_aggregate(table, group_keys, aggs)

    out = {}
    for k in group_keys:
        out[k] = rep_rows.column(k)
    for out_name, fn, col, dtype, vals, validity in reduced:
        out[out_name] = _out_column(fn, col, dtype, vals, validity)
    return Table(out)


# ---------------------------------------------------------------------------
# Streaming chunk-carry aggregation (the read-side pipeline's reduce stage)
# ---------------------------------------------------------------------------

#: Aggregate functions the chunk-carry stream supports. count_distinct is
#: excluded by design: its state is a per-group value SET, not a scalar.
STREAMING_AGG_FNS = ("count", "sum", "avg", "min", "max")

_STATE_PREFIX = "__hs_"


def streaming_agg_supported(group_keys, aggs: Sequence[AggTriple]) -> bool:
    """Whether this GROUP BY shape can run as a chunk-carry stream: grouped
    (global aggregates keep the one-pass host path), scalar-state functions
    only, and no group key colliding with the internal state-column names."""
    if not group_keys:
        return False
    if any(fn not in STREAMING_AGG_FNS for _, fn, _ in aggs):
        return False
    return not any(str(k).startswith(_STATE_PREFIX) for k in group_keys)


def _pow2_ceil(n: int) -> int:
    return 1 << max(n - 1, 1).bit_length()


def direct_stream_hint(key_cols, aggs: Sequence[AggTriple]):
    """Direct-address layout hint for a `StreamAggregator` whose chunks are
    GATHERS of known source columns (the streamed join→aggregate): when the
    SOURCE key columns qualify for the direct-address layout, every chunk of
    gathered values is guaranteed inside the source's value ranges, so the
    stream can accumulate straight into one dense cell array — no per-chunk
    state tables and no record-keyed carry merge at all. Returns
    (los, ranges, strides, cells, key_meta) or None; key_meta reconstructs
    the output key columns from cell ids exactly like
    `_direct_host_aggregate` does."""
    layout = _direct_layout(key_cols, aggs)
    if layout is None:
        return None
    los, ranges, _datas, strides, cells = layout
    key_meta = [
        (c.dtype, c.dictionary if c.is_string else None, c.data.dtype)
        for c in key_cols
    ]
    return los, ranges, strides, cells, key_meta


def _pad_repeat_first(a: np.ndarray, cap: int) -> np.ndarray:
    """Pad to `cap` rows by REPEATING the first row: pad slots join a real
    group (they duplicate real key values) and are masked out of every
    reduction by the row-validity lane — the same padding contract as the
    fused join→aggregate's compacted pair arrays."""
    if len(a) == cap:
        return a
    return np.concatenate([a, np.broadcast_to(a[:1], (cap - len(a),))])


# Per-arity compiled reducers (the arity is the flat lane count; donation
# wants a static argnum tuple, so each arity gets its own jitted wrapper).
_STREAM_REDUCE_FNS: dict = {}


def _stream_reduce_fn(n_flat: int, donate: bool):
    """ALL of one chunk's segment reductions as ONE compiled program, with a
    row-validity lane ANDed into every aggregate (pad slots and, when a
    caller fuses a filter, masked-out rows contribute nothing). `n_seg` is
    pow2-quantized by the caller so growing group counts share programs.
    With `donate`, the one-shot staged chunk lanes (and gid/perm/row_valid)
    are donated so XLA reuses their buffers across chunks."""
    key = (n_flat, donate)
    fn = _STREAM_REDUCE_FNS.get(key)
    if fn is not None:
        return fn

    def body(specs, n_seg, gid, perm, row_valid, *flat):
        out = []
        i = 0
        for sfn, has_valid in specs:
            x = flat[i]
            i += 1
            v = row_valid
            if has_valid:
                v = flat[i] & row_valid
                i += 1
            out.extend(_seg_reduce_body(sfn, n_seg, True, gid, perm, x, v))
        return tuple(out)

    donate_argnums = tuple(range(2, 5 + n_flat)) if donate else ()
    fn = _observed_jit(
        body,
        label="aggregate.stream_reduce",
        static_argnums=(0, 1),
        donate_argnums=donate_argnums,
    )
    _STREAM_REDUCE_FNS[key] = fn
    return fn


class StreamAggregator:
    """Chunk-carry GROUP BY: feed table chunks with `add_chunk`, read the
    final aggregate with `finalize`.

    Each chunk reduces to per-group PARTIAL STATES through the same machinery
    the one-pass `hash_aggregate` uses (key64 hash-sort, adjacent-ACTUAL-value
    boundaries, segment reductions — host `reduceat` on the CPU backend, the
    fused jitted programs on the device path with pow2-quantized chunk shapes
    and donated staging buffers). States are (value, n_valid) pairs — avg
    carries (sum, count) — packaged as a small state TABLE (group keys + state
    columns), and carried states merge by exact key records (`_key_records`
    over the concatenated state tables, so string codes re-encode through
    union dictionaries and a 64-bit hash collision can never merge two
    groups). Merging is deferred until pending partials outgrow the carry
    (compaction), which keeps memory bounded without re-sorting the carry per
    chunk; the left-to-right chunk fold order is preserved regardless of
    compaction cadence, so results do not depend on prefetch/thread counts.

    Float sum/avg accumulate per chunk then across chunks, which reorders the
    additions relative to the one-pass path — results match it exactly for
    integer/count/min/max outputs and to float-associativity rounding for
    float sums (docs/query-pipeline.md).

    `finalize` emits groups in the one-pass path's output order: the
    direct-address cell order when `hash_aggregate`'s host fast path would
    have taken it (`_direct_layout` on the carried keys reproduces the same
    decision), else ascending key64."""

    def __init__(
        self, group_keys, aggs: Sequence[AggTriple], stages=None, direct_hint=None
    ):
        self.group_keys = list(group_keys)
        self.aggs = [tuple(a) for a in aggs]
        if not streaming_agg_supported(self.group_keys, self.aggs):
            raise HyperspaceException("aggregate shape not streamable")
        self._stages = stages
        self._carry: Optional[Table] = None
        self._pending: list = []
        self._pending_rows = 0
        self._in_dtypes: list = [None] * len(self.aggs)
        self.chunks = 0
        self.rows = 0
        # Direct-address cells mode (`direct_stream_hint`): dense accumulators
        # over the hinted cell space replace the state-table carry entirely.
        self._direct = direct_hint
        self._dcounts: Optional[np.ndarray] = None
        self._dstates: Optional[list] = None

    def _timed(self, stage: str):
        if self._stages is None:
            import contextlib

            return contextlib.nullcontext()
        return self._stages.timed(stage)

    # -- per-chunk partial ---------------------------------------------------

    def add_chunk(self, t: Table) -> None:
        # Dtype tracking BEFORE the empty-chunk return: a mixed-width source
        # whose wider-typed file is empty (or fully filtered) still promotes
        # in the one-pass path's concat, so it must promote here too.
        for i, (_out, fn, cname) in enumerate(self.aggs):
            if cname is not None:
                # Track the PROMOTED input dtype across chunks — a mixed-width
                # multi-file source promotes in the one-pass path's concat, so
                # the streamed result dtype must promote identically.
                cur = self._in_dtypes[i]
                new = t.column(cname).dtype
                if cur is None or cur == new or STRING in (cur, new):
                    self._in_dtypes[i] = new if cur is None else cur
                else:
                    from ..engine.schema import dtype_from_numpy

                    self._in_dtypes[i] = dtype_from_numpy(
                        np.promote_types(np.dtype(cur), np.dtype(new))
                    )
        if t.num_rows == 0:
            return
        if self._direct is not None:
            with self._timed("partial"):
                self._add_chunk_direct(t)
            self.chunks += 1
            self.rows += t.num_rows
            return
        from .backend import use_device_path

        with self._timed("partial"):
            partial = (
                self._partial_device(t) if use_device_path() else self._partial_host(t)
            )
        self.chunks += 1
        self.rows += t.num_rows
        self._pending.append(partial)
        self._pending_rows += partial.num_rows
        carry_rows = self._carry.num_rows if self._carry is not None else 0
        if self._pending_rows >= max(1 << 16, carry_rows):
            with self._timed("merge"):
                self._compact()

    def _direct_gid(self, t: Table) -> np.ndarray:
        los, _ranges, strides, _cells, _meta = self._direct
        gid0 = np.zeros(t.num_rows, np.int64)
        for k, lo, st in zip(self.group_keys, los, strides):
            c = t.column(k)
            if c.validity is not None:
                # The hint promised null-free source keys; a null here means
                # the chunks are NOT gathers of the hinted columns — fail
                # loudly rather than mis-bin.
                raise HyperspaceException("direct-hint chunk carries null keys")
            data = c.data
            if data.dtype == np.bool_:
                data = data.astype(np.int64)
            gid0 += (data.astype(np.int64) - lo) * st
        return gid0

    def _add_chunk_direct(self, t: Table) -> None:
        """Chunk fold in direct-address cells mode: one bincount pass per
        aggregate into persistent dense accumulators — the streamed twin of
        `_direct_host_aggregate`'s passes, with identical per-cell arithmetic
        (exact int64 sums; float64 bincount sums to associativity rounding)."""
        cells = self._direct[3]
        gid0 = self._direct_gid(t)
        if self._dcounts is None:
            self._dcounts = np.zeros(cells, np.int64)
            self._dstates = [
                [None, None] for _ in self.aggs
            ]  # per agg: [nv_cells, val_cells]
        self._dcounts += np.bincount(gid0, minlength=cells)
        for i, (_out, fn, cname) in enumerate(self.aggs):
            col = t.column(cname) if cname is not None else None
            state = self._dstates[i]
            if fn == "count" and col is None:
                continue  # count(*) reads self._dcounts
            valid = col.validity
            if valid is None:
                # All rows valid: the per-cell valid counts stay derivable
                # from _dcounts until some chunk introduces nulls.
                if state[0] is not None:
                    state[0] += np.bincount(gid0, minlength=cells)
            else:
                if state[0] is None:
                    # First null-bearing chunk: every earlier chunk was
                    # all-valid, so their per-cell valid counts equal the row
                    # counts accumulated so far minus THIS chunk's rows
                    # (_dcounts already folded it above).
                    state[0] = self._dcounts - np.bincount(gid0, minlength=cells)
                state[0] += np.bincount(gid0[valid], minlength=cells)
            if fn == "count":
                continue
            data = col.data
            g = gid0
            if valid is not None:
                data, g = data[valid], g[valid]
            if np.issubdtype(data.dtype, np.floating):
                s = np.bincount(g, weights=data.astype(np.float64), minlength=cells)
            else:
                # Exact int64 accumulation (bincount weights are float64 and
                # would round sums past 2^53).
                s = np.zeros(cells, np.int64)
                np.add.at(s, g, data.astype(np.int64))
            if state[1] is None:
                state[1] = s
            else:
                if state[1].dtype != s.dtype:
                    common = np.promote_types(state[1].dtype, s.dtype)
                    state[1] = state[1].astype(common)
                    s = s.astype(common)
                state[1] += s

    def _finalize_direct(self) -> Optional[Table]:
        if self._dcounts is None or self.rows == 0:
            return None
        los, ranges_, strides, _cells, key_meta = self._direct
        present = np.nonzero(self._dcounts)[0]
        counts_p = self._dcounts[present]
        out = {}
        for k, (dtype, dictionary, np_dtype), lo, rng, st in zip(
            self.group_keys, key_meta, los, ranges_, strides
        ):
            vals = lo + (present // st) % rng
            if dtype == STRING:
                out[k] = Column(STRING, vals.astype(np.int32), dictionary, None)
            else:
                out[k] = Column(dtype, vals.astype(np_dtype), None, None)
        for i, (out_name, fn, cname) in enumerate(self.aggs):
            dtype = result_dtype(fn, self._in_dtypes[i])
            nv_cells, val_cells = self._dstates[i]
            nv = counts_p if nv_cells is None else nv_cells[present]
            if fn == "count":
                out[out_name] = _out_column(fn, None, dtype, nv, None)
                continue
            vals = val_cells[present]
            if fn == "avg":
                vals = vals.astype(np.float64) / np.maximum(nv, 1)
            out[out_name] = _out_column(fn, None, dtype, vals, nv > 0)
        return Table(out)

    def _state_table(self, rep_keys: Table, states: list) -> Table:
        """Assemble the state-layout table: group keys + per-agg value/count
        columns (value codes of all-null groups clamped to 0 so string state
        columns always index their dictionaries)."""
        out = dict(rep_keys.columns)
        for i, (vals_col, n_valid) in enumerate(states):
            if vals_col is not None:
                out[f"{_STATE_PREFIX}v{i}"] = vals_col
            out[f"{_STATE_PREFIX}n{i}"] = Column(
                INT64, np.asarray(n_valid, np.int64).copy()
            )
        return Table(out)

    def _pack_state_col(
        self, fn: str, vals: np.ndarray, n_valid: np.ndarray, dictionary
    ) -> Column:
        anyv = n_valid > 0
        if dictionary is not None:
            codes = np.where(anyv, vals, 0).astype(np.int32)
            return Column(STRING, codes, dictionary, anyv.copy())
        data = np.where(anyv, vals, np.zeros((), dtype=np.asarray(vals).dtype))
        from ..engine.schema import dtype_from_numpy

        return Column(dtype_from_numpy(data.dtype), data, None, anyv.copy())

    def _partial_host(self, t: Table) -> Table:
        from .join import stable_argsort_host

        n = t.num_rows
        key_cols = [t.column(k) for k in self.group_keys]
        layout = _direct_layout(key_cols, self.aggs)
        if layout is not None:
            # Bounded-range keys: the chunk partial is a handful of bincount
            # passes instead of a per-chunk hash-sort — the same trade
            # `_direct_host_aggregate` makes for the one-pass path.
            return self._partial_host_direct(t, key_cols, layout)
        from ..engine.encoded_device import stage_codes

        k64 = key64(key_cols, [stage_codes(c, "agg_keys") for c in key_cols])
        perm = stable_argsort_host(k64)
        flat_host, has_valid = [], []
        for c in key_cols:
            flat_host.append(c.data)
            has_valid.append(c.validity is not None)
            if c.validity is not None:
                flat_host.append(c.validity)
        _boundary, gid = _group_ids_body(tuple(has_valid), perm, flat_host, xp=np)
        starts = np.nonzero(_boundary)[0]
        seg_rows = np.diff(np.append(starts, n))
        rep_keys = t.select(self.group_keys).take(perm[starts])
        states = []
        for _out, fn, cname in self.aggs:
            col = t.column(cname) if cname is not None else None
            sfn = "sum" if fn == "avg" else fn
            vals, n_valid = _segment_reduce_host(sfn, col, perm, starts, seg_rows)
            if fn == "count":
                states.append((None, n_valid))
                continue
            states.append(
                (
                    self._pack_state_col(
                        fn, vals, n_valid, col.dictionary if col.is_string else None
                    ),
                    n_valid,
                )
            )
        return self._state_table(rep_keys, states)

    def _partial_host_direct(self, t: Table, key_cols, layout) -> Table:
        """Direct-address chunk partial: dense mixed-radix cells + bincount
        reductions (`_direct_layout` already proved eligibility: null-free
        bounded int/bool/dictionary keys, no min/max). State contract is
        identical to the sort-based partial; only the internal group order of
        the partial differs, which the record-keyed merge erases."""
        n = t.num_rows
        los, ranges, datas, strides, cells = layout
        gid0 = np.zeros(n, np.int64)
        for data, lo, st in zip(datas, los, strides):
            gid0 += (data.astype(np.int64) - lo) * st
        counts = np.bincount(gid0, minlength=cells)
        present = np.nonzero(counts)[0]
        counts_p = counts[present].astype(np.int64)

        rep_cols = {}
        for k, c, lo, rng, st in zip(
            self.group_keys, key_cols, los, ranges, strides
        ):
            vals = lo + (present // st) % rng
            if c.is_string:
                rep_cols[k] = Column(
                    STRING, vals.astype(np.int32), c.dictionary, None
                )
            else:
                rep_cols[k] = Column(c.dtype, vals.astype(c.data.dtype), None, None)

        states = []
        for _out, fn, cname in self.aggs:
            col = t.column(cname) if cname is not None else None
            if fn == "count" and col is None:
                states.append((None, counts_p))
                continue
            valid = col.validity
            if valid is None:
                nv = counts_p
            else:
                nv = np.bincount(gid0[valid], minlength=cells)[present].astype(
                    np.int64
                )
            if fn == "count":
                states.append((None, nv))
                continue
            # sum / avg state (avg carries its sum): exact int64 accumulation
            # for ints (bincount weights are float64 and would round past
            # 2^53), float64 bincount for floats.
            data = col.data
            if np.issubdtype(data.dtype, np.floating):
                w = data.astype(np.float64)
                g = gid0
                if valid is not None:
                    w, g = w[valid], g[valid]
                s = np.bincount(g, weights=w, minlength=cells)[present]
            else:
                acc = data.astype(np.int64)
                g = gid0
                if valid is not None:
                    acc, g = acc[valid], g[valid]
                s = np.zeros(cells, np.int64)
                np.add.at(s, g, acc)
                s = s[present]
            states.append((self._pack_state_col(fn, s, nv, None), nv))
        return self._state_table(Table(rep_cols), states)

    def _partial_device(self, t: Table) -> Table:
        """Device twin of `_partial_host`: pow2-padded staged lanes, the fused
        group-id program, then every reduction in one compiled (and
        buffer-donating, off-CPU) program quantized to pow2 segment counts."""
        n = t.num_rows
        cap = _pow2_ceil(n)
        staged_bytes = [0, 0]  # [payload, pow2 padding] across all lanes

        def _stage(host_arr):
            # One pow2-padded H2D staging lane; the split feeds the padding
            # ledger once all lanes are up (`pad.agg_partials.*`).
            sz = int(np.asarray(host_arr).dtype.itemsize)
            staged_bytes[0] += n * sz
            staged_bytes[1] += (cap - n) * sz
            return jax.device_put(_pad_repeat_first(host_arr, cap))

        key_cols = [t.column(k) for k in self.group_keys]
        from ..engine.encoded_device import column_qualifies, narrow_codes

        enc_split = [0, 0]  # [flat, staged] bytes of narrowed key lanes

        def _key_lane(c):
            # Qualifying string keys stage as narrow codes; the rep
            # materialization below widens back to int32 before any Column
            # is built, and key64/group boundaries are value-preserving.
            if column_qualifies(c):
                narrow = narrow_codes(c)
                if narrow is not c.data:
                    enc_split[0] += int(c.data.nbytes)
                    enc_split[1] += int(narrow.nbytes)
                    return narrow
            return c.data

        staged_keys = [_stage(_key_lane(c)) for c in key_cols]
        k64 = key64(key_cols, staged_keys)
        flat, has_valid = [], []
        staged_valid = []
        for c, arr in zip(key_cols, staged_keys):
            flat.append(arr)
            has_valid.append(c.validity is not None)
            if c.validity is not None:
                sv = _stage(c.validity)
                staged_valid.append(sv)
                flat.append(sv)
            else:
                staged_valid.append(None)
        perm, boundary, gid = _group_ids_fused(tuple(has_valid), k64, *flat)
        n_groups = int(gid[-1]) + 1  # the one scalar sync per chunk
        n_seg = _pow2_ceil(n_groups)
        rep_rows = perm[jnp.nonzero(boundary, size=n_seg, fill_value=0)[0]]

        # Representative key rows (gathered BEFORE the reduce so its donated
        # buffers are never read afterwards).
        rep_cols = {}
        for k, c, arr, sv in zip(
            self.group_keys, key_cols, staged_keys, staged_valid
        ):
            data = _devobs.to_host(arr[rep_rows])[:n_groups]
            v = (
                None
                if sv is None
                else _devobs.to_host(sv[rep_rows])
                .astype(bool, copy=False)[:n_groups]
                .copy()
            )
            if c.is_string:
                codes = data.astype(np.int32)
                if v is not None:
                    codes = np.where(v, codes, 0).astype(np.int32)
                rep_cols[k] = Column(STRING, codes, c.dictionary, v)
            else:
                if v is not None:
                    data = np.where(v, data, np.zeros((), dtype=data.dtype))
                rep_cols[k] = Column(c.dtype, data.astype(c.data.dtype), None, v)

        specs, lanes = [], []
        for _out, fn, cname in self.aggs:
            col = t.column(cname) if cname is not None else None
            sfn = "sum" if fn == "avg" else fn
            if fn == "count" and col is None:
                # count(*): the row-validity lane IS the data.
                specs.append(("count", False))
                lanes.append(jnp.zeros(cap, jnp.int32))
                continue
            specs.append((sfn, col.validity is not None))
            lanes.append(_stage(col.data))
            if col.validity is not None:
                lanes.append(_stage(col.validity))
        _devobs.record_pad("agg_partials", staged_bytes[0], staged_bytes[1])
        _devobs.record_h2d(staged_bytes[0] + staged_bytes[1])
        if enc_split[1]:
            _devobs.record_encoded_stage("agg_partials", enc_split[0], enc_split[1])
        row_valid = jnp.arange(cap) < n
        donate = jax.default_backend() != "cpu"
        results = jax.device_get(
            _stream_reduce_fn(len(lanes), donate)(
                tuple(specs), n_seg, gid, perm, row_valid, *lanes
            )
        )
        _devobs.record_d2h(
            sum(int(getattr(r, "nbytes", 0) or 0) for r in results)
        )
        states = []
        for i, (_out, fn, cname) in enumerate(self.aggs):
            vals = np.asarray(results[2 * i])[:n_groups]
            n_valid = np.asarray(results[2 * i + 1])[:n_groups]
            if fn == "count":
                states.append((None, n_valid))
                continue
            col = t.column(cname)
            states.append(
                (
                    self._pack_state_col(
                        fn, vals, n_valid, col.dictionary if col.is_string else None
                    ),
                    n_valid,
                )
            )
        return self._state_table(Table(rep_cols), states)

    # -- carry merge ---------------------------------------------------------

    def _compact(self) -> None:
        parts = ([self._carry] if self._carry is not None else []) + self._pending
        self._pending = []
        self._pending_rows = 0
        if not parts:
            return
        if len(parts) == 1:
            self._carry = parts[0]
            return
        # Concat re-encodes string keys AND string min/max states over union
        # dictionaries, so codes are comparable across chunks.
        pt = Table.concat(parts)
        recs = _key_records(pt, self.group_keys)
        uniq, first_idx, inverse = np.unique(
            recs, return_index=True, return_inverse=True
        )
        n_groups = len(uniq)
        out = dict(pt.select(self.group_keys).take(first_idx).columns)
        for i, (_out, fn, _cname) in enumerate(self.aggs):
            contrib = pt.column(f"{_STATE_PREFIX}n{i}").data
            nv = np.zeros(n_groups, np.int64)
            np.add.at(nv, inverse, contrib)
            out[f"{_STATE_PREFIX}n{i}"] = Column(INT64, nv)
            if fn == "count":
                continue
            vcol = pt.column(f"{_STATE_PREFIX}v{i}")
            mask = contrib > 0
            sfn = "sum" if fn == "avg" else fn
            if sfn == "sum":
                acc = np.zeros(n_groups, vcol.data.dtype)
                # np.add.at folds in row order (carry first, then chunks in
                # arrival order) — the float fold stays left-to-right across
                # any compaction cadence.
                np.add.at(acc, inverse[mask], vcol.data[mask])
            else:
                acc = np.full(
                    n_groups,
                    _minmax_fill(vcol.data.dtype, sfn),
                    vcol.data.dtype,
                )
                op = np.minimum if sfn == "min" else np.maximum
                op.at(acc, inverse[mask], vcol.data[mask])
            out[f"{_STATE_PREFIX}v{i}"] = self._pack_state_col(
                fn, acc, nv, vcol.dictionary if vcol.is_string else None
            )
        self._carry = Table(out)

    # -- finalize ------------------------------------------------------------

    def _output_order(self, key_cols) -> np.ndarray:
        """Group output order of the ONE-PASS path: the direct-address cell
        order when its host fast path would have applied (the carried keys
        reproduce the same layout decision), ascending key64 otherwise."""
        from .backend import use_device_path

        if not use_device_path():
            layout = _direct_layout(key_cols, self.aggs)
            if layout is not None:
                los, _ranges, datas, strides, _cells = layout
                gid0 = np.zeros(len(key_cols[0]), np.int64)
                for data, lo, st in zip(datas, los, strides):
                    gid0 += (data.astype(np.int64) - lo) * st
                return np.argsort(gid0, kind="stable")
        from ..engine.encoded_device import stage_codes

        k64 = np.asarray(
            key64(key_cols, [stage_codes(c, "agg_keys") for c in key_cols])
        )
        return np.argsort(k64, kind="stable")

    def finalize(self) -> Optional[Table]:
        """The aggregate over everything streamed so far; None when no chunk
        carried rows (the caller owns the empty-input result shape)."""
        if self._direct is not None:
            with self._timed("finalize"):
                return self._finalize_direct()
        with self._timed("merge"):
            self._compact()
        if self._carry is None:
            return None
        carry = self._carry
        key_cols = [carry.column(k) for k in self.group_keys]
        with self._timed("finalize"):
            order = self._output_order(key_cols)
            out = {}
            for k in self.group_keys:
                out[k] = carry.column(k).take(order)
            for i, (out_name, fn, _cname) in enumerate(self.aggs):
                nv = carry.column(f"{_STATE_PREFIX}n{i}").data[order]
                dtype = result_dtype(fn, self._in_dtypes[i])
                if fn == "count":
                    out[out_name] = _out_column(fn, None, dtype, nv, None)
                    continue
                vcol = carry.column(f"{_STATE_PREFIX}v{i}").take(order)
                vals = vcol.data
                if fn == "avg":
                    vals = vals.astype(np.float64) / np.maximum(nv, 1)
                out[out_name] = _out_column(fn, vcol, dtype, vals, nv > 0)
        return Table(out)
