"""Pallas TPU kernel for the in-bucket sort (hash-mode padded reps).

`bucket_join._pad_and_sort` sorts each padded bucket row with `jnp.argsort`
(XLA variadic sort): at round-4 bench shapes that was the DOMINANT device
kernel (pad+sort 5.49 s vs the probe's 1.15 s at 8M rows) — a bitonic network
whose every stage round-trips HBM. This kernel keeps a whole [TB, cap] bucket
group resident in VMEM and runs the complete bitonic network in one
`pallas_call` — a single HBM read + write per element regardless of the
network's O(log² cap) stages. That trade only exists while the block fits
VMEM, so the dispatcher gates on cap (pow2 by construction — `_cap_pow2`).

Formulation: compare-exchange at stride j is a reshape to [TB, m, 2, j] —
lane-local slicing, no gathers (partner i^j sits at [..., 1, :] of the pair
axis). Keys are 64-bit, pre-split OUTSIDE the kernel into the same
lexicographic (hi, lo) int32 pair the probe kernel uses (no 64-bit values on
the VPU; no 64-bit bitcasts for the relay's X64-elimination to reject). The
row-index payload rides the exchanges, so the kernel returns both sorted keys
and the argsort permutation in one pass.

Bitonic networks are NOT stable; equal keys land in arbitrary order. That is
sound here by the same argument as hash collisions: the probe emits the whole
equal-key RANGE and verification compares actual values, so any permutation
within an equal run yields the identical pair set.

Equivalence with `jnp.argsort` is pinned by tests/test_pallas_sort.py
(interpret mode off-TPU); the guarded dispatcher falls back to the XLA path
on any lowering failure, scoped with the same latch discipline as the probe.
"""

from __future__ import annotations

import os
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_ENV_KEY = "HYPERSPACE_PALLAS_SORT"
# VMEM budget: 3 int32 payloads (hi, lo, idx) x in/out + temps. TB=8 rows of
# cap=32768 is ~6 MB — comfortable; 65536 doubles it and starts crowding
# double-buffering, so the gate stops at 32768.
_MAX_CAP = 32768
_MIN_CAP = 256  # below this the dispatch overhead beats any fusion win
_sort_broken: dict = {}  # scoped latch (single kind: "sort")
_fallback_counts: dict = {}  # diverted-dispatch counter after a latch

from ..telemetry import metrics as _metrics
from ..telemetry.compile_log import observed_jit as _observed_jit

# Bound once: incremented on every diverted dispatch after a latch.
_FALLBACK_METRIC = _metrics.counter("pallas.sort.fallbacks")


def pallas_fallback_stats() -> dict:
    """Session counters of sort-kernel fallbacks (see the probe twin): how
    many sorts were diverted after a failure latched, and the first error.
    Empty when the kernel never failed."""
    if not _sort_broken and not _fallback_counts:
        return {}
    return {
        "failures": dict(_fallback_counts),
        "errors": dict(_sort_broken),
    }


def _pairs_gt(ah, al, bh, bl):
    """64-bit (hi, lo) lexicographic signed compare: a > b."""
    return (ah > bh) | ((ah == bh) & (al > bl))


def _bitonic_body(h, l, idx):
    """The full bitonic network over the LAST axis of [TB, cap] arrays,
    python-unrolled (cap is static): O(log² cap) reshape/where stages."""
    tb, n = h.shape
    k = 2
    while k <= n:
        j = k // 2
        while j >= 1:
            m = n // (2 * j)
            h4 = h.reshape(tb, m, 2, j)
            l4 = l.reshape(tb, m, 2, j)
            i4 = idx.reshape(tb, m, 2, j)
            ah, bh = h4[:, :, 0, :], h4[:, :, 1, :]
            al, bl = l4[:, :, 0, :], l4[:, :, 1, :]
            ai, bi = i4[:, :, 0, :], i4[:, :, 1, :]
            # Direction of the pair's bitonic run: bit log2(k) of the lower
            # element's global position g*2j (t < j never reaches that bit).
            g = jax.lax.broadcasted_iota(jnp.int32, (tb, m, 1, j), 1)
            desc = ((g * (2 * j)) & k) > 0
            desc = desc[:, :, 0, :]
            swap = _pairs_gt(ah, al, bh, bl) != desc
            nah = jnp.where(swap, bh, ah)
            nbh = jnp.where(swap, ah, bh)
            nal = jnp.where(swap, bl, al)
            nbl = jnp.where(swap, al, bl)
            nai = jnp.where(swap, bi, ai)
            nbi = jnp.where(swap, ai, bi)
            h = jnp.stack([nah, nbh], axis=2).reshape(tb, n)
            l = jnp.stack([nal, nbl], axis=2).reshape(tb, n)
            idx = jnp.stack([nai, nbi], axis=2).reshape(tb, n)
            j //= 2
        k *= 2
    return h, l, idx


def _sort_kernel(h_ref, l_ref, i_ref, ho_ref, lo_ref, io_ref):
    h, l, idx = _bitonic_body(h_ref[...], l_ref[...], i_ref[...])
    ho_ref[...] = h
    lo_ref[...] = l
    io_ref[...] = idx


def _bucket_tile(B: int) -> int:
    """Same legality rule as the probe kernel: 8-row groups when divisible,
    whole axis otherwise (equal-to-dimension)."""
    return 8 if B % 8 == 0 else B


def shape_supported(B: int, cap: int) -> bool:
    if B <= 0 or cap < _MIN_CAP or cap > _MAX_CAP:
        return False
    if cap & (cap - 1):
        return False  # bitonic needs pow2 (guaranteed by _cap_pow2 upstream)
    tb = _bucket_tile(B)
    if tb > 8 and B > 8:
        return False  # whole-axis block beyond 8 rows would blow VMEM
    return True


@_observed_jit(label="pallas.sort", static_argnums=(3,))
def _sort_pallas_call(hi, lo, idx, interpret: bool):
    B, cap = hi.shape
    TB = _bucket_tile(B)
    grid = (B // TB,)
    spec = pl.BlockSpec((TB, cap), lambda b: (b, 0))
    return pl.pallas_call(
        _sort_kernel,
        grid=grid,
        in_specs=[spec, spec, spec],
        out_specs=[spec, spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, cap), jnp.int32),
            jax.ShapeDtypeStruct((B, cap), jnp.int32),
            jax.ShapeDtypeStruct((B, cap), jnp.int32),
        ],
        interpret=interpret,
    )(hi, lo, idx)


@_observed_jit(label="pallas.sort_recombine")
def _recombine(hi, lo):
    """(hi, lo) int32 pair → the original int64 keys (undo `_split_hi_lo`)."""
    h = hi.astype(jnp.int64) << 32
    l = (lo.astype(jnp.int64) + jnp.int64(0x80000000)) & jnp.int64(0xFFFFFFFF)
    return h | l


def sort_padded_with_order(keys_i64) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Drop-in replacement for the argsort+gather inside `_pad_and_sort`:
    returns (sorted_keys int64 [B, cap], order int32 [B, cap]) where
    `sorted[b, s] == keys[b, order[b, s]]`. Equal keys may permute (bitonic
    is unstable) — sound for the join, see the module docstring."""
    from .pallas_probe import _split_hi_lo

    keys_i64 = jnp.asarray(keys_i64)
    B, cap = keys_i64.shape
    hi, lo = _split_hi_lo(keys_i64)
    idx = jnp.broadcast_to(jnp.arange(cap, dtype=jnp.int32)[None, :], (B, cap))
    interpret = jax.default_backend() != "tpu"
    sh, sl, order = _sort_pallas_call(hi, lo, idx, interpret)
    return _recombine(sh, sl), order


# --- sort on PACKED sub-byte code words --------------------------------------
#
# Sub-byte dictionary codes (`engine/packed_codes.py`) don't need the 64-bit
# (hi, lo, idx) triple: a biased code (< 16) and its slot index (< cap <=
# 32768) TOGETHER fit one int32 composite, comp = (code << log2 cap) | slot.
# Comps are UNIQUE (slot bits), so the unstable bitonic reproduces the STABLE
# argsort of the code matrix exactly — and the network moves one int32 lane
# instead of three, a third of the VMEM traffic of `_sort_kernel`. The kernel
# reads the packed WORD matrix from HBM (bits-per-code traffic) and unpacks
# in VMEM.


def _bitonic_body_single(v):
    """`_bitonic_body` specialised to ONE int32 lane (the composite): same
    reshape/where network, a third of the exchanged state."""
    tb, n = v.shape
    k = 2
    while k <= n:
        j = k // 2
        while j >= 1:
            m = n // (2 * j)
            v4 = v.reshape(tb, m, 2, j)
            a, b = v4[:, :, 0, :], v4[:, :, 1, :]
            g = jax.lax.broadcasted_iota(jnp.int32, (tb, m, 1, j), 1)
            desc = ((g * (2 * j)) & k) > 0
            desc = desc[:, :, 0, :]
            swap = (a > b) != desc
            na = jnp.where(swap, b, a)
            nb = jnp.where(swap, a, b)
            v = jnp.stack([na, nb], axis=2).reshape(tb, n)
            j //= 2
        k *= 2
    return v


def _sort_packed_kernel(w_ref, o_ref, *, bits, log2cap):
    from .pallas_probe import _unpack_words_block

    lanes = _unpack_words_block(w_ref[...], bits)  # [TB, cap] biased int32
    slot = jax.lax.broadcasted_iota(jnp.int32, lanes.shape, 1)
    comp = (lanes << log2cap) | slot
    o_ref[...] = _bitonic_body_single(comp)


@_observed_jit(label="pallas.sort_packed", static_argnums=(1, 2))
def _sort_packed_call(words, bits: int, interpret: bool):
    import functools

    B, n_words = words.shape
    lpw = 32 // bits
    cap = n_words * lpw
    assert cap & (cap - 1) == 0, cap
    TB = _bucket_tile(B)
    in_spec = pl.BlockSpec((TB, n_words), lambda b: (b, 0))
    out_spec = pl.BlockSpec((TB, cap), lambda b: (b, 0))
    kern = functools.partial(
        _sort_packed_kernel, bits=bits, log2cap=cap.bit_length() - 1
    )
    return pl.pallas_call(
        kern,
        grid=(B // TB,),
        in_specs=[in_spec],
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((B, cap), jnp.int32),
        interpret=interpret,
    )(words)


def sort_codes_packed(words, bits: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sort each padded bucket row of a packed BIASED-code word matrix:
    (sorted_biased_codes int32 [B, cap], order int32 [B, cap]) with
    `sorted[b, s] == codes[b, order[b, s]]`. Requires pad slots packed as the
    top lane value (2**bits - 1 — `probe_bits_for_cardinality` keeps it above
    every real biased code), so pads sort last like the int64 path's pad key.
    Matches `jnp.argsort` EXACTLY including ties (comp uniqueness => stable)."""
    words = jnp.asarray(words)
    cap = words.shape[1] * (32 // bits)
    comp = _sort_packed_call(words, bits, jax.default_backend() != "tpu")
    return comp >> (cap.bit_length() - 1), comp & (cap - 1)


def _sort_comp_kernel(v_ref, o_ref):
    o_ref[...] = _bitonic_body_single(v_ref[...])


@_observed_jit(label="pallas.sort_comp", static_argnums=(1,))
def sort_comp_padded(v, interpret: bool):
    """Single-lane int32 bitonic over [B, cap] composite rows (build-side
    bucket|code|row composites — `partition.pallas_packed_build_sort`). The
    caller owns the composite encoding; this just sorts rows ascending."""
    B, cap = v.shape
    TB = _bucket_tile(B)
    spec = pl.BlockSpec((TB, cap), lambda b: (b, 0))
    return pl.pallas_call(
        _sort_comp_kernel,
        grid=(B // TB,),
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((B, cap), jnp.int32),
        interpret=interpret,
    )(v)


def pallas_packed_sort_wanted(B: int, cap: int, bits: int) -> bool:
    """Gate for the packed-word sort: the ordinary sort gate plus whole-word
    rows. Shares the single "sort" latch — both variants lower the same
    bitonic network, so a Mosaic failure in one predicts the other."""
    if cap % (32 // bits):
        return False
    return pallas_sort_wanted(B, cap)


def pallas_sort_wanted(B: int, cap: int) -> bool:
    """Dispatch decision: forced by env (1/0), else auto on TPU within the
    VMEM shape budget. Any lowering failure latches a permanent fallback
    (scoped to the sort; the validated probe kernel is unaffected)."""
    if "sort" in _sort_broken:
        _fallback_counts["sort"] = _fallback_counts.get("sort", 0) + 1
        _FALLBACK_METRIC.inc()
        return False
    mode = os.environ.get(_ENV_KEY, "auto")
    if mode == "0":
        return False
    if not shape_supported(B, cap):
        return False
    if mode == "1":
        return True
    return jax.default_backend() == "tpu"


def record_sort_failure(exc: BaseException) -> None:
    import logging

    _sort_broken["sort"] = f"{type(exc).__name__}: {exc}"
    _fallback_counts["sort"] = _fallback_counts.get("sort", 0) + 1
    _FALLBACK_METRIC.inc()
    logging.getLogger("hyperspace_tpu.ops").warning(
        "pallas sort failed; falling back to the XLA sort permanently: %s",
        _sort_broken["sort"],
    )
