"""Non-inner join execution: left/right/full outer, semi, anti.

The reference's rules skip ineligible joins but Spark still executes them; the
engine must do the same — an outer-join query with hyperspace enabled runs
unindexed instead of erroring (r1 VERDICT item 7). Null join keys follow SQL outer
semantics: they never match, so their rows surface as unmatched."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace


@pytest.fixture()
def jsession(tmp_path):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    os.makedirs(tmp_path / "l")
    pq.write_table(
        pa.table(
            {
                "k": pa.array([1, 2, 3, None], type=pa.int64()),
                "lv": pa.array(["a", "b", "c", "d"]),
            }
        ),
        str(tmp_path / "l" / "part-00000.parquet"),
    )
    os.makedirs(tmp_path / "r")
    pq.write_table(
        pa.table(
            {
                "k2": pa.array([2, 3, 3, 5, None], type=pa.int64()),
                "rv": pa.array([20, 30, 31, 50, 99], type=pa.int64()),
            }
        ),
        str(tmp_path / "r" / "part-00000.parquet"),
    )
    return s, str(tmp_path)


def _dfs(s, base):
    return (
        s.read.parquet(os.path.join(base, "l")),
        s.read.parquet(os.path.join(base, "r")),
    )


def test_left_outer(jsession):
    s, base = jsession
    l, r = _dfs(s, base)
    got = l.join(r, col("k") == col("k2"), how="left").select("lv", "rv").sorted_rows()
    assert got == sorted(
        [("a", None), ("b", 20), ("c", 30), ("c", 31), ("d", None)],
        key=lambda t: tuple(str(x) for x in t),
    )


def test_right_outer(jsession):
    s, base = jsession
    l, r = _dfs(s, base)
    got = l.join(r, col("k") == col("k2"), how="right").select("lv", "rv").sorted_rows()
    assert sorted(x for _, x in got) == sorted([20, 30, 31, 50, 99])
    assert (None, 50) in got and (None, 99) in got


def test_full_outer(jsession):
    s, base = jsession
    l, r = _dfs(s, base)
    got = l.join(r, col("k") == col("k2"), how="full").select("lv", "rv").sorted_rows()
    assert len(got) == 7  # 3 matches + 2 left-unmatched + 2 right-unmatched
    assert ("a", None) in got and ("d", None) in got
    assert (None, 50) in got and (None, 99) in got


def test_semi_and_anti(jsession):
    s, base = jsession
    l, r = _dfs(s, base)
    semi = l.join(r, col("k") == col("k2"), how="left_semi").select("lv").sorted_rows()
    assert semi == [("b",), ("c",)]
    anti = l.join(r, col("k") == col("k2"), how="left_anti").select("lv").sorted_rows()
    assert anti == [("a",), ("d",)]


def test_join_type_spellings(jsession):
    s, base = jsession
    l, r = _dfs(s, base)
    a = l.join(r, col("k") == col("k2"), how="leftouter").select("lv", "rv").sorted_rows()
    b = l.join(r, col("k") == col("k2"), how="LEFT_OUTER").select("lv", "rv").sorted_rows()
    assert a == b


def test_outer_join_rides_index_with_hyperspace_enabled(jsession):
    """The join rule rewrites ANY equi-join type — the reference's matcher is
    a type wildcard (`JoinIndexRule.scala:60`) — so the outer join rides the
    bucketed index scans shuffle-free, with identical results."""
    s, base = jsession
    hs = Hyperspace(s)
    l, r = _dfs(s, base)
    hs.create_index(l, IndexConfig("lIdx", ["k"], ["lv"]))
    hs.create_index(r, IndexConfig("rIdx", ["k2"], ["rv"]))
    l, r = _dfs(s, base)
    q = lambda: l.join(r, col("k") == col("k2"), how="left").select("lv", "rv")
    disable_hyperspace(s)
    expected = q().sorted_rows()
    enable_hyperspace(s)
    plan = q().explain_string()
    assert "bucketed, no exchange" in plan  # outer joins ride the index too
    got = q().sorted_rows()
    assert got == expected and len(got) == 5


def test_count_fast_path_matches_materialized_counts(jsession):
    """`count()` (footer/pair-count fast path) must equal collect().num_rows for
    every join type, null keys included."""
    s, base = jsession
    l = lambda: s.read.parquet(os.path.join(base, "l"))
    r = lambda: s.read.parquet(os.path.join(base, "r"))
    for how in ("inner", "left", "right", "full", "semi", "anti"):
        df = l().join(r(), col("k") == col("k2"), how=how)
        assert df.count() == df.collect().num_rows, how
    # plain scans + limit + orderby + union-ish shapes
    assert l().count() == l().collect().num_rows
    assert l().limit(2).count() == 2
    assert l().order_by("k").count() == l().count()


class TestIndexedNonInnerJoins:
    """The join rule rewrites ANY equi-join type (reference
    `JoinIndexRule.scala:60` matches `Join(l, r, _, Some(condition))` with a
    type wildcard): outer/semi/anti joins ride the covering-index bucketed
    scans shuffle-free, deriving their results from the verified inner pairs."""

    @pytest.fixture()
    def indexed_pair(self, tmp_path):
        session = HyperspaceSession(warehouse=str(tmp_path))
        session.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
        session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
        rng = np.random.RandomState(4)
        session.write_parquet(
            {
                "k": rng.randint(0, 50, 4000).astype(np.int64),
                "v": rng.randint(0, 1000, 4000).astype(np.int64),
            },
            str(tmp_path / "L"),
        )
        # Right keys: some never matched by the left (0..49), some unmatched.
        session.write_parquet(
            {
                "rk": np.arange(20, 70, dtype=np.int64),
                "w": np.arange(50, dtype=np.int64),
            },
            str(tmp_path / "R"),
        )
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(str(tmp_path / "L")),
            IndexConfig("niL", ["k"], ["v"]),
        )
        hs.create_index(
            session.read.parquet(str(tmp_path / "R")),
            IndexConfig("niR", ["rk"], ["w"]),
        )
        return session, str(tmp_path)

    @pytest.mark.parametrize("how", ["left", "right", "full", "left_semi", "left_anti"])
    def test_indexed_join_matches_oracle(self, indexed_pair, how):
        s, base = indexed_pair

        def q():
            l = s.read.parquet(os.path.join(base, "L"))
            r = s.read.parquet(os.path.join(base, "R"))
            return l.join(r, col("k") == col("rk"), how=how)

        disable_hyperspace(s)
        expected_rows = q().sorted_rows()
        expected_count = q().count()

        enable_hyperspace(s)
        plan = q().explain_string()
        assert "niL" in plan and "niR" in plan, plan
        assert "bucketed, no exchange" in plan, plan
        assert "ShuffleExchange" not in plan, plan
        assert q().count() == expected_count
        assert q().sorted_rows() == expected_rows


def test_bare_collect_never_leaks_lineage_columns(tmp_path):
    """With lineage enabled, an UNPROJECTED collect over an indexed join must
    show exactly the source schema — the index's internal `_data_file_name`
    (and its join-collision suffixes) must not leak (found by the mutation
    soak: the non-indexed oracle and the indexed plan disagreed on schema)."""
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    s.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
    s.write_parquet(
        {"k": np.arange(10, dtype=np.int64), "v": np.arange(10, dtype=np.int64)},
        str(tmp_path / "L"),
    )
    s.write_parquet(
        {"rk": np.arange(10, dtype=np.int64), "w": np.arange(10, dtype=np.int64)},
        str(tmp_path / "R"),
    )
    hs = Hyperspace(s)
    hs.create_index(s.read.parquet(str(tmp_path / "L")), IndexConfig("llx", ["k"], ["v"]))
    hs.create_index(s.read.parquet(str(tmp_path / "R")), IndexConfig("llr", ["rk"], ["w"]))
    enable_hyperspace(s)
    for how in ("inner", "left", "full"):
        q = s.read.parquet(str(tmp_path / "L")).join(
            s.read.parquet(str(tmp_path / "R")), col("k") == col("rk"), how=how
        )
        assert "llx" in q.explain_string()
        assert q.collect().column_names == ["k", "v", "rk", "w"], how
    # Reading the raw index data as a plain parquet source still exposes the
    # lineage column (it IS that relation's schema).
    raw = s.read.parquet(str(tmp_path / "indexes" / "llx" / "v__=0")).collect()
    assert any(c.lower() == "_data_file_name" for c in raw.column_names)


def test_union_over_delete_pruned_indexed_join(tmp_path):
    """Whole-table operators (union/intersect) above a delete-pruned indexed
    join: the prune filter strips its internal lineage column after
    evaluating, so the physical schema matches the logical union check
    (review finding: the hidden-column mismatch crashed UnionExec)."""
    from hyperspace_tpu.engine import io as eio
    from hyperspace_tpu.engine.table import Table
    from hyperspace_tpu.hyperspace import disable_hyperspace

    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    s.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
    s.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
    d = tmp_path / "L"
    eio.write_parquet(
        Table.from_pydict({"k": np.arange(6, dtype=np.int64), "v": np.arange(6, dtype=np.int64)}),
        str(d / "p0.parquet"),
    )
    eio.write_parquet(
        Table.from_pydict({"k": np.arange(6, 12, dtype=np.int64), "v": np.arange(6, 12, dtype=np.int64)}),
        str(d / "p1.parquet"),
    )
    s.write_parquet(
        {"rk": np.arange(12, dtype=np.int64), "w": np.arange(12, dtype=np.int64)},
        str(tmp_path / "R"),
    )
    hs = Hyperspace(s)
    hs.create_index(s.read.parquet(str(d)), IndexConfig("upl", ["k"], ["v"]))
    hs.create_index(s.read.parquet(str(tmp_path / "R")), IndexConfig("upr", ["rk"], ["w"]))
    os.remove(str(d / "p1.parquet"))  # forces the delete-prune filter
    s.write_parquet(
        {"k": np.array([100], dtype=np.int64), "v": np.array([100], dtype=np.int64),
         "rk": np.array([100], dtype=np.int64), "w": np.array([100], dtype=np.int64)},
        str(tmp_path / "other"),
    )
    enable_hyperspace(s)
    other = s.read.parquet(str(tmp_path / "other"))
    for how in ("inner", "left"):
        def j():
            return s.read.parquet(str(d)).join(
                s.read.parquet(str(tmp_path / "R")), col("k") == col("rk"), how=how
            )

        assert "upl" in j().explain_string()
        got = j().union(other).sorted_rows()
        assert j().union(other).collect().column_names == ["k", "v", "rk", "w"]
        disable_hyperspace(s)
        expected = j().union(other).sorted_rows()
        enable_hyperspace(s)
        assert got == expected
        assert j().intersect(j()).count() == j().distinct().count()
