"""Non-inner join execution: left/right/full outer, semi, anti.

The reference's rules skip ineligible joins but Spark still executes them; the
engine must do the same — an outer-join query with hyperspace enabled runs
unindexed instead of erroring (r1 VERDICT item 7). Null join keys follow SQL outer
semantics: they never match, so their rows surface as unmatched."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.hyperspace import Hyperspace, enable_hyperspace


@pytest.fixture()
def jsession(tmp_path):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    os.makedirs(tmp_path / "l")
    pq.write_table(
        pa.table(
            {
                "k": pa.array([1, 2, 3, None], type=pa.int64()),
                "lv": pa.array(["a", "b", "c", "d"]),
            }
        ),
        str(tmp_path / "l" / "part-00000.parquet"),
    )
    os.makedirs(tmp_path / "r")
    pq.write_table(
        pa.table(
            {
                "k2": pa.array([2, 3, 3, 5, None], type=pa.int64()),
                "rv": pa.array([20, 30, 31, 50, 99], type=pa.int64()),
            }
        ),
        str(tmp_path / "r" / "part-00000.parquet"),
    )
    return s, str(tmp_path)


def _dfs(s, base):
    return (
        s.read.parquet(os.path.join(base, "l")),
        s.read.parquet(os.path.join(base, "r")),
    )


def test_left_outer(jsession):
    s, base = jsession
    l, r = _dfs(s, base)
    got = l.join(r, col("k") == col("k2"), how="left").select("lv", "rv").sorted_rows()
    assert got == sorted(
        [("a", None), ("b", 20), ("c", 30), ("c", 31), ("d", None)],
        key=lambda t: tuple(str(x) for x in t),
    )


def test_right_outer(jsession):
    s, base = jsession
    l, r = _dfs(s, base)
    got = l.join(r, col("k") == col("k2"), how="right").select("lv", "rv").sorted_rows()
    assert sorted(x for _, x in got) == sorted([20, 30, 31, 50, 99])
    assert (None, 50) in got and (None, 99) in got


def test_full_outer(jsession):
    s, base = jsession
    l, r = _dfs(s, base)
    got = l.join(r, col("k") == col("k2"), how="full").select("lv", "rv").sorted_rows()
    assert len(got) == 7  # 3 matches + 2 left-unmatched + 2 right-unmatched
    assert ("a", None) in got and ("d", None) in got
    assert (None, 50) in got and (None, 99) in got


def test_semi_and_anti(jsession):
    s, base = jsession
    l, r = _dfs(s, base)
    semi = l.join(r, col("k") == col("k2"), how="left_semi").select("lv").sorted_rows()
    assert semi == [("b",), ("c",)]
    anti = l.join(r, col("k") == col("k2"), how="left_anti").select("lv").sorted_rows()
    assert anti == [("a",), ("d",)]


def test_join_type_spellings(jsession):
    s, base = jsession
    l, r = _dfs(s, base)
    a = l.join(r, col("k") == col("k2"), how="leftouter").select("lv", "rv").sorted_rows()
    b = l.join(r, col("k") == col("k2"), how="LEFT_OUTER").select("lv", "rv").sorted_rows()
    assert a == b


def test_outer_join_runs_with_hyperspace_enabled(jsession):
    """The covering-index rules must skip the outer join, not break it
    (reference FilterIndexRule.scala:74-78 'never break the user's query')."""
    s, base = jsession
    hs = Hyperspace(s)
    l, r = _dfs(s, base)
    hs.create_index(l, IndexConfig("lIdx", ["k"], ["lv"]))
    hs.create_index(r, IndexConfig("rIdx", ["k2"], ["rv"]))
    enable_hyperspace(s)
    l, r = _dfs(s, base)
    q = l.join(r, col("k") == col("k2"), how="left").select("lv", "rv")
    plan = q.explain_string()
    assert "bucketed, no exchange" not in plan  # rule correctly skipped
    got = q.sorted_rows()
    assert len(got) == 5

    # The inner join over the same data still uses both indexes.
    qi = l.join(r, col("k") == col("k2"), how="inner").select("lv", "rv")
    assert "bucketed, no exchange" in qi.explain_string()


def test_count_fast_path_matches_materialized_counts(jsession):
    """`count()` (footer/pair-count fast path) must equal collect().num_rows for
    every join type, null keys included."""
    s, base = jsession
    l = lambda: s.read.parquet(os.path.join(base, "l"))
    r = lambda: s.read.parquet(os.path.join(base, "r"))
    for how in ("inner", "left", "right", "full", "semi", "anti"):
        df = l().join(r(), col("k") == col("k2"), how=how)
        assert df.count() == df.collect().num_rows, how
    # plain scans + limit + orderby + union-ish shapes
    assert l().count() == l().collect().num_rows
    assert l().limit(2).count() == 2
    assert l().order_by("k").count() == l().count()
