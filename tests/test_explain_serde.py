"""Explain subsystem + plan serde + cache tests.

Mirrors reference `ExplainTest` (golden-ish assertions on explain output in display
modes), `PhysicalOperatorAnalyzerTest`, `BufferStreamTest`, `DisplayModeTest`,
`LogicalPlanSerDeTests` (round-trip), `IndexCacheTest`.
"""

import time

import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.hyperspace import Hyperspace, enable_hyperspace
from hyperspace_tpu.plananalysis import (
    BufferStream,
    ConsoleMode,
    HTMLMode,
    PlainTextMode,
    create_display_mode,
)
from hyperspace_tpu.serde import deserialize_plan, serialize_plan


@pytest.fixture()
def session(tmp_path):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    return s


SAMPLE = {
    "c1": ["a", "b", "c", "d"],
    "c2": [1, 2, 3, 4],
    "c3": ["x", "x", "y", "y"],
}


class TestDisplayModes:
    def test_plaintext_default_tags(self):
        from hyperspace_tpu.config import SessionConf

        m = PlainTextMode(SessionConf())
        assert m.highlight_tag == ("<----", "---->")

    def test_html_mode(self):
        from hyperspace_tpu.config import SessionConf

        m = HTMLMode(SessionConf())
        b = BufferStream(m)
        b.write_line("x").highlight("y")
        assert b.to_string() == '<pre>x<br/><b style="background: #ff9900">y</b></pre>'

    def test_tags_overridable_via_conf(self):
        from hyperspace_tpu.config import SessionConf

        conf = SessionConf()
        conf.set(IndexConstants.DISPLAY_MODE, "console")
        conf.set(IndexConstants.HIGHLIGHT_BEGIN_TAG, ">>")
        conf.set(IndexConstants.HIGHLIGHT_END_TAG, "<<")
        m = create_display_mode(conf)
        assert isinstance(m, ConsoleMode)
        assert m.highlight_tag == (">>", "<<")


class TestExplain:
    def test_explain_shows_diff_and_indexes_used(self, session, tmp_path):
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(df, IndexConfig("exIdx", ["c3"], ["c2"]))

        q = session.read.parquet(str(tmp_path / "t")).filter(col("c3") == "x").select("c2")
        out = []
        hs.explain(q, verbose=True, redirect=out.append)
        s = out[0]
        assert "Plan with indexes:" in s
        assert "Plan without indexes:" in s
        assert "exIdx" in s
        assert "<----" in s  # differing subtree highlighted
        assert "Physical operator stats:" in s
        # operator table counts the Scan in both columns
        assert "Scan" in s

    def test_explain_join_counts_eliminated_exchanges(self, session, tmp_path):
        session.write_parquet({"k": [1, 2, 3], "v": [1, 2, 3]}, str(tmp_path / "l"))
        session.write_parquet({"k2": [1, 2, 3], "w": [4, 5, 6]}, str(tmp_path / "r"))
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(str(tmp_path / "l")), IndexConfig("lIdx", ["k"], ["v"])
        )
        hs.create_index(
            session.read.parquet(str(tmp_path / "r")), IndexConfig("rIdx", ["k2"], ["w"])
        )
        l = session.read.parquet(str(tmp_path / "l"))
        r = session.read.parquet(str(tmp_path / "r"))
        q = l.join(r, col("k") == col("k2")).select("v", "w")
        out = []
        hs.explain(q, verbose=True, redirect=out.append)
        s = out[0]
        # ShuffleExchange: 2 disabled, 0 enabled, diff -2
        import re

        m = re.search(r"ShuffleExchange\s*\|\s*2\|\s*0\|\s*-2", s)
        assert m, s

    def test_explain_leaves_session_state(self, session, tmp_path):
        from hyperspace_tpu.hyperspace import is_hyperspace_enabled

        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(df, IndexConfig("stIdx", ["c3"], ["c2"]))
        q = session.read.parquet(str(tmp_path / "t")).filter(col("c3") == "x").select("c2")
        assert not is_hyperspace_enabled(session)
        hs.explain(q, redirect=lambda s: None)
        assert not is_hyperspace_enabled(session)
        enable_hyperspace(session)
        hs.explain(q, redirect=lambda s: None)
        assert is_hyperspace_enabled(session)


class TestPlanSerde:
    def test_roundtrip_filter_project(self, session, tmp_path):
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = (
            session.read.parquet(str(tmp_path / "t"))
            .filter((col("c2") > 1) & (col("c3") == "y"))
            .select("c1", "c2")
        )
        s = serialize_plan(df.plan)
        restored = deserialize_plan(s)
        assert restored.tree_string() == df.plan.tree_string()
        # restored plan executes identically
        from hyperspace_tpu.engine.session import DataFrame

        assert DataFrame(session, restored).sorted_rows() == df.sorted_rows()

    def test_roundtrip_join_with_bucketspec(self, session, tmp_path):
        session.write_parquet({"k": [1]}, str(tmp_path / "l"))
        session.write_parquet({"k2": [1]}, str(tmp_path / "r"))
        l = session.read.parquet(str(tmp_path / "l"))
        r = session.read.parquet(str(tmp_path / "r"))
        j = l.join(r, col("k") == col("k2"))
        restored = deserialize_plan(serialize_plan(j.plan))
        assert restored.tree_string() == j.plan.tree_string()

    def test_roundtrip_isnull_isin(self, session, tmp_path):
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = (
            session.read.parquet(str(tmp_path / "t"))
            .filter(col("c1").is_null() | ~col("c2").is_null() | col("c2").isin(1, 2))
            .select("c1")
        )
        restored = deserialize_plan(serialize_plan(df.plan))
        assert restored.tree_string() == df.plan.tree_string()

    def test_version_check(self):
        import base64
        import json

        from hyperspace_tpu import HyperspaceException

        bad = base64.b64encode(json.dumps({"version": "99", "plan": {}}).encode()).decode()
        with pytest.raises(HyperspaceException, match="version"):
            deserialize_plan(bad)


class TestCache:
    def test_ttl_and_mutation_clear(self, session, tmp_path):
        from hyperspace_tpu.index.collection_manager import CachingIndexCollectionManager

        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        mgr = CachingIndexCollectionManager(session)
        mgr.create(df, IndexConfig("cIdx", ["c3"], ["c2"]))
        first = mgr.get_indexes()
        assert [e.name for e in first] == ["cIdx"]
        # cached: poke the cache to prove reads come from it
        mgr._cache.set([])
        assert mgr.get_indexes() == []
        # mutation clears cache
        mgr.delete("cIdx")
        assert [e.state for e in mgr.get_indexes()] == ["DELETED"]
        # expiry clears
        session.conf.set(IndexConstants.INDEX_CACHE_EXPIRY_DURATION_SECONDS, 0)
        mgr._cache.set([])
        time.sleep(0.01)
        assert [e.name for e in mgr.get_indexes()] == ["cIdx"]


class TestCacheFactory:
    def test_pluggable_cache_injected_via_factory(self, session, tmp_path):
        """Cache trait + factory keyed by policy name (reference
        `IndexCacheFactory.scala:23-38`): a custom policy is selected by conf."""
        from hyperspace_tpu.index.collection_manager import (
            CachingIndexCollectionManager,
            IndexCache,
            IndexCacheFactory,
        )

        calls = {"get": 0, "set": 0, "clear": 0}

        class SpyCache(IndexCache):
            def __init__(self):
                self._entries = None

            def get(self):
                calls["get"] += 1
                return self._entries

            def set(self, entries):
                calls["set"] += 1
                self._entries = list(entries)

            def clear(self):
                calls["clear"] += 1
                self._entries = None

        IndexCacheFactory.register("SPY", lambda s: SpyCache())
        session.conf.set(IndexConstants.INDEX_CACHE_TYPE, "spy")
        session.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "idx"))
        mgr = CachingIndexCollectionManager(session)
        mgr.get_indexes()
        assert calls["get"] == 1 and calls["set"] == 1
        mgr.get_indexes()
        assert calls["get"] == 2 and calls["set"] == 1  # hit
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        mgr.create(
            session.read.parquet(str(tmp_path / "t")),
            __import__("hyperspace_tpu").IndexConfig("cIdx", ["c3"], ["c2"]),
        )
        assert calls["clear"] >= 1  # mutation cleared the injected cache

    def test_unknown_cache_type_raises(self, session):
        from hyperspace_tpu import HyperspaceException
        from hyperspace_tpu.index.collection_manager import IndexCacheFactory

        with pytest.raises(HyperspaceException, match="cache type"):
            IndexCacheFactory.create("NOPE", session)


def test_union_plan_round_trip(tmp_path):
    """UnionNode serde (publicly reachable via df.union)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu.engine import HyperspaceSession, col
    from hyperspace_tpu.serde.plan_serde import deserialize_plan, serialize_plan

    s = HyperspaceSession(warehouse=str(tmp_path))
    d = tmp_path / "t"
    d.mkdir()
    pq.write_table(
        pa.table({"k": pa.array([1, 2, 3], type=pa.int64())}),
        str(d / "part-0.parquet"),
    )
    df = s.read.parquet(str(d))
    plan = df.filter(col("k") > 1).union(df).plan
    rt = deserialize_plan(serialize_plan(plan))
    assert rt.tree_string() == plan.tree_string()
