"""Adaptive cost-based planner (`plananalysis.costmodel` + `planner`).

Pins the ISSUE-17 contracts:

- model decisions with no learned history reproduce today's defaults
  (byte-identical results planner-on vs planner-off);
- explicit env flags always win ("pinned") — the planner is never even
  consulted by a gate whose flag is set;
- `HYPERSPACE_PLANNER=0` is zero-cost-off: no cost-model work, no stat
  reads, a bounded number of env reads per query (the counting oracle);
- planner decisions never mint plan-fingerprint classes (only explicit env
  pins shape `flag_posture`);
- predicted-vs-actual self-correction: a measurably wrong model arm flips
  to the better arm within N queries and STAYS flipped across a store
  restart (re-fold from disk);
- the hash-quantize auto-gate routes through the planner decision and the
  chosen arm + measured wall land on the ledger/span;
- decisions + drift surface in `explain(analyze=True)` and hsreport.
"""

import glob
import importlib.util
import json
import os

import pytest

from hyperspace_tpu.engine import HyperspaceSession, streaming
from hyperspace_tpu.ops import hashing
from hyperspace_tpu.plananalysis import costmodel, planner
from hyperspace_tpu.plananalysis.fingerprint import plan_fingerprint
from hyperspace_tpu.telemetry import accounting, history

PLANNER_ENVS = (
    planner.ENV_PLANNER,
    planner.ENV_PLANNER_DIR,
    planner.ENV_MIN_SAMPLES,
    planner.ENV_DRIFT_X,
    costmodel.ENV_MEMCPY_GBPS,
    "HYPERSPACE_HISTORY",
    "HYPERSPACE_HISTORY_DIR",
    "HYPERSPACE_ACCOUNTING",
)


@pytest.fixture(autouse=True)
def _clean_planner(monkeypatch):
    for k in PLANNER_ENVS + tuple(costmodel.KNOB_ENV.values()):
        monkeypatch.delenv(k, raising=False)
    planner.reset()
    history.reset_stores()
    yield
    planner.reset()
    history.reset_stores()


@pytest.fixture()
def session(tmp_path):
    return HyperspaceSession(warehouse=str(tmp_path))


def _write_source(session, tmp_path, rows=600, name="t"):
    src = os.path.join(str(tmp_path), name)
    session.write_parquet(
        {
            "k": [i % 7 for i in range(rows)],
            "grp": [f"g{i % 5}" for i in range(rows)],
            "v": [float(i) for i in range(rows)],
        },
        src,
    )
    return src


def _agg(session, src):
    return session.read.parquet(src).group_by("k").agg(total=("v", "sum"))


# ---------------------------------------------------------------------------
# Decisions: model defaults, pins, ledger/span recording
# ---------------------------------------------------------------------------


class TestDecisions:
    def test_model_arms_match_todays_defaults(self, session, tmp_path):
        """With no learned history the model reproduces the env-flag
        defaults — the planner changes who decides, not (yet) what runs."""
        src = _write_source(session, tmp_path)
        os.environ["HYPERSPACE_ACCOUNTING"] = "1"
        try:
            _agg(session, src).collect()
        finally:
            del os.environ["HYPERSPACE_ACCOUNTING"]
        led = accounting.recent_ledgers()[-1].to_dict()
        p = led["planner"]
        assert set(p) >= set(costmodel.KNOBS)
        assert p["streaming"]["arm"] == "on"
        assert p["encoded_exec"]["arm"] == "on"
        assert p["packed_codes"]["arm"] == "on"
        assert p["pushdown"]["arm"] == "on"
        assert p["join_size_classes"]["arm"] == "on"
        assert p["chunk_rows"]["arm"] == str(streaming._DEFAULT_QUERY_CHUNK_ROWS)
        from hyperspace_tpu.ops.backend import use_device_path

        assert p["hash_quantize"]["arm"] == ("on" if use_device_path() else "off")
        for d in (p[k] for k in costmodel.KNOBS):
            assert d["source"] == "model"
            assert "predicted_s" in d and "predicted_alt_s" in d and "alt" in d
        # ledger close annotated predicted-vs-actual
        assert p["actual_wall_s"] > 0

    def test_explicit_flag_pins_and_gate_skips_planner(self, session, tmp_path, monkeypatch):
        """A set env flag wins at the gate WITHOUT consulting the planner,
        and the decision is recorded as pinned."""
        src = _write_source(session, tmp_path)
        expect = _agg(session, src).collect().to_pydict()
        for knob, env in costmodel.KNOB_ENV.items():
            monkeypatch.setenv(env, "4096" if knob in costmodel.INT_KNOBS else "1")

        def boom(knob):
            raise AssertionError(f"gate consulted planner for pinned {knob}")

        monkeypatch.setattr(planner, "decided_value", boom)
        got = _agg(session, src).collect().to_pydict()
        assert got == expect
        monkeypatch.setattr(planner, "decided_value", lambda k: None)
        os.environ["HYPERSPACE_ACCOUNTING"] = "1"
        try:
            _agg(session, src).collect()
        finally:
            del os.environ["HYPERSPACE_ACCOUNTING"]
        p = accounting.recent_ledgers()[-1].to_dict()["planner"]
        assert all(p[k]["source"] == "pinned" for k in costmodel.KNOBS)
        assert p["chunk_rows"]["arm"] == "4096"

    def test_pinned_zero_disables_through_gate(self, session, tmp_path, monkeypatch):
        src = _write_source(session, tmp_path)
        expect = _agg(session, src).collect().sorted_rows()
        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "0")
        monkeypatch.setenv("HYPERSPACE_ENCODED_EXEC", "0")
        assert _agg(session, src).collect().sorted_rows() == expect


# ---------------------------------------------------------------------------
# Satellite: zero-cost-off oracle
# ---------------------------------------------------------------------------


class TestZeroCostOff:
    def test_off_runs_no_model_and_no_stat_reads(self, session, tmp_path, monkeypatch):
        src = _write_source(session, tmp_path)
        monkeypatch.setenv(planner.ENV_PLANNER, "0")
        calls = {"stats": 0, "cal": 0, "store": 0}
        monkeypatch.setattr(
            costmodel, "collect_stats", lambda phys: calls.__setitem__("stats", calls["stats"] + 1)
        )
        monkeypatch.setattr(
            costmodel, "current_calibration", lambda: calls.__setitem__("cal", calls["cal"] + 1)
        )
        monkeypatch.setattr(
            planner, "_outcome_store", lambda: calls.__setitem__("store", calls["store"] + 1)
        )
        out = _agg(session, src).collect()
        assert out.num_rows == 7
        assert calls == {"stats": 0, "cal": 0, "store": 0}

    def test_off_bounded_env_reads(self, session, tmp_path, monkeypatch):
        """The whole off-path is planner_enabled() checks at plan time —
        never one per gate, never any on the row path."""
        src = _write_source(session, tmp_path)
        monkeypatch.setenv(planner.ENV_PLANNER, "0")
        _agg(session, src).collect()  # warm caches/compiles
        calls = {"n": 0}
        real = planner.planner_enabled

        def counted():
            calls["n"] += 1
            return real()

        monkeypatch.setattr(planner, "planner_enabled", counted)
        n_queries = 4
        for _ in range(n_queries):
            _agg(session, src).collect()
        # decide() + _attach_fingerprint() check once each per query.
        assert 0 < calls["n"] <= 2 * n_queries

    def test_rows_byte_identical_on_vs_off(self, session, tmp_path, monkeypatch):
        src = _write_source(session, tmp_path)
        on = _agg(session, src).collect()
        monkeypatch.setenv(planner.ENV_PLANNER, "0")
        off = _agg(session, src).collect()
        assert on.sorted_rows() == off.sorted_rows()
        assert {n: c.dtype for n, c in on.columns.items()} == {
            n: c.dtype for n, c in off.columns.items()
        }


# ---------------------------------------------------------------------------
# Satellite: planner decisions never mint fingerprint classes
# ---------------------------------------------------------------------------


class TestFingerprintStability:
    def test_decisions_do_not_change_fingerprint(self, session, tmp_path):
        src = _write_source(session, tmp_path)
        phys = _agg(session, src).physical_plan()
        base = plan_fingerprint(phys)
        for value in (True, False):
            pd = planner.PlanDecisions(
                None,
                {
                    "streaming": planner.Decision("streaming", value, not value, 0.0, 0.0, "model"),
                    "chunk_rows": planner.Decision("chunk_rows", 4096 if value else 512, 0, 0.0, 0.0, "model"),
                },
            )
            with planner.decisions_scope(pd):
                assert plan_fingerprint(phys) == base

    def test_rotating_decisions_one_ledger_class(self, session, tmp_path, monkeypatch):
        """E2E: queries whose planner-chosen arms rotate every run still land
        under ONE fingerprint class — only explicit env pins mint classes."""
        src = _write_source(session, tmp_path)
        seq = {"i": 0}
        real_estimate = costmodel.estimate

        def rotating(stats, cal):
            est = dict(real_estimate(stats, cal))
            flip = bool(seq["i"] % 2)
            seq["i"] += 1
            est["streaming"] = (flip, not flip, 0.0, 0.0)
            est["join_size_classes"] = (not flip, flip, 0.0, 0.0)
            return est

        monkeypatch.setattr(costmodel, "estimate", rotating)
        monkeypatch.setenv("HYPERSPACE_ACCOUNTING", "1")
        fps = set()
        for _ in range(4):
            _agg(session, src).collect()
            led = accounting.recent_ledgers()[-1].to_dict()
            fps.add(led.get("plan_fingerprint"))
        assert len(fps) == 1 and None not in fps
        assert seq["i"] >= 4  # the rotation really ran


# ---------------------------------------------------------------------------
# Satellite: predicted-vs-actual self-correction
# ---------------------------------------------------------------------------


def _bad_chunk_estimate(stats, cal):
    """Force a measurably wrong model arm: tiny chunks, priced cheap."""
    est = {k: (True, False, 0.0, 0.0) for k in costmodel.KNOBS}
    est["chunk_rows"] = (512, 4_000_000, 0.006, 0.006)
    est["hash_quantize"] = (False, True, 0.0, 0.0)
    return est


class TestSelfCorrection:
    def _loop(self, session, src, n, walls):
        """Decide + observe `n` queries; wall depends on the chosen arm
        (the synthetic 'tiny chunks are slow' workload)."""
        log = []
        for _ in range(n):
            phys = _agg(session, src).physical_plan()
            pd = planner.decide(phys, "fp-selfcorrect")
            d = pd.decisions["chunk_rows"]
            log.append((d.value, d.source))
            planner.observe(pd, walls[d.value])
        return log

    def test_wrong_arm_flips_and_survives_restart(self, session, tmp_path, monkeypatch):
        src = _write_source(session, tmp_path)
        store_dir = os.path.join(str(tmp_path), "planner-store")
        monkeypatch.setenv(planner.ENV_PLANNER_DIR, store_dir)
        monkeypatch.setenv(planner.ENV_MIN_SAMPLES, "2")
        monkeypatch.setenv(planner.ENV_DRIFT_X, "1.0")
        monkeypatch.setattr(costmodel, "estimate", _bad_chunk_estimate)
        walls = {512: 0.2, 4_000_000: 0.05}

        log = self._loop(session, src, 6, walls)
        # starts on the (wrong) model arm, drift triggers exploration of the
        # alternative, and the measured-better arm wins within N queries
        assert log[0] == (512, "model")
        assert ("explore" in {s for _, s in log})
        assert log[-1] == (4_000_000, "measured")

        # ...and the flipped arm is what gates actually execute with
        phys = _agg(session, src).physical_plan()
        pd = planner.decide(phys, "fp-selfcorrect")
        with planner.decisions_scope(pd):
            assert streaming.query_chunk_rows() == 4_000_000

        # restart: drop every in-memory store; decide re-folds from disk
        planner.reset()
        assert glob.glob(os.path.join(store_dir, "planner-*.jsonl"))
        log2 = self._loop(session, src, 1, walls)
        assert log2[0] == (4_000_000, "measured")

    def test_no_learning_without_persistent_home(self, session, tmp_path, monkeypatch):
        """No HYPERSPACE_PLANNER_DIR and no history store -> pure model
        (no files written anywhere, decisions stay on the model arm)."""
        src = _write_source(session, tmp_path)
        monkeypatch.setattr(costmodel, "estimate", _bad_chunk_estimate)
        walls = {512: 0.2, 4_000_000: 0.05}
        log = self._loop(session, src, 5, walls)
        assert all(v == 512 and s == "model" for v, s in log)

    def test_history_dir_sidecar_default(self, session, tmp_path, monkeypatch):
        """With history on (and no explicit planner dir) outcomes persist in
        the `<history_dir>/planner` sidecar."""
        hdir = os.path.join(str(tmp_path), "hist")
        monkeypatch.setenv("HYPERSPACE_HISTORY", "1")
        monkeypatch.setenv("HYPERSPACE_HISTORY_DIR", hdir)
        assert planner.outcome_dir() == os.path.join(hdir, "planner")
        src = _write_source(session, tmp_path)
        phys = _agg(session, src).physical_plan()
        pd = planner.decide(phys, "fp-sidecar")
        planner.observe(pd, 0.01)
        assert glob.glob(os.path.join(hdir, "planner", "planner-*.jsonl"))

    def test_outcome_persistence_is_bounded(self, tmp_path, monkeypatch):
        store_dir = os.path.join(str(tmp_path), "store")
        monkeypatch.setenv(planner.ENV_PLANNER_DIR, store_dir)
        store = planner._outcome_store()
        for _ in range(planner._PERSIST_CAP + 20):
            store.observe("fp-cap", {"streaming": {"arm": "on", "wall_s": 0.01, "predicted_s": 0.0}})
        lines = []
        for f in glob.glob(os.path.join(store_dir, "planner-*.jsonl")):
            lines += open(f).read().splitlines()
        assert len(lines) == planner._PERSIST_CAP
        assert store.stat("fp-cap", "streaming", "on").n == planner._PERSIST_CAP + 20

    def test_explores_one_knob_at_a_time(self, tmp_path, monkeypatch, session):
        src = _write_source(session, tmp_path)
        monkeypatch.setenv(planner.ENV_PLANNER_DIR, os.path.join(str(tmp_path), "s"))
        monkeypatch.setenv(planner.ENV_MIN_SAMPLES, "1")
        monkeypatch.setenv(planner.ENV_DRIFT_X, "1.0")

        def two_drifting(stats, cal):
            est = {k: (True, False, 0.0, 0.0) for k in costmodel.KNOBS}
            est["streaming"] = (True, False, 0.01, 0.01)
            est["pushdown"] = (True, False, 0.01, 0.01)
            est["chunk_rows"] = (4_000_000, 4_000_000, 0.0, 0.0)
            est["hash_quantize"] = (False, True, 0.0, 0.0)
            return est

        monkeypatch.setattr(costmodel, "estimate", two_drifting)
        phys = _agg(session, src).physical_plan()
        pd = planner.decide(phys, "fp-onekn")
        planner.observe(pd, 0.5)  # huge drift on both knobs
        pd2 = planner.decide(phys, "fp-onekn")
        exploring = [k for k, d in pd2.decisions.items() if d.source == "explore"]
        assert len(exploring) == 1


# ---------------------------------------------------------------------------
# Satellite: the HASH_QUANTIZE auto-gate
# ---------------------------------------------------------------------------


class TestHashQuantizeGate:
    def test_unset_routes_through_decision(self):
        for arm in (True, False):
            pd = planner.PlanDecisions(
                None, {"hash_quantize": planner.Decision("hash_quantize", arm, not arm, 0.0, 0.0, "model")}
            )
            with planner.decisions_scope(pd):
                assert hashing._hash_quantize_enabled() is arm

    def test_unset_no_decision_keeps_device_heuristic(self):
        from hyperspace_tpu.ops.backend import use_device_path

        assert hashing._hash_quantize_enabled() == use_device_path()

    def test_env_pin_beats_decision(self, monkeypatch):
        monkeypatch.setenv(hashing.ENV_HASH_QUANTIZE, "0")
        pd = planner.PlanDecisions(
            None, {"hash_quantize": planner.Decision("hash_quantize", True, False, 0.0, 0.0, "model")}
        )
        with planner.decisions_scope(pd):
            assert hashing._hash_quantize_enabled() is False

    def test_arm_and_wall_on_ledger(self, session, tmp_path, monkeypatch):
        """The chosen arm + the measured wall are joined on the ledger — the
        45% CPU regression case is visible in hsreport either way."""
        src = _write_source(session, tmp_path)
        monkeypatch.setenv("HYPERSPACE_ACCOUNTING", "1")
        _agg(session, src).collect()
        p = accounting.recent_ledgers()[-1].to_dict()["planner"]
        assert p["hash_quantize"]["arm"] in ("on", "off")
        assert p["hash_quantize"]["source"] == "model"
        assert p["actual_wall_s"] > 0


# ---------------------------------------------------------------------------
# explain(analyze=True) + hsreport surfacing
# ---------------------------------------------------------------------------


class TestSurfacing:
    def test_explain_analyze_renders_every_knob(self, session, tmp_path):
        src = _write_source(session, tmp_path)
        txt = _agg(session, src).explain(analyze=True)
        assert "Planner:" in txt
        for knob in costmodel.KNOBS:
            assert f"{knob}:" in txt
        assert "predicted=" in txt and "[model]" in txt
        assert "actual wall=" in txt

    def test_explain_analyze_off_message(self, session, tmp_path, monkeypatch):
        monkeypatch.setenv(planner.ENV_PLANNER, "0")
        src = _write_source(session, tmp_path)
        txt = _agg(session, src).explain(analyze=True)
        assert "Planner:" in txt
        assert "env-flag defaults in force" in txt

    def test_hsreport_planner_table(self, session, tmp_path, monkeypatch):
        hdir = os.path.join(str(tmp_path), "hist")
        monkeypatch.setenv("HYPERSPACE_HISTORY", "1")
        monkeypatch.setenv("HYPERSPACE_HISTORY_DIR", hdir)
        monkeypatch.setenv("HYPERSPACE_ACCOUNTING", "1")
        src = _write_source(session, tmp_path)
        for _ in range(2):
            _agg(session, src).collect()
        path = os.path.join(os.path.dirname(__file__), "..", "tools", "hsreport.py")
        if not os.path.exists(path):
            pytest.skip("tools/hsreport.py not present (installed-wheel run)")
        spec = importlib.util.spec_from_file_location("hsreport", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        report = mod.build_report(hdir, top=10, recent_k=5)
        assert report["planner"], "planner table empty"
        row = report["planner"][0]
        assert {"fingerprint", "knob", "arm", "n", "mean_wall_s", "drift_x"} <= set(row)
        txt = mod.render(report)
        assert "planner decisions" in txt

    def test_ledger_json_roundtrips(self, session, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_ACCOUNTING", "1")
        src = _write_source(session, tmp_path)
        _agg(session, src).collect()
        d = accounting.recent_ledgers()[-1].to_dict()
        assert json.loads(json.dumps(d))["planner"]["streaming"]["arm"] == "on"


# ---------------------------------------------------------------------------
# Cost-model units
# ---------------------------------------------------------------------------


class TestCostModel:
    def test_calibration_env_override(self, monkeypatch):
        monkeypatch.setenv(costmodel.ENV_MEMCPY_GBPS, "12.5")
        cal = costmodel.current_calibration()
        assert cal.memcpy_gbps == 12.5 and cal.source == "env"

    def test_quantize_arms_follow_backend(self):
        st = costmodel.PlanStats(has_agg=True, rows=100_000, n_files=1, warm_files=1, decoded_bytes=10 << 20)
        host = costmodel.Calibration(device=False)
        dev = costmodel.Calibration(device=True, compile_s=0.5)
        mh = costmodel.estimate(st, host)["hash_quantize"]
        md = costmodel.estimate(st, dev)["hash_quantize"]
        assert mh[0] is False and mh[2] == 0.0  # host: off is free
        assert md[0] is True  # device: quantize (avoid per-shape compiles)
        assert md[3] >= 0.5  # alt arm pays the compile

    def test_chunk_shaping_requires_warm_large_scans(self):
        cal = costmodel.Calibration()
        small = costmodel.PlanStats(has_agg=True, n_files=1, warm_files=1, rows=10_000, decoded_bytes=1 << 20)
        assert costmodel.estimate(small, cal)["chunk_rows"][0] == 4_000_000
        big = costmodel.PlanStats(
            has_agg=True, n_files=1, warm_files=1, rows=16_000_000, decoded_bytes=8 << 30
        )
        shaped = costmodel.estimate(big, cal)["chunk_rows"][0]
        assert shaped < 4_000_000 and shaped >= costmodel._MIN_CHUNK_ROWS
        cold = costmodel.PlanStats(has_agg=True, n_files=2, warm_files=1, rows=16_000_000, decoded_bytes=8 << 30)
        assert costmodel.estimate(cold, cal)["chunk_rows"][0] == 4_000_000

    def test_collect_stats_walks_plan_without_io(self, session, tmp_path):
        src = _write_source(session, tmp_path)
        df = _agg(session, src)
        phys = df.physical_plan()
        import hyperspace_tpu.engine.io as engine_io

        def no_io(*a, **k):
            raise AssertionError("collect_stats must not parse footers")

        with pytest.MonkeyPatch.context() as mp:
            mp.setattr(engine_io, "footer_metadata", no_io)
            st = costmodel.collect_stats(phys)  # cold cache: warm peeks only
        assert st.n_files >= 1 and st.has_agg
        df.collect()  # warms the scan cache
        st2 = costmodel.collect_stats(phys)
        assert st2.warm_files == st2.n_files and st2.rows == 600

    def test_estimate_covers_every_knob(self):
        st = costmodel.PlanStats(has_agg=True, has_join=True, has_filter=True, n_files=1, warm_files=1, rows=1000, decoded_bytes=1 << 20)
        est = costmodel.estimate(st, costmodel.Calibration())
        assert set(est) == set(costmodel.KNOBS)
        for model_v, alt_v, pm, pa in est.values():
            assert pm >= 0.0 and pa >= 0.0


# ---------------------------------------------------------------------------
# Satellite (ISSUE 18): learned pushdown prune selectivity + the multiway knob
# ---------------------------------------------------------------------------


class TestPruneSelectivity:
    def _stats(self):
        return costmodel.PlanStats(
            has_agg=True,
            has_filter=True,
            n_files=1,
            warm_files=1,
            rows=1000,
            decoded_bytes=1 << 20,
        )

    def test_learned_selectivity_replaces_half_prune_prior(self):
        cal = costmodel.Calibration()
        static = costmodel.estimate(self._stats(), cal)["pushdown"]
        sharp = costmodel.estimate(
            self._stats(), cal, prune_selectivity=0.1
        )["pushdown"]
        blunt = costmodel.estimate(
            self._stats(), cal, prune_selectivity=1.0
        )["pushdown"]
        # ON-arm prediction scales with the measured scanned fraction; the
        # OFF arm (decode everything) never moves.
        assert sharp[2] < static[2] < blunt[2]
        assert sharp[3] == static[3] == blunt[3]
        assert blunt[2] == pytest.approx(blunt[3])  # never prunes -> no win
        # Out-of-range values clamp instead of corrupting the price.
        clamped = costmodel.estimate(
            self._stats(), cal, prune_selectivity=7.5
        )["pushdown"]
        assert clamped[2] == blunt[2]

    def test_store_folds_and_refolds_pruning_counters(self, tmp_path, monkeypatch):
        monkeypatch.setenv(planner.ENV_PLANNER_DIR, str(tmp_path / "ps"))
        store = planner._outcome_store()
        assert store.prune_selectivity("fp-sel") is None
        outcomes = {"pushdown": {"arm": "on", "wall_s": 0.01, "predicted_s": 0.01}}
        store.observe("fp-sel", outcomes, pruning=(2, 8))
        store.observe("fp-sel", outcomes, pruning=(0, 10))
        assert store.prune_selectivity("fp-sel") == pytest.approx(0.1)
        # Restart: the selectivity re-folds from the persisted JSONL records.
        planner.reset()
        store2 = planner._outcome_store()
        assert store2.prune_selectivity("fp-sel") == pytest.approx(0.1)
        # Malformed pruning payloads are ignored, never fatal.
        store2.observe("fp-sel", outcomes, pruning=("x", None))
        assert store2.prune_selectivity("fp-sel") == pytest.approx(0.1)

    def test_prune_counters_delta_clamped(self):
        base = planner.prune_counters()
        assert base is not None and len(base) == 2
        delta = planner.prune_counters(base)
        assert delta == (0, 0)
        assert planner.prune_counters((10**12, 10**12)) == (0, 0)

    def test_decided_query_records_pruning_delta(
        self, tmp_path, monkeypatch, session
    ):
        """End to end: a decided filtered query lands its row-group counter
        delta in the store, and the next decide prices pushdown from it."""
        monkeypatch.setenv(planner.ENV_PLANNER_DIR, str(tmp_path / "pe"))
        src = os.path.join(str(tmp_path), "pruned")
        # Bounded row groups + a selective range filter: the zone maps skip
        # most groups, so the io.pruning counters really move.
        session.write_parquet(
            {
                "k": [i % 7 for i in range(600)],
                "v": [float(i) for i in range(600)],
            },
            src,
            row_group_rows=100,
        )
        from hyperspace_tpu.engine import col

        def q():
            return (
                session.read.parquet(src)
                .filter(col("v") < 150.0)
                .group_by("k")
                .agg(t=("v", "sum"))
            )

        q().collect()  # cold: warms footers; may or may not prune yet
        q().collect()  # warm zone maps: pruning counters tick
        store = planner._outcome_store()
        fps = {fp for (fp, _k, _a) in store.summary()}
        sels = [store.prune_selectivity(fp) for fp in fps]
        learned = [s for s in sels if s is not None]
        assert learned and all(0.0 < s < 1.0 for s in learned)


class TestMultiwayKnob:
    def test_estimate_prices_star_plans(self):
        cal = costmodel.Calibration()
        flat = costmodel.estimate(costmodel.PlanStats(has_join=True), cal)
        assert flat["multiway"] == (True, False, 0.0, 0.0)
        st = costmodel.PlanStats(
            has_join=True, rows=1_000_000, decoded_bytes=50_000_000, star_dims=3
        )
        star = costmodel.estimate(st, cal)["multiway"]
        assert star[0] is True and star[2] > 0.0 and star[3] > 0.0
        # The cascade arm carries the intermediate-fact bytes: pricier than
        # the star arm's key64 probes at realistic row widths.
        assert star[3] > star[2]

    def test_collect_stats_sees_star_and_dedupes_relations(
        self, tmp_path, monkeypatch
    ):
        import numpy as np

        from hyperspace_tpu import IndexConfig, IndexConstants
        from hyperspace_tpu.engine import col
        from hyperspace_tpu.engine import physical as phys
        from hyperspace_tpu.hyperspace import Hyperspace, enable_hyperspace

        phys.clear_device_memos()
        s = HyperspaceSession(warehouse=str(tmp_path))
        s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "idx"))
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
        hs = Hyperspace(s)
        rng = np.random.RandomState(23)
        s.write_parquet(
            {
                "k1": rng.randint(0, 20, 400).astype(np.int64),
                "k2": rng.randint(0, 10, 400).astype(np.int64),
                "v": rng.randint(0, 9, 400).astype(np.int64),
            },
            str(tmp_path / "fact"),
        )
        s.write_parquet(
            {"d1": np.arange(20, dtype=np.int64), "g1": np.arange(20, dtype=np.int64)},
            str(tmp_path / "dim1"),
        )
        s.write_parquet(
            {"d2": np.arange(10, dtype=np.int64), "g2": np.arange(10, dtype=np.int64)},
            str(tmp_path / "dim2"),
        )
        hs.create_index(
            s.read.parquet(str(tmp_path / "dim1")), IndexConfig("mk1", ["d1"], ["g1"])
        )
        hs.create_index(
            s.read.parquet(str(tmp_path / "dim2")), IndexConfig("mk2", ["d2"], ["g2"])
        )
        enable_hyperspace(s)
        f = s.read.parquet(str(tmp_path / "fact"))
        d1 = s.read.parquet(str(tmp_path / "dim1"))
        d2 = s.read.parquet(str(tmp_path / "dim2"))
        plan = (
            f.join(d1, col("k1") == col("d1"))
            .join(d2, col("k2") == col("d2"))
            .group_by("g1")
            .agg(t=("v", "sum"))
        ).physical_plan()
        assert any(
            isinstance(n, phys.MultiwayJoinExec) for n in plan.collect_nodes()
        )
        st = costmodel.collect_stats(plan)
        assert st.has_join and st.star_dims == 2
        # The star exec's fact/dim children share relations with its cascade
        # child: the byte totals must count each relation once.
        n_rels = len(
            {
                id(n.relation)
                for n in plan.collect_nodes()
                if getattr(n, "relation", None) is not None
            }
        )
        assert st.n_scans == n_rels

    def test_multiway_env_pin_reported_not_decided(self, monkeypatch, session, tmp_path):
        src = _write_source(session, tmp_path)
        monkeypatch.setenv("HYPERSPACE_MULTIWAY", "0")
        pd = planner.decide(_agg(session, src).physical_plan(), "fp-mw")
        assert pd.decisions["multiway"].source == "pinned"
        assert pd.value("multiway") is None  # gates re-read the env flag
