"""Device cost observatory: per-program device-time probes, transfer and
padding ledgers, memory watermarks, and anomaly-triggered profile capture.

Pins the PR-14 contracts:
- ``HYPERSPACE_DEVICE_TIMING`` unset = zero cost: no probes, no
  ``latency.device.*`` series, exactly one env check per `observed_jit`
  call, and traced-vs-untraced results identical;
- probes bill dispatch→ready wall per label (``all`` = every call,
  sampled ``1`` = one probe per label per interval) and SKIP compiling
  calls — compile wall is the compile observatory's, not execute time;
- pad/transfer BYTE counters are always on (registry philosophy); SECONDS
  only appear under timing (they force a sync);
- the query ledger closes with ``device_time_s``/``host_time_s``,
  ``pad_ratio``, and ``device_live_bytes_age_s`` (the staleness of the
  shared 1 Hz device-bytes sample);
- pool workers adopt the submitting query's ledger (`use_ledger`), so
  chunk work on streamed-join threads bills the query, not nothing;
- profile capture is manifest-first (``capture.json`` parses the moment
  `maybe_capture` returns), rate-limited, keep-N rotated, and never
  overlaps trace windows (concurrent jax.profiler sessions crash).
"""

import json
import os
import threading

import numpy as np
import pytest

from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.telemetry import accounting, compile_log, metrics
from hyperspace_tpu.telemetry import device_observatory as dv


@pytest.fixture(autouse=True)
def _fresh_observatory(monkeypatch):
    monkeypatch.delenv(dv.ENV_DEVICE_TIMING, raising=False)
    monkeypatch.delenv(dv.ENV_PROFILE_DIR, raising=False)
    dv.reset()
    yield
    dv.reset()


@pytest.fixture()
def session(tmp_path):
    return HyperspaceSession(warehouse=str(tmp_path))


def _jnp():
    import jax.numpy as jnp

    return jnp


# ---------------------------------------------------------------------------
# Zero-cost-off oracle
# ---------------------------------------------------------------------------


class TestZeroCostOff:
    def test_no_probes_no_series_by_default(self):
        jnp = _jnp()
        f = compile_log.observed_jit(lambda x: x * 2, label="obs.test_off")
        f(jnp.arange(8))
        f(jnp.arange(8))
        assert dv.device_summary() == {}
        assert dv.probe_start("anything") is None
        hists = metrics.snapshot().get("histograms", {})
        assert not any(n.startswith("latency.device.") for n in hists)

    def test_exactly_one_env_check_per_call(self, monkeypatch):
        """The whole off-path cost of a probe is ONE timing_mode() read
        inside probe_start — nothing else on the observed_jit hot path
        touches the observatory."""
        jnp = _jnp()
        calls = {"n": 0}
        real = dv.timing_mode

        def counted():
            calls["n"] += 1
            return real()

        monkeypatch.setattr(dv, "timing_mode", counted)
        f = compile_log.observed_jit(lambda x: x + 3, label="obs.env_check")
        x = jnp.arange(4)
        f(x)  # compile
        calls["n"] = 0
        for _ in range(5):
            f(x)
        assert calls["n"] == 5

    def test_rows_identical_with_timing_on(self, session, tmp_path, monkeypatch):
        src = os.path.join(str(tmp_path), "t")
        session.write_parquet(
            {
                "k": list(range(400)),
                "grp": [i % 7 for i in range(400)],
                "v": [float(i) for i in range(400)],
            },
            src,
        )

        def q():
            return (
                session.read.parquet(src)
                .filter(col("k") > 50)
                .group_by("grp")
                .agg(s=("v", "sum"), n=("*", "count"))
                .collect()
                .sorted_rows()
            )

        off = q()
        monkeypatch.setenv(dv.ENV_DEVICE_TIMING, "all")
        monkeypatch.setenv(dv.ENV_TIMING_INTERVAL_S, "0")
        on = q()
        assert off == on


# ---------------------------------------------------------------------------
# Device-time probes
# ---------------------------------------------------------------------------


class TestProbes:
    def test_all_mode_bills_per_label_and_skips_compile(self, monkeypatch):
        monkeypatch.setenv(dv.ENV_DEVICE_TIMING, "all")
        jnp = _jnp()
        f = compile_log.observed_jit(lambda x: x + 1, label="obs.probe_all")
        x = jnp.arange(16)
        f(x)  # compile: traced, probe must NOT record
        f(x)
        f(x)
        summ = dv.device_summary()["obs.probe_all"]
        assert summ["calls"] == 2
        assert summ["device_s"] >= 0.0
        hists = metrics.snapshot().get("histograms", {})
        assert hists["latency.device.obs.probe_all"]["count"] == 2

    def test_sampled_mode_one_probe_per_interval(self, monkeypatch):
        jnp = _jnp()
        f = compile_log.observed_jit(lambda x: x - 1, label="obs.probe_sampled")
        x = jnp.arange(16)
        f(x)  # compile with timing OFF: no probe slot consumed
        monkeypatch.setenv(dv.ENV_DEVICE_TIMING, "1")
        monkeypatch.setenv(dv.ENV_TIMING_INTERVAL_S, "9999")
        for _ in range(5):
            f(x)
        assert dv.device_summary()["obs.probe_sampled"]["calls"] == 1

    def test_ledger_gets_device_host_split(self, monkeypatch):
        monkeypatch.setenv(dv.ENV_DEVICE_TIMING, "all")
        jnp = _jnp()
        f = compile_log.observed_jit(lambda x: x * 3, label="obs.probe_ledger")
        x = jnp.arange(32)
        f(x)  # compile outside the ledger
        with accounting.ledger_scope("qid-devsplit", "query:test"):
            f(x)
            f(x)
        led = accounting.ledger_for("qid-devsplit")
        assert led is not None
        d = led.to_dict()
        assert d["device_time_s"] > 0.0
        assert d["host_time_s"] >= 0.0
        assert d["host_time_s"] <= d["wall_s"]


# ---------------------------------------------------------------------------
# Padding + transfer ledgers (bytes always on)
# ---------------------------------------------------------------------------


class TestPadsAndTransfers:
    def test_record_pad_sites_and_ratio(self):
        c0 = metrics.counter("pad.bytes_padded").value
        dv.record_pad("site_x", 300, 100)
        dv.record_pad("site_x", 100, 0)
        s = dv.pad_summary()["site_x"]
        assert s["bytes_payload"] == 400
        assert s["bytes_padded"] == 100
        assert s["pad_ratio"] == 0.2
        assert metrics.counter("pad.bytes_padded").value == c0 + 100

    def test_hash_dictionary_records_pad_without_timing(self):
        from hyperspace_tpu.ops import hashing

        words = np.array([f"w{i:03d}" for i in range(100)], dtype=object)
        os.environ["HYPERSPACE_HASH_QUANTIZE"] = "1"
        try:
            hashing.host_hash_dictionary(words, seed=7)
        finally:
            os.environ.pop("HYPERSPACE_HASH_QUANTIZE", None)
        s = dv.pad_summary()
        assert "hash_dict" in s
        assert s["hash_dict"]["bytes_payload"] > 0

    def test_to_host_is_passthrough_for_numpy_and_records_d2h(self):
        a = np.arange(8)
        assert dv.to_host(a) is a
        jnp = _jnp()
        arr = jnp.arange(1024)
        before = dv.transfer_summary().get("d2h", {}).get("bytes", 0)
        out = dv.to_host(arr)
        assert isinstance(out, np.ndarray)
        assert dv.transfer_summary()["d2h"]["bytes"] >= before + arr.nbytes

    def test_device_cache_upload_records_h2d_and_gauge(self):
        from hyperspace_tpu.engine import device_cache

        host = np.random.RandomState(0).rand(4096)
        before = dv.transfer_summary().get("h2d", {}).get("bytes", 0)
        device_cache.device_array(host)
        after = dv.transfer_summary()["h2d"]
        assert after["bytes"] >= before + host.nbytes
        g = metrics.snapshot().get("gauges", {})
        assert g.get("cache.device_upload.bytes", 0) >= host.nbytes

    def test_transfer_seconds_only_under_timing(self, monkeypatch):
        jnp = _jnp()
        dv.to_host(jnp.arange(256))
        assert "seconds" not in dv.transfer_summary()["d2h"]
        monkeypatch.setenv(dv.ENV_DEVICE_TIMING, "all")
        dv.to_host(jnp.arange(256) * 2)
        t = dv.transfer_summary()["d2h"]
        assert t["seconds"] >= 0.0
        assert "gb_per_s" in t

    def test_ledger_pad_ratio(self):
        with accounting.ledger_scope("qid-padratio", "query:test"):
            dv.record_pad("site_y", 300, 100)
        d = accounting.ledger_for("qid-padratio").to_dict()
        assert d["pad_bytes_payload"] == 300
        assert d["pad_bytes_padded"] == 100
        assert d["pad_ratio"] == 0.25


# ---------------------------------------------------------------------------
# Memory watermarks + sample age
# ---------------------------------------------------------------------------


class TestWatermarks:
    def test_device_live_bytes_sample_reports_age(self, monkeypatch):
        # Clear the shared 1 Hz slot so THIS call takes a fresh reading.
        monkeypatch.setattr(accounting, "_device_sample", [-1e18, None, None])
        val, age = accounting.device_live_bytes_sample()
        if val is None:
            pytest.skip("backend exposes no live-bytes stats")
        assert age == 0.0  # fresh sample
        val2, age2 = accounting.device_live_bytes_sample()
        assert val2 == val  # rate-limited: reused reading...
        assert age2 >= 0.0  # ...with its honest age

    def test_ledger_close_attaches_sample_age(self):
        with accounting.ledger_scope("qid-age", "query:test"):
            pass
        d = accounting.ledger_for("qid-age").to_dict()
        if "device_live_bytes" in d:
            assert "device_live_bytes_age_s" in d
            assert d["device_live_bytes_age_s"] >= 0.0

    def test_memo_footprint_gauge_registered(self, session, tmp_path):
        src = os.path.join(str(tmp_path), "t")
        session.write_parquet({"k": list(range(64))}, src)
        session.read.parquet(src).filter(col("k") > 3).collect()
        g = metrics.snapshot().get("gauges", {})
        # Registered and consistent: the peak never lags the live value.
        if "memo.device_cache.bytes" in g:
            assert g["memo.device_cache.bytes_peak"] >= g["memo.device_cache.bytes"]
        if "cache.device_upload.bytes" in g:
            assert g["cache.device_upload.bytes_peak"] >= g["cache.device_upload.bytes"]


# ---------------------------------------------------------------------------
# Pool workers adopt the query ledger (streamed join chunks)
# ---------------------------------------------------------------------------


class TestPoolLedgerAdoption:
    def test_stream_join_workers_bill_the_query_ledger(self, tmp_path, monkeypatch):
        """Chunk work on the streamed-join pool must see the SUBMITTING
        query's ledger — without `use_ledger` adoption its compiles, pads,
        and device probes bill nothing."""
        from hyperspace_tpu import IndexConfig, IndexConstants
        from hyperspace_tpu.engine import physical
        from hyperspace_tpu.hyperspace import Hyperspace, enable_hyperspace

        physical.clear_device_memos()
        s = HyperspaceSession(warehouse=str(tmp_path))
        s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
        hs = Hyperspace(s)
        rng = np.random.RandomState(3)
        n = 9000
        s.write_parquet(
            {
                "k": rng.randint(0, 300, n).astype(np.int64),
                "v": rng.randint(0, 100, n).astype(np.int64),
            },
            str(tmp_path / "l"),
        )
        s.write_parquet(
            {
                "k2": np.arange(300, dtype=np.int64),
                "g": rng.randint(0, 20, 300).astype(np.int64),
            },
            str(tmp_path / "r"),
        )
        hs.create_index(
            s.read.parquet(str(tmp_path / "l")), IndexConfig("dvJl", ["k"], ["v"])
        )
        hs.create_index(
            s.read.parquet(str(tmp_path / "r")), IndexConfig("dvJr", ["k2"], ["g"])
        )
        enable_hyperspace(s)
        monkeypatch.setenv("HYPERSPACE_ACCOUNTING", "1")  # queries carry ledgers
        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
        monkeypatch.setenv("HYPERSPACE_JOIN_CHUNK_ROWS", "2000")
        monkeypatch.delenv("HYPERSPACE_BUILD_DECODE_THREADS", raising=False)
        monkeypatch.delenv("HYPERSPACE_FORCE_DEVICE_OPS", raising=False)

        seen = []
        real = physical._assemble_join

        def spy(*args, **kwargs):
            seen.append(
                (threading.current_thread() is threading.main_thread(),
                 accounting.current_ledger())
            )
            return real(*args, **kwargs)

        monkeypatch.setattr(physical, "_assemble_join", spy)

        def q():
            l = s.read.parquet(str(tmp_path / "l"))
            r = s.read.parquet(str(tmp_path / "r"))
            return (
                l.join(r, col("k") == col("k2"))
                .group_by("g")
                .agg(sv=("v", "sum"), n=("*", "count"))
            )

        streamed = q().collect().sorted_rows()
        from hyperspace_tpu.telemetry.profiling import last_join_stages

        js = last_join_stages()
        assert js is not None and js["mode"] == "join-stream" and js["chunks"] > 1
        worker_calls = [(m, led) for m, led in seen if not m]
        assert worker_calls, "join did not stream on the worker pool"
        led_ids = {led.query_id for _, led in worker_calls if led is not None}
        assert led_ids, "worker chunks saw no adopted ledger"
        closed = {l.query_id for l in accounting.recent_ledgers()}
        assert led_ids & closed, "adopted ledger is not the query's own"

        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "0")
        physical.clear_device_memos()
        assert streamed == q().collect().sorted_rows()


# ---------------------------------------------------------------------------
# Profile capture
# ---------------------------------------------------------------------------


@pytest.fixture()
def _stub_trace(monkeypatch):
    """Replace the jax.profiler window with a recording stub: these tests
    pin capture mechanics (manifest, rate limit, rotation, overlap guard) —
    the real profiler runs once in the CI smoke leg."""
    windows = []

    def stub(cap_dir, window_s):
        windows.append(cap_dir)
        try:
            with open(os.path.join(cap_dir, "trace.json"), "w") as f:
                json.dump({"window_s": window_s, "trace": True, "stub": True}, f)
        finally:
            dv._trace_in_flight.clear()

    monkeypatch.setattr(dv, "_trace_window", stub)
    return windows


class TestProfileCapture:
    def test_disabled_without_env(self):
        assert dv.maybe_capture("anomaly") is None

    def test_manifest_parses_and_rate_limit(self, tmp_path, monkeypatch, _stub_trace):
        monkeypatch.setenv(dv.ENV_PROFILE_DIR, str(tmp_path / "prof"))
        monkeypatch.setenv(dv.ENV_PROFILE_MIN_INTERVAL_S, "60")
        c0 = metrics.counter("profiler.captures_suppressed").value
        d1 = dv.maybe_capture("anomaly", {"sigma": 4.2})
        assert d1 is not None
        m = json.load(open(os.path.join(d1, "capture.json")))
        assert m["schema_version"] == 1
        assert m["reason"] == "anomaly"
        assert m["detail"] == {"sigma": 4.2}
        assert "pads" in m and "transfers" in m and "programs" in m
        assert dv.maybe_capture("anomaly") is None  # suppressed
        assert metrics.counter("profiler.captures_suppressed").value == c0 + 1

    def test_keep_n_rotation(self, tmp_path, monkeypatch, _stub_trace):
        monkeypatch.setenv(dv.ENV_PROFILE_DIR, str(tmp_path / "prof"))
        monkeypatch.setenv(dv.ENV_PROFILE_MIN_INTERVAL_S, "0")
        monkeypatch.setenv(dv.ENV_PROFILE_KEEP, "2")
        for i in range(4):
            assert dv.maybe_capture("slo_fast_burn", {"i": i}) is not None
        names = sorted(os.listdir(str(tmp_path / "prof")))
        assert "capture" in names and "capture.1" in names
        assert "capture.3" not in names  # keep=2 bounds the generations
        newest = json.load(open(str(tmp_path / "prof" / "capture" / "capture.json")))
        assert newest["detail"] == {"i": 3}

    def test_overlap_guard_skips_trace_not_manifest(
        self, tmp_path, monkeypatch, _stub_trace
    ):
        monkeypatch.setenv(dv.ENV_PROFILE_DIR, str(tmp_path / "prof"))
        monkeypatch.setenv(dv.ENV_PROFILE_MIN_INTERVAL_S, "0")
        dv._trace_in_flight.set()  # a window is "running"
        try:
            d1 = dv.maybe_capture("anomaly")
            assert d1 is not None  # manifest still lands
            t = json.load(open(os.path.join(d1, "trace.json")))
            assert t["trace"] is False
            assert "in flight" in t["error"]
            assert _stub_trace == []  # no second window spawned
        finally:
            dv._trace_in_flight.clear()
        d2 = dv.maybe_capture("anomaly")
        assert _stub_trace == [d2]  # guard released: window runs again

    def test_anomaly_hook_never_raises(self, monkeypatch):
        """The history/SLO call sites wrap maybe_capture in try/except, and
        maybe_capture itself must swallow capture-side failures."""
        monkeypatch.setenv(dv.ENV_PROFILE_DIR, "/dev/null/not-a-dir")
        monkeypatch.setenv(dv.ENV_PROFILE_MIN_INTERVAL_S, "0")
        assert dv.maybe_capture("anomaly") is None
