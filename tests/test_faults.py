"""Fault-injection + resilient-execution contracts (docs/reliability.md).

The chaos oracle: injected transient faults at the engine's named fault
points must change NOTHING about results (retries absorb them), permanent
faults and exhausted retries must fail classified, a corrupt index bucket
file must quarantine the index and fall back to a correct source scan, and a
query past its deadline must die with a classified timeout leaving no
partial cache/memo state.
"""

import os
import time

import pytest

from hyperspace_tpu import resilience
from hyperspace_tpu.engine.expr import col
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.exceptions import (
    CompileTimeoutError,
    ConcurrentWriteError,
    CorruptIndexError,
    HyperspaceException,
    LogCommitError,
    PermanentError,
    QueryTimeoutError,
    RetryBudgetExceededError,
    TransientError,
    is_transient,
)
from hyperspace_tpu.index import quarantine
from hyperspace_tpu.telemetry import faults, metrics


@pytest.fixture(autouse=True)
def _clean_fault_state(monkeypatch):
    """Every test starts with injection off, zeroed per-point counters, a
    fast backoff, and an empty quarantine."""
    monkeypatch.delenv("HYPERSPACE_FAULTS", raising=False)
    monkeypatch.delenv("HYPERSPACE_QUERY_TIMEOUT_S", raising=False)
    monkeypatch.setenv("HYPERSPACE_RETRY_BACKOFF_S", "0.001")
    faults.clear()
    faults.reset_counters()
    quarantine.clear()
    yield
    faults.clear()
    faults.reset_counters()
    quarantine.clear()


def _clear_caches():
    from hyperspace_tpu.engine.physical import clear_device_memos
    from hyperspace_tpu.engine.scan_cache import (
        global_bucketed_cache,
        global_concat_cache,
        global_scan_cache,
    )

    global_scan_cache().clear()
    global_concat_cache().clear()
    global_bucketed_cache().clear()
    clear_device_memos()


def _session(tmp_path, n_files=4, rows_per_file=200):
    from hyperspace_tpu.engine import io as eio

    s = HyperspaceSession(warehouse=str(tmp_path))
    src = str(tmp_path / "src")
    for i in range(n_files):
        base = i * rows_per_file
        eio.write_parquet(
            s.create_table(
                {
                    "k": list(range(base, base + rows_per_file)),
                    "v": [j % 7 for j in range(base, base + rows_per_file)],
                }
            ),
            os.path.join(src, f"part-{i:05d}.parquet"),
        )
    return s, src


def _counter(name: str) -> int:
    return metrics.snapshot()["counters"].get(name, 0)


class TestFaultRegistry:
    def test_env_spec_grammar(self, monkeypatch):
        monkeypatch.setenv(
            "HYPERSPACE_FAULTS", "io.decode:0.5, log.write:1.0:permanent:3:2"
        )
        specs = faults._active_specs()
        assert specs["io.decode"].rate == 0.5
        assert specs["io.decode"].kind == "transient"
        assert specs["log.write"].kind == "permanent"
        assert specs["log.write"].limit == 3
        assert specs["log.write"].after == 2

    def test_unknown_point_rejected(self):
        with pytest.raises(ValueError, match="Unknown fault point"):
            faults.FaultSpec("io.bogus", 1.0)

    def test_hang_kind_parses_seconds(self):
        spec = faults.FaultSpec("storage.write", 1.0, "hang2.5")
        assert spec.kind == "hang" and spec.hang_s == 2.5

    def test_deterministic_under_seed(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_FAULTS_SEED", "42")
        draws1 = [faults._decide("io.decode", n, 0.3) for n in range(200)]
        draws2 = [faults._decide("io.decode", n, 0.3) for n in range(200)]
        assert draws1 == draws2
        assert any(draws1) and not all(draws1)
        monkeypatch.setenv("HYPERSPACE_FAULTS_SEED", "43")
        assert [faults._decide("io.decode", n, 0.3) for n in range(200)] != draws1

    def test_inject_scope_counts_and_restores(self):
        before = _counter("faults.io.decode.injected")
        with faults.inject("io.decode", rate=1.0, kind="transient"):
            with pytest.raises(TransientError, match="injected"):
                faults.check("io.decode")
        faults.check("io.decode")  # no-op again after the scope
        assert _counter("faults.io.decode.injected") == before + 1
        assert faults.injected_count("io.decode") >= 1

    def test_limit_and_after(self):
        with faults.inject("io.footer", rate=1.0, limit=1, after=2):
            faults.check("io.footer")  # call 0: skipped (after)
            faults.check("io.footer")  # call 1: skipped (after)
            with pytest.raises(TransientError):
                faults.check("io.footer")  # call 2: injected
            faults.check("io.footer")  # limit reached: no-op


class TestTaxonomy:
    def test_is_transient(self):
        assert is_transient(TransientError("x"))
        assert is_transient(ConnectionError("x"))
        assert is_transient(OSError("flaky nfs"))
        assert not is_transient(PermanentError("x"))
        assert not is_transient(FileNotFoundError("x"))
        assert not is_transient(ValueError("corrupt parquet"))
        assert not is_transient(HyperspaceException("x"))

    def test_retry_io_retries_transient(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise TransientError("blip")
            return "ok"

        assert resilience.retry_io("io.decode", flaky) == "ok"
        assert len(calls) == 3

    def test_retry_io_fails_fast_on_permanent(self):
        calls = []

        def broken():
            calls.append(1)
            raise PermanentError("gone")

        before = _counter("io.retries.attempts")
        with pytest.raises(PermanentError):
            resilience.retry_io("io.decode", broken)
        assert len(calls) == 1
        assert _counter("io.retries.attempts") == before


class TestChaosOracle:
    """Results under injected transient faults are byte-identical to clean
    runs, with retries observed in the metrics snapshot."""

    def test_collect_identical_under_decode_faults(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        # At rate 0.4 the default 2 retries can exhaust on consecutive draws;
        # the chaos contract raises the bound like the CI leg does.
        monkeypatch.setenv("HYPERSPACE_IO_RETRIES", "6")
        s, src = _session(tmp_path)
        _clear_caches()
        clean = s.read.parquet(src).collect().sorted_rows()
        retries_before = _counter("io.retries.attempts")
        with faults.inject("io.decode", rate=0.4, kind="transient"):
            for _ in range(3):
                _clear_caches()
                assert s.read.parquet(src).collect().sorted_rows() == clean
        assert _counter("io.retries.attempts") > retries_before
        assert _counter("faults.injected") > 0

    def test_streamed_aggregate_identical_under_faults(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
        s, src = _session(tmp_path)

        def q():
            return (
                s.read.parquet(src)
                .group_by("v")
                .agg(total=("k", "sum"), n=("*", "count"))
                .collect()
                .sorted_rows()
            )

        _clear_caches()
        clean = q()
        with faults.inject("io.decode", rate=0.4, kind="transient"):
            _clear_caches()
            assert q() == clean

    def test_exhausted_retries_fail_classified(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        s, src = _session(tmp_path)
        _clear_caches()
        before = _counter("io.retries.exhausted")
        with faults.inject("io.decode", rate=1.0, kind="transient"):
            with pytest.raises(TransientError, match="injected"):
                s.read.parquet(src).collect()
        assert _counter("io.retries.exhausted") > before
        # Nothing poisoned: the same query succeeds once the fault clears.
        _clear_caches()
        assert s.read.parquet(src).count() == 800

    def test_permanent_fault_not_retried(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        s, src = _session(tmp_path)
        _clear_caches()
        before = _counter("io.retries.attempts")
        with faults.inject("io.decode", rate=1.0, kind="permanent"):
            with pytest.raises(PermanentError):
                s.read.parquet(src).collect()
        assert _counter("io.retries.attempts") == before

    def test_retry_budget_exceeded(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        monkeypatch.setenv("HYPERSPACE_QUERY_RETRY_BUDGET", "1")
        s, src = _session(tmp_path)
        _clear_caches()
        with faults.inject("io.decode", rate=1.0, kind="transient"):
            with pytest.raises(RetryBudgetExceededError, match="retry budget"):
                s.read.parquet(src).collect()

    def test_build_identical_under_faults(self, tmp_path, monkeypatch):
        """The chaos contract covers the BUILD too: an index built under
        injected transient decode/write faults is byte-identical to a clean
        build."""
        from hyperspace_tpu import Hyperspace, IndexConfig
        from hyperspace_tpu.hyperspace import enable_hyperspace

        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "2")
        s, src = _session(tmp_path)
        s.conf.set("hyperspace.system.path", str(tmp_path / "idx_clean"))
        s.conf.set("hyperspace.index.num.buckets", 4)
        _clear_caches()
        hs = Hyperspace(s)
        hs.create_index(s.read.parquet(src), IndexConfig("idx", ["k"], ["v"]))
        clean_dir = str(tmp_path / "idx_clean" / "idx" / "v__=0")
        clean = {
            f: open(os.path.join(clean_dir, f), "rb").read()
            for f in sorted(os.listdir(clean_dir))
        }
        s.conf.set("hyperspace.system.path", str(tmp_path / "idx_chaos"))
        _clear_caches()
        with faults.inject("storage.write", rate=0.3, kind="transient"):
            Hyperspace(s).create_index(
                s.read.parquet(src), IndexConfig("idx", ["k"], ["v"])
            )
        chaos_dir = str(tmp_path / "idx_chaos" / "idx" / "v__=0")
        chaos = {
            f: open(os.path.join(chaos_dir, f), "rb").read()
            for f in sorted(os.listdir(chaos_dir))
        }
        assert clean == chaos


class TestLogWriteClassification:
    def test_transient_log_fault_retried_to_success(self, tmp_path, monkeypatch):
        from hyperspace_tpu import Hyperspace, IndexConfig

        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        s, src = _session(tmp_path, n_files=2, rows_per_file=50)
        s.conf.set("hyperspace.system.path", str(tmp_path / "indexes"))
        s.conf.set("hyperspace.index.num.buckets", 2)
        _clear_caches()
        before = _counter("io.retries.log.write")
        with faults.inject("log.write", rate=1.0, kind="transient", limit=1):
            Hyperspace(s).create_index(
                s.read.parquet(src), IndexConfig("idx", ["k"], ["v"])
            )
        assert _counter("io.retries.log.write") > before
        mgr = Hyperspace(s)._manager
        assert [e.state for e in mgr.get_indexes(["ACTIVE"])] == ["ACTIVE"]

    def test_failed_stable_pointer_raises_classified(self):
        """Satellite: a failed latestStable refresh no longer silently
        proceeds — the action raises `LogCommitError` (the numbered entry IS
        committed; readers fall back to the id scan)."""
        from tests.test_actions import FakeBuilder, FakeLogManager

        from hyperspace_tpu import IndexConfig
        from hyperspace_tpu.actions.create import CreateAction

        class PointerLossManager(FakeLogManager):
            def create_latest_stable_log(self, log_id):
                super().create_latest_stable_log(log_id)
                return False

        mgr = PointerLossManager()
        action = CreateAction(
            "df", IndexConfig("idx", ["a"]), FakeBuilder(), mgr, "/i", "/i/v__=0"
        )
        with pytest.raises(LogCommitError, match="latestStable"):
            action.run()
        # The numbered final entry DID commit before the pointer failure.
        assert mgr.entries[1].state == "ACTIVE"

    def test_occ_conflict_is_concurrent_write_error(self):
        from tests.test_actions import FakeLogManager

        from hyperspace_tpu.actions.lifecycle import DeleteAction
        from hyperspace_tpu.actions import states as st
        from tests.test_actions import make_entry

        mgr = FakeLogManager({0: make_entry(state=st.ACTIVE)})
        mgr.entries[1] = make_entry(state=st.DELETING)  # the contested id
        action = DeleteAction(mgr)
        action._base_id = 0
        with pytest.raises(ConcurrentWriteError, match="in progress"):
            action.begin()


class TestQuarantine:
    def _indexed_session(self, tmp_path, monkeypatch):
        from hyperspace_tpu import Hyperspace, IndexConfig
        from hyperspace_tpu.hyperspace import enable_hyperspace

        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        s, src = _session(tmp_path, n_files=2, rows_per_file=100)
        s.conf.set("hyperspace.system.path", str(tmp_path / "indexes"))
        s.conf.set("hyperspace.index.num.buckets", 3)
        _clear_caches()
        hs = Hyperspace(s)
        hs.create_index(s.read.parquet(src), IndexConfig("fidx", ["k"], ["v"]))
        enable_hyperspace(s)
        return s, src, hs

    def test_corrupt_bucket_file_quarantines_and_falls_back(
        self, tmp_path, monkeypatch
    ):
        s, src, hs = self._indexed_session(tmp_path, monkeypatch)

        def q():
            # A range filter on the head indexed column: covered by the index
            # (rewritten to an index scan over EVERY part-<bucket> file).
            return (
                s.read.parquet(src)
                .filter(col("k") > 42)
                .select("k", "v")
                .collect()
                .sorted_rows()
            )

        _clear_caches()
        clean = q()
        # Truncate/corrupt one index bucket file on the lake.
        idx_dir = str(tmp_path / "indexes" / "fidx" / "v__=0")
        victim = sorted(os.listdir(idx_dir))[0]
        with open(os.path.join(idx_dir, victim), "wb") as f:
            f.write(b"not a parquet file")
        _clear_caches()
        before = _counter("index.quarantine.events")
        with pytest.warns(RuntimeWarning, match="quarantined"):
            rows = q()
        assert rows == clean  # correct via source-scan fallback
        assert quarantine.is_quarantined("fidx")
        assert _counter("index.quarantine.events") == before + 1
        # Subsequent queries skip the index at candidate selection.
        _clear_caches()
        rq_before = _counter("rule.FilterIndexRule.quarantined")
        assert q() == clean
        assert _counter("rule.FilterIndexRule.quarantined") > rq_before

    def test_refresh_lifts_quarantine(self, tmp_path, monkeypatch):
        s, src, hs = self._indexed_session(tmp_path, monkeypatch)
        quarantine.mark("fidx", reason="test")
        hs.refresh_index("fidx", mode="full")
        assert not quarantine.is_quarantined("fidx")

    def test_engine_bug_never_quarantines(self, tmp_path, monkeypatch):
        """The corruption guard is decode-layer-typed: a TypeError (engine
        bug) during an index scan surfaces raw instead of masquerading as a
        corrupt index."""
        from hyperspace_tpu.engine import io as engine_io

        s, src, hs = self._indexed_session(tmp_path, monkeypatch)
        _clear_caches()

        def boom(*a, **k):
            raise TypeError("engine bug, not corruption")

        monkeypatch.setattr(engine_io, "_read_one", boom)
        with pytest.raises(TypeError, match="engine bug"):
            s.read.parquet(src).filter(col("k") > 42).collect()
        assert not quarantine.is_quarantined("fidx")

    def test_malformed_fault_spec_is_classified(self, monkeypatch):
        """A bad HYPERSPACE_FAULTS value raises a HyperspaceException (config
        error), never a raw ValueError the corruption guard could misread."""
        monkeypatch.setenv("HYPERSPACE_FAULTS", "io.decode")  # missing rate
        with pytest.raises(HyperspaceException, match="Bad HYPERSPACE_FAULTS"):
            faults.check("io.decode")

    def test_transient_faults_never_quarantine(self, tmp_path, monkeypatch):
        """An injected transient fault exhausting its retries is NOT
        corruption: the query fails classified, the index stays usable."""
        s, src, hs = self._indexed_session(tmp_path, monkeypatch)
        _clear_caches()
        with faults.inject("io.decode", rate=1.0, kind="transient"):
            with pytest.raises(TransientError):
                s.read.parquet(src).filter(col("k") > 42).collect()
        assert not quarantine.is_quarantined("fidx")


class TestDeadlines:
    def test_query_timeout_classified_and_clean(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        s, src = _session(tmp_path, n_files=4)
        _clear_caches()
        monkeypatch.setenv("HYPERSPACE_QUERY_TIMEOUT_S", "0.1")
        before = _counter("query.timeouts")
        with faults.inject("io.decode", rate=1.0, kind="hang0.06"):
            with pytest.raises(QueryTimeoutError, match="HYPERSPACE_QUERY_TIMEOUT_S"):
                s.read.parquet(src).collect()
        assert _counter("query.timeouts") > before
        # No partial cache/memo entries: with the deadline lifted the query
        # returns the full, correct result.
        monkeypatch.delenv("HYPERSPACE_QUERY_TIMEOUT_S")
        faults.clear()
        assert len(s.read.parquet(src).collect().rows()) == 800

    def test_streamed_aggregate_timeout(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
        s, src = _session(tmp_path, n_files=4)
        _clear_caches()
        monkeypatch.setenv("HYPERSPACE_QUERY_TIMEOUT_S", "0.1")
        with faults.inject("io.decode", rate=1.0, kind="hang0.06"):
            with pytest.raises(QueryTimeoutError):
                s.read.parquet(src).group_by("v").agg(total=("k", "sum")).collect()
        monkeypatch.delenv("HYPERSPACE_QUERY_TIMEOUT_S")
        faults.clear()
        _clear_caches()
        out = s.read.parquet(src).group_by("v").agg(total=("k", "sum")).collect()
        assert out.num_rows == 7

    def test_no_scope_no_deadline(self):
        resilience.check_deadline("anywhere")  # no ambient scope: no-op

    def test_nested_scope_shares_deadline(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_QUERY_TIMEOUT_S", "60")
        with resilience.query_scope("outer") as outer:
            with resilience.query_scope("inner") as inner:
                assert inner is outer


class TestCompileDeadline:
    def test_slow_trace_raises_classified(self, monkeypatch):
        from hyperspace_tpu.telemetry.compile_log import observed_jit

        def slow(x):
            time.sleep(0.5)  # runs during TRACING (= inside the watchdog)
            return x + 1

        wrapped = observed_jit(slow, label="test.slow_compile")
        monkeypatch.setenv("HYPERSPACE_COMPILE_TIMEOUT_S", "0.05")
        before = _counter("xla.compiles.deadline_exceeded")
        with pytest.raises(CompileTimeoutError, match="test.slow_compile"):
            wrapped(1)
        assert _counter("xla.compiles.deadline_exceeded") == before + 1

    def test_fast_call_unaffected(self, monkeypatch):
        import numpy as np

        from hyperspace_tpu.telemetry.compile_log import observed_jit

        wrapped = observed_jit(lambda x: x * 2, label="test.fast")
        monkeypatch.setenv("HYPERSPACE_COMPILE_TIMEOUT_S", "30")
        assert int(np.asarray(wrapped(21))) == 42

    def test_device_compile_fault_point(self):
        from hyperspace_tpu.telemetry.compile_log import observed_jit

        wrapped = observed_jit(lambda x: x + 0, label="test.faulted")
        with faults.inject("device.compile", rate=1.0, kind="transient"):
            with pytest.raises(TransientError, match="device.compile"):
                wrapped(1)


class TestCrashRecoveryInProcess:
    """Simulated dead-writer states (the subprocess SIGKILL twins live in
    tests/test_crash_recovery.py)."""

    def _orphan_transient_entry(self, tmp_path, state):
        from hyperspace_tpu.index.log_manager import IndexLogManagerImpl

        idx_path = str(tmp_path / "indexes" / "idx")
        mgr = IndexLogManagerImpl(idx_path)
        from tests.test_actions import make_entry

        entry = make_entry(name="idx", state=state)
        assert mgr.write_log(0, entry)
        return idx_path

    def test_create_over_dead_creating_entry(self, tmp_path, monkeypatch):
        from hyperspace_tpu import Hyperspace, IndexConfig

        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        s, src = _session(tmp_path, n_files=2, rows_per_file=50)
        s.conf.set("hyperspace.system.path", str(tmp_path / "indexes"))
        s.conf.set("hyperspace.index.num.buckets", 2)
        self._orphan_transient_entry(tmp_path, "CREATING")
        _clear_caches()
        before = _counter("index.recovered_transient")
        Hyperspace(s).create_index(
            s.read.parquet(src), IndexConfig("idx", ["k"], ["v"])
        )
        assert _counter("index.recovered_transient") > before
        mgr = Hyperspace(s)._manager
        latest = mgr.get_indexes(["ACTIVE"])
        assert [e.name for e in latest] == ["idx"]

    def test_dead_staging_dir_reclaimed(self, tmp_path, monkeypatch):
        import subprocess

        from hyperspace_tpu import Hyperspace, IndexConfig
        from hyperspace_tpu.index.staging import STAGING_PREFIX

        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        s, src = _session(tmp_path, n_files=2, rows_per_file=50)
        s.conf.set("hyperspace.system.path", str(tmp_path / "indexes"))
        s.conf.set("hyperspace.index.num.buckets", 2)
        idx_path = tmp_path / "indexes" / "idx"
        idx_path.mkdir(parents=True)
        import socket

        proc = subprocess.Popen(["true"])
        proc.wait()  # a real pid, guaranteed dead
        orphan = (
            idx_path
            / f"{STAGING_PREFIX}v__=0~{socket.gethostname()}~{proc.pid}~deadbeef"
        )
        orphan.mkdir()
        (orphan / "part-00000.parquet").write_bytes(b"partial")
        # A LIVE foreign-host staging dir must survive reclamation (pid
        # liveness is unknowable cross-host; only TTL age reclaims those).
        foreign = idx_path / f"{STAGING_PREFIX}v__=0~otherhost~12345~cafef00d"
        foreign.mkdir()
        _clear_caches()
        Hyperspace(s).create_index(
            s.read.parquet(src), IndexConfig("idx", ["k"], ["v"])
        )
        leftovers = [
            n for n in os.listdir(idx_path) if n.startswith(STAGING_PREFIX)
        ]
        assert leftovers == [foreign.name]  # dead local reclaimed, foreign kept
        # Once stale past the TTL, the foreign dir is reclaimed too.
        monkeypatch.setenv("HYPERSPACE_STAGING_TTL_S", "0")
        from hyperspace_tpu.index.staging import reclaim_orphans

        time.sleep(0.01)
        assert reclaim_orphans(str(idx_path)) == 1

    def test_stage_commit_concurrent_loser_aborts_cleanly(self, tmp_path):
        from hyperspace_tpu.index.staging import STAGING_PREFIX, stage_commit

        final = tmp_path / "v__=0"
        with pytest.raises(ConcurrentWriteError, match="committed"):
            with stage_commit(str(final)) as stage:
                os.makedirs(stage)
                with open(os.path.join(stage, "f.parquet"), "wb") as f:
                    f.write(b"x")
                # The racing winner lands first.
                final.mkdir()
                (final / "f.parquet").write_bytes(b"winner")
        assert (final / "f.parquet").read_bytes() == b"winner"
        leftovers = [
            n for n in os.listdir(tmp_path) if n.startswith(STAGING_PREFIX)
        ]
        assert leftovers == []

    def test_refresh_over_dead_refreshing_entry(self, tmp_path, monkeypatch):
        """A killed refresh leaves REFRESHING as the latest entry; the next
        refresh recovers from the latest STABLE (ACTIVE) entry and completes."""
        from hyperspace_tpu import Hyperspace, IndexConfig

        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        s, src = _session(tmp_path, n_files=2, rows_per_file=50)
        s.conf.set("hyperspace.system.path", str(tmp_path / "indexes"))
        s.conf.set("hyperspace.index.num.buckets", 2)
        _clear_caches()
        hs = Hyperspace(s)
        hs.create_index(s.read.parquet(src), IndexConfig("idx", ["k"], ["v"]))
        # Simulate a writer killed mid-refresh: an orphan REFRESHING entry.
        from hyperspace_tpu.index.log_manager import IndexLogManagerImpl

        mgr = IndexLogManagerImpl(str(tmp_path / "indexes" / "idx"))
        import copy

        orphan = copy.deepcopy(mgr.get_latest_log())
        orphan.state = "REFRESHING"
        assert mgr.write_log(mgr.get_latest_id() + 1, orphan)
        hs._manager.clear_cache()
        hs.refresh_index("idx", mode="full")
        stable = mgr.get_latest_stable_log()
        assert stable is not None and stable.state == "ACTIVE"


class TestReliabilitySurfaces:
    def test_exporter_frame_carries_reliability(self, tmp_path):
        from hyperspace_tpu.telemetry.exporter import MetricsExporter

        quarantine.mark("brokenidx", reason="test")
        exp = MetricsExporter(str(tmp_path / "m.jsonl"), interval_s=60.0)
        frame = exp._frame()
        rel = frame["reliability"]
        assert "faults_injected" in rel and "io_retries" in rel
        assert rel["quarantined"] == ["brokenidx"]

    def test_explain_analyze_renders_retries(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        s, src = _session(tmp_path, n_files=2, rows_per_file=50)
        _clear_caches()
        with faults.inject("io.decode", rate=1.0, kind="transient", limit=1):
            out = s.read.parquet(src).explain(analyze=True)
        assert "io_retries=" in out
