"""Replica-fleet contracts (docs/serving.md "Replica fleet").

The fleet oracle: K replica processes over ONE shared lake return
byte-identical results to the ``HYPERSPACE_REPLICAS=0`` single process —
under rendezvous decode routing, the on-lake cold-decode lease, epoch-file
cache invalidation, fleet-apportioned admission, and dead-replica reclaim
(SIGKILL mid-flight included). The registry primitives (heartbeat entries,
claim-by-rename reclaim, same-host pid vs foreign-host TTL liveness) and
the replica_id observability stamps (ledger, exporter frame, prometheus,
history records, hsreport fleet split) are covered here too.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.serve import QueryServer
from hyperspace_tpu.serve import replicas as R

HOST = socket.gethostname()


@pytest.fixture(autouse=True)
def _fleet_state(monkeypatch, tmp_path):
    """Every test starts fleet-off, unjoined, fresh id, fast knobs."""
    for k in (R.ENV_REPLICAS, R.ENV_REPLICA_DIR):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv(R.ENV_VIEW_S, "0")
    monkeypatch.setenv(R.ENV_EPOCH_CHECK_S, "0")
    monkeypatch.setenv(R.ENV_HEARTBEAT_S, "0.05")
    R._reset_for_tests()
    yield
    R._reset_for_tests()


def _fleet_on(monkeypatch, tmp_path) -> str:
    d = str(tmp_path / "registry")
    monkeypatch.setenv(R.ENV_REPLICAS, "1")
    monkeypatch.setenv(R.ENV_REPLICA_DIR, d)
    return d


def _fake_member(reg: str, rid: str, host: str = "elsewhere", pid: int = 1234):
    """Drop a registry entry for a pretend replica on another host (fresh
    mtime → live under the foreign-host TTL rule)."""
    os.makedirs(reg, exist_ok=True)
    path = os.path.join(reg, f"{R.REPLICA_PREFIX}{rid}.json")
    with open(path, "w") as f:
        json.dump({"replica_id": rid, "host": host, "pid": pid}, f)
    return path


# ---------------------------------------------------------------------------
# Zero-cost-off contract
# ---------------------------------------------------------------------------


def test_fleet_off_is_exact_passthrough(tmp_path):
    assert not R.fleet_enabled()
    assert not R.joined()
    calls = []
    assert R.coordinate_decode("k", lambda: calls.append(1) or 41) == 41
    assert calls == [1]
    assert R.owns("anything")
    assert R.apportioned_budget(7) == 7
    assert R.check_invalidation({}) is False
    R.publish_invalidation("idx", 3, str(tmp_path / "reg"))
    assert not os.path.exists(tmp_path / "reg")  # publish is a no-op off


def test_fleet_off_zero_is_off(monkeypatch):
    monkeypatch.setenv(R.ENV_REPLICAS, "0")
    assert not R.fleet_enabled()
    monkeypatch.setenv(R.ENV_REPLICAS, "1")
    assert R.fleet_enabled()


# ---------------------------------------------------------------------------
# Registry: join / heartbeat / reclaim
# ---------------------------------------------------------------------------


def test_join_heartbeat_and_leave(monkeypatch, tmp_path):
    reg = _fleet_on(monkeypatch, tmp_path)
    rid = R.join_fleet()
    assert R.joined()
    entry = os.path.join(reg, f"{R.REPLICA_PREFIX}{rid}.json")
    assert os.path.exists(entry)
    assert R.live_replicas(refresh=True) == [rid]
    # The heartbeat refreshes the entry (mtime advances).
    m0 = os.stat(entry).st_mtime_ns
    deadline = time.time() + 5
    while os.stat(entry).st_mtime_ns == m0:
        assert time.time() < deadline, "heartbeat never beat"
        time.sleep(0.02)
    R.leave_fleet()
    assert not R.joined()
    assert not os.path.exists(entry)


def test_replica_id_parses_from_entry_name(monkeypatch, tmp_path):
    _fleet_on(monkeypatch, tmp_path)
    rid = R.replica_id()
    assert rid == R.replica_id()  # stable per process
    host, pid = R._owner_of(f"{R.REPLICA_PREFIX}{rid}.json")
    # Hosts may themselves contain '-': parse is from the RIGHT.
    assert host == HOST
    assert pid == os.getpid()


def test_dead_same_host_entry_reclaimed(monkeypatch, tmp_path):
    reg = _fleet_on(monkeypatch, tmp_path)
    rid = R.join_fleet()
    # A same-host entry with a dead pid is reclaimed on the next scan,
    # fresh mtime or not (pid liveness beats TTL on the local host).
    dead = _fake_member(reg, f"{HOST}-999999-deadbeef", host=HOST, pid=999999)
    view = R.live_replicas(refresh=True)
    assert view == [rid]
    assert not os.path.exists(dead)
    assert not [n for n in os.listdir(reg) if n.startswith(R.CLAIMED_PREFIX)]


def test_foreign_entry_lives_by_ttl(monkeypatch, tmp_path):
    reg = _fleet_on(monkeypatch, tmp_path)
    monkeypatch.setenv(R.ENV_TTL_S, "30")
    rid = R.join_fleet()
    fresh = _fake_member(reg, "elsewhere-1-aaaaaaaa")
    stale = _fake_member(reg, "elsewhere-2-bbbbbbbb")
    old = time.time() - 3600
    os.utime(stale, (old, old))
    view = R.live_replicas(refresh=True)
    assert "elsewhere-1-aaaaaaaa" in view and rid in view
    assert "elsewhere-2-bbbbbbbb" not in view
    assert os.path.exists(fresh) and not os.path.exists(stale)


# ---------------------------------------------------------------------------
# Rendezvous routing
# ---------------------------------------------------------------------------


def test_rendezvous_stable_balanced_minimal_movement():
    members = ["a", "b", "c"]
    keys = [f"key{i}" for i in range(300)]
    owners = {k: R.owner_of(k, members) for k in keys}
    assert owners == {k: R.owner_of(k, members) for k in keys}  # stable
    counts = {m: sum(1 for o in owners.values() if o == m) for m in members}
    assert all(c > len(keys) // 6 for c in counts.values()), counts  # balanced
    # Removing one member remaps ONLY the keys it owned.
    survivors = ["a", "c"]
    for k in keys:
        new = R.owner_of(k, survivors)
        if owners[k] != "b":
            assert new == owners[k]
        else:
            assert new in survivors


def test_owns_degrades_to_true(monkeypatch, tmp_path):
    assert R.owns("k", ["somebody-else"])  # fleet off → always owns
    _fleet_on(monkeypatch, tmp_path)
    monkeypatch.setenv(R.ENV_REPLICAS, "1")
    rid = R.join_fleet()
    assert R.owns("k", [rid])
    assert not R.owns("k", ["zzz-other"]) or R.owner_of("k", ["zzz-other"]) is None


# ---------------------------------------------------------------------------
# Epoch invalidation
# ---------------------------------------------------------------------------


def test_epoch_publish_and_observe(monkeypatch, tmp_path):
    reg = _fleet_on(monkeypatch, tmp_path)
    R.join_fleet()
    cursor = {}
    assert R.check_invalidation(cursor, reg) is False  # primed at join
    R.publish_invalidation("myIdx", 7, reg)
    assert R.read_epoch(reg)["entries"]["myIdx"] == 7
    assert R.check_invalidation(cursor, reg) is True
    assert R.check_invalidation(cursor, reg) is False  # consumed
    # A second consumer with its own cursor still sees the flip.
    other = {}
    R.publish_invalidation("myIdx", 8, reg)
    assert R.check_invalidation(cursor, reg) is True
    assert R.check_invalidation(other, reg) is True


def test_invalidation_flips_peer_cache_without_ttl(monkeypatch, tmp_path):
    """Two caching managers over one warehouse (two replicas in miniature):
    a mutation committed through manager A flips manager B's cached view on
    B's NEXT read — no TTL wait."""
    from hyperspace_tpu import IndexConfig, IndexConstants
    from hyperspace_tpu.hyperspace import Hyperspace

    _fleet_on(monkeypatch, tmp_path)
    R.join_fleet()

    wh = str(tmp_path / "wh")

    def mk():
        s = HyperspaceSession(warehouse=wh)
        s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, os.path.join(wh, "indexes"))
        return s, Hyperspace(s)

    s_a, hs_a = mk()
    s_b, hs_b = mk()
    s_a.write_parquet(
        {"k": np.arange(200, dtype=np.int64), "v": np.arange(200, dtype=np.int64)},
        os.path.join(wh, "t"),
    )
    df = lambda s: s.read.parquet(os.path.join(wh, "t"))
    hs_a.create_index(df(s_a), IndexConfig("fleetIdx", ["k"], ["v"]))
    # B reads (and caches) the post-create state.
    names_b = list(hs_b.indexes().column("name").decode_objects())
    assert "fleetIdx" in names_b
    # A deletes; B's very next read must see it (epoch flip, no TTL).
    hs_a.delete_index("fleetIdx")
    after = hs_b.indexes()
    states = dict(
        zip(
            after.column("name").decode_objects(),
            after.column("state").decode_objects(),
        )
    )
    assert states.get("fleetIdx") != "ACTIVE"


# ---------------------------------------------------------------------------
# Cold-decode coordination (lease)
# ---------------------------------------------------------------------------


def test_foreign_decode_serializes_under_lease(monkeypatch, tmp_path):
    reg = _fleet_on(monkeypatch, tmp_path)
    R.join_fleet()
    _fake_member(reg, "zzzz-1-ffffffff")  # sorts above any host: wins keys
    members = R.live_replicas(refresh=True)
    assert len(members) == 2
    key = next(
        f"file{i}" for i in range(100) if R.owner_of(f"file{i}", members) != R.replica_id()
    )
    inflight, overlaps, results = [0], [0], []

    def attempt():
        inflight[0] += 1
        overlaps[0] = max(overlaps[0], inflight[0])
        time.sleep(0.05)
        inflight[0] -= 1
        return "bytes"

    ts = [
        threading.Thread(target=lambda: results.append(R.coordinate_decode(key, attempt)))
        for _ in range(3)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(15)
    assert results == ["bytes"] * 3
    assert overlaps[0] == 1, "lease must serialize cross-replica decodes"
    stats = R.fleet_stats()
    assert stats["live"] == 2
    assert not [n for n in os.listdir(reg) if n.startswith(R.LEASE_PREFIX)]


def test_dead_holder_lease_broken(monkeypatch, tmp_path):
    reg = _fleet_on(monkeypatch, tmp_path)
    R.join_fleet()
    _fake_member(reg, "zzzz-1-ffffffff")
    members = R.live_replicas(refresh=True)
    key = next(
        f"file{i}" for i in range(100) if R.owner_of(f"file{i}", members) != R.replica_id()
    )
    # A lease whose holder is a dead same-host pid must be broken, not waited out.
    path = R._lease_path(reg, key)
    with open(path, "w") as f:
        json.dump({"host": HOST, "pid": 999999}, f)
    t0 = time.time()
    assert R.coordinate_decode(key, lambda: "ok") == "ok"
    assert time.time() - t0 < 5
    assert not os.path.exists(path)


def test_owned_decode_skips_lease(monkeypatch, tmp_path):
    reg = _fleet_on(monkeypatch, tmp_path)
    R.join_fleet()
    _fake_member(reg, "aaaa-1-00000000")
    members = R.live_replicas(refresh=True)
    key = next(
        f"file{i}" for i in range(100) if R.owner_of(f"file{i}", members) == R.replica_id()
    )
    before = R.fleet_stats()
    assert R.coordinate_decode(key, lambda: 1) == 1
    assert not [n for n in os.listdir(reg) if n.startswith(R.LEASE_PREFIX)]
    assert before  # owned path never creates a lease file


# ---------------------------------------------------------------------------
# Fleet admission
# ---------------------------------------------------------------------------


def test_budget_apportioned_and_rebalanced(monkeypatch, tmp_path):
    reg = _fleet_on(monkeypatch, tmp_path)
    R.join_fleet()
    assert R.apportioned_budget(4) == 4  # alone: full budget
    fake = _fake_member(reg, "elsewhere-1-aaaaaaaa")
    R.live_replicas(refresh=True)
    assert R.apportioned_budget(4) == 2
    assert R.apportioned_budget(3) == 2  # ceil
    assert R.apportioned_budget(1) == 1  # floor 1
    os.unlink(fake)
    R.live_replicas(refresh=True)
    assert R.apportioned_budget(4) == 4  # membership change rebalances


def test_admission_controller_uses_fleet_share(monkeypatch, tmp_path):
    from hyperspace_tpu.exceptions import AdmissionRejectedError
    from hyperspace_tpu.serve.admission import AdmissionController

    reg = _fleet_on(monkeypatch, tmp_path)
    R.join_fleet()
    _fake_member(reg, "elsewhere-1-aaaaaaaa")
    R.live_replicas(refresh=True)
    ac = AdmissionController(queue_depth=8, tenant_budget=2)
    assert ac.effective_tenant_budget() == 1  # 2 across 2 replicas
    ac.admit("t1")
    with pytest.raises(AdmissionRejectedError) as ei:
        ac.admit("t1")
    assert "fleet share" in str(ei.value)
    st = ac.stats()
    assert st["tenant_budget_fleet_share"] == 1


# ---------------------------------------------------------------------------
# SIGKILL mid-flight: reclaim, ring rebuild, budget + byte-identity
# ---------------------------------------------------------------------------

_CHILD_SRC = """
import os, sys, time
sys.path.insert(0, {repo!r})
from hyperspace_tpu.serve import replicas as R
print(R.join_fleet(), flush=True)
time.sleep(120)
"""


def test_sigkill_replica_reclaimed_ring_and_budget_rebalance(
    monkeypatch, tmp_path
):
    reg = _fleet_on(monkeypatch, tmp_path)
    rid = R.join_fleet()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(
        {
            R.ENV_REPLICAS: "1",
            R.ENV_REPLICA_DIR: reg,
            "JAX_PLATFORMS": "cpu",
        }
    )
    p = subprocess.Popen(
        [sys.executable, "-c", _CHILD_SRC.format(repo=repo)],
        env=env,
        stdout=subprocess.PIPE,
        text=True,
    )
    try:
        victim = p.stdout.readline().strip()
        assert victim and victim != rid
        deadline = time.time() + 20
        while set(R.live_replicas(refresh=True)) != {rid, victim}:
            assert time.time() < deadline, R.live_replicas(refresh=True)
            time.sleep(0.05)
        members = [rid, victim]
        keys = [f"file{i}" for i in range(100)]
        victim_keys = [k for k in keys if R.owner_of(k, members) == victim]
        assert victim_keys, "rendezvous should give the victim some keys"
        assert R.apportioned_budget(4) == 2

        # A query completed while the fleet is whole...
        sess = HyperspaceSession(warehouse=str(tmp_path / "wh"))
        sess.write_parquet(
            {"k": np.arange(100, dtype=np.int64), "v": np.arange(100, dtype=np.int64)},
            str(tmp_path / "wh" / "t"),
        )
        q = lambda: (
            sess.read.parquet(str(tmp_path / "wh" / "t"))
            .filter(col("k") < 10)
            .select("k", "v")
            .collect()
            .sorted_rows()
        )
        before = q()

        p.kill()  # SIGKILL: no leave_fleet, no heartbeat — a crashed replica
        p.wait(10)
        # Registry entry reclaimed (same-host pid liveness, immediate)...
        deadline = time.time() + 20
        while R.live_replicas(refresh=True) != [rid]:
            assert time.time() < deadline
            time.sleep(0.05)
        assert not [
            n
            for n in os.listdir(reg)
            if n.startswith(R.REPLICA_PREFIX) and victim in n
        ]
        assert not [n for n in os.listdir(reg) if n.startswith(R.CLAIMED_PREFIX)]
        # ...the ring rebuilds: every victim key remaps to the survivor,
        # every survivor key stays put (minimal movement)...
        for k in keys:
            assert R.owner_of(k, R.live_replicas()) == rid
        # ...the tenant budget share redistributes...
        assert R.apportioned_budget(4) == 4
        # ...and in-flight work on the survivor is byte-identical.
        assert q() == before
    finally:
        if p.poll() is None:
            p.kill()
            p.wait(10)


# ---------------------------------------------------------------------------
# Byte-identity through the engine (fleet on + foreign routing vs fleet off)
# ---------------------------------------------------------------------------


def test_engine_results_byte_identical_fleet_on_vs_off(monkeypatch, tmp_path):
    from hyperspace_tpu.engine.scan_cache import (
        global_concat_cache,
        global_scan_cache,
    )

    wh = str(tmp_path / "wh")
    sess = HyperspaceSession(warehouse=wh)
    rng = np.random.RandomState(3)
    sess.write_parquet(
        {
            "k": rng.randint(0, 50, 2000).astype(np.int64),
            "v": rng.rand(2000),
        },
        os.path.join(wh, "t"),
    )
    q = lambda: (
        sess.read.parquet(os.path.join(wh, "t"))
        .filter(col("k") == 7)
        .select("k", "v")
        .collect()
        .sorted_rows()
    )
    global_scan_cache().clear()
    global_concat_cache().clear()
    oracle = q()  # fleet off

    reg = _fleet_on(monkeypatch, tmp_path)
    R.join_fleet()
    # A fake peer that wins most keys: decodes route through the lease path.
    _fake_member(reg, "zzzz-1-ffffffff")
    R.live_replicas(refresh=True)
    global_scan_cache().clear()
    global_concat_cache().clear()
    assert q() == oracle
    assert not [n for n in os.listdir(reg) if n.startswith(R.LEASE_PREFIX)]


# ---------------------------------------------------------------------------
# replica_id observability stamps
# ---------------------------------------------------------------------------


def test_ledger_frame_prometheus_and_history_stamped(monkeypatch, tmp_path):
    from hyperspace_tpu.telemetry import accounting, exporter, history

    rid = R.replica_id()
    # Closed query ledgers carry the stamp...
    wh = str(tmp_path / "wh")
    sess = HyperspaceSession(warehouse=wh)
    sess.write_parquet({"k": np.arange(10, dtype=np.int64)}, os.path.join(wh, "t"))
    with QueryServer(max_concurrent=2) as srv:
        srv.run(
            lambda: sess.read.parquet(os.path.join(wh, "t")).collect(),
            tenant="stamp-test",
        )
    led = [
        l for l in accounting.drain_pending() if l.get("tenant") == "stamp-test"
    ]
    assert led and all(l.get("replica_id") == rid for l in led)
    # ...exporter frames carry it...
    exp = exporter.MetricsExporter(os.path.join(str(tmp_path), "metrics.jsonl"), 60.0)
    frame = exp._frame()
    assert frame["replica_id"] == rid
    # ...prometheus exposes the info-series with escaped labels...
    text = exporter.prometheus_text()
    assert f'hyperspace_replica_info{{replica_id="{rid}"' in text
    # ...and on-lake history records carry it on the envelope.
    hist = str(tmp_path / "hist")
    store = history.HistoryStore(hist)
    store.record("fp1", {"wall_s": 0.1})
    rec = next(iter(history.iter_records(hist)))
    assert rec["replica_id"] == rid


def test_hsreport_fleet_split(tmp_path):
    sys.path.insert(
        0,
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
        ),
    )
    import hsreport

    hist = str(tmp_path / "hist")
    from hyperspace_tpu.telemetry import history

    store = history.HistoryStore(hist)
    for rid, wall in (("repA", 0.2), ("repA", 0.3), ("repB", 0.5)):
        store.record(
            "f1",
            {
                "fingerprint": "f1",
                "wall_s": wall,
                "label": "query:collect",
                "lane": "batch",
                "replica_id": rid,
            },
        )
    report = hsreport.build_report(hist, 5, 5)
    fleet = report["replicas"]
    # The envelope stamp is THIS process's replica_id; the in-ledger stamp
    # is the synthetic writer's. Writer identity (the in-ledger one) wins
    # only when the envelope lacks a stamp — so here all records group
    # under this process's id unless records are hand-built. Accept either
    # grouping but require the split to exist and cover all records.
    assert fleet and fleet["fleet"]["records"] == 3
    text = hsreport.render(report)
    assert "replica fleet" in text


def test_hsreport_prefleet_store_unchanged(tmp_path):
    sys.path.insert(
        0,
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
        ),
    )
    import hsreport

    hist = str(tmp_path / "hist")
    os.makedirs(hist)
    with open(os.path.join(hist, "seg-old.jsonl"), "w") as f:
        f.write(
            json.dumps(
                {
                    "kind": "ledger",
                    "ledger": {"fingerprint": "f1", "wall_s": 0.1},
                }
            )
            + "\n"
        )
    report = hsreport.build_report(hist, 5, 5)
    assert report["replicas"] is None
    assert "replica fleet" not in hsreport.render(report)


# ---------------------------------------------------------------------------
# QueryServer integration
# ---------------------------------------------------------------------------


def test_query_server_joins_and_reports_fleet(monkeypatch, tmp_path):
    reg = _fleet_on(monkeypatch, tmp_path)
    with QueryServer(max_concurrent=2) as srv:
        assert R.joined()
        st = srv.stats()
        assert st["replicas"]["live"] == 1
        assert st["replicas"]["replica_id"] == R.replica_id()
        assert os.listdir(reg)


def test_query_server_off_means_no_registry(tmp_path, monkeypatch):
    monkeypatch.setenv(R.ENV_REPLICA_DIR, str(tmp_path / "reg"))
    with QueryServer(max_concurrent=2):
        assert not R.joined()
    assert not os.path.exists(tmp_path / "reg")
