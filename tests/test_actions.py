"""Action FSM tests against a fake log backend.

Mirrors reference tier 2 (SURVEY §4): `ActionTest` asserts the exact
writeLog(base+1, transient) / writeLog(base+2, final) / deleteLatestStableLog /
createLatestStableLog sequence against a mocked IndexLogManager; per-action tests cover
validate() state checks and op() effects.
"""

import copy

import pytest

from hyperspace_tpu import HyperspaceException, IndexConfig
from hyperspace_tpu.actions import states
from hyperspace_tpu.actions.action import Action
from hyperspace_tpu.actions.create import CreateAction, IndexerBuilder
from hyperspace_tpu.actions.lifecycle import (
    CancelAction,
    DeleteAction,
    RestoreAction,
    VacuumAction,
)
from hyperspace_tpu.actions.refresh import RefreshAction
from hyperspace_tpu.index.log_entry import (
    Content,
    CoveringIndexProperties,
    Directory,
    FileInfo,
    IndexLogEntry,
    LogicalPlanFingerprint,
    Relation,
    Signature,
    Source,
    SourcePlanProperties,
)
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.telemetry import RecordingEventLogger


def make_entry(name="idx", state=states.ACTIVE, sig="s1"):
    e = IndexLogEntry(
        name,
        CoveringIndexProperties(["a"], ["b"], "{}", 4),
        Content(Directory("/idx/v__=0", files=[FileInfo("f", 1, 1)])),
        Source(
            SourcePlanProperties(
                [Relation(["/src"], Content(Directory("/src")), "{}", "parquet")],
                fingerprint=LogicalPlanFingerprint(signatures=[Signature("p", sig)]),
            )
        ),
    )
    e.state = state
    return e


class FakeLogManager(IndexLogManager):
    """In-memory log manager recording the call sequence (the reference's Mockito mock)."""

    def __init__(self, entries=None):
        self.entries = dict(entries or {})
        self.calls = []
        self.stable_id = None

    def get_log(self, log_id):
        return copy.deepcopy(self.entries.get(log_id))

    def get_latest_id(self):
        return max(self.entries) if self.entries else None

    def get_latest_stable_log(self):
        if self.stable_id is not None:
            return copy.deepcopy(self.entries.get(self.stable_id))
        for i in sorted(self.entries, reverse=True):
            if self.entries[i].state in states.STABLE_STATES:
                return copy.deepcopy(self.entries[i])
        return None

    def create_latest_stable_log(self, log_id):
        self.calls.append(("createLatestStable", log_id))
        self.stable_id = log_id
        return True

    def delete_latest_stable_log(self):
        self.calls.append(("deleteLatestStable",))
        self.stable_id = None
        return True

    def write_log(self, log_id, entry):
        self.calls.append(("writeLog", log_id, entry.state))
        if log_id in self.entries:
            return False
        self.entries[log_id] = copy.deepcopy(entry)
        return True


class FakeBuilder(IndexerBuilder):
    def __init__(self, entry=None):
        self.writes = []
        self.validated = []
        self._entry = entry or make_entry(state="")

    def validate_source(self, df, index_config):
        self.validated.append((df, index_config))

    def write(self, df, index_config, index_data_path):
        self.writes.append((df, index_config, index_data_path))

    def derive_log_entry(self, df, index_config, index_path, index_data_path):
        return copy.deepcopy(self._entry)

    def reconstruct_df(self, relation):
        return ("df-from", tuple(relation.root_paths))


class TestActionFSM:
    def test_create_sequence_on_empty_log(self):
        """Exact writeLog(0, CREATING) / writeLog(1, ACTIVE) / delete+createLatestStable(1)
        sequence (reference ActionTest.scala:64-84)."""
        mgr = FakeLogManager()
        events = RecordingEventLogger()
        action = CreateAction(
            "df", IndexConfig("idx", ["a"], ["b"]), FakeBuilder(), mgr, "/idx", "/idx/v__=0",
            event_logger=events,
        )
        action.run()
        assert mgr.calls == [
            ("writeLog", 0, states.CREATING),
            ("writeLog", 1, states.ACTIVE),
            ("deleteLatestStable",),
            ("createLatestStable", 1),
        ]
        assert [e.message for e in events.events] == [
            "Operation Started.",
            "Operation Succeeded.",
        ]

    def test_occ_conflict_raises(self):
        mgr = FakeLogManager({0: make_entry(state=states.CREATING)})
        mgr.entries[1] = make_entry(state=states.CREATING)  # simulate concurrent begin
        action = DeleteAction(mgr)
        # base id = 1, begin writes 2, ok; but let's make conflict: prefill 2 and 3.
        mgr.entries[2] = make_entry(state=states.DELETING)
        with pytest.raises(HyperspaceException, match="in progress"):
            action._base_id = 1
            action.begin()

    def test_failed_op_leaves_transient_state_and_logs_event(self):
        class FailingBuilder(FakeBuilder):
            def write(self, df, index_config, index_data_path):
                raise RuntimeError("boom")

        mgr = FakeLogManager()
        events = RecordingEventLogger()
        action = CreateAction(
            "df", IndexConfig("idx", ["a"]), FailingBuilder(), mgr, "/i", "/i/v__=0",
            event_logger=events,
        )
        with pytest.raises(RuntimeError):
            action.run()
        # The transient entry remains; no final entry was written (crash-consistent).
        assert mgr.entries[0].state == states.CREATING
        assert 1 not in mgr.entries
        assert "Operation Failed" in events.events[-1].message


class TestCreateAction:
    def test_rejects_existing_live_index(self):
        mgr = FakeLogManager({0: make_entry(state=states.ACTIVE)})
        action = CreateAction(
            "df", IndexConfig("idx", ["a"]), FakeBuilder(), mgr, "/i", "/i/v__=1"
        )
        with pytest.raises(HyperspaceException, match="already exists"):
            action.validate()

    def test_allows_create_over_doesnotexist(self):
        mgr = FakeLogManager({0: make_entry(state=states.DOESNOTEXIST)})
        action = CreateAction(
            "df", IndexConfig("idx", ["a"]), FakeBuilder(), mgr, "/i", "/i/v__=1"
        )
        action.validate()  # no raise


class TestRefreshAction:
    def test_full_rebuild_from_logged_relation(self):
        mgr = FakeLogManager({0: make_entry(state=states.ACTIVE)})
        builder = FakeBuilder(make_entry(state=""))
        action = RefreshAction(builder, mgr, "/i", "/i/v__=1")
        action.run()
        # df reconstructed from the logged relation's root paths
        assert builder.writes[0][0] == ("df-from", ("/src",))
        assert builder.writes[0][2] == "/i/v__=1"
        assert mgr.entries[2].state == states.ACTIVE

    def test_requires_active(self):
        mgr = FakeLogManager({0: make_entry(state=states.DELETED)})
        action = RefreshAction(FakeBuilder(), mgr, "/i", "/i/v__=1")
        with pytest.raises(HyperspaceException, match="ACTIVE"):
            action.validate()


class TestDeleteRestore:
    def test_delete_soft(self):
        mgr = FakeLogManager({0: make_entry(state=states.ACTIVE)})
        DeleteAction(mgr).run()
        assert mgr.entries[2].state == states.DELETED

    def test_delete_requires_active(self):
        mgr = FakeLogManager({0: make_entry(state=states.DELETED)})
        with pytest.raises(HyperspaceException):
            DeleteAction(mgr).run()

    def test_restore(self):
        mgr = FakeLogManager({0: make_entry(state=states.DELETED)})
        RestoreAction(mgr).run()
        assert mgr.entries[2].state == states.ACTIVE

    def test_restore_requires_deleted(self):
        mgr = FakeLogManager({0: make_entry(state=states.ACTIVE)})
        with pytest.raises(HyperspaceException):
            RestoreAction(mgr).run()


class FakeDataManager:
    def __init__(self, latest=2):
        self.latest = latest
        self.deleted = []

    def get_latest_version_id(self):
        return self.latest

    def get_path(self, vid):
        return f"/i/v__={vid}"

    def delete(self, vid):
        self.deleted.append(vid)


class TestVacuumAction:
    def test_deletes_all_versions(self):
        mgr = FakeLogManager({0: make_entry(state=states.DELETED)})
        dm = FakeDataManager(latest=2)
        VacuumAction(dm, mgr).run()
        assert dm.deleted == [0, 1, 2]
        assert mgr.entries[2].state == states.DOESNOTEXIST

    def test_requires_deleted(self):
        mgr = FakeLogManager({0: make_entry(state=states.ACTIVE)})
        with pytest.raises(HyperspaceException):
            VacuumAction(FakeDataManager(), mgr).run()


class TestCancelAction:
    def test_rolls_back_to_last_stable(self):
        mgr = FakeLogManager(
            {0: make_entry(state=states.ACTIVE), 1: make_entry(state=states.REFRESHING)}
        )
        CancelAction(mgr).run()
        assert mgr.entries[3].state == states.ACTIVE  # last stable state restored

    def test_vacuuming_cancels_to_doesnotexist(self):
        mgr = FakeLogManager(
            {
                0: make_entry(state=states.DELETED),
                1: make_entry(state=states.VACUUMING),
            }
        )
        CancelAction(mgr).run()
        assert mgr.entries[3].state == states.DOESNOTEXIST

    def test_rejects_stable_state(self):
        mgr = FakeLogManager({0: make_entry(state=states.ACTIVE)})
        with pytest.raises(HyperspaceException, match="transient"):
            CancelAction(mgr).run()


class TestEventLoggerFactory:
    def test_reflective_load_and_noop_default(self):
        from hyperspace_tpu.telemetry import EventLoggerFactory, NoOpEventLogger, RecordingEventLogger

        EventLoggerFactory.reset()
        assert isinstance(EventLoggerFactory.get_logger(None), NoOpEventLogger)
        logger = EventLoggerFactory.get_logger(
            "hyperspace_tpu.telemetry.event_logging.RecordingEventLogger"
        )
        assert isinstance(logger, RecordingEventLogger)
        assert EventLoggerFactory.get_logger(
            "hyperspace_tpu.telemetry.event_logging.RecordingEventLogger"
        ) is logger  # singleton per class
