"""Equivalence: the Pallas in-VMEM bitonic sort == jnp.argsort over padded
bucket matrices. Off-TPU the kernel runs in interpret mode (same program the
TPU lowers via Mosaic). Bitonic is unstable, so equivalence is: sorted keys
identical, and the order is a valid permutation reproducing them."""

import numpy as np
import pytest

import jax.numpy as jnp

from hyperspace_tpu.ops.bucket_join import _PAD, pad_buckets_by_hash
from hyperspace_tpu.ops.pallas_sort import (
    pallas_sort_wanted,
    shape_supported,
    sort_padded_with_order,
)


def _check(keys_np):
    got_sorted, got_order = sort_padded_with_order(jnp.asarray(keys_np))
    ref_sorted = np.sort(keys_np, axis=1)
    np.testing.assert_array_equal(np.asarray(got_sorted), ref_sorted)
    # order is a permutation per row and reproduces the sorted keys
    order = np.asarray(got_order)
    for b in range(keys_np.shape[0]):
        assert sorted(order[b]) == list(range(keys_np.shape[1]))
        np.testing.assert_array_equal(keys_np[b][order[b]], ref_sorted[b])


def test_random_int64_keys_with_pads():
    rng = np.random.RandomState(0)
    B, cap = 8, 256
    keys = rng.randint(-(2**62), 2**62, size=(B, cap)).astype(np.int64)
    # Ragged valid prefixes: pad tails with the sentinel like production.
    for b in range(B):
        keys[b, rng.randint(1, cap):] = np.iinfo(np.int64).max
    _check(keys)


def test_duplicate_heavy_keys():
    rng = np.random.RandomState(1)
    keys = rng.randint(0, 7, size=(8, 256)).astype(np.int64)
    _check(keys)


def test_nonmultiple_bucket_axis_whole_block():
    rng = np.random.RandomState(2)
    keys = rng.randint(-1000, 1000, size=(3, 256)).astype(np.int64)
    _check(keys)


def test_shape_gate():
    assert shape_supported(8, 256)
    assert shape_supported(64, 32768)
    assert not shape_supported(8, 65536)  # beyond the VMEM budget
    assert not shape_supported(8, 128)  # below the dispatch-overhead floor
    assert not shape_supported(8, 300)  # not a pow2
    assert not shape_supported(20, 1024)  # >8 and not a multiple of 8


def test_pad_buckets_by_hash_via_pallas_matches_xla(monkeypatch):
    """End-to-end through pad_buckets_by_hash: forced Pallas sort must yield
    the same sorted key matrices and consistent order maps as the XLA path."""
    import hyperspace_tpu.ops.pallas_sort as ps

    rng = np.random.RandomState(3)
    n = 4000
    key64 = rng.randint(-(2**62), 2**62, n).astype(np.int64)
    starts = np.linspace(0, n, 9).astype(np.int64)

    monkeypatch.setenv("HYPERSPACE_PALLAS_SORT", "0")
    ref = pad_buckets_by_hash(key64, starts)
    monkeypatch.setenv("HYPERSPACE_PALLAS_SORT", "1")
    monkeypatch.setattr(ps, "_sort_broken", {})
    got = pad_buckets_by_hash(key64, starts)
    np.testing.assert_array_equal(np.asarray(got.keys), np.asarray(ref.keys))
    np.testing.assert_array_equal(np.asarray(got.lengths), np.asarray(ref.lengths))
    # order maps agree up to permutations within equal keys: re-gathering the
    # keys through each order must reproduce the sorted matrices.
    padded_ref = np.full(ref.keys.shape, np.iinfo(np.int64).max, np.int64)
    clipped = np.minimum(key64, np.iinfo(np.int64).max - 1)
    for b in range(8):
        lo, hi = int(starts[b]), int(starts[b + 1])
        padded_ref[b, : hi - lo] = clipped[lo:hi]
    for b in range(8):
        np.testing.assert_array_equal(
            padded_ref[b][np.asarray(got.order)[b]], np.asarray(got.keys)[b]
        )


def test_sort_failure_latches_fallback(monkeypatch):
    import hyperspace_tpu.ops.pallas_sort as ps

    monkeypatch.setenv("HYPERSPACE_PALLAS_SORT", "1")
    monkeypatch.setattr(ps, "_sort_broken", {})

    def boom(*a, **k):
        raise RuntimeError("mosaic lowering failed")

    monkeypatch.setattr(ps, "sort_padded_with_order", boom)
    rng = np.random.RandomState(4)
    n = 2048
    key64 = rng.randint(0, 10**9, n).astype(np.int64)
    starts = np.linspace(0, n, 9).astype(np.int64)
    rep = pad_buckets_by_hash(key64, starts)  # must not raise (XLA fallback)
    assert rep.keys.shape[0] == 8
    assert ps._sort_broken
    assert not ps.pallas_sort_wanted(8, 256)
