"""Test harness: force JAX onto a virtual 8-device CPU platform.

Mirrors the reference's test strategy of running everything on Spark local[4] in-process
(`SparkInvolvedSuite.scala:30-46`): no real cluster/TPU needed; sharding and collectives
are exercised on a virtual 8-device CPU mesh.

Note: this image preloads jax at interpreter start with JAX_PLATFORMS=axon (TPU tunnel),
so a plain env-var default is not enough — we must override the already-created jax
config before the first backend initialization.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_system_path(tmp_path):
    """Per-test index system path (the reference's per-suite systemPath fixture,
    `HyperspaceSuite.scala:25-89`)."""
    p = tmp_path / "indexes"
    return str(p)
