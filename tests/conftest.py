"""Test harness: force JAX onto a virtual 8-device CPU platform.

Mirrors the reference's test strategy of running everything on Spark local[4] in-process
(`SparkInvolvedSuite.scala:30-46`): no real cluster/TPU needed; sharding and collectives
are exercised on a virtual 8-device CPU mesh.

Note: this image preloads jax at interpreter start with JAX_PLATFORMS=axon (TPU tunnel),
so a plain env-var default is not enough — we must override the already-created jax
config before the first backend initialization.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hyperspace_tpu.parallel.mesh import force_virtual_cpu  # noqa: E402

force_virtual_cpu(8)

import pytest  # noqa: E402


@pytest.fixture()
def tmp_system_path(tmp_path):
    """Per-test index system path (the reference's per-suite systemPath fixture,
    `HyperspaceSuite.scala:25-89`)."""
    p = tmp_path / "indexes"
    return str(p)
