"""UDF escape hatch + INTERSECT/EXCEPT set operations (round-5 expression-
surface slice; reference wraps exactly these in its serde,
`index/serde/package.scala:59-186`, and exercises them in
`LogicalPlanSerDeTests.scala`)."""

import os

import numpy as np
import pytest

from hyperspace_tpu import HyperspaceException, IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col, udf
from hyperspace_tpu.hyperspace import (
    Hyperspace,
    disable_hyperspace,
    enable_hyperspace,
)
from hyperspace_tpu.serde.plan_serde import deserialize_plan, serialize_plan


@pytest.fixture()
def session(tmp_path):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
    return s


# Module-scope so the serde round-trip can re-import it.
def double_plus_one(x):
    return None if x is None else 2 * x + 1


def tier_of(q):
    return "big" if q is not None and q > 5 else "small"


class TestUdf:
    def test_with_column_udf_numeric_and_nulls(self, session, tmp_path):
        session.write_parquet(
            {"k": [1, 2, 3], "q": [1, None, 7]}, str(tmp_path / "t")
        )
        f = udf(double_plus_one, "int64")
        df = session.read.parquet(str(tmp_path / "t")).with_column("d", f(col("q")))
        got = {r[0]: r[2] for r in df.select("k", "q", "d").collect().rows()}
        assert got == {1: 3, 2: None, 3: 15}

    def test_udf_string_result_and_filter(self, session, tmp_path):
        session.write_parquet({"q": [1, 9, 3, 8]}, str(tmp_path / "t"))
        tier = udf(tier_of, "string")
        df = (
            session.read.parquet(str(tmp_path / "t"))
            .with_column("tier", tier(col("q")))
            .filter(col("tier") == "big")
            .select("q")
        )
        assert sorted(r[0] for r in df.collect().rows()) == [8, 9]

    def test_index_still_fires_under_udf_projection(self, session, tmp_path):
        """The join index must apply when a UDF column is computed ABOVE the
        join from covered columns (the reference's UDF-tolerance contract)."""
        rng = np.random.RandomState(4)
        session.write_parquet(
            {
                "k": rng.randint(0, 40, 3000).astype(np.int64),
                "qty": rng.randint(1, 9, 3000).astype(np.int64),
            },
            str(tmp_path / "l"),
        )
        session.write_parquet(
            {"k2": np.arange(40, dtype=np.int64), "w": np.arange(40, dtype=np.int64)},
            str(tmp_path / "r"),
        )
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(str(tmp_path / "l")), IndexConfig("ul", ["k"], ["qty"])
        )
        hs.create_index(
            session.read.parquet(str(tmp_path / "r")), IndexConfig("ur", ["k2"], ["w"])
        )
        f = udf(double_plus_one, "int64")

        def q():
            l = session.read.parquet(str(tmp_path / "l"))
            r = session.read.parquet(str(tmp_path / "r"))
            return (
                l.join(r, col("k") == col("k2"))
                .with_column("dq", f(col("qty")))
                .select("dq", "w")
            )

        disable_hyperspace(session)
        expected = q().sorted_rows()
        enable_hyperspace(session)
        assert "ul" in q().explain_string()
        assert q().sorted_rows() == expected

    def test_udf_serde_round_trip(self, session, tmp_path):
        session.write_parquet({"q": [1, 2]}, str(tmp_path / "t"))
        f = udf(double_plus_one, "int64")
        df = session.read.parquet(str(tmp_path / "t")).with_column("d", f(col("q")))
        plan2 = deserialize_plan(serialize_plan(df.plan))
        assert "udf:double_plus_one" in plan2.tree_string()
        from hyperspace_tpu.engine.session import DataFrame

        assert DataFrame(session, plan2).collect().rows() == df.collect().rows()

    def test_udf_lambda_serde_fails_loudly(self, session, tmp_path):
        session.write_parquet({"q": [1]}, str(tmp_path / "t"))
        f = udf(lambda x: x, "int64")
        df = session.read.parquet(str(tmp_path / "t")).with_column("d", f(col("q")))
        with pytest.raises(HyperspaceException, match="cannot round-trip"):
            deserialize_plan(serialize_plan(df.plan))


class TestSetOps:
    def _two(self, session, tmp_path):
        session.write_parquet(
            {"k": [1, 2, 2, 3, None], "v": ["a", "b", "b", "c", "d"]},
            str(tmp_path / "l"),
        )
        session.write_parquet(
            {"k": [2, 3, 4, None], "v": ["b", "zzz", "e", "d"]},
            str(tmp_path / "r"),
        )
        return (
            session.read.parquet(str(tmp_path / "l")),
            session.read.parquet(str(tmp_path / "r")),
        )

    def test_intersect_distinct_null_aware(self, session, tmp_path):
        l, r = self._two(session, tmp_path)
        got = l.intersect(r).sorted_rows()
        # (2,b) in both; (None,d): nulls compare equal in set ops (SQL).
        assert got == sorted([(2, "b"), (None, "d")], key=lambda t: tuple(str(x) for x in t))

    def test_except_distinct(self, session, tmp_path):
        l, r = self._two(session, tmp_path)
        got = l.subtract(r).sorted_rows()
        assert got == sorted(
            [(1, "a"), (3, "c")], key=lambda t: tuple(str(x) for x in t)
        )
        # right-side absent rows don't appear; duplicates deduped.
        assert l.subtract(l).count() == 0

    def test_setop_schema_mismatch_raises(self, session, tmp_path):
        l, _ = self._two(session, tmp_path)
        session.write_parquet({"x": [1]}, str(tmp_path / "other"))
        other = session.read.parquet(str(tmp_path / "other"))
        with pytest.raises(Exception):
            l.intersect(other)

    def test_setop_serde_round_trip(self, session, tmp_path):
        l, r = self._two(session, tmp_path)
        for df in (l.intersect(r), l.subtract(r)):
            plan2 = deserialize_plan(serialize_plan(df.plan))
            from hyperspace_tpu.engine.session import DataFrame

            assert DataFrame(session, plan2).sorted_rows() == df.sorted_rows()

    def test_setop_composes_with_index_rewrites(self, session, tmp_path):
        """A filter under an intersect still gets the filter-index rewrite and
        the oracle equality holds."""
        session.write_parquet(
            {"name": [f"n{i:02d}" for i in range(50)], "v": list(range(50))},
            str(tmp_path / "a"),
        )
        session.write_parquet(
            {"name": [f"n{i:02d}" for i in range(0, 50, 2)], "v": list(range(0, 50, 2))},
            str(tmp_path / "b"),
        )
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(str(tmp_path / "a")),
            IndexConfig("sa", ["name"], ["v"]),
        )

        def q():
            a = session.read.parquet(str(tmp_path / "a")).filter(col("name") < "n10")
            b = session.read.parquet(str(tmp_path / "b"))
            return a.select("name", "v").intersect(b.select("name", "v"))

        disable_hyperspace(session)
        expected = q().sorted_rows()
        enable_hyperspace(session)
        assert "sa" in q().explain_string()
        assert q().sorted_rows() == expected
        assert len(expected) == 5  # n00..n08 even


def test_string_udf_over_zero_rows(session, tmp_path):
    """A rows-eliminating filter beneath a string UDF must not crash (empty
    object arrays can't infer stringness)."""
    session.write_parquet({"q": [1, 2, 3]}, str(tmp_path / "t"))
    tier = udf(tier_of, "string")
    df = (
        session.read.parquet(str(tmp_path / "t"))
        .filter(col("q") > 100)
        .with_column("tier", tier(col("q")))
    )
    assert df.collect().rows() == []
    assert df.schema.names == ["q", "tier"]


def test_udf_scalar_literal_argument(session, tmp_path):
    """A UDF argument that evaluates to a 0-d scalar (literal arithmetic) is
    broadcast as a per-row constant, matching evaluate_column's behavior."""
    from hyperspace_tpu.engine import lit

    session.write_parquet({"q": [1, 2, 3]}, str(tmp_path / "t"))
    f = udf(lambda a, b: a + b, "int64")
    df = session.read.parquet(str(tmp_path / "t")).with_column(
        "z", f(col("q"), lit(2) + lit(3))
    )
    assert [r[1] for r in df.select("q", "z").collect().rows()] == [6, 7, 8]


def test_scalar_subquery_pattern(session, tmp_path):
    """df.scalar(): the scalar-subquery composition (eager, like the
    reference's serde-wrapped ScalarSubquery in spirit)."""
    session.write_parquet({"x": [1, 5, 9, 3]}, str(tmp_path / "t"))
    df = session.read.parquet(str(tmp_path / "t"))
    mx = df.group_by().agg(m=("x", "max")).scalar()
    assert mx == 9
    above_avg = df.filter(col("x") > df.group_by().agg(a=("x", "avg")).scalar())
    assert sorted(r[0] for r in above_avg.collect().rows()) == [5, 9]
    with pytest.raises(HyperspaceException, match="1x1"):
        df.scalar()


def test_udf_of_ufunc_and_distinct_lambdas(session, tmp_path):
    """Non-weakref-able callables (numpy ufuncs) work as UDFs, and two
    distinct same-named lambdas never share a cache identity (repr differs)."""
    session.write_parquet({"q": [1.0, 4.0, 9.0]}, str(tmp_path / "t"))
    sq = udf(np.sqrt, "float64")
    df = session.read.parquet(str(tmp_path / "t")).with_column("r", sq(col("q")))
    assert [r[1] for r in df.select("q", "r").collect().rows()] == [1.0, 2.0, 3.0]
    f1 = udf(lambda x: x + 1, "int64")
    f2 = udf(lambda x: x + 2, "int64")
    assert repr(f1(col("q"))) != repr(f2(col("q")))
