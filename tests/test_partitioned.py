"""Hive-partitioned sources: `root/key=value/.../file.parquet`.

The reference indexes partitioned datasets (partitioned cases throughout
`E2EHyperspaceRulesTests.scala`) and lineage pulls missing partition columns into
the index (`CreateActionBase.scala:176-188`). These tests drive the engine's
partition discovery + the rewrite rules over a partitioned dataset with the
on/off result-equality oracle.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace


@pytest.fixture()
def part_session(tmp_path):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    rng = np.random.RandomState(5)
    root = tmp_path / "events"
    for year in (2023, 2024):
        for country in ("us", "de"):
            d = root / f"year={year}" / f"country={country}"
            os.makedirs(d)
            n = 50
            pq.write_table(
                pa.table(
                    {
                        "uid": rng.randint(0, 40, n).astype(np.int64),
                        "value": rng.randint(0, 1000, n).astype(np.int64),
                    }
                ),
                str(d / "part-00000.parquet"),
            )
    return s, str(root), str(tmp_path)


def test_partition_columns_materialize(part_session):
    s, root, _ = part_session
    df = s.read.parquet(root)
    assert df.schema.names == ["uid", "value", "year", "country"]
    assert df.schema.field("year").dtype == "int64"
    assert df.schema.field("country").dtype == "string"
    assert df.count() == 200
    assert df.filter(col("year") == 2023).count() == 100
    assert df.filter((col("country") == "us") & (col("year") == 2024)).count() == 50
    # grouped over partition column
    rows = df.group_by("country").agg(n=("*", "count")).sorted_rows()
    assert rows == [("de", 100), ("us", 100)]


def test_partition_value_types_and_nulls(part_session, tmp_path):
    s = part_session[0]
    root = tmp_path / "t2"
    for seg, vals in (("k=12", [1]), ("k=__HIVE_DEFAULT_PARTITION__", [2]), ("k=7", [3])):
        d = root / seg
        os.makedirs(d)
        pq.write_table(pa.table({"x": pa.array(vals, type=pa.int64())}), str(d / "f.parquet"))
    df = s.read.parquet(str(root))
    assert df.schema.field("k").dtype == "int64"
    rows = df.select("x", "k").sorted_rows()
    assert rows == [(1, 12), (2, None), (3, 7)]
    # null partition value participates in IS NULL
    assert df.filter(col("k").is_null()).count() == 1


def test_partition_clash_with_data_column_rejected(part_session, tmp_path):
    s = part_session[0]
    root = tmp_path / "t3"
    d = root / "x=1"
    os.makedirs(d)
    pq.write_table(pa.table({"x": pa.array([1], type=pa.int64())}), str(d / "f.parquet"))
    from hyperspace_tpu import HyperspaceException

    with pytest.raises(HyperspaceException, match="Partition column"):
        s.read.parquet(str(root))


def test_filter_index_over_partitioned_source(part_session):
    """E2E filter-index on/off oracle with a partition column in the index."""
    s, root, _ = part_session
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(root),
        IndexConfig("pfIdx", ["country"], ["uid", "value", "year"]),
    )

    def q():
        return (
            s.read.parquet(root)
            .filter(col("country") == "de")
            .select("uid", "value", "year")
        )

    disable_hyperspace(s)
    expected = q().sorted_rows()
    enable_hyperspace(s)
    plan = q().explain_string()
    assert "index=pfIdx" in plan
    got = q().sorted_rows()
    assert got == expected and len(got) == 100


def test_join_index_over_partitioned_source(part_session, tmp_path):
    """E2E join-index on/off oracle where one side is partitioned."""
    s, root, _ = part_session
    s.write_parquet(
        {
            "userId": np.arange(40, dtype=np.int64),
            "name": np.array([f"u{i}" for i in range(40)]),
        },
        str(tmp_path / "users"),
    )
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(root), IndexConfig("evIdx", ["uid"], ["value", "year"])
    )
    hs.create_index(
        s.read.parquet(str(tmp_path / "users")), IndexConfig("uIdx", ["userId"], ["name"])
    )

    def q():
        e = s.read.parquet(root)
        u = s.read.parquet(str(tmp_path / "users"))
        return e.join(u, col("uid") == col("userId")).select("name", "value", "year")

    disable_hyperspace(s)
    expected = q().sorted_rows()
    enable_hyperspace(s)
    plan = q().explain_string()
    assert "bucketed, no exchange" in plan
    got = q().sorted_rows()
    assert got == expected and len(got) == 200


def test_lineage_pulls_missing_partition_columns(part_session):
    """With lineage on, partition columns not in the config land in the index data
    and schema (reference CreateActionBase.scala:176-188)."""
    s, root, base = part_session
    s.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, True)
    hs = Hyperspace(s)
    hs.create_index(s.read.parquet(root), IndexConfig("linIdx", ["uid"], ["value"]))
    s.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, False)

    # Inspect the written index files directly: they must carry year+country.
    idx_dir = os.path.join(base, "indexes", "linIdx", "v__=0")
    f = [x for x in sorted(os.listdir(idx_dir)) if x.startswith("part-")][0]
    t = pq.read_table(os.path.join(idx_dir, f))
    assert "year" in t.column_names and "country" in t.column_names
    assert IndexConstants.DATA_FILE_NAME_COLUMN in t.column_names


def test_incremental_refresh_partitioned(part_session):
    """Appended partition dir + incremental refresh + hybrid-type query oracle."""
    s, root, _ = part_session
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(root), IndexConfig("incIdx", ["uid"], ["value", "country"])
    )
    # New partition arrives.
    d = os.path.join(root, "year=2025", "country=fr")
    os.makedirs(d)
    rng = np.random.RandomState(9)
    pq.write_table(
        pa.table(
            {
                "uid": rng.randint(0, 40, 30).astype(np.int64),
                "value": rng.randint(0, 1000, 30).astype(np.int64),
            }
        ),
        os.path.join(d, "part-00000.parquet"),
    )
    hs.refresh_index("incIdx", mode="incremental")

    def q():
        return (
            s.read.parquet(root).filter(col("uid") == 3).select("uid", "value", "country")
        )

    disable_hyperspace(s)
    expected = q().sorted_rows()
    enable_hyperspace(s)
    got = q().sorted_rows()
    assert got == expected


def test_hybrid_scan_partitioned_append(part_session):
    """Hybrid Scan merges appended rows from a NEW partition dir, carrying the
    partition values, without a rebuild."""
    s, root, _ = part_session
    s.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, True)
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(root),
        IndexConfig("hyIdx", ["country"], ["uid", "value", "year"]),
    )
    d = os.path.join(root, "year=2025", "country=us")
    os.makedirs(d)
    pq.write_table(
        pa.table(
            {
                "uid": pa.array([1, 2], type=pa.int64()),
                "value": pa.array([11, 22], type=pa.int64()),
            }
        ),
        os.path.join(d, "part-00000.parquet"),
    )

    def q():
        return (
            s.read.parquet(root)
            .filter(col("country") == "us")
            .select("uid", "value", "year")
        )

    disable_hyperspace(s)
    expected = q().sorted_rows()
    enable_hyperspace(s)
    got = q().sorted_rows()
    s.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, False)
    assert got == expected and len(got) == 102


def test_relative_path_read_discovers_partitions(part_session, monkeypatch):
    """Partition discovery must not depend on path spelling (relative vs absolute)."""
    s, root, base = part_session
    monkeypatch.chdir(base)
    rel_df = s.read.parquet("events")
    abs_df = s.read.parquet(root)
    assert rel_df.schema.names == abs_df.schema.names
    assert rel_df.count() == abs_df.count() == 200


def test_dataskipping_sketch_on_partition_column(part_session):
    """MinMax sketch over a hive-partition column builds and prunes."""
    s, root, _ = part_session
    from hyperspace_tpu.index.dataskipping import DataSkippingIndexConfig, MinMaxSketch

    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(root), DataSkippingIndexConfig("dsYear", [MinMaxSketch("year")])
    )

    def q():
        return s.read.parquet(root).filter(col("year") == 2023).select("uid", "value")

    disable_hyperspace(s)
    expected = q().sorted_rows()
    enable_hyperspace(s)
    plan = q().explain_string()
    got = q().sorted_rows()
    assert got == expected and len(got) == 100
    assert "pruned by dsYear" in plan or "files pruned" in plan, plan


def test_concat_cache_partition_aware(part_session, tmp_path):
    """The multi-file concat cache must not alias partitioned and plain reads of
    the same files (the partition columns are path facts, not file content)."""
    s, root, _ = part_session
    # Partitioned read first (4 files -> concat cached WITH year/country).
    df1 = s.read.parquet(root)
    assert df1.schema.names == ["uid", "value", "year", "country"]
    assert df1.count() == 200
    r1 = df1.select("uid", "value", "year", "country").collect()
    # Plain read of one partition SUBDIR (2 files, non-partitioned layout below it).
    sub = os.path.join(root, "year=2023")
    df2 = s.read.parquet(sub)
    assert df2.schema.names == ["uid", "value", "country"]
    t2 = df2.collect()
    assert t2.num_rows == 100 and "year" not in t2.column_names
    # Re-run the partitioned read: still carries all partition columns.
    t3 = s.read.parquet(root).collect()
    assert t3.column_names == r1.column_names and t3.num_rows == 200
