"""Computed columns (`with_column`): arithmetic expressions as first-class
columns, including the TPC-H revenue shape `price * (1 - discount)` aggregated
over an indexed join — the real workload BASELINE config-2 describes.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col, lit
from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace


@pytest.fixture()
def wc_session(tmp_path):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    os.makedirs(tmp_path / "li")
    pq.write_table(
        pa.table(
            {
                "okey": pa.array([1, 1, 2, 3, 3], type=pa.int64()),
                "price": pa.array([10.0, 20.0, 30.0, 40.0, None]),
                "discount": pa.array([0.1, 0.0, 0.5, None, 0.2]),
            }
        ),
        str(tmp_path / "li" / "part-00000.parquet"),
    )
    return s, str(tmp_path)


def test_arithmetic_column(wc_session):
    s, base = wc_session
    df = (
        s.read.parquet(os.path.join(base, "li"))
        .with_column("revenue", col("price") * (1 - col("discount")))
        .select("okey", "revenue")
    )
    assert df.schema.field("revenue").dtype == "float64"
    rows = df.collect().rows()
    # rows with a null operand yield null revenue
    assert sorted(rows, key=lambda r: (r[0], r[1] is None, r[1])) == [
        (1, 9.0),
        (1, 20.0),
        (2, 15.0),
        (3, None),
        (3, None),
    ]


def test_replace_existing_column_in_place(wc_session):
    s, base = wc_session
    df = s.read.parquet(os.path.join(base, "li")).with_column(
        "price", col("price") * 2
    )
    assert df.schema.names == ["okey", "price", "discount"]
    got = {r[0:1] + (r[1],) for r in df.select("okey", "price").sorted_rows()}
    assert (1, 20.0) in got and (2, 60.0) in got


def test_division_and_rsub(wc_session):
    s, base = wc_session
    df = (
        s.read.parquet(os.path.join(base, "li"))
        .with_column("half", col("okey") / 2)
        .with_column("neg", 10 - col("okey"))
        .select("half", "neg")
    )
    assert df.schema.field("half").dtype == "float64"
    assert df.schema.field("neg").dtype == "int64"
    rows = df.sorted_rows()
    assert rows[0] == (0.5, 9) and rows[-1] == (1.5, 7)


def test_boolean_computed_column_and_filter(wc_session):
    s, base = wc_session
    df = (
        s.read.parquet(os.path.join(base, "li"))
        .with_column("cheap", col("price") < 25)
        .filter(col("cheap") == True)  # noqa: E712
        .select("okey")
    )
    assert df.sorted_rows() == [(1,), (1,)]


def test_groupby_over_computed_column(wc_session):
    s, base = wc_session
    rows = (
        s.read.parquet(os.path.join(base, "li"))
        .with_column("revenue", col("price") * (1 - col("discount")))
        .group_by("okey")
        .agg(rev=("revenue", "sum"), n=("revenue", "count"))
        .sorted_rows()
    )
    assert rows == [(1, 29.0, 2), (2, 15.0, 1), (3, None, 0)]


def test_revenue_over_indexed_join_oracle(wc_session, tmp_path):
    """TPC-H Q3 shape: revenue aggregation over the indexed join, on/off oracle."""
    s, base = wc_session
    s.write_parquet(
        {
            "o_key": np.array([1, 2, 3], dtype=np.int64),
            "cust": np.array([100, 100, 200], dtype=np.int64),
        },
        str(tmp_path / "ord"),
    )
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "li")),
        IndexConfig("liIdx", ["okey"], ["price", "discount"]),
    )
    hs.create_index(
        s.read.parquet(str(tmp_path / "ord")), IndexConfig("oIdx", ["o_key"], ["cust"])
    )

    def q():
        li = s.read.parquet(os.path.join(base, "li"))
        o = s.read.parquet(str(tmp_path / "ord"))
        return (
            li.join(o, col("okey") == col("o_key"))
            .with_column("revenue", col("price") * (1 - col("discount")))
            .group_by("cust")
            .agg(rev=("revenue", "sum"))
            .order_by(("rev", False))
        )

    disable_hyperspace(s)
    expected = q().collect().rows()
    enable_hyperspace(s)
    plan = q().explain_string()
    assert "bucketed, no exchange" in plan and "WithColumn" in plan
    got = q().collect().rows()
    assert got == expected and len(got) == 2


def test_serde_roundtrip_with_column(wc_session):
    s, base = wc_session
    from hyperspace_tpu.serde import deserialize_plan, serialize_plan

    df = (
        s.read.parquet(os.path.join(base, "li"))
        .with_column("r", col("price") * (lit(1.0) - col("discount")))
    )
    restored = deserialize_plan(serialize_plan(df.plan))
    assert restored.tree_string() == df.plan.tree_string()


def test_string_arithmetic_rejected(wc_session, tmp_path):
    s, _ = wc_session
    s.write_parquet({"a": ["x", "y"]}, str(tmp_path / "str_t"))
    from hyperspace_tpu import HyperspaceException

    with pytest.raises(HyperspaceException, match="Arithmetic"):
        s.read.parquet(str(tmp_path / "str_t")).with_column("b", col("a") * 2)


def test_declared_dtype_matches_execution_f32_i32(wc_session, tmp_path):
    """Schema contract: the executed column's dtype equals the declared one,
    including 32-bit inputs where backend promotion rules differ."""
    s, _ = wc_session
    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(tmp_path / "narrow")
    pq.write_table(
        pa.table(
            {
                "i": pa.array([1, 2, 3], type=pa.int32()),
                "f": pa.array([1.5, 2.5, 3.5], type=pa.float32()),
            }
        ),
        str(tmp_path / "narrow" / "p.parquet"),
    )
    df = (
        s.read.parquet(str(tmp_path / "narrow"))
        .with_column("q", col("i") / col("i"))
        .with_column("p", col("i") * col("i"))
        .with_column("g", col("f") / col("f"))
    )
    t = df.collect()
    for name in ("q", "p", "g"):
        declared = df.schema.field(name).dtype
        assert str(t.column(name).data.dtype) == declared, (
            name, declared, t.column(name).data.dtype
        )


def test_pruned_computed_column_not_evaluated(wc_session, monkeypatch):
    """A computed column dropped by downstream pruning is never evaluated."""
    s, base = wc_session
    import hyperspace_tpu.engine.physical as phys

    calls = {"n": 0}
    real = phys.WithColumnExec.execute

    def spy(self, ctx):
        calls["n"] += 1
        return real(self, ctx)

    monkeypatch.setattr(phys.WithColumnExec, "execute", spy)
    df = (
        s.read.parquet(os.path.join(base, "li"))
        .with_column("revenue", col("price") * (1 - col("discount")))
        .select("okey")
    )
    assert df.count() == 5
    assert df.collect().column_names == ["okey"]
    assert calls["n"] == 0  # elided by the planner


def test_division_by_zero_is_null(wc_session, tmp_path):
    """SQL semantics: x / 0 -> NULL (not inf/nan), and aggregates ignore it."""
    s, _ = wc_session
    s.write_parquet(
        {"a": np.array([10, 20, 30], np.int64), "b": np.array([2, 0, 5], np.int64)},
        str(tmp_path / "div"),
    )
    df = s.read.parquet(str(tmp_path / "div")).with_column("q", col("a") / col("b"))
    rows = df.select("a", "q").sorted_rows()
    assert rows == [(10, 5.0), (20, None), (30, 6.0)]
    agg = df.agg(total=("q", "sum"), n=("q", "count")).sorted_rows()
    assert agg == [(11.0, 2)]


def test_filter_pushdown_enables_filter_index(wc_session):
    """`.with_column(...).filter(src_col)` still uses a filter index: the
    optimizer sinks the filter below the computed column before the rules run."""
    s, base = wc_session
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "li")),
        IndexConfig("fwIdx", ["okey"], ["price", "discount"]),
    )

    def q():
        return (
            s.read.parquet(os.path.join(base, "li"))
            .with_column("revenue", col("price") * (1 - col("discount")))
            .with_column("double_rev", col("revenue") * 2)
            .filter(col("okey") == 1)
            .select("okey", "revenue", "double_rev")
        )

    disable_hyperspace(s)
    expected = q().collect().rows()
    enable_hyperspace(s)
    plan = q().explain_string()
    assert "index=fwIdx" in plan, plan
    got = q().collect().rows()
    assert sorted(map(repr, got)) == sorted(map(repr, expected)) and len(got) == 2


def test_filter_on_computed_column_not_pushed(wc_session):
    """A predicate that references the computed column stays above it (and the
    query still answers correctly)."""
    s, base = wc_session
    df = (
        s.read.parquet(os.path.join(base, "li"))
        .with_column("revenue", col("price") * (1 - col("discount")))
        .filter(col("revenue") > 10)
        .select("okey", "revenue")
    )
    rows = df.collect().rows()
    assert sorted(r[0] for r in rows) == [1, 2]


def test_filter_pushdown_through_filter_stack(wc_session):
    """A source-column filter stacked ABOVE a computed-column filter still sinks
    to the scan (filters commute), so the filter index applies."""
    s, base = wc_session
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "li")),
        IndexConfig("stackIdx", ["okey"], ["price", "discount"]),
    )

    def q():
        return (
            s.read.parquet(os.path.join(base, "li"))
            .with_column("revenue", col("price") * (1 - col("discount")))
            .filter(col("revenue") > 10)
            .filter(col("okey") == 1)
            .select("okey", "revenue")
        )

    disable_hyperspace(s)
    expected = q().collect().rows()
    enable_hyperspace(s)
    plan = q().explain_string()
    assert "index=stackIdx" in plan, plan
    got = q().collect().rows()
    assert sorted(map(repr, got)) == sorted(map(repr, expected))
    # plain filter stacks are NOT reordered
    p2 = (
        s.read.parquet(os.path.join(base, "li"))
        .filter(col("okey") == 1)
        .filter(col("price") > 5)
    )
    t = p2.optimized_plan().tree_string()
    assert t.index("price") < t.index("okey"), t  # outer filter still outermost


def test_literal_arithmetic_column(wc_session):
    """An expression referencing NO columns (lit(2) * lit(3)) must broadcast its
    0-d result to the table length (advisor r3 medium finding)."""
    s, base = wc_session
    df = s.read.parquet(os.path.join(base, "li")).with_column("x", lit(2) * lit(3))
    rows = df.select("okey", "x").collect().rows()
    assert len(rows) == 5
    assert all(r[1] == 6 for r in rows)
    # Float literal arithmetic keeps its dtype through the broadcast.
    df2 = s.read.parquet(os.path.join(base, "li")).with_column("y", lit(1.5) + lit(2.0))
    vals = [r[0] for r in df2.select("y").collect().rows()]
    assert vals == [3.5] * 5
