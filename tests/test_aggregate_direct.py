"""Direct-address (bincount) host aggregation vs the exact host oracle.

`_direct_host_aggregate` fires on the CPU path for bounded-range integer /
dictionary / bool group keys and count/sum/avg/count_distinct aggregates;
every result here is compared against `_host_aggregate` (the collision-repair
oracle) after sorting by group keys."""

import numpy as np
import pytest

from hyperspace_tpu.engine.schema import STRING
from hyperspace_tpu.engine.table import Column, Table
from hyperspace_tpu.ops import aggregate as agg
from hyperspace_tpu.ops.backend import use_device_path


def _sorted_rows(table: Table, group_keys):
    cols = {name: table.column(name) for name in table.schema.names}
    keys = [cols[k].data for k in group_keys]
    order = np.lexsort(tuple(reversed(keys)))
    out = {}
    for name, c in cols.items():
        data = c.data[order]
        valid = None if c.validity is None else c.validity[order]
        out[name] = (data, valid, c.dictionary)
    return out


def _assert_same(a: Table, b: Table, group_keys):
    ra, rb = _sorted_rows(a, group_keys), _sorted_rows(b, group_keys)
    assert set(ra) == set(rb)
    for name in ra:
        da, va, dicta = ra[name]
        db, vb, dictb = rb[name]
        if dicta is not None:
            da, db = dicta[da], dictb[db]
        if va is not None or vb is not None:
            va = va if va is not None else np.ones(len(da), bool)
            vb = vb if vb is not None else np.ones(len(db), bool)
            np.testing.assert_array_equal(va, vb, err_msg=name)
            da, db = da[va], db[vb]
        if np.issubdtype(np.asarray(da).dtype, np.floating):
            np.testing.assert_allclose(da, db, rtol=1e-9, err_msg=name)
        else:
            np.testing.assert_array_equal(da, db, err_msg=name)


def _table(n=5000, seed=0, key_nulls=False, float_key=False, wide_key=False):
    rng = np.random.RandomState(seed)
    vals = rng.rand(n) * 100
    vv = rng.rand(n) > 0.15
    keys = rng.randint(-7, 23, n).astype(np.int64)
    if wide_key:
        keys = keys * (1 << 40)
    cols = {
        "g": Column(
            "float64" if float_key else "int64",
            keys.astype(np.float64) if float_key else keys,
            None,
            (rng.rand(n) > 0.1) if key_nulls else None,
        ),
        "s": Column(
            STRING,
            rng.randint(0, 5, n).astype(np.int32),
            np.array(["a", "b", "c", "d", "e"]),
            None,
        ),
        "b": Column("bool", rng.rand(n) > 0.5, None, None),
        "v": Column("float64", vals, None, vv),
        "w": Column("int64", rng.randint(-1000, 1000, n).astype(np.int64), None, None),
    }
    return Table(cols)


AGGS = [
    ("c_star", "count", None),
    ("c_v", "count", "v"),
    ("s_v", "sum", "v"),
    ("a_v", "avg", "v"),
    ("s_w", "sum", "w"),
    ("cd_w", "count_distinct", "w"),
]


@pytest.mark.parametrize("gk", [["g"], ["g", "s"], ["g", "s", "b"], ["s"], ["b"]])
def test_direct_matches_oracle(gk):
    t = _table()
    direct = agg._direct_host_aggregate(t, gk, [t.column(k) for k in gk], AGGS)
    assert direct is not None, "direct path should fire for these shapes"
    _assert_same(direct, agg._host_aggregate(t, gk, AGGS), gk)


@pytest.mark.skipif(
    # The REAL dispatch gate, not a hand copy: the direct path fires only on
    # the CPU backend without forced device ops (hash_aggregate's condition).
    use_device_path(),
    reason="direct host aggregation is gated off on the device path",
)
def test_hash_aggregate_dispatches_direct_and_matches(monkeypatch):
    t = _table(seed=3)
    fired = []
    real = agg._direct_host_aggregate

    def spy(*a, **k):
        r = real(*a, **k)
        fired.append(r is not None)
        return r

    monkeypatch.setattr(agg, "_direct_host_aggregate", spy)
    out = agg.hash_aggregate(t, ["g", "s"], AGGS)
    # The direct path must actually have produced the result — otherwise the
    # sort path masks a dead optimization (both match the oracle).
    assert fired == [True]
    _assert_same(out, agg._host_aggregate(t, ["g", "s"], AGGS), ["g", "s"])


@pytest.mark.parametrize(
    "kwargs, aggs",
    [
        (dict(key_nulls=True), AGGS),  # null-able key -> fallback
        (dict(float_key=True), AGGS),  # float key -> fallback
        (dict(wide_key=True), AGGS),  # range over the cell budget -> fallback
        (dict(), AGGS + [("mn", "min", "w")]),  # min/max -> fallback
    ],
)
def test_fallback_shapes_return_none_and_sort_path_agrees(kwargs, aggs):
    t = _table(seed=5, **kwargs)
    gk = ["g"]
    assert agg._direct_host_aggregate(t, gk, [t.column(k) for k in gk], aggs) is None
    _assert_same(agg.hash_aggregate(t, gk, aggs), agg._host_aggregate(t, gk, aggs), gk)


def test_int_sum_exact_past_float53():
    # int64 sums must not round through bincount's float64 weights.
    big = np.int64(1) << 52
    t = Table(
        {
            "g": Column("int64", np.array([0, 0, 1], np.int64), None, None),
            "w": Column("int64", np.array([big, 3, 7], np.int64), None, None),
        }
    )
    out = agg._direct_host_aggregate(
        t, ["g"], [t.column("g")], [("s", "sum", "w")]
    )
    s = out.column("s").data
    g = out.column("g").data
    assert s[g == 0][0] == big + 3 and s[g == 1][0] == 7


def test_direct_string_groups_decode():
    t = _table(seed=9)
    out = agg.hash_aggregate(t, ["s"], [("n", "count", None)])
    c = out.column("s")
    assert set(c.dictionary[c.data]) <= {"a", "b", "c", "d", "e"}
    assert int(out.column("n").data.sum()) == t.num_rows
