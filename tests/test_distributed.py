"""Distributed build/join tests on the virtual 8-device CPU mesh.

The reference has no in-repo distribution engine (Spark's shuffle does it all,
SURVEY §2.11); these tests validate the TPU-native replacement: all_to_all bucketed
exchange preserves the global multiset and lands each bucket on its owning device,
and the co-bucketed join step runs with zero collectives.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.parallel import (
    BUCKET_AXIS,
    distributed_bucketed_join_counts,
    distributed_bucketize,
    make_mesh,
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


class TestDistributedBucketize:
    def test_exchange_preserves_rows_and_bucket_ownership(self, mesh):
        n_dev = 8
        num_buckets = 32
        n = 4096
        rng = np.random.RandomState(3)
        keys = rng.randint(0, 10_000, size=n).astype(np.int64)
        payload = np.arange(n, dtype=np.int64)

        from hyperspace_tpu.engine.table import Column
        from hyperspace_tpu.ops.hashing import _SEED1, column_hash_u32

        kcol = Column.from_values(keys)
        h1 = column_hash_u32(kcol, jnp.asarray(keys), _SEED1)

        bucket, valid, (pay_out, keys_out) = distributed_bucketize(
            mesh, h1, [jnp.asarray(payload), jnp.asarray(keys)], [jnp.asarray(keys)], num_buckets
        )
        # Outputs are [n_dev, n_dev*cap]: one padded block row per device.
        bucket = np.asarray(bucket)
        valid = np.asarray(valid).astype(bool)
        pay_out = np.asarray(pay_out)
        keys_out = np.asarray(keys_out)
        assert bucket.shape[0] == n_dev

        # All rows survive exactly once.
        assert valid.sum() == n
        assert sorted(pay_out[valid].tolist()) == sorted(payload.tolist())

        # Each device block holds only its own bucket range, valid rows first,
        # sorted by (bucket, key).
        for d in range(n_dev):
            dvalid = valid[d]
            vb = bucket[d][dvalid]
            if len(vb) == 0:
                continue
            assert (vb * n_dev // num_buckets == d).all()
            nv = int(dvalid.sum())
            assert dvalid[:nv].all()  # valid rows are contiguous at the front
            vk = keys_out[d][dvalid]
            order = np.lexsort((vk, vb))
            assert (order == np.arange(len(vb))).all()

    def test_matches_single_device_bucketing(self, mesh):
        """Same hash function ⇒ distributed bucket assignment agrees with the
        single-device build path."""
        from hyperspace_tpu.engine.table import Column, Table
        from hyperspace_tpu.ops.hashing import _SEED1, column_hash_u32
        from hyperspace_tpu.ops.partition import bucketize_table

        n = 512
        num_buckets = 16
        keys = np.random.RandomState(0).randint(0, 100, n).astype(np.int64)
        t = Table({"k": Column.from_values(keys)})
        sorted_t, starts = bucketize_table(t, ["k"], num_buckets)
        single_sizes = np.diff(starts)

        kcol = Column.from_values(keys)
        h1 = column_hash_u32(kcol, jnp.asarray(keys), _SEED1)
        bucket, valid, _ = distributed_bucketize(
            mesh, h1, [jnp.asarray(keys)], [jnp.asarray(keys)], num_buckets
        )
        bucket = np.asarray(bucket)[np.asarray(valid).astype(bool)]
        dist_sizes = np.bincount(bucket, minlength=num_buckets)
        assert (dist_sizes == single_sizes).all()


class TestDistributedJoin:
    def test_join_counts_with_no_collectives(self, mesh):
        B, cap = 32, 64
        rng = np.random.RandomState(1)
        lk = np.sort(rng.randint(0, 50, size=(B, cap)), axis=1).astype(np.int64)
        rk = np.sort(rng.randint(0, 50, size=(B, cap)), axis=1).astype(np.int64)
        l_len = np.full(B, cap, dtype=np.int64)
        r_len = np.full(B, cap, dtype=np.int64)

        counts = np.asarray(
            distributed_bucketed_join_counts(
                mesh, jnp.asarray(lk), jnp.asarray(rk), jnp.asarray(l_len), jnp.asarray(r_len)
            )
        )
        # Oracle: per-bucket pair counts.
        expect = np.array(
            [
                sum(int((rk[b] == v).sum()) for v in lk[b])
                for b in range(B)
            ]
        )
        assert (counts == expect).all()

        # The compiled HLO must contain no cross-device communication.
        lowered = jax.jit(
            lambda a, b, c, d: distributed_bucketed_join_counts(mesh, a, b, c, d)
        ).lower(jnp.asarray(lk), jnp.asarray(rk), jnp.asarray(l_len), jnp.asarray(r_len))
        hlo = lowered.compile().as_text()
        for coll in ("all-to-all", "all-reduce", "collective-permute", "all-gather"):
            assert coll not in hlo, f"unexpected collective {coll} in bucketed join HLO"

    def test_build_exchange_does_use_all_to_all(self, mesh):
        """Sanity check on the inverse: the build exchange genuinely communicates."""
        from hyperspace_tpu.parallel.distributed import exchange_rows

        n = 256
        h1 = jnp.asarray(np.random.RandomState(2).randint(0, 2**31, n), dtype=jnp.uint32)
        pay = jnp.arange(n, dtype=jnp.int64)
        lowered = jax.jit(
            lambda h, p: exchange_rows(mesh, h, [p], [p], 16, 64)
        ).lower(h1, pay)
        hlo = lowered.compile().as_text()
        assert "all-to-all" in hlo
