"""Streaming scan→filter→aggregate executor: equivalence, fault, and carry
semantics (ISSUE 2 tentpole).

The contract under test: with ``HYPERSPACE_QUERY_STREAMING`` on (the default),
a grouped aggregate over a multi-file scan runs chunked with accumulator carry
and equals the materialized path — exactly for integer/count/min/max/string
outputs and group order, to float-associativity rounding for float sum/avg.
``HYPERSPACE_QUERY_STREAMING=0`` is the byte-identical materialized fallback.
A decoder fault mid-stream fails the query cleanly and poisons no scan-cache
entries. The general-join pairs memo (the same PR's satellite perf fix) is
covered at the bottom.
"""

import os

import numpy as np
import pytest

from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.engine import io as engine_io
from hyperspace_tpu.engine.table import Table


def _rows_close(a, b, tol=1e-9):
    assert len(a) == len(b), (len(a), len(b))
    for ra, rb in zip(a, b):
        assert len(ra) == len(rb)
        for x, y in zip(ra, rb):
            if isinstance(x, float) and isinstance(y, float):
                assert abs(x - y) <= tol * max(1.0, abs(x)), (ra, rb)
            else:
                assert x == y, (ra, rb)


def _write_files(base, name, data, n_files):
    n = len(next(iter(data.values())))
    per = (n + n_files - 1) // n_files
    for i in range(n_files):
        sl = slice(i * per, min((i + 1) * per, n))
        if sl.start >= n:
            break
        engine_io.write_parquet(
            Table.from_pydict({k: list(v[sl]) for k, v in data.items()}),
            os.path.join(base, name, f"part-{i:05d}.parquet"),
        )


N_FILES = 6


@pytest.fixture()
def stream_session(tmp_path):
    rng = np.random.RandomState(7)
    n = 6000
    grp = rng.randint(0, 40, n).astype(np.int64)
    sgrp = np.array([f"g{i:02d}" if i % 7 else None for i in grp], dtype=object)
    amount = rng.randint(-50, 50, n).astype(object)
    amount[::11] = None
    price = (rng.rand(n) * 100).astype(object)
    price[::13] = None
    tag = np.array([f"t{i % 17:02d}" for i in rng.randint(0, 999, n)], dtype=object)
    tag[::19] = None
    flag = rng.randint(0, 2, n).astype(bool)
    _write_files(
        str(tmp_path),
        "src",
        {
            "grp": grp,
            "sgrp": sgrp,
            "amount": amount,
            "price": price,
            "tag": tag,
            "flag": flag,
        },
        N_FILES,
    )
    s = HyperspaceSession(warehouse=str(tmp_path))
    return s, os.path.join(str(tmp_path), "src")


ALL_AGGS = dict(
    rows=("*", "count"),
    n=("amount", "count"),
    s=("amount", "sum"),
    sp=("price", "sum"),
    a=("price", "avg"),
    lo=("amount", "min"),
    hi=("amount", "max"),
    tmin=("tag", "min"),
    tmax=("tag", "max"),
    fmin=("flag", "min"),
)


def _on_off(monkeypatch, q):
    """(streamed result, materialized result, streaming stage summary)."""
    from hyperspace_tpu.telemetry.profiling import last_query_stages

    monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
    before = last_query_stages()
    streamed = q().collect()
    stages = last_query_stages()
    ran_stream = stages is not None and stages is not before and stages != before
    monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "0")
    materialized = q().collect()
    return streamed, materialized, (stages if ran_stream else None)


class TestStreamingEquivalence:
    def test_every_agg_fn_multi_file(self, stream_session, monkeypatch):
        s, src = stream_session

        def q():
            return s.read.parquet(src).group_by("grp").agg(**ALL_AGGS)

        streamed, materialized, stages = _on_off(monkeypatch, q)
        assert stages is not None, "streaming path did not run"
        assert stages["chunks"] == N_FILES
        assert stages["stage_counts"]["partial"] == N_FILES
        _rows_close(streamed.sorted_rows(), materialized.sorted_rows())
        # Group ORDER matches the one-pass path too (same key64/direct order).
        assert [r[0] for r in streamed.rows()] == [
            r[0] for r in materialized.rows()
        ]

    def test_string_null_group_keys_and_multi_key(self, stream_session, monkeypatch):
        s, src = stream_session

        def q():
            return (
                s.read.parquet(src)
                .group_by("sgrp", "flag")
                .agg(n=("*", "count"), s=("amount", "sum"), t=("tag", "max"))
            )

        streamed, materialized, stages = _on_off(monkeypatch, q)
        assert stages is not None
        _rows_close(streamed.sorted_rows(), materialized.sorted_rows())

    def test_filter_withcolumn_project_chain(self, stream_session, monkeypatch):
        s, src = stream_session

        def q():
            return (
                s.read.parquet(src)
                .filter((col("amount") > -20) & col("tag").is_not_null())
                .with_column("rev", col("price") * (1 - col("amount") / 100))
                .select("grp", "rev", "amount")
                .group_by("grp")
                .agg(r=("rev", "sum"), lo=("amount", "min"))
            )

        streamed, materialized, stages = _on_off(monkeypatch, q)
        assert stages is not None
        _rows_close(streamed.sorted_rows(), materialized.sorted_rows())

    def test_empty_chunks_mid_stream(self, stream_session, monkeypatch):
        """A filter wiping out entire files leaves empty chunks mid-stream."""
        s, src = stream_session

        def q():
            # grp values are spread over all files; a tight range keeps few rows.
            return (
                s.read.parquet(src)
                .filter(col("amount") == 17)
                .group_by("grp")
                .agg(n=("*", "count"), s=("amount", "sum"))
            )

        streamed, materialized, stages = _on_off(monkeypatch, q)
        assert stages is not None
        _rows_close(streamed.sorted_rows(), materialized.sorted_rows())
        # count+sum over bounded null-free int keys: the one-pass host path
        # takes the direct-address order, and streaming must reproduce it.
        assert [r[0] for r in streamed.rows()] == [
            r[0] for r in materialized.rows()
        ]

    def test_all_rows_filtered_empty_result(self, stream_session, monkeypatch):
        s, src = stream_session

        def q():
            return (
                s.read.parquet(src)
                .filter(col("amount") == 10_000)  # matches nothing
                .group_by("grp")
                .agg(n=("*", "count"), s=("amount", "sum"), t=("tag", "min"))
            )

        streamed, materialized, _ = _on_off(monkeypatch, q)
        assert streamed.num_rows == 0 == materialized.num_rows
        assert streamed.column_names == materialized.column_names
        assert streamed.schema.names == materialized.schema.names
        assert [f.dtype for f in streamed.schema.fields] == [
            f.dtype for f in materialized.schema.fields
        ]

    def test_mixed_width_promotion_with_filtered_file(self, tmp_path, monkeypatch):
        """A wider-typed file whose rows are entirely filtered out must still
        promote the result dtype, exactly as the materialized path's concat
        does — including for the all-rows-filtered empty result."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        s = HyperspaceSession(warehouse=str(tmp_path))
        src = tmp_path / "mixed"
        os.makedirs(src)
        pq.write_table(
            pa.table(
                {
                    "g": pa.array([1, 2, 1], type=pa.int64()),
                    "x": pa.array([5, 6, 7], type=pa.int32()),
                    "keep": pa.array([1, 1, 1], type=pa.int64()),
                }
            ),
            str(src / "part-00000.parquet"),
        )
        pq.write_table(
            pa.table(
                {
                    "g": pa.array([2, 3], type=pa.int64()),
                    "x": pa.array([8, 9], type=pa.int64()),
                    "keep": pa.array([0, 0], type=pa.int64()),
                }
            ),
            str(src / "part-00001.parquet"),
        )

        def q(keep):
            return (
                s.read.parquet(str(src))
                .filter(col("keep") == keep)
                .group_by("g")
                .agg(hi=("x", "max"), sx=("x", "sum"))
            )

        streamed, materialized, stages = _on_off(monkeypatch, lambda: q(1))
        assert stages is not None
        assert streamed.sorted_rows() == materialized.sorted_rows()
        assert [f.dtype for f in streamed.schema.fields] == [
            f.dtype for f in materialized.schema.fields
        ]
        # All rows filtered: the empty result's schema still promotes.
        streamed_e, materialized_e, _ = _on_off(monkeypatch, lambda: q(7))
        assert streamed_e.num_rows == 0 == materialized_e.num_rows
        assert [f.dtype for f in streamed_e.schema.fields] == [
            f.dtype for f in materialized_e.schema.fields
        ]

    def test_chunk_rows_splitting(self, stream_session, monkeypatch):
        """Sub-file chunking (HYPERSPACE_QUERY_CHUNK_ROWS) changes nothing."""
        s, src = stream_session
        from hyperspace_tpu.telemetry.profiling import last_query_stages

        def q():
            return s.read.parquet(src).group_by("grp").agg(**ALL_AGGS)

        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
        monkeypatch.setenv("HYPERSPACE_QUERY_CHUNK_ROWS", "137")
        chunked = q().collect()
        assert last_query_stages()["chunks"] > N_FILES
        monkeypatch.delenv("HYPERSPACE_QUERY_CHUNK_ROWS")
        whole = q().collect()
        _rows_close(chunked.sorted_rows(), whole.sorted_rows())


class TestStreamingGating:
    def test_single_file_source_stays_materialized(self, tmp_path, monkeypatch):
        s = HyperspaceSession(warehouse=str(tmp_path))
        s.write_parquet({"g": [1, 2, 1], "x": [1.0, 2.0, 3.0]}, str(tmp_path / "one"))
        from hyperspace_tpu.telemetry.profiling import last_query_stages

        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
        before = last_query_stages()
        rows = (
            s.read.parquet(str(tmp_path / "one"))
            .group_by("g")
            .agg(s=("x", "sum"))
            .sorted_rows()
        )
        assert rows == [(1, 4.0), (2, 2.0)]
        assert last_query_stages() == before  # no streaming run recorded

    def test_count_distinct_falls_back(self, stream_session, monkeypatch):
        s, src = stream_session
        from hyperspace_tpu.telemetry.profiling import last_query_stages

        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
        before = last_query_stages()

        def q():
            return (
                s.read.parquet(src)
                .group_by("grp")
                .agg(d=("tag", "count_distinct"), n=("*", "count"))
            )

        streamed_era = q().collect()
        assert last_query_stages() == before  # materialized path handled it
        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "0")
        assert streamed_era.sorted_rows() == q().collect().sorted_rows()

    def test_env_zero_disables(self, stream_session, monkeypatch):
        s, src = stream_session
        from hyperspace_tpu.telemetry.profiling import last_query_stages

        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "0")
        before = last_query_stages()
        s.read.parquet(src).group_by("grp").agg(n=("*", "count")).collect()
        assert last_query_stages() == before


class TestDecodePoolContract:
    def test_decode_pool_size_honors_shared_knob(self, monkeypatch):
        """Satellite: `read_files`/streaming/build share ONE threading knob;
        `=1` is the serial path, explicit values cap the pool."""
        from hyperspace_tpu.engine.io import decode_pool_size

        monkeypatch.delenv("HYPERSPACE_BUILD_DECODE_THREADS", raising=False)
        assert decode_pool_size(40) == 16
        assert decode_pool_size(3) == 3
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        assert decode_pool_size(40) == 1
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "4")
        assert decode_pool_size(40) == 4
        assert decode_pool_size(2) == 2

    def test_streaming_serial_threads_equivalent(self, stream_session, monkeypatch):
        s, src = stream_session

        def q():
            return s.read.parquet(src).group_by("grp").agg(**ALL_AGGS)

        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        serial = q().collect()
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "3")
        pooled = q().collect()
        # Same fold order regardless of thread count: EXACT equality, floats
        # included.
        assert serial.rows() == pooled.rows()


class TestStreamingFaults:
    def test_decoder_fault_fails_clean_no_poisoned_cache(
        self, stream_session, monkeypatch
    ):
        s, src = stream_session
        from hyperspace_tpu.engine.scan_cache import global_scan_cache

        global_scan_cache().clear()
        files = sorted(os.listdir(src))
        victim = os.path.join(src, files[3])
        real = engine_io._read_one

        def boom(path, file_format, columns=None):
            if path == victim:
                raise RuntimeError("injected decode fault")
            return real(path, file_format, columns)

        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
        monkeypatch.setattr(engine_io, "_read_one", boom)

        def q():
            return (
                s.read.parquet(src)
                .group_by("grp")
                .agg(n=("*", "count"), s=("amount", "sum"))
            )

        with pytest.raises(RuntimeError, match="injected decode fault"):
            q().collect()
        # The failed file left nothing behind; cached neighbors are intact.
        assert global_scan_cache().missing_columns(victim, ["grp", "amount"]) != []
        monkeypatch.setattr(engine_io, "_read_one", real)
        streamed = q().collect()
        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "0")
        _rows_close(streamed.sorted_rows(), q().collect().sorted_rows())


class TestCountDistinctDevice:
    def test_device_matches_host_oracle(self, monkeypatch):
        """Satellite: grouped count_distinct runs on the device when the
        group-id program did; the host path stays the pinned oracle."""
        monkeypatch.setenv("HYPERSPACE_FORCE_DEVICE_OPS", "1")
        from hyperspace_tpu.ops.aggregate import _host_aggregate, hash_aggregate

        rng = np.random.RandomState(5)
        n = 500
        vals = rng.rand(n).astype(object)
        vals[::9] = None
        vals[1::9] = float("nan")
        vals[2::9] = 0.0
        vals[3::9] = -0.0
        t = Table.from_pydict(
            {
                "k": rng.randint(0, 7, n).tolist(),
                "f": list(vals),
                "s": [f"u{i % 13}" for i in rng.randint(0, 40, n)],
                "i": rng.randint(0, 9, n).tolist(),
            }
        )
        aggs = [
            ("df", "count_distinct", "f"),
            ("ds", "count_distinct", "s"),
            ("di", "count_distinct", "i"),
        ]
        got = hash_aggregate(t, ["k"], aggs)
        exp = _host_aggregate(t, ["k"], aggs)
        assert got.sorted_rows() == exp.sorted_rows()


class TestGeneralJoinPairsMemo:
    def test_pairs_computed_once_across_queries(self, tmp_path, monkeypatch):
        """Steady-state general (non-bucketed) joins reuse the verified pair
        memo instead of re-running the host sort+probe per query."""
        import hyperspace_tpu.ops.join as ops_join
        from hyperspace_tpu.engine import physical

        s = HyperspaceSession(warehouse=str(tmp_path))
        rng = np.random.RandomState(2)
        n = 20_000
        _write_files(
            str(tmp_path),
            "fact",
            {
                "k": rng.randint(0, 500, n).astype(np.int64),
                "v": rng.randint(0, 100, n).astype(np.int64),
            },
            3,
        )
        # Two files per side: the memo keys on table identity, and only
        # multi-file concats are object-stable across queries (single-file
        # reads assemble a fresh Table from the per-column cache each time).
        _write_files(
            str(tmp_path),
            "dim",
            {
                "dk": np.arange(500, dtype=np.int64),
                "w": rng.randint(0, 9, 500).astype(np.int64),
            },
            2,
        )

        def q():
            f = s.read.parquet(str(tmp_path / "fact"))
            d = s.read.parquet(str(tmp_path / "dim"))
            return (
                f.join(d, col("k") == col("dk"))
                .group_by("w")
                .agg(s=("v", "sum"), n=("*", "count"))
            )

        calls = {"n": 0}
        real = ops_join.merge_join_pairs

        def counted(lk, rk):
            calls["n"] += 1
            return real(lk, rk)

        monkeypatch.setattr(physical, "merge_join_pairs", counted)
        first = q().collect().sorted_rows()
        after_first = calls["n"]
        assert after_first >= 1
        second = q().collect().sorted_rows()
        assert calls["n"] == after_first  # memo hit: no re-probe
        _rows_close(first, second)
