"""Pipelined index build: determinism, fault handling, telemetry.

The pipeline's whole contract is that overlap NEVER changes output: the
pipelined build (decode pool + chunked hash/transfer + fused sort + writer
pool) must produce byte-identical index files and an identical log-entry
signature to the serial fallback (`HYPERSPACE_BUILD_DECODE_THREADS=1`, the
pre-pipeline code path). These tests pin that, plus the failure contract
(a worker exception fails the build cleanly: no partial index directory, no
committed log entry) and the stage telemetry the bench surfaces.

This file is tier-1 (`-m 'not slow'`): the threads=2 smoke below exercises
the overlap machinery on every run, not only in bench.py.
"""

import hashlib
import json
import os

import numpy as np
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession
from hyperspace_tpu.engine import io as eio
from hyperspace_tpu.engine.table import Table
from hyperspace_tpu.hyperspace import Hyperspace


def _write_source(src_dir, n=6000, n_files=5, strings=False, nulls=False, seed=3):
    rng = np.random.RandomState(seed)
    per = n // n_files
    for i in range(n_files):
        d = {
            "k": (
                np.array([f"key-{v:04d}" for v in rng.randint(0, 200, per)])
                if strings
                else rng.randint(0, 200, per).astype(np.int64)
            ),
            "v": rng.randint(0, 100, per).astype(np.int64),
            "f": rng.rand(per),
        }
        if nulls:
            vals = d["v"].astype(object)
            vals[rng.rand(per) < 0.1] = None
            d["v"] = vals
        eio.write_parquet(
            Table.from_pydict(d), os.path.join(src_dir, f"part-{i:05d}.parquet")
        )


def _build(tmp_path, src_dir, tag, lineage=False, num_buckets=8):
    """One covering-index build in its own warehouse; returns (index data file
    hashes by relative path, the ACTIVE log entry)."""
    base = str(tmp_path / tag)
    s = HyperspaceSession(warehouse=base)
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, os.path.join(base, "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, num_buckets)
    if lineage:
        s.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
    hs = Hyperspace(s)
    hs.create_index(s.read.parquet(src_dir), IndexConfig("idx", ["k"], ["v", "f"]))
    idir = os.path.join(base, "indexes", "idx")
    hashes = {}
    for root, _, fs in os.walk(idir):
        for f in sorted(fs):
            if f.endswith(".parquet"):
                p = os.path.join(root, f)
                hashes[os.path.relpath(p, idir)] = hashlib.sha256(
                    open(p, "rb").read()
                ).hexdigest()
    from hyperspace_tpu.hyperspace import _index_manager_for

    entries = _index_manager_for(s).get_indexes(["ACTIVE"])
    assert len(entries) == 1
    return hashes, entries[0]


def _fresh_caches():
    """Drop all decode/concat caches so a build exercises the cold path."""
    from hyperspace_tpu.engine.scan_cache import (
        global_concat_cache,
        global_scan_cache,
    )

    global_scan_cache().clear()
    cc = global_concat_cache()
    budget = cc.stats()["budget"]
    cc.set_capacity(0)
    cc.set_capacity(budget)


@pytest.mark.parametrize(
    "variant",
    [
        {},
        {"strings": True},
        {"nulls": True},
        {"lineage": True},
    ],
    ids=["ints", "strings", "nulls", "lineage"],
)
def test_pipelined_build_is_byte_identical_to_serial(tmp_path, monkeypatch, variant):
    """threads>1 must produce byte-identical index files AND an identical
    IndexLogEntry signature to the serial (threads=1) build."""
    lineage = variant.pop("lineage", False)
    src = str(tmp_path / "src")
    _write_source(src, **variant)

    monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
    _fresh_caches()
    serial_hashes, serial_entry = _build(tmp_path, src, "serial", lineage=lineage)

    monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "3")
    _fresh_caches()
    piped_hashes, piped_entry = _build(tmp_path, src, "piped", lineage=lineage)

    assert len(serial_hashes) > 0
    assert piped_hashes == serial_hashes
    assert piped_entry.signature().value == serial_entry.signature().value
    assert piped_entry.schema_json == serial_entry.schema_json
    # Inventories live under different warehouses: compare basename + size.
    assert sorted(
        (os.path.basename(f.name), f.size) for f in piped_entry.content.file_infos()
    ) == sorted(
        (os.path.basename(f.name), f.size) for f in serial_entry.content.file_infos()
    )


def test_pipelined_build_warm_cache_identical(tmp_path, monkeypatch):
    """The warm-concat shortcut (a prior scan populated the caches) produces
    the same bytes as a cold pipelined build."""
    src = str(tmp_path / "src")
    _write_source(src)
    monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "3")
    _fresh_caches()
    cold_hashes, _ = _build(tmp_path, src, "cold")
    # Warm every cache level with a scan over the exact build projection.
    base = str(tmp_path / "warm")
    s = HyperspaceSession(warehouse=base)
    s.read.parquet(src).select("k", "v", "f").count()
    warm_hashes, _ = _build(tmp_path, src, "warm")
    assert warm_hashes == cold_hashes


def test_pipelined_build_forced_device_ops_identical(tmp_path, monkeypatch):
    """The device program (fused bucketize+sort, staged chunk buffers) matches
    the serial device path bit-for-bit — certified on XLA-CPU via
    HYPERSPACE_FORCE_DEVICE_OPS, the same lever CI uses."""
    src = str(tmp_path / "src")
    _write_source(src)
    monkeypatch.setenv("HYPERSPACE_FORCE_DEVICE_OPS", "1")
    monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
    _fresh_caches()
    serial_hashes, _ = _build(tmp_path, src, "dev_serial")
    monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "3")
    _fresh_caches()
    piped_hashes, _ = _build(tmp_path, src, "dev_piped")
    assert piped_hashes == serial_hashes and len(piped_hashes) > 0


def test_pallas_composite_sort_matches_stable_lax_sort(monkeypatch):
    """The Pallas in-VMEM composite build sort (bucket,key,row packed into one
    int64) must reproduce the STABLE `lax.sort` permutation exactly — the
    row-index tiebreaker makes the unstable bitonic network deterministic.
    Certified in interpret mode off-TPU, like the other Pallas kernels."""
    import jax.numpy as jnp

    from hyperspace_tpu.engine.table import Column
    from hyperspace_tpu.ops.hashing import bucket_id
    from hyperspace_tpu.ops.partition import (
        _sort_perm,
        _sortable,
        pallas_composite_build_sort,
    )

    monkeypatch.setenv("HYPERSPACE_PALLAS_SORT", "1")
    rng = np.random.RandomState(0)
    n, nb = 5000, 16
    key = rng.randint(0, 300, n).astype(np.int64)  # heavy duplicates
    col = Column.from_values(key)
    arr = jnp.asarray(key)
    b = bucket_id([col], [arr], nb)
    res = pallas_composite_build_sort(b, arr, n, nb)
    assert res is not None, "pallas composite path not taken"
    perm_p, sb_p = res
    perm_x, sb_x = _sort_perm(b, (_sortable(arr),), n)
    assert np.array_equal(np.asarray(perm_x), perm_p)
    assert np.array_equal(np.asarray(sb_x), sb_p)


def _failing_session(tmp_path, tag):
    base = str(tmp_path / tag)
    s = HyperspaceSession(warehouse=base)
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, os.path.join(base, "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    return s, base


def _assert_clean_failure(s, base):
    """No partial index data directory; no committed (ACTIVE/stable) entry."""
    idir = os.path.join(base, "indexes", "idx")
    data_dirs = [
        d
        for d in (os.listdir(idir) if os.path.isdir(idir) else [])
        if d.startswith(IndexConstants.INDEX_VERSION_DIR_PREFIX)
    ]
    assert data_dirs == [], data_dirs
    from hyperspace_tpu.hyperspace import _index_manager_for

    assert _index_manager_for(s).get_indexes(["ACTIVE"]) == []


def test_decode_worker_failure_fails_build_cleanly(tmp_path, monkeypatch):
    src = str(tmp_path / "src")
    _write_source(src)
    monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "3")
    _fresh_caches()
    s, base = _failing_session(tmp_path, "decode_fail")

    from hyperspace_tpu.index import build_pipeline

    real = build_pipeline._decode_file

    def boom(path, *a, **k):
        if path.endswith("part-00002.parquet"):
            raise RuntimeError("decode worker down")
        return real(path, *a, **k)

    monkeypatch.setattr(build_pipeline, "_decode_file", boom)
    with pytest.raises(Exception, match="decode worker down"):
        Hyperspace(s).create_index(
            s.read.parquet(src), IndexConfig("idx", ["k"], ["v", "f"])
        )
    _assert_clean_failure(s, base)


def test_write_worker_failure_fails_build_cleanly(tmp_path, monkeypatch):
    src = str(tmp_path / "src")
    _write_source(src)
    monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "3")
    _fresh_caches()
    s, base = _failing_session(tmp_path, "write_fail")

    from hyperspace_tpu.index.build_pipeline import _BucketWriter

    real = _BucketWriter.write_bucket

    def boom(self, b, lo, hi):
        if b == 2:
            raise RuntimeError("writer down")
        return real(self, b, lo, hi)

    monkeypatch.setattr(_BucketWriter, "write_bucket", boom)
    with pytest.raises(Exception, match="writer down"):
        Hyperspace(s).create_index(
            s.read.parquet(src), IndexConfig("idx", ["k"], ["v", "f"])
        )
    _assert_clean_failure(s, base)


def test_serial_build_failure_also_cleans_data_dir(tmp_path, monkeypatch):
    """The failure contract holds on the serial fallback too."""
    src = str(tmp_path / "src")
    _write_source(src)
    monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
    _fresh_caches()
    s, base = _failing_session(tmp_path, "serial_fail")
    monkeypatch.setattr(
        eio, "write_parquet", lambda *a, **k: (_ for _ in ()).throw(RuntimeError("io down"))
    )
    with pytest.raises(Exception, match="io down"):
        Hyperspace(s).create_index(
            s.read.parquet(src), IndexConfig("idx", ["k"], ["v", "f"])
        )
    _assert_clean_failure(s, base)


def test_pipeline_smoke_records_stage_telemetry(tmp_path, monkeypatch):
    """Fast tier-1 smoke (threads=2): the pipelined path runs, and records the
    decode/hash/sort/write stage counters bench.py surfaces."""
    src = str(tmp_path / "src")
    _write_source(src, n=2000, n_files=3)
    monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "2")
    monkeypatch.setenv("HYPERSPACE_BUILD_WRITERS", "2")
    _fresh_caches()
    _build(tmp_path, src, "smoke")
    from hyperspace_tpu.telemetry.profiling import last_build_stages

    stages = last_build_stages()
    assert stages is not None
    assert stages["mode"].startswith("pipelined")
    assert stages["decode_threads"] == 2 and stages["writers"] == 2
    assert stages["rows"] > 0
    for key in ("decode_s", "sort_s", "write_s", "wall_s", "overlap_ratio"):
        assert key in stages, stages
    assert json.dumps(stages)  # bench_detail-serializable


def test_pipeline_queries_see_identical_data(tmp_path, monkeypatch):
    """End to end: an indexed join over a pipelined build returns the same
    rows as over the serial build."""
    from hyperspace_tpu.engine import col
    from hyperspace_tpu.hyperspace import enable_hyperspace

    src = str(tmp_path / "src")
    _write_source(src)
    dim = str(tmp_path / "dim")
    rng = np.random.RandomState(9)
    eio.write_parquet(
        Table.from_pydict(
            {
                "k2": np.arange(200, dtype=np.int64),
                "w": rng.randint(1, 9, 200).astype(np.int64),
            }
        ),
        os.path.join(dim, "part-00000.parquet"),
    )
    counts = {}
    for threads, tag in (("1", "ser"), ("3", "pip")):
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", threads)
        _fresh_caches()
        base = str(tmp_path / f"q_{tag}")
        s = HyperspaceSession(warehouse=base)
        s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, os.path.join(base, "indexes"))
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
        hs = Hyperspace(s)
        hs.create_index(s.read.parquet(src), IndexConfig("liIdx", ["k"], ["v"]))
        hs.create_index(s.read.parquet(dim), IndexConfig("dimIdx", ["k2"], ["w"]))
        enable_hyperspace(s)
        q = s.read.parquet(src).join(
            s.read.parquet(dim), col("k") == col("k2")
        ).select("v", "w")
        assert "liIdx" in q.explain_string()
        counts[tag] = q.count()
    assert counts["ser"] == counts["pip"] > 0
