"""Multi-tenant serving-layer contracts (docs/serving.md).

The serving oracle: concurrent traffic through `serve.QueryServer` returns
byte-identical results to serial single-caller execution — under priority
lanes, admission rejections, single-flight cache sharing, injected faults,
and the ``HYPERSPACE_SERVING=0`` fallback. Single-flight edge cases (leader
failure, leader timeout, selection aliasing) and the concurrency-safety
audit of the shared caches (two-thread same-cold-scan stress, pinned
miss-count semantics) live here too.
"""

import json
import os
import threading
import time

import pytest

from hyperspace_tpu import resilience
from hyperspace_tpu.engine.expr import col
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.exceptions import (
    AdmissionRejectedError,
    HyperspaceException,
    QueryTimeoutError,
    TransientError,
)
from hyperspace_tpu.serve import QueryServer, serving_enabled
from hyperspace_tpu.serve import singleflight as sf
from hyperspace_tpu.telemetry import accounting, faults, metrics, tracing


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    monkeypatch.delenv("HYPERSPACE_FAULTS", raising=False)
    monkeypatch.delenv("HYPERSPACE_QUERY_TIMEOUT_S", raising=False)
    monkeypatch.delenv("HYPERSPACE_SERVING", raising=False)
    monkeypatch.setenv("HYPERSPACE_RETRY_BACKOFF_S", "0.001")
    faults.clear()
    faults.reset_counters()
    accounting.reset_tenant_rollup()
    yield
    faults.clear()
    faults.reset_counters()
    accounting.reset_tenant_rollup()
    # Served (tenant-labeled) queries always carry a ledger; drain the
    # exporter's pending queue so a later suite's exporter test doesn't
    # receive THIS suite's closed ledgers in its frames.
    accounting.drain_pending()


def _clear_caches():
    from hyperspace_tpu.engine.physical import clear_device_memos
    from hyperspace_tpu.engine.scan_cache import (
        global_bucketed_cache,
        global_concat_cache,
        global_filtered_cache,
        global_scan_cache,
    )

    global_scan_cache().clear()
    global_concat_cache().clear()
    global_bucketed_cache().clear()
    global_filtered_cache().clear()
    clear_device_memos()


def _session(tmp_path, n_files=4, rows_per_file=200):
    from hyperspace_tpu.engine import io as eio

    s = HyperspaceSession(warehouse=str(tmp_path))
    src = str(tmp_path / "src")
    for i in range(n_files):
        base = i * rows_per_file
        eio.write_parquet(
            s.create_table(
                {
                    "k": list(range(base, base + rows_per_file)),
                    "v": [j % 7 for j in range(base, base + rows_per_file)],
                }
            ),
            os.path.join(src, f"part-{i:05d}.parquet"),
        )
    return s, src


def _counters():
    return dict(metrics.snapshot()["counters"])


def _delta(before, after=None):
    after = after if after is not None else _counters()
    return {k: after.get(k, 0) - before.get(k, 0) for k in set(after) | set(before)}


# ---------------------------------------------------------------------------
# Scheduler basics + the HYPERSPACE_SERVING=0 oracle
# ---------------------------------------------------------------------------


class TestScheduler:
    def test_concurrent_results_match_serial(self, tmp_path):
        s, src = _session(tmp_path)
        q_scan = lambda: s.read.parquet(src).collect()
        q_agg = lambda: (
            s.read.parquet(src).group_by("v").agg(n=("k", "count"), m=("k", "max")).collect()
        )
        q_point = lambda: s.read.parquet(src).filter(col("k") == 137).collect()
        serial = [q().rows() for q in (q_scan, q_agg, q_point)]
        _clear_caches()
        with QueryServer(max_concurrent=4) as srv:
            futs = [
                srv.submit(q, tenant=f"t{i % 3}")
                for i, q in enumerate((q_scan, q_agg, q_point) * 3)
            ]
            got = [f.result(60).rows() for f in futs]
        for i, rows in enumerate(got):
            assert rows == serial[i % 3], f"query {i} diverged under concurrency"

    def test_serving_off_is_single_caller(self, tmp_path, monkeypatch):
        s, src = _session(tmp_path)
        on_rows = s.read.parquet(src).collect().rows()
        monkeypatch.setenv("HYPERSPACE_SERVING", "0")
        assert not serving_enabled()
        _clear_caches()
        srv = QueryServer(max_concurrent=4)
        fut = srv.submit(lambda: s.read.parquet(src).collect(), tenant="a")
        # The fallback executes INLINE: the future is resolved before
        # submit() returns, no worker thread exists.
        assert fut.done()
        assert fut.result().rows() == on_rows
        assert srv.stats()["workers"] == 0
        srv.close()

    def test_serving_off_propagates_exceptions(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_SERVING", "0")
        srv = QueryServer()

        def boom():
            raise ValueError("inline failure")

        fut = srv.submit(boom)
        assert fut.done()
        with pytest.raises(ValueError, match="inline failure"):
            fut.result()

    def test_run_convenience_and_lane_validation(self):
        with QueryServer(max_concurrent=1) as srv:
            assert srv.run(lambda: 41 + 1) == 42
            with pytest.raises(HyperspaceException, match="lane"):
                srv.submit(lambda: 1, lane="turbo")

    def test_closed_server_rejects_submissions(self):
        srv = QueryServer(max_concurrent=1)
        srv.close()
        with pytest.raises(HyperspaceException, match="closed"):
            srv.submit(lambda: 1)

    def test_interactive_lane_jumps_batch_queue(self):
        """One worker is busy; of the queued work, the interactive submission
        must run before earlier-queued batch submissions."""
        order = []
        started, release = threading.Event(), threading.Event()
        with QueryServer(max_concurrent=1) as srv:
            srv.submit(lambda: (started.set(), release.wait(10), order.append("b0")))
            assert started.wait(10)
            f1 = srv.submit(lambda: order.append("b1"), lane="batch")
            f2 = srv.submit(lambda: order.append("b2"), lane="batch")
            fi = srv.submit(lambda: order.append("i"), lane="interactive")
            release.set()
            for f in (f1, f2, fi):
                f.result(30)
        assert order[0] == "b0" and order[1] == "i", order

    def test_worker_exception_resolves_future_and_releases_slot(self):
        with QueryServer(max_concurrent=1, tenant_budget=1) as srv:

            def boom():
                raise RuntimeError("worker failure")

            with pytest.raises(RuntimeError, match="worker failure"):
                srv.submit(boom, tenant="t").result(30)
            # The failed query's token was released: the tenant can submit again.
            assert srv.run(lambda: 7, tenant="t") == 7

    def test_facade_server_entry_point(self, tmp_path):
        from hyperspace_tpu.hyperspace import Hyperspace

        s, src = _session(tmp_path, n_files=1)
        hs = Hyperspace(s)
        with hs.server(max_concurrent=2) as srv:
            assert isinstance(srv, QueryServer)
            assert srv.run(lambda: s.read.parquet(src).count()) == 200


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_queue_depth_rejection_classified(self):
        release = threading.Event()
        with QueryServer(max_concurrent=1, queue_depth=1) as srv:
            started = threading.Event()
            fut = srv.submit(lambda: (started.set(), release.wait(10), 1)[2])
            assert started.wait(10)
            with pytest.raises(AdmissionRejectedError) as ei:
                srv.submit(lambda: 2, tenant="b")
            assert ei.value.reason == "queue_depth"
            assert ei.value.tenant == "b"
            release.set()
            assert fut.result(30) == 1
        # A rejection is a load-shedding signal, never retry-eligible.
        from hyperspace_tpu.exceptions import is_transient

        assert not is_transient(ei.value)

    def test_tenant_budget_isolates_tenants(self):
        release = threading.Event()
        with QueryServer(max_concurrent=1, tenant_budget=1) as srv:
            started = threading.Event()
            f1 = srv.submit(
                lambda: (started.set(), release.wait(10), 1)[2], tenant="hog"
            )
            assert started.wait(10)
            with pytest.raises(AdmissionRejectedError) as ei:
                srv.submit(lambda: 2, tenant="hog")
            assert ei.value.reason == "tenant_budget"
            # The OTHER tenant is admitted while the hog is over budget.
            f2 = srv.submit(lambda: 42, tenant="quiet")
            release.set()
            assert f1.result(30) == 1 and f2.result(30) == 42

    def test_rejection_counters(self):
        before = _counters()
        release = threading.Event()
        with QueryServer(max_concurrent=1, queue_depth=1, tenant_budget=1) as srv:
            started = threading.Event()
            srv.submit(lambda: (started.set(), release.wait(10)), tenant="a")
            assert started.wait(10)
            with pytest.raises(AdmissionRejectedError):
                srv.submit(lambda: 1, tenant="a")  # tenant budget fires first? no: depth=1
            release.set()
        d = _delta(before)
        assert d.get("serve.admitted", 0) == 1
        assert (
            d.get("serve.rejected.queue_depth", 0)
            + d.get("serve.rejected.tenant_budget", 0)
            == 1
        )

    def test_serve_admit_fault_point(self):
        with QueryServer(max_concurrent=1) as srv:
            with faults.inject("serve.admit", rate=1.0, kind="transient"):
                with pytest.raises(TransientError, match="serve.admit"):
                    srv.submit(lambda: 1, tenant="a")
            # Injection off again: the same submission is admitted.
            assert srv.run(lambda: 1, tenant="a") == 1
        assert faults.injected_count("serve.admit") == 1


# ---------------------------------------------------------------------------
# Single-flight: the dedup acceptance counters + edge cases
# ---------------------------------------------------------------------------


class TestSingleFlight:
    def test_two_identical_cold_scans_decode_once(self, tmp_path):
        """THE acceptance contract: two identical concurrent cold scans
        decode the lake exactly once — one decode per file, one dedup hit."""
        s, src = _session(tmp_path, n_files=4)
        _clear_caches()
        before = _counters()
        barrier = threading.Barrier(2)

        def scan():
            barrier.wait(10)
            return s.read.parquet(src).collect()

        # 3 workers = 2 batch workers (worker 0 is the reserved interactive
        # worker): both scans must really run concurrently for the barrier.
        with QueryServer(max_concurrent=3) as srv:
            f1 = srv.submit(scan, tenant="a")
            f2 = srv.submit(scan, tenant="b")
            r1, r2 = f1.result(60), f2.result(60)
        assert r1.rows() == r2.rows()
        d = _delta(before)
        assert d.get("io.decode.files", 0) == 4, d  # once per file, NOT twice
        assert d.get("serve.singleflight.dedup_hits", 0) == 1, d
        # Miss-count semantics under contention (pinned): the leader's scan
        # counts one per-file miss each; the follower never probes per-file
        # entries — it counts ONE concat miss then is served the concat hit.
        assert d.get("cache.scan.misses", 0) == 4, d
        assert d.get("cache.concat.misses", 0) == 2, d
        assert d.get("cache.concat.hits", 0) == 1, d

    def test_footer_parsed_once_under_concurrency(self, tmp_path):
        s, src = _session(tmp_path, n_files=1)
        from hyperspace_tpu.engine import io as eio

        path = os.path.join(src, "part-00000.parquet")
        _clear_caches()
        faults.reset_counters()
        barrier = threading.Barrier(4)
        out = []

        def probe():
            barrier.wait(10)
            out.append(eio.footer_metadata(path))

        # rate=0 spec: counts io.footer parse calls without injecting.
        with faults.inject("io.footer", rate=0.0):
            threads = [threading.Thread(target=probe) for _ in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(10)
        assert len(out) == 4 and all(m is not None for m in out)
        assert faults.call_count("io.footer") == 1  # ONE parse for 4 callers

    def test_leader_failure_does_not_poison_followers(self):
        """Leader fails → flight cleared, followers retry INDEPENDENTLY and
        succeed; the leader's exception stays with the leader's caller."""
        key = ("test", "leader-fail")
        leader_started, release = threading.Event(), threading.Event()
        cached = {}
        errors, results = [], []

        def leader():
            try:
                sf.shared(
                    key,
                    lambda: (leader_started.set(), release.wait(10), _boom())[-1],
                    lambda: cached.get("v"),
                )
            except TransientError as e:
                errors.append(e)

        def _boom():
            raise TransientError("leader died")

        def follower():
            def attempt():
                cached["v"] = 42
                return 42

            results.append(sf.shared(key, attempt, lambda: cached.get("v")))

        t1 = threading.Thread(target=leader)
        t1.start()
        assert leader_started.wait(10)
        t2 = threading.Thread(target=follower)
        t2.start()
        time.sleep(0.1)  # follower is parked on the flight
        release.set()
        t1.join(10), t2.join(10)
        assert len(errors) == 1 and results == [42]
        assert sf.in_flight_count() == 0

    def test_leader_timeout_unblocks_followers(self):
        """A leader that dies on its own query deadline clears the flight on
        the way out; the waiting follower retries immediately."""
        key = ("test", "leader-timeout")
        leader_started, release = threading.Event(), threading.Event()
        errors, results = [], []

        def leader():
            def attempt():
                leader_started.set()
                release.wait(10)
                raise QueryTimeoutError("leader deadline", 0.1, 0.1)

            try:
                sf.shared(key, attempt, lambda: None)
            except QueryTimeoutError as e:
                errors.append(e)

        def follower():
            results.append(sf.shared(key, lambda: "recovered", lambda: None))

        t1 = threading.Thread(target=leader)
        t1.start()
        assert leader_started.wait(10)
        t2 = threading.Thread(target=follower)
        t2.start()
        release.set()
        t1.join(10), t2.join(10)
        assert len(errors) == 1 and results == ["recovered"]

    def test_follower_wait_bounded_by_own_deadline(self, monkeypatch):
        """A HUNG leader costs a deadlined follower a classified
        QueryTimeoutError — never an unbounded block."""
        key = ("test", "hung-leader")
        leader_started, release = threading.Event(), threading.Event()
        follower_err = []

        def leader():
            sf.shared(key, lambda: (leader_started.set(), release.wait(30), 1)[2], None)

        def follower():
            monkeypatch.setenv("HYPERSPACE_QUERY_TIMEOUT_S", "0.3")
            try:
                with resilience.query_scope("query:test"):
                    sf.shared(key, lambda: 2, lambda: None)
            except QueryTimeoutError as e:
                follower_err.append(e)

        t1 = threading.Thread(target=leader)
        t1.start()
        assert leader_started.wait(10)
        t2 = threading.Thread(target=follower)
        t2.start()
        t2.join(10)
        assert follower_err, "follower did not honor its deadline"
        release.set()
        t1.join(10)

    def test_selection_keys_never_alias(self, tmp_path, monkeypatch):
        """Dedup across pushdown-selection-keyed entries: concurrent reads of
        DISTINCT row-group selections of one file both decode (no aliasing);
        concurrent reads of the SAME selection decode once."""
        from hyperspace_tpu.engine import io as eio

        s = HyperspaceSession(warehouse=str(tmp_path))
        path = str(tmp_path / "rg" / "part-00000.parquet")
        eio.write_parquet(
            s.create_table({"k": list(range(400))}), path, row_group_rows=100
        )
        meta = eio.footer_metadata(path)
        assert meta is not None and len(meta.row_groups) == 4
        _clear_caches()
        meta = eio.footer_metadata(path)
        before = _counters()
        results = {}

        def read(sel, tag):
            barrier.wait(10)
            results[tag] = eio.pruned_file_table(path, "parquet", ["k"], meta, sel)

        barrier = threading.Barrier(2)
        t1 = threading.Thread(target=read, args=((0,), "a"))
        t2 = threading.Thread(target=read, args=((1,), "b"))
        t1.start(), t2.start(), t1.join(10), t2.join(10)
        assert results["a"].num_rows == 100 and results["b"].num_rows == 100
        assert results["a"].column("k").data[0] != results["b"].column("k").data[0]
        d = _delta(before)
        assert d.get("io.decode.files", 0) == 2, d  # distinct selections: no dedup
        assert d.get("serve.singleflight.dedup_hits", 0) == 0, d

        # Same-selection leg: the dedup assertion needs both threads inside
        # the flight window. A fast leader decode can finish before the
        # follower's cache probe (the follower then takes a plain cache hit —
        # decode.files is still 1 but no dedup is recorded), so hold the
        # leader's decode until a follower has actually joined its flight.
        real_read = eio._read_row_groups_one

        def read_after_follower_joins(*args, **kwargs):
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                with sf._lock:
                    if any(fl.waiters > 0 for fl in sf._flights.values()):
                        break
                time.sleep(0.001)
            return real_read(*args, **kwargs)

        monkeypatch.setattr(eio, "_read_row_groups_one", read_after_follower_joins)
        before = _counters()
        barrier = threading.Barrier(2)
        t3 = threading.Thread(target=read, args=((2, 3), "c"))
        t4 = threading.Thread(target=read, args=((2, 3), "d"))
        t3.start(), t4.start(), t3.join(10), t4.join(10)
        assert results["c"].rows() == results["d"].rows()
        d = _delta(before)
        assert d.get("io.decode.files", 0) == 1, d  # same selection: dedup
        assert d.get("serve.singleflight.dedup_hits", 0) == 1, d

    def test_serving_off_disables_flights(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_SERVING", "0")
        s, src = _session(tmp_path, n_files=2)
        _clear_caches()
        before = _counters()
        rows = s.read.parquet(src).collect().rows()
        assert rows
        d = _delta(before)
        assert d.get("serve.singleflight.leaders", 0) == 0, d


# ---------------------------------------------------------------------------
# Concurrency-safety audit: shared caches hammered from competing queries
# ---------------------------------------------------------------------------


class TestCacheContention:
    def test_same_cold_scan_stress(self, tmp_path):
        """Satellite audit: 8 competing threads hammer the same cold scan for
        several cache-cleared rounds — results stay byte-identical and the
        lake decodes once per round (misses pinned: leader pays one per-file
        miss; every follower is served the concat entry)."""
        s, src = _session(tmp_path, n_files=4)
        expected = s.read.parquet(src).collect().rows()
        for round_i in range(3):
            _clear_caches()
            before = _counters()
            barrier = threading.Barrier(8)
            out, errs = [], []

            def scan():
                try:
                    barrier.wait(10)
                    out.append(s.read.parquet(src).collect().rows())
                except BaseException as e:  # pragma: no cover - diagnostic
                    errs.append(e)

            threads = [threading.Thread(target=scan) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30)
            assert not errs, errs
            assert all(rows == expected for rows in out)
            d = _delta(before)
            assert d.get("io.decode.files", 0) == 4, (round_i, d)
            assert d.get("cache.scan.misses", 0) == 4, (round_i, d)

    def test_bucketed_concat_hammer(self, tmp_path):
        """Competing indexed queries share ONE bucketed-concat assembly per
        round; results match the serial oracle."""
        from hyperspace_tpu.config import IndexConstants
        from hyperspace_tpu.hyperspace import Hyperspace, enable_hyperspace
        from hyperspace_tpu.index.index_config import IndexConfig

        s, src = _session(tmp_path, n_files=2)
        s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
        hs = Hyperspace(s)
        hs.create_index(
            s.read.parquet(src), IndexConfig("srvIdx", ["v"], ["k"])
        )
        enable_hyperspace(s)
        q = lambda: s.read.parquet(src).filter(col("v") == 3).collect()
        expected = q().sorted_rows()
        _clear_caches()
        before = _counters()
        barrier = threading.Barrier(4)
        out, errs = [], []

        def run():
            try:
                barrier.wait(10)
                out.append(q().sorted_rows())
            except BaseException as e:  # pragma: no cover - diagnostic
                errs.append(e)

        threads = [threading.Thread(target=run) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errs, errs
        assert all(rows == expected for rows in out)
        d = _delta(before)
        # Whatever concat/filtered level served this plan assembled at most
        # once — competing queries shared the flight instead of re-reading
        # the index files.
        assert d.get("serve.singleflight.leaders", 0) >= 1, d


# ---------------------------------------------------------------------------
# Tenant labels end to end
# ---------------------------------------------------------------------------


class TestTenantLabels:
    def test_ledger_span_and_rollup_carry_tenant(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_TRACING", "1")
        s, src = _session(tmp_path, n_files=2)
        with QueryServer(max_concurrent=2) as srv:
            srv.submit(
                lambda: s.read.parquet(src).collect(), tenant="alice"
            ).result(60)
        trace = tracing.last_trace()
        assert trace is not None
        assert trace.root.attrs.get("tenant") == "alice"
        led = accounting.recent_ledgers()[-1]
        assert led.tenant == "alice"
        assert led.to_dict()["tenant"] == "alice"
        roll = accounting.tenant_rollup()
        assert roll["alice"]["queries"] == 1
        assert roll["alice"]["rows_produced"] == 400

    def test_tenant_label_alone_enables_ledger(self, tmp_path):
        """A served (labeled) query is ALWAYS accounted, even with every
        tracing/exporter sink off — the label is the opt-in."""
        s, src = _session(tmp_path, n_files=1)
        with QueryServer(max_concurrent=1) as srv:
            srv.submit(lambda: s.read.parquet(src).count(), tenant="bob").result(60)
        roll = accounting.tenant_rollup()
        assert roll.get("bob", {}).get("queries") == 1

    def test_unlabeled_queries_stay_out_of_rollup(self, tmp_path):
        s, src = _session(tmp_path, n_files=1)
        s.read.parquet(src).count()
        assert accounting.tenant_rollup() == {}

    def test_prometheus_tenant_series(self, tmp_path):
        from hyperspace_tpu.telemetry import exporter

        s, src = _session(tmp_path, n_files=1)
        with QueryServer(max_concurrent=1) as srv:
            srv.submit(lambda: s.read.parquet(src).count(), tenant="p8s").result(60)
        text = exporter.prometheus_text()
        assert '# TYPE hyperspace_tenant_queries counter' in text
        assert 'hyperspace_tenant_queries{tenant="p8s"} 1' in text

    def test_exporter_frames_carry_tenant_rollup(self, tmp_path):
        from hyperspace_tpu.telemetry.exporter import MetricsExporter

        s, src = _session(tmp_path, n_files=1)
        path = str(tmp_path / "frames.jsonl")
        exp = MetricsExporter(path, 0.05).start()
        try:
            with QueryServer(max_concurrent=1) as srv:
                srv.submit(
                    lambda: s.read.parquet(src).count(), tenant="exp"
                ).result(60)
        finally:
            exp.stop()
        frames = [json.loads(l) for l in open(path)]
        assert frames and frames[-1].get("final") is True
        assert frames[-1]["tenants"]["exp"]["queries"] >= 1


# ---------------------------------------------------------------------------
# Chaos + no-deadlock smoke (the CI legs' unit twins)
# ---------------------------------------------------------------------------


def _mixed_workload(s, src):
    return {
        "scan": lambda: s.read.parquet(src).collect(),
        "agg": lambda: s.read.parquet(src)
        .group_by("v")
        .agg(n=("k", "count"), m=("k", "max"))
        .collect(),
        "point": lambda: s.read.parquet(src).filter(col("k") == 77).collect(),
    }


class TestChaosAndSmoke:
    def test_mixed_workload_per_tenant_byte_identical_under_faults(
        self, tmp_path, monkeypatch
    ):
        """Satellite chaos contract: the N-tenant mixed workload under
        injected transient decode faults returns byte-identical results to
        clean serial execution, with retries observed."""
        monkeypatch.setenv("HYPERSPACE_IO_RETRIES", "6")
        s, src = _session(tmp_path, n_files=4)
        workload = _mixed_workload(s, src)
        clean = {name: q().rows() for name, q in workload.items()}
        _clear_caches()
        before = _counters()
        with faults.inject("io.decode", rate=0.3, kind="transient"):
            with QueryServer(max_concurrent=4) as srv:
                futs = {
                    (name, tenant): srv.submit(q, tenant=tenant)
                    for tenant in ("t1", "t2", "t3")
                    for name, q in workload.items()
                }
                got = {k: f.result(120).rows() for k, f in futs.items()}
        for (name, tenant), rows in got.items():
            assert rows == clean[name], f"{name}/{tenant} diverged under faults"
        d = _delta(before)
        assert d.get("faults.injected", 0) > 0, d
        assert d.get("io.retries.attempts", 0) > 0, d

    def test_eight_thread_mixed_workload_no_deadlock(self, tmp_path, monkeypatch):
        """Satellite CI twin: 8 workers × mixed workload under an ambient
        query timeout — every future resolves (no deadlock), results match
        serial, and single-flight demonstrably deduplicated."""
        monkeypatch.setenv("HYPERSPACE_QUERY_TIMEOUT_S", "60")
        s, src = _session(tmp_path, n_files=4)
        workload = _mixed_workload(s, src)
        serial = {name: q().rows() for name, q in workload.items()}
        _clear_caches()
        before = _counters()
        # Two barrier-synchronized identical cold scans lead the traffic:
        # dedup_hits > 0 must hold deterministically, not by scheduling luck
        # (the ad-hoc mixed overlap below may or may not collide).
        barrier = threading.Barrier(2)

        def cold_scan():
            barrier.wait(30)
            return s.read.parquet(src).collect()

        names = list(workload) * 8
        with QueryServer(max_concurrent=8) as srv:
            futs = [srv.submit(cold_scan, tenant="cold") for _ in range(2)]
            futs += [
                srv.submit(
                    workload[name],
                    tenant=f"t{i % 4}",
                    lane="interactive" if name == "point" else "batch",
                )
                for i, name in enumerate(names)
            ]
            got = [f.result(120).rows() for f in futs]
        assert got[0] == got[1]
        for name, rows in zip(names, got[2:]):
            assert rows == serial[name]
        d = _delta(before)
        assert d.get("serve.singleflight.dedup_hits", 0) > 0, d
        assert d.get("serve.completed", 0) == len(names) + 2, d

    def test_on_off_oracle_byte_identical(self, tmp_path, monkeypatch):
        """The flag contract: the same workload under HYPERSPACE_SERVING=1
        (concurrent) and =0 (inline serial) returns byte-identical rows."""
        s, src = _session(tmp_path, n_files=4)
        workload = _mixed_workload(s, src)
        _clear_caches()
        with QueryServer(max_concurrent=4) as srv:
            futs = {n: srv.submit(q, tenant="x") for n, q in workload.items()}
            on = {n: f.result(60).rows() for n, f in futs.items()}
        monkeypatch.setenv("HYPERSPACE_SERVING", "0")
        _clear_caches()
        srv2 = QueryServer()
        off = {n: srv2.submit(q, tenant="x").result() for n, q in workload.items()}
        srv2.close()
        for n in workload:
            assert on[n] == off[n].rows(), f"{n} diverged between serving modes"
