"""End-to-end index tests: create real indexes on real files, then assert (a) the
rewritten plan scans exactly the index files and (b) results and schema are identical
with Hyperspace on vs off.

Mirrors reference tier 5 (SURVEY §4): `E2EHyperspaceRulesTests.scala` — the
`verifyIndexUsage` oracle (:454-470), filter + join coverage, case-sensitivity both
ways, enable/disable round-trip. Plus `IndexManagerTests`-style CRUD over csv/parquet/
json sources.
"""

import os

import numpy as np
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.hyperspace import (
    Hyperspace,
    disable_hyperspace,
    enable_hyperspace,
    is_hyperspace_enabled,
)


@pytest.fixture()
def session(tmp_path):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
    return s


SAMPLE = {
    "c1": ["2017-09-03", "2017-09-03", "2018-09-03", "2019-10-03", "2019-10-03"],
    "c2": [412, 411, 362, 322, 322],
    "c3": ["facebook", "facebook", "donde", "facebook", "ibraco"],
    "c4": [1, 1, 3, 5, 7],
    "c5": ["productmanager", "areamanager", "areamanager", "productmanager", "areamanager"],
}


def scanned_index_names(df):
    """Names of indexes whose files the physical plan scans."""
    out = set()
    for n in df.physical_plan().collect_nodes():
        rel = getattr(n, "relation", None)
        if rel is not None and rel.index_name:
            out.add(rel.index_name)
    return out


def verify_index_usage(session, make_df, expected_indexes):
    """The reference E2E oracle (`verifyIndexUsage`): same sorted rows and schema with
    hyperspace on vs off; with it on, the plan scans exactly the expected indexes."""
    disable_hyperspace(session)
    df_off = make_df()
    rows_off = df_off.sorted_rows()
    schema_off = [f.name.lower() for f in df_off.collect().schema.fields]

    enable_hyperspace(session)
    df_on = make_df()
    assert scanned_index_names(df_on) == set(expected_indexes)
    rows_on = df_on.sorted_rows()
    schema_on = [f.name.lower() for f in df_on.collect().schema.fields]

    assert rows_on == rows_off
    assert schema_on == schema_off


class TestFilterIndexE2E:
    def test_point_lookup_uses_index(self, session, tmp_path):
        """BASELINE config 1: CoveringIndex point lookup via FilterIndexRule."""
        depts = {
            "deptId": [10, 20, 30, 40, 50],
            "deptName": ["Accounting", "Research", "Sales", "Operations", "Marketing"],
            "loc": ["NY", "DL", "CH", "BO", "SF"],
        }
        session.write_parquet(depts, str(tmp_path / "depts"))
        df = session.read.parquet(str(tmp_path / "depts"))
        hs = Hyperspace(session)
        hs.create_index(df, IndexConfig("deptIndex", ["deptId"], ["deptName"]))

        verify_index_usage(
            session,
            lambda: session.read.parquet(str(tmp_path / "depts"))
            .filter(col("deptId") == 30)
            .select("deptName"),
            ["deptIndex"],
        )

    def test_filter_without_project(self, session, tmp_path):
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(df, IndexConfig("idxAll", ["c3"], ["c1", "c2", "c4", "c5"]))
        verify_index_usage(
            session,
            lambda: session.read.parquet(str(tmp_path / "t")).filter(col("c3") == "facebook"),
            ["idxAll"],
        )

    def test_index_not_used_when_not_covering(self, session, tmp_path):
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(df, IndexConfig("narrow", ["c3"], ["c2"]))
        enable_hyperspace(session)
        q = session.read.parquet(str(tmp_path / "t")).filter(col("c3") == "facebook").select("c1")
        assert scanned_index_names(q) == set()  # c1 not covered

    def test_index_not_used_when_filter_not_on_head_column(self, session, tmp_path):
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(df, IndexConfig("headidx", ["c3", "c2"], ["c1"]))
        enable_hyperspace(session)
        # filter only on c2 (not head col c3) -> no rewrite
        q = session.read.parquet(str(tmp_path / "t")).filter(col("c2") == 322).select("c1")
        assert scanned_index_names(q) == set()

    def test_index_not_used_after_source_data_changes(self, session, tmp_path):
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(df, IndexConfig("sigidx", ["c3"], ["c2"]))
        # Append another file -> file-based signature changes -> index not applicable.
        import hyperspace_tpu.engine.io as eio
        from hyperspace_tpu.engine.table import Table

        eio.write_parquet(
            Table.from_pydict({k: v[:1] for k, v in SAMPLE.items()}),
            str(tmp_path / "t" / "part-00001.parquet"),
        )
        enable_hyperspace(session)
        q = session.read.parquet(str(tmp_path / "t")).filter(col("c3") == "facebook").select("c2")
        assert scanned_index_names(q) == set()
        # and results are still correct (from source)
        assert q.count() == 4

    def test_case_insensitivity_both_ways(self, session, tmp_path):
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(df, IndexConfig("ciidx", ["C3"], ["c2"]))  # config upper-cases
        verify_index_usage(
            session,
            lambda: session.read.parquet(str(tmp_path / "t"))
            .filter(col("c3") == "facebook")
            .select("C2"),  # query flips the case
            ["ciidx"],
        )

    def test_enable_disable_roundtrip(self, session, tmp_path):
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(df, IndexConfig("edidx", ["c3"], ["c2"]))
        assert not is_hyperspace_enabled(session)
        enable_hyperspace(session)
        assert is_hyperspace_enabled(session)
        q = lambda: session.read.parquet(str(tmp_path / "t")).filter(col("c3") == "donde").select("c2")
        assert scanned_index_names(q()) == {"edidx"}
        disable_hyperspace(session)
        disable_hyperspace(session)  # disable twice is a no-op
        assert not is_hyperspace_enabled(session)
        assert scanned_index_names(q()) == set()
        enable_hyperspace(session)
        enable_hyperspace(session)  # idempotent
        assert len(session.extra_optimizations) == 3  # join, filter, data-skipping
        assert scanned_index_names(q()) == {"edidx"}  # round-trip preserves rewrites


class TestJoinIndexE2E:
    def _setup_join(self, session, tmp_path, n=50):
        rng = np.random.RandomState(7)
        lineitem = {
            "orderkey": [int(x) for x in rng.randint(0, n, size=n * 4)],
            "qty": [int(x) for x in rng.randint(1, 50, size=n * 4)],
        }
        orders = {
            "o_orderkey": list(range(n)),
            "o_status": [["O", "F", "P"][i % 3] for i in range(n)],
        }
        session.write_parquet(lineitem, str(tmp_path / "lineitem"))
        session.write_parquet(orders, str(tmp_path / "orders"))
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(str(tmp_path / "lineitem")),
            IndexConfig("liIdx", ["orderkey"], ["qty"]),
        )
        hs.create_index(
            session.read.parquet(str(tmp_path / "orders")),
            IndexConfig("ordIdx", ["o_orderkey"], ["o_status"]),
        )
        return hs

    def test_join_uses_both_indexes_no_shuffle(self, session, tmp_path):
        """BASELINE config 2: two CoveringIndexes; bucketed SMJ with no exchange."""
        self._setup_join(session, tmp_path)

        def make_df():
            l = session.read.parquet(str(tmp_path / "lineitem"))
            o = session.read.parquet(str(tmp_path / "orders"))
            return l.join(o, col("orderkey") == col("o_orderkey")).select("qty", "o_status")

        verify_index_usage(session, make_df, ["liIdx", "ordIdx"])

        # The indexed plan must have NO shuffle and a bucketed SMJ.
        enable_hyperspace(session)
        names = [n.name for n in make_df().physical_plan().collect_nodes()]
        assert names.count("ShuffleExchange") == 0
        assert names.count("SortMergeJoin") == 1
        # while the non-indexed plan has two exchanges
        disable_hyperspace(session)
        names_off = [n.name for n in make_df().physical_plan().collect_nodes()]
        assert names_off.count("ShuffleExchange") == 2

    def test_join_with_filters_on_sides(self, session, tmp_path):
        self._setup_join(session, tmp_path)

        def make_df():
            l = session.read.parquet(str(tmp_path / "lineitem")).filter(col("qty") > 10)
            o = session.read.parquet(str(tmp_path / "orders")).filter(col("o_status") == "O")
            return l.join(o, col("orderkey") == col("o_orderkey")).select("qty", "o_status")

        verify_index_usage(session, make_df, ["liIdx", "ordIdx"])

    def test_join_not_rewritten_if_one_side_missing_index(self, session, tmp_path):
        hs = self._setup_join(session, tmp_path)
        hs.delete_index("ordIdx")
        enable_hyperspace(session)
        l = session.read.parquet(str(tmp_path / "lineitem"))
        o = session.read.parquet(str(tmp_path / "orders"))
        q = l.join(o, col("orderkey") == col("o_orderkey")).select("qty", "o_status")
        assert scanned_index_names(q) == set()

    def test_join_side_projection_narrows_required_columns(self, session, tmp_path):
        """A projection on a join side means the index only needs to cover the
        projected + key columns, not the relation's full schema."""
        session.write_parquet(
            {"orderkey": [1, 2], "qty": [5, 6], "extra1": [0, 0], "extra2": [0, 0]},
            str(tmp_path / "wide"),
        )
        session.write_parquet({"o_orderkey": [1, 2], "o_status": ["O", "F"]}, str(tmp_path / "o2"))
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(str(tmp_path / "wide")),
            IndexConfig("wideIdx", ["orderkey"], ["qty"]),  # does NOT cover extra1/2
        )
        hs.create_index(
            session.read.parquet(str(tmp_path / "o2")),
            IndexConfig("o2Idx", ["o_orderkey"], ["o_status"]),
        )

        def make_df():
            l = session.read.parquet(str(tmp_path / "wide")).select("orderkey", "qty")
            o = session.read.parquet(str(tmp_path / "o2"))
            return l.join(o, col("orderkey") == col("o_orderkey")).select("qty", "o_status")

        verify_index_usage(session, make_df, ["wideIdx", "o2Idx"])

    def test_join_requires_indexed_cols_equal_join_cols(self, session, tmp_path):
        """An index whose indexed cols are a superset of the join cols is NOT usable
        (reference: set equality required)."""
        session.write_parquet({"a": [1, 2], "b": [1, 2], "v": [5, 6]}, str(tmp_path / "l2"))
        session.write_parquet({"a2": [1, 2], "w": [7, 8]}, str(tmp_path / "r2"))
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(str(tmp_path / "l2")), IndexConfig("two", ["a", "b"], ["v"])
        )
        hs.create_index(
            session.read.parquet(str(tmp_path / "r2")), IndexConfig("one", ["a2"], ["w"])
        )
        enable_hyperspace(session)
        l = session.read.parquet(str(tmp_path / "l2"))
        r = session.read.parquet(str(tmp_path / "r2"))
        q = l.join(r, col("a") == col("a2")).select("v", "w")
        assert scanned_index_names(q) == set()


class TestIndexManagerE2E:
    @pytest.mark.parametrize("fmt", ["parquet", "csv", "json", "orc"])
    def test_full_crud_and_refresh_across_formats(self, session, tmp_path, fmt):
        """Reference `IndexManagerTests` (:196-252): CRUD + refresh rebuild across
        csv/parquet/json/orc sources (the reference's format whitelist,
        `LogicalPlanSerDeUtils.scala:223-243`)."""
        path = str(tmp_path / f"src_{fmt}")
        getattr(session, f"write_{fmt}")(SAMPLE, path)
        df = getattr(session.read, fmt)(path)
        hs = Hyperspace(session)
        hs.create_index(df, IndexConfig("fmtIdx", ["c3"], ["c2"]))

        idx = hs.indexes()
        assert idx.to_pydict()["name"] == ["fmtIdx"]
        assert idx.to_pydict()["state"] == ["ACTIVE"]

        # Query via index works and matches source results.
        verify_index_usage(
            session,
            lambda: getattr(session.read, fmt)(path).filter(col("c3") == "facebook").select("c2"),
            ["fmtIdx"],
        )

        # Source changes -> index stale; refresh -> applicable again.
        disable_hyperspace(session)
        import hyperspace_tpu.engine.io as eio
        from hyperspace_tpu.engine.table import Table

        extra = {k: v[:2] for k, v in SAMPLE.items()}
        getattr(eio, f"write_{fmt}")(Table.from_pydict(extra), os.path.join(path, f"extra.{fmt}"))
        enable_hyperspace(session)
        q = lambda: getattr(session.read, fmt)(path).filter(col("c3") == "facebook").select("c2")
        assert scanned_index_names(q()) == set()
        hs.refresh_index("fmtIdx")
        assert scanned_index_names(q()) == {"fmtIdx"}
        assert sorted(q().to_pydict()["c2"]) == [322, 411, 411, 412, 412]

        # delete -> not used; restore -> used; vacuum after delete -> gone.
        hs.delete_index("fmtIdx")
        assert scanned_index_names(q()) == set()
        hs.restore_index("fmtIdx")
        assert scanned_index_names(q()) == {"fmtIdx"}
        hs.delete_index("fmtIdx")
        hs.vacuum_index("fmtIdx")
        assert hs.indexes().num_rows == 0

    def test_lineage_column(self, session, tmp_path):
        """Reference CreateIndexTests lineage coverage: `_data_file_name` records the
        source file of each index row."""
        session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(df, IndexConfig("linIdx", ["c3"], ["c2"]))
        entry = [e for e in hs._manager.get_indexes() if e.name == "linIdx"][0]
        import hyperspace_tpu.engine.io as eio

        t = eio.read_files(entry.content.files(), "parquet")
        assert IndexConstants.DATA_FILE_NAME_COLUMN in t.column_names
        vals = set(t.to_pydict()[IndexConstants.DATA_FILE_NAME_COLUMN])
        assert vals == {f.path for f in df.plan.relation.files}

    def test_index_data_is_bucketed_and_sorted(self, session, tmp_path):
        """Reference DataFrameWriterExtensionsTests: read back bucket files to verify
        the bucketing+sort contract."""
        import jax.numpy as jnp

        import hyperspace_tpu.engine.io as eio
        from hyperspace_tpu.ops.hashing import bucket_id

        n = 100
        data = {"k": [int(x) for x in np.arange(n)[::-1]], "v": [str(i) for i in range(n)]}
        session.write_parquet(data, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(df, IndexConfig("bsIdx", ["k"], ["v"]))
        entry = [e for e in hs._manager.get_indexes() if e.name == "bsIdx"][0]
        files = entry.content.files()
        assert len(files) > 1
        total = 0
        for f in files:
            b = int(os.path.basename(f).split("-")[1].split(".")[0])
            t = eio.read_files([f], "parquet")
            total += t.num_rows
            karr = t.column("k")
            got_buckets = np.asarray(
                bucket_id([karr], [jnp.asarray(karr.data)], entry.num_buckets)
            )
            assert (got_buckets == b).all()  # every row in its bucket
            assert (np.diff(karr.data) >= 0).all()  # sorted within bucket
        assert total == n


class TestMultiInstance:
    def test_two_instances_same_session_see_each_other(self, session, tmp_path):
        """Reference `HyperspaceTests`: two Hyperspace instances over one session
        share the lake state — an index created through one is visible to, and
        usable by, the other (and mutations propagate through the TTL cache)."""
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        hs1 = Hyperspace(session)
        hs2 = Hyperspace(session)
        hs1.create_index(df, IndexConfig("sharedIdx", ["c3"], ["c2"]))
        assert hs2.indexes().to_pydict()["name"] == ["sharedIdx"]
        hs2.delete_index("sharedIdx")
        assert hs1.indexes().to_pydict()["state"] == ["DELETED"]
        hs1.restore_index("sharedIdx")
        verify_index_usage(
            session,
            lambda: session.read.parquet(str(tmp_path / "t"))
            .filter(col("c3") == "facebook")
            .select("c2"),
            ["sharedIdx"],
        )



class TestRuleFailureTelemetry:
    def test_rule_failure_emits_event_and_query_survives(
        self, session, tmp_path, monkeypatch
    ):
        """A programming error inside a rewrite rule must (a) not break the
        query and (b) leave a HyperspaceRuleFailureEvent behind (r3 verdict
        weak item 7)."""
        from hyperspace_tpu.rules import filter_index_rule
        from hyperspace_tpu.telemetry import EventLoggerFactory, RecordingEventLogger
        from hyperspace_tpu.telemetry.events import HyperspaceRuleFailureEvent

        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(str(tmp_path / "t")),
            IndexConfig("failIdx", ["c3"], ["c4"]),
        )
        session.conf.set(
            IndexConstants.EVENT_LOGGER_CLASS,
            "hyperspace_tpu.telemetry.event_logging.RecordingEventLogger",
        )
        logger = EventLoggerFactory.get_logger(
            "hyperspace_tpu.telemetry.event_logging.RecordingEventLogger"
        )
        assert isinstance(logger, RecordingEventLogger)
        logger.events.clear()

        def boom(*a, **k):
            raise RuntimeError("synthetic rule bug")

        monkeypatch.setattr(filter_index_rule, "get_candidate_indexes", boom)
        enable_hyperspace(session)
        df = (
            session.read.parquet(str(tmp_path / "t"))
            .filter(col("c3") == "facebook")
            .select("c4")
        )
        assert scanned_index_names(df) == set()  # rule failed -> no rewrite
        assert df.collect().num_rows == 3  # ...but the query still runs
        failures = [
            e for e in logger.events if isinstance(e, HyperspaceRuleFailureEvent)
        ]
        assert failures, [type(e).__name__ for e in logger.events]
        assert failures[0].rule_name == "FilterIndexRule"
        assert "synthetic rule bug" in failures[0].exception


class TestCaseSensitivityConf:
    """`hyperspace.resolution.caseSensitive` consumed end-to-end (the
    spark.sql.caseSensitive analogue; reference E2EHyperspaceRulesTests:120-133
    exercises both modes)."""

    def test_case_sensitive_create_rejects_wrong_case(self, session, tmp_path):
        from hyperspace_tpu.exceptions import HyperspaceException

        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        session.conf.set(IndexConstants.RESOLUTION_CASE_SENSITIVE, True)
        hs = Hyperspace(session)
        df = session.read.parquet(str(tmp_path / "t"))
        with pytest.raises(HyperspaceException, match="could not be resolved"):
            hs.create_index(df, IndexConfig("csIdx", ["C3"], ["c2"]))

    def test_case_sensitive_rule_requires_exact_case(self, session, tmp_path):
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        session.conf.set(IndexConstants.RESOLUTION_CASE_SENSITIVE, True)
        hs = Hyperspace(session)
        df = session.read.parquet(str(tmp_path / "t"))
        hs.create_index(df, IndexConfig("csIdx", ["c3"], ["c2"]))
        # Exact-case query: rule applies, on/off results identical.
        verify_index_usage(
            session,
            lambda: session.read.parquet(str(tmp_path / "t"))
            .filter(col("c3") == "facebook")
            .select("c2"),
            ["csIdx"],
        )
        # Wrong-case projection: under case-sensitive resolution the covering
        # check must NOT treat C2 as covered by c2.
        enable_hyperspace(session)
        df_wrong = (
            session.read.parquet(str(tmp_path / "t"))
            .filter(col("c3") == "facebook")
            .select("C2")
        )
        assert scanned_index_names(df_wrong) == set()

    def test_case_insensitive_default_still_flips(self, session, tmp_path):
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        session.conf.set(IndexConstants.RESOLUTION_CASE_SENSITIVE, False)
        hs = Hyperspace(session)
        df = session.read.parquet(str(tmp_path / "t"))
        hs.create_index(df, IndexConfig("ciIdx2", ["C3"], ["C2"]))
        verify_index_usage(
            session,
            lambda: session.read.parquet(str(tmp_path / "t"))
            .filter(col("c3") == "facebook")
            .select("c2"),
            ["ciIdx2"],
        )


class TestViews:
    """Named views resolve to their underlying plans, so rewrite rules apply
    through them (reference E2EHyperspaceRulesTests.scala:221-247 covers index
    application on views and catalog tables)."""

    def test_join_over_views_uses_bucketed_index_scans(self, session, tmp_path):
        from hyperspace_tpu.engine.physical import SortMergeJoinExec

        n = 200
        lineitem = {
            "orderkey": (np.arange(n) % 40).tolist(),
            "qty": (np.arange(n) % 7 + 1).tolist(),
        }
        orders = {
            "o_orderkey": list(range(40)),
            "o_custkey": (np.arange(40) % 11).tolist(),
        }
        session.write_parquet(lineitem, str(tmp_path / "lineitem"))
        session.write_parquet(orders, str(tmp_path / "orders"))
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(str(tmp_path / "lineitem")),
            IndexConfig("vLi", ["orderkey"], ["qty"]),
        )
        hs.create_index(
            session.read.parquet(str(tmp_path / "orders")),
            IndexConfig("vOrd", ["o_orderkey"], ["o_custkey"]),
        )
        session.create_view("li_view", session.read.parquet(str(tmp_path / "lineitem")))
        session.create_view("ord_view", session.read.parquet(str(tmp_path / "orders")))

        def q():
            l = session.read.view("li_view")
            o = session.read.view("ORD_VIEW")  # case-insensitive name lookup
            return l.join(o, col("orderkey") == col("o_orderkey")).select(
                "qty", "o_custkey"
            )

        verify_index_usage(session, q, ["vLi", "vOrd"])
        # The join must ride the shuffle-free bucketed path.
        joins = [
            nde
            for nde in q().physical_plan().collect_nodes()
            if isinstance(nde, SortMergeJoinExec)
        ]
        assert joins and joins[0].bucketed

    def test_view_crud(self, session, tmp_path):
        from hyperspace_tpu.exceptions import HyperspaceException

        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        session.create_view("v1", df)
        assert session.read.view("v1").count() == 5
        with pytest.raises(HyperspaceException, match="already exists"):
            session.create_view("V1", df.select("c2"), replace=False)
        session.create_view("v1", df.select("c2"))  # replace
        assert session.read.view("v1").schema.names == ["c2"]
        assert session.drop_view("v1") is True
        assert session.drop_view("v1") is False
        with pytest.raises(HyperspaceException, match="not found"):
            session.read.view("v1")


class TestBucketPreservingFilters:
    """A filter between the index scan and the join preserves bucket structure
    (rows never change buckets; compaction keeps in-bucket order), so the
    co-bucketed no-shuffle join still applies — the analogue of Spark
    propagating outputPartitioning through FilterExec, which is what keeps
    the reference's bucketed index joins shuffle-free under side filters."""

    def test_filtered_join_rides_bucketed_path(self, session, tmp_path):
        from hyperspace_tpu.engine.physical import SortMergeJoinExec

        n = 3000
        rng = np.random.RandomState(21)
        session.write_parquet(
            {
                "okey": rng.randint(0, 200, n).tolist(),
                "qty": rng.randint(1, 9, n).tolist(),
                "ship": rng.randint(0, 100, n).tolist(),
            },
            str(tmp_path / "li"),
        )
        session.write_parquet(
            {
                "okey2": list(range(200)),
                "cust": (np.arange(200) % 17).tolist(),
            },
            str(tmp_path / "ord"),
        )
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(str(tmp_path / "li")),
            IndexConfig("bpfLi", ["okey"], ["qty", "ship"]),
        )
        hs.create_index(
            session.read.parquet(str(tmp_path / "ord")),
            IndexConfig("bpfOrd", ["okey2"], ["cust"]),
        )

        def q():
            l = session.read.parquet(str(tmp_path / "li"))
            o = session.read.parquet(str(tmp_path / "ord"))
            return (
                l.filter((col("ship") >= 20) & (col("ship") < 45))
                .join(o, col("okey") == col("okey2"))
                .select("qty", "cust")
            )

        verify_index_usage(session, q, ["bpfLi", "bpfOrd"])
        joins = [
            nde
            for nde in q().physical_plan().collect_nodes()
            if isinstance(nde, SortMergeJoinExec)
        ]
        assert joins and joins[0].bucketed, q().physical_plan().tree_string()
        # Repeat run exercises the filtered-concat cache.
        c1 = q().count()
        c2 = q().count()
        assert c1 == c2 > 0

    def test_filters_on_both_sides_still_bucketed(self, session, tmp_path):
        from hyperspace_tpu.engine.physical import SortMergeJoinExec

        session.write_parquet(
            {"k": [1, 2, 3, 4, 5, 6] * 50, "v": list(range(300))},
            str(tmp_path / "a"),
        )
        session.write_parquet(
            {"k2": [1, 2, 3, 4, 5, 6] * 20, "w": list(range(120))},
            str(tmp_path / "b"),
        )
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(str(tmp_path / "a")), IndexConfig("bpA", ["k"], ["v"])
        )
        hs.create_index(
            session.read.parquet(str(tmp_path / "b")), IndexConfig("bpB", ["k2"], ["w"])
        )

        def q():
            a = session.read.parquet(str(tmp_path / "a"))
            b = session.read.parquet(str(tmp_path / "b"))
            return (
                a.filter(col("v") > 10)
                .join(b.filter(col("w") < 100), col("k") == col("k2"))
                .select("v", "w")
            )

        verify_index_usage(session, q, ["bpA", "bpB"])
        joins = [
            nde
            for nde in q().physical_plan().collect_nodes()
            if isinstance(nde, SortMergeJoinExec)
        ]
        assert joins and joins[0].bucketed

    def test_filtered_join_with_hybrid_append(self, session, tmp_path):
        """Side filter + hybrid scan together: appended rows are bucketized on
        the fly AND the filter applies over the merged concat (uncacheable —
        hybrid concats depend on query-time source state)."""
        from hyperspace_tpu.engine import io as eio
        from hyperspace_tpu.engine.physical import SortMergeJoinExec
        from hyperspace_tpu.engine.table import Table

        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, True)
        session.write_parquet(
            {"k": [1, 2, 3, 4] * 30, "s": list(range(120))}, str(tmp_path / "hl")
        )
        session.write_parquet(
            {"k2": [1, 2, 3, 4], "w": [10, 20, 30, 40]}, str(tmp_path / "hr")
        )
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(str(tmp_path / "hl")), IndexConfig("hfL", ["k"], ["s"])
        )
        hs.create_index(
            session.read.parquet(str(tmp_path / "hr")), IndexConfig("hfR", ["k2"], ["w"])
        )
        # Append AFTER the build: hybrid scan must pick these up.
        eio.write_parquet(
            Table.from_pydict({"k": [1, 2], "s": [500, 501]}),
            str(tmp_path / "hl" / "part-00001.parquet"),
        )

        def q():
            l = session.read.parquet(str(tmp_path / "hl"))
            r = session.read.parquet(str(tmp_path / "hr"))
            return (
                l.filter(col("s") >= 100)
                .join(r, col("k") == col("k2"))
                .select("s", "w")
            )

        disable_hyperspace(session)
        off = q().sorted_rows()
        enable_hyperspace(session)
        on = q().sorted_rows()
        assert on == off
        assert any(r[0] == 500 for r in on)  # appended row passed the filter
        joins = [
            nde
            for nde in q().physical_plan().collect_nodes()
            if isinstance(nde, SortMergeJoinExec)
        ]
        assert joins and joins[0].bucketed


def test_hash_scheme_version_guard(session, tmp_path):
    """An index recorded under a DIFFERENT bucket-hash scheme must sit out
    (bucket co-location with the current scheme would be silently wrong);
    current-version and legacy (unversioned) entries stay candidates."""
    import json as _json

    from hyperspace_tpu.config import IndexConstants as IC
    from hyperspace_tpu.index.factories import IndexLogManagerFactory

    session.write_parquet(
        {"k": list(range(40)), "v": list(range(40))}, str(tmp_path / "hv")
    )
    hs = Hyperspace(session)
    hs.create_index(
        session.read.parquet(str(tmp_path / "hv")), IndexConfig("hvIdx", ["k"], ["v"])
    )
    enable_hyperspace(session)
    q = lambda: session.read.parquet(str(tmp_path / "hv")).filter(col("k") == 3).select("v")
    assert "hvIdx" in q().explain_string()

    # Rewrite the entry's recorded scheme to a future version: index sits out.
    import os as _os

    idx_root = _os.path.join(str(tmp_path / "indexes"), "hvIdx")
    lm = IndexLogManagerFactory().create(idx_root)
    entry = lm.get_latest_stable_log()
    entry.derived_dataset.properties[IC.HASH_SCHEME_KEY] = "999"
    log_dir = _os.path.join(idx_root, IC.HYPERSPACE_LOG)
    latest = max(int(p) for p in _os.listdir(log_dir) if p.isdigit())
    with open(_os.path.join(log_dir, str(latest)), "w") as f:
        _json.dump(entry.to_json(), f)
    with open(_os.path.join(log_dir, "latestStable"), "w") as f:
        _json.dump(entry.to_json(), f)
    from hyperspace_tpu.hyperspace import _index_manager_for

    _index_manager_for(session).clear_cache()
    assert "hvIdx" not in q().explain_string()
    assert q().to_pydict()["v"] == [3]  # query still correct via the scan
