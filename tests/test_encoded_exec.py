"""Encoded execution: dictionary-code keys through build/scan/join with late
materialization (ISSUE 8 tentpole).

The contract under test: with ``HYPERSPACE_ENCODED_EXEC`` on (the default),
dictionary-encoded parquet string columns enter the engine as codes + a
sorted dictionary WITHOUT ever materializing the N decoded strings
(`engine/encoding.dictionary_array_to_column`), index bucket files are
written as compacted arrow dictionary arrays through ONE shared helper for
the serial and pipelined writers, and every result — values, row order,
aggregate GROUP order, dtypes — is BYTE-IDENTICAL to the
``HYPERSPACE_ENCODED_EXEC=0`` decoded fallback. The oracle matrix covers
nulls, unicode, empty (all-null) dictionaries, dictionary mismatch across
files, the ``HYPERSPACE_ENCODED_DICT_MAX`` large-dictionary fallback, mixed
encoded/plain columns inside one join, and a decode fault mid-scan leaving
no partial encoded cache entry (the PR-7 fault contract).
"""

import glob
import hashlib
import os

import numpy as np
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.engine import encoding
from hyperspace_tpu.engine import io as engine_io
from hyperspace_tpu.engine.table import Column, Table
from hyperspace_tpu.hyperspace import Hyperspace, enable_hyperspace
from hyperspace_tpu.telemetry import metrics

ENV = encoding.ENV_ENCODED_EXEC


@pytest.fixture()
def session(tmp_path):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    return s


def _clear_caches():
    from hyperspace_tpu.engine.physical import clear_device_memos
    from hyperspace_tpu.engine.scan_cache import (
        global_bucketed_cache,
        global_concat_cache,
        global_filtered_cache,
        global_scan_cache,
    )

    global_scan_cache().clear()
    global_concat_cache().clear()
    global_filtered_cache().clear()
    global_bucketed_cache().clear()
    clear_device_memos()


def _encoded_counters():
    return {
        "encoded": encoding.COLUMNS_ENCODED.value,
        "flattened": encoding.COLUMNS_FLATTENED.value,
        "kept_bytes": encoding.BYTES_ENCODED_KEPT.value,
        "mat_bytes": encoding.BYTES_MATERIALIZED.value,
        "dict_written": encoding.COLUMNS_DICT_WRITTEN.value,
        "shared_dict": encoding.VERIFY_SHARED_DICT.value,
        "realigned": encoding.VERIFY_REALIGNED.value,
    }


def _tables_identical(a: Table, b: Table):
    """Byte-level equality: codes, dictionaries, validity, dtype labels, and
    column order — stronger than row equality (the on/off contract)."""
    assert a.column_names == b.column_names
    assert a.schema.names == b.schema.names
    for n in a.column_names:
        ca, cb = a.columns[n], b.columns[n]
        assert ca.dtype == cb.dtype, n
        assert np.array_equal(ca.data, cb.data), n
        if ca.is_string:
            assert np.array_equal(ca.dictionary, cb.dictionary), n
        assert (ca.validity is None) == (cb.validity is None), n
        if ca.validity is not None:
            assert np.array_equal(ca.validity, cb.validity), n


def _on_off(monkeypatch, make_result):
    """(result_on, result_off), each produced COLD (caches cleared)."""
    monkeypatch.setenv(ENV, "1")
    _clear_caches()
    on = make_result()
    monkeypatch.setenv(ENV, "0")
    _clear_caches()
    off = make_result()
    monkeypatch.delenv(ENV, raising=False)
    return on, off


def _write_string_source(base: str, name: str, n_files: int = 2, rows: int = 400):
    """Dictionary-heavy multi-file source: moderate-cardinality string key,
    nulls, unicode, empty strings, plus numeric payloads."""
    rng = np.random.RandomState(3)
    src = os.path.join(base, name)
    names = np.asarray([f"cust#{i:03d}" for i in range(40)] + ["δ-ünïcode", ""])
    for i in range(n_files):
        ks = names[rng.randint(0, len(names), rows)]
        t = Table.from_pydict(
            {
                "k": [None if j % 11 == 0 else str(ks[j]) for j in range(rows)],
                "v": rng.randint(0, 50, rows).tolist(),
                "f": rng.randn(rows).tolist(),
            }
        )
        engine_io.write_parquet(t, os.path.join(src, f"part-{i:05d}.parquet"))
    return src


class TestEncodedDecodedOracle:
    def test_scan_collect_identical_and_encoded_counted(
        self, session, tmp_path, monkeypatch
    ):
        src = _write_string_source(str(tmp_path), "src")
        c0 = _encoded_counters()
        on, off = _on_off(monkeypatch, lambda: session.read.parquet(src).collect())
        c1 = _encoded_counters()
        _tables_identical(on, off)
        # The ON run really took the encoded path (one string column per
        # file) and charged the byte split to both halves.
        assert c1["encoded"] - c0["encoded"] >= 2
        assert c1["kept_bytes"] > c0["kept_bytes"]

    def test_group_by_string_key_group_order_identical(
        self, session, tmp_path, monkeypatch
    ):
        src = _write_string_source(str(tmp_path), "src")

        def q():
            return (
                session.read.parquet(src)
                .group_by("k")
                .agg(n=("v", "count"), sv=("v", "sum"))
                .collect()
            )

        on, off = _on_off(monkeypatch, q)
        _tables_identical(on, off)  # includes GROUP ORDER via codes equality

    def test_filter_and_pushdown_compose(self, session, tmp_path, monkeypatch):
        """Encoded execution composes with PR-5 row-group pushdown: a
        clustered numeric filter prunes row groups while the string payload
        rides encoded — including the all-pruned file's 0-row dictionary
        schema table."""
        src = os.path.join(str(tmp_path), "clustered")
        for i in range(2):
            t = Table.from_pydict(
                {
                    "ts": (np.arange(300, dtype=np.int64) + i * 300).tolist(),
                    "s": [f"tag{j % 7}" for j in range(300)],
                }
            )
            engine_io.write_parquet(
                t, os.path.join(src, f"part-{i:05d}.parquet"), row_group_rows=100
            )
        monkeypatch.setenv("HYPERSPACE_SCAN_PUSHDOWN", "1")

        def q():
            return session.read.parquet(src).filter(col("ts") < 150).collect()

        on, off = _on_off(monkeypatch, q)
        _tables_identical(on, off)
        assert on.num_rows == 150

    def test_dictionary_mismatch_across_files(self, session, tmp_path, monkeypatch):
        """Two files with DISJOINT value sets: the concat's union dictionary
        must come out identical in both modes (codes included)."""
        src = os.path.join(str(tmp_path), "mismatch")
        engine_io.write_parquet(
            Table.from_pydict({"k": ["a", "b", "c"], "v": [1, 2, 3]}),
            os.path.join(src, "part-00000.parquet"),
        )
        engine_io.write_parquet(
            Table.from_pydict({"k": ["x", "y", "a"], "v": [4, 5, 6]}),
            os.path.join(src, "part-00001.parquet"),
        )
        on, off = _on_off(monkeypatch, lambda: session.read.parquet(src).collect())
        _tables_identical(on, off)
        assert list(on.columns["k"].dictionary) == ["a", "b", "c", "x", "y"]

    def test_empty_dictionary_all_null_column(self, session, tmp_path, monkeypatch):
        """An all-null string column writes an EMPTY disk dictionary; the
        encoded read must reproduce the decoded path's ['' ] fill dictionary
        and all-zero codes."""
        src = os.path.join(str(tmp_path), "allnull")
        engine_io.write_parquet(
            Table.from_pydict({"k": [None, None, None], "v": [1, 2, 3]}),
            os.path.join(src, "part-00000.parquet"),
        )
        on, off = _on_off(monkeypatch, lambda: session.read.parquet(src).collect())
        _tables_identical(on, off)
        assert on.to_pydict()["k"] == [None, None, None]

    def test_large_dict_fallback(self, session, tmp_path, monkeypatch):
        """A dictionary above HYPERSPACE_ENCODED_DICT_MAX silently takes the
        flatten path — identical results, `columns_flattened` ticked."""
        src = os.path.join(str(tmp_path), "bigdict")
        engine_io.write_parquet(
            Table.from_pydict(
                {"k": [f"u{i}" for i in range(64)], "v": list(range(64))}
            ),
            os.path.join(src, "part-00000.parquet"),
        )
        monkeypatch.setenv(encoding.ENV_ENCODED_DICT_MAX, "8")
        c0 = _encoded_counters()
        on, off = _on_off(monkeypatch, lambda: session.read.parquet(src).collect())
        c1 = _encoded_counters()
        _tables_identical(on, off)
        assert c1["flattened"] > c0["flattened"]

    def test_mixed_encoded_plain_columns_in_one_join(
        self, session, tmp_path, monkeypatch
    ):
        """One join side's key column written PLAIN (no dictionary page — the
        footer marks it ineligible), the other dictionary-encoded: the
        per-column decision flattens only the plain one, and the join result
        matches the decoded oracle exactly."""
        import pyarrow.parquet as pq

        left = os.path.join(str(tmp_path), "left")
        right = os.path.join(str(tmp_path), "right")
        lt = Table.from_pydict(
            {"k": ["a", "b", "c", "a", None], "lv": [1, 2, 3, 4, 5]}
        )
        rt = Table.from_pydict({"k": ["b", "c", "d", None], "rv": [10, 20, 30, 40]})
        engine_io.write_parquet(lt, os.path.join(left, "part-00000.parquet"))
        os.makedirs(right, exist_ok=True)
        pq.write_table(  # plain-encoded string column: encoded path ineligible
            engine_io.table_to_arrow(rt),
            os.path.join(right, "part-00000.parquet"),
            use_dictionary=False,
        )
        meta = engine_io.footer_metadata(os.path.join(right, "part-00000.parquet"))
        assert meta is not None and meta.dict_cols.get("k") is False

        def q():
            l = session.read.parquet(left)
            r = session.read.parquet(right)
            return (
                l.join(r, col("k") == col("k"))
                .select("k", "lv", "rv")
                .collect()
            )

        on, off = _on_off(monkeypatch, q)
        _tables_identical(on, off)
        assert sorted(on.rows()) == [("b", 2, 10), ("c", 3, 20)]

    def test_fault_mid_scan_leaves_no_partial_encoded_entry(
        self, session, tmp_path, monkeypatch
    ):
        """A decode fault on the encoded path propagates cleanly and caches
        NOTHING — the clean retry decodes from scratch and matches (the PR-7
        only-cache-on-success contract)."""
        from hyperspace_tpu.engine.scan_cache import global_scan_cache

        src = _write_string_source(str(tmp_path), "src", n_files=2)
        monkeypatch.setenv(ENV, "1")
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        _clear_caches()

        real = engine_io._read_one
        boom = {"path": None}

        def failing(path, file_format, columns=None):
            if boom["path"] is None:
                boom["path"] = path
            if path == boom["path"]:
                raise OSError("injected decode fault")
            return real(path, file_format, columns)

        monkeypatch.setattr(engine_io, "_read_one", failing)
        with pytest.raises(OSError, match="injected"):
            session.read.parquet(src).collect()
        assert boom["path"] is not None
        missing = global_scan_cache().missing_columns(boom["path"], ["k", "v", "f"])
        assert missing == ["k", "v", "f"]  # no partial encoded entry
        monkeypatch.setattr(engine_io, "_read_one", real)
        t = session.read.parquet(src).collect()
        assert t.num_rows == 800

    def test_chaos_fault_point_oracle(self, session, tmp_path, monkeypatch):
        """Riding the PR-7 seeded fault registry: transient io.decode faults
        under the encoded path retry to an identical result."""
        from hyperspace_tpu.telemetry import faults

        src = _write_string_source(str(tmp_path), "src")
        monkeypatch.setenv(ENV, "1")
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        monkeypatch.setenv("HYPERSPACE_IO_RETRIES", "6")
        monkeypatch.setenv("HYPERSPACE_RETRY_BACKOFF_S", "0.001")
        _clear_caches()
        clean = session.read.parquet(src).collect()
        faults.configure("io.decode:0.4:transient")
        try:
            _clear_caches()
            chaotic = session.read.parquet(src).collect()
        finally:
            faults.clear()
        assert metrics.counter("faults.io.decode.injected").value > 0
        _tables_identical(clean, chaotic)


class TestEncodedBuild:
    def test_indexed_join_identical_on_off(self, session, tmp_path, monkeypatch):
        """Covering-index build + bucketed string-key join: flag on vs off
        produce identical query results (rows, order, dtypes); the encoded
        build writes dictionary-typed bucket files."""
        import pyarrow.parquet as pq

        left = _write_string_source(str(tmp_path), "left", n_files=2, rows=300)
        right = _write_string_source(str(tmp_path), "right", n_files=1, rows=120)
        hs = Hyperspace(session)

        def run():
            hs.create_index(
                session.read.parquet(left), IndexConfig("encL", ["k"], ["v"])
            )
            hs.create_index(
                session.read.parquet(right), IndexConfig("encR", ["k"], ["f"])
            )
            enable_hyperspace(session)
            out = (
                session.read.parquet(left)
                .join(session.read.parquet(right), col("k") == col("k"))
                .group_by("k")
                .agg(n=("v", "count"))
                .collect()
            )
            hs.delete_index("encL"), hs.vacuum_index("encL")
            hs.delete_index("encR"), hs.vacuum_index("encR")
            return out

        on, off = _on_off(monkeypatch, run)
        _tables_identical(on, off)

    def test_bucket_files_dictionary_preserving(self, session, tmp_path, monkeypatch):
        import pyarrow as pa
        import pyarrow.parquet as pq

        src = _write_string_source(str(tmp_path), "src", n_files=1, rows=100)
        monkeypatch.setenv(ENV, "1")
        _clear_caches()
        Hyperspace(session).create_index(
            session.read.parquet(src), IndexConfig("dp", ["k"], ["v"])
        )
        parts = glob.glob(str(tmp_path / "indexes" / "dp" / "v__=0" / "*.parquet"))
        assert parts
        seen_dict = False
        for p in parts:
            at = pq.read_table(p)
            f = at.schema.field("k")
            assert pa.types.is_dictionary(f.type), f.type
            seen_dict = True
            # Compaction: no bucket file carries values absent from its rows.
            darr = at.column("k").combine_chunks()
            present = set(at.column("k").to_pylist()) - {None}
            assert set(darr.dictionary.to_pylist()) == present
        assert seen_dict

    def test_serial_pipelined_byte_identical_encoded(
        self, session, tmp_path, monkeypatch
    ):
        src = _write_string_source(str(tmp_path), "src", n_files=3, rows=200)
        monkeypatch.setenv(ENV, "1")

        def build(threads: str, name: str):
            monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", threads)
            _clear_caches()
            Hyperspace(session).create_index(
                session.read.parquet(src), IndexConfig(name, ["k"], ["v", "f"])
            )
            files = sorted(
                glob.glob(str(tmp_path / "indexes" / name / "v__=0" / "*.parquet"))
            )
            return {
                os.path.basename(f): hashlib.sha256(open(f, "rb").read()).hexdigest()
                for f in files
            }

        assert build("1", "serIdx") == build("4", "pipIdx")


class TestEncodedCacheAndVerify:
    def test_encoded_hits_counter_and_true_size_charge(
        self, session, tmp_path, monkeypatch
    ):
        """Warm reads of encoded entries tick `cache.scan.encoded_hits`, and
        `cache_bytes_charged` charges the TRUE encoded size (codes +
        dictionary + validity), not the flattened decoded size."""
        from hyperspace_tpu.engine.scan_cache import ScanCache, _column_nbytes

        src = _write_string_source(str(tmp_path), "solo", n_files=1, rows=200)
        path = os.path.join(src, "part-00000.parquet")
        monkeypatch.setenv(ENV, "1")
        _clear_caches()
        t = engine_io.read_files([path], "parquet")
        kc = t.columns["k"]
        true_size = _column_nbytes(kc)
        decoded_size = kc.dictionary[kc.data].nbytes
        assert true_size < decoded_size  # codes+dict beat N flat strings
        c0 = metrics.counter("cache.scan.encoded_hits").value
        engine_io.read_files([path], "parquet")  # whole-file per-column hit
        assert metrics.counter("cache.scan.encoded_hits").value > c0

        cache = ScanCache(capacity_bytes=1 << 20)
        cache.put(path, ["k"], Table({"k": kc}))
        assert cache.stats()["bytes"] == true_size

    def test_ledger_byte_split(self, session, tmp_path, monkeypatch):
        """The per-query ledger distinguishes bytes_encoded_kept from
        bytes_materialized (rendered by explain(analyze=True))."""
        from hyperspace_tpu.telemetry import accounting

        src = _write_string_source(str(tmp_path), "src", n_files=1, rows=200)
        monkeypatch.setenv(ENV, "1")
        monkeypatch.setenv("HYPERSPACE_ACCOUNTING", "1")
        _clear_caches()
        session.read.parquet(src).collect()
        led = accounting.recent_ledgers()[-1].to_dict()
        assert led.get("bytes_encoded_kept", 0) > 0
        assert led.get("bytes_materialized", 0) > 0  # numeric cols flatten

    def test_explain_analyze_renders_byte_split(self, session, tmp_path, monkeypatch):
        src = _write_string_source(str(tmp_path), "src", n_files=1, rows=100)
        monkeypatch.setenv(ENV, "1")
        _clear_caches()
        s = session.read.parquet(src).explain(analyze=True)
        assert "bytes_encoded_kept" in s
        assert "bytes_materialized" in s

    def test_shared_dictionary_verify_fast_path(self):
        """Equal dictionaries skip the union re-encode entirely (codes come
        back untouched); a real mismatch still realigns."""
        from hyperspace_tpu.engine.table import align_dictionaries

        d = np.asarray(["a", "b", "c"])
        a = Column("string", np.asarray([0, 1, 2], np.int32), d)
        b = Column("string", np.asarray([2, 1, 0], np.int32), d.copy())
        s0 = encoding.VERIFY_SHARED_DICT.value
        ra, rb = align_dictionaries(a, b)
        assert ra is a and rb is b
        assert encoding.VERIFY_SHARED_DICT.value == s0 + 1
        c = Column("string", np.asarray([0], np.int32), np.asarray(["z"]))
        r0 = encoding.VERIFY_REALIGNED.value
        ra, rc = align_dictionaries(a, c)
        assert list(ra.dictionary) == ["a", "b", "c", "z"]
        assert encoding.VERIFY_REALIGNED.value == r0 + 1

    def test_streamed_aggregate_oracle_under_encoded(
        self, session, tmp_path, monkeypatch
    ):
        """The streaming executor (PR 2) consumes encoded chunks unchanged:
        streamed == materialized == decoded-fallback, group order included."""
        src = _write_string_source(str(tmp_path), "src", n_files=2, rows=300)

        def q():
            return (
                session.read.parquet(src)
                .filter(col("v") < 40)
                .group_by("k")
                .agg(n=("v", "count"))
                .collect()
            )

        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
        on_stream, off_stream = _on_off(monkeypatch, q)
        _tables_identical(on_stream, off_stream)
        # The materialized leg agrees on VALUES (its group order for
        # nullable keys is first-occurrence, the stream's is the one-pass
        # sort order — the standing PR-2 contract, independent of this flag).
        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "0")
        monkeypatch.setenv(ENV, "1")
        _clear_caches()
        mat = q()
        assert mat.sorted_rows() == on_stream.sorted_rows()
