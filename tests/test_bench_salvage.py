"""The bench's salvage machinery: metric degradation and the transport-death
gate. Round-5 incident: the relay PROCESS died mid-bench (port connection
refused), the builds phase hung forever inside a PJRT reconnect loop, and the
salvaged metric line carried a fabricated value of 0.0 — these tests pin the
behaviors that prevent each part of that failure from recurring."""

import importlib.util
import os
import sys

import pytest

_BENCH_PATH = os.path.join(os.path.dirname(__file__), "..", "bench.py")


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_under_test", _BENCH_PATH)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_under_test"] = mod
    spec.loader.exec_module(mod)
    return mod


def test_metric_full(bench):
    m = bench._metric_from(
        {"rows": 8, "build_s": 5.0, "indexed_join_p50_s": 0.2, "scan_join_p50_s": 4.0}
    )
    assert m["metric"].startswith("tpch(8) index-build+join-p50")
    assert "(partial)" not in m["metric"]
    assert m["value"] == 5.2
    assert m["vs_baseline"] == 20.0


def test_metric_build_only(bench):
    # Device phase order runs builds first: a transport death during the
    # indexed join leaves build-only partials — report them, not 0.0.
    m = bench._metric_from({"rows": 8, "build_s": 5.0, "aborted_at": "x"})
    assert m["metric"] == "tpch(8) index-build (partial)"
    assert m["value"] == 5.0
    assert m["vs_baseline"] is None


def test_metric_degrades_to_indexed_then_scan(bench):
    m = bench._metric_from({"rows": 8, "indexed_join_p50_s": 0.2, "aborted_at": "x"})
    assert m["metric"] == "tpch(8) indexed-join-p50 (partial)"
    assert m["value"] == 0.2
    # Scan-only (the round-5 relay-death shape): value must be the scan
    # number, never a fabricated 0.0.
    m = bench._metric_from({"rows": 8, "scan_join_p50_s": 6.7, "aborted_at": "x"})
    assert m["metric"] == "tpch(8) scan-join-p50 (partial)"
    assert m["value"] == 6.7
    assert m["vs_baseline"] is None


def test_metric_partial_marker_from_skips(bench):
    m = bench._metric_from(
        {"rows": 8, "build_s": 1.0, "indexed_join_p50_s": 0.1, "skipped_phases": ["x"]}
    )
    assert "(partial)" in m["metric"]


def test_transport_death_skips_device_phases_not_host(bench):
    ph = bench._Phases("tpu")
    ran = []
    assert ph.run("ok", lambda: ran.append("ok"))

    def boom():
        raise RuntimeError(
            "UNAVAILABLE: http://127.0.0.1:8083/remote_compile: transport: "
            "Connection Failed: Connect error: Connection refused (os error 111)"
        )

    assert not ph.run("dies", boom)
    assert ph.transport_dead()
    # Device phase is skipped without being entered (a PJRT call against the
    # dead relay hangs in a reconnect loop forever).
    assert not ph.run("device_phase", lambda: ran.append("device"))
    assert "device_phase" in ph.out["skipped_phases"]
    assert ph.out["aborted_at"] == "relay-dead"
    # Host-only phases still run: cache stats etc. need no transport.
    assert ph.run("host_phase", lambda: ran.append("host"), host_only=True)
    assert ran == ["ok", "host"]


def test_transport_gate_inert_on_cpu(bench):
    ph = bench._Phases("cpu")
    ph.out["phase_errors"]["x"] = "Connection refused"
    # CPU backend has no relay: the gate must not fire.
    assert ph.run("next", lambda: None)


def test_checkpoint_abort_records_tail_skip(bench):
    ph = bench._Phases("tpu")
    steps = []

    def phase():
        steps.append("head")
        ph.deadline = bench._now() - 1  # budget expires mid-phase
        ph.checkpoint()  # -> aborts the tail, recorded as a skip (not an error)
        steps.append("tail")

    assert not ph.run("timed", phase)
    assert steps == ["head"]
    assert "timed (tail)" in ph.out["skipped_phases"]
    assert ph.out["aborted_at"] == "child-deadline"
    assert "timed" not in ph.out["phase_errors"]
