"""SIGKILL crash recovery + signal-clean telemetry shutdown.

The crash-safety acceptance contract (ISSUE 7 / docs/reliability.md): a
process killed with SIGKILL at any point during an index build leaves no torn
visible state — the latest stable log still resolves, orphaned staging dirs
are reclaimed, and the NEXT action completes, producing index files
byte-identical to a clean build. The kill windows are aimed with the fault
registry's `hang` kind (`telemetry/faults.py`): the child build blocks inside
a chosen fault point, the parent SIGKILLs it there.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_BUILD_CHILD = """
import os, sys
sys.path.insert(0, {repo!r})
from hyperspace_tpu import Hyperspace, IndexConfig
from hyperspace_tpu.engine.session import HyperspaceSession

s = HyperspaceSession(warehouse={warehouse!r})
s.conf.set("hyperspace.system.path", {syspath!r})
s.conf.set("hyperspace.index.num.buckets", "2")
Hyperspace(s).create_index(s.read.parquet({src!r}), IndexConfig("idx", ["k"], ["v"]))
print("BUILD DONE", flush=True)
"""


def _write_source(tmp_path, n_files=2, rows=120):
    from hyperspace_tpu.engine import io as eio
    from hyperspace_tpu.engine.table import Table

    src = str(tmp_path / "src")
    for i in range(n_files):
        base = i * rows
        eio.write_parquet(
            Table.from_pydict(
                {
                    "k": list(range(base, base + rows)),
                    "v": [j % 5 for j in range(base, base + rows)],
                }
            ),
            os.path.join(src, f"part-{i:05d}.parquet"),
        )
    return src


def _clean_build(tmp_path, src, monkeypatch, name="clean"):
    """Reference build in THIS process; returns {filename: bytes} of v__=0."""
    from hyperspace_tpu import Hyperspace, IndexConfig
    from hyperspace_tpu.engine.session import HyperspaceSession

    monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
    syspath = str(tmp_path / f"indexes_{name}")
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set("hyperspace.system.path", syspath)
    s.conf.set("hyperspace.index.num.buckets", "2")
    Hyperspace(s).create_index(
        s.read.parquet(src), __import__("hyperspace_tpu").IndexConfig("idx", ["k"], ["v"])
    )
    vdir = os.path.join(syspath, "idx", "v__=0")
    return {
        f: open(os.path.join(vdir, f), "rb").read() for f in sorted(os.listdir(vdir))
    }


def _spawn_build(tmp_path, src, syspath, fault_spec):
    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "HYPERSPACE_BUILD_DECODE_THREADS": "1",
            "HYPERSPACE_FAULTS": fault_spec,
            "PYTHONPATH": REPO,
        }
    )
    script = _BUILD_CHILD.format(
        repo=REPO, warehouse=str(tmp_path), syspath=syspath, src=src
    )
    return subprocess.Popen(
        [sys.executable, "-c", script],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
    )


def _wait_for(predicate, timeout_s=180.0, what=""):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def _recover_and_compare(tmp_path, src, syspath, clean, monkeypatch):
    """The post-kill half of both crash tests: the next create_index succeeds
    (transient-orphan recovery), staging dirs are reclaimed, the stable log
    resolves ACTIVE, and the new version dir is byte-identical to the clean
    build."""
    from hyperspace_tpu import Hyperspace, IndexConfig
    from hyperspace_tpu.engine.scan_cache import global_concat_cache, global_scan_cache
    from hyperspace_tpu.engine.session import HyperspaceSession
    from hyperspace_tpu.index.log_manager import IndexLogManagerImpl
    from hyperspace_tpu.index.staging import STAGING_PREFIX

    monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
    global_scan_cache().clear()
    global_concat_cache().clear()
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set("hyperspace.system.path", syspath)
    s.conf.set("hyperspace.index.num.buckets", "2")
    Hyperspace(s).create_index(s.read.parquet(src), IndexConfig("idx", ["k"], ["v"]))

    idx_path = os.path.join(syspath, "idx")
    leftovers = [n for n in os.listdir(idx_path) if n.startswith(STAGING_PREFIX)]
    assert leftovers == [], leftovers
    stable = IndexLogManagerImpl(idx_path).get_latest_stable_log()
    assert stable is not None and stable.state == "ACTIVE"
    # The committed version dir of the RECOVERY build is byte-identical to a
    # clean build's (version numbering may differ when the kill landed after
    # the data commit — compare the dir the stable entry references).
    vdirs = sorted(
        n for n in os.listdir(idx_path) if n.startswith("v__=")
    )
    latest_vdir = os.path.join(idx_path, vdirs[-1])
    got = {
        f: open(os.path.join(latest_vdir, f), "rb").read()
        for f in sorted(os.listdir(latest_vdir))
    }
    assert got == clean
    # And the recovered index actually serves queries.
    from hyperspace_tpu.engine.expr import col
    from hyperspace_tpu.hyperspace import enable_hyperspace

    enable_hyperspace(s)
    rows = (
        s.read.parquet(src).filter(col("k") == 7).select("k", "v").collect().rows()
    )
    assert rows == [(7, 2)]


@pytest.mark.parametrize(
    "fault_spec,wait_marker",
    [
        # Window 1: hung (then killed) INSIDE a bucket-file write — data only
        # ever existed in the invisible staging dir.
        ("storage.write:1.0:hang600", "staging"),
        # Window 2: hung at the SECOND log write (the action's end()) — the
        # data dir committed via rename, the log entry never landed.
        ("log.write:1.0:hang600::1", "vdir"),
    ],
)
def test_sigkill_mid_build_is_recoverable(
    tmp_path, monkeypatch, fault_spec, wait_marker
):
    from hyperspace_tpu.index.staging import STAGING_PREFIX

    src = _write_source(tmp_path)
    clean = _clean_build(tmp_path, src, monkeypatch)

    syspath = str(tmp_path / "indexes_kill")
    idx_path = os.path.join(syspath, "idx")
    proc = _spawn_build(tmp_path, src, syspath, fault_spec)
    try:
        if wait_marker == "staging":
            _wait_for(
                lambda: os.path.isdir(idx_path)
                and any(n.startswith(STAGING_PREFIX) for n in os.listdir(idx_path)),
                what="staging dir to appear",
            )
        else:
            _wait_for(
                lambda: os.path.isdir(os.path.join(idx_path, "v__=0")),
                what="committed version dir to appear",
            )
        time.sleep(0.2)  # let the child reach (and block inside) the hang
        assert proc.poll() is None, (
            "child finished before the kill window: "
            + proc.stdout.read().decode()
            + proc.stderr.read().decode()
        )
    finally:
        proc.kill()  # SIGKILL — no handlers, no cleanup
        proc.wait(timeout=30)

    _recover_and_compare(tmp_path, src, syspath, clean, monkeypatch)


# ---------------------------------------------------------------------------
# Live-table crash matrix (ISSUE 12): SIGKILL at every commit window of the
# incremental-refresh and compaction paths — mid-delta-write, between the
# delta data commit and the log commit, and mid-compaction. The next reader
# stays on the old generation, the next refresher/compactor recovers, and the
# fully-recovered end state is byte-identical to a clean build.
# ---------------------------------------------------------------------------

_LIVE_CHILD = """
import os, sys
sys.path.insert(0, {repo!r})
from hyperspace_tpu import Hyperspace, IndexConfig
from hyperspace_tpu.engine.session import HyperspaceSession

s = HyperspaceSession(warehouse={warehouse!r})
s.conf.set("hyperspace.system.path", {syspath!r})
s.conf.set("hyperspace.index.num.buckets", "2")
Hyperspace(s).{action}
print("ACTION DONE", flush=True)
"""


def _live_session(tmp_path, syspath, monkeypatch):
    from hyperspace_tpu.engine.session import HyperspaceSession

    monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set("hyperspace.system.path", syspath)
    s.conf.set("hyperspace.index.num.buckets", "2")
    return s


def _spawn_live_action(tmp_path, syspath, action, fault_spec):
    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "HYPERSPACE_BUILD_DECODE_THREADS": "1",
            "HYPERSPACE_FAULTS": fault_spec,
            "PYTHONPATH": REPO,
        }
    )
    script = _LIVE_CHILD.format(
        repo=REPO, warehouse=str(tmp_path), syspath=syspath, action=action
    )
    return subprocess.Popen(
        [sys.executable, "-c", script],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
    )


def _append_batch(src):
    from hyperspace_tpu.engine import io as eio
    from hyperspace_tpu.engine.table import Table

    eio.write_parquet(
        Table.from_pydict({"k": [9001, 9002, 9003], "v": [1, 2, 3]}),
        os.path.join(src, "part-00009.parquet"),
    )


@pytest.mark.parametrize(
    "action,fault_spec,wait_marker",
    [
        # Window A: SIGKILL INSIDE a delta bucket-file write — the delta only
        # ever existed in the invisible staging dir.
        ('refresh_index("idx", mode="incremental")', "storage.write:1.0:hang600", "staging"),
        # Window B: SIGKILL between the delta DATA commit (v__=1 renamed into
        # place) and the merged LOG commit (`refresh.merge` fault point).
        ('refresh_index("idx", mode="incremental")', "refresh.merge:1.0:hang600", "vdir1"),
        # Window C: SIGKILL mid-compaction — every compacted bucket staged,
        # the atomic rename not reached (`compact.commit` fault point).
        ('optimize_index("idx")', "compact.commit:1.0:hang600", "staging"),
    ],
)
def test_sigkill_live_table_windows_recover(
    tmp_path, monkeypatch, action, fault_spec, wait_marker
):
    import hashlib

    from hyperspace_tpu import Hyperspace, IndexConfig
    from hyperspace_tpu.engine.expr import col
    from hyperspace_tpu.engine.scan_cache import global_concat_cache, global_scan_cache
    from hyperspace_tpu.hyperspace import enable_hyperspace
    from hyperspace_tpu.index.log_manager import IndexLogManagerImpl
    from hyperspace_tpu.index.staging import STAGING_PREFIX

    src = _write_source(tmp_path)
    syspath = str(tmp_path / "indexes_live")
    idx_path = os.path.join(syspath, "idx")
    s = _live_session(tmp_path, syspath, monkeypatch)
    hs = Hyperspace(s)
    hs.create_index(s.read.parquet(src), IndexConfig("idx", ["k"], ["v"]))
    _append_batch(src)
    if action.startswith("optimize"):
        # The compaction windows need accumulated delta files first.
        hs.refresh_index("idx", mode="incremental")

    proc = _spawn_live_action(tmp_path, syspath, action, fault_spec)
    try:
        if wait_marker == "staging":
            _wait_for(
                lambda: any(n.startswith(STAGING_PREFIX) for n in os.listdir(idx_path)),
                what="staging dir to appear",
            )
        else:
            _wait_for(
                lambda: os.path.isdir(os.path.join(idx_path, "v__=1")),
                what="committed delta version dir to appear",
            )
        time.sleep(0.3)  # let the child reach (and block inside) the hang
        assert proc.poll() is None, (
            "child finished before the kill window: "
            + proc.stdout.read().decode()
            + proc.stderr.read().decode()
        )
    finally:
        proc.kill()  # SIGKILL — no handlers, no cleanup
        proc.wait(timeout=30)

    # 1) The NEXT READER recovers to a consistent generation: rows correct
    #    (old index generation or source scan — never torn index data).
    global_scan_cache().clear()
    global_concat_cache().clear()
    hs._manager.clear_cache()
    enable_hyperspace(s)
    rows = s.read.parquet(src).filter(col("k") == 7).select("k", "v").collect().rows()
    assert rows == [(7, 2)]

    # 2) The NEXT REFRESHER/COMPACTOR recovers: the same action the child
    #    died in now completes, then compaction converges the layout.
    hs._manager.clear_cache()
    if action.startswith("refresh"):
        hs.refresh_index("idx", mode="incremental")
    hs.optimize_index("idx")

    leftovers = [n for n in os.listdir(idx_path) if n.startswith(STAGING_PREFIX)]
    assert leftovers == [], leftovers
    stable = IndexLogManagerImpl(idx_path).get_latest_stable_log()
    assert stable is not None and stable.state == "ACTIVE"

    # 3) End state byte-identical to a clean from-scratch build of the same
    #    (post-append) source.
    s2 = _live_session(tmp_path, str(tmp_path / "indexes_clean"), monkeypatch)
    hs2 = Hyperspace(s2)
    hs2.create_index(s2.read.parquet(src), IndexConfig("idx", ["k"], ["v"]))
    clean_entry = [e for e in hs2._manager.get_indexes() if e.name == "idx"][0]
    recovered = [e for e in hs._manager.get_indexes() if e.name == "idx"][0]
    sha = lambda p: hashlib.sha256(open(p, "rb").read()).hexdigest()  # noqa: E731
    assert {os.path.basename(p): sha(p) for p in recovered.content.files()} == {
        os.path.basename(p): sha(p) for p in clean_entry.content.files()
    }

    # 4) And the recovered index serves queries.
    global_scan_cache().clear()
    global_concat_cache().clear()
    rows = s.read.parquet(src).filter(col("k") == 9002).select("v").collect().rows()
    assert rows == [(2,)]


_EXPORTER_CHILD = """
import os, sys, time
sys.path.insert(0, {repo!r})
import hyperspace_tpu.telemetry  # arms the exporter + SIGTERM/SIGINT flush
from hyperspace_tpu.telemetry import metrics
metrics.counter("crash.test.alive").inc()
open({marker!r}, "w").write("ready")
time.sleep(120)
"""


def test_sigterm_flushes_final_exporter_frame(tmp_path):
    """Satellite: a SIGTERM'd serving process flushes its `final: true` frame
    (atexit alone never runs on a signal death) and still dies BY the signal."""
    metrics_file = str(tmp_path / "metrics.jsonl")
    marker = str(tmp_path / "ready")
    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO,
            "HYPERSPACE_METRICS_FILE": metrics_file,
            "HYPERSPACE_METRICS_INTERVAL_S": "0.2",
        }
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", _EXPORTER_CHILD.format(repo=REPO, marker=marker)],
        env=env,
    )
    try:
        _wait_for(lambda: os.path.exists(marker), timeout_s=60, what="child readiness")
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
    assert proc.returncode == -signal.SIGTERM  # default action still applied
    frames = [json.loads(l) for l in open(metrics_file)]
    assert frames, "no exporter frames written"
    assert frames[-1].get("final") is True, frames[-1]
    assert frames[-1]["snapshot"]["counters"].get("crash.test.alive") == 1
    assert "reliability" in frames[-1]
