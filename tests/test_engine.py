"""Columnar engine tests: Table model, expressions, IO, executor, joins.

The engine has no direct reference analogue (it replaces Spark itself); tests focus on
the semantics the index layer depends on: dictionary-encoded string ordering, stable
hashing, equi-join correctness incl. duplicates and collision verification, and
multi-format IO round-trips.
"""

import numpy as np
import pytest

from hyperspace_tpu import HyperspaceException
from hyperspace_tpu.engine import HyperspaceSession, Table, col, lit
from hyperspace_tpu.engine.expr import extract_equi_join_keys
from hyperspace_tpu.engine.physical import ShuffleExchangeExec, SortMergeJoinExec
from hyperspace_tpu.engine.table import Column, align_dictionaries


@pytest.fixture()
def session(tmp_path):
    return HyperspaceSession(warehouse=str(tmp_path))


# Reference SampleData (SampleData.scala:26-56): fixed small dataset incl. strings.
SAMPLE = {
    "c1": ["2017-09-03", "2017-09-03", "2018-09-03", "2019-10-03", "2019-10-03"],
    "c2": [412, 411, 362, 322, 322],
    "c3": ["facebook", "facebook", "donde", "facebook", "ibraco"],
    "c4": [1, 1, 3, 5, 7],
    "c5": ["productmanager", "areamanager", "areamanager", "productmanager", "areamanager"],
}


class TestTable:
    def test_string_dictionary_is_sorted_and_order_preserving(self):
        c = Column.from_values(np.asarray(["b", "a", "c", "a"]))
        assert list(c.dictionary) == ["a", "b", "c"]
        assert list(c.decode()) == ["b", "a", "c", "a"]
        # codes are order-preserving
        assert (np.argsort(c.data) == np.argsort(np.asarray(["b", "a", "c", "a"]))).all()

    def test_align_dictionaries(self):
        a = Column.from_values(np.asarray(["x", "z"]))
        b = Column.from_values(np.asarray(["y", "z"]))
        a2, b2 = align_dictionaries(a, b)
        assert list(a2.dictionary) == ["x", "y", "z"]
        assert list(a2.decode()) == ["x", "z"]
        assert list(b2.decode()) == ["y", "z"]
        assert a2.data[1] == b2.data[1]  # same code for "z"

    def test_concat_reencodes_strings(self):
        t1 = Table.from_pydict({"s": ["a", "c"], "n": [1, 2]})
        t2 = Table.from_pydict({"s": ["b"], "n": [3]})
        t = Table.concat([t1, t2])
        assert t.to_pydict() == {"s": ["a", "c", "b"], "n": [1, 2, 3]}

    def test_nulls_ride_validity_masks(self):
        t = Table.from_pydict({"s": ["a", None], "n": [1, None]})
        assert t.column("s").has_nulls and t.column("n").has_nulls
        assert t.to_pydict() == {"s": ["a", None], "n": [1, None]}


class TestIO:
    @pytest.mark.parametrize("fmt", ["parquet", "csv", "json"])
    def test_roundtrip(self, session, tmp_path, fmt):
        path = str(tmp_path / f"data_{fmt}")
        getattr(session, f"write_{fmt}")(SAMPLE, path)
        df = getattr(session.read, fmt)(path)
        got = df.collect()
        assert got.to_pydict() == SAMPLE

    def test_multi_file_scan(self, session, tmp_path):
        import hyperspace_tpu.engine.io as eio

        p = str(tmp_path / "multi")
        eio.write_parquet(Table.from_pydict({"a": [1, 2], "s": ["x", "y"]}), p + "/f1.parquet")
        eio.write_parquet(Table.from_pydict({"a": [3], "s": ["z"]}), p + "/f2.parquet")
        df = session.read.parquet(p)
        assert df.sorted_rows() == [(1, "x"), (2, "y"), (3, "z")]

    def test_metadata_files_ignored(self, session, tmp_path):
        import hyperspace_tpu.engine.io as eio

        p = str(tmp_path / "meta")
        eio.write_parquet(Table.from_pydict({"a": [1]}), p + "/f1.parquet")
        eio.write_parquet(Table.from_pydict({"a": [99]}), p + "/_hidden/f.parquet")
        df = session.read.parquet(p)
        assert df.collect().to_pydict() == {"a": [1]}


class TestFilterProject:
    def test_numeric_filters(self, session, tmp_path):
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        assert df.filter(col("c2") == 322).count() == 2
        assert df.filter(col("c2") > 400).count() == 2
        assert df.filter((col("c2") >= 362) & (col("c4") <= 3)).count() == 3
        assert df.filter((col("c2") == 322) | (col("c2") == 412)).count() == 3
        assert df.filter(~(col("c2") == 322)).count() == 3

    def test_string_filters(self, session, tmp_path):
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        assert df.filter(col("c3") == "facebook").count() == 3
        assert df.filter(col("c3") == "notthere").count() == 0
        assert df.filter(col("c3") != "facebook").count() == 2
        assert df.filter(col("c3") < "f").count() == 1  # donde
        assert df.filter(col("c3") >= "f").count() == 4
        assert df.filter(col("c3") <= "facebook").count() == 4
        # literal not in dictionary but between values
        assert df.filter(col("c3") < "e").count() == 1
        assert df.filter(col("c3") > "e").count() == 4

    def test_select_and_prune(self, session, tmp_path):
        session.write_parquet(SAMPLE, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        out = df.filter(col("c2") == 322).select("c1", "c3")
        assert out.sorted_rows() == [("2019-10-03", "facebook"), ("2019-10-03", "ibraco")]
        # pruned scan only reads needed columns
        phys = out.physical_plan()
        scan = [n for n in phys.collect_nodes() if n.name == "Scan"][0]
        assert set(scan.columns) == {"c1", "c2", "c3"}
        with pytest.raises(HyperspaceException, match="not found"):
            df.select("nope")

    def test_string_cross_column_compare(self, session, tmp_path):
        session.write_parquet({"a": ["x", "y"], "b": ["x", "z"]}, str(tmp_path / "t"))
        df = session.read.parquet(str(tmp_path / "t"))
        assert df.filter(col("a") == col("b")).count() == 1
        assert df.filter(col("a") < col("b")).count() == 1


class TestJoin:
    def test_equi_key_extraction(self):
        pairs = extract_equi_join_keys((col("a") == col("b")) & (col("c") == col("d")))
        assert pairs == [("a", "b"), ("c", "d")]
        assert extract_equi_join_keys(col("a") > col("b")) is None
        assert extract_equi_join_keys((col("a") == col("b")) | (col("c") == col("d"))) is None
        assert extract_equi_join_keys(col("a") == lit(3)) is None

    def test_inner_join_with_duplicates(self, session, tmp_path):
        session.write_parquet({"k": [1, 2, 2, 3], "l": ["a", "b", "c", "d"]}, str(tmp_path / "l"))
        session.write_parquet({"k2": [2, 2, 3, 4], "r": [20, 21, 30, 40]}, str(tmp_path / "r"))
        l = session.read.parquet(str(tmp_path / "l"))
        r = session.read.parquet(str(tmp_path / "r"))
        out = l.join(r, col("k") == col("k2")).select("l", "r")
        assert out.sorted_rows() == sorted(
            [("b", 20), ("b", 21), ("c", 20), ("c", 21), ("d", 30)]
        )

    def test_join_on_strings_across_dictionaries(self, session, tmp_path):
        session.write_parquet({"s": ["apple", "pear", "kiwi"], "x": [1, 2, 3]}, str(tmp_path / "l"))
        session.write_parquet({"t": ["pear", "apple", "mango"], "y": [10, 20, 30]}, str(tmp_path / "r"))
        l = session.read.parquet(str(tmp_path / "l"))
        r = session.read.parquet(str(tmp_path / "r"))
        out = l.join(r, col("s") == col("t")).select("x", "y")
        assert out.sorted_rows() == [(1, 20), (2, 10)]

    def test_multi_key_join(self, session, tmp_path):
        session.write_parquet(
            {"a": [1, 1, 2], "b": ["x", "y", "x"], "v": [10, 11, 12]}, str(tmp_path / "l")
        )
        session.write_parquet(
            {"c": [1, 1, 2], "d": ["x", "z", "x"], "w": [100, 101, 102]}, str(tmp_path / "r")
        )
        l = session.read.parquet(str(tmp_path / "l"))
        r = session.read.parquet(str(tmp_path / "r"))
        out = l.join(r, (col("a") == col("c")) & (col("b") == col("d"))).select("v", "w")
        assert out.sorted_rows() == [(10, 100), (12, 102)]

    def test_general_join_plan_has_exchanges(self, session, tmp_path):
        session.write_parquet({"k": [1]}, str(tmp_path / "l"))
        session.write_parquet({"k2": [1]}, str(tmp_path / "r"))
        l = session.read.parquet(str(tmp_path / "l"))
        r = session.read.parquet(str(tmp_path / "r"))
        phys = l.join(r, col("k") == col("k2")).physical_plan()
        names = [n.name for n in phys.collect_nodes()]
        assert names.count("ShuffleExchange") == 2
        assert names.count("SortMergeJoin") == 1

    def test_same_column_names_suffixed(self, session, tmp_path):
        session.write_parquet({"k": [1], "v": [1]}, str(tmp_path / "l"))
        session.write_parquet({"k": [1], "v": [2]}, str(tmp_path / "r"))
        l = session.read.parquet(str(tmp_path / "l"))
        r = session.read.parquet(str(tmp_path / "r"))
        out = l.join(r, col("k") == col("k")).collect()
        assert set(out.column_names) == {"k", "v", "k_r", "v_r"}


class TestHashing:
    def test_stability_across_tables(self):
        """The same value must hash to the same bucket in any table (bucket
        co-location across independently built indexes)."""
        import jax.numpy as jnp

        from hyperspace_tpu.ops.hashing import bucket_id, key64

        c1 = Column.from_values(np.asarray([5, 17, 99], dtype=np.int64))
        c2 = Column.from_values(np.asarray([99, 5], dtype=np.int64))
        b1 = np.asarray(bucket_id([c1], [jnp.asarray(c1.data)], 8))
        b2 = np.asarray(bucket_id([c2], [jnp.asarray(c2.data)], 8))
        assert b1[0] == b2[1] and b1[2] == b2[0]

        # strings: equal values in different dictionaries hash equal
        s1 = Column.from_values(np.asarray(["aa", "bb", "zz"]))
        s2 = Column.from_values(np.asarray(["zz", "mm"]))
        k1 = np.asarray(key64([s1], [jnp.asarray(s1.data)]))
        k2 = np.asarray(key64([s2], [jnp.asarray(s2.data)]))
        assert k1[2] == k2[0]
        assert len({int(x) for x in k1}) == 3  # distinct values hash distinct

    def test_cross_width_same_value_hash_equal(self):
        """int32 vs int64 (and f32 vs f64) columns holding equal values must hash
        equal — joins across mixed-width key columns depend on it."""
        import jax.numpy as jnp

        from hyperspace_tpu.ops.hashing import key64

        a = Column.from_values(np.asarray([7, 1000, -3], dtype=np.int32))
        b = Column.from_values(np.asarray([7, 1000, -3], dtype=np.int64))
        ka = np.asarray(key64([a], [jnp.asarray(a.data)]))
        kb = np.asarray(key64([b], [jnp.asarray(b.data)]))
        assert (ka == kb).all()

        f = Column.from_values(np.asarray([7.5, -0.0], dtype=np.float32))
        g = Column.from_values(np.asarray([7.5, 0.0], dtype=np.float64))
        kf = np.asarray(key64([f], [jnp.asarray(f.data)]))
        kg = np.asarray(key64([g], [jnp.asarray(g.data)]))
        assert (kf == kg).all()

    def test_mixed_width_join(self, session, tmp_path):
        import hyperspace_tpu.engine.io as eio
        import pyarrow as pa
        import pyarrow.parquet as pq

        p = tmp_path
        pq.write_table(
            pa.table({"k": pa.array([1, 2, 3], type=pa.int32()), "l": ["a", "b", "c"]}),
            str(p / "l.parquet"),
        )
        session.write_parquet({"k2": [2, 3, 4], "r": [20, 30, 40]}, str(p / "r"))
        l = session.read.parquet(str(p / "l.parquet"))
        r = session.read.parquet(str(p / "r"))
        out = l.join(r, col("k") == col("k2")).select("l", "r")
        assert out.sorted_rows() == [("b", 20), ("c", 30)]


class TestShowDistinctProfiling:
    def test_distinct(self, session, tmp_path):
        session.write_parquet(
            {"a": [1, 1, 2, 2, 2], "b": ["x", "x", "y", "y", "z"]}, str(tmp_path / "d")
        )
        df = session.read.parquet(str(tmp_path / "d"))
        assert df.distinct().sorted_rows() == [(1, "x"), (2, "y"), (2, "z")]
        assert df.select("a").distinct().count() == 2

    def test_show_formats_and_truncates(self, session, tmp_path):
        session.write_parquet({"k": list(range(5)), "s": ["aa"] * 5}, str(tmp_path / "t"))
        out = []
        session.read.parquet(str(tmp_path / "t")).show(3, redirect=out.append)
        s = out[0]
        assert "| k|" in s.replace("  ", " ") or "k" in s
        assert "only showing top 3 rows" in s
        assert s.count("\n") >= 6

    def test_profiling_trace_noop_and_annotate(self, tmp_path):
        import jax.numpy as jnp

        from hyperspace_tpu.telemetry.profiling import annotate, trace

        with trace(None):  # disabled: pure no-op
            pass
        with trace(str(tmp_path / "prof")):
            with annotate("probe"):
                (jnp.arange(8.0) * 2).sum().block_until_ready()
        # trace directory exists (contents are backend-dependent)
        import os as _os

        assert _os.path.isdir(tmp_path / "prof")


def test_device_sort_perm_matches_lexsort():
    """The device `_sort_perm` (TPU path) and the host lexsort (CPU path) must
    produce the same (bucket, keys...) ordering contract — the CPU suite would
    otherwise never execute the device branch."""
    import jax.numpy as jnp

    from hyperspace_tpu.ops.partition import _sort_perm

    rng = np.random.RandomState(3)
    n = 5000
    key = rng.randint(0, 400, n).astype(np.int64)
    key2 = rng.randint(0, 7, n).astype(np.int64)
    bucket = (key % 16).astype(np.int32)

    perm_dev, sorted_b_dev = _sort_perm(
        jnp.asarray(bucket), (jnp.asarray(key), jnp.asarray(key2)), n
    )
    perm_dev = np.asarray(perm_dev)
    perm_host = np.lexsort((key2, key, bucket))

    # Permutations may differ on exact ties; the ORDERED TUPLES must be equal.
    dev_rows = list(zip(bucket[perm_dev], key[perm_dev], key2[perm_dev]))
    host_rows = list(zip(bucket[perm_host], key[perm_host], key2[perm_host]))
    assert dev_rows == host_rows
    assert np.array_equal(np.asarray(sorted_b_dev), bucket[perm_host])


def test_scan_cache_stats_and_capacity_clamp(tmp_path):
    """stats()/set_capacity: eviction counters move when the budget clamps below
    the held bytes, and the cache stays correct afterwards (bench relies on
    these counters for its eviction-stress section)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu.engine.scan_cache import ScanCache

    c = ScanCache(capacity_bytes=1 << 30)
    from hyperspace_tpu.engine.table import Table

    paths = []
    for i in range(4):
        p = str(tmp_path / f"f{i}.parquet")
        pq.write_table(pa.table({"x": pa.array(range(1000), type=pa.int64())}), p)
        paths.append(p)
        t = Table.from_pydict({"x": list(range(1000))})
        c.put(p, None, t)
    s0 = c.stats()
    assert s0["evictions"] == 0 and s0["bytes"] > 0
    c.set_capacity(s0["bytes"] // 2)
    s1 = c.stats()
    assert s1["evictions"] > 0
    assert s1["bytes"] <= s0["bytes"] // 2
    # survivors still readable
    hits = sum(1 for p in paths if c.get(p, None) is not None)
    assert 0 < hits < 4


def test_device_array_memo_budget_and_identity():
    """Upload memo: identity hits, LRU byte budget, id-reuse-safe eviction."""
    import numpy as np

    import hyperspace_tpu.engine.device_cache as dc

    saved_budget, saved_bytes = dc._BUDGET, dc._bytes
    saved_cache = dict(dc._cache)
    dc._cache.clear()
    dc._bytes = 0
    try:
        a = np.arange(1000, dtype=np.int64)
        d1 = dc.device_array(a)
        d2 = dc.device_array(a)
        assert d1 is d2  # identity hit
        dc._BUDGET = 3 * a.nbytes
        keep = [np.arange(1000, dtype=np.int64) + i for i in range(5)]
        for arr in keep:
            dc.device_array(arr)
        assert dc._bytes <= dc._BUDGET
        # Most-recent entries survive; the result is still correct either way.
        d_last = dc.device_array(keep[-1])
        assert (np.asarray(d_last) == keep[-1]).all()
    finally:
        dc._BUDGET, dc._bytes = saved_budget, saved_bytes
        dc._cache.clear()
        dc._cache.update(saved_cache)


def test_compiled_predicate_cache_hits_and_str_fallback(tmp_path, monkeypatch):
    """evaluate_predicate compiles one program per expression shape, hits the
    cache on repeats, and permanently falls back for trace-unsafe shapes
    (cross-column string compares) without breaking correctness.

    Pinned under HYPERSPACE_PRED_FUSE_MIN_ROWS=0 (always fuse): on the CPU
    backend, small tables route to the eager pow2-padded path by default and
    never touch the fused-program cache this test is about."""
    import numpy as np

    import hyperspace_tpu.engine.evaluate as ev
    from hyperspace_tpu.engine import HyperspaceSession, col

    monkeypatch.setenv("HYPERSPACE_PRED_FUSE_MIN_ROWS", "0")

    s = HyperspaceSession(warehouse=str(tmp_path))
    s.write_parquet(
        {
            "a": np.arange(500, dtype=np.int64),
            "s1": np.array([f"x{i % 5}" for i in range(500)]),
            "s2": np.array([f"x{i % 3}" for i in range(500)]),
        },
        str(tmp_path / "t"),
    )
    df = s.read.parquet(str(tmp_path / "t"))
    n0 = len(ev._PRED_CACHE)
    q = df.filter((col("a") > 100) & (col("a") < 400))
    assert q.count() == 299
    assert len(ev._PRED_CACHE) == n0 + 1
    assert q.count() == 299  # second run: cache hit, no new entry
    assert len(ev._PRED_CACHE) == n0 + 1

    # Cross-column string compare: permanent eager fallback, correct result.
    u0 = len(ev._PRED_UNCACHEABLE)
    got = df.filter(col("s1") == col("s2")).count()
    oracle = sum(1 for i in range(500) if f"x{i % 5}" == f"x{i % 3}")
    assert got == oracle
    assert len(ev._PRED_UNCACHEABLE) > u0


def test_limit_over_multifile_scan_reads_prefix_only(tmp_path):
    """Limit directly over a plain multi-file parquet scan stops reading files
    once n rows are in hand (footer counts), and results match the full path."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu.engine import HyperspaceSession
    from hyperspace_tpu.engine.scan_cache import global_scan_cache

    s = HyperspaceSession(warehouse=str(tmp_path))
    d = tmp_path / "t"
    d.mkdir()
    for i in range(6):
        pq.write_table(
            pa.table({"x": pa.array(range(i * 100, i * 100 + 100), type=pa.int64())}),
            str(d / f"part-{i:05d}.parquet"),
        )
    df = s.read.parquet(str(d))
    sc = global_scan_cache()
    m0 = sc.misses
    t = df.limit(150).collect()
    assert t.num_rows == 150
    assert [r[0] for r in t.rows()][:3] == [0, 1, 2]
    # Only the first two files were decoded (2 misses), not all six.
    assert sc.misses - m0 <= 2, sc.misses - m0
    # Full read still fine and larger.
    assert df.count() == 600
    # limit >= total: generic path, all rows.
    assert df.limit(10_000).collect().num_rows == 600


def test_limit_prefix_through_projection(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu.engine import HyperspaceSession
    from hyperspace_tpu.engine.scan_cache import global_scan_cache

    s = HyperspaceSession(warehouse=str(tmp_path))
    d = tmp_path / "t"
    d.mkdir()
    for i in range(5):
        pq.write_table(
            pa.table(
                {
                    "x": pa.array(range(i * 50, i * 50 + 50), type=pa.int64()),
                    "y": pa.array([i] * 50, type=pa.int64()),
                }
            ),
            str(d / f"part-{i:05d}.parquet"),
        )
    df = s.read.parquet(str(d)).select("y", "x")
    m0 = global_scan_cache().misses
    t = df.limit(60).collect()
    assert t.num_rows == 60
    assert t.column_names == ["y", "x"]  # projection order preserved
    assert global_scan_cache().misses - m0 <= 2


def test_dataframe_union_and_drop(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu.engine import HyperspaceSession, col
    from hyperspace_tpu.exceptions import HyperspaceException
    import pytest as _pytest

    s = HyperspaceSession(warehouse=str(tmp_path))
    for name, lo in (("a", 0), ("b", 100)):
        d = tmp_path / name
        d.mkdir()
        pq.write_table(
            pa.table(
                {
                    "k": pa.array(range(lo, lo + 10), type=pa.int64()),
                    "v": pa.array([name] * 10),
                }
            ),
            str(d / "part-0.parquet"),
        )
    da = s.read.parquet(str(tmp_path / "a"))
    db = s.read.parquet(str(tmp_path / "b"))
    u = da.union(db)
    assert u.count() == 20
    assert sorted(r[0] for r in u.select("k").collect().rows()) == list(range(10)) + list(range(100, 110))
    # union + filter + distinct compose
    assert da.union(da).distinct().count() == 10
    # drop
    assert da.drop("v").schema.names == ["k"]
    assert da.drop("nosuch").schema.names == ["k", "v"]  # missing ignored
    with _pytest.raises(HyperspaceException):
        da.drop("k", "v")
    # mismatched schemas refuse
    with _pytest.raises(Exception):
        da.union(db.select("k"))


def test_union_dtype_mismatch_raises(session, tmp_path):
    """Same-named union columns with incompatible types fail loudly at plan
    construction (the reference validates union schema compatibility), not with
    an obscure concat error at execution."""
    session.write_parquet({"k": np.arange(3, dtype=np.int64)}, str(tmp_path / "n"))
    session.write_parquet({"k": np.array(["a", "b"])}, str(tmp_path / "s"))
    dn = session.read.parquet(str(tmp_path / "n"))
    ds = session.read.parquet(str(tmp_path / "s"))
    with pytest.raises(ValueError, match="type mismatch"):
        dn.union(ds)
    # Numeric width differences still union (concat promotes).
    session.write_parquet({"k": np.arange(3, dtype=np.int32)}, str(tmp_path / "n32"))
    d32 = session.read.parquet(str(tmp_path / "n32"))
    assert dn.union(d32).count() == 6


def test_ambiguous_join_orientation_refused(session, tmp_path):
    """DIFFERENT condition names that each resolve on both sides are refused
    loudly (never silently oriented left-to-right); the SAME name on both
    operands stays legal — left.name == right.name is unambiguous."""
    session.write_parquet(
        {"k": np.arange(4, dtype=np.int64), "x": np.arange(4, dtype=np.int64)},
        str(tmp_path / "l"),
    )
    session.write_parquet(
        {"k": np.arange(4, dtype=np.int64), "x": np.arange(4, dtype=np.int64)},
        str(tmp_path / "r"),
    )
    dl = session.read.parquet(str(tmp_path / "l"))
    dr = session.read.parquet(str(tmp_path / "r"))
    with pytest.raises(HyperspaceException, match="Ambiguous"):
        dl.join(dr, col("k") == col("x")).count()
    assert dl.join(dr, col("k") == col("k")).count() == 4


def test_cross_kind_numeric_join_spark_parity(session, tmp_path):
    """int keys join float keys by VALUE (Spark casts both to double): the
    hash canonicalizes all numerics to float64 bits, verification compares
    numpy-promoted values. Distinct int64 beyond 2^53 that alias in float64
    are hash collisions — found as candidates, removed by verification."""
    session.write_parquet(
        {"a": np.array([5, 7, 2**53 + 1, 2**53 + 2], dtype=np.int64)},
        str(tmp_path / "ints"),
    )
    session.write_parquet(
        {"b": np.array([5.0, 8.0], dtype=np.float64)}, str(tmp_path / "floats")
    )
    di = session.read.parquet(str(tmp_path / "ints"))
    df = session.read.parquet(str(tmp_path / "floats"))
    q = di.join(df, col("a") == col("b"))
    assert q.count() == len(q.collect().rows()) == 1  # 5 == 5.0 only

    # Aliasing ints join EXACTLY among themselves despite equal hashes.
    session.write_parquet(
        {"c": np.array([2**53 + 1], dtype=np.int64)}, str(tmp_path / "big")
    )
    db = session.read.parquet(str(tmp_path / "big"))
    q2 = di.join(db, col("a") == col("c"))
    assert q2.count() == len(q2.collect().rows()) == 1  # not 2**53+2


def test_cross_kind_bucketed_pair_demotes_to_general_join(session, tmp_path):
    """An int-bucketed index joined against a float-bucketed index is NOT
    co-located (each column bucketized in its own kind's hash space): the
    planner must refuse the no-shuffle path and still produce exact results
    via the general join's joint float64 hashing."""
    from hyperspace_tpu import IndexConfig, IndexConstants
    from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace

    session.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
    session.write_parquet(
        {"a": np.arange(200, dtype=np.int64) % 40, "v": np.arange(200, dtype=np.int64)},
        str(tmp_path / "il"),
    )
    session.write_parquet(
        {"b": np.arange(40, dtype=np.float64), "w": np.arange(40, dtype=np.int64)},
        str(tmp_path / "fr"),
    )
    hs = Hyperspace(session)
    hs.create_index(session.read.parquet(str(tmp_path / "il")), IndexConfig("cki", ["a"], ["v"]))
    hs.create_index(session.read.parquet(str(tmp_path / "fr")), IndexConfig("ckf", ["b"], ["w"]))

    def q():
        l = session.read.parquet(str(tmp_path / "il"))
        r = session.read.parquet(str(tmp_path / "fr"))
        return l.join(r, col("a") == col("b")).select("v", "w")

    disable_hyperspace(session)
    expected = q().sorted_rows()
    assert len(expected) == 200  # every int 0..39 matches its float
    enable_hyperspace(session)
    plan = q().explain_string()
    assert "bucketed, no exchange" not in plan  # co-location refused
    assert q().sorted_rows() == expected
    assert q().count() == 200


def test_composite_sort_matches_lexsort_contract():
    """The single-key composite sort (CPU fast path) must order identically to
    the lexsort: same ordered (bucket, key) tuples, same bucket boundaries.
    Negative keys, string codes, and the fallback conditions are all pinned."""
    from hyperspace_tpu.engine.table import Column
    from hyperspace_tpu.ops.partition import _composite_sort_host

    rng = np.random.RandomState(9)
    n = 20000
    for key in (
        rng.randint(-500, 400, n).astype(np.int64),  # negative range
        rng.randint(0, 37, n).astype(np.int32),
    ):
        b = (rng.randint(0, 16, n)).astype(np.int32)
        col = Column(str(key.dtype), key, None, None)
        perm = _composite_sort_host(b, [col], 16)
        assert perm is not None
        ref = np.lexsort((key, b))
        assert np.array_equal(
            np.stack([b[perm], key[perm]]), np.stack([b[ref], key[ref]])
        )
    # String keys sort by dictionary code.
    codes = rng.randint(0, 5, n).astype(np.int32)
    scol = Column("string", codes, np.array(["a", "b", "c", "d", "e"]), None)
    b = (codes % 4).astype(np.int32)
    perm = _composite_sort_host(b, [scol], 4)
    ref = np.lexsort((codes, b))
    assert np.array_equal(codes[perm], codes[ref])
    # Fallbacks: nullable key, float key, multi-key, oversized span.
    assert _composite_sort_host(b, [Column("int64", codes.astype(np.int64), None,
                                           rng.rand(n) > 0.5)], 4) is None
    assert _composite_sort_host(b, [Column("float64", codes.astype(np.float64),
                                           None, None)], 4) is None
    assert _composite_sort_host(b, [scol, scol], 4) is None
    wide = codes.astype(np.int64)
    wide[0] = 1 << 61
    assert _composite_sort_host(b, [Column("int64", wide, None, None)], 4) is None
