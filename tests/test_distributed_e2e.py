"""Distributed execution through the PUBLIC API on the virtual 8-device CPU mesh.

The reference gets cluster-wide builds and shuffle-free cluster joins for free from
Spark (`CreateActionBase.scala:119-140`, `JoinIndexRule.scala:137-162`); here the
equivalent paths are the mesh exchange + sharded probes, and these tests drive them
end-to-end via `create_index` + queries with the result-equality oracle
(`E2EHyperspaceRulesTests.scala:454-470`).

`hyperspace.distributed.minRows=0` forces the mesh path at test sizes; the oracle
runs the same queries with distribution disabled, so single-device and distributed
execution check each other.
"""

import os

import numpy as np
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace

N_DEPT = 3000
N_EMP = 500


@pytest.fixture()
def dist_session(tmp_path):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 16)  # divides the 8-device mesh
    s.conf.set(IndexConstants.DISTRIBUTED_MIN_ROWS, 0)
    rng = np.random.RandomState(3)
    s.write_parquet(
        {
            "deptId": rng.randint(0, 40, N_DEPT).astype(np.int64),
            "deptName": np.array([f"dept{i % 40}" for i in range(N_DEPT)]),
            "score": rng.rand(N_DEPT),
        },
        str(tmp_path / "dept"),
    )
    s.write_parquet(
        {
            "empId": np.arange(N_EMP, dtype=np.int64),
            "empDept": rng.randint(0, 40, N_EMP).astype(np.int64),
        },
        str(tmp_path / "emp"),
    )
    return s, str(tmp_path)


def _join_query(s, base):
    d = s.read.parquet(os.path.join(base, "dept"))
    e = s.read.parquet(os.path.join(base, "emp"))
    return d.join(e, col("deptId") == col("empDept")).select("deptName", "empId")


def test_mesh_is_active_at_test_sizes(dist_session):
    s, _ = dist_session
    mesh = s.mesh_for(10)
    assert mesh is not None and mesh.devices.size == 8


def test_distributed_build_matches_single_device_files(dist_session, tmp_path):
    """The mesh build and the single-device build must produce interchangeable
    index data: same bucket → same rows (hash identity across paths)."""
    s, base = dist_session
    hs = Hyperspace(s)
    df = s.read.parquet(os.path.join(base, "dept"))
    hs.create_index(df, IndexConfig("distIdx", ["deptId"], ["deptName"]))

    s.conf.set(IndexConstants.DISTRIBUTED_MIN_ROWS, 10**9)  # force single-device
    hs.create_index(df, IndexConfig("localIdx", ["deptId"], ["deptName"]))
    s.conf.set(IndexConstants.DISTRIBUTED_MIN_ROWS, 0)

    import pyarrow.parquet as pq

    def bucket_contents(index_name):
        root = os.path.join(base, "indexes", index_name, "v__=0")
        out = {}
        for f in sorted(os.listdir(root)):
            if f.startswith("part-"):
                t = pq.read_table(os.path.join(root, f)).to_pydict()
                rows = sorted(zip(*[t[c] for c in sorted(t)]))
                out[f] = rows
        return out

    assert bucket_contents("distIdx") == bucket_contents("localIdx")


def test_indexed_join_on_mesh_matches_oracle(dist_session):
    s, base = dist_session
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "dept")),
        IndexConfig("deptIdx", ["deptId"], ["deptName"]),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "emp")),
        IndexConfig("empIdx", ["empDept"], ["empId"]),
    )
    disable_hyperspace(s)
    expected = _join_query(s, base).sorted_rows()
    enable_hyperspace(s)
    plan = _join_query(s, base).explain_string()
    assert "bucketed, no exchange" in plan
    got = _join_query(s, base).sorted_rows()
    assert len(got) > 0
    assert got == expected


def test_general_join_real_exchange_matches_oracle(dist_session):
    """No index: the plan keeps ShuffleExchange nodes, which now move rows over the
    mesh for real; results must equal the single-device join."""
    s, base = dist_session
    disable_hyperspace(s)
    plan = _join_query(s, base).explain_string()
    assert "ShuffleExchange" in plan
    got = _join_query(s, base).sorted_rows()

    s.conf.set(IndexConstants.DISTRIBUTED_MIN_ROWS, 10**9)  # single-device oracle
    expected = _join_query(s, base).sorted_rows()
    assert len(got) > 0
    assert got == expected


def test_distributed_filter_index_query(dist_session):
    s, base = dist_session
    hs = Hyperspace(s)
    df = s.read.parquet(os.path.join(base, "dept"))
    hs.create_index(df, IndexConfig("fIdx", ["deptName"], ["deptId"]))

    def q():
        return (
            s.read.parquet(os.path.join(base, "dept"))
            .filter(col("deptName") == "dept7")
            .select("deptId", "deptName")
        )

    disable_hyperspace(s)
    expected = q().sorted_rows()
    enable_hyperspace(s)
    got = q().sorted_rows()
    assert len(got) > 0
    assert got == expected


def test_mixed_mode_join_after_incremental_refresh(dist_session):
    """One side's buckets become multi-file (incremental refresh) so its padded rep
    can't go value-direct; the probe must fall back to hash on BOTH sides — a mixed
    value/hash probe would silently return nothing (r2 review finding)."""
    s, base = dist_session
    s.conf.set(IndexConstants.DISTRIBUTED_MIN_ROWS, 10**9)  # single-device path
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "dept")),
        IndexConfig("deptIdx", ["deptId"], ["deptName"]),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "emp")),
        IndexConfig("empIdx", ["empDept"], ["empId"]),
    )
    # Append new emp rows and incremental-refresh: per-bucket files multiply, so
    # concatenated buckets are no longer globally sorted by the key.
    rng = np.random.RandomState(9)
    import pyarrow as pa
    import pyarrow.parquet as pq

    pq.write_table(
        pa.table(
            {
                "empId": np.arange(N_EMP, N_EMP + 300, dtype=np.int64),
                "empDept": rng.randint(0, 40, 300).astype(np.int64),
            }
        ),
        os.path.join(base, "emp", "part-00001.parquet"),
    )
    hs.refresh_index("empIdx", mode="incremental")
    hs.refresh_index("deptIdx", mode="full")  # dept unchanged content, stays 1-file

    enable_hyperspace(s)
    plan = _join_query(s, base).explain_string()
    assert "bucketed, no exchange" in plan
    got = _join_query(s, base).sorted_rows()
    disable_hyperspace(s)
    expected = _join_query(s, base).sorted_rows()
    assert len(got) > 0
    assert got == expected


def test_string_key_distributed_join(dist_session, tmp_path):
    """String join keys ride the same exchange (dictionary-hash stability across
    independently encoded tables)."""
    s, base = dist_session
    d = s.read.parquet(os.path.join(base, "dept"))
    s.write_parquet(
        {
            "deptName": np.array([f"dept{i % 50}" for i in range(200)]),
            "budget": np.arange(200, dtype=np.int64),
        },
        os.path.join(base, "budgets"),
    )
    b = s.read.parquet(os.path.join(base, "budgets"))
    q = d.join(b, col("deptName") == col("deptName")).select("deptId", "budget")
    got = q.sorted_rows()
    s.conf.set(IndexConstants.DISTRIBUTED_MIN_ROWS, 10**9)
    expected = q.sorted_rows()
    assert len(got) > 0
    assert got == expected


def test_nondivisible_bucket_count_takes_distributed_probe(dist_session, monkeypatch):
    """A bucket count that does NOT divide the mesh (20 % 8 != 0 — the default 200
    on a v5e-16 has the same shape) must still take the sharded probe, via virtual
    empty-bucket padding, and match the oracle."""
    s, base = dist_session
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 20)
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "dept")),
        IndexConfig("deptIdx20", ["deptId"], ["deptName"]),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "emp")),
        IndexConfig("empIdx20", ["empDept"], ["empId"]),
    )

    from hyperspace_tpu.parallel import table_ops

    calls = {"n": 0, "none": 0}
    real = table_ops.probe_dist_blocks

    def spy(*a, **k):
        out = real(*a, **k)
        calls["n"] += 1
        calls["none"] += out is None
        return out

    monkeypatch.setattr(table_ops, "probe_dist_blocks", spy)

    disable_hyperspace(s)
    expected = _join_query(s, base).sorted_rows()
    enable_hyperspace(s)
    got = _join_query(s, base).sorted_rows()
    assert got == expected and len(got) > 0
    assert calls["n"] > 0 and calls["none"] == 0


def test_steady_state_probes_without_rebuilding_blocks(dist_session):
    """The sharded join's block layouts upload ONCE per table (the r2 'host
    round-trip' finding), and since the pairs memo was unified over both
    execution strategies, repeat queries don't even re-probe: the verified
    pairs are served from the row-identity memo."""
    s, base = dist_session
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "dept")),
        IndexConfig("ssIdx1", ["deptId"], ["deptName"]),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "emp")),
        IndexConfig("ssIdx2", ["empDept"], ["empId"]),
    )
    enable_hyperspace(s)
    from hyperspace_tpu.parallel.table_ops import DIST_JOIN_STATS

    pre = DIST_JOIN_STATS["probes"]  # module-global counter: delta, not value
    expected = _join_query(s, base).count()  # warm-up: builds block layouts
    b0, p0 = DIST_JOIN_STATS["block_builds"], DIST_JOIN_STATS["probes"]
    assert p0 > pre  # THIS test's first query really probed
    for _ in range(3):
        assert _join_query(s, base).count() == expected
    assert DIST_JOIN_STATS["block_builds"] == b0  # no re-upload
    assert DIST_JOIN_STATS["probes"] == p0  # repeats: pairs memo, no re-probe


def test_filtered_bucketed_join_on_mesh(dist_session):
    """A side filter over the bucketed index scan still rides the sharded
    co-bucketed probe on the mesh (bucket structure survives filtering), with
    single-device execution as the oracle."""
    from hyperspace_tpu.engine.physical import SortMergeJoinExec

    s, base = dist_session
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "dept")),
        IndexConfig("dfDept", ["deptId"], ["deptName", "score"]),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "emp")),
        IndexConfig("dfEmp", ["empDept"], ["empId"]),
    )

    def q():
        d = s.read.parquet(os.path.join(base, "dept"))
        e = s.read.parquet(os.path.join(base, "emp"))
        return (
            d.filter(col("score") > 0.5)
            .join(e, col("deptId") == col("empDept"))
            .select("deptName", "empId")
        )

    enable_hyperspace(s)
    plan = q().physical_plan()
    joins = [n for n in plan.collect_nodes() if isinstance(n, SortMergeJoinExec)]
    assert joins and joins[0].bucketed, plan.tree_string()
    dist_rows = q().sorted_rows()

    # Oracle 1: same plan, single-device execution.
    s.conf.set(IndexConstants.DISTRIBUTED_MIN_ROWS, 10**9)
    single_rows = q().sorted_rows()
    assert dist_rows == single_rows and len(dist_rows) > 0
    # Oracle 2: non-indexed path.
    disable_hyperspace(s)
    scan_rows = q().sorted_rows()
    assert dist_rows == scan_rows
    s.conf.set(IndexConstants.DISTRIBUTED_MIN_ROWS, 0)
