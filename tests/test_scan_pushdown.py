"""Pushdown-driven selective scan: row-group zone maps, predicate pushdown
into the decode layer, and the footer-metadata cache (ISSUE 5 tentpole).

The contract under test: with ``HYPERSPACE_SCAN_PUSHDOWN`` on (the default),
a filtered parquet scan decodes only the row groups whose footer zone maps
can satisfy the filter's conjuncts — and produces results BYTE-IDENTICAL
(values, row order, and aggregate GROUP order) to the
``HYPERSPACE_SCAN_PUSHDOWN=0`` whole-file fallback, across int/float/string/
null filters, all-pruned and none-pruned files, and single-row-group files.
A decode fault mid-scan propagates cleanly and leaves no partial
selection-keyed cache entry. Footers parse once per file (the footer cache
under the scan-cache budget). The build-side satellite — bounded, key-sorted
row groups in index bucket files — lets indexed point lookups prune INSIDE a
bucket file, and the row-group MinMaxSketch variant prunes whole files whose
per-row-group zones all exclude the literal.
"""

import os

import numpy as np
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.engine import io as engine_io
from hyperspace_tpu.engine.table import Table
from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace
from hyperspace_tpu.telemetry import metrics

ENV = "HYPERSPACE_SCAN_PUSHDOWN"


@pytest.fixture()
def session(tmp_path):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    return s


def _clear_caches():
    from hyperspace_tpu.engine.physical import clear_device_memos
    from hyperspace_tpu.engine.scan_cache import (
        global_bucketed_cache,
        global_concat_cache,
        global_filtered_cache,
        global_scan_cache,
    )

    global_scan_cache().clear()
    global_concat_cache().clear()
    global_filtered_cache().clear()
    global_bucketed_cache().clear()
    clear_device_memos()


def _pruning_counters():
    return {
        "scanned": metrics.counter("io.pruning.row_groups_scanned").value,
        "skipped": metrics.counter("io.pruning.row_groups_skipped").value,
        "footer_misses": metrics.counter("io.footer.misses").value,
        "footer_hits": metrics.counter("io.footer.hits").value,
    }


def _on_off(monkeypatch, make_df):
    """(rows_on, rows_off, pruning delta of the ON run) — each mode runs COLD
    (all caches cleared) so the ON run's decode work is the pruned one."""
    monkeypatch.setenv(ENV, "1")
    _clear_caches()
    c0 = _pruning_counters()
    rows_on = make_df().collect().rows()
    c1 = _pruning_counters()
    monkeypatch.setenv(ENV, "0")
    _clear_caches()
    rows_off = make_df().collect().rows()
    monkeypatch.delenv(ENV, raising=False)
    return rows_on, rows_off, {k: c1[k] - c0[k] for k in c0}


def _write_clustered(base, name, n=4000, files=2, row_groups_per_file=8):
    """Ascending-ts multi-row-group files: mixed int/float/string/null
    payloads so every filter dtype has tight per-row-group zones."""
    per = n // files
    rg = per // row_groups_per_file
    rng = np.random.RandomState(5)
    for i in range(files):
        ts = np.arange(i * per, (i + 1) * per, dtype=np.int64)
        fv = ts.astype(np.float64) / 10.0
        sv = np.asarray([f"s{v:06d}" for v in ts], dtype=object)
        nv = np.asarray([int(v) if v % 5 else None for v in ts], dtype=object)
        engine_io.write_parquet(
            Table.from_pydict({"ts": ts, "fv": fv, "sv": sv, "nv": nv}),
            os.path.join(base, name, f"part-{i:05d}.parquet"),
            row_group_rows=rg,
        )
    return os.path.join(base, name)


class TestOnOffOracle:
    """Byte-identical results (values, row order, group order) with pushdown
    on vs off, with the ON run provably decoding fewer row groups."""

    def test_int_range_filter_prunes_and_matches(self, session, tmp_path, monkeypatch):
        src = _write_clustered(str(tmp_path), "src")

        def q():
            return session.read.parquet(src).filter(
                (col("ts") >= 700) & (col("ts") < 780)
            ).select("ts", "fv", "sv")

        on, off, d = _on_off(monkeypatch, q)
        assert on == off and len(on) == 80
        assert d["skipped"] > 0 and d["scanned"] < d["scanned"] + d["skipped"]

    def test_float_string_null_filters_match(self, session, tmp_path, monkeypatch):
        src = _write_clustered(str(tmp_path), "src")
        cases = [
            lambda df: df.filter(col("fv") < 12.5),
            lambda df: df.filter(col("fv") >= 399.9),
            lambda df: df.filter(col("sv") == "s001234"),
            lambda df: df.filter((col("sv") > "s0030") & (col("sv") <= "s003210")),
            lambda df: df.filter(col("nv").is_not_null() & (col("nv") < 40)),
            lambda df: df.filter((col("ts") != 3) & (col("ts") < 9)),
            lambda df: df.filter(col("ts").isin([17, 2801, 9999])),
        ]
        for make in cases:
            on, off, _ = _on_off(
                monkeypatch, lambda: make(session.read.parquet(src))
            )
            assert on == off, make

    def test_grouped_aggregate_group_order_identical(
        self, session, tmp_path, monkeypatch
    ):
        src = _write_clustered(str(tmp_path), "src")

        def q():
            return (
                session.read.parquet(src)
                .filter(col("ts") < 900)
                .group_by("sv")
                .agg(n=("ts", "count"), sm=("ts", "sum"))
            )

        on, off, d = _on_off(monkeypatch, q)
        assert on == off  # unsorted: group ORDER is part of the contract
        assert d["skipped"] > 0

    def test_all_pruned_and_none_pruned_files(self, session, tmp_path, monkeypatch):
        src = _write_clustered(str(tmp_path), "src", n=4000, files=4)

        # Range entirely outside the data: EVERY row group of every file
        # prunes; the scan yields the 0-row schema without decoding a byte.
        def q_none():
            return session.read.parquet(src).filter(col("ts") >= 10_000_000)

        on, off, d = _on_off(monkeypatch, q_none)
        assert on == off == []
        assert d["scanned"] == 0 and d["skipped"] == 32

        # Filter no zone can exclude: selection keeps everything → the scan
        # runs the plain whole-file path (no pruning counters tick).
        def q_all():
            # != is prunable only for a CONSTANT zone equal to the literal;
            # -1 is nowhere, so every zone keeps and no pruning fires.
            return session.read.parquet(src).filter(col("ts") != -1)

        on, off, d = _on_off(monkeypatch, q_all)
        assert on == off and len(on) == 4000
        assert d["scanned"] == 0 and d["skipped"] == 0

    def test_single_row_group_files(self, session, tmp_path, monkeypatch):
        # One row group per file: pruning degenerates to file-level zone
        # skipping (the all-or-nothing selection).
        per = 500
        for i in range(4):
            engine_io.write_parquet(
                Table.from_pydict(
                    {"ts": np.arange(i * per, (i + 1) * per, dtype=np.int64)}
                ),
                os.path.join(str(tmp_path), "one_rg", f"part-{i:05d}.parquet"),
            )
        src = os.path.join(str(tmp_path), "one_rg")

        def q():
            return session.read.parquet(src).filter(
                (col("ts") >= 600) & (col("ts") < 640)
            )

        on, off, d = _on_off(monkeypatch, q)
        assert on == off and len(on) == 40
        assert d["scanned"] == 1 and d["skipped"] == 3

    def test_mixed_width_promotion_with_all_pruned_file(
        self, session, tmp_path, monkeypatch
    ):
        """An all-pruned file still contributes its 0-row schema to the
        concat, so dtype promotion (int32 file + int64 file) matches the
        unpruned path exactly."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        d = str(tmp_path / "mixed")
        os.makedirs(d)
        pq.write_table(
            pa.table({"k": pa.array(np.arange(100, dtype=np.int32))}),
            os.path.join(d, "part-00000.parquet"),
        )
        pq.write_table(
            pa.table({"k": pa.array(np.arange(1000, 1100, dtype=np.int64))}),
            os.path.join(d, "part-00001.parquet"),
        )

        def q():
            return session.read.parquet(d).filter(col("k") >= 1000)

        monkeypatch.setenv(ENV, "1")
        _clear_caches()
        t_on = q().collect()
        monkeypatch.setenv(ENV, "0")
        _clear_caches()
        t_off = q().collect()
        monkeypatch.delenv(ENV, raising=False)
        assert t_on.rows() == t_off.rows()
        assert t_on.column("k").data.dtype == t_off.column("k").data.dtype


class TestCacheAndFaults:
    def test_footer_parsed_once_per_file(self, session, tmp_path, monkeypatch):
        src = _write_clustered(str(tmp_path), "src")
        monkeypatch.setenv(ENV, "1")
        _clear_caches()

        def q():
            return session.read.parquet(src).filter(col("ts") < 100)

        c0 = _pruning_counters()
        q().collect()
        c1 = _pruning_counters()
        assert c1["footer_misses"] - c0["footer_misses"] == 2  # one per file
        q().collect()
        q().collect()
        c2 = _pruning_counters()
        assert c2["footer_misses"] == c1["footer_misses"]  # cached thereafter
        assert c2["footer_hits"] > c1["footer_hits"]

    def test_fault_mid_scan_leaves_no_partial_entry(
        self, session, tmp_path, monkeypatch
    ):
        from hyperspace_tpu.engine.scan_cache import global_scan_cache

        src = _write_clustered(str(tmp_path), "src")
        monkeypatch.setenv(ENV, "1")
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")  # deterministic order
        _clear_caches()

        real = engine_io._read_row_groups_one
        boom = {"path": None}

        def failing(path, sel, columns):
            if boom["path"] is None:
                boom["path"] = path  # fail the FIRST pruned decode
            if path == boom["path"]:
                raise OSError("injected decode fault")
            return real(path, sel, columns)

        monkeypatch.setattr(engine_io, "_read_row_groups_one", failing)

        def q():
            return session.read.parquet(src).filter(col("ts") < 900)

        with pytest.raises(OSError, match="injected"):
            q().collect()
        assert boom["path"] is not None
        # The faulted file has NO selection-keyed entries: a retry decodes
        # from scratch (and succeeds once the fault clears).
        cache = global_scan_cache()
        names = ["ts", "fv", "sv", "nv"]
        for sel in [(0,), (0, 1)]:
            missing = cache.missing_columns(boom["path"], names, sel=sel)
            assert missing == names
        monkeypatch.setattr(engine_io, "_read_row_groups_one", real)
        assert len(q().collect().rows()) == 900

    def test_selection_entries_never_alias_whole_file(self, session, tmp_path, monkeypatch):
        """A pruned decode must not satisfy a later UNFILTERED read (which
        needs every row) — the selection rides the cache key."""
        src = _write_clustered(str(tmp_path), "src")
        monkeypatch.setenv(ENV, "1")
        _clear_caches()
        filtered = (
            session.read.parquet(src).filter(col("ts") < 100).collect().rows()
        )
        assert len(filtered) == 100
        full = session.read.parquet(src).collect()
        assert full.num_rows == 4000


class TestIndexedShapes:
    def test_point_lookup_prunes_inside_bucket_file(
        self, session, tmp_path, monkeypatch
    ):
        """The build satellite: bounded, key-sorted row groups in bucket
        files → an indexed point lookup decodes only the literal's row
        group(s) inside the one bucket file bucket pruning left."""
        monkeypatch.setenv("HYPERSPACE_INDEX_ROW_GROUP_ROWS", "128")
        n = 4000
        session.write_parquet(
            {
                "k": np.arange(n, dtype=np.int64).tolist(),
                "v": (np.arange(n) % 97).tolist(),
            },
            str(tmp_path / "pts"),
        )
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(str(tmp_path / "pts")),
            IndexConfig("ptIdx", ["k"], ["v"]),
        )
        enable_hyperspace(session)

        def q():
            return (
                session.read.parquet(str(tmp_path / "pts"))
                .filter(col("k") == 1234)
                .select("v")
            )

        assert "ptIdx" in q().explain_string()
        on, off, d = _on_off(monkeypatch, q)
        assert on == off == [(1234 % 97,)]
        assert d["skipped"] > 0  # pruned INSIDE the bucket file

    def test_filtered_bucketed_join_equivalence(self, session, tmp_path, monkeypatch):
        """A range filter on one side of a bucketed index join takes the
        row-group-pruned concat; join results (incl. the streamed/fused
        aggregates above it) equal the whole-file path's exactly."""
        from hyperspace_tpu.engine.physical import SortMergeJoinExec

        monkeypatch.setenv("HYPERSPACE_INDEX_ROW_GROUP_ROWS", "256")
        n = 3000
        session.write_parquet(
            {
                "okey": np.arange(n, dtype=np.int64).tolist(),
                "qty": (np.arange(n) % 9 + 1).tolist(),
            },
            str(tmp_path / "li"),
        )
        session.write_parquet(
            {
                "okey2": list(range(n)),
                "cust": (np.arange(n) % 17).tolist(),
            },
            str(tmp_path / "ord"),
        )
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(str(tmp_path / "li")),
            IndexConfig("rpLi", ["okey"], ["qty"]),
        )
        hs.create_index(
            session.read.parquet(str(tmp_path / "ord")),
            IndexConfig("rpOrd", ["okey2"], ["cust"]),
        )
        enable_hyperspace(session)

        def q():
            l = session.read.parquet(str(tmp_path / "li"))
            o = session.read.parquet(str(tmp_path / "ord"))
            return (
                l.filter((col("okey") >= 500) & (col("okey") < 620))
                .join(o, col("okey") == col("okey2"))
                .select("qty", "cust")
            )

        joins = [
            nde
            for nde in q().physical_plan().collect_nodes()
            if isinstance(nde, SortMergeJoinExec)
        ]
        assert joins and joins[0].bucketed
        on, off, d = _on_off(monkeypatch, q)
        assert on == off and len(on) == 120
        assert d["skipped"] > 0
        disable_hyperspace(session)
        _clear_caches()
        assert sorted(on) == sorted(q().collect().rows())


class TestRowGroupSketch:
    def test_rowgroup_minmax_prunes_straddling_file(self, session, tmp_path):
        """Per-row-group sketch zones prune a file whose OVERALL min/max
        straddles the literal but whose individual row groups all exclude it
        — the row-group variant of MinMaxSketch through the shared zone-map
        evaluator."""
        from hyperspace_tpu.index.dataskipping import (
            DataSkippingIndexConfig,
            MinMaxSketch,
        )

        d = str(tmp_path / "gap")
        # One file, two row groups: [0..99] and [200..299] — value 150 falls
        # in the file's overall range but in NO row group's zone.
        vals = np.concatenate(
            [np.arange(100, dtype=np.int64), np.arange(200, 300, dtype=np.int64)]
        )
        engine_io.write_parquet(
            Table.from_pydict({"ts": vals, "v": vals % 7}),
            os.path.join(d, "part-00000.parquet"),
            row_group_rows=100,
        )
        engine_io.write_parquet(
            Table.from_pydict(
                {"ts": np.arange(1000, 1200, dtype=np.int64), "v": np.arange(200, dtype=np.int64) % 7}
            ),
            os.path.join(d, "part-00001.parquet"),
            row_group_rows=100,
        )
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(d),
            DataSkippingIndexConfig("rgDs", [MinMaxSketch("ts", granularity="rowgroup")]),
        )
        enable_hyperspace(session)

        def q(v):
            return session.read.parquet(d).filter(col("ts") == v).select("v")

        # 150: straddles file 0's [0, 299] range — only the ROW-GROUP zones
        # prove it absent, so both files prune and the scan is empty.
        plan = q(150).physical_plan().tree_string()
        assert "pruned by" in plan, plan
        assert q(150).collect().rows() == []
        # A value actually present keeps exactly its file.
        assert q(250).collect().rows() == [(250 % 7,)]
        disable_hyperspace(session)
        assert q(150).collect().rows() == []
        assert q(250).collect().rows() == [(250 % 7,)]

    def test_file_granularity_unchanged(self, session, tmp_path):
        from hyperspace_tpu.index.dataskipping import (
            DataSkippingIndexConfig,
            MinMaxSketch,
        )

        d = str(tmp_path / "plain")
        for i in range(4):
            engine_io.write_parquet(
                Table.from_pydict(
                    {"ts": np.arange(i * 100, (i + 1) * 100, dtype=np.int64)}
                ),
                os.path.join(d, f"part-{i:05d}.parquet"),
            )
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(d),
            DataSkippingIndexConfig("fDs", [MinMaxSketch("ts")]),
        )
        enable_hyperspace(session)
        df = session.read.parquet(d).filter(col("ts") == 250)
        assert "pruned by" in df.physical_plan().tree_string()
        assert df.collect().rows() == [(250,)]


class TestExplainAnalyze:
    def test_pruning_attrs_surface(self, session, tmp_path, monkeypatch):
        src = _write_clustered(str(tmp_path), "src")
        monkeypatch.setenv(ENV, "1")
        _clear_caches()
        out = (
            session.read.parquet(src)
            .filter(col("ts") < 60)
            .explain(analyze=True)
        )
        assert "row_groups_scanned=" in out and "row_groups_skipped=" in out
