"""Null-value support: ingestion, SQL predicate semantics, joins, indexing.

The reference inherits nullable columns from Spark (every CSV/JSON/parquet source
may carry nulls, `SampleData.scala` included); this engine carries them as validity
masks over dense filled storage. The tests drive the reference's own oracle —
identical results with indexing on vs off — over nullable datasets, plus the SQL
semantics nulls must honor (comparisons unknown, null never equal to null).
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace


@pytest.fixture()
def nullable_session(tmp_path):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
    os.makedirs(tmp_path / "users")
    pq.write_table(
        pa.table(
            {
                "uid": pa.array([1, 2, None, 4, 5, None, 7, 8], type=pa.int64()),
                "city": pa.array(["a", None, "b", "a", None, "c", "b", "a"]),
                "score": pa.array([1.5, None, 3.0, None, 5.5, 6.0, 7.5, 8.0]),
            }
        ),
        str(tmp_path / "users" / "part-00000.parquet"),
    )
    os.makedirs(tmp_path / "orders")
    pq.write_table(
        pa.table(
            {
                "ouid": pa.array([1, None, 4, 4, 8, 9], type=pa.int64()),
                "amount": pa.array([10, 20, 30, 40, 50, 60], type=pa.int64()),
            }
        ),
        str(tmp_path / "orders" / "part-00000.parquet"),
    )
    return s, str(tmp_path)


def test_nullable_ingest_round_trip(nullable_session):
    s, base = nullable_session
    rows = s.read.parquet(os.path.join(base, "users")).sorted_rows()
    assert len(rows) == 8
    flat = [x for r in rows for x in r]
    assert any(x is None for x in flat)


def test_filter_semantics_nulls_excluded(nullable_session):
    """SQL WHERE: a comparison with null is unknown → row dropped, for ==, !=, <."""
    s, base = nullable_session
    df = s.read.parquet(os.path.join(base, "users"))
    eq = df.filter(col("city") == "a").to_pydict()
    assert eq["uid"] == [1, 4, 8]
    # != drops null cities too (unknown); survivors: (None,'b'), (None,'c'), (7,'b').
    ne = df.filter(col("city") != "a").to_pydict()
    assert ne["uid"] == [None, None, 7]
    lt = df.filter(col("score") < 6.0).to_pydict()
    assert all(v is not None and v < 6.0 for v in lt["score"])


def test_is_null_predicates(nullable_session):
    s, base = nullable_session
    df = s.read.parquet(os.path.join(base, "users"))
    nulls = df.filter(col("uid").is_null()).to_pydict()
    assert nulls["city"] == ["b", "c"]
    not_nulls = df.filter(col("uid").is_not_null()).count()
    assert not_nulls == 6


def test_kleene_and_or(nullable_session):
    s, base = nullable_session
    df = s.read.parquet(os.path.join(base, "users"))
    # (city == 'a') OR (score > 7): null city row with score 7.5 must survive via OR.
    got = df.filter((col("city") == "a") | (col("score") > 7.0)).to_pydict()
    assert 7 in got["uid"]
    # (city == 'a') AND (score > 0): null score rows dropped even when city matches.
    got2 = df.filter((col("city") == "a") & (col("score") > 0.0)).to_pydict()
    assert got2["uid"] == [1, 8]


def test_join_null_keys_never_match(nullable_session):
    s, base = nullable_session
    u = s.read.parquet(os.path.join(base, "users"))
    o = s.read.parquet(os.path.join(base, "orders"))
    got = u.join(o, col("uid") == col("ouid")).select("uid", "amount").sorted_rows()
    # uid nulls and ouid null must not pair up; expected matches: 1→10, 4→30, 4→40, 8→50.
    assert got == [(1, 10), (4, 30), (4, 40), (8, 50)]


def test_indexed_join_oracle_nullable(nullable_session):
    s, base = nullable_session
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "users")), IndexConfig("uIdx", ["uid"], ["city"])
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "orders")),
        IndexConfig("oIdx", ["ouid"], ["amount"]),
    )

    def q():
        u = s.read.parquet(os.path.join(base, "users"))
        o = s.read.parquet(os.path.join(base, "orders"))
        return u.join(o, col("uid") == col("ouid")).select("city", "amount")

    enable_hyperspace(s)
    assert "bucketed, no exchange" in q().explain_string()
    on = q().sorted_rows()
    disable_hyperspace(s)
    off = q().sorted_rows()
    assert on == off and len(on) == 4


def test_indexed_filter_oracle_nullable(nullable_session):
    s, base = nullable_session
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "users")),
        IndexConfig("cIdx", ["city"], ["uid", "score"]),
    )

    def q():
        return (
            s.read.parquet(os.path.join(base, "users"))
            .filter(col("city") == "a")
            .select("uid", "city")
        )

    enable_hyperspace(s)
    plan = q().explain_string()
    assert "index=cIdx" in plan
    on = q().sorted_rows()
    disable_hyperspace(s)
    off = q().sorted_rows()
    assert on == off and len(on) == 3


def test_nullable_index_preserves_nulls(nullable_session):
    """The covering index stores null rows; a full scan through the index (project
    without filter... via filter rule needs head col) keeps them queryable."""
    s, base = nullable_session
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "users")),
        IndexConfig("nIdx", ["city"], ["uid"]),
    )
    enable_hyperspace(s)
    got = (
        s.read.parquet(os.path.join(base, "users"))
        .filter(col("city").is_not_null())
        .select("city", "uid")
        .sorted_rows()
    )
    disable_hyperspace(s)
    off = (
        s.read.parquet(os.path.join(base, "users"))
        .filter(col("city").is_not_null())
        .select("city", "uid")
        .sorted_rows()
    )
    assert got == off and len(got) == 6


def test_distributed_build_nullable(nullable_session):
    """Nullable keys ride the mesh exchange consistently (filled-hash routing)."""
    s, base = nullable_session
    s.conf.set(IndexConstants.DISTRIBUTED_MIN_ROWS, 0)
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "users")), IndexConfig("dIdx", ["uid"], ["city"])
    )
    s.conf.set(IndexConstants.DISTRIBUTED_MIN_ROWS, 10**9)

    def q():
        u = s.read.parquet(os.path.join(base, "users"))
        o = s.read.parquet(os.path.join(base, "orders"))
        return u.join(o, col("uid") == col("ouid")).select("city", "amount")

    enable_hyperspace(s)
    on = q().sorted_rows()
    disable_hyperspace(s)
    off = q().sorted_rows()
    assert on == off and len(on) == 4


def test_isin_with_null_in_list_kleene(nullable_session):
    """`x IN (v, NULL)` is TRUE on match else UNKNOWN, so NOT(...) drops
    non-matching rows too (SQL/Spark three-valued logic)."""
    s, base = nullable_session
    users = s.read.parquet(os.path.join(base, "users"))
    # uid IN (1, NULL): only uid==1 is TRUE; everything else UNKNOWN -> dropped.
    rows = users.filter(col("uid").isin([1, None])).select("uid").sorted_rows()
    assert rows == [(1,)]
    # NOT (uid IN (1, NULL)): never TRUE for any row -> empty.
    rows = users.filter(~col("uid").isin([1, None])).select("uid").sorted_rows()
    assert rows == []
    # Without the null the complement keeps the known non-matches.
    rows = users.filter(~col("uid").isin([1])).select("uid").sorted_rows()
    assert rows == [(2,), (4,), (5,), (7,), (8,)]
