"""Multiway star-join execution (ISSUE 18 tentpole).

The contract under test: a recognized star shape (one fact, >=2 covered
dimensions, all inner equi-joins on fact FKs) plans a `MultiwayJoinExec`
and — when a grouped aggregate sits on top under streaming — executes as
ONE pass that probes every dimension's covering index per fact chunk and
folds straight into `StreamAggregator`, never materializing the cascaded
intermediate. Byte-identity is the law: the star stream must equal the
``HYPERSPACE_MULTIWAY=0`` cascaded execution rows()-for-rows() (group
order included) across int/string/null keys, hot-key skew, shared payload
names (the ``_r`` collision suffix), and every encoded/packed flag
ambient; a mid-stream fault fails the query cleanly with NO partial pair
memo; unrecognized shapes (single join, outer join, key-name overlap)
never wrap; and a multi-file fact's second star query starts from the
per-dimension pair memos.
"""

import os

import numpy as np
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, Table, col
from hyperspace_tpu.engine import io as engine_io
from hyperspace_tpu.engine import physical as phys
from hyperspace_tpu.hyperspace import (
    Hyperspace,
    disable_hyperspace,
    enable_hyperspace,
)
from hyperspace_tpu.telemetry.profiling import last_join_stages

NUM_BUCKETS = 8


def _write_parts(data: dict, path: str, parts: int) -> None:
    """Write `data` as `parts` parquet files — multi-file facts keep the
    concat Table identity warm across queries (the pair-memo key)."""
    os.makedirs(path, exist_ok=True)
    n = len(next(iter(data.values())))
    cut = [int(round(i * n / parts)) for i in range(parts + 1)]
    for i in range(parts):
        sl = {k: np.asarray(v)[cut[i]:cut[i + 1]] for k, v in data.items()}
        engine_io.write_parquet(
            Table.from_pydict(sl), os.path.join(path, f"part-{i:05d}.parquet")
        )


@pytest.fixture()
def make_star(tmp_path, monkeypatch):
    """Factory: write one fact + N dimension tables, index every dimension
    on its first column (covering the rest), return the session. Fresh
    device memos per build."""
    monkeypatch.delenv("HYPERSPACE_QUERY_STREAMING", raising=False)
    monkeypatch.delenv("HYPERSPACE_MULTIWAY", raising=False)
    monkeypatch.delenv("HYPERSPACE_JOIN_SIZE_CLASSES", raising=False)
    monkeypatch.delenv("HYPERSPACE_JOIN_CHUNK_ROWS", raising=False)

    def build(fact, dims, num_buckets=NUM_BUCKETS, fact_parts=2):
        phys.clear_device_memos()
        s = HyperspaceSession(warehouse=str(tmp_path))
        s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, num_buckets)
        hs = Hyperspace(s)
        _write_parts(fact, str(tmp_path / "fact"), fact_parts)
        for name, d in dims:
            s.write_parquet(d, str(tmp_path / name))
            k = list(d.keys())[0]
            hs.create_index(
                s.read.parquet(str(tmp_path / name)),
                IndexConfig(f"star_{name}", [k], [c for c in d if c != k]),
            )
        enable_hyperspace(s)
        return s, hs

    return build


def _star2(seed=3, n=8000, hot=True):
    """The canonical 1-fact/2-dim star: skewed FK on dim1 when `hot`."""
    rng = np.random.RandomState(seed)
    k1 = rng.randint(0, 200, n).astype(np.int64)
    if hot:
        k1[: n // 3] = 7
    fact = {
        "k1": k1,
        "k2": rng.randint(0, 50, n).astype(np.int64),
        "v": rng.randint(0, 100, n).astype(np.int64),
    }
    dim1 = {
        "d1": np.arange(200, dtype=np.int64),
        "g1": rng.randint(0, 10, 200).astype(np.int64),
    }
    dim2 = {
        "d2": np.arange(50, dtype=np.int64),
        "g2": rng.randint(0, 5, 50).astype(np.int64),
    }
    return fact, [("dim1", dim1), ("dim2", dim2)]


def _q2(s, tmp_path, group="g1", agg_col="v"):
    f = s.read.parquet(str(tmp_path / "fact"))
    d1 = s.read.parquet(str(tmp_path / "dim1"))
    d2 = s.read.parquet(str(tmp_path / "dim2"))
    return (
        f.join(d1, col("k1") == col("d1"))
        .join(d2, col("k2") == col("d2"))
        .group_by(group)
        .agg(t=(agg_col, "sum"), c=(agg_col, "count"), m=(agg_col, "max"))
    )


def _check_star(s, tmp_path, q, monkeypatch, expect_dims=2):
    """The shared harness: star stream == cascaded fallback byte-for-byte
    (group order included) == the non-indexed oracle (row sets)."""
    pp = q().physical_plan()
    star_nodes = [
        n for n in pp.collect_nodes() if isinstance(n, phys.MultiwayJoinExec)
    ]
    assert len(star_nodes) == 1 and len(star_nodes[0].dims) == expect_dims

    monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
    star = q().collect().rows()
    js = last_join_stages()
    assert js is not None and js.get("join_mode") == "star"
    assert len(js["star_dims"]) == expect_dims
    for d in js["star_dims"]:
        assert d["index"].startswith("star_") and d["pairs"] >= 0

    monkeypatch.setenv("HYPERSPACE_MULTIWAY", "0")
    phys.clear_device_memos()
    pp0 = q().physical_plan()
    assert not any(
        isinstance(n, phys.MultiwayJoinExec) for n in pp0.collect_nodes()
    )
    cascade = q().collect().rows()
    assert star == cascade  # byte-identical, group order included
    monkeypatch.delenv("HYPERSPACE_MULTIWAY", raising=False)

    disable_hyperspace(s)
    oracle = q().collect().rows()
    enable_hyperspace(s)
    assert sorted(star) == sorted(oracle)
    return star


class TestStarOracle:
    def test_int_keys_hot_fk(self, make_star, tmp_path, monkeypatch):
        fact, dims = _star2(hot=True)
        s, _hs = make_star(fact, dims)
        _check_star(s, tmp_path, lambda: _q2(s, tmp_path), monkeypatch)

    def test_group_by_fact_column(self, make_star, tmp_path, monkeypatch):
        """Grouping on a FACT column exercises the direct-cells hint through
        the star fold (the key never came from a dimension gather)."""
        fact, dims = _star2(seed=5)
        fact["gf"] = (np.asarray(fact["v"]) % 7).astype(np.int64)
        s, _hs = make_star(fact, dims)
        _check_star(
            s, tmp_path, lambda: _q2(s, tmp_path, group="gf"), monkeypatch
        )

    def test_three_dimensions(self, make_star, tmp_path, monkeypatch):
        rng = np.random.RandomState(9)
        n = 6000
        fact = {
            "k1": rng.randint(0, 100, n).astype(np.int64),
            "k2": rng.randint(0, 40, n).astype(np.int64),
            "k3": rng.randint(0, 20, n).astype(np.int64),
            "v": rng.randint(0, 100, n).astype(np.int64),
        }
        fact["k1"][: n // 2] = 11  # hot key
        dims = [
            ("dim1", {"d1": np.arange(100, dtype=np.int64),
                      "g1": rng.randint(0, 10, 100).astype(np.int64)}),
            ("dim2", {"d2": np.arange(40, dtype=np.int64),
                      "g2": rng.randint(0, 5, 40).astype(np.int64)}),
            ("dim3", {"d3": np.arange(20, dtype=np.int64),
                      "g3": rng.randint(0, 4, 20).astype(np.int64)}),
        ]
        s, _hs = make_star(fact, dims)

        def q():
            f = s.read.parquet(str(tmp_path / "fact"))
            d1 = s.read.parquet(str(tmp_path / "dim1"))
            d2 = s.read.parquet(str(tmp_path / "dim2"))
            d3 = s.read.parquet(str(tmp_path / "dim3"))
            return (
                f.join(d1, col("k1") == col("d1"))
                .join(d2, col("k2") == col("d2"))
                .join(d3, col("k3") == col("d3"))
                .group_by("g1")
                .agg(t=("v", "sum"), c=("v", "count"))
            )

        _check_star(s, tmp_path, q, monkeypatch, expect_dims=3)

    def test_string_keys(self, make_star, tmp_path, monkeypatch):
        rng = np.random.RandomState(6)
        n = 4000
        k1 = np.array(
            [f"sku-{i:03d}" for i in rng.randint(0, 60, n)], dtype=object
        )
        k1[: n // 2] = "sku-HOT"
        fact = {
            "k1": k1,
            "k2": rng.randint(0, 30, n).astype(np.int64),
            "v": rng.randint(0, 100, n).astype(np.int64),
        }
        dims = [
            ("dim1", {
                "d1": np.array(
                    [f"sku-{i:03d}" for i in range(60)] + ["sku-HOT"],
                    dtype=object,
                ),
                "g1": rng.randint(0, 8, 61).astype(np.int64),
            }),
            ("dim2", {"d2": np.arange(30, dtype=np.int64),
                      "g2": rng.randint(0, 5, 30).astype(np.int64)}),
        ]
        s, _hs = make_star(fact, dims)
        _check_star(s, tmp_path, lambda: _q2(s, tmp_path), monkeypatch)

    def test_null_keys_match_nothing(self, make_star, tmp_path, monkeypatch):
        rng = np.random.RandomState(7)
        n = 3000
        k1 = rng.randint(0, 80, n).astype(object)
        k1[::5] = None
        d1k = np.arange(80).astype(object)
        d1k[::9] = None
        fact = {
            "k1": k1,
            "k2": rng.randint(0, 25, n).astype(np.int64),
            "v": rng.randint(0, 100, n).astype(np.int64),
        }
        dims = [
            ("dim1", {"d1": d1k, "g1": rng.randint(0, 6, 80).astype(np.int64)}),
            ("dim2", {"d2": np.arange(25, dtype=np.int64),
                      "g2": rng.randint(0, 5, 25).astype(np.int64)}),
        ]
        s, _hs = make_star(fact, dims)
        _check_star(s, tmp_path, lambda: _q2(s, tmp_path), monkeypatch)

    def test_shared_payload_name_collision_suffix(
        self, make_star, tmp_path, monkeypatch
    ):
        """Two dimensions carrying the same payload NAME must surface exactly
        the cascade's collision behavior (second one lands as ``w_r``)."""
        rng = np.random.RandomState(8)
        n = 3000
        fact = {
            "k1": rng.randint(0, 50, n).astype(np.int64),
            "k2": rng.randint(0, 20, n).astype(np.int64),
            "v": rng.randint(0, 100, n).astype(np.int64),
        }
        dims = [
            ("dim1", {"d1": np.arange(50, dtype=np.int64),
                      "w": rng.randint(0, 9, 50).astype(np.int64)}),
            ("dim2", {"d2": np.arange(20, dtype=np.int64),
                      "w": rng.randint(0, 9, 20).astype(np.int64)}),
        ]
        s, _hs = make_star(fact, dims)

        def q():
            f = s.read.parquet(str(tmp_path / "fact"))
            d1 = s.read.parquet(str(tmp_path / "dim1"))
            d2 = s.read.parquet(str(tmp_path / "dim2"))
            return (
                f.join(d1, col("k1") == col("d1"))
                .join(d2, col("k2") == col("d2"))
                .group_by("w")
                .agg(t=("v", "sum"), c=("v", "count"))
            )

        _check_star(s, tmp_path, q, monkeypatch)

    def test_multi_chunk_stream(self, make_star, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_JOIN_CHUNK_ROWS", "2000")
        fact, dims = _star2(seed=12)
        s, _hs = make_star(fact, dims)
        _check_star(s, tmp_path, lambda: _q2(s, tmp_path), monkeypatch)
        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
        phys.clear_device_memos()
        _q2(s, tmp_path).collect()
        js = last_join_stages()
        assert js["join_mode"] == "star" and js["chunks"] > 1


class TestStarFlagAmbients:
    @pytest.mark.parametrize(
        "ambient",
        [
            {"HYPERSPACE_ENCODED_DEVICE": "0"},
            {"HYPERSPACE_ENCODED_DEVICE": "1"},
            {"HYPERSPACE_ENCODED_DEVICE": "1", "HYPERSPACE_PACKED_CODES": "1"},
        ],
        ids=["encoded-off", "encoded-on", "encoded+packed"],
    )
    def test_encoded_packed_states(
        self, make_star, tmp_path, monkeypatch, ambient
    ):
        """String-keyed star (dictionary columns in play) under each encoded/
        packed posture: star == cascade == oracle in every ambient."""
        for k, v in ambient.items():
            monkeypatch.setenv(k, v)
        rng = np.random.RandomState(21)
        n = 3000
        k1 = np.array(
            [f"c-{i:02d}" for i in rng.randint(0, 40, n)], dtype=object
        )
        fact = {
            "k1": k1,
            "k2": rng.randint(0, 16, n).astype(np.int64),
            "v": rng.randint(0, 50, n).astype(np.int64),
        }
        dims = [
            ("dim1", {
                "d1": np.array([f"c-{i:02d}" for i in range(40)], dtype=object),
                "g1": np.array(
                    [f"grp-{i % 5}" for i in range(40)], dtype=object
                ),
            }),
            ("dim2", {"d2": np.arange(16, dtype=np.int64),
                      "g2": rng.randint(0, 4, 16).astype(np.int64)}),
        ]
        s, _hs = make_star(fact, dims)
        _check_star(s, tmp_path, lambda: _q2(s, tmp_path), monkeypatch)


class TestStarShapeNegatives:
    def _tables(self, make_star, hot=False):
        fact, dims = _star2(seed=14, n=2000, hot=hot)
        return make_star(fact, dims)

    def test_single_join_is_not_a_star(self, make_star, tmp_path):
        s, _hs = self._tables(make_star)
        f = s.read.parquet(str(tmp_path / "fact"))
        d1 = s.read.parquet(str(tmp_path / "dim1"))
        q = f.join(d1, col("k1") == col("d1")).group_by("g1").agg(t=("v", "sum"))
        assert not any(
            isinstance(n, phys.MultiwayJoinExec)
            for n in q.physical_plan().collect_nodes()
        )

    def test_outer_join_is_not_a_star(self, make_star, tmp_path):
        s, _hs = self._tables(make_star)
        f = s.read.parquet(str(tmp_path / "fact"))
        d1 = s.read.parquet(str(tmp_path / "dim1"))
        d2 = s.read.parquet(str(tmp_path / "dim2"))
        q = (
            f.join(d1, col("k1") == col("d1"), how="left")
            .join(d2, col("k2") == col("d2"))
            .group_by("g1")
            .agg(t=("v", "sum"))
        )
        assert not any(
            isinstance(n, phys.MultiwayJoinExec)
            for n in q.physical_plan().collect_nodes()
        )

    def test_env_zero_never_plans_star(self, make_star, tmp_path, monkeypatch):
        s, _hs = self._tables(make_star)
        monkeypatch.setenv("HYPERSPACE_MULTIWAY", "0")
        assert not any(
            isinstance(n, phys.MultiwayJoinExec)
            for n in _q2(s, tmp_path).physical_plan().collect_nodes()
        )

    def test_non_aggregate_star_rides_the_cascade(
        self, make_star, tmp_path, monkeypatch
    ):
        """A star-shaped plain join (no aggregate on top) still plans the
        MultiwayJoinExec wrapper but EXECUTES its byte-identical cascade."""
        s, _hs = self._tables(make_star)

        def q():
            f = s.read.parquet(str(tmp_path / "fact"))
            d1 = s.read.parquet(str(tmp_path / "dim1"))
            d2 = s.read.parquet(str(tmp_path / "dim2"))
            return (
                f.join(d1, col("k1") == col("d1"))
                .join(d2, col("k2") == col("d2"))
                .select("v", "g1", "g2")
            )

        rows = q().collect().sorted_rows()
        cnt = q().count()
        monkeypatch.setenv("HYPERSPACE_MULTIWAY", "0")
        phys.clear_device_memos()
        assert q().collect().sorted_rows() == rows
        assert q().count() == cnt


class TestStarFaultsAndMemos:
    def test_mid_stream_fault_leaves_no_partial_memo(
        self, make_star, tmp_path, monkeypatch
    ):
        """A fault between star chunks fails the query cleanly; the pair
        memos hold NOTHING partial; the retry recomputes correctly."""
        import hyperspace_tpu.resilience as resilience

        monkeypatch.setenv("HYPERSPACE_JOIN_CHUNK_ROWS", "2000")
        fact, dims = _star2(seed=17)
        s, _hs = make_star(fact, dims)
        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")

        real = resilience.check_deadline
        calls = []

        def boom(tag, *a, **k):
            if tag == "query.star_stream":
                calls.append(1)
                if len(calls) >= 2:
                    raise RuntimeError("injected star fault")
            return real(tag, *a, **k)

        monkeypatch.setattr(resilience, "check_deadline", boom)
        with pytest.raises(RuntimeError, match="injected"):
            _q2(s, tmp_path).collect()
        assert len(calls) >= 2  # really died mid-stream
        assert len(phys._pairs_cache) == 0  # no partial pair memo
        monkeypatch.setattr(resilience, "check_deadline", real)
        streamed = _q2(s, tmp_path).collect().rows()
        monkeypatch.setenv("HYPERSPACE_MULTIWAY", "0")
        phys.clear_device_memos()
        assert _q2(s, tmp_path).collect().rows() == streamed

    def test_warm_star_hits_per_dimension_memos(
        self, make_star, tmp_path, monkeypatch
    ):
        """A multi-file fact keeps the concat Table identity stable, so the
        second star query serves every dimension off the verified-pairs
        memo — no fresh probe."""
        fact, dims = _star2(seed=19)
        s, _hs = make_star(fact, dims, fact_parts=2)
        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")

        cold = _q2(s, tmp_path).collect().rows()
        js = last_join_stages()
        assert [d["memo"] for d in js["star_dims"]] == ["miss", "miss"]
        assert len(phys._pairs_cache) == 2

        warm = _q2(s, tmp_path).collect().rows()
        js2 = last_join_stages()
        assert [d["memo"] for d in js2["star_dims"]] == ["hit", "hit"]
        assert warm == cold
