"""Extension tests: Hybrid Scan, DataSkippingIndex, incremental refresh,
optimizeIndex, delta-style source (BASELINE.md configs 3-5 — north-star features
absent from the v0 reference snapshot)."""

import os

import numpy as np
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.engine.table import Table
from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace
from hyperspace_tpu.index.dataskipping import (
    BloomFilterSketch,
    DataSkippingIndexConfig,
    MinMaxSketch,
)

import hyperspace_tpu.engine.io as eio


@pytest.fixture()
def session(tmp_path):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
    return s


def scanned_index_names(df):
    out = set()
    for n in df.physical_plan().collect_nodes():
        rel = getattr(n, "relation", None)
        if rel is not None and rel.index_name:
            out.add(rel.index_name)
    return out


def plan_op_names(df):
    return [n.name for n in df.physical_plan().collect_nodes()]


class TestHybridScan:
    def test_filter_union_with_appended_files(self, session, tmp_path):
        """BASELINE config 3: index ∪ appended source files."""
        session.write_parquet({"k": [1, 2, 3], "v": ["a", "b", "c"]}, str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(str(tmp_path / "t")), IndexConfig("h1", ["k"], ["v"]))
        eio.write_parquet(Table.from_pydict({"k": [1, 9], "v": ["x", "y"]}),
                          str(tmp_path / "t" / "appended.parquet"))

        q = lambda: session.read.parquet(str(tmp_path / "t")).filter(col("k") == 1).select("v")
        # Without hybrid scan: stale index unused.
        enable_hyperspace(session)
        assert scanned_index_names(q()) == set()
        # With hybrid scan: index + appended union, correct results.
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        assert scanned_index_names(q()) == {"h1"}
        assert "Union" in plan_op_names(q())
        assert sorted(q().to_pydict()["v"]) == ["a", "x"]
        # Oracle: identical to non-indexed.
        disable_hyperspace(session)
        assert sorted(q().to_pydict()["v"]) == ["a", "x"]

    def test_join_shuffle_union_with_appended_files(self, session, tmp_path):
        session.write_parquet(
            {"k": [1, 2, 3, 4], "v": [10, 20, 30, 40]}, str(tmp_path / "l")
        )
        session.write_parquet(
            {"k2": [1, 2, 3, 4, 5], "w": [100, 200, 300, 400, 500]}, str(tmp_path / "r")
        )
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(str(tmp_path / "l")), IndexConfig("hl", ["k"], ["v"]))
        hs.create_index(session.read.parquet(str(tmp_path / "r")), IndexConfig("hr", ["k2"], ["w"]))
        # Append to the LEFT side only.
        eio.write_parquet(Table.from_pydict({"k": [5, 5], "v": [55, 56]}),
                          str(tmp_path / "l" / "appended.parquet"))

        def q():
            l = session.read.parquet(str(tmp_path / "l"))
            r = session.read.parquet(str(tmp_path / "r"))
            return l.join(r, col("k") == col("k2")).select("v", "w")

        disable_hyperspace(session)
        expected = q().sorted_rows()
        assert (55, 500) in expected  # appended rows join

        enable_hyperspace(session)
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        assert scanned_index_names(q()) == {"hl", "hr"}
        names = plan_op_names(q())
        assert names.count("ShuffleExchange") == 0  # still no exchange of index data
        assert q().sorted_rows() == expected

    def test_hybrid_not_used_when_recorded_file_changed(self, session, tmp_path):
        session.write_parquet({"k": [1], "v": ["a"]}, str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(str(tmp_path / "t")), IndexConfig("h2", ["k"], ["v"]))
        # Overwrite the recorded file (size/mtime change) -> not hybrid-scannable.
        eio.write_parquet(Table.from_pydict({"k": [1, 2], "v": ["zz", "ww"]}),
                          str(tmp_path / "t" / "part-00000.parquet"))
        enable_hyperspace(session)
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        q = session.read.parquet(str(tmp_path / "t")).filter(col("k") == 1).select("v")
        assert scanned_index_names(q) == set()
        assert q.to_pydict()["v"] == ["zz"]  # correct from source


class TestDataSkipping:
    def _setup(self, session, tmp_path):
        """Three files with disjoint k ranges and known c3 values."""
        p = str(tmp_path / "ds")
        eio.write_parquet(Table.from_pydict(
            {"k": list(range(0, 100)), "c3": ["alpha"] * 100}), p + "/f0.parquet")
        eio.write_parquet(Table.from_pydict(
            {"k": list(range(100, 200)), "c3": ["beta"] * 100}), p + "/f1.parquet")
        eio.write_parquet(Table.from_pydict(
            {"k": list(range(200, 300)), "c3": ["gamma"] * 100}), p + "/f2.parquet")
        return p

    def test_minmax_prunes_files(self, session, tmp_path):
        p = self._setup(session, tmp_path)
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(p),
            DataSkippingIndexConfig("mmIdx", [MinMaxSketch("k")]),
        )
        enable_hyperspace(session)
        q = session.read.parquet(p).filter(col("k") == 150).select("k", "c3")
        scans = [n for n in q.physical_plan().collect_nodes() if n.name == "Scan"]
        assert len(scans[0].relation.files) == 1  # two of three files pruned
        assert scans[0].relation.pruned_by == ["mmIdx"]
        assert q.to_pydict() == {"k": [150], "c3": ["beta"]}
        # range filter
        q2 = session.read.parquet(p).filter(col("k") >= 250).select("c3")
        scans2 = [n for n in q2.physical_plan().collect_nodes() if n.name == "Scan"]
        assert len(scans2[0].relation.files) == 1
        assert set(q2.to_pydict()["c3"]) == {"gamma"}

    def test_bloom_prunes_files(self, session, tmp_path):
        p = self._setup(session, tmp_path)
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(p),
            DataSkippingIndexConfig("bfIdx", [BloomFilterSketch("c3", 256, 4)]),
        )
        enable_hyperspace(session)
        q = session.read.parquet(p).filter(col("c3") == "beta").select("k")
        scans = [n for n in q.physical_plan().collect_nodes() if n.name == "Scan"]
        assert len(scans[0].relation.files) == 1
        assert len(q.to_pydict()["k"]) == 100
        # absent value prunes everything
        q2 = session.read.parquet(p).filter(col("c3") == "nope").select("k")
        scans2 = [n for n in q2.physical_plan().collect_nodes() if n.name == "Scan"]
        assert len(scans2[0].relation.files) == 0
        assert q2.count() == 0

    def test_bloom_probe_int_float_literals(self, session, tmp_path):
        """A float literal equal in value to an int column entry must not cause a
        false-negative prune (and vice versa)."""
        p = str(tmp_path / "bf2")
        eio.write_parquet(Table.from_pydict({"k": [5, 6]}), p + "/f0.parquet")
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(p),
            DataSkippingIndexConfig("bfT", [BloomFilterSketch("k", 128, 4)]),
        )
        enable_hyperspace(session)
        q = session.read.parquet(p).filter(col("k") == 5.0).select("k")
        scans = [n for n in q.physical_plan().collect_nodes() if n.name == "Scan"]
        assert len(scans[0].relation.files) == 1  # NOT pruned
        assert q.to_pydict()["k"] == [5]
        # a non-representable literal may prune everything — and that is correct
        q2 = session.read.parquet(p).filter(col("k") == 5.5).select("k")
        assert q2.count() == 0

    def test_skipping_index_stale_after_change(self, session, tmp_path):
        p = self._setup(session, tmp_path)
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(p), DataSkippingIndexConfig("stIdx", [MinMaxSketch("k")])
        )
        eio.write_parquet(Table.from_pydict({"k": [5000], "c3": ["delta"]}), p + "/f3.parquet")
        enable_hyperspace(session)
        q = session.read.parquet(p).filter(col("k") == 5000).select("c3")
        scans = [n for n in q.physical_plan().collect_nodes() if n.name == "Scan"]
        assert len(scans[0].relation.files) == 4  # stale: no pruning
        assert q.to_pydict()["c3"] == ["delta"]
        # hybrid semantics: appended file kept, old files still prunable
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        scans = [
            n for n in q.physical_plan().collect_nodes() if n.name == "Scan"
        ]
        assert len(scans[0].relation.files) == 1  # three pruned, appended kept
        assert q.to_pydict()["c3"] == ["delta"]

    def test_refresh_data_skipping_index(self, session, tmp_path):
        p = self._setup(session, tmp_path)
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(p), DataSkippingIndexConfig("rfIdx", [MinMaxSketch("k")])
        )
        eio.write_parquet(Table.from_pydict({"k": [400], "c3": ["delta"]}), p + "/f3.parquet")
        hs.refresh_index("rfIdx")
        enable_hyperspace(session)
        q = session.read.parquet(p).filter(col("k") == 400).select("c3")
        scans = [n for n in q.physical_plan().collect_nodes() if n.name == "Scan"]
        assert len(scans[0].relation.files) == 1
        assert q.to_pydict()["c3"] == ["delta"]


class TestIncrementalRefresh:
    def test_incremental_appends_new_version(self, session, tmp_path):
        session.write_parquet({"k": [1, 2], "v": ["a", "b"]}, str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(str(tmp_path / "t")), IndexConfig("inc", ["k"], ["v"]))
        eio.write_parquet(Table.from_pydict({"k": [3], "v": ["c"]}),
                          str(tmp_path / "t" / "new.parquet"))
        hs.refresh_index("inc", mode="incremental")

        entry = [e for e in hs._manager.get_indexes() if e.name == "inc"][0]
        files = entry.content.files()
        assert any("v__=0" in f for f in files) and any("v__=1" in f for f in files)

        enable_hyperspace(session)
        q = session.read.parquet(str(tmp_path / "t")).filter(col("k") == 3).select("v")
        assert scanned_index_names(q) == {"inc"}
        assert q.to_pydict()["v"] == ["c"]
        # the whole index remains queryable
        q2 = session.read.parquet(str(tmp_path / "t")).filter(col("k") == 1).select("v")
        assert q2.to_pydict()["v"] == ["a"]

    def test_incremental_rejects_deletes_and_noop(self, session, tmp_path):
        from hyperspace_tpu import HyperspaceException

        session.write_parquet({"k": [1], "v": ["a"]}, str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(str(tmp_path / "t")), IndexConfig("inc2", ["k"], ["v"]))
        with pytest.raises(HyperspaceException, match="no appended"):
            hs.refresh_index("inc2", mode="incremental")
        # validate() fails before begin(): the index stays ACTIVE, no rollback needed
        entry = [e for e in hs._manager.get_indexes() if e.name == "inc2"][0]
        assert entry.state == "ACTIVE"
        os.remove(str(tmp_path / "t" / "part-00000.parquet"))
        eio.write_parquet(Table.from_pydict({"k": [9], "v": ["z"]}),
                          str(tmp_path / "t" / "other.parquet"))
        with pytest.raises(HyperspaceException, match="deleted"):
            hs.refresh_index("inc2", mode="incremental")

    def test_incremental_rejects_modified_in_place_file(self, session, tmp_path):
        """A source file overwritten at the same path invalidates its indexed rows —
        incremental must refuse (full rebuild required)."""
        from hyperspace_tpu import HyperspaceException

        session.write_parquet({"k": [1], "v": ["a"]}, str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(str(tmp_path / "t")), IndexConfig("mod", ["k"], ["v"]))
        eio.write_parquet(Table.from_pydict({"k": [1, 2], "v": ["x", "y"]}),
                          str(tmp_path / "t" / "part-00000.parquet"))  # same path, new content
        with pytest.raises(HyperspaceException, match="modified"):
            hs.refresh_index("mod", mode="incremental")
        hs.refresh_index("mod", mode="full")  # full works
        enable_hyperspace(session)
        q = session.read.parquet(str(tmp_path / "t")).filter(col("k") == 1).select("v")
        assert q.to_pydict()["v"] == ["x"]

    def test_incremental_join_still_bucketed(self, session, tmp_path):
        session.write_parquet({"k": [1, 2], "v": [10, 20]}, str(tmp_path / "l"))
        session.write_parquet({"k2": [1, 2, 3], "w": [7, 8, 9]}, str(tmp_path / "r"))
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(str(tmp_path / "l")), IndexConfig("jl", ["k"], ["v"]))
        hs.create_index(session.read.parquet(str(tmp_path / "r")), IndexConfig("jr", ["k2"], ["w"]))
        eio.write_parquet(Table.from_pydict({"k": [3], "v": [30]}),
                          str(tmp_path / "l" / "new.parquet"))
        hs.refresh_index("jl", mode="incremental")
        enable_hyperspace(session)
        l = session.read.parquet(str(tmp_path / "l"))
        r = session.read.parquet(str(tmp_path / "r"))
        q = l.join(r, col("k") == col("k2")).select("v", "w")
        assert scanned_index_names(q) == {"jl", "jr"}
        assert plan_op_names(q).count("ShuffleExchange") == 0
        assert q.sorted_rows() == [(10, 7), (20, 8), (30, 9)]


class TestOptimize:
    def test_optimize_compacts_bucket_files(self, session, tmp_path):
        from hyperspace_tpu import HyperspaceException

        session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 2)
        session.write_parquet({"k": [1, 2, 3, 4], "v": ["a", "b", "c", "d"]}, str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(str(tmp_path / "t")), IndexConfig("opt", ["k"], ["v"]))
        for i in range(2):
            eio.write_parquet(
                Table.from_pydict({"k": [10 + i], "v": [f"x{i}"]}),
                str(tmp_path / "t" / f"new{i}.parquet"),
            )
            hs.refresh_index("opt", mode="incremental")
        entry = [e for e in hs._manager.get_indexes() if e.name == "opt"][0]
        files_before = entry.content.files()
        assert len(files_before) > 2  # one+ file per version per bucket

        hs.optimize_index("opt")  # quick mode, tiny files all below threshold
        entry = [e for e in hs._manager.get_indexes() if e.name == "opt"][0]
        files_after = entry.content.files()
        buckets = {os.path.basename(f).split(".")[0] for f in files_after}
        assert len(files_after) == len(buckets)  # one file per bucket now

        enable_hyperspace(session)
        q = session.read.parquet(str(tmp_path / "t")).filter(col("k") == 11).select("v")
        assert scanned_index_names(q) == {"opt"}
        assert q.to_pydict()["v"] == ["x1"]

        with pytest.raises(HyperspaceException, match="no optimizable"):
            hs.optimize_index("opt")  # nothing left to merge

    def test_optimize_unknown_mode_rejected(self, session, tmp_path):
        from hyperspace_tpu import HyperspaceException

        session.write_parquet({"k": [1], "v": ["a"]}, str(tmp_path / "t"))
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(str(tmp_path / "t")), IndexConfig("m", ["k"], ["v"]))
        with pytest.raises(HyperspaceException, match="mode"):
            hs.optimize_index("m", mode="turbo")


class TestDeltaSource:
    def test_snapshot_read_and_overwrite(self, session, tmp_path):
        p = str(tmp_path / "dtable")
        session.write_delta({"k": [1, 2], "v": ["a", "b"]}, p)
        session.write_delta({"k": [3], "v": ["c"]}, p, mode="append")
        df = session.read.delta(p)
        assert df.sorted_rows() == [(1, "a"), (2, "b"), (3, "c")]
        session.write_delta({"k": [9], "v": ["z"]}, p, mode="overwrite")
        assert session.read.delta(p).sorted_rows() == [(9, "z")]

    def test_index_over_delta_source(self, session, tmp_path):
        p = str(tmp_path / "dtable")
        session.write_delta({"k": [1, 2, 3], "v": ["a", "b", "c"]}, p)
        hs = Hyperspace(session)
        hs.create_index(session.read.delta(p), IndexConfig("dIdx", ["k"], ["v"]))
        enable_hyperspace(session)
        q = lambda: session.read.delta(p).filter(col("k") == 2).select("v")
        assert scanned_index_names(q()) == {"dIdx"}
        assert q().to_pydict()["v"] == ["b"]
        # append a new commit -> snapshot changes -> index stale -> refresh incremental
        session.write_delta({"k": [4], "v": ["d"]}, p, mode="append")
        assert scanned_index_names(q()) == set()
        hs.refresh_index("dIdx", mode="incremental")
        assert scanned_index_names(q()) == {"dIdx"}
        q4 = session.read.delta(p).filter(col("k") == 4).select("v")
        assert q4.to_pydict()["v"] == ["d"]

    def test_remove_commits_respected(self, session, tmp_path):
        from hyperspace_tpu.storage import delta as dlog

        p = str(tmp_path / "dtable")
        session.write_delta({"k": [1], "v": ["a"]}, p)
        session.write_delta({"k": [2], "v": ["b"]}, p, mode="append")
        files = dlog.active_files(p)
        assert len(files) == 2
        dlog.remove_file(p, os.path.relpath(files[0].path, p))
        assert session.read.delta(p).sorted_rows() == [(2, "b")]


class TestHybridScanDeleteTolerance:
    """Round-5: a vanished source file no longer disqualifies the index when
    lineage is recorded — its rows are pruned at scan time by a
    bucket-preserving `_data_file_name NOT IN deleted` filter."""

    def _write_two_files(self, tmp_path, name, rows_a, rows_b):
        d = tmp_path / name
        d.mkdir(exist_ok=True)
        eio.write_parquet(Table.from_pydict(rows_a), str(d / "part-a.parquet"))
        eio.write_parquet(Table.from_pydict(rows_b), str(d / "part-b.parquet"))
        return d

    def test_filter_index_survives_deleted_file(self, session, tmp_path):
        import os

        session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        d = self._write_two_files(
            tmp_path, "t",
            {"k": [1, 2, 3], "v": ["a", "b", "c"]},
            {"k": [1, 4], "v": ["x", "y"]},
        )
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(str(d)), IndexConfig("dt1", ["k"], ["v"]))
        os.remove(str(d / "part-b.parquet"))

        q = lambda: session.read.parquet(str(d)).filter(col("k") == 1).select("v")
        enable_hyperspace(session)
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        assert scanned_index_names(q()) == {"dt1"}
        assert sorted(q().to_pydict()["v"]) == ["a"]  # "x" (deleted file) pruned
        disable_hyperspace(session)
        assert sorted(q().to_pydict()["v"]) == ["a"]  # oracle agrees

    def test_filter_index_without_lineage_not_used_on_delete(self, session, tmp_path):
        import os

        d = self._write_two_files(
            tmp_path, "t0",
            {"k": [1, 2], "v": ["a", "b"]},
            {"k": [3], "v": ["c"]},
        )
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(str(d)), IndexConfig("dt0", ["k"], ["v"]))
        os.remove(str(d / "part-b.parquet"))
        enable_hyperspace(session)
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        q = lambda: session.read.parquet(str(d)).filter(col("k") == 1).select("v")
        assert scanned_index_names(q()) == set()  # no lineage -> disqualified
        assert sorted(q().to_pydict()["v"]) == ["a"]

    def test_join_survives_delete_plus_append(self, session, tmp_path):
        """Delete one left source file AND append another: the co-bucketed join
        still fires shuffle-free, results equal the oracle."""
        import os

        session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        dl = self._write_two_files(
            tmp_path, "l",
            {"k": [1, 2, 3, 4], "v": [10, 20, 30, 40]},
            {"k": [5, 6], "v": [50, 60]},
        )
        session.write_parquet(
            {"k2": [1, 2, 3, 4, 5, 6, 7], "w": [100, 200, 300, 400, 500, 600, 700]},
            str(tmp_path / "r"),
        )
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(str(dl)), IndexConfig("djl", ["k"], ["v"]))
        hs.create_index(
            session.read.parquet(str(tmp_path / "r")), IndexConfig("djr", ["k2"], ["w"])
        )
        os.remove(str(dl / "part-b.parquet"))  # k=5,6 rows vanish
        eio.write_parquet(
            Table.from_pydict({"k": [7, 7], "v": [70, 71]}),
            str(dl / "appended.parquet"),
        )

        def q():
            l = session.read.parquet(str(dl))
            r = session.read.parquet(str(tmp_path / "r"))
            return l.join(r, col("k") == col("k2")).select("v", "w")

        disable_hyperspace(session)
        expected = q().sorted_rows()
        assert (70, 700) in expected and (50, 500) not in expected

        enable_hyperspace(session)
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        assert scanned_index_names(q()) == {"djl", "djr"}
        assert plan_op_names(q()).count("ShuffleExchange") == 0
        assert q().sorted_rows() == expected
        assert q().count() == len(expected)  # device count path agrees too


def test_data_skipping_survives_deleted_file(session, tmp_path):
    """Sketches are per source file: deleting one file keeps the data-skipping
    index usable WITHOUT lineage (the vanished file vanishes from the scan;
    survivors still prune), under hybrid scan."""
    import os as _os

    from hyperspace_tpu.index.dataskipping import DataSkippingIndexConfig, MinMaxSketch

    d = tmp_path / "ds"
    d.mkdir()
    for i in range(4):
        eio.write_parquet(
            Table.from_pydict(
                {"ts": list(range(i * 100, i * 100 + 100)),
                 "val": list(range(100))}
            ),
            str(d / f"part-{i}.parquet"),
        )
    hs = Hyperspace(session)
    hs.create_index(
        session.read.parquet(str(d)), DataSkippingIndexConfig("dsd", [MinMaxSketch("ts")])
    )
    _os.remove(str(d / "part-3.parquet"))
    enable_hyperspace(session)
    session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
    q = lambda: session.read.parquet(str(d)).filter(col("ts") == 150).select("val")
    assert "pruned by" in q().explain_string()  # still prunes after the delete
    assert q().to_pydict()["val"] == [50]
    disable_hyperspace(session)
    assert q().to_pydict()["val"] == [50]


class TestPairCacheFreshness:
    def test_join_count_sees_append_after_cached_pairs(self, session, tmp_path):
        """The pairs/probe memos key on ROW identity (file inventory incl. the
        hybrid-append set): a join count cached before a source append must
        re-key — not serve stale pairs — once the appended file joins the
        scan (docs/caching.md 'Freshness')."""
        session.write_parquet(
            {"k": [1, 2, 3, 4], "v": [10, 20, 30, 40]}, str(tmp_path / "l")
        )
        session.write_parquet({"rk": [1, 2, 3, 4, 9]}, str(tmp_path / "r"))
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(str(tmp_path / "l")), IndexConfig("pf_l", ["k"], ["v"])
        )
        hs.create_index(
            session.read.parquet(str(tmp_path / "r")), IndexConfig("pf_r", ["rk"], [])
        )
        enable_hyperspace(session)
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")

        def q():
            l = session.read.parquet(str(tmp_path / "l"))
            r = session.read.parquet(str(tmp_path / "r"))
            return l.join(r, col("k") == col("rk")).select("v")

        assert scanned_index_names(q()) == {"pf_l", "pf_r"}
        # Spy on the probe so the memo's hit/miss behavior is ASSERTED, not
        # assumed: a regressed cache key would leave the value checks passing
        # while the memo guards nothing.
        from hyperspace_tpu.ops import bucket_join as bj

        calls = []
        real = bj.probe_ranges

        def spy(*a, **k):
            calls.append(1)
            return real(*a, **k)

        bj.probe_ranges = spy
        try:
            assert q().count() == 4  # caches pairs for the pre-append inventory
            n_first = len(calls)
            assert n_first >= 1
            assert q().count() == 4  # repeat: served through the memo
            assert len(calls) == n_first

            # Append a row that matches rk=9: the left scan's hybrid inventory
            # (hence its rows token) changes, so the cached pairs must miss.
            eio.write_parquet(
                Table.from_pydict({"k": [9, 9], "v": [90, 91]}),
                str(tmp_path / "l" / "appended.parquet"),
            )
            assert scanned_index_names(q()) == {"pf_l", "pf_r"}
            assert q().count() == 6
            assert len(calls) > n_first  # fresh probe: the stale entry missed
        finally:
            bj.probe_ranges = real
        assert sorted(q().to_pydict()["v"]) == [10, 20, 30, 40, 90, 91]
        # Oracle: non-indexed agrees.
        disable_hyperspace(session)
        assert q().count() == 6

    def test_join_count_sees_delete_after_cached_pairs(self, session, tmp_path):
        """Cross-query DELETION freshness: pairs cached against the intact
        source must not serve once a recorded file vanishes — the
        lineage-prune filter enters the plan and re-keys the rows token."""
        session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        d = tmp_path / "dl"
        eio.write_parquet(
            Table.from_pydict({"k": [1, 2], "v": [10, 20]}), str(d / "part-a.parquet")
        )
        eio.write_parquet(
            Table.from_pydict({"k": [3, 4], "v": [30, 40]}), str(d / "part-b.parquet")
        )
        session.write_parquet({"rk": [1, 2, 3, 4]}, str(tmp_path / "dr"))
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(str(d)), IndexConfig("dfl", ["k"], ["v"])
        )
        hs.create_index(
            session.read.parquet(str(tmp_path / "dr")), IndexConfig("dfr", ["rk"], [])
        )
        enable_hyperspace(session)
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")

        def q():
            l = session.read.parquet(str(d))
            r = session.read.parquet(str(tmp_path / "dr"))
            return l.join(r, col("k") == col("rk")).select("v")

        assert q().count() == 4  # caches pairs for the intact inventory
        os.remove(str(d / "part-b.parquet"))  # k=3,4 rows vanish
        assert scanned_index_names(q()) == {"dfl", "dfr"}
        assert q().count() == 2
        assert sorted(q().to_pydict()["v"]) == [10, 20]
        disable_hyperspace(session)
        assert q().count() == 2
