"""Data-skipping indexes under source mutations, against the non-indexed
oracle: file deletion is tolerated without lineage (a vanished file simply
stops being prunable), appends re-key the pruned file set — randomized over
file layouts and cut points (condensed from the round-5 soak)."""

import os
import numpy as np
import pytest

from hyperspace_tpu import IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.engine import io as eio
from hyperspace_tpu.engine.table import Table
from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace
from hyperspace_tpu.index.dataskipping import DataSkippingIndexConfig, MinMaxSketch


@pytest.mark.parametrize("seed", range(3))
def test_dataskip_mutation_differential(tmp_path, seed):
    rng = np.random.RandomState(3000 + seed)
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    hs = Hyperspace(s)
    d = tmp_path / "T"
    nf = int(rng.randint(4, 10))
    for i in range(nf):
        lo = i * 100
        n = int(rng.randint(50, 300))
        eio.write_parquet(Table.from_pydict({
            "x": rng.randint(lo, lo + 100, n).astype(np.int64),
            "v": rng.randint(0, 1000, n).astype(np.int64),
        }), str(d / f"part-{i}.parquet"))
    hs.create_index(
        s.read.parquet(str(d)),
        DataSkippingIndexConfig(f"sk{seed}", [MinMaxSketch("x")]),
    )
    enable_hyperspace(s)

    def q(cut):
        return s.read.parquet(str(d)).filter(col("x") < cut)

    def check():
        cut = int(rng.randint(0, nf * 100))
        enable_hyperspace(s)
        got_c, got_r = q(cut).count(), q(cut).sorted_rows()
        disable_hyperspace(s)
        assert got_c == q(cut).count()
        assert got_r == q(cut).sorted_rows()
        enable_hyperspace(s)

    check(); check()
    # mutations: delete a file (tolerated without lineage for skipping), append
    files = sorted(p for p in os.listdir(str(d)) if p.endswith(".parquet"))
    os.remove(str(d / files[int(rng.randint(len(files)))]))
    check()
    eio.write_parquet(Table.from_pydict({
        "x": rng.randint(0, nf * 100, 80).astype(np.int64),
        "v": rng.randint(0, 1000, 80).astype(np.int64),
    }), str(d / "appended.parquet"))
    check(); check()
