"""Skew-aware streamed bucket-join executor (ISSUE 3 tentpole).

The contract under test: the size-classed padded layout (pow2 capacity
classes + host-merged outlier buckets) produces exactly the verified pairs the
single global-cap dense layout produced, across the skew matrix — one-hot-key
bucket, empty buckets, all-rows-one-bucket, string keys, null keys, float
keys; a bucketed inner join feeding a grouped aggregate streams per-chunk
through `StreamAggregator` and is byte-identical to the
``HYPERSPACE_QUERY_STREAMING=0`` materialized fallback (group order included);
a mid-stream fault fails the query cleanly with NO partial pair memo; and the
verified-pairs memos re-key across index refresh (log entry id), so a rebuilt
index can never serve stale pair indices.
"""

import os

import numpy as np
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.engine import physical as phys
from hyperspace_tpu.hyperspace import (
    Hyperspace,
    disable_hyperspace,
    enable_hyperspace,
)

NUM_BUCKETS = 8


@pytest.fixture()
def make_session(tmp_path, monkeypatch):
    """Factory: write left/right tables, index both, return (session, q_join,
    q_agg) with fresh device memos. Keys are the first column of each dict."""
    monkeypatch.delenv("HYPERSPACE_QUERY_STREAMING", raising=False)
    monkeypatch.delenv("HYPERSPACE_JOIN_SIZE_CLASSES", raising=False)
    monkeypatch.delenv("HYPERSPACE_JOIN_OUTLIER_FACTOR", raising=False)

    def build(left, right, includes_l=None, includes_r=None, num_buckets=NUM_BUCKETS):
        phys.clear_device_memos()
        s = HyperspaceSession(warehouse=str(tmp_path))
        s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, num_buckets)
        hs = Hyperspace(s)
        lk = list(left.keys())[0]
        rk = list(right.keys())[0]
        s.write_parquet(left, str(tmp_path / "l"))
        s.write_parquet(right, str(tmp_path / "r"))
        hs.create_index(
            s.read.parquet(str(tmp_path / "l")),
            IndexConfig("skJl", [lk], includes_l or [c for c in left if c != lk]),
        )
        hs.create_index(
            s.read.parquet(str(tmp_path / "r")),
            IndexConfig("skJr", [rk], includes_r or [c for c in right if c != rk]),
        )
        enable_hyperspace(s)

        def q_join():
            l = s.read.parquet(str(tmp_path / "l"))
            r = s.read.parquet(str(tmp_path / "r"))
            return l.join(r, col(lk) == col(rk))

        return s, hs, q_join

    return build


def _check_matrix(build, left, right, agg_spec, monkeypatch):
    """The shared equivalence harness: non-indexed oracle == indexed classed
    == indexed dense (sorted rows); streamed aggregate == materialized
    aggregate byte-for-byte (rows(), order included); counts agree."""
    s, _hs, q_join_raw = build(left, right)
    group_key, agg_col = agg_spec

    def q_join():
        # Project the payload columns: a bare select-all additionally surfaces
        # the index version partition column (`v__`) on the indexed side,
        # which is orthogonal to the executor under test.
        return q_join_raw().select("k", "v", "w")

    def q_agg():
        return q_join_raw().group_by(group_key).agg(
            t=(agg_col, "sum"), c=(agg_col, "count"), m=(agg_col, "max")
        )

    disable_hyperspace(s)
    oracle_join = q_join().sorted_rows()
    oracle_cnt = q_join().count()
    oracle_agg = q_agg().collect().sorted_rows()
    enable_hyperspace(s)

    monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
    assert q_join().count() == oracle_cnt
    assert q_join().sorted_rows() == oracle_join
    streamed = q_agg().collect().rows()
    assert sorted(streamed) == sorted(tuple(r) for r in oracle_agg)

    monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "0")
    phys.clear_device_memos()
    materialized = q_agg().collect().rows()
    assert streamed == materialized  # byte-identical, group order included

    # The dense (pre-classed) executor agrees on everything.
    monkeypatch.setenv("HYPERSPACE_JOIN_SIZE_CLASSES", "0")
    phys.clear_device_memos()
    assert q_join().count() == oracle_cnt
    assert q_join().sorted_rows() == oracle_join
    assert sorted(q_agg().collect().rows()) == sorted(materialized)


class TestSkewMatrix:
    def test_one_hot_key_bucket_with_outliers(self, make_session, monkeypatch):
        """40% of rows on one key; a low outlier factor forces the host merge
        path for the hot bucket."""
        monkeypatch.setenv("HYPERSPACE_JOIN_OUTLIER_FACTOR", "2")
        rng = np.random.RandomState(3)
        n = 8000
        k = rng.randint(0, 400, n).astype(np.int64)
        k[: int(n * 0.4)] = 7
        left = {"k": k, "v": rng.randint(0, 100, n).astype(np.int64)}
        right = {
            "k2": np.arange(400, dtype=np.int64),
            "w": rng.randint(0, 10, 400).astype(np.int64),
        }
        _check_matrix(make_session, left, right, ("k", "v"), monkeypatch)

    def test_all_rows_one_bucket(self, make_session, monkeypatch):
        """A single distinct key: every row lands in ONE bucket (the worst
        dense-layout case — every other bucket pads to the hot cap)."""
        rng = np.random.RandomState(4)
        left = {
            "k": np.full(300, 42, np.int64),
            "v": rng.randint(0, 9, 300).astype(np.int64),
        }
        right = {
            "k2": np.full(40, 42, np.int64),
            "w": rng.randint(0, 9, 40).astype(np.int64),
        }
        _check_matrix(make_session, left, right, ("k", "v"), monkeypatch)

    def test_empty_buckets(self, make_session, monkeypatch):
        """3 distinct keys over 8 buckets: most buckets are empty on both
        sides and must be skipped, not padded."""
        rng = np.random.RandomState(5)
        left = {
            "k": rng.choice(np.asarray([1, 50, 999], np.int64), 2000),
            "v": rng.randint(0, 100, 2000).astype(np.int64),
        }
        right = {
            "k2": np.asarray([1, 999, 1234], np.int64),
            "w": np.asarray([5, 6, 7], np.int64),
        }
        _check_matrix(make_session, left, right, ("k", "v"), monkeypatch)

    def test_string_keys_hot(self, make_session, monkeypatch):
        rng = np.random.RandomState(6)
        n = 4000
        k = np.array([f"sku-{i:04d}" for i in rng.randint(0, 200, n)], dtype=object)
        k[: n // 2] = "sku-HOT"
        left = {"k": k, "v": rng.randint(0, 100, n).astype(np.int64)}
        right = {
            "k2": np.array(
                [f"sku-{i:04d}" for i in range(200)] + ["sku-HOT"], dtype=object
            ),
            "w": rng.randint(0, 10, 201).astype(np.int64),
        }
        _check_matrix(make_session, left, right, ("k", "v"), monkeypatch)

    def test_null_keys(self, make_session, monkeypatch):
        """Nullable join keys force hash mode; null keys match nothing."""
        rng = np.random.RandomState(7)
        n = 3000
        k = rng.randint(0, 100, n).astype(object)
        k[::5] = None
        left = {"k": k, "v": rng.randint(0, 100, n).astype(np.int64)}
        k2 = np.arange(100).astype(object)
        k2[::9] = None
        right = {"k2": k2, "w": rng.randint(0, 10, 100).astype(np.int64)}
        _check_matrix(make_session, left, right, ("k", "v"), monkeypatch)

    def test_float_keys_value_mode(self, make_session, monkeypatch):
        """Float keys incl. signed zeros ride value mode (canonicalized)."""
        rng = np.random.RandomState(8)
        n = 2000
        k = (rng.randint(0, 50, n) * 0.5).astype(np.float64)
        k[::17] = -0.0
        left = {"k": k, "v": rng.randint(0, 100, n).astype(np.int64)}
        right = {
            "k2": np.concatenate([np.arange(50) * 0.5, [0.0]]).astype(np.float64),
            "w": rng.randint(0, 10, 51).astype(np.int64),
        }
        _check_matrix(make_session, left, right, ("k", "v"), monkeypatch)


class TestStreamedJoinAggregate:
    def _skewed(self, make_session, monkeypatch, **kw):
        rng = np.random.RandomState(11)
        n = 9000
        k = rng.randint(0, 300, n).astype(np.int64)
        k[: n // 3] = 5
        left = {"k": k, "v": rng.randint(0, 100, n).astype(np.int64)}
        right = {
            "k2": np.arange(300, dtype=np.int64),
            "g": rng.randint(0, 20, 300).astype(np.int64),
        }
        return make_session(left, right, **kw)

    def test_multi_chunk_stream_matches_materialized(
        self, make_session, monkeypatch
    ):
        monkeypatch.setenv("HYPERSPACE_JOIN_CHUNK_ROWS", "2000")
        # Pin the host path: under force-device-ops the FUSED device
        # join→aggregate takes this shape before the streamed executor.
        monkeypatch.delenv("HYPERSPACE_FORCE_DEVICE_OPS", raising=False)
        s, _hs, q_join = self._skewed(make_session, monkeypatch)

        def q_agg():
            return (
                q_join()
                .with_column("x", col("v") * col("g"))
                .group_by("g")
                .agg(t=("x", "sum"), c=("v", "count"))
            )

        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
        streamed = q_agg().collect().rows()
        from hyperspace_tpu.telemetry.profiling import last_join_stages

        js = last_join_stages()
        assert js is not None and js["chunks"] > 1 and js["pairs"] == 9000
        assert js["mode"] == "join-stream"
        assert "gather_s" in js and "partial_s" in js and js["overlap_ratio"]
        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "0")
        phys.clear_device_memos()
        assert q_agg().collect().rows() == streamed

    def test_multi_chunk_float_sum_within_associativity_rounding(
        self, make_session, monkeypatch
    ):
        """Float sums through the direct-cells hint: bitwise-identical to the
        materialized fallback at single-chunk scale; across chunks the
        partial cell folds differ only by float associativity (same contract
        as the scan-side stream). Group ORDER is identical either way."""
        monkeypatch.delenv("HYPERSPACE_FORCE_DEVICE_OPS", raising=False)
        rng = np.random.RandomState(19)
        n = 9000
        k = rng.randint(0, 300, n).astype(np.int64)
        k[: n // 3] = 5
        left = {"k": k, "p": rng.rand(n) * 100.0}
        right = {
            "k2": np.arange(300, dtype=np.int64),
            "g": rng.randint(0, 20, 300).astype(np.int64),
        }
        s, _hs, q_join = make_session(left, right)

        def q_agg():
            return q_join().group_by("g").agg(rev=("p", "sum"), n=("p", "count"))

        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "0")
        materialized = q_agg().collect().rows()

        # Single chunk: bitwise identical.
        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
        phys.clear_device_memos()
        assert q_agg().collect().rows() == materialized

        # Multi-chunk: identical group order + counts, float sums to tol.
        monkeypatch.setenv("HYPERSPACE_JOIN_CHUNK_ROWS", "2000")
        phys.clear_device_memos()
        chunked = q_agg().collect().rows()
        from hyperspace_tpu.telemetry.profiling import last_join_stages

        assert last_join_stages()["chunks"] > 1
        assert [r[0] for r in chunked] == [r[0] for r in materialized]
        assert [r[2] for r in chunked] == [r[2] for r in materialized]
        for rc, rm in zip(chunked, materialized):
            assert abs(rc[1] - rm[1]) <= 1e-9 * max(1.0, abs(rm[1]))

    def test_streamed_pass_populates_pairs_memo(self, make_session, monkeypatch):
        """Warm queries after a streamed aggregate start from the verified
        pairs: no fresh probe, the count is free."""
        s, _hs, q_join = self._skewed(make_session, monkeypatch)
        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")

        def q_agg():
            return q_join().group_by("g").agg(t=("v", "sum"))

        q_agg().collect()  # streamed: populates the pairs memo on success
        from hyperspace_tpu.ops import bucket_join as bj

        calls = []
        real = bj.probe_ranges

        def spy(*a, **k):
            calls.append(1)
            return real(*a, **k)

        monkeypatch.setattr(bj, "probe_ranges", spy)
        expected = q_join().count()
        assert not calls  # served off the streamed pass's memo
        assert q_agg().collect().num_rows > 0
        assert not calls
        disable_hyperspace(s)
        assert q_join().count() == expected

    def test_serial_decode_threads_equivalent(self, make_session, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_JOIN_CHUNK_ROWS", "2000")
        s, _hs, q_join = self._skewed(make_session, monkeypatch)

        def q_agg():
            return q_join().group_by("g").agg(t=("v", "sum"), c=("v", "count"))

        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
        parallel = q_agg().collect().rows()
        monkeypatch.setenv("HYPERSPACE_BUILD_DECODE_THREADS", "1")
        phys.clear_device_memos()
        serial = q_agg().collect().rows()
        assert parallel == serial

    def test_mid_stream_fault_leaves_no_partial_memo(
        self, make_session, monkeypatch
    ):
        """A gather fault mid-stream fails the query cleanly; the pairs memo
        holds NOTHING partial, and the retry recomputes correctly."""
        monkeypatch.setenv("HYPERSPACE_JOIN_CHUNK_ROWS", "2000")
        # The fused device path (force-device-ops CI leg) would take the
        # aggregate before the streamed path: pin the host path for the fault
        # injection, which targets the streamed executor's chunk gathers.
        monkeypatch.delenv("HYPERSPACE_FORCE_DEVICE_OPS", raising=False)
        s, _hs, q_join = self._skewed(make_session, monkeypatch)
        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")

        def q_agg():
            return q_join().group_by("g").agg(t=("v", "sum"))

        phys.clear_device_memos()
        real = phys._assemble_join
        calls = []

        def boom(*a, **k):
            calls.append(1)
            if len(calls) >= 2:
                raise RuntimeError("injected decoder fault")
            return real(*a, **k)

        monkeypatch.setattr(phys, "_assemble_join", boom)
        with pytest.raises(RuntimeError, match="injected"):
            q_agg().collect()
        assert len(phys._pairs_cache) == 0  # no partial pair memo
        monkeypatch.setattr(phys, "_assemble_join", real)
        streamed = q_agg().collect().rows()
        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "0")
        phys.clear_device_memos()
        assert q_agg().collect().rows() == streamed

    def test_env_zero_is_materialized_fallback(self, make_session, monkeypatch):
        s, _hs, q_join = self._skewed(make_session, monkeypatch)

        def q_agg():
            return q_join().group_by("g").agg(t=("v", "sum"))

        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "0")
        from hyperspace_tpu.telemetry.profiling import _JOIN_STAGES

        before = len(_JOIN_STAGES)
        q_agg().collect()
        assert len(_JOIN_STAGES) == before  # the streamed executor never ran


class TestRefreshMemoInvalidation:
    def test_rows_token_rekeys_on_refresh(self, tmp_path, monkeypatch):
        """The pair memos key on the index LOG ENTRY id: refresh bumps it even
        when the rewritten files could alias the old stat signature, so stale
        pair indices can never serve a rebuilt index."""
        s = HyperspaceSession(warehouse=str(tmp_path))
        s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
        hs = Hyperspace(s)
        rng = np.random.RandomState(13)
        s.write_parquet(
            {
                "k": rng.randint(0, 50, 500).astype(np.int64),
                "v": rng.randint(0, 9, 500).astype(np.int64),
            },
            str(tmp_path / "src"),
        )
        s.write_parquet(
            {
                "k2": np.arange(50, dtype=np.int64),
                "w": np.arange(50, dtype=np.int64),
            },
            str(tmp_path / "dim"),
        )
        hs.create_index(
            s.read.parquet(str(tmp_path / "src")), IndexConfig("rfL", ["k"], ["v"])
        )
        hs.create_index(
            s.read.parquet(str(tmp_path / "dim")), IndexConfig("rfR", ["k2"], ["w"])
        )
        enable_hyperspace(s)

        def q():
            l = s.read.parquet(str(tmp_path / "src"))
            d = s.read.parquet(str(tmp_path / "dim"))
            return l.join(d, col("k") == col("k2")).select("v", "w")

        def scan_token():
            plan = q().physical_plan()
            for node in plan.collect_nodes():
                if isinstance(node, phys.BucketedIndexScanExec):
                    if node.relation.index_name == "rfL":
                        return node.rows_token(None)
            raise AssertionError("no bucketed scan for rfL in plan")

        before_cnt = q().count()
        tok_before = scan_token()
        assert tok_before[0][0] == "log" and tok_before[0][2] is not None

        # Rewrite the source with DIFFERENT data and refresh the index: the
        # log entry id component must advance, and results must be fresh.
        s.write_parquet(
            {
                "k": np.full(500, 1, np.int64),
                "v": np.full(500, 3, np.int64),
            },
            str(tmp_path / "src"),
        )
        hs.refresh_index("rfL")
        tok_after = scan_token()
        assert tok_after[0] != tok_before[0]  # entry id advanced
        after_cnt = q().count()
        assert after_cnt == 500  # every row matches k2 == 1 exactly once
        assert after_cnt != before_cnt or before_cnt == 500
        disable_hyperspace(s)
        assert q().count() == after_cnt

    def test_general_join_memo_keys_carry_relation_sig(self, tmp_path):
        """The general-path pairs memo subkey includes each side's relation
        signature (entry id + file inventory), not just the join keys."""
        s = HyperspaceSession(warehouse=str(tmp_path))
        rng = np.random.RandomState(17)
        s.write_parquet(
            {"a": rng.randint(0, 9, 100).astype(np.int64)}, str(tmp_path / "ga")
        )
        s.write_parquet(
            {"b": rng.randint(0, 9, 80).astype(np.int64)}, str(tmp_path / "gb")
        )
        l = s.read.parquet(str(tmp_path / "ga"))
        r = s.read.parquet(str(tmp_path / "gb"))
        df = l.join(r, col("a") == col("b"))
        plan = df.physical_plan()
        smj = next(
            n for n in plan.collect_nodes() if isinstance(n, phys.SortMergeJoinExec)
        )
        sig = phys._relation_sig(smj.left)
        assert sig is not None
        assert len(sig[2]) >= 1  # file inventory present
