"""Aggregation, ORDER BY, LIMIT: the query shapes real index workloads take
(BASELINE config-2 is a grouped aggregation over the indexed join — TPC-H Q3-like).
The reference gets these operators from Spark SQL; the tests below hold the engine
to SQL semantics (null grouping, null-ignoring aggregates, Spark null ordering) and
to the reference's own E2E oracle: identical results with indexing on vs off
(`E2EHyperspaceRulesTests.scala:454-470`).
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace


@pytest.fixture()
def agg_session(tmp_path):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
    os.makedirs(tmp_path / "sales")
    pq.write_table(
        pa.table(
            {
                "region": pa.array(["east", "west", "east", None, "west", "east", None]),
                "item": pa.array([1, 2, 1, 3, 2, 2, 3], type=pa.int64()),
                "amount": pa.array([10, 20, None, 40, 50, 60, None], type=pa.int64()),
                "price": pa.array([1.5, 2.0, 2.5, None, 4.0, 5.5, 6.0]),
            }
        ),
        str(tmp_path / "sales" / "part-00000.parquet"),
    )
    return s, str(tmp_path)


def _sales(s, base):
    return s.read.parquet(os.path.join(base, "sales"))


class TestGroupBy:
    def test_groupby_sum_count(self, agg_session):
        s, base = agg_session
        rows = (
            _sales(s, base)
            .group_by("region")
            .agg(total=("amount", "sum"), n=("amount", "count"), rows=("*", "count"))
            .sorted_rows()
        )
        # region null group: amounts 40, None -> sum 40, count 1, rows 2
        assert sorted(rows, key=lambda r: (r[0] is None, r)) == [
            ("east", 70, 2, 3),
            ("west", 70, 2, 2),
            (None, 40, 1, 2),
        ]

    def test_groupby_min_max_avg(self, agg_session):
        s, base = agg_session
        got = {
            r[0]: r[1:]
            for r in _sales(s, base)
            .group_by("region")
            .agg(lo=("amount", "min"), hi=("amount", "max"), mean=("price", "avg"))
            .sorted_rows()
        }
        assert got["east"] == (10, 60, (1.5 + 2.5 + 5.5) / 3)
        assert got["west"] == (20, 50, 3.0)
        assert got[None] == (40, 40, 6.0)

    def test_groupby_multiple_keys(self, agg_session):
        s, base = agg_session
        rows = (
            _sales(s, base).group_by("region", "item").agg(n=("*", "count")).sorted_rows()
        )
        assert len(rows) == 4  # distinct keys: (east,1) (east,2) (west,2) (None,3)
        counts = {(r[0], r[1]): r[2] for r in rows}
        assert counts[("east", 1)] == 2
        assert counts[("west", 2)] == 2
        assert counts[(None, 3)] == 2

    def test_all_null_group_aggregate_is_null(self, agg_session):
        s, base = agg_session
        got = {
            r[0]: r[1]
            for r in _sales(s, base)
            .group_by("region")
            .agg(total=("amount", "sum"))
            .sorted_rows()
        }
        # No all-null group for amount here; filter to item=3 (amounts 40, None, None)
        rows = (
            _sales(s, base)
            .filter(col("item") == 3)
            .group_by("item")
            .agg(s=("amount", "sum"), n=("amount", "count"))
            .sorted_rows()
        )
        assert rows == [(3, 40, 1)]

    def test_string_min_max(self, agg_session):
        s, base = agg_session
        rows = (
            _sales(s, base)
            .group_by("item")
            .agg(first=("region", "min"), last=("region", "max"))
            .sorted_rows()
        )
        got = {r[0]: r[1:] for r in rows}
        assert got[1] == ("east", "east")
        assert got[2] == ("east", "west")
        # item 3: regions are [None, None] -> all-null group -> NULL min/max
        assert got[3] == (None, None)

    def test_bool_min_max_grouped(self, agg_session):
        from hyperspace_tpu.engine.table import Table
        from hyperspace_tpu.ops.aggregate import _host_aggregate, hash_aggregate

        t = Table.from_pydict(
            {"k": np.array([1, 1, 2, 2], np.int64), "b": np.array([True, False, True, True])}
        )
        aggs = [("lo", "min", "b"), ("hi", "max", "b")]
        expected = [(1, False, True), (2, True, True)]
        assert hash_aggregate(t, ["k"], aggs).sorted_rows() == expected
        assert _host_aggregate(t, ["k"], aggs).sorted_rows() == expected

    def test_global_agg(self, agg_session):
        s, base = agg_session
        rows = (
            _sales(s, base)
            .agg(total=("amount", "sum"), rows=("*", "count"), navg=("price", "avg"))
            .sorted_rows()
        )
        assert rows == [(180, 7, pytest.approx((1.5 + 2 + 2.5 + 4 + 5.5 + 6) / 6))]

    def test_global_agg_empty_input(self, agg_session):
        s, base = agg_session
        rows = (
            _sales(s, base)
            .filter(col("item") == 99)
            .agg(total=("amount", "sum"), n=("*", "count"))
            .sorted_rows()
        )
        assert rows == [(None, 0)]

    def test_groupby_empty_input(self, agg_session):
        s, base = agg_session
        rows = (
            _sales(s, base)
            .filter(col("item") == 99)
            .group_by("region")
            .agg(n=("*", "count"))
            .sorted_rows()
        )
        assert rows == []

    def test_sum_on_string_raises(self, agg_session):
        s, base = agg_session
        from hyperspace_tpu import HyperspaceException

        with pytest.raises(HyperspaceException, match="sum"):
            _sales(s, base).group_by("item").agg(x=("region", "sum"))

    def test_device_matches_host_oracle(self, agg_session):
        """The device hash-sort/segment path against the exact host groupby."""
        s, base = agg_session
        from hyperspace_tpu.ops.aggregate import _host_aggregate, hash_aggregate

        t = _sales(s, base).collect()
        aggs = [
            ("s", "sum", "amount"),
            ("n", "count", "amount"),
            ("lo", "min", "price"),
            ("hi", "max", "price"),
            ("m", "avg", "amount"),
        ]
        dev = hash_aggregate(t, ["region", "item"], aggs).sorted_rows()
        host = _host_aggregate(t, ["region", "item"], aggs).sorted_rows()
        assert dev == host

    def test_device_matches_host_oracle_large_random(self, agg_session):
        s, base = agg_session
        from hyperspace_tpu.engine.table import Table
        from hyperspace_tpu.ops.aggregate import _host_aggregate, hash_aggregate

        rng = np.random.RandomState(7)
        n = 20_000
        t = Table.from_pydict(
            {
                "k": rng.randint(0, 500, n).astype(np.int64),
                "v": rng.randint(-100, 100, n).astype(np.int64),
                "f": rng.rand(n),
            }
        )
        aggs = [
            ("s", "sum", "v"),
            ("n", "count", "*".replace("*", "v")),
            ("lo", "min", "f"),
            ("hi", "max", "f"),
        ]
        aggs = [("s", "sum", "v"), ("n", "count", "v"), ("lo", "min", "f"), ("hi", "max", "f")]
        assert (
            hash_aggregate(t, ["k"], aggs).sorted_rows()
            == _host_aggregate(t, ["k"], aggs).sorted_rows()
        )


class TestOrderByLimit:
    def test_order_by_asc_desc(self, agg_session):
        s, base = agg_session
        rows = _sales(s, base).order_by("item", ("amount", False)).select("item", "amount").collect().rows()
        # item asc; within item, amount desc with nulls last
        assert rows == [
            (1, 10), (1, None), (2, 60), (2, 50), (2, 20), (3, 40), (3, None),
        ]

    def test_order_by_nulls_first_asc(self, agg_session):
        s, base = agg_session
        rows = _sales(s, base).order_by("amount").select("amount").collect().rows()
        assert rows[:2] == [(None,), (None,)]
        assert rows[2:] == [(10,), (20,), (40,), (50,), (60,)]

    def test_order_by_string(self, agg_session):
        s, base = agg_session
        rows = _sales(s, base).order_by("region").select("region").collect().rows()
        assert rows[:2] == [(None,), (None,)]
        assert [r[0] for r in rows[2:]] == ["east", "east", "east", "west", "west"]

    def test_limit(self, agg_session):
        s, base = agg_session
        assert _sales(s, base).limit(3).count() == 3
        assert _sales(s, base).limit(0).count() == 0
        assert _sales(s, base).limit(100).count() == 7
        rows = _sales(s, base).order_by(("amount", False)).limit(2).select("amount").collect().rows()
        assert rows == [(60,), (50,)]


class TestIndexedAggregation:
    """The point of the exercise: index rewrites accelerate aggregation-bearing
    queries, and results match the non-indexed oracle."""

    def test_groupby_over_indexed_join(self, agg_session, tmp_path):
        s, base = agg_session
        rng = np.random.RandomState(1)
        s.write_parquet(
            {
                "itemId": np.arange(1, 5, dtype=np.int64),
                "weight": rng.randint(1, 10, 4).astype(np.int64),
            },
            str(tmp_path / "items"),
        )
        hs = Hyperspace(s)
        hs.create_index(
            _sales(s, base), IndexConfig("salesIdx", ["item"], ["region", "amount"])
        )
        hs.create_index(
            s.read.parquet(str(tmp_path / "items")),
            IndexConfig("itemsIdx", ["itemId"], ["weight"]),
        )

        def q():
            sales = _sales(s, base)
            items = s.read.parquet(str(tmp_path / "items"))
            return (
                sales.join(items, col("item") == col("itemId"))
                .group_by("region")
                .agg(total=("amount", "sum"), w=("weight", "max"), n=("*", "count"))
            )

        disable_hyperspace(s)
        expected = q().sorted_rows()
        enable_hyperspace(s)
        plan = q().explain_string()
        assert "bucketed, no exchange" in plan
        assert "HashAggregate" in plan
        got = q().sorted_rows()
        assert got == expected and len(got) > 0

    def test_filter_index_under_aggregate(self, agg_session):
        s, base = agg_session
        hs = Hyperspace(s)
        hs.create_index(
            _sales(s, base),
            IndexConfig("fIdx", ["region"], ["item", "amount", "price"]),
        )

        def q():
            return (
                _sales(s, base)
                .filter(col("region") == "east")
                .group_by("item")
                .agg(total=("amount", "sum"))
            )

        disable_hyperspace(s)
        expected = q().sorted_rows()
        enable_hyperspace(s)
        plan = q().explain_string()
        assert "index=fIdx" in plan
        got = q().sorted_rows()
        assert got == expected and len(got) > 0

    def test_orderby_limit_over_indexed_join(self, agg_session, tmp_path):
        s, base = agg_session
        hs = Hyperspace(s)
        hs.create_index(
            _sales(s, base), IndexConfig("sIdx2", ["item"], ["amount"])
        )
        s.write_parquet(
            {"iid": np.arange(1, 4, dtype=np.int64), "tag": np.array(["a", "b", "c"])},
            str(tmp_path / "tags"),
        )
        hs.create_index(
            s.read.parquet(str(tmp_path / "tags")), IndexConfig("tIdx", ["iid"], ["tag"])
        )

        def q():
            sales = _sales(s, base)
            tags = s.read.parquet(str(tmp_path / "tags"))
            return (
                sales.join(tags, col("item") == col("iid"))
                .order_by(("amount", False), "tag")
                .limit(3)
                .select("amount", "tag")
            )

        disable_hyperspace(s)
        expected = q().collect().rows()
        enable_hyperspace(s)
        got = q().collect().rows()
        assert got == expected and len(got) == 3


def test_duplicate_agg_output_name_rejected(agg_session):
    s, base = agg_session
    from hyperspace_tpu import HyperspaceException

    with pytest.raises(HyperspaceException, match="Duplicate"):
        _sales(s, base).group_by("item").agg(item=("amount", "sum"))
    with pytest.raises(HyperspaceException, match="Duplicate"):
        _sales(s, base).group_by("item").agg(x=("amount", "sum"), X=("amount", "min"))


def test_count_distinct(agg_session):
    s, base = agg_session
    rows = (
        _sales(s, base)
        .group_by("region")
        .agg(items=("item", "count_distinct"), amounts=("amount", "count_distinct"))
        .sorted_rows()
    )
    got = {r[0]: r[1:] for r in rows}
    # east: items {1,2}, amounts {10,60} (None excluded)
    assert got["east"] == (2, 2)
    # west: items {2}, amounts {20,50}
    assert got["west"] == (1, 2)
    # null region: items {3}, amounts {40}
    assert got[None] == (1, 1)
    # global
    assert _sales(s, base).agg(n=("item", "count_distinct")).sorted_rows() == [(3,)]
    # host oracle agrees
    from hyperspace_tpu.ops.aggregate import _host_aggregate, hash_aggregate

    t = _sales(s, base).collect()
    aggs = [("d", "count_distinct", "item"), ("a", "count_distinct", "amount")]
    assert (
        hash_aggregate(t, ["region"], aggs).sorted_rows()
        == _host_aggregate(t, ["region"], aggs).sorted_rows()
    )


def test_having_style_filter_on_aggregate_output(agg_session):
    """SQL HAVING: filter over the aggregation's output columns."""
    s, base = agg_session
    rows = (
        _sales(s, base)
        .group_by("region")
        .agg(total=("amount", "sum"))
        .filter(col("total") > 50)
        .sorted_rows()
    )
    assert sorted(r[0] for r in rows) == ["east", "west"]


def test_count_distinct_nan_consistency():
    """NaN counts as ONE distinct value, identically in grouped / host / global
    paths (structured np.unique would otherwise split every NaN)."""
    from hyperspace_tpu.engine.table import Table
    from hyperspace_tpu.ops.aggregate import _host_aggregate, hash_aggregate

    t = Table.from_pydict(
        {
            "k": np.array([1, 1, 1, 2], np.int64),
            "x": np.array([np.nan, np.nan, 1.0, -0.0]),
        }
    )
    aggs = [("d", "count_distinct", "x")]
    grouped = hash_aggregate(t, ["k"], aggs).sorted_rows()
    assert grouped == [(1, 2), (2, 1)]  # {nan, 1.0} and {0.0}
    assert _host_aggregate(t, ["k"], aggs).sorted_rows() == grouped
    assert hash_aggregate(t, [], aggs).sorted_rows() == [(3,)]  # {nan, 1.0, 0.0}
