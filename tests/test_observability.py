"""Resource ledger, quantile histograms, compile observatory, exporter.

Pins the continuous-accounting contracts on top of the PR-4 tracing layer:
- `Histogram` gains bounded log-spaced buckets: p50/p90/p99 in `summary()`
  within bucket tolerance, exact under a concurrent observe hammer, with the
  legacy count/total/min/max fields byte-compatible.
- The per-query ledger attributes bytes decoded/skipped (reconciling with
  the `io.pruning.*` counters), decode-pool work, rows, and cache charges to
  the right query_id — including across two INTERLEAVED queries on separate
  threads, and through the decode pool's worker threads.
- The compile observatory counts XLA compiles per program label and ticks
  `xla.compiles.*` on a forced recompile.
- The exporter appends parseable JSONL frames, drains ledgers, shuts down
  cleanly (final frame, dead thread), and never changes query results.
- Span-cap drops are surfaced (`spans_dropped` root attr + counter) and the
  decode pool's in-flight gauge returns to zero with a recorded peak.
- `tools/bench_compare.py` reports deltas and gates on regressions.
"""

import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.telemetry import (
    accounting,
    compile_log,
    exporter,
    metrics,
    tracing,
)


@pytest.fixture()
def session(tmp_path):
    return HyperspaceSession(warehouse=str(tmp_path))


# ---------------------------------------------------------------------------
# Histogram quantiles
# ---------------------------------------------------------------------------


def test_histogram_summary_keeps_legacy_fields_and_adds_quantiles():
    h = metrics.Histogram("t")
    for v in (0.001, 0.01, 0.1, 1.0):
        h.observe(v)
    s = h.summary()
    # The pre-bucket consumers' fields, unchanged semantics.
    assert s["count"] == 4
    assert s["total"] == pytest.approx(1.111, abs=1e-6)
    assert s["min"] == 0.001 and s["max"] == 1.0
    # Additive quantile keys, clamped to the observed range.
    for k in ("p50", "p90", "p99"):
        assert s["min"] <= s[k] <= s["max"]
    assert json.dumps(s)


def test_histogram_quantiles_within_bucket_tolerance():
    h = metrics.Histogram("t")
    rng = np.random.RandomState(11)
    vals = rng.uniform(0.001, 1.0, 20000)
    for v in vals:
        h.observe(v)
    # Log buckets are 10^0.25 ≈ 1.78x wide: estimates must land within one
    # bucket of the true quantile (generous 2x band both ways).
    for q in (0.5, 0.9, 0.99):
        true = float(np.quantile(vals, q))
        est = h.quantile(q)
        assert true / 2 <= est <= true * 2, (q, est, true)
    # Degenerate cases.
    empty = metrics.Histogram("e")
    assert empty.quantile(0.5) is None and "p50" not in empty.summary()
    assert empty.bucket_counts() == []
    one = metrics.Histogram("o")
    one.observe(0.25)
    assert one.quantile(0.5) == 0.25  # clamped to the single observation
    cum = one.bucket_counts()
    assert cum[-1] == (float("inf"), 1)


def test_histogram_concurrent_observe_loses_nothing():
    h = metrics.histogram("test.obs.hammer")
    h.reset()
    n_threads, n_obs = 16, 500
    # Each thread observes a distinct value so bucket totals are checkable.
    vals = [0.001 * (i + 1) for i in range(n_threads)]

    def work(i):
        for _ in range(n_obs):
            h.observe(vals[i])

    with ThreadPoolExecutor(max_workers=n_threads) as pool:
        list(pool.map(work, range(n_threads)))
    s = h.summary()
    assert s["count"] == n_threads * n_obs
    assert s["min"] == vals[0] and s["max"] == vals[-1]
    assert s["total"] == pytest.approx(sum(vals) * n_obs, rel=1e-9)
    # Bucket mass equals the observation count (no torn increments).
    assert h.bucket_counts()[-1][1] == n_threads * n_obs


def test_gauge_add_and_high_water_mark():
    g = metrics.Gauge("t")
    g.inc(3)
    g.dec()
    assert g.value == 2
    g.set_max(10)
    g.set_max(5)
    assert g.value == 10


# ---------------------------------------------------------------------------
# Per-query resource ledger
# ---------------------------------------------------------------------------


def _write_sorted_table(session, path, n=4000, offset=0):
    """A key-sorted 4-row-group file: an equality filter on `k` prunes 3 of 4
    groups, so pruned decodes (→ ledger bytes_decoded) actually happen."""
    session.write_parquet(
        {
            "k": (np.arange(n, dtype=np.int64) + offset),
            "v": np.arange(n, dtype=np.int64),
        },
        path,
        row_group_rows=n // 4,
    )


def test_ledger_attributes_decodes_and_reconciles_bytes(session, tmp_path, monkeypatch):
    monkeypatch.setenv(accounting.ENV_ACCOUNTING, "1")
    path = os.path.join(str(tmp_path), "t")
    _write_sorted_table(session, path)
    before = metrics.counter("io.pruning.bytes_decoded").value
    df = session.read.parquet(path).filter(col("k") == 7)
    out = df.collect()
    assert out.num_rows == 1
    after = metrics.counter("io.pruning.bytes_decoded").value
    led = accounting.recent_ledgers()[-1]
    d = led.to_dict()
    assert d["name"] == "query:collect"
    assert d["rows_produced"] == 1
    assert d["decode_files"] >= 1 and d["decode_task_s"] > 0
    # Reconciliation: the ledger's bytes_decoded IS the counter's move.
    assert d["bytes_decoded"] == after - before > 0
    assert d["bytes_skipped"] > 0
    assert d["wall_s"] > 0


def test_ledger_interleaved_queries_attribute_separately(session, tmp_path, monkeypatch):
    """Two queries running concurrently on separate threads each get their
    own ledger; decode work crosses the pool but lands on the right query."""
    monkeypatch.setenv(accounting.ENV_ACCOUNTING, "1")
    paths = []
    for i, n_files in enumerate((3, 5)):
        root = os.path.join(str(tmp_path), f"t{i}")
        from hyperspace_tpu.engine import io as engine_io
        from hyperspace_tpu.engine.table import Table

        for j in range(n_files):
            engine_io.write_parquet(
                Table.from_pydict(
                    {"k": np.arange(500, dtype=np.int64) + 1000 * i + j}
                ),
                os.path.join(root, f"part-{j:05d}.parquet"),
            )
        paths.append(root)

    barrier = threading.Barrier(2)
    results = {}

    def run(i):
        s = HyperspaceSession(warehouse=str(tmp_path))
        df = s.read.parquet(paths[i])
        barrier.wait()
        out = df.collect()
        results[i] = out.num_rows

    threads = [threading.Thread(target=run, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == {0: 1500, 1: 2500}
    by_rows = {}
    for led in accounting.recent_ledgers():
        d = led.to_dict()
        if d["name"] == "query:collect" and d.get("rows_produced") in (1500, 2500):
            by_rows[d["rows_produced"]] = d
    assert set(by_rows) == {1500, 2500}
    # Decode-pool attribution: each query's cold files landed on ITS ledger
    # (workers adopt the submitter's ledger), not pooled into one.
    assert by_rows[1500]["decode_files"] == 3
    assert by_rows[2500]["decode_files"] == 5
    assert by_rows[1500]["query_id"] != by_rows[2500]["query_id"]


def test_ledger_rides_root_span_and_explain_analyze(session, tmp_path):
    path = os.path.join(str(tmp_path), "t")
    _write_sorted_table(session, path)
    df = session.read.parquet(path).filter(col("k") < 100)
    with tracing.capture() as cap:
        df.collect()
    root = cap.trace.root
    led = root.attrs.get("ledger")
    assert led is not None and led["query_id"] == cap.trace.query_id
    assert led["rows_produced"] == 100
    # explain(analyze=True) renders the ledger section for ITS query.
    s = df.explain(analyze=True)
    assert "Resource ledger (this query):" in s
    assert "rows_produced: 100" in s


def test_nested_collect_reports_root_rows_only(session, tmp_path, monkeypatch):
    """rows_produced is a ROOT fact: a collect nested inside an outer query
    scope shares the outer LEDGER (one ledger per outermost action), and the
    outer action's own row count wins — never an inner+outer sum."""
    monkeypatch.setenv(accounting.ENV_ACCOUNTING, "1")
    path = os.path.join(str(tmp_path), "t")
    session.write_parquet({"k": np.arange(500, dtype=np.int64)}, path)
    df = session.read.parquet(path)
    with tracing.capture():
        with tracing.query_span("query:outer"):
            inner = df.collect()  # nested: writes 500 to the SHARED ledger
            assert inner.num_rows == 500
            # The outer action's root fact lands last (what collect() does).
            accounting.set_value("rows_produced", 7)
    led = accounting.recent_ledgers()[-1].to_dict()
    assert led["name"] == "query:outer"
    assert led["rows_produced"] == 7  # last root write wins, no 500+7 sum
    # The inner collect's decode work still charges the one shared ledger.
    assert led["decode_files"] >= 1


def test_no_ledger_when_everything_off(session, tmp_path, monkeypatch):
    monkeypatch.delenv(accounting.ENV_ACCOUNTING, raising=False)
    monkeypatch.delenv(tracing.ENV_TRACE_FILE, raising=False)
    monkeypatch.delenv(tracing.ENV_TRACING, raising=False)
    path = os.path.join(str(tmp_path), "t")
    session.write_parquet({"k": np.arange(10, dtype=np.int64)}, path)
    before = len(accounting.recent_ledgers())
    session.read.parquet(path).collect()
    assert len(accounting.recent_ledgers()) == before  # zero-cost off contract


# ---------------------------------------------------------------------------
# Compile observatory
# ---------------------------------------------------------------------------


def test_forced_recompile_ticks_compile_counters():
    from hyperspace_tpu.ops import hashing

    c0 = metrics.counter("xla.compiles.count").value
    t0 = metrics.counter("xla.compiles.traces").value
    p0 = compile_log.program_summary().get("hashing.key64", {"compiles": 0})
    import jax.numpy as jnp

    # Two never-before-seen prime lengths through the fused key64 program:
    # each is a fresh shape signature → at least one fresh backend compile.
    from hyperspace_tpu.engine.table import Column

    for n in (1231, 2459):
        col_ = Column.from_values(np.arange(n, dtype=np.int64))
        hashing.key64([col_], [jnp.asarray(col_.data)])
    assert metrics.counter("xla.compiles.count").value > c0
    assert metrics.counter("xla.compiles.traces").value > t0
    p1 = compile_log.program_summary()["hashing.key64"]
    assert p1["compiles"] > p0["compiles"]
    assert p1["compile_s"] > 0


def test_compile_storm_warns_once_per_label(monkeypatch):
    monkeypatch.setenv(compile_log.ENV_STORM_THRESHOLD, "3")
    label = "test.storm_program"
    s0 = metrics.counter("xla.compiles.storm_warnings").value
    p = compile_log._program(label)
    p.update(compiles=0, compile_s=0.0, traces=0, storm_warned=False)
    with pytest.warns(RuntimeWarning, match="compile storm.*storm_program"):
        for _ in range(4):
            with compile_log._lock:
                p["traces"] += 1
            compile_log._check_storm(label, p)
    assert metrics.counter("xla.compiles.storm_warnings").value == s0 + 1
    # Already-warned: more traces never warn again.
    with compile_log._lock:
        p["traces"] += 10
    compile_log._check_storm(label, p)
    assert metrics.counter("xla.compiles.storm_warnings").value == s0 + 1


def test_compile_delta_lands_on_ambient_span():
    import jax.numpy as jnp

    f = compile_log.observed_jit(lambda x: x * 3 + 1, label="test.span_delta")
    with tracing.capture() as cap:
        with tracing.query_span("query:compile_span"):
            with tracing.span("op:Test") as sp:
                f(jnp.ones((641,)))  # fresh prime shape → compiles here
    spans = cap.trace.find("op:Test")
    assert spans and spans[0].attrs.get("xla_compiles", 0) >= 1
    assert spans[0].attrs.get("xla_compile_s", 0) > 0


# ---------------------------------------------------------------------------
# Exporter
# ---------------------------------------------------------------------------


def test_exporter_frames_schema_and_clean_shutdown(session, tmp_path, monkeypatch):
    path = os.path.join(str(tmp_path), "metrics.jsonl")
    # Ledgers pending from EARLIER tests would ride this exporter's frames
    # (the queue is process-wide) and could alias the rows_produced==1
    # assertion below — start from a drained queue so the frames carry
    # exactly this test's query.
    accounting.drain_pending()
    ex = exporter.MetricsExporter(path, interval_s=0.05).start()
    try:
        monkeypatch.setenv(accounting.ENV_ACCOUNTING, "1")
        t = os.path.join(str(tmp_path), "t")
        _write_sorted_table(session, t)
        session.read.parquet(t).filter(col("k") == 3).collect()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not os.path.exists(path):
            time.sleep(0.02)
    finally:
        ex.stop()
    assert not ex.running  # clean shutdown: thread joined
    frames = [json.loads(line) for line in open(path)]
    assert len(frames) >= 2
    for fr in frames:
        assert {"ts", "seq", "interval_s", "snapshot"} <= set(fr)
        assert "counters" in fr["snapshot"]
        assert isinstance(fr["ledgers"], list)
        assert isinstance(fr["compile_programs"], dict)
    assert frames[-1]["final"] is True
    seqs = [fr["seq"] for fr in frames]
    assert seqs == sorted(seqs)
    # The query's ledger rode a frame, with its decode work attributed.
    ledgers = [l for fr in frames for l in fr["ledgers"]]
    mine = [l for l in ledgers if l.get("rows_produced") == 1]
    assert mine and mine[0]["bytes_decoded"] > 0
    # Quantile histograms in the snapshot stream.
    hists = frames[-1]["snapshot"]["histograms"]
    lat = [k for k in hists if k.startswith("latency.query.")]
    assert lat and hists[lat[0]]["p50"] is not None
    assert hists[lat[0]]["p99"] is not None


def test_exporter_env_start_stop_roundtrip(tmp_path, monkeypatch):
    path = os.path.join(str(tmp_path), "m.jsonl")
    monkeypatch.setenv(exporter.ENV_METRICS_FILE, path)
    monkeypatch.setenv(exporter.ENV_METRICS_INTERVAL, "0.05")
    assert exporter.start() is True
    assert exporter.running()
    assert exporter.start() is True  # idempotent on a live exporter
    exporter.stop()
    assert not exporter.running()
    exporter.stop()  # repeat-safe
    frames = [json.loads(line) for line in open(path)]
    assert frames and frames[-1]["final"] is True


def test_traced_rows_identical_with_exporter_running(session, tmp_path, monkeypatch):
    path = os.path.join(str(tmp_path), "t")
    _write_sorted_table(session, path)
    df = session.read.parquet(path).filter(col("k") < 50)
    plain = sorted(map(tuple, df.collect().rows()))
    ex = exporter.MetricsExporter(
        os.path.join(str(tmp_path), "m.jsonl"), interval_s=0.05
    ).start()
    try:
        monkeypatch.setenv(tracing.ENV_TRACE_FILE, os.path.join(str(tmp_path), "tr.jsonl"))
        observed = sorted(map(tuple, df.collect().rows()))
    finally:
        ex.stop()
    assert observed == plain


def test_prometheus_text_renders_counters_and_histograms():
    metrics.counter("test.prom.hits").inc(4)
    h = metrics.histogram("test.prom.lat")
    h.observe(0.02)
    text = exporter.prometheus_text()
    assert "# TYPE hyperspace_test_prom_hits counter" in text
    assert "hyperspace_test_prom_hits 4" in text
    assert "# TYPE hyperspace_test_prom_lat histogram" in text
    assert 'hyperspace_test_prom_lat_bucket{le="+Inf"}' in text
    assert "hyperspace_test_prom_lat_count 1" in text


# ---------------------------------------------------------------------------
# Satellites: span-cap drops, decode in-flight gauge
# ---------------------------------------------------------------------------


def test_span_cap_drops_surface_on_root_and_counter(monkeypatch):
    monkeypatch.setattr(tracing, "MAX_SPANS_PER_TRACE", 8)
    before = metrics.counter("trace.spans.dropped").value
    with tracing.capture() as cap:
        with tracing.query_span("query:overflow") as root:
            for i in range(20):
                with tracing.span(f"w{i}", parent=root):
                    pass
    trace = cap.trace
    assert trace.dropped > 0
    assert trace.root.attrs["spans_dropped"] == trace.dropped
    assert metrics.counter("trace.spans.dropped").value == before + trace.dropped


def test_decode_in_flight_gauge_returns_to_zero(session, tmp_path, monkeypatch):
    from hyperspace_tpu.engine import io as engine_io
    from hyperspace_tpu.engine.table import Table

    monkeypatch.setenv(engine_io.ENV_DECODE_THREADS, "4")
    root = os.path.join(str(tmp_path), "multi")
    for j in range(6):
        engine_io.write_parquet(
            Table.from_pydict({"k": np.arange(200, dtype=np.int64) + j}),
            os.path.join(root, f"part-{j:05d}.parquet"),
        )
    peak0 = metrics.gauge("io.decode.in_flight_peak").value
    metrics.gauge("io.decode.in_flight_peak").set(0)
    session.read.parquet(root).collect()
    assert metrics.gauge("io.decode.in_flight").value == 0
    assert metrics.gauge("io.decode.in_flight_peak").value >= 1
    metrics.gauge("io.decode.in_flight_peak").set_max(peak0)


# ---------------------------------------------------------------------------
# tools/bench_compare.py
# ---------------------------------------------------------------------------


def _bench_compare():
    import importlib.util

    path = os.path.join(os.path.dirname(__file__), "..", "tools", "bench_compare.py")
    if not os.path.exists(path):
        # The wheel CI job runs the tests copied OUT of the source tree;
        # tools/ ships with the repo, not the package.
        pytest.skip("tools/bench_compare.py not present (installed-wheel run)")
    spec = importlib.util.spec_from_file_location("bench_compare", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_compare_passes_on_improvement(tmp_path, capsys):
    bc = _bench_compare()
    a = os.path.join(str(tmp_path), "a.json")
    b = os.path.join(str(tmp_path), "b.json")
    json.dump({"bench_detail": {"build_s": 2.0, "rows": 100}}, open(a, "w"))
    json.dump({"bench_detail": {"build_s": 1.0, "rows": 100}}, open(b, "w"))
    assert bc.main([a, b]) == 0
    out = capsys.readouterr().out
    assert "build_s: 2 -> 1" in out


def test_bench_compare_fails_past_threshold(tmp_path, capsys):
    bc = _bench_compare()
    a = os.path.join(str(tmp_path), "a.json")
    b = os.path.join(str(tmp_path), "b.json")
    json.dump({"q_p50_s": 1.0, "other_count": 5}, open(a, "w"))
    json.dump({"q_p50_s": 1.5, "other_count": 50}, open(b, "w"))
    # 50% slower: fails at 25%, passes at 60%; the counter never gates.
    assert bc.main([a, b, "--threshold", "0.25"]) == 1
    assert "REGRESSION" in capsys.readouterr().out
    assert bc.main([a, b, "--threshold", "0.6"]) == 0
    # Noise floor: the same ratio under min-seconds never gates.
    json.dump({"q_p50_s": 0.001}, open(a, "w"))
    json.dump({"q_p50_s": 0.002}, open(b, "w"))
    assert bc.main([a, b, "--threshold", "0.25"]) == 0
    # Key filter restricts gating.
    json.dump({"q_p50_s": 1.0, "build_s": 1.0}, open(a, "w"))
    json.dump({"q_p50_s": 2.0, "build_s": 1.0}, open(b, "w"))
    assert bc.main([a, b, "--keys", "build*"]) == 0
    assert bc.main([a, b, "--keys", "q_*"]) == 1


def test_bench_compare_unreadable_input(tmp_path):
    bc = _bench_compare()
    a = os.path.join(str(tmp_path), "a.json")
    json.dump({"x_s": 1.0}, open(a, "w"))
    assert bc.main([a, os.path.join(str(tmp_path), "missing.json")]) == 2
