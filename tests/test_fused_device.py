"""Device-side count fast path + fused bucketed-join→aggregate pipeline.

These pin the round-5 performance paths against the engine's own oracle (the
reference's E2E contract: identical results with indexing on vs off,
`E2EHyperspaceRulesTests.scala:454-470`). HYPERSPACE_FORCE_DEVICE_OPS=1 forces
the device kernels on the CPU backend so CI certifies the exact programs a TPU
runs (`ops/backend.py`)."""

import os

import numpy as np
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.hyperspace import (
    Hyperspace,
    disable_hyperspace,
    enable_hyperspace,
)


@pytest.fixture()
def dev_session(tmp_path, monkeypatch):
    monkeypatch.setenv("HYPERSPACE_FORCE_DEVICE_OPS", "1")
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
    return s


def _rows_close(a, b, tol=1e-6):
    assert len(a) == len(b), (len(a), len(b))
    for ra, rb in zip(a, b):
        for x, y in zip(ra, rb):
            if isinstance(x, float):
                assert abs(x - y) <= tol * max(1.0, abs(x)), (ra, rb)
            else:
                assert x == y, (ra, rb)


def _fact_dim(s, base, n=20000, with_nulls=False):
    rng = np.random.RandomState(11)
    qty = rng.randint(1, 50, n).astype(np.int64)
    price = rng.rand(n) * 100
    if with_nulls:
        price = price.astype(object)
        price[::97] = None
    s.write_parquet(
        {
            "k": rng.randint(0, 400, n).astype(np.int64),
            "qty": qty,
            "price": price,
        },
        os.path.join(base, "fact"),
    )
    s.write_parquet(
        {
            "dk": np.arange(400, dtype=np.int64),
            "grp": np.array([f"g{i % 13:02d}" for i in range(400)]),
        },
        os.path.join(base, "dim"),
    )


def test_value_mode_device_count_matches_oracle(dev_session, tmp_path):
    s = dev_session
    base = str(tmp_path)
    _fact_dim(s, base)
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "fact")), IndexConfig("cf", ["k"], ["qty"])
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "dim")), IndexConfig("cd", ["dk"], ["grp"])
    )

    def q():
        f = s.read.parquet(os.path.join(base, "fact"))
        d = s.read.parquet(os.path.join(base, "dim"))
        return f.join(d, col("k") == col("dk")).select("qty", "grp")

    disable_hyperspace(s)
    expected = q().count()
    enable_hyperspace(s)
    assert "cf" in q().explain_string()
    assert q().count() == expected


def test_hash_mode_device_count_string_keys_with_nulls(dev_session, tmp_path):
    s = dev_session
    base = str(tmp_path)
    rng = np.random.RandomState(5)
    sk = np.array([f"s{i % 60:02d}" for i in range(5000)], dtype=object)
    sk[::113] = None  # null keys never match (SQL semantics)
    s.write_parquet(
        {"sk": sk, "v": rng.randint(0, 9, 5000).astype(np.int64)},
        os.path.join(base, "ls"),
    )
    s.write_parquet(
        {
            "sk2": np.array([f"s{i:02d}" for i in range(80)]),
            "w": np.arange(80, dtype=np.int64),
        },
        os.path.join(base, "rs"),
    )
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "ls")), IndexConfig("hl", ["sk"], ["v"])
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "rs")), IndexConfig("hr", ["sk2"], ["w"])
    )

    def q():
        l = s.read.parquet(os.path.join(base, "ls"))
        r = s.read.parquet(os.path.join(base, "rs"))
        return l.join(r, col("sk") == col("sk2")).select("v", "w")

    disable_hyperspace(s)
    expected = q().count()
    enable_hyperspace(s)
    assert q().count() == expected
    assert expected < 5000  # the null keys really dropped rows


def test_fused_join_agg_matches_oracle(dev_session, tmp_path):
    """Computed column + string group key + sum/count/min/max/avg with NULL
    aggregate inputs, fused end-to-end on device vs the host oracle."""
    s = dev_session
    base = str(tmp_path)
    _fact_dim(s, base, with_nulls=True)
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "fact")),
        IndexConfig("af", ["k"], ["qty", "price"]),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "dim")), IndexConfig("ad", ["dk"], ["grp"])
    )

    def q():
        f = s.read.parquet(os.path.join(base, "fact"))
        d = s.read.parquet(os.path.join(base, "dim"))
        return (
            f.join(d, col("k") == col("dk"))
            .with_column("rev", col("price") * col("qty"))
            .group_by("grp")
            .agg(
                rev=("rev", "sum"),
                n=("qty", "count"),
                np_=("price", "count"),  # null-aware count
                mn=("price", "min"),
                mx=("price", "max"),
                av=("price", "avg"),
            )
            .order_by(("grp", True))
        )

    disable_hyperspace(s)
    expected = q().collect().sorted_rows()
    enable_hyperspace(s)

    from hyperspace_tpu.engine import physical as ph

    fired = []
    orig = ph.HashAggregateExec._try_fused_join_agg

    def spy(self, ctx):
        r = orig(self, ctx)
        fired.append(r is not None)
        return r

    ph.HashAggregateExec._try_fused_join_agg = spy
    try:
        got = q().collect().sorted_rows()
    finally:
        ph.HashAggregateExec._try_fused_join_agg = orig
    assert any(fired), "fused join→agg path did not fire"
    _rows_close(got, expected)


def test_fused_agg_group_by_join_key_under_filter(dev_session, tmp_path):
    """Q14 shape: side filter below the join (bucket-preserving), grouping by a
    right-side column, fused path vs oracle."""
    s = dev_session
    base = str(tmp_path)
    _fact_dim(s, base)
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "fact")),
        IndexConfig("qf", ["k"], ["qty", "price"]),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "dim")), IndexConfig("qd", ["dk"], ["grp"])
    )

    def q():
        f = s.read.parquet(os.path.join(base, "fact"))
        d = s.read.parquet(os.path.join(base, "dim"))
        return (
            f.filter(col("qty") >= 25)
            .join(d, col("k") == col("dk"))
            .group_by("grp")
            .agg(total=("qty", "sum"))
            .order_by(("grp", True))
        )

    disable_hyperspace(s)
    expected = q().collect().sorted_rows()
    enable_hyperspace(s)
    _rows_close(q().collect().sorted_rows(), expected)


def test_count_distinct_falls_back_correctly(dev_session, tmp_path):
    """count_distinct is not fused — the fallback must still be correct."""
    s = dev_session
    base = str(tmp_path)
    _fact_dim(s, base)
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "fact")),
        IndexConfig("df", ["k"], ["qty"]),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "dim")), IndexConfig("dd", ["dk"], ["grp"])
    )

    def q():
        f = s.read.parquet(os.path.join(base, "fact"))
        d = s.read.parquet(os.path.join(base, "dim"))
        return (
            f.join(d, col("k") == col("dk"))
            .group_by("grp")
            .agg(u=("qty", "count_distinct"))
            .order_by(("grp", True))
        )

    disable_hyperspace(s)
    expected = q().collect().sorted_rows()
    enable_hyperspace(s)
    assert q().collect().sorted_rows() == expected


def test_fused_agg_collision_rename_matches_unfused(dev_session, tmp_path):
    """Right side has BOTH a colliding column `y` and a literal `y_r`: the fused
    env must resolve `y_r` exactly like _assemble_join's renaming does (the
    collision-renamed right.y, not the literal)."""
    s = dev_session
    base = str(tmp_path)
    rng = np.random.RandomState(2)
    s.write_parquet(
        {
            "k": rng.randint(0, 50, 4000).astype(np.int64),
            "y": rng.randint(0, 5, 4000).astype(np.int64),
        },
        os.path.join(base, "lf"),
    )
    s.write_parquet(
        {
            "k2": np.arange(50, dtype=np.int64),
            "y": (np.arange(50) % 7 + 100).astype(np.int64),
            "y_r": (np.arange(50) % 3 + 500).astype(np.int64),
        },
        os.path.join(base, "rf"),
    )
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "lf")), IndexConfig("xl", ["k"], ["y"])
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "rf")),
        IndexConfig("xr", ["k2"], ["y", "y_r"]),
    )

    def q():
        # Both right.y and the literal right.y_r survive pruning (both names
        # are referenced), so the join output renames right.y -> y_r and the
        # literal y_r -> y_r_r. `sum(y_r)` must aggregate right.y (100-106),
        # not the literal (500-502).
        l = s.read.parquet(os.path.join(base, "lf"))
        r = s.read.parquet(os.path.join(base, "rf"))
        return (
            l.join(r, col("k") == col("k2"))
            .group_by("y")
            .agg(s=("y_r", "sum"), n=("k", "count"))
            .order_by(("y", True))
        )

    disable_hyperspace(s)
    expected = q().collect().sorted_rows()
    enable_hyperspace(s)
    got = q().collect().sorted_rows()
    assert got == expected
    for y, ssum, n in got:
        assert 100 * n <= ssum <= 106 * n, (y, ssum, n)  # right.y, not the literal


def test_general_join_device_count_matches_oracle(dev_session, tmp_path):
    """The NON-indexed (general sort-merge) inner-join count also stays on
    device: string keys + nulls, against the materializing oracle."""
    s = dev_session
    base = str(tmp_path)
    rng = np.random.RandomState(8)
    sk = np.array([f"g{i % 70:02d}" for i in range(6000)], dtype=object)
    sk[::101] = None
    s.write_parquet(
        {"gk": sk, "v": rng.randint(0, 5, 6000).astype(np.int64)},
        os.path.join(base, "gl"),
    )
    s.write_parquet(
        {
            "gk2": np.array([f"g{i:02d}" for i in range(90)]),
            "w": np.arange(90, dtype=np.int64),
        },
        os.path.join(base, "gr"),
    )

    def q():
        l = s.read.parquet(os.path.join(base, "gl"))
        r = s.read.parquet(os.path.join(base, "gr"))
        return l.join(r, col("gk") == col("gk2")).select("v", "w")

    # No indexes at all: this is the general path.
    disable_hyperspace(s)
    expected_rows = len(q().collect().rows())
    assert q().count() == expected_rows
    assert expected_rows < 6000  # nulls dropped


def test_fused_agg_with_shadowing_withcolumn(dev_session, tmp_path):
    """A withColumn that SHADOWS a source column (reading it in its own
    expression) must aggregate the computed values, not the source."""
    s = dev_session
    base = str(tmp_path)
    _fact_dim(s, base)
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "fact")),
        IndexConfig("sh", ["k"], ["qty"]),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "dim")), IndexConfig("sd", ["dk"], ["grp"])
    )

    def q():
        f = s.read.parquet(os.path.join(base, "fact"))
        d = s.read.parquet(os.path.join(base, "dim"))
        return (
            f.join(d, col("k") == col("dk"))
            .with_column("qty", col("qty") * 10)  # shadows the source column
            .group_by("grp")
            .agg(total=("qty", "sum"))
            .order_by(("grp", True))
        )

    disable_hyperspace(s)
    expected = q().collect().sorted_rows()
    enable_hyperspace(s)
    got = q().collect().sorted_rows()
    assert got == expected


@pytest.mark.parametrize("how", ["left", "right", "full", "left_semi", "left_anti"])
def test_general_join_device_count_all_types(dev_session, tmp_path, how):
    """Every join type's COUNT stays on device on the general path — verified
    against the materializing oracle, with null keys present."""
    s = dev_session
    base = str(tmp_path)
    rng = np.random.RandomState(12)
    lk = rng.randint(0, 30, 2000).astype(object)
    lk[::37] = None
    s.write_parquet({"a": lk, "v": np.arange(2000, dtype=np.int64)},
                    os.path.join(base, "jl"))
    s.write_parquet({"b": np.arange(20, 45, dtype=np.int64),
                     "w": np.arange(25, dtype=np.int64)},
                    os.path.join(base, "jr"))

    def q():
        l = s.read.parquet(os.path.join(base, "jl"))
        r = s.read.parquet(os.path.join(base, "jr"))
        return l.join(r, col("a") == col("b"), how=how)

    disable_hyperspace(s)
    expected = len(q().collect().rows())
    assert q().count() == expected


def test_general_value_direct_count_with_nans(dev_session, tmp_path):
    """Single numeric-key inner count takes the value-direct device program;
    NaN keys never match (SQL), matching the materializing oracle."""
    s = dev_session
    base = str(tmp_path)
    rng = np.random.RandomState(13)
    lk = rng.randint(0, 40, 3000).astype(np.float64)
    lk[::29] = np.nan
    rk = np.arange(50, dtype=np.float64)
    rk[7] = np.nan  # right-side NaN must match nothing either
    s.write_parquet({"a": lk, "v": np.arange(3000, dtype=np.int64)},
                    os.path.join(base, "vl"))
    s.write_parquet({"b": rk, "w": np.arange(50, dtype=np.int64)},
                    os.path.join(base, "vr"))

    def q():
        l = s.read.parquet(os.path.join(base, "vl"))
        r = s.read.parquet(os.path.join(base, "vr"))
        return l.join(r, col("a") == col("b"))

    disable_hyperspace(s)
    expected = len(q().collect().rows())
    assert q().count() == expected
    assert expected < 3000


def test_general_value_count_numpy_promotion(dev_session, tmp_path):
    """int64 x float32 keys promote per NUMPY (-> float64), matching the
    verify oracle: a 2^24+1 int key must NOT match 2^24 float32."""
    s = dev_session
    base = str(tmp_path)
    s.write_parquet(
        {"a": np.array([16777217, 5], dtype=np.int64)}, os.path.join(base, "pl")
    )
    s.write_parquet(
        {"b": np.array([16777216.0, 5.0], dtype=np.float32)}, os.path.join(base, "pr")
    )
    l = s.read.parquet(os.path.join(base, "pl"))
    r = s.read.parquet(os.path.join(base, "pr"))
    q = l.join(r, col("a") == col("b"))
    disable_hyperspace(s)
    assert q.count() == len(q.collect().rows()) == 1  # only the 5 == 5.0 pair


def test_fused_agg_device_pairs_cached_across_queries(dev_session, tmp_path):
    """Steady-state fused aggregates must not redo the device probe/expansion/
    verification: the compacted device pairs are cached per (left, right)
    table identity, so the second identical query computes them zero times
    (on TPU the probe alone measured 1.15 s at 8M rows)."""
    from hyperspace_tpu.engine import physical as ph

    s = dev_session
    base = str(tmp_path)
    _fact_dim(s, base)
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "fact")),
        IndexConfig("pc_f", ["k"], ["qty", "price"]),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "dim")), IndexConfig("pc_d", ["dk"], ["grp"])
    )
    enable_hyperspace(s)

    def q():
        f = s.read.parquet(os.path.join(base, "fact"))
        d = s.read.parquet(os.path.join(base, "dim"))
        return (
            f.join(d, col("k") == col("dk"))
            .group_by("grp")
            .agg(total=("qty", "sum"))
            .order_by(("grp", True))
        )

    calls = []
    orig = ph.SortMergeJoinExec._device_pairs_compacted

    def spy(self, *a, **k):
        calls.append(1)
        return orig(self, *a, **k)

    ph.SortMergeJoinExec._device_pairs_compacted = spy
    try:
        first = q().collect().rows()
        n_first = len(calls)
        assert n_first >= 1  # the fused path actually computed device pairs
        second = q().collect().rows()
        assert len(calls) == n_first  # cache hit: zero recomputes
    finally:
        ph.SortMergeJoinExec._device_pairs_compacted = orig
    assert first == second


def test_count_reuses_pairs_cached_by_aggregate(dev_session, tmp_path):
    """Cross-query reuse: after an aggregate cached the device pairs for a
    table pair, a count on the same join must answer from the cache without
    re-deriving the padded reps (the probe's input)."""
    from hyperspace_tpu.engine import physical as ph

    s = dev_session
    base = str(tmp_path)
    _fact_dim(s, base)
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "fact")),
        IndexConfig("cr_f", ["k"], ["qty", "price"]),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "dim")), IndexConfig("cr_d", ["dk"], ["grp"])
    )

    def join():
        f = s.read.parquet(os.path.join(base, "fact"))
        d = s.read.parquet(os.path.join(base, "dim"))
        return f.join(d, col("k") == col("dk"))

    disable_hyperspace(s)
    expected = join().count()
    enable_hyperspace(s)
    # The aggregate populates the device pairs cache for this table pair.
    join().group_by("grp").agg(total=("qty", "sum")).collect()

    orig = ph.SortMergeJoinExec._reconciled_reps

    def boom(self, *a, **k):
        raise AssertionError("count should answer from the pairs cache")

    ph.SortMergeJoinExec._reconciled_reps = boom
    try:
        assert join().count() == expected
    finally:
        ph.SortMergeJoinExec._reconciled_reps = orig


class _FakeRelNode:
    """Stub exec node exposing only what `_node_relation_names` reads."""

    class _Rel:
        class _Schema:
            def __init__(self, names):
                self.names = names

        def __init__(self, names):
            self.schema = self._Schema(names)

    def __init__(self, names):
        self.relation = self._Rel(names)


def test_pair_subkey_preserves_case_on_colliding_schemas():
    """With both 'K' and 'k' in scope, joins on col('K') and col('k') read
    DIFFERENT columns (resolution is exact-match-first) and must not share a
    pairs-cache entry under the projection-independent rows key. The guard
    keys off the UNDERLYING RELATION schemas: pair entries are shared across
    prunings of the same scan, so a pruning that dropped one of the colliding
    spellings must still key exactly (ADVICE round 5)."""
    from hyperspace_tpu.engine import physical as ph
    from hyperspace_tpu.engine.table import Table

    plain_l = Table.from_pydict({"k": np.array([1]), "v": np.array([2])})
    plain_r = Table.from_pydict({"dk": np.array([1])})
    ln = _FakeRelNode(["k", "v"])
    rn = _FakeRelNode(["dk"])
    assert ph._pair_subkey(["K"], ["dk"], ln, rn, plain_l, plain_r) == (
        ("k",),
        ("dk",),
    )

    # Case-colliding RELATION schema, but a pruned table that kept only one
    # spelling: the guard must still see the collision and keep exact keys.
    collide_node = _FakeRelNode(["K", "k"])
    pruned_l = Table.from_pydict({"K": np.array([1])})
    a = ph._pair_subkey(["K"], ["dk"], collide_node, rn, pruned_l, plain_r)
    b = ph._pair_subkey(["k"], ["dk"], collide_node, rn, pruned_l, plain_r)
    assert a != b  # exact spellings kept: no shared entry
    assert a == (("K",), ("dk",))

    # Fallback without a relation (no single underlying scan): the pruned
    # tables' own names decide, as before.
    collide_l = Table.from_pydict({"K": np.array([1]), "k": np.array([2])})
    a = ph._pair_subkey(["K"], ["dk"], object(), object(), collide_l, plain_r)
    b = ph._pair_subkey(["k"], ["dk"], object(), object(), collide_l, plain_r)
    assert a != b


def test_repeated_count_probes_once(dev_session, tmp_path):
    """Steady-state counts must not re-probe: probe ranges ride the pairs
    memo keyed by row identity (the probe was the dominant repeated-count
    device cost — 1.15s at 8M on TPU), and a later aggregate starts from the
    same cached ranges."""
    from hyperspace_tpu.ops import bucket_join as bj

    s = dev_session
    base = str(tmp_path)
    _fact_dim(s, base)
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "fact")),
        IndexConfig("rp_f", ["k"], ["qty", "price"]),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "dim")), IndexConfig("rp_d", ["dk"], ["grp"])
    )

    def join():
        f = s.read.parquet(os.path.join(base, "fact"))
        d = s.read.parquet(os.path.join(base, "dim"))
        return f.join(d, col("k") == col("dk"))

    disable_hyperspace(s)
    expected = join().count()
    enable_hyperspace(s)

    calls = []
    real = bj.probe_ranges

    def spy(*a, **k):
        calls.append(1)
        return real(*a, **k)

    bj.probe_ranges = spy
    try:
        assert join().count() == expected
        n_first = len(calls)
        assert n_first >= 1
        assert join().count() == expected  # repeat: cached ranges, no probe
        assert len(calls) == n_first
        # An aggregate on the same rows starts from the cached ranges too
        # (the fused device path computes pairs, not ranges, so at most the
        # pair-expansion machinery runs — never a fresh probe_ranges).
        join().group_by("grp").agg(total=("qty", "sum")).collect()
        assert len(calls) == n_first
    finally:
        bj.probe_ranges = real
