"""Equivalence: the Pallas tiled-compare probe kernel == the XLA searchsorted
probe, across key dtypes, duplicates, empty buckets, and pad slots.

Off-TPU the kernel runs in Pallas interpret mode (same program, interpreted),
which is how CI certifies the kernel the TPU lowers via Mosaic. The per-bucket
merge under test is the reference's SortMergeJoinExec-over-co-bucketed-scans
equivalent (`JoinIndexRule.scala:137-162`).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from hyperspace_tpu.ops.bucket_join import _PAD, _probe, probe_ranges
from hyperspace_tpu.ops.pallas_probe import probe_pallas


def _padded_from_lists(buckets, cap, dtype, pad):
    B = len(buckets)
    mat = np.full((B, cap), pad, dtype=dtype)
    lens = np.zeros(B, np.int64)
    for i, b in enumerate(buckets):
        b = np.sort(np.asarray(b, dtype=dtype))
        mat[i, : len(b)] = b
        lens[i] = len(b)
    return jnp.asarray(mat), jnp.asarray(lens)


def _assert_equiv(ls, rs, l_len, r_len):
    lo_x, cnt_x = _probe(ls, rs, l_len, r_len)
    lo_p, cnt_p = probe_pallas(ls, rs, l_len, r_len)
    # Counts must agree everywhere; lo must agree wherever a match exists
    # (lo is meaningless where count==0, but the XLA path still clamps it —
    # compare under the same clamp).
    np.testing.assert_array_equal(np.asarray(cnt_x), np.asarray(cnt_p))
    mask = np.asarray(cnt_x) > 0
    np.testing.assert_array_equal(np.asarray(lo_x)[mask], np.asarray(lo_p)[mask])


def test_int64_hash_keys_with_duplicates():
    rng = np.random.RandomState(0)
    buckets_l = [rng.randint(-(2**62), 2**62, size=n) for n in (5, 0, 17, 32)]
    # Force cross-side duplicates: reuse some left keys on the right.
    buckets_r = [
        np.concatenate([rng.choice(bl, size=min(3, len(bl)), replace=True), rng.randint(-(2**62), 2**62, size=m)])
        if len(bl)
        else rng.randint(-(2**62), 2**62, size=m)
        for bl, m in zip(buckets_l, (7, 4, 0, 61))
    ]
    ls, llen = _padded_from_lists(buckets_l, 32, np.int64, _PAD)
    rs, rlen = _padded_from_lists(buckets_r, 64, np.int64, _PAD)
    _assert_equiv(ls, rs, llen, rlen)


def test_int64_value_keys_small_range():
    # Small key range → lots of equal runs on both sides.
    rng = np.random.RandomState(1)
    buckets_l = [rng.randint(0, 5, size=n) for n in (16, 16, 16, 16)]
    buckets_r = [rng.randint(0, 5, size=n) for n in (16, 16, 16, 16)]
    ls, llen = _padded_from_lists(buckets_l, 16, np.int64, np.iinfo(np.int64).max)
    rs, rlen = _padded_from_lists(buckets_r, 16, np.int64, np.iinfo(np.int64).max)
    _assert_equiv(ls, rs, llen, rlen)


def test_float64_value_keys_including_zero_signs():
    rng = np.random.RandomState(2)
    vals = np.concatenate([rng.randn(20), [-0.0, 0.0, 0.0, -1.5, 1e300, -1e300]])
    buckets_l = [vals[:13], vals[13:]]
    buckets_r = [vals[5:20], vals[:6]]
    pad = np.finfo(np.float64).max
    ls, llen = _padded_from_lists(buckets_l, 16, np.float64, pad)
    rs, rlen = _padded_from_lists(buckets_r, 16, np.float64, pad)
    _assert_equiv(ls, rs, llen, rlen)


def test_int32_value_keys():
    rng = np.random.RandomState(3)
    buckets = [rng.randint(-100, 100, size=9) for _ in range(3)]
    ls, llen = _padded_from_lists(buckets, 16, np.int32, np.iinfo(np.int32).max)
    rs, rlen = _padded_from_lists(buckets[::-1], 16, np.int32, np.iinfo(np.int32).max)
    _assert_equiv(ls, rs, llen, rlen)


def test_large_caps_exercise_tiling():
    # cap_l > TL(256) and cap_r > TR(512): multiple grid tiles + accumulation.
    rng = np.random.RandomState(4)
    B, cap_l, cap_r = 3, 512, 2048
    buckets_l = [rng.randint(0, 1000, size=rng.randint(1, cap_l)) for _ in range(B)]
    buckets_r = [rng.randint(0, 1000, size=rng.randint(1, cap_r)) for _ in range(B)]
    ls, llen = _padded_from_lists(buckets_l, cap_l, np.int64, _PAD)
    rs, rlen = _padded_from_lists(buckets_r, cap_r, np.int64, _PAD)
    _assert_equiv(ls, rs, llen, rlen)


def test_probe_ranges_dispatches_to_pallas(monkeypatch):
    monkeypatch.setenv("HYPERSPACE_PALLAS_PROBE", "1")
    calls = []
    import hyperspace_tpu.ops.bucket_join as bj
    import hyperspace_tpu.ops.pallas_probe as pp

    real = pp.probe_pallas

    def spy(*a, **k):
        calls.append(1)
        return real(*a, **k)

    monkeypatch.setattr(pp, "probe_pallas", spy)
    rng = np.random.RandomState(5)
    buckets = [rng.randint(0, 50, size=10) for _ in range(2)]
    ls, llen = _padded_from_lists(buckets, 16, np.int64, _PAD)
    rs, rlen = _padded_from_lists(buckets, 16, np.int64, _PAD)
    lo, cnt = bj.probe_ranges(ls, rs, llen, rlen)
    assert calls, "pallas probe not dispatched under HYPERSPACE_PALLAS_PROBE=1"
    lo_x, cnt_x = _probe(ls, rs, llen, rlen)
    np.testing.assert_array_equal(np.asarray(cnt), np.asarray(cnt_x))


def test_pallas_failure_falls_back(monkeypatch):
    monkeypatch.setenv("HYPERSPACE_PALLAS_PROBE", "1")
    import hyperspace_tpu.ops.bucket_join as bj
    import hyperspace_tpu.ops.pallas_probe as pp

    def boom(*a, **k):
        raise RuntimeError("mosaic lowering failed")

    monkeypatch.setattr(pp, "probe_pallas", boom)
    monkeypatch.setattr(pp, "_pallas_broken", {})
    rng = np.random.RandomState(6)
    buckets = [rng.randint(0, 50, size=10) for _ in range(2)]
    ls, llen = _padded_from_lists(buckets, 16, np.int64, _PAD)
    rs, rlen = _padded_from_lists(buckets, 16, np.int64, _PAD)
    lo, cnt = bj.probe_ranges(ls, rs, llen, rlen)  # must not raise
    lo_x, cnt_x = _probe(ls, rs, llen, rlen)
    np.testing.assert_array_equal(np.asarray(cnt), np.asarray(cnt_x))
    assert pp._pallas_broken  # failure recorded
    assert not pp.pallas_probe_wanted(16, 16, 2, np.dtype(np.int64))
    # The latch is SCOPED per key kind: an int failure must not drop the
    # (independent) float path, and vice versa.
    assert pp.pallas_probe_wanted(16, 16, 2, np.dtype(np.float64))
    pp.record_pallas_failure(RuntimeError("float lowering failed"), np.dtype(np.float64))
    assert not pp.pallas_probe_wanted(16, 16, 2, np.dtype(np.float64))


def test_shape_gate_refuses_unlowerable_bucket_counts(monkeypatch):
    """Bucket counts that are neither <=8 nor a multiple of 8 cannot lower
    (whole-axis blocks would blow VMEM); the dispatcher must refuse them even
    when forced, instead of tripping the permanent failure latch."""
    import hyperspace_tpu.ops.pallas_probe as pp

    monkeypatch.setenv("HYPERSPACE_PALLAS_PROBE", "1")
    monkeypatch.setattr(pp, "_pallas_broken", {})
    assert pp.shape_supported(8, 256, 512)
    assert pp.shape_supported(64, 256, 512)
    assert pp.shape_supported(3, 64, 64)
    assert not pp.shape_supported(20, 256, 512)  # >8, not a multiple of 8
    assert not pp.pallas_probe_wanted(256, 512, 20)
    assert not pp._pallas_broken  # refusal is not a failure


def test_float_split_32bit_matches_64bit_transform():
    """The pure-32-bit float split (`_split_hi_lo_float`, no 64-bit bitcast —
    the relay's X64-elimination rejects `bitcast f64->s64`) must reproduce the
    canonical transform's (hi, lo) pair bit-for-bit, including sign flips,
    -0.0 canonicalization, denormals, and extreme magnitudes."""
    import jax.numpy as jnp

    from hyperspace_tpu.ops.pallas_probe import (
        _sortable_i64,
        _split_hi_lo,
        _split_hi_lo_float,
    )

    rng = np.random.RandomState(17)
    vals = np.concatenate(
        [
            rng.randn(256) * 1e3,
            # NOTE on denormals (±5e-324): XLA flushes f64 denormals to zero
            # (measured on XLA-CPU: x + 0.0 == 0.0 and x == 0 is True), so
            # both the 64-bit and 32-bit transforms map them to the zero key
            # IDENTICALLY — the bit-equality check below covers them, but the
            # order-vs-numpy check can't (numpy doesn't flush).
            np.array([0.0, -0.0, 1e308, -1e308, 1.5, -1.5]),
            rng.randn(64) * 1e-300,
        ]
    )
    x = jnp.asarray(vals)
    hi64, lo64 = _split_hi_lo(_sortable_i64(x))
    hi32, lo32 = _split_hi_lo_float(x)
    np.testing.assert_array_equal(np.asarray(hi64), np.asarray(hi32))
    np.testing.assert_array_equal(np.asarray(lo64), np.asarray(lo32))
    # And the pair really orders like the floats do under the kernel's
    # lexicographic SIGNED compare (hi first, then the biased lo).
    order = np.lexsort((np.asarray(lo32), np.asarray(hi32)))
    np.testing.assert_array_equal(vals[order], np.sort(vals))


def test_float_keys_admitted_on_any_backend(monkeypatch):
    """Round-4 excluded float value-mode keys on TPU (64-bit bitcast rejected
    by the relay); the 32-bit split lifts that — the dispatcher must admit
    floats wherever shapes allow."""
    import hyperspace_tpu.ops.pallas_probe as pp

    monkeypatch.setattr(pp, "_pallas_broken", {})
    monkeypatch.setenv("HYPERSPACE_PALLAS_PROBE", "1")
    assert pp.pallas_probe_wanted(256, 512, 8, np.dtype(np.float64))


def test_host_probe_matches_xla_probe():
    """The CPU backend's host probe (`_probe_host`) must match the XLA probe
    exactly on valid regions: lo wherever counts > 0, counts everywhere."""
    from hyperspace_tpu.ops.bucket_join import _probe, _probe_host

    rng = np.random.RandomState(3)
    B, capL, capR = 6, 256, 64
    L = np.sort(rng.randint(0, 300, (B, capL)).astype(np.int64), axis=1)
    R = np.sort(rng.randint(0, 300, (B, capR)).astype(np.int64), axis=1)
    l_len = rng.randint(0, capL + 1, B).astype(np.int32)
    r_len = rng.randint(0, capR + 1, B).astype(np.int32)
    lo_h, cnt_h = _probe_host(L, R, l_len, r_len)
    lo_x, cnt_x = (np.asarray(a) for a in _probe(L, R, l_len, r_len))
    valid = np.arange(capL)[None, :] < l_len[:, None]
    np.testing.assert_array_equal(cnt_h[valid], cnt_x[valid])
    np.testing.assert_array_equal(cnt_h[~valid], 0)
    m = valid & (cnt_h > 0)
    np.testing.assert_array_equal(lo_h[m], lo_x[m])


def test_value_rep_canonicalizes_negative_zero():
    """pad_buckets_by_value must emit no -0.0 keys (probe implementations
    disagree on signed-zero ordering; the engine's equality treats them
    equal), and a NaN-holding bucket must fall back to the hash rep."""
    import jax.numpy as jnp
    from hyperspace_tpu.ops import bucket_join as bj

    rep = bj.pad_buckets_by_value(
        jnp.asarray(np.array([-0.0, 0.0, 1.5])), np.array([0, 3])
    )
    assert rep is not None and rep.mode == "value"
    keys = np.asarray(rep.keys)[0, :3]
    assert not np.signbit(keys).any()
    np.testing.assert_array_equal(keys, [0.0, 0.0, 1.5])
    assert (
        bj.pad_buckets_by_value(
            jnp.asarray(np.array([1.0, np.nan])), np.array([0, 2])
        )
        is None
    )
    # A SINGLETON NaN bucket has zero sortedness comparisons — the explicit
    # NaN gate (not the non-decreasing check) must reject it.
    assert (
        bj.pad_buckets_by_value(
            jnp.asarray(np.array([np.nan])), np.array([0, 1])
        )
        is None
    )
