"""Device-resident encoded execution (ISSUE 15 tentpole).

Contract under test: with ``HYPERSPACE_ENCODED_DEVICE`` on (the auto
default, riding the encoded-exec master switch), string key lanes cross the
host→device boundary as NARROW dictionary codes (int8/int16 when the
dictionary fits) and the mesh exchange moves code-space lanes — while every
result (join rows, aggregate groups, index file bytes) stays BYTE-IDENTICAL
to the ``HYPERSPACE_ENCODED_DEVICE=0`` flat-staging fallback, in both
``HYPERSPACE_DISTRIBUTED`` ambients. Code width folds into the jit cache key
as a bounded class set: two cardinalities in the same width class share one
compiled exchange (no per-cardinality shapes).
"""

import hashlib
import os

import numpy as np
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.engine import encoded_device
from hyperspace_tpu.engine.table import Column, Table
from hyperspace_tpu.hyperspace import Hyperspace, enable_hyperspace
from hyperspace_tpu.telemetry import compile_log, metrics

ENV = encoded_device.ENV_ENCODED_DEVICE

# Distinct from every other suite so mesh program shapes are this file's own.
NUM_BUCKETS = 28


def _session(tmp_path, num_buckets=NUM_BUCKETS):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, num_buckets)
    s.conf.set(IndexConstants.DISTRIBUTED_MIN_ROWS, 0)
    return s


def _clear_caches():
    from hyperspace_tpu.engine.physical import clear_device_memos
    from hyperspace_tpu.engine.scan_cache import (
        global_bucketed_cache,
        global_concat_cache,
        global_filtered_cache,
        global_scan_cache,
    )

    global_scan_cache().clear()
    global_concat_cache().clear()
    global_filtered_cache().clear()
    global_bucketed_cache().clear()
    clear_device_memos()


def _write_str_pair(s, base, n, card=60, seed=7, suffix=""):
    """String-key fact/dim pair; `card` distinct keys (≤127 → int8 codes)."""
    rng = np.random.RandomState(seed)
    s.write_parquet(
        {
            "sk": np.array([f"c{v:04d}" for v in rng.randint(0, card, n)]),
            "val": np.arange(n, dtype=np.int64),
        },
        os.path.join(base, f"fact{suffix}"),
    )
    s.write_parquet(
        {
            "dk": np.array([f"c{v:04d}" for v in rng.randint(0, card, n // 4)]),
            "w": rng.randint(0, 100, n // 4).astype(np.int64),
        },
        os.path.join(base, f"dim{suffix}"),
    )


def _tables_identical(a: Table, b: Table):
    assert a.column_names == b.column_names
    for n in a.column_names:
        ca, cb = a.columns[n], b.columns[n]
        assert ca.dtype == cb.dtype, n
        assert np.array_equal(ca.data, cb.data), n
        if ca.is_string:
            assert np.array_equal(ca.dictionary, cb.dictionary), n
        assert (ca.validity is None) == (cb.validity is None), n
        if ca.validity is not None:
            assert np.array_equal(ca.validity, cb.validity), n


def _on_off(monkeypatch, make_result):
    """(result_on, result_off), each produced COLD (caches cleared)."""
    monkeypatch.setenv(ENV, "1")
    _clear_caches()
    on = make_result()
    monkeypatch.setenv(ENV, "0")
    _clear_caches()
    off = make_result()
    monkeypatch.delenv(ENV, raising=False)
    _clear_caches()
    return on, off


def _dir_hashes(root):
    return {
        f: hashlib.sha256(open(os.path.join(root, f), "rb").read()).hexdigest()
        for f in sorted(os.listdir(root))
        if f.startswith("part-")
    }


# ---------------------------------------------------------------------------
# Width policy units
# ---------------------------------------------------------------------------


class TestWidthPolicy:
    def test_code_dtype_boundaries(self):
        assert encoded_device.code_dtype_for(1) is np.int8
        assert encoded_device.code_dtype_for(127) is np.int8
        assert encoded_device.code_dtype_for(128) is np.int16
        assert encoded_device.code_dtype_for(32767) is np.int16
        assert encoded_device.code_dtype_for(32768) is None

    def test_mode_parsing(self, monkeypatch):
        monkeypatch.delenv(ENV, raising=False)
        assert encoded_device.encoded_device_mode() == "auto"
        monkeypatch.setenv(ENV, "0")
        assert encoded_device.encoded_device_mode() == "off"
        assert not encoded_device.encoded_device_enabled()
        monkeypatch.setenv(ENV, "1")
        assert encoded_device.encoded_device_mode() == "force"
        assert encoded_device.encoded_device_enabled()

    def test_narrow_codes_value_identical_and_memoized(self, monkeypatch):
        monkeypatch.setenv(ENV, "1")
        strings = np.array([f"s{i}" for i in range(50)])
        codes = np.arange(50, dtype=np.int32) % 50
        c = Column("string", codes, np.sort(strings))
        narrow = encoded_device.narrow_codes(c)
        assert narrow.dtype == np.int8
        assert np.array_equal(narrow.astype(np.int32), c.data)
        assert encoded_device.narrow_codes(c) is narrow  # memoized
        assert encoded_device.column_qualifies(c)  # force mode: marker not needed

    def test_wide_dictionary_stays_flat(self, monkeypatch):
        monkeypatch.setenv(ENV, "1")
        card = 40000
        dictionary = np.sort(np.array([f"u{i:05d}" for i in range(card)]))
        c = Column("string", np.arange(card, dtype=np.int32), dictionary)
        assert not encoded_device.narrowable(c)
        assert encoded_device.narrow_codes(c) is c.data

    def test_auto_mode_wants_encoded_read_marker(self, monkeypatch):
        monkeypatch.delenv(ENV, raising=False)
        monkeypatch.delenv("HYPERSPACE_ENCODED_EXEC", raising=False)
        dictionary = np.sort(np.array([f"s{i}" for i in range(30)]))
        c = Column("string", np.zeros(8, np.int32), dictionary)
        assert encoded_device.narrowable(c)  # lane-level: no marker needed
        assert not encoded_device.column_qualifies(c)
        c._encoded_read = True
        assert encoded_device.column_qualifies(c)


# ---------------------------------------------------------------------------
# Flag oracle: byte-identical results, flat vs codes-on-device
# ---------------------------------------------------------------------------


class TestFlagOracle:
    def test_string_key_join_identical(self, tmp_path, monkeypatch):
        s = _session(tmp_path)
        base = str(tmp_path)
        _write_str_pair(s, base, 1200, card=60)

        def q():
            f = s.read.parquet(os.path.join(base, "fact"))
            d = s.read.parquet(os.path.join(base, "dim"))
            return f.join(d, col("sk") == col("dk")).select("sk", "val", "w").collect()

        on, off = _on_off(monkeypatch, q)
        _tables_identical(on, off)
        assert on.num_rows > 0

    def test_int_key_join_identical(self, tmp_path, monkeypatch):
        s = _session(tmp_path)
        base = str(tmp_path)
        rng = np.random.RandomState(11)
        s.write_parquet(
            {"k": rng.randint(0, 50, 900).astype(np.int64), "v": np.arange(900)},
            os.path.join(base, "ifact"),
        )
        s.write_parquet(
            {"ik": rng.randint(0, 50, 200).astype(np.int64), "w": np.arange(200)},
            os.path.join(base, "idim"),
        )

        def q():
            f = s.read.parquet(os.path.join(base, "ifact"))
            d = s.read.parquet(os.path.join(base, "idim"))
            return f.join(d, col("k") == col("ik")).select("k", "v", "w").collect()

        on, off = _on_off(monkeypatch, q)
        _tables_identical(on, off)
        assert on.num_rows > 0

    def test_null_key_join_identical(self, tmp_path, monkeypatch):
        from hyperspace_tpu.engine import io as engine_io

        s = _session(tmp_path)
        base = str(tmp_path)
        lt = Table.from_pydict(
            {"k": ["a", "b", None, "c", "a", None], "lv": [1, 2, 3, 4, 5, 6]}
        )
        rt = Table.from_pydict({"k": ["b", "a", None, "d"], "rv": [10, 20, 30, 40]})
        engine_io.write_parquet(lt, os.path.join(base, "nl", "part-00000.parquet"))
        engine_io.write_parquet(rt, os.path.join(base, "nr", "part-00000.parquet"))

        def q():
            l = s.read.parquet(os.path.join(base, "nl"))
            r = s.read.parquet(os.path.join(base, "nr"))
            return l.join(r, col("k") == col("k")).select("k", "lv", "rv").collect()

        on, off = _on_off(monkeypatch, q)
        _tables_identical(on, off)
        assert sorted(on.rows()) == [("a", 1, 20), ("a", 5, 20), ("b", 2, 10)]

    def test_streamed_aggregate_identical(self, tmp_path, monkeypatch):
        s = _session(tmp_path)
        base = str(tmp_path)
        _write_str_pair(s, base, 1500, card=40, seed=13)

        def q():
            return (
                s.read.parquet(os.path.join(base, "fact"))
                .group_by("sk")
                .agg(n=("*", "count"), tot=("val", "sum"))
                .collect()
            )

        on, off = _on_off(monkeypatch, q)
        _tables_identical(on, off)
        assert on.num_rows == 40


# ---------------------------------------------------------------------------
# Mesh build: byte-identical index files + code-space exchange traffic
# ---------------------------------------------------------------------------


class TestMeshCodedExchange:
    @pytest.mark.parametrize("distributed", ["1", "0"])
    def test_build_byte_identical_across_flag(
        self, tmp_path, monkeypatch, distributed
    ):
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", distributed)
        s = _session(tmp_path)
        base = str(tmp_path)
        _write_str_pair(s, base, 2000, card=90, seed=5)
        hs = Hyperspace(s)
        f = s.read.parquet(os.path.join(base, "fact"))

        monkeypatch.setenv(ENV, "1")
        _clear_caches()
        hs.create_index(f, IndexConfig("codedIdx", ["sk"], ["val"]))
        monkeypatch.setenv(ENV, "0")
        _clear_caches()
        hs.create_index(f, IndexConfig("flatIdx", ["sk"], ["val"]))
        monkeypatch.delenv(ENV, raising=False)

        hc = _dir_hashes(os.path.join(base, "indexes", "codedIdx", "v__=0"))
        hf = _dir_hashes(os.path.join(base, "indexes", "flatIdx", "v__=0"))
        assert len(hc) > 0
        assert hc == hf

        # And the indexed query answers identically rows-wise in this ambient.
        enable_hyperspace(s)
        d = s.read.parquet(os.path.join(base, "dim"))
        q = f.join(d, col("sk") == col("dk")).select("val", "w")
        monkeypatch.setenv(ENV, "1")
        _clear_caches()
        rows_on = q.sorted_rows()
        monkeypatch.setenv(ENV, "0")
        _clear_caches()
        rows_off = q.sorted_rows()
        assert rows_on == rows_off and len(rows_on) > 0

    def test_exchange_bytes_moved_shrinks_2x(self, tmp_path, monkeypatch):
        """The coded exchange's wire lanes (narrow bucket + int8 validity +
        int32 row id + int8 codes) move ≥2× fewer bytes than the flat lanes
        (uint32 hash + int32 validity + int64 row id + int32 codes) for the
        SAME build."""
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "1")
        s = _session(tmp_path)
        base = str(tmp_path)
        _write_str_pair(s, base, 3000, card=100, seed=9)
        hs = Hyperspace(s)
        f = s.read.parquet(os.path.join(base, "fact"))

        def moved_during(build):
            before = metrics.counter("parallel.exchange.bytes_moved").value
            build()
            return metrics.counter("parallel.exchange.bytes_moved").value - before

        monkeypatch.setenv(ENV, "1")
        _clear_caches()
        moved_on = moved_during(
            lambda: hs.create_index(f, IndexConfig("mcIdx", ["sk"], ["val"]))
        )
        monkeypatch.setenv(ENV, "0")
        _clear_caches()
        moved_off = moved_during(
            lambda: hs.create_index(f, IndexConfig("mfIdx", ["sk"], ["val"]))
        )
        monkeypatch.delenv(ENV, raising=False)
        assert moved_on > 0 and moved_off > 0
        assert moved_off / moved_on >= 2.0, (moved_off, moved_on)

    def test_no_per_cardinality_compile_classes(self, tmp_path, monkeypatch):
        """Two dictionary cardinalities in the SAME width class (both int8)
        share one compiled exchange: the code-width class key mints no
        per-cardinality shapes."""
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "1")
        monkeypatch.setenv(ENV, "1")
        s = _session(tmp_path)
        base = str(tmp_path)
        hs = Hyperspace(s)
        _write_str_pair(s, base, 2000, card=50, seed=21, suffix="a")
        _write_str_pair(s, base, 2000, card=100, seed=22, suffix="b")

        def compiles(lbl):
            return compile_log.program_summary().get(lbl, {}).get("compiles", 0)

        fa = s.read.parquet(os.path.join(base, "facta"))
        hs.create_index(fa, IndexConfig("cardA", ["sk"], ["val"]))
        after_first = compiles("parallel.exchange")
        assert after_first >= 1
        fb = s.read.parquet(os.path.join(base, "factb"))
        hs.create_index(fb, IndexConfig("cardB", ["sk"], ["val"]))
        assert compiles("parallel.exchange") == after_first, (
            "a second cardinality in the same code-width class recompiled "
            "the exchange"
        )


# ---------------------------------------------------------------------------
# Ledgers and cache accounting
# ---------------------------------------------------------------------------


class TestEncodedStagingLedger:
    def test_encoded_hits_and_staged_bytes_tick(self, tmp_path, monkeypatch):
        monkeypatch.setenv(ENV, "1")
        s = _session(tmp_path)
        base = str(tmp_path)
        _write_str_pair(s, base, 1000, card=60, seed=17)
        _clear_caches()
        f = s.read.parquet(os.path.join(base, "fact"))
        d = s.read.parquet(os.path.join(base, "dim"))
        q = f.join(d, col("sk") == col("dk")).select("val", "w")

        flat0 = metrics.counter("device.encoded.bytes_flat").value
        staged0 = metrics.counter("device.encoded.bytes_staged").value
        hits0 = metrics.counter("cache.device_upload.encoded_hits").value
        q.count()
        flat1 = metrics.counter("device.encoded.bytes_flat").value
        staged1 = metrics.counter("device.encoded.bytes_staged").value
        assert flat1 > flat0, "no encoded staging recorded"
        # int8 codes: the staged bytes are a strict fraction of the flat ones.
        assert staged1 - staged0 < flat1 - flat0
        # Warm path: restaging the SAME column serves the memoized narrow lane
        # from the id-keyed upload cache and ticks the encoded-hit counter.
        kc = f.collect().columns["sk"]
        encoded_device.stage_codes(kc, "test_site")
        encoded_device.stage_codes(kc, "test_site")
        assert metrics.counter("cache.device_upload.encoded_hits").value > hits0

    def test_flag_off_records_nothing(self, tmp_path, monkeypatch):
        monkeypatch.setenv(ENV, "0")
        s = _session(tmp_path)
        base = str(tmp_path)
        _write_str_pair(s, base, 800, card=60, seed=19)
        _clear_caches()
        flat0 = metrics.counter("device.encoded.bytes_flat").value
        f = s.read.parquet(os.path.join(base, "fact"))
        d = s.read.parquet(os.path.join(base, "dim"))
        f.join(d, col("sk") == col("dk")).select("val", "w").count()
        assert metrics.counter("device.encoded.bytes_flat").value == flat0
