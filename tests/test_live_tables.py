"""Live tables under fire (ISSUE 12): incremental delta refresh with
delete folding, crash-safe background compaction, and the races between
refreshers, compactors, and readers.

Contracts pinned here (docs/reliability.md "Live tables"):

- Incremental refresh indexes ONLY appended source files; deleted source
  files FOLD through lineage into the log entry's ``deletedSourceFiles`` set
  and are pruned at scan time on every read path — no data rewrite.
- Compaction (`optimize_index`) coalesces delta files back to one file per
  bucket, physically removes folded-deleted rows, clears the set, and its end
  state is BYTE-identical (sha256) to a from-scratch rebuild of the same
  source — in both ``HYPERSPACE_ENCODED_EXEC`` states.
- Refresh × compaction × reader races arbitrate through the OCC operation
  log: the loser aborts with ``ConcurrentWriteError`` and zero partial state;
  readers observe the winner's generation.
- The new fault points (``refresh.merge``, ``compact.commit``) fail CLEAN:
  the index stays readable and the next action succeeds.
"""

import hashlib
import os
import threading
import time

import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.engine.table import Table
from hyperspace_tpu.exceptions import (
    ConcurrentWriteError,
    HyperspaceException,
    TransientError,
)
from hyperspace_tpu.hyperspace import Hyperspace, enable_hyperspace
from hyperspace_tpu.telemetry import faults, metrics

import hyperspace_tpu.engine.io as eio


@pytest.fixture()
def session(tmp_path):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 2)
    s.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
    return s


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _clear_caches():
    from hyperspace_tpu.engine.scan_cache import (
        global_bucketed_cache,
        global_concat_cache,
        global_scan_cache,
    )

    global_scan_cache().clear()
    global_concat_cache().clear()
    global_bucketed_cache().clear()


def _write_src(tmp_path, name="t"):
    src = str(tmp_path / name)
    eio.write_parquet(
        Table.from_pydict({"k": [1, 2, 3, 4], "v": ["a", "b", "c", "d"]}),
        os.path.join(src, "part-00000.parquet"),
    )
    eio.write_parquet(
        Table.from_pydict({"k": [5, 6], "v": ["e", "f"]}),
        os.path.join(src, "part-00001.parquet"),
    )
    return src


def _append(src, name, keys, vals):
    # Write-then-rename: the TestRaces readers list this dir concurrently, and
    # the scan's extension filter hides the .tmp name until the atomic replace
    # — an in-place write lets a reader open a half-written footer.
    tmp = os.path.join(src, name + ".tmp")
    eio.write_parquet(Table.from_pydict({"k": keys, "v": vals}), tmp)
    os.replace(tmp, os.path.join(src, name))


def _entry(hs, name):
    return [e for e in hs._manager.get_indexes() if e.name == name][0]


def _sha_by_basename(entry):
    return {
        os.path.basename(p): hashlib.sha256(open(p, "rb").read()).hexdigest()
        for p in entry.content.files()
    }


def _oracle_shas(tmp_path, src, name="oracle"):
    """A from-scratch rebuild of the CURRENT source in its own index tree —
    the byte-identity oracle."""
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / f"indexes_{name}"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 2)
    s.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
    hs = Hyperspace(s)
    hs.create_index(s.read.parquet(src), IndexConfig(name, ["k"], ["v"]))
    return _sha_by_basename(_entry(hs, name))


class TestDeleteFolding:
    def test_incremental_folds_deletes_with_lineage(self, session, tmp_path):
        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("live", ["k"], ["v"]))
        _append(src, "part-00002.parquet", [7, 8], ["g", "h"])
        os.remove(os.path.join(src, "part-00001.parquet"))
        hs.refresh_index("live", mode="incremental")

        entry = _entry(hs, "live")
        assert entry.deleted_source_files() == [os.path.join(src, "part-00001.parquet")]
        enable_hyperspace(session)
        # Exact-signature match (refresh covered the delete): the folded set
        # must STILL prune — the rows are physically present until compaction.
        q = session.read.parquet(src).filter(col("k") >= 0).select("k", "v")
        assert sorted(q.collect().rows()) == [
            (1, "a"), (2, "b"), (3, "c"), (4, "d"), (7, "g"), (8, "h"),
        ]
        assert session.read.parquet(src).filter(col("k") == 5).select("v").collect().rows() == []

    def test_join_prunes_folded_deletes(self, session, tmp_path):
        src = _write_src(tmp_path, "l")
        session.write_parquet(
            {"k2": [1, 2, 5, 7], "w": [10, 20, 50, 70]}, str(tmp_path / "r")
        )
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("jl", ["k"], ["v"]))
        hs.create_index(
            session.read.parquet(str(tmp_path / "r")), IndexConfig("jr", ["k2"], ["w"])
        )
        _append(src, "part-00002.parquet", [7], ["g"])
        os.remove(os.path.join(src, "part-00001.parquet"))  # rows k=5,6
        hs.refresh_index("jl", mode="incremental")
        enable_hyperspace(session)
        l = session.read.parquet(src)
        r = session.read.parquet(str(tmp_path / "r"))
        q = l.join(r, col("k") == col("k2")).select("k", "v", "w")
        # k=5 joined before the delete; folded away now.
        assert sorted(q.collect().rows()) == [(1, "a", 10), (2, "b", 20), (7, "g", 70)]

    def test_deletes_only_is_metadata_only_refresh(self, session, tmp_path):
        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("del", ["k"], ["v"]))
        before = _entry(hs, "del").content.files()
        os.remove(os.path.join(src, "part-00001.parquet"))
        hs.refresh_index("del", mode="incremental")
        entry = _entry(hs, "del")
        # No new version dir: the delete folded as pure metadata.
        assert entry.content.files() == before
        assert entry.deleted_source_files() == [os.path.join(src, "part-00001.parquet")]
        enable_hyperspace(session)
        assert session.read.parquet(src).filter(col("k") == 5).select("v").collect().rows() == []

    def test_reappeared_deleted_path_rejects_as_modified(self, session, tmp_path):
        """A deleted path that RE-APPEARS (new file at the same path) is
        modified-in-place in disguise: the index still holds the OLD rows
        under that path and the path-keyed lineage prune cannot separate them
        from the new file's — folding it out would resurrect old rows,
        folding it in would drop the new ones. Incremental rejects; full
        rebuild serves exactly the new file's rows."""
        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("re", ["k"], ["v"]))
        gone = os.path.join(src, "part-00001.parquet")  # rows k=5,6
        os.remove(gone)
        hs.refresh_index("re", mode="incremental")
        assert _entry(hs, "re").deleted_source_files() == [gone]
        eio.write_parquet(Table.from_pydict({"k": [11], "v": ["z"]}), gone)
        with pytest.raises(HyperspaceException, match="modified"):
            hs.refresh_index("re", mode="incremental")
        hs.refresh_index("re", mode="auto")  # auto falls back to full
        assert _entry(hs, "re").deleted_source_files() == []
        enable_hyperspace(session)
        _clear_caches()
        q = session.read.parquet(src).filter(col("k") == 11).select("k", "v")
        assert q.collect().rows() == [(11, "z")]
        # The vanished file's OLD rows stay gone after the rewrite.
        assert session.read.parquet(src).filter(col("k") == 5).select("v").collect().rows() == []

    def test_auto_mode_rebuilds_a_quarantined_fresh_index(self, session, tmp_path):
        """A quarantined index with an unchanged source must not no-op under
        mode='auto' — the serving loop's timed auto refresh is the documented
        remediation, so it rebuilds full and lifts the quarantine."""
        from hyperspace_tpu.index import quarantine

        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("qa", ["k"], ["v"]))
        quarantine.mark("qa", reason="test corruption")
        id_before = _entry(hs, "qa").id
        hs.refresh_index("qa", mode="auto")
        assert not quarantine.is_quarantined("qa")
        assert _entry(hs, "qa").id > id_before  # a real rebuild, not a no-op

    def test_rejects_deletes_without_lineage(self, session, tmp_path):
        session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "false")
        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("nolin", ["k"], ["v"]))
        os.remove(os.path.join(src, "part-00001.parquet"))
        with pytest.raises(HyperspaceException, match="lineage"):
            hs.refresh_index("nolin", mode="incremental")
        # Clean abort before begin(): still ACTIVE, full refresh recovers.
        assert _entry(hs, "nolin").state == "ACTIVE"
        hs.refresh_index("nolin", mode="full")
        assert _entry(hs, "nolin").deleted_source_files() == []

    def test_full_refresh_clears_folded_set(self, session, tmp_path):
        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("fr", ["k"], ["v"]))
        os.remove(os.path.join(src, "part-00001.parquet"))
        hs.refresh_index("fr", mode="incremental")
        assert _entry(hs, "fr").deleted_source_files() != []
        hs.refresh_index("fr", mode="full")
        entry = _entry(hs, "fr")
        assert entry.deleted_source_files() == []
        # The rewrite also matches a from-scratch build byte-for-byte.
        assert _sha_by_basename(entry) == _oracle_shas(tmp_path, src)

    def test_missing_file_inventory_is_a_clear_error(self, session, tmp_path):
        """Satellite fix: incremental mode on a previous entry with NO
        per-file source signatures must surface a clear error, not silently
        full-rebuild (or worse, re-index everything as appended)."""
        import json

        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("nosig", ["k"], ["v"]))
        # Doctor the latest log entry: blank the recorded file inventory (an
        # older/foreign writer that only recorded a plan-level signature).
        log_dir = str(tmp_path / "indexes" / "nosig" / "_hyperspace_log")
        latest = max(int(n) for n in os.listdir(log_dir) if n.isdigit())
        p = os.path.join(log_dir, str(latest))
        d = json.load(open(p))
        rel = d["source"]["plan"]["properties"]["relations"][0]
        rel["data"]["properties"]["content"]["root"]["files"] = []
        rel["data"]["properties"]["content"]["root"]["subDirs"] = []
        json.dump(d, open(p, "w"))
        hs._manager.clear_cache()
        _append(src, "part-00002.parquet", [9], ["i"])
        with pytest.raises(HyperspaceException, match="per-file source signatures"):
            hs.refresh_index("nosig", mode="incremental")

    def test_modified_in_place_still_rejects(self, session, tmp_path):
        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("mod2", ["k"], ["v"]))
        time.sleep(0.01)  # mtime tick
        eio.write_parquet(
            Table.from_pydict({"k": [1], "v": ["x"]}),
            os.path.join(src, "part-00000.parquet"),
        )
        with pytest.raises(HyperspaceException, match="modified"):
            hs.refresh_index("mod2", mode="incremental")

    def test_auto_mode_routes(self, session, tmp_path):
        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("auto", ["k"], ["v"]))
        # Fresh: no-op (no new log entries, no error).
        id_before = _entry(hs, "auto").id
        hs.refresh_index("auto", mode="auto")
        assert _entry(hs, "auto").id == id_before
        # Appends: incremental (content spans two version dirs).
        _append(src, "part-00002.parquet", [9], ["i"])
        hs.refresh_index("auto", mode="auto")
        files = _entry(hs, "auto").content.files()
        assert any("v__=0" in f for f in files) and any("v__=1" in f for f in files)
        # Modified in place: falls back to full.
        time.sleep(0.01)
        eio.write_parquet(
            Table.from_pydict({"k": [1, 2, 3, 4], "v": ["A", "b", "c", "d"]}),
            os.path.join(src, "part-00000.parquet"),
        )
        hs.refresh_index("auto", mode="auto")
        entry = _entry(hs, "auto")
        vdirs = {f.split("v__=")[1].split(os.sep)[0] for f in entry.content.files()}
        assert len(vdirs) == 1  # full rebuild: one version dir again
        enable_hyperspace(session)
        assert session.read.parquet(src).filter(col("k") == 1).select("v").collect().rows() == [("A",)]

    def test_refresh_mode_env_default(self, session, tmp_path, monkeypatch):
        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("envm", ["k"], ["v"]))
        _append(src, "part-00002.parquet", [9], ["i"])
        monkeypatch.setenv("HYPERSPACE_REFRESH_MODE", "incremental")
        hs.refresh_index("envm")  # mode=None → env
        files = _entry(hs, "envm").content.files()
        assert any("v__=1" in f for f in files)


class TestCompaction:
    @pytest.mark.parametrize("encoded", ["0", "1"])
    def test_compaction_byte_identical_to_full_rebuild(
        self, session, tmp_path, monkeypatch, encoded
    ):
        """The acceptance oracle: appends + deletes folded across TWO
        incremental refreshes, then compaction — the end state matches a
        from-scratch rebuild of the same source sha-for-sha, in both encoded
        execution states."""
        monkeypatch.setenv("HYPERSPACE_ENCODED_EXEC", encoded)
        _clear_caches()
        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("cmp", ["k"], ["v"]))
        _append(src, "part-00002.parquet", [7, 8], ["g", "h"])
        hs.refresh_index("cmp", mode="incremental")
        _append(src, "part-00003.parquet", [9, 10], ["i", "j"])
        os.remove(os.path.join(src, "part-00001.parquet"))
        hs.refresh_index("cmp", mode="incremental")
        assert _entry(hs, "cmp").deleted_source_files() != []

        hs.optimize_index("cmp")
        entry = _entry(hs, "cmp")
        assert entry.deleted_source_files() == []
        basenames = {os.path.basename(f) for f in entry.content.files()}
        assert len(basenames) == len(entry.content.files())  # one file/bucket
        _clear_caches()
        assert _sha_by_basename(entry) == _oracle_shas(tmp_path, src, f"oracle{encoded}")

        _clear_caches()
        enable_hyperspace(session)
        q = session.read.parquet(src).filter(col("k") >= 0).select("k", "v")
        assert sorted(q.collect().rows()) == [
            (1, "a"), (2, "b"), (3, "c"), (4, "d"),
            (7, "g"), (8, "h"), (9, "i"), (10, "j"),
        ]

    def test_compacted_files_carry_index_schema_only(self, session, tmp_path):
        """Regression pin for the pre-existing optimize wart: reading delta
        files under `v__=N` dirs used to sprout a hive-inferred `v__` column
        that was WRITTEN into the compacted files (breaking later dataset-API
        reads — the old post-optimize quarantine fallback)."""
        import pyarrow.parquet as pq

        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("schm", ["k"], ["v"]))
        _append(src, "part-00002.parquet", [7, 8], ["g", "h"])
        hs.refresh_index("schm", mode="incremental")
        hs.optimize_index("schm")
        for f in _entry(hs, "schm").content.files():
            names = pq.ParquetFile(f).schema_arrow.names
            assert names == ["k", "v", "_data_file_name"], names

    def test_needs_compaction_trigger(self, session, tmp_path, monkeypatch):
        from hyperspace_tpu.actions.optimize import needs_compaction

        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("trig", ["k"], ["v"]))
        assert not needs_compaction(_entry(hs, "trig"))
        # Delta files accumulate: keys 7..10 spread over both buckets.
        _append(src, "part-00002.parquet", [7, 8, 9, 10], ["g", "h", "i", "j"])
        hs.refresh_index("trig", mode="incremental")
        assert needs_compaction(_entry(hs, "trig"))
        monkeypatch.setenv("HYPERSPACE_COMPACT_TRIGGER_FILES", "9")
        assert not needs_compaction(_entry(hs, "trig"))
        # A folded delete set triggers regardless of file spread.
        os.remove(os.path.join(src, "part-00002.parquet"))
        hs.refresh_index("trig", mode="incremental")
        assert needs_compaction(_entry(hs, "trig"))
        hs.optimize_index("trig")
        monkeypatch.delenv("HYPERSPACE_COMPACT_TRIGGER_FILES")
        assert not needs_compaction(_entry(hs, "trig"))


class TestChaos:
    def test_refresh_merge_fault_fails_clean(self, session, tmp_path):
        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("rm", ["k"], ["v"]))
        _append(src, "part-00002.parquet", [7], ["g"])
        with faults.inject("refresh.merge", kind="transient"):
            with pytest.raises(TransientError):
                hs.refresh_index("rm", mode="incremental")
        # The failed refresh left a transient orphan; the index stays
        # readable on the stable generation and the next refresh recovers.
        enable_hyperspace(session)
        _clear_caches()
        assert session.read.parquet(src).filter(col("k") == 1).select("v").collect().rows() == [("a",)]
        hs.refresh_index("rm", mode="incremental")
        entry = _entry(hs, "rm")
        assert entry.state == "ACTIVE"
        _clear_caches()
        assert session.read.parquet(src).filter(col("k") == 7).select("v").collect().rows() == [("g",)]

    def test_compact_commit_fault_aborts_staging_clean(self, session, tmp_path):
        from hyperspace_tpu.index.staging import STAGING_PREFIX

        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("cc", ["k"], ["v"]))
        _append(src, "part-00002.parquet", [7, 8], ["g", "h"])
        hs.refresh_index("cc", mode="incremental")
        with faults.inject("compact.commit", kind="transient"):
            with pytest.raises(TransientError):
                hs.optimize_index("cc")
        idx_path = str(tmp_path / "indexes" / "cc")
        assert not [n for n in os.listdir(idx_path) if n.startswith(STAGING_PREFIX)]
        # Retry compacts, and the result still matches the rebuild oracle.
        hs.optimize_index("cc")
        entry = _entry(hs, "cc")
        assert entry.state == "ACTIVE"
        assert _sha_by_basename(entry) == _oracle_shas(tmp_path, src)

    def test_hybrid_scan_appended_rows_survive_decode_chaos(
        self, session, tmp_path, monkeypatch
    ):
        """Satellite: the hybrid-scan appended-rows bucketize path rides the
        PR-7 resilience contract — transient decode faults on the appended
        lake files retry to byte-identical results."""
        monkeypatch.setenv("HYPERSPACE_IO_RETRIES", "6")
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        src = _write_src(tmp_path)
        session.write_parquet({"k2": [1, 5, 7], "w": [10, 50, 70]}, str(tmp_path / "r"))
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("hyb", ["k"], ["v"]))
        hs.create_index(
            session.read.parquet(str(tmp_path / "r")), IndexConfig("hybr", ["k2"], ["w"])
        )
        _append(src, "part-00002.parquet", [7], ["g"])  # NOT refreshed: hybrid merge
        enable_hyperspace(session)

        def run_queries():
            l = session.read.parquet(src)
            r = session.read.parquet(str(tmp_path / "r"))
            join = sorted(
                l.join(r, col("k") == col("k2")).select("k", "v", "w").collect().rows()
            )
            filt = session.read.parquet(src).filter(col("k") == 7).select("v").collect().rows()
            return join, filt

        _clear_caches()
        clean = run_queries()
        assert clean[1] == [("g",)]
        _clear_caches()
        r0 = metrics.counter("io.retries.attempts").value
        with faults.inject("io.decode", rate=0.4, kind="transient"):
            chaotic = run_queries()
        assert chaotic == clean
        assert metrics.counter("io.retries.attempts").value > r0


class TestRaces:
    def test_compactor_loses_occ_race_to_refresher(self, session, tmp_path):
        """Satellite: refresh × compaction race — the compactor hangs in its
        commit window while a full refresh lands; the compactor must abort
        with ConcurrentWriteError, leave zero partial state, and readers
        observe the refresher's generation."""
        from hyperspace_tpu.index.staging import STAGING_PREFIX

        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("race", ["k"], ["v"]))
        _append(src, "part-00002.parquet", [7, 8], ["g", "h"])
        hs.refresh_index("race", mode="incremental")

        errs = []

        def compact():
            try:
                # A fresh manager view (thread-local action), same log dir.
                Hyperspace(session).optimize_index("race")
            except Exception as e:  # noqa: BLE001 - recorded for the assert
                errs.append(e)

        calls0 = faults.call_count("compact.commit")
        with faults.inject("compact.commit", kind="hang2.0"):
            t = threading.Thread(target=compact)
            t.start()
            deadline = time.monotonic() + 30
            while faults.call_count("compact.commit") == calls0:
                assert time.monotonic() < deadline, "compactor never reached commit"
                time.sleep(0.02)
            # Compactor is inside its commit window: land a full refresh.
            hs.refresh_index("race", mode="full")
            t.join(timeout=60)
        assert not t.is_alive()
        assert len(errs) == 1 and isinstance(errs[0], ConcurrentWriteError), errs

        idx_path = str(tmp_path / "indexes" / "race")
        assert not [n for n in os.listdir(idx_path) if n.startswith(STAGING_PREFIX)]
        entry = _entry(hs, "race")
        assert entry.state == "ACTIVE"
        # The winner is the full refresh: one version dir, rebuild-identical.
        assert _sha_by_basename(entry) == _oracle_shas(tmp_path, src)
        enable_hyperspace(session)
        _clear_caches()
        q = session.read.parquet(src).filter(col("k") == 7).select("v")
        assert q.collect().rows() == [("g",)]

    def test_readers_stay_correct_across_refresh_generations(self, session, tmp_path):
        """Readers racing a refresher never see torn results: a stable key's
        row is correct in every generation."""
        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("rd", ["k"], ["v"]))
        enable_hyperspace(session)
        stop = threading.Event()
        failures = []

        def read_loop():
            while not stop.is_set():
                try:
                    rows = (
                        session.read.parquet(src)
                        .filter(col("k") == 1)
                        .select("k", "v")
                        .collect()
                        .rows()
                    )
                    if rows != [(1, "a")]:
                        failures.append(rows)
                except Exception as e:  # noqa: BLE001
                    failures.append(e)

        t = threading.Thread(target=read_loop)
        t.start()
        try:
            for i in range(3):
                _append(src, f"part-1000{i}.parquet", [100 + i], [f"x{i}"])
                hs.refresh_index("rd", mode="incremental")
            hs.optimize_index("rd")
            hs.refresh_index("rd", mode="full")
        finally:
            stop.set()
            t.join(timeout=60)
        assert failures == []

    def test_readers_keep_stable_generation_during_writer_window(
        self, session, tmp_path
    ):
        """While a refresher/compactor holds its transient log window (or died
        inside it), readers ride the last COMMITTED generation — the index
        never vanishes from candidate selection mid-refresh (which would send
        every interactive query to a full source scan for the duration)."""
        from hyperspace_tpu.hyperspace import _index_manager_for

        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("stab", ["k"], ["v"]))
        _append(src, "part-00002.parquet", [7], ["g"])
        # Fail the refresh INSIDE its merge window: the log's latest entry is
        # now a transient REFRESHING orphan.
        with faults.inject("refresh.merge", kind="transient"):
            with pytest.raises(TransientError):
                hs.refresh_index("stab", mode="incremental")
        mgr = _index_manager_for(session)
        mgr.clear_cache()
        active = [e for e in mgr.get_indexes(["ACTIVE"]) if e.name == "stab"]
        assert len(active) == 1  # the stable generation, not the orphan
        assert active[0].state == "ACTIVE"
        # And the reader actually uses it (the appended file keeps the
        # signature stale, so enable hybrid to make it a candidate).
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        enable_hyperspace(session)
        _clear_caches()
        q = session.read.parquet(src).filter(col("k") == 1).select("v")
        assert "stab" in q.explain_string()
        assert q.collect().rows() == [("a",)]

    def test_quarantine_clears_on_new_generation(self, session, tmp_path):
        from hyperspace_tpu.index import quarantine

        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("qr", ["k"], ["v"]))
        quarantine.mark("qr", reason="test corruption")
        assert quarantine.is_quarantined("qr")
        _append(src, "part-00002.parquet", [7], ["g"])
        hs.refresh_index("qr", mode="incremental")
        assert not quarantine.is_quarantined("qr")
        quarantine.mark("qr", reason="test corruption")
        hs.optimize_index("qr")
        assert not quarantine.is_quarantined("qr")


class TestPredicateCompileClasses:
    """The serving half of the live-table tail contract: interactive filter
    evaluation must not mint XLA compiles per literal value or per index
    generation's new row count (CPU backend: eager pow2-padded evaluation)."""

    def test_literal_rotation_compiles_nothing_new(self, session, tmp_path):
        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("pc", ["k"], ["v"]))
        enable_hyperspace(session)

        def q(key):
            return (
                session.read.parquet(src).filter(col("k") == key).select("v").collect()
            )

        for k in (1, 2, 3, 4, 5, 6):
            q(k)  # warm every bucket's shape class (and the literal plumbing)
        c = metrics.counter("xla.compiles.count")
        c0 = c.value
        q(7), q(8), q(9), q(10)  # rotated NEW literals over the warm shapes
        assert c.value == c0, f"{c.value - c0} compiles for rotated literals"

    @pytest.mark.parametrize(
        "min_rows,max_classes",
        [("0", None), (str(1 << 30), "0")],  # always-fused vs always-eager-padded
    )
    def test_padded_eager_matches_fused_oracle(
        self, session, tmp_path, monkeypatch, min_rows, max_classes
    ):
        """The pow2-padded eager path and the fused-program path produce
        identical rows over nulls, strings, floats, and non-pow2 row counts."""
        import numpy as np

        fuse = f"{min_rows}/{max_classes}"  # assertion label
        monkeypatch.setenv("HYPERSPACE_PRED_FUSE_MIN_ROWS", min_rows)
        if max_classes is not None:
            monkeypatch.setenv("HYPERSPACE_PRED_FUSE_MAX_CLASSES", max_classes)
        n = 1000  # not a power of two
        session.write_parquet(
            {
                "a": np.arange(n, dtype=np.int64),
                "f": np.where(np.arange(n) % 7 == 0, np.nan, np.arange(n) / 3.0),
                "s": np.array([None if i % 11 == 0 else f"s{i % 4}" for i in range(n)], dtype=object),
            },
            str(tmp_path / "p"),
        )
        df = lambda: session.read.parquet(str(tmp_path / "p"))  # noqa: E731
        cases = [
            (col("a") > 500, 499),
            ((col("a") >= 10) & (col("a") < 20), 10),
            (col("s") == "s1", None),
            (col("f") < 100.0, None),
            (~(col("s") == "s2"), None),
        ]
        for cond, expected in cases:
            got = df().filter(cond).count()
            if expected is not None:
                assert got == expected, (fuse, str(cond), got)
            rows = sorted(df().filter(cond).select("a").collect().rows())
            # Oracle: eager un-padded reference via a direct evaluate call.
            from hyperspace_tpu.engine.evaluate import _evaluate_predicate_eager

            t = df().collect()
            mask = np.asarray(_evaluate_predicate_eager(cond, t))
            ref = sorted((int(v),) for v in np.asarray(t.column("a").data)[mask])
            assert rows == ref, (fuse, str(cond))


class TestTelemetry:
    def test_staleness_gauge_and_refresh_latency(self, session, tmp_path):
        from hyperspace_tpu.telemetry.exporter import prometheus_text

        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("stale", ["k"], ["v"]))
        enable_hyperspace(session)
        # Fresh index + a query → candidate scan sets staleness to 0.
        session.read.parquet(src).filter(col("k") == 1).select("v").collect()
        g = metrics.gauge("index.staleness_s.stale")
        assert g.value == 0.0
        # Appended file older than "now" → staleness > 0 at candidate time.
        _append(src, "part-00002.parquet", [7], ["g"])
        past = time.time() - 120
        os.utime(os.path.join(src, "part-00002.parquet"), (past, past))
        session.read.parquet(src).filter(col("k") == 1).select("v").collect()
        assert g.value >= 100.0
        # Refresh resets it and lands latency observations.
        h_before = metrics.histogram("refresh.latency").count
        hi_before = metrics.histogram("refresh.latency.incremental").count
        hs.refresh_index("stale", mode="incremental")
        assert g.value == 0.0
        assert metrics.histogram("refresh.latency").count == h_before + 1
        assert metrics.histogram("refresh.latency.incremental").count == hi_before + 1
        text = prometheus_text()
        assert "hyperspace_index_staleness_s_stale" in text
        assert "hyperspace_refresh_latency" in text

    def test_compact_latency_histogram(self, session, tmp_path):
        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("cl", ["k"], ["v"]))
        _append(src, "part-00002.parquet", [7, 8], ["g", "h"])
        hs.refresh_index("cl", mode="incremental")
        before = metrics.histogram("compact.latency").count
        hs.optimize_index("cl")
        assert metrics.histogram("compact.latency").count == before + 1

    def test_fingerprint_changes_with_index_generation(self, session, tmp_path):
        """The history fingerprint is keyed on the index generation
        (`log_entry_id`): a refresh makes the same query a NEW plan class."""
        from hyperspace_tpu.plananalysis.fingerprint import plan_fingerprint

        src = _write_src(tmp_path)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(src), IndexConfig("fp", ["k"], ["v"]))
        enable_hyperspace(session)

        def fp():
            df = session.read.parquet(src).filter(col("k") == 1).select("v")
            return plan_fingerprint(df.physical_plan())

        f1 = fp()
        _append(src, "part-00002.parquet", [7], ["g"])
        hs.refresh_index("fp", mode="incremental")
        f2 = fp()
        assert f1 != f2
