"""Tests for the storage + metadata core (L1/L3).

Mirrors reference tiers 1 and 3 (SURVEY §4): `IndexConfigTests`, `JsonUtilsTests`,
`HashingUtilsTests`, `IndexLogEntryTest` (Content/Directory tree construction),
`IndexLogManagerImplTest` (real files under a tmpdir).
"""

import os

import pytest

from hyperspace_tpu import HyperspaceException, IndexConfig, IndexConstants, SessionConf
from hyperspace_tpu.actions import states
from hyperspace_tpu.index.data_manager import IndexDataManagerImpl
from hyperspace_tpu.index.log_entry import (
    Content,
    CoveringIndexProperties,
    Directory,
    FileInfo,
    IndexLogEntry,
    LogEntry,
    LogicalPlanFingerprint,
    Relation,
    Signature,
    Source,
    SourcePlanProperties,
)
from hyperspace_tpu.index.log_manager import IndexLogManagerImpl
from hyperspace_tpu.index.path_resolver import PathResolver
from hyperspace_tpu.storage.filesystem import InMemoryFileSystem, LocalFileSystem
from hyperspace_tpu.util import hashing_utils, json_utils, resolver_utils
from hyperspace_tpu.util.path_utils import is_data_path


# ---------------------------------------------------------------------------
# IndexConfig (reference IndexConfigTests)
# ---------------------------------------------------------------------------


class TestIndexConfig:
    def test_basic(self):
        c = IndexConfig("idx", ["a", "b"], ["c"])
        assert c.index_name == "idx"
        assert c.indexed_columns == ["a", "b"]

    def test_empty_name_rejected(self):
        with pytest.raises(HyperspaceException):
            IndexConfig("", ["a"])

    def test_empty_indexed_rejected(self):
        with pytest.raises(HyperspaceException):
            IndexConfig("idx", [])

    def test_case_insensitive_duplicates_rejected(self):
        with pytest.raises(HyperspaceException):
            IndexConfig("idx", ["a", "A"])
        with pytest.raises(HyperspaceException):
            IndexConfig("idx", ["a"], ["b", "B"])
        with pytest.raises(HyperspaceException):
            IndexConfig("idx", ["a"], ["A"])

    def test_case_insensitive_equality(self):
        assert IndexConfig("IDX", ["A"], ["b", "C"]) == IndexConfig("idx", ["a"], ["c", "B"])
        assert hash(IndexConfig("IDX", ["A"])) == hash(IndexConfig("idx", ["a"]))
        assert IndexConfig("idx", ["a", "b"]) != IndexConfig("idx", ["b", "a"])  # order matters

    def test_builder(self):
        c = IndexConfig.builder().index_name("n").index_by("a", "b").include("c").create()
        assert c == IndexConfig("n", ["a", "b"], ["c"])
        with pytest.raises(HyperspaceException):
            IndexConfig.builder().index_name("n").index_name("m")
        with pytest.raises(HyperspaceException):
            IndexConfig.builder().index_by("a").index_by("b")


# ---------------------------------------------------------------------------
# Utils (reference JsonUtilsTests / HashingUtilsTests / ResolverUtils)
# ---------------------------------------------------------------------------


class TestUtils:
    def test_json_roundtrip(self):
        obj = {"a": 1, "b": [1, 2, {"c": None}]}
        assert json_utils.from_json(json_utils.to_json(obj)) == obj

    def test_md5_stable(self):
        assert hashing_utils.md5_hex("x") == hashing_utils.md5_hex("x")
        assert hashing_utils.md5_hex("x") != hashing_utils.md5_hex("y")

    def test_resolver_case_insensitive_default(self):
        assert resolver_utils.resolve("DeptId", ["deptId", "other"]) == "deptId"
        assert resolver_utils.resolve("deptid", ["deptId"], case_sensitive=True) is None
        assert resolver_utils.resolve_all(["A", "b"], ["a", "B"]) == ["a", "B"]
        assert resolver_utils.resolve_all(["A", "x"], ["a", "B"]) is None

    def test_data_path_filter(self):
        assert is_data_path("part-0.parquet")
        assert not is_data_path("_SUCCESS")
        assert not is_data_path(".hidden")
        assert is_data_path("v__=3")  # hive-style partition dir counts as data


# ---------------------------------------------------------------------------
# Content / Directory tree (reference IndexLogEntryTest)
# ---------------------------------------------------------------------------


def _sample_entry(name="idx1", state=states.ACTIVE, sig="deadbeef"):
    content = Content(
        Directory(
            "/tmp/indexes/idx1/v__=0",
            files=[FileInfo("part-0.parquet", 100, 1)],
            subdirs=[],
        )
    )
    rel = Content(Directory("/data/t1", files=[FileInfo("f1.parquet", 10, 2)]))
    entry = IndexLogEntry(
        name,
        CoveringIndexProperties(["deptId"], ["deptName"], '{"fields":[]}', 8),
        content,
        Source(
            SourcePlanProperties(
                [Relation(["/data/t1"], rel, '{"fields":[]}', "parquet", {})],
                None,
                None,
                LogicalPlanFingerprint(signatures=[Signature("prov", sig)]),
            )
        ),
    )
    entry.state = state
    return entry


class TestContent:
    def test_tree_from_leaf_files_and_flatten(self, tmp_path):
        fs = LocalFileSystem()
        root = tmp_path / "data"
        (root / "a").mkdir(parents=True)
        (root / "a" / "f1").write_text("xx")
        (root / "f2").write_text("yyy")
        (root / "_meta").write_text("ignored")
        content = Content.from_directory(str(root), fs)
        files = content.files()
        assert str(root / "a" / "f1") in files
        assert str(root / "f2") in files
        assert all("_meta" not in f for f in files)

    def test_json_roundtrip(self):
        e = _sample_entry()
        d = e.to_json()
        e2 = IndexLogEntry.from_json(d)
        assert e2 == e
        assert e2.name == "idx1"
        assert e2.num_buckets == 8
        assert e2.signature().value == "deadbeef"
        assert e2.indexed_columns == ["deptId"]

    def test_polymorphic_decode(self):
        text = json_utils.to_json(_sample_entry().to_json())
        e = LogEntry.from_json(text)
        assert isinstance(e, IndexLogEntry)


# ---------------------------------------------------------------------------
# IndexLogManager (reference IndexLogManagerImplTest + ActionTest OCC checks)
# ---------------------------------------------------------------------------


def _make_fs(kind: str):
    if kind == "local":
        return LocalFileSystem()
    if kind == "memory":
        return InMemoryFileSystem()
    # Remote-protocol backend (fsspec adapter over an isolated instance).
    from fsspec.implementations.memory import MemoryFileSystem

    from hyperspace_tpu.storage.remote import FsspecFileSystem

    inst = MemoryFileSystem()
    inst.store = {}  # MemoryFileSystem state is class-global; isolate per test
    inst.pseudo_dirs = [""]
    return FsspecFileSystem(inst)


class TestIndexLogManager:
    @pytest.mark.parametrize("fs_kind", ["local", "memory", "fsspec"])
    def test_occ_write_refuses_existing_id(self, tmp_path, fs_kind):
        fs = _make_fs(fs_kind)
        mgr = IndexLogManagerImpl(str(tmp_path / "idx"), fs)
        assert mgr.write_log(0, _sample_entry(state=states.CREATING))
        assert not mgr.write_log(0, _sample_entry(state=states.ACTIVE))  # OCC conflict
        assert mgr.get_log(0).state == states.CREATING

    @pytest.mark.parametrize("fs_kind", ["local", "fsspec"])
    def test_occ_racing_writers_exactly_one_wins(self, tmp_path, fs_kind):
        """N threads race the same log id: exactly one commit succeeds (the
        reference's temp+atomic-rename contract; conditional put on remote)."""
        from concurrent.futures import ThreadPoolExecutor

        fs = _make_fs(fs_kind)
        mgr = IndexLogManagerImpl(str(tmp_path / "race"), fs)
        with ThreadPoolExecutor(max_workers=8) as pool:
            wins = list(
                pool.map(
                    lambda i: mgr.write_log(0, _sample_entry(state=states.CREATING)),
                    range(8),
                )
            )
        assert sum(bool(w) for w in wins) == 1

    @pytest.mark.parametrize("fs_kind", ["local", "memory", "fsspec"])
    def test_full_log_flow_per_backend(self, tmp_path, fs_kind):
        """latestStable pointer + fallback scan, on every storage backend."""
        fs = _make_fs(fs_kind)
        mgr = IndexLogManagerImpl(str(tmp_path / "flow"), fs)
        mgr.write_log(0, _sample_entry(state=states.CREATING))
        assert mgr.get_latest_stable_log() is None
        mgr.write_log(1, _sample_entry(state=states.ACTIVE))
        assert mgr.get_latest_stable_log().state == states.ACTIVE
        assert mgr.create_latest_stable_log(1)
        assert mgr.get_latest_stable_log().id == 1
        assert mgr.get_latest_id() == 1
        assert mgr.delete_latest_stable_log()
        assert mgr.get_latest_stable_log().id == 1  # descending scan fallback

    def test_latest_id_and_log(self, tmp_path):
        mgr = IndexLogManagerImpl(str(tmp_path / "idx"))
        assert mgr.get_latest_id() is None
        assert mgr.get_latest_log() is None
        mgr.write_log(0, _sample_entry(state=states.CREATING))
        mgr.write_log(1, _sample_entry(state=states.ACTIVE))
        assert mgr.get_latest_id() == 1
        assert mgr.get_latest_log().state == states.ACTIVE

    def test_latest_stable_pointer_and_fallback(self, tmp_path):
        mgr = IndexLogManagerImpl(str(tmp_path / "idx"))
        mgr.write_log(0, _sample_entry(state=states.CREATING))
        assert mgr.get_latest_stable_log() is None
        mgr.write_log(1, _sample_entry(state=states.ACTIVE))
        # No pointer yet -> descending scan finds id 1.
        assert mgr.get_latest_stable_log().state == states.ACTIVE
        assert mgr.create_latest_stable_log(1)
        assert mgr.get_latest_stable_log().id == 1
        # Pointer refuses non-stable ids.
        mgr.write_log(2, _sample_entry(state=states.DELETING))
        assert not mgr.create_latest_stable_log(2)
        assert mgr.delete_latest_stable_log()
        assert mgr.get_latest_stable_log().id == 1  # fallback scan again

    def test_entry_roundtrip_through_log(self, tmp_path):
        mgr = IndexLogManagerImpl(str(tmp_path / "idx"))
        e = _sample_entry()
        mgr.write_log(0, e)
        got = mgr.get_log(0)
        assert got == e
        assert got.id == 0


# ---------------------------------------------------------------------------
# IndexDataManager versioned dirs
# ---------------------------------------------------------------------------


class TestIndexDataManager:
    def test_versions(self, tmp_path):
        root = str(tmp_path / "idx")
        mgr = IndexDataManagerImpl(root)
        assert mgr.get_latest_version_id() is None
        os.makedirs(os.path.join(root, "v__=0"))
        os.makedirs(os.path.join(root, "v__=3"))
        os.makedirs(os.path.join(root, "not_a_version"))
        assert mgr.get_latest_version_id() == 3
        assert mgr.get_path(4).endswith("v__=4")
        mgr.delete(3)
        assert mgr.get_latest_version_id() == 0


# ---------------------------------------------------------------------------
# PathResolver
# ---------------------------------------------------------------------------


class TestPathResolver:
    def test_default_and_configured_root(self, tmp_path):
        conf = SessionConf()
        r = PathResolver(conf, warehouse=str(tmp_path))
        assert r.system_path() == os.path.join(str(tmp_path), "indexes")
        conf.set(IndexConstants.INDEX_SYSTEM_PATH, "/custom/root")
        assert r.system_path() == "/custom/root"

    def test_case_insensitive_index_dir_match(self, tmp_path):
        conf = SessionConf()
        conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
        os.makedirs(tmp_path / "indexes" / "MyIdx")
        r = PathResolver(conf)
        assert r.get_index_path("myidx") == str(tmp_path / "indexes" / "MyIdx")
        assert r.get_index_path("other") == str(tmp_path / "indexes" / "other")


# ---------------------------------------------------------------------------
# Conf
# ---------------------------------------------------------------------------


class TestConf:
    def test_typed_accessors(self):
        from hyperspace_tpu import HyperspaceConf

        conf = SessionConf()
        h = HyperspaceConf(conf)
        assert h.num_buckets == 200
        assert h.cache_expiry_seconds == 300
        assert not h.hybrid_scan_enabled
        assert not h.lineage_enabled
        conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
        conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        assert h.num_buckets == 8
        assert h.hybrid_scan_enabled


def test_active_session_is_thread_local(tmp_path):
    """Per-thread active sessions (reference Hyperspace.scala:108-120): a
    session created on another thread becomes THAT thread's context without
    stealing this thread's, and threads without their own fall back to the
    most recent global one."""
    import threading

    from hyperspace_tpu.engine import HyperspaceSession

    main_s = HyperspaceSession(warehouse=str(tmp_path / "main"))
    assert HyperspaceSession.active() is main_s

    seen = {}

    def worker():
        other = HyperspaceSession(warehouse=str(tmp_path / "other"))
        seen["worker_active"] = HyperspaceSession.active() is other

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert seen["worker_active"]
    # This thread's context is untouched by the worker's session.
    assert HyperspaceSession.active() is main_s

    def fresh_thread():
        # No session created on this thread: falls back to the global latest.
        seen["fallback"] = HyperspaceSession.active()

    t2 = threading.Thread(target=fresh_thread)
    t2.start()
    t2.join()
    assert seen["fallback"] is not None
