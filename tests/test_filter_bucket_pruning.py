"""FilterIndexRule bucket pruning: point lookups read only the literal's bucket.

Round-5 found the filter-index rewrite LOSING to the raw scan it replaces at
small inputs (filter_indexed_p50 0.0122 s vs scan 0.0032 s in BENCH_r05): the
substituted scan read all `num_buckets` index files per query. An equality/IN
filter on the head indexed column can only match rows in the literals' hash
buckets — the build partitioned by exactly that hash — so the rewrite now
prunes the file list to those `part-<bucket>` files and never loses the
read-volume race again. Gated by `hyperspace.index.filter.bucketPruning`
(default on); pruning bails (keeps all files) whenever the literal can't be
placed in the build's hash space or a file sits outside the part-<bucket>
naming contract.
"""

import os

import numpy as np
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.hyperspace import Hyperspace, enable_hyperspace


@pytest.fixture()
def session(tmp_path):
    base = str(tmp_path)
    s = HyperspaceSession(warehouse=base)
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, os.path.join(base, "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 16)
    return s


def _mk_source(s, tmp_path, name="t", n=20_000, seed=5):
    rng = np.random.RandomState(seed)
    path = os.path.join(str(tmp_path), name)
    s.write_parquet(
        {
            "sku": np.array([f"sku-{i % 3000:05d}" for i in range(n)]),
            "ik": rng.randint(0, 500, n).astype(np.int64),
            "w": rng.randint(1, 99, n).astype(np.int64),
        },
        path,
    )
    return path


def _rows(df):
    return sorted(map(tuple, df.collect().rows()))


def _oracle(s, df):
    """Same query with pruning disabled (still index-rewritten)."""
    s.conf.set(IndexConstants.INDEX_FILTER_BUCKET_PRUNING, "false")
    try:
        return _rows(df)
    finally:
        s.conf.set(IndexConstants.INDEX_FILTER_BUCKET_PRUNING, "true")


def test_string_equality_prunes_to_one_bucket(session, tmp_path):
    s = session
    path = _mk_source(s, tmp_path)
    Hyperspace(s).create_index(
        s.read.parquet(path), IndexConfig("strIdx", ["sku"], ["ik", "w"])
    )
    enable_hyperspace(s)

    def q():
        return s.read.parquet(path).filter(col("sku") == "sku-00042").select("w")

    ex = q().explain_string()
    assert "strIdx" in ex
    assert "pruned by FilterIndexRule:bucket" in ex, ex
    got = _rows(q())
    assert got == _oracle(s, q()) and len(got) > 0


def test_isin_prunes_to_value_buckets(session, tmp_path):
    s = session
    path = _mk_source(s, tmp_path)
    Hyperspace(s).create_index(
        s.read.parquet(path), IndexConfig("strIdx", ["sku"], ["ik", "w"])
    )
    enable_hyperspace(s)

    def q():
        return (
            s.read.parquet(path)
            .filter(col("sku").isin("sku-00042", "sku-00999", "sku-02718"))
            .select("sku", "w")
        )

    assert "pruned by FilterIndexRule:bucket" in q().explain_string()
    got = _rows(q())
    assert got == _oracle(s, q()) and len(got) > 0


def test_int_equality_and_conjunction(session, tmp_path):
    s = session
    path = _mk_source(s, tmp_path)
    Hyperspace(s).create_index(
        s.read.parquet(path), IndexConfig("intIdx", ["ik"], ["w"])
    )
    enable_hyperspace(s)

    def q():
        return (
            s.read.parquet(path)
            .filter((col("ik") == 123) & (col("w") > 10))
            .select("w")
        )

    assert "pruned by FilterIndexRule:bucket" in q().explain_string()
    got = _rows(q())
    assert got == _oracle(s, q()) and len(got) > 0


def test_range_filter_keeps_all_files(session, tmp_path):
    """A range predicate on the head column can land in any bucket: the
    rewrite still fires, but nothing is pruned."""
    s = session
    path = _mk_source(s, tmp_path)
    Hyperspace(s).create_index(
        s.read.parquet(path), IndexConfig("intIdx", ["ik"], ["w"])
    )
    enable_hyperspace(s)
    q = s.read.parquet(path).filter(col("ik") >= 490).select("w")
    ex = q.explain_string()
    assert "intIdx" in ex
    assert "pruned by FilterIndexRule:bucket" not in ex
    got = _rows(q)
    assert len(got) > 0


def test_pruning_disabled_by_conf(session, tmp_path):
    s = session
    path = _mk_source(s, tmp_path)
    Hyperspace(s).create_index(
        s.read.parquet(path), IndexConfig("intIdx", ["ik"], ["w"])
    )
    enable_hyperspace(s)
    s.conf.set(IndexConstants.INDEX_FILTER_BUCKET_PRUNING, "false")
    q = s.read.parquet(path).filter(col("ik") == 123).select("w")
    ex = q.explain_string()
    assert "intIdx" in ex and "pruned by" not in ex


def test_fractional_literal_on_int_head_skips_pruning(session, tmp_path):
    """col_int == 2.5 can't be placed in the int hash space — the rewrite must
    keep all files rather than mis-prune (the filter itself returns no rows)."""
    s = session
    path = _mk_source(s, tmp_path)
    Hyperspace(s).create_index(
        s.read.parquet(path), IndexConfig("intIdx", ["ik"], ["w"])
    )
    enable_hyperspace(s)
    q = s.read.parquet(path).filter(col("ik") == 2.5).select("w")
    assert "pruned by" not in q.explain_string()
    assert q.collect().num_rows == 0


def test_pruned_bucket_count_matches_hash(session, tmp_path):
    """The kept files are exactly the literal's hash bucket."""
    from hyperspace_tpu.hyperspace import _index_manager_for
    from hyperspace_tpu.rules.filter_index_rule import _bucket_of_literal

    s = session
    path = _mk_source(s, tmp_path)
    Hyperspace(s).create_index(
        s.read.parquet(path), IndexConfig("intIdx", ["ik"], ["w"])
    )
    enable_hyperspace(s)
    entry = _index_manager_for(s).get_indexes(["ACTIVE"])[0]
    b = _bucket_of_literal(123, "int64", entry.num_buckets)
    plan = (
        s.read.parquet(path).filter(col("ik") == 123).select("w").optimized_plan()
    )
    scans = []

    def collect(node):
        rel = getattr(node, "relation", None)
        if rel is not None and rel.index_name == "intIdx":
            scans.append(rel)
        return node

    plan.transform_up(collect)
    assert scans, "index scan not found in optimized plan"
    names = [os.path.basename(f.path) for f in scans[0].files]
    assert names == [f"part-{b:05d}.parquet"], names
