"""Randomized differential testing: random tables and query shapes, the
indexed plan vs the scan plan vs count().

The reference's strongest correctness tool is the E2E result-equality oracle
(`E2EHyperspaceRulesTests.scala:454-470`); this extends it with generated
inputs so dtype mixes, null densities, duplicate-heavy keys, and join/agg
shapes the hand-written tests didn't anticipate still hit the oracle. Seeds
are fixed — failures reproduce."""

import os

import numpy as np
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.hyperspace import (
    Hyperspace,
    disable_hyperspace,
    enable_hyperspace,
)


from contextlib import contextmanager


@contextmanager
def _random_device_ops(rng):
    """Coin-flip HYPERSPACE_FORCE_DEVICE_OPS for one test body, restoring the
    CI matrix's value afterwards — one implementation for every fuzz test."""
    saved = os.environ.get("HYPERSPACE_FORCE_DEVICE_OPS")
    if rng.rand() < 0.5:
        os.environ["HYPERSPACE_FORCE_DEVICE_OPS"] = "1"
    else:
        os.environ.pop("HYPERSPACE_FORCE_DEVICE_OPS", None)
    try:
        yield
    finally:
        if saved is None:
            os.environ.pop("HYPERSPACE_FORCE_DEVICE_OPS", None)
        else:
            os.environ["HYPERSPACE_FORCE_DEVICE_OPS"] = saved


def _random_table(rng, n, key_kind):
    if key_kind == "int":
        keys = rng.randint(0, max(n // 4, 4), n).astype(np.int64)
    elif key_kind == "float":
        keys = (rng.randint(0, max(n // 4, 4), n)).astype(np.float64)
    else:
        keys = np.array([f"k{v:04d}" for v in rng.randint(0, max(n // 4, 4), n)])
    cols = {
        "k": keys,
        "m": rng.randint(-50, 50, n).astype(np.int64),
        "x": rng.rand(n) * 100,
        "s": np.array([f"s{v:02d}" for v in rng.randint(0, 7, n)]),
    }
    if rng.rand() < 0.5:  # null some measure values
        x = cols["x"].astype(object)
        x[:: rng.randint(5, 17)] = None
        cols["x"] = x
    return cols


def _rows_close(a, b, tol=1e-9):
    assert len(a) == len(b), (len(a), len(b))
    for ra, rb in zip(a, b):
        for x, y in zip(ra, rb):
            if isinstance(x, float) and isinstance(y, float):
                assert abs(x - y) <= tol * max(1.0, abs(x)), (ra, rb)
            else:
                assert x == y, (ra, rb)


@pytest.mark.parametrize("seed", range(8))
def test_random_join_agg_differential(tmp_path, seed):
    rng = np.random.RandomState(1000 + seed)
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, int(rng.choice([4, 8, 16])))
    with _random_device_ops(rng):
        hs = Hyperspace(s)
        key_kind = ["int", "float", "str"][seed % 3]
        n_l, n_r = int(rng.randint(500, 4000)), int(rng.randint(50, 800))
        s.write_parquet(_random_table(rng, n_l, key_kind), str(tmp_path / "l"))
        rt = _random_table(rng, n_r, key_kind)
        rt["k2"] = rt.pop("k")
        rt["w"] = rt.pop("m")
        rt = {k: v for k, v in rt.items() if k in ("k2", "w")}
        s.write_parquet(rt, str(tmp_path / "r"))
        hs.create_index(
            s.read.parquet(str(tmp_path / "l")),
            IndexConfig(f"fzl{seed}", ["k"], ["m", "x", "s"]),
        )
        hs.create_index(
            s.read.parquet(str(tmp_path / "r")),
            IndexConfig(f"fzr{seed}", ["k2"], ["w"]),
        )

        filt_cut = int(rng.randint(-20, 20))

        def q_join():
            l = s.read.parquet(str(tmp_path / "l"))
            r = s.read.parquet(str(tmp_path / "r"))
            return l.join(r, col("k") == col("k2")).select("m", "w", "s")

        def q_agg():
            l = s.read.parquet(str(tmp_path / "l"))
            r = s.read.parquet(str(tmp_path / "r"))
            return (
                l.filter(col("m") >= filt_cut)
                .join(r, col("k") == col("k2"))
                .with_column("v2", col("x") * 2 + col("m"))
                .group_by("s")
                .agg(
                    t=("v2", "sum"),
                    c=("w", "count"),
                    mn=("x", "min"),
                    mx=("m", "max"),
                )
                .order_by(("s", True))
            )

        disable_hyperspace(s)
        join_oracle = q_join().sorted_rows()
        agg_oracle = q_agg().collect().sorted_rows()
        count_oracle = len(join_oracle)

        enable_hyperspace(s)
        assert q_join().count() == count_oracle
        assert q_join().sorted_rows() == join_oracle
        _rows_close(q_agg().collect().sorted_rows(), agg_oracle)


@pytest.mark.parametrize("seed", range(6))
def test_random_mutation_sequence_differential(tmp_path, seed):
    """Random interleavings of source mutations (append / delete / refresh /
    optimize) and queries (count / rows / aggregate), each query checked
    against the non-indexed oracle. This is the adversarial workload for the
    row-identity memo hierarchy (docs/caching.md): every mutation must re-key
    the probe/pair caches, every query must still be exact."""
    from hyperspace_tpu.engine import io as eio
    from hyperspace_tpu.engine.table import Table

    rng = np.random.RandomState(2000 + seed)
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, int(rng.choice([4, 8])))
    s.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
    s.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
    with _random_device_ops(rng):
        hs = Hyperspace(s)
        d = tmp_path / "ml"

        def mk_rows(n):
            return {
                "k": rng.randint(0, 40, n).astype(np.int64),
                "v": rng.randint(-100, 100, n).astype(np.int64),
                "x": rng.rand(n) * 10,
            }

        n_files = 0

        def write_file(tag):
            nonlocal n_files
            eio.write_parquet(
                Table.from_pydict(mk_rows(int(rng.randint(20, 200)))),
                str(d / f"part-{tag}-{n_files:03d}.parquet"),
            )
            n_files += 1

        write_file("base")
        write_file("base")
        s.write_parquet(
            {"rk": np.arange(40, dtype=np.int64),
             "w": rng.randint(0, 9, 40).astype(np.int64)},
            str(tmp_path / "mr"),
        )
        hs.create_index(
            s.read.parquet(str(d)), IndexConfig(f"ml{seed}", ["k"], ["v", "x"])
        )
        hs.create_index(
            s.read.parquet(str(tmp_path / "mr")), IndexConfig(f"mr{seed}", ["rk"], ["w"])
        )
        enable_hyperspace(s)

        def q_join():
            l = s.read.parquet(str(d))
            r = s.read.parquet(str(tmp_path / "mr"))
            return l.join(r, col("k") == col("rk")).select("v", "w")

        def q_agg():
            l = s.read.parquet(str(d))
            r = s.read.parquet(str(tmp_path / "mr"))
            return (
                l.join(r, col("k") == col("rk"))
                .with_column("y", col("x") + col("w"))
                .group_by("w")
                .agg(t=("y", "sum"), c=("v", "count"))
                .order_by(("w", True))
            )

        def check():
            enable_hyperspace(s)
            got_count = q_join().count()
            got_rows = q_join().sorted_rows()
            got_agg = q_agg().collect().sorted_rows()
            disable_hyperspace(s)
            assert got_count == q_join().count()
            assert got_rows == q_join().sorted_rows()
            _rows_close(got_agg, q_agg().collect().sorted_rows())
            enable_hyperspace(s)

        check()
        for step in range(8):
            op = rng.choice(["append", "delete", "refresh", "optimize", "query"])
            if op == "append":
                write_file("app")
            elif op == "delete":
                files = sorted(p for p in os.listdir(str(d)) if p.endswith(".parquet"))
                if len(files) > 1:  # never drop the last file of the dir
                    os.remove(str(d / files[int(rng.randint(len(files)))]))
            elif op == "refresh":
                mode = str(rng.choice(["full", "incremental"]))
                try:
                    hs.refresh_index(f"ml{seed}", mode=mode)
                except HyperspaceException:
                    if mode == "full":
                        raise  # full refresh has no legal refusal here
                    # incremental refusing deletes/modifications is legal
            elif op == "optimize":
                try:
                    hs.optimize_index(f"ml{seed}")
                except HyperspaceException:
                    pass  # nothing compactable — a legal refusal
            check()
