"""Randomized differential testing: random tables and query shapes, the
indexed plan vs the scan plan vs count().

The reference's strongest correctness tool is the E2E result-equality oracle
(`E2EHyperspaceRulesTests.scala:454-470`); this extends it with generated
inputs so dtype mixes, null densities, duplicate-heavy keys, and join/agg
shapes the hand-written tests didn't anticipate still hit the oracle. Seeds
are fixed — failures reproduce."""

import os

import numpy as np
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.hyperspace import (
    Hyperspace,
    disable_hyperspace,
    enable_hyperspace,
)


def _random_table(rng, n, key_kind):
    if key_kind == "int":
        keys = rng.randint(0, max(n // 4, 4), n).astype(np.int64)
    elif key_kind == "float":
        keys = (rng.randint(0, max(n // 4, 4), n)).astype(np.float64)
    else:
        keys = np.array([f"k{v:04d}" for v in rng.randint(0, max(n // 4, 4), n)])
    cols = {
        "k": keys,
        "m": rng.randint(-50, 50, n).astype(np.int64),
        "x": rng.rand(n) * 100,
        "s": np.array([f"s{v:02d}" for v in rng.randint(0, 7, n)]),
    }
    if rng.rand() < 0.5:  # null some measure values
        x = cols["x"].astype(object)
        x[:: rng.randint(5, 17)] = None
        cols["x"] = x
    return cols


def _rows_close(a, b, tol=1e-9):
    assert len(a) == len(b), (len(a), len(b))
    for ra, rb in zip(a, b):
        for x, y in zip(ra, rb):
            if isinstance(x, float) and isinstance(y, float):
                assert abs(x - y) <= tol * max(1.0, abs(x)), (ra, rb)
            else:
                assert x == y, (ra, rb)


@pytest.mark.parametrize("seed", range(8))
def test_random_join_agg_differential(tmp_path, seed):
    rng = np.random.RandomState(1000 + seed)
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, int(rng.choice([4, 8, 16])))
    saved = os.environ.get("HYPERSPACE_FORCE_DEVICE_OPS")  # CI matrix sets it
    if rng.rand() < 0.5:
        os.environ["HYPERSPACE_FORCE_DEVICE_OPS"] = "1"
    else:
        os.environ.pop("HYPERSPACE_FORCE_DEVICE_OPS", None)
    try:
        hs = Hyperspace(s)
        key_kind = ["int", "float", "str"][seed % 3]
        n_l, n_r = int(rng.randint(500, 4000)), int(rng.randint(50, 800))
        s.write_parquet(_random_table(rng, n_l, key_kind), str(tmp_path / "l"))
        rt = _random_table(rng, n_r, key_kind)
        rt["k2"] = rt.pop("k")
        rt["w"] = rt.pop("m")
        rt = {k: v for k, v in rt.items() if k in ("k2", "w")}
        s.write_parquet(rt, str(tmp_path / "r"))
        hs.create_index(
            s.read.parquet(str(tmp_path / "l")),
            IndexConfig(f"fzl{seed}", ["k"], ["m", "x", "s"]),
        )
        hs.create_index(
            s.read.parquet(str(tmp_path / "r")),
            IndexConfig(f"fzr{seed}", ["k2"], ["w"]),
        )

        filt_cut = int(rng.randint(-20, 20))

        def q_join():
            l = s.read.parquet(str(tmp_path / "l"))
            r = s.read.parquet(str(tmp_path / "r"))
            return l.join(r, col("k") == col("k2")).select("m", "w", "s")

        def q_agg():
            l = s.read.parquet(str(tmp_path / "l"))
            r = s.read.parquet(str(tmp_path / "r"))
            return (
                l.filter(col("m") >= filt_cut)
                .join(r, col("k") == col("k2"))
                .with_column("v2", col("x") * 2 + col("m"))
                .group_by("s")
                .agg(
                    t=("v2", "sum"),
                    c=("w", "count"),
                    mn=("x", "min"),
                    mx=("m", "max"),
                )
                .order_by(("s", True))
            )

        disable_hyperspace(s)
        join_oracle = q_join().sorted_rows()
        agg_oracle = q_agg().collect().sorted_rows()
        count_oracle = len(join_oracle)

        enable_hyperspace(s)
        assert q_join().count() == count_oracle
        assert q_join().sorted_rows() == join_oracle
        _rows_close(q_agg().collect().sorted_rows(), agg_oracle)
    finally:
        if saved is None:
            os.environ.pop("HYPERSPACE_FORCE_DEVICE_OPS", None)
        else:
            os.environ["HYPERSPACE_FORCE_DEVICE_OPS"] = saved
